"""Benchmark: dense-LM training MFU on the available accelerator.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

The flagship path: bf16 TransformerLm (scan-over-layers) full train step
(fwd+bwd+Adafactor) on synthetic packed input. MFU = model FLOPs / (step
time * peak FLOPs). Baseline target: 45% MFU (BASELINE.md north star).
Secondary numbers in "detail": flash-attention vs naive step time (proves
the Pallas kernel runs on hardware) and a 64-expert MoE step.

Hardened against TPU-backend flakiness (the round-1 failure mode): the TPU
is probed in a subprocess with a timeout, `jax.devices()` is retried with
exponential backoff on Unavailable, CPU is the fallback backend, and a valid
JSON line is emitted even on partial failure.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def _PeakFlops(device) -> float:
  kind = getattr(device, "device_kind", "").lower()
  # bf16 peak per chip
  table = {
      "tpu v5 lite": 197e12,   # v5e
      "tpu v5e": 197e12,
      "tpu v5": 459e12,        # v5p
      "tpu v5p": 459e12,
      "tpu v4": 275e12,
      "tpu v6 lite": 918e12,   # v6e / trillium
      "tpu v6e": 918e12,
  }
  for k, v in sorted(table.items(), key=lambda kv: -len(kv[0])):
    if k in kind:
      return v
  if "tpu" in kind:
    return 197e12
  return float(os.environ.get("BENCH_PEAK_FLOPS", 2e11))  # cpu-ish


def _ProbeTpu(timeout_s: float) -> str:
  """Probe (in a throwaway subprocess) which backend comes up.

  Returns "tpu", "cpu" (definitive: this machine resolves to CPU — don't
  retry), or "error" (transient init failure/timeout — retry). The axon PJRT
  plugin can block for minutes inside backend init when its tunnel is down —
  a subprocess + kill is the only reliable timeout.
  """
  code = "import jax; d = jax.devices(); print(d[0].platform)"
  try:
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=timeout_s)
  except subprocess.TimeoutExpired:
    return "error"
  if proc.returncode != 0:
    return "error"
  return "cpu" if "cpu" in proc.stdout else "tpu"


def _ForceCpu():
  """Make this process CPU-only even if a TPU plugin already registered.

  Env vars alone are not enough: a sitecustomize may have imported jax and
  registered a tunneled PJRT plugin at interpreter start. Same recipe as
  tests/conftest.py: re-point the config at cpu and strip non-cpu backend
  factories (importing chex/pallas first — they register 'tpu' lowering
  rules and fail if the platform is already gone).
  """
  os.environ["JAX_PLATFORMS"] = "cpu"
  os.environ.pop("PYTHONPATH", None)
  try:
    import jax
    try:
      import chex  # noqa: F401
    except ImportError:
      pass
    try:
      import jax.experimental.pallas  # noqa: F401
      import jax.experimental.pallas.tpu  # noqa: F401
    except ImportError:
      pass
    from jax._src import xla_bridge
    jax.config.update("jax_platforms", "cpu")
    for name in list(getattr(xla_bridge, "_backend_factories", {})):
      if name not in ("cpu", "interpreter"):
        xla_bridge._backend_factories.pop(name, None)
  except Exception as e:  # noqa: BLE001
    print(f"bench: cpu fallback setup issue: {e}", file=sys.stderr)


_TPU_UNREACHABLE = False


def _EnsureBackend():
  """Pick TPU if reachable (with retries), else CPU. Must run pre-`import jax`.

  Sets the module-global _TPU_UNREACHABLE when a TPU plugin exists but never
  came up: main() then stamps `valid_for_mfu: false` in the JSON and exits
  nonzero so a CPU-fallback run can't be misread as a TPU perf regression
  (the round-3 failure: BENCH_r03.json silently recorded CPU numbers).
  """
  global _TPU_UNREACHABLE
  if os.environ.get("BENCH_FORCE_CPU"):
    _ForceCpu()
    return
  # Retry-with-backoff around TPU probe (ref base_runner.py:399-528 retry
  # taxonomy: Unavailable during TPU init is transient). The final window is
  # long (10 min): the axon tunnel has been observed to wedge for multiple
  # minutes and then recover.
  probes = [(0, 90), (5, 90), (15, 90), (30, 90), (60, 90), (60, 600)]
  for i, (delay, window) in enumerate(probes):
    if delay:
      time.sleep(delay)
    status = _ProbeTpu(timeout_s=window)
    if status == "tpu":
      return  # leave env alone: real backend resolves to the TPU plugin
    if status == "cpu":
      break  # definitive: no TPU plugin on this machine — don't retry
    print(f"bench: TPU probe {i + 1}/{len(probes)} failed", file=sys.stderr)
  else:
    _TPU_UNREACHABLE = True
  print("bench: no TPU available, using CPU", file=sys.stderr)
  _ForceCpu()


def _MemSnapshot(dev=None):
  """Point-in-time memory stats: device allocator stats on TPU
  (`memory_stats()`), /proc/self/status VmRSS/VmHWM on CPU. Values in
  bytes; missing sources simply omit their keys."""
  out = {}
  if dev is not None and getattr(dev, "platform", "cpu") != "cpu":
    try:
      st = dev.memory_stats() or {}
      out["device_bytes_in_use"] = st.get("bytes_in_use")
      out["device_peak_bytes"] = st.get("peak_bytes_in_use")
    except Exception:  # noqa: BLE001
      pass
  try:
    with open("/proc/self/status") as f:
      for line in f:
        if line.startswith("VmRSS:"):
          out["rss_bytes"] = int(line.split()[1]) * 1024
        elif line.startswith("VmHWM:"):
          out["rss_peak_bytes"] = int(line.split()[1]) * 1024
  except OSError:
    pass
  return out


def _MemDelta(before, after):
  """Per-section memory figure for the BENCH json: deltas for in-use
  counters; high-water marks as a RAISED-BY delta (the absolute HWM is
  process-lifetime and would just echo the biggest earlier section) plus
  the running absolute under an explicitly-cumulative name. Gives every
  section (and future memory optimisations) a trajectory to compare
  against."""
  out = {}
  for key in ("device_bytes_in_use", "rss_bytes"):
    if before.get(key) is not None and after.get(key) is not None:
      out[f"{key}_delta_mb"] = round(
          (after[key] - before[key]) / 1e6, 1)
  for key in ("device_peak_bytes", "rss_peak_bytes"):
    if after.get(key) is not None:
      name = key.replace("_bytes", "")
      out[f"{name}_so_far_mb"] = round(after[key] / 1e6, 1)
      if before.get(key) is not None:
        out[f"{name}_raised_mb"] = round(
            max(after[key] - before[key], 0) / 1e6, 1)
  return out


def _DonateState(on_tpu):
  """donate_argnums for train-state args: donation only buys the in-place
  update on accelerators; the CPU backend warns 'Some donated buffers were
  not usable' for every non-aliasable leaf (runners/program.py gating)."""
  return (0,) if on_tpu else ()


def _MarginalStepTime(dispatch_fn, fetch_fn, reps_lo, reps_hi):
  """Per-step wall time via two-point marginal measurement.

  On tunneled PJRT backends `block_until_ready` can return before the device
  work finishes (the round-1 failure mode: 172 'MFU'); only fetching a value
  that data-depends on the result truly synchronizes, and each fetch pays the
  tunnel round-trip (~75ms here). Timing reps_hi and reps_lo dispatch loops
  and differencing cancels both the fetch latency and dispatch overhead.
  """

  def _Run(reps):
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
      out = dispatch_fn(out)
    fetch_fn(out)
    return time.perf_counter() - t0

  _Run(2)  # warmup (compile cache hit + tunnel warm)
  t_lo = _Run(reps_lo)
  t_hi = _Run(reps_hi)
  return max((t_hi - t_lo) / (reps_hi - reps_lo), 1e-9)


def _BenchFlashAttention(jax, jnp, on_tpu):
  """Flash Pallas kernel vs naive einsum attention: fwd+bwd step time."""
  from lingvo_tpu.ops import flash_attention
  b, t, n, h = (4, 2048, 8, 128) if on_tpu else (1, 256, 2, 32)
  q = jax.random.normal(jax.random.PRNGKey(0), (b, t, n, h), jnp.bfloat16)
  k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h), jnp.bfloat16)
  v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h), jnp.bfloat16)

  def flash_loss(q, k, v):
    return jnp.sum(flash_attention.FlashAttention(
        q, k, v, causal=True).astype(jnp.float32) ** 2)

  def naive_loss(q, k, v):
    s = jnp.einsum("bqnh,bknh->bnqk", q, k).astype(jnp.float32)
    s = s / (h ** 0.5)
    mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.sum(jnp.einsum("bnqk,bknh->bqnh", p, v).astype(
        jnp.float32) ** 2)

  def timed(fn):
    g = jax.jit(jax.value_and_grad(fn, argnums=(0, 1, 2)))
    reps_lo, reps_hi = (3, 13) if on_tpu else (1, 3)
    return _MarginalStepTime(
        lambda _: g(q, k, v),
        lambda out: float(out[0]),  # scalar fetch = true synchronization
        reps_lo, reps_hi)

  flash_t = timed(flash_loss)
  naive_t = timed(naive_loss)
  return {
      "flash_ms": round(flash_t * 1e3, 3),
      "naive_ms": round(naive_t * 1e3, 3),
      "flash_speedup": round(naive_t / flash_t, 3),
      "shape_btnh": [b, t, n, h],
      # which lowering the shape heuristic picked (small off-TPU shapes
      # fall back to plain XLA instead of Pallas interpret mode)
      "lowering": flash_attention.SelectedLowering(t, n, h),
  }


def _BenchDecode(jax, jnp, model_registry, on_tpu):
  """Decode fast path: chunked prefill + length-aware paged flash decode.

  Measures the serving hot loop on a tiny LM: (a) prompt prefill via the
  legacy per-token ExtendStep scan vs one chunked Prefill pass, (b)
  steady-state decode step latency with the dense full-cache read vs the
  paged read (`decode_page_size`), at max_len >= 4 * prompt_len where the
  early decode steps touch only ~1/4 of the cache.
  """
  from lingvo_tpu.core import attention as attention_lib
  p_len, t_max = (64, 192) if not on_tpu else (256, 768)
  page = 64 if not on_tpu else 128
  total = p_len + t_max
  b = 4

  def _MakeTask(page_size):
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    if on_tpu:
      # DenseLmTiny's dim_per_head (64/4 = 16) can't tile the Pallas decode
      # kernel (SupportedOnTpu needs a 128-lane-aligned head dim), so the
      # paged path would silently fall back to dense and the TPU decode
      # budget would time two identical samplers
      mp.task.model_dim = 512
      mp.task.num_heads = 4
      mp.task.hidden_dim = 1024
    mp.task.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
        decode_page_size=page_size)
    task = mp.task.Instantiate()
    task.FinalizePaths()
    return task

  task_dense = _MakeTask(0)
  task_paged = _MakeTask(page)
  # identical architectures -> one theta serves both
  theta = task_dense.InstantiateVariables(jax.random.PRNGKey(0))
  prompts = jax.random.randint(jax.random.PRNGKey(1), (b, p_len), 1,
                               task_dense.p.vocab_size)

  @jax.jit
  def prime_legacy(theta, prompts):
    states = task_dense.InitDecodeState(theta, b, total)

    def _Prime(carry, ids_t):
      states = carry
      logits, states = task_dense.ExtendStep(theta, ids_t[:, None], states)
      return states, logits

    states, logits = jax.lax.scan(_Prime, states, prompts.swapaxes(0, 1))
    return logits[-1]

  @jax.jit
  def prefill_chunked(theta, prompts):
    states = task_dense.InitDecodeState(theta, b, total)
    logits, states = task_dense.Prefill(theta, prompts, states,
                                        live_len=p_len)
    return logits[:, -1, :]

  def _MakeSampler(task):
    @jax.jit
    def run(theta, prompts):
      states = task.InitDecodeState(theta, b, total)
      logits, states = task.Prefill(theta, prompts, states, live_len=p_len)

      def _Sample(carry, _):
        states, logits = carry
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        new_logits, states = task.ExtendStep(theta, nxt[:, None], states)
        return (states, new_logits), nxt

      (_, _), out = jax.lax.scan(_Sample, (states, logits[:, -1, :]),
                                 None, length=t_max)
      return out

    return run

  sample_dense = _MakeSampler(task_dense)
  sample_paged = _MakeSampler(task_paged)

  # ask the real eligibility gate whether sample_paged takes the paged read
  # or silently fell back to dense (in which case decode_speedup ~1.0 means
  # "never ran", not "regressed")
  stack = task_paged.stack
  atten = (getattr(stack, "body", None) or stack.x_layers[0]).self_atten.atten
  paged_active = bool(atten.PagedDecodeEligible(total))
  paged_path = ("pallas" if on_tpu else "xla") if paged_active else "dense"

  # the dense-vs-paged step delta is a fraction of a ms; (1,3) reps put CPU
  # timer noise at the same scale as the signal, so spend a few extra
  # seconds here for a stable decode_speedup
  reps = (2, 6) if on_tpu else (2, 10)
  fetch = lambda out: float(jnp.sum(out))
  t_prime = _MarginalStepTime(lambda _: prime_legacy(theta, prompts), fetch,
                              *reps)
  t_prefill = _MarginalStepTime(lambda _: prefill_chunked(theta, prompts),
                                fetch, *reps)
  t_dense = _MarginalStepTime(lambda _: sample_dense(theta, prompts), fetch,
                              *reps)
  t_paged = _MarginalStepTime(lambda _: sample_paged(theta, prompts), fetch,
                              *reps)
  # the samplers share the chunked-prefill cost; difference is decode steps.
  # clamp at 0: t_prefill comes from a separately-jitted program, so timer
  # noise on low rep counts could otherwise report negative step latency
  step_dense = max(t_dense - t_prefill, 0.0) / t_max
  step_paged = max(t_paged - t_prefill, 0.0) / t_max
  return {
      "batch": b, "prompt_len": p_len, "decode_steps": t_max,
      "max_len": total, "page_size": page, "paged_path": paged_path,
      "prefill_legacy_scan_ms": round(t_prime * 1e3, 2),
      "prefill_chunked_ms": round(t_prefill * 1e3, 2),
      "prefill_speedup": round(t_prime / t_prefill, 2),
      "prefill_sequential_atten_calls": {"legacy": p_len, "chunked": 1},
      "decode_step_dense_ms": round(step_dense * 1e3, 3),
      "decode_step_paged_ms": round(step_paged * 1e3, 3),
      "decode_tokens_per_sec_dense": round(b * t_max / max(
          t_dense - t_prefill, 1e-9), 1),
      "decode_tokens_per_sec_paged": round(b * t_max / max(
          t_paged - t_prefill, 1e-9), 1),
      "decode_speedup": round(step_dense / max(step_paged, 1e-9), 3),
  }


def _BenchServing(jax, jnp, model_registry, on_tpu):
  """Continuous-batching serving engine vs batch-synchronous baseline.

  A seeded Poisson request stream with mixed prompt/output lengths is
  played in real time against (a) `serving/engine.py`'s ServingLoop and
  (b) the batch-synchronous GShardDecode serving pattern: requests form
  fixed batches in arrival order, every batch pads to the global max
  prompt width, decodes the global max output length for everyone, and
  the next batch cannot start until the previous one finishes — the
  head-of-line blocking the engine exists to remove. Reports useful
  tokens/sec, p50/p99 per-request latency, and KV page utilization; the
  engine's `paged_path` says which attention lowering actually ran
  (silent dense fallback must never masquerade as a paged run).
  """
  from lingvo_tpu.runners import gshard_decode
  from lingvo_tpu.serving import engine as engine_lib

  rng = np.random.RandomState(0)
  # load is deliberately past saturation (mean inter-arrival well under the
  # per-request service time): an underloaded server is arrival-bound and
  # both architectures tie on throughput; the interesting regime is where
  # the queue is never empty and scheduling quality decides tokens/sec
  if on_tpu:
    n_req, b_slots, page, max_seq = 48, 8, 128, 1024
    p_lo, p_hi, o_lo, o_hi = 16, 256, 16, 256
    mean_gap_s = 0.005
  else:
    n_req, b_slots, page, max_seq = 24, 4, 8, 64
    p_lo, p_hi, o_lo, o_hi = 4, 32, 2, 32
    mean_gap_s = 0.005

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True   # serve rotary models (position-aware decode)
  if on_tpu:
    # 128-lane-aligned head dim so the Pallas block-decode kernel tiles
    mp.task.model_dim = 512
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
  else:
    # big enough that per-token model compute dominates per-step dispatch
    # overhead — at DenseLmTiny size the comparison measures the Python
    # host loop, not the serving architecture
    mp.task.model_dim = 256
    mp.task.num_layers = 4
    mp.task.num_heads = 4
    mp.task.hidden_dim = 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size

  prompts = [rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
      np.int32) for _ in range(n_req)]
  max_news = rng.randint(o_lo, o_hi + 1, n_req)
  arrivals = np.concatenate(
      [[0.0], np.cumsum(rng.exponential(mean_gap_s, n_req - 1))])
  total_useful = int(np.sum(max_news))

  # -- continuous-batching engine (played in real time) ----------------------
  pages_per_seq = -(-max_seq // page)
  # prefill_chunk trades prefill progress per step against padding waste:
  # decode rows riding a mixed step compute all C positions for 1 token
  eng = engine_lib.ServingLoop(
      task, theta, page_size=page, num_pages=b_slots * pages_per_seq,
      max_batch=b_slots, max_seq_len=max_seq,
      prefill_chunk=16 if on_tpu else 4)
  eng.Start()
  # warmup outside the timed window: compiles BOTH step programs (the
  # mixed prefill step and the pure decode step)
  eng.Submit([1, 2, 3], 4).Result(timeout=1200)
  t0 = time.perf_counter()
  handles = []
  for i in range(n_req):
    dt = t0 + arrivals[i] - time.perf_counter()
    if dt > 0:
      time.sleep(dt)
    handles.append(eng.Submit(prompts[i], int(max_news[i])))
  for h in handles:
    h.Result(timeout=1200)
  eng_wall = time.perf_counter() - t0
  eng_lat = np.array([h.finish_time - h.submit_time for h in handles])
  eng_stats = eng.Stats()
  eng.Stop()

  # -- batch-synchronous baseline (same arrival process, same model) ---------
  p_len = int(max(len(p) for p in prompts))
  t_max = int(max(max_news))
  total = p_len + t_max

  def _RunBatchSync(theta, aligned, lens):
    states = task.InitDecodeState(theta, b_slots, total)
    slot = jnp.arange(total)[None, :]
    cache_paddings = (slot < (p_len - lens)[:, None]).astype(jnp.float32)
    logits, states = task.Prefill(theta, aligned, states,
                                  cache_paddings=cache_paddings,
                                  live_len=p_len)

    def _Sample(carry, _):
      states, lg = carry
      nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
      nl, states = task.ExtendStep(theta, nxt[:, None], states,
                                   cache_paddings=cache_paddings)
      return (states, nl), nxt

    (_, _), out = jax.lax.scan(_Sample, (states, logits[:, -1, :]), None,
                               length=t_max)
    return out.swapaxes(0, 1)

  run_sync = jax.jit(_RunBatchSync)
  warm = np.zeros((b_slots, p_len), np.int32)
  jax.block_until_ready(run_sync(theta, jnp.asarray(warm),
                                 jnp.ones((b_slots,), np.int32)))

  prompt_mat = np.zeros((n_req, p_len), np.int32)
  for i, pr in enumerate(prompts):
    prompt_mat[i, :len(pr)] = pr
  t0 = time.perf_counter()
  finish = np.zeros(n_req)
  for g0 in range(0, n_req, b_slots):
    idx = list(range(g0, min(g0 + b_slots, n_req)))
    # a batch only forms once its LAST member has arrived
    dt = t0 + arrivals[idx[-1]] - time.perf_counter()
    if dt > 0:
      time.sleep(dt)
    lens_g = np.array([len(prompts[i]) for i in idx], np.int32)
    rows = prompt_mat[idx]
    if len(idx) < b_slots:   # ragged tail batch: pad with dummy rows
      pad = b_slots - len(idx)
      rows = np.concatenate([rows, np.zeros((pad, p_len), np.int32)])
      lens_g = np.concatenate([lens_g, np.ones((pad,), np.int32)])
    aligned = gshard_decode.GShardDecode._RightAlign(rows, lens_g,
                                                     width=p_len)
    jax.block_until_ready(run_sync(theta, jnp.asarray(aligned),
                                   jnp.asarray(lens_g)))
    tfin = time.perf_counter()
    for i in idx:
      finish[i] = tfin
  base_wall = time.perf_counter() - t0
  base_lat = finish - (t0 + arrivals)

  def _LatStats(lat):
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 1),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 1),
        "mean_ms": round(float(np.mean(lat)) * 1e3, 1),
    }

  eng_tps = total_useful / eng_wall
  base_tps = total_useful / base_wall
  kv = eng_stats["kv_pages"]
  return {
      "requests": n_req,
      "useful_tokens": total_useful,
      "prompt_len_range": [p_lo, p_hi],
      "output_len_range": [o_lo, o_hi],
      "mean_interarrival_ms": round(mean_gap_s * 1e3, 1),
      "slots": b_slots,
      "page_size": page,
      "paged_path": eng_stats["paged_path"],
      "dense_fallback_steps": eng_stats["dense_fallback_steps"],
      "engine": {
          "wall_s": round(eng_wall, 3),
          "tokens_per_sec": round(eng_tps, 1),
          "latency": _LatStats(eng_lat),
          "steps": eng_stats["steps"],
          "mixed_steps": eng_stats["mixed_steps"],
          "decode_steps": eng_stats["decode_steps"],
          "kv_page_peak_utilization": round(
              kv["peak_in_use"] / kv["num_pages"], 3),
      },
      "batch_synchronous": {
          "wall_s": round(base_wall, 3),
          "tokens_per_sec": round(base_tps, 1),
          "latency": _LatStats(base_lat),
          "padded_prompt_len": p_len,
          "decode_steps_per_batch": t_max,
      },
      "tokens_per_sec_speedup": round(eng_tps / max(base_tps, 1e-9), 3),
      "p99_latency_ratio": round(
          float(np.percentile(base_lat, 99))
          / max(float(np.percentile(eng_lat, 99)), 1e-9), 3),
  }


def _BenchMultiTenant(jax, jnp, model_registry, on_tpu):
  """SLO-aware scheduling vs FIFO under multi-tenant saturation.

  A seeded Poisson stream from a low-priority "bulk" tenant saturates
  the pool (long generations, arrivals past the service rate) while
  sparse high-priority "vip" probes arrive throughout. The SAME stream
  plays against the SAME device pool twice: scheduler_mode='fifo' (the
  legacy head-of-line-blocking baseline) and scheduler_mode='priority'
  with preemption by KV page spill to the host tier. Acceptance: vip
  p99 TTFT improves >= 2x under priority+spill, every request's greedy
  token stream is byte-identical in both arms (scheduling may delay
  tokens, never change them), and the preemption/spill counters that
  /statusz surfaces (scheduler section) are reported here along with
  the host tier's peak byte footprint."""
  from lingvo_tpu.serving import engine as engine_lib

  rng = np.random.RandomState(0)
  if on_tpu:
    n_bulk, n_vip, b_slots, page, max_seq = 24, 6, 8, 128, 1024
    bulk_out, vip_out, p_lo, p_hi = 192, 16, 32, 128
    mean_gap_s = 0.005
  else:
    n_bulk, n_vip, b_slots, page, max_seq = 10, 3, 2, 8, 64
    bulk_out, vip_out, p_lo, p_hi = 24, 4, 4, 12
    mean_gap_s = 0.003

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True
  if on_tpu:
    mp.task.model_dim = 512
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
  else:
    mp.task.model_dim = 256
    mp.task.num_layers = 4
    mp.task.num_heads = 4
    mp.task.hidden_dim = 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size

  # saturating bulk arrivals + vip probes spread across the bulk window
  reqs = []
  t = 0.0
  for _ in range(n_bulk):
    prompt = rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
        np.int32)
    reqs.append((t, prompt, bulk_out, 0, "bulk"))
    t += rng.exponential(mean_gap_s)
  for i in range(n_vip):
    prompt = rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
        np.int32)
    reqs.append((t * (i + 1) / (n_vip + 1), prompt, vip_out, 5, "vip"))
  reqs.sort(key=lambda r: r[0])

  full_pages = -(-(p_hi + bulk_out) // page)
  num_pages = b_slots * full_pages   # slot-bound: spill frees the SLOT

  def _Play(scheduler_mode):
    eng = engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=num_pages,
        max_batch=b_slots, max_seq_len=max_seq,
        prefill_chunk=16 if on_tpu else 4,
        scheduler_mode=scheduler_mode)
    # compile the step program off the clock
    eng.RunBatch(np.array([[1, 2, 3, 4]], np.int32),
                 np.array([4], np.int32), 2)
    eng.Start()
    t0 = time.perf_counter()
    handles = []
    for arrival, prompt, max_new, priority, tenant in reqs:
      dt = t0 + arrival - time.perf_counter()
      if dt > 0:
        time.sleep(dt)
      handles.append((eng.Submit(prompt, int(max_new), eos_id=None,
                                 priority=priority, tenant=tenant),
                      priority))
    streams = [h.Result(timeout=1200) for h, _ in handles]
    wall = time.perf_counter() - t0
    ttft = {}
    for h, pr in handles:
      ttft.setdefault(pr, []).append((h.first_token_time - h.submit_time)
                                     * 1e3)
    stats = eng.Stats()
    host_peak = (eng.sched.host_store.Stats()["peak_host_bytes"]
                 if eng.sched.host_store is not None else 0)
    eng.Stop()
    return streams, ttft, wall, stats["scheduler"], host_peak

  s_fifo, ttft_fifo, wall_fifo, _, _ = _Play("fifo")
  s_prio, ttft_prio, wall_prio, sched, host_peak = _Play("priority")

  def _P(v, q):
    return round(float(np.percentile(v, q)), 2)

  vip_p99_fifo = _P(ttft_fifo[5], 99)
  vip_p99_prio = _P(ttft_prio[5], 99)
  return {
      "requests": len(reqs),
      "bulk_requests": n_bulk,
      "vip_requests": n_vip,
      "slots": b_slots,
      "num_pages": num_pages,
      "streams_identical": s_fifo == s_prio,
      "vip_ttft_ms": {
          "fifo": {"p50": _P(ttft_fifo[5], 50), "p99": vip_p99_fifo},
          "priority_spill": {"p50": _P(ttft_prio[5], 50),
                             "p99": vip_p99_prio},
      },
      "bulk_ttft_ms": {
          "fifo": {"p50": _P(ttft_fifo[0], 50), "p99": _P(ttft_fifo[0], 99)},
          "priority_spill": {"p50": _P(ttft_prio[0], 50),
                             "p99": _P(ttft_prio[0], 99)},
      },
      "vip_p99_ttft_improvement": round(
          vip_p99_fifo / max(vip_p99_prio, 1e-9), 3),
      # the >= 2x acceptance bar (ISSUE 20): priority+spill must cut vip
      # tail TTFT at least in half at the same device pool
      "meets_2x_bar": vip_p99_fifo >= 2.0 * vip_p99_prio,
      "wall_s": {"fifo": round(wall_fifo, 3),
                 "priority_spill": round(wall_prio, 3)},
      "preemptions": sched["preemptions"],
      "restores": sched["restores"],
      "spilled_pages": sched["spilled_pages"],
      "restored_pages": sched["restored_pages"],
      # host-tier footprint rides the section's mem telemetry contract
      "host_tier_bytes_peak": host_peak,
  }


def _BenchObservability(jax, jnp, model_registry, on_tpu):
  """Tracing overhead on the serving hot path (ISSUE 12 acceptance).

  Replays the serving bench's seeded Poisson request stream twice through
  identical engines — lifecycle tracing ON (the default) vs OFF — and
  reports the tokens/sec ratio. Tracing must be effectively free
  (ratio >= 0.98 is the acceptance bar) and must never change decode
  results: both runs sample greedily, so the per-request output streams
  are asserted BYTE-IDENTICAL. The traced run's trace is exported to
  Chrome trace-event JSON and summarized via tools/trace_report.py, and
  the engine's one-shot compile records ride along.

  The fleet-telemetry layer rides the same stream: a third replay runs
  with the status endpoints live (`serve_port=0`) and a scraper thread
  hammering /metrics + /statusz the whole time — the exporter must also
  be effectively free (ratio >= 0.98) and change no tokens — and a
  two-replica fleet smoke scrapes + merges both /statusz documents the
  way tools/fleet_report.py does.
  """
  import tempfile
  import threading
  import urllib.request
  from lingvo_tpu.serving import engine as engine_lib

  # same stream + sizing as _BenchServing (the PR 6 recipe): load past
  # saturation so the per-token registry/trace work sits on a hot loop
  if on_tpu:
    n_req, b_slots, page, max_seq = 48, 8, 128, 1024
    p_lo, p_hi, o_lo, o_hi = 16, 256, 16, 256
    mean_gap_s = 0.005
  else:
    n_req, b_slots, page, max_seq = 24, 4, 8, 64
    p_lo, p_hi, o_lo, o_hi = 4, 32, 2, 32
    mean_gap_s = 0.005

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True
  if on_tpu:
    mp.task.model_dim = 512
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
  else:
    mp.task.model_dim = 256
    mp.task.num_layers = 4
    mp.task.num_heads = 4
    mp.task.hidden_dim = 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size

  rng = np.random.RandomState(0)
  prompts = [rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
      np.int32) for _ in range(n_req)]
  max_news = rng.randint(o_lo, o_hi + 1, n_req)
  arrivals = np.concatenate(
      [[0.0], np.cumsum(rng.exponential(mean_gap_s, n_req - 1))])
  total_useful = int(np.sum(max_news))
  pages_per_seq = -(-max_seq // page)

  def _Play(trace_on, serve=False):
    eng = engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=b_slots * pages_per_seq,
        max_batch=b_slots, max_seq_len=max_seq,
        prefill_chunk=16 if on_tpu else 4, trace=trace_on,
        serve_port=0 if serve else None, watchdog=serve or None)
    eng.Start()
    eng.Submit([1, 2, 3], 4).Result(timeout=1200)
    stop_scrape = threading.Event()
    scraper = None
    ok = {}
    eng.scrape_ok = ok
    if serve:
      # 1 scrape round/sec (x3 endpoints) — 15x above the default
      # Prometheus cadence, NOT a zero-sleep busy loop: each /statusz
      # runs engine.Stats() under the engine lock, and every socket
      # handoff between the handler thread and the GIL-heavy CPU engine
      # loop costs up to one switch-interval quantum, so hammering
      # measures scraper contention, not exporter overhead (the slow
      # soak in test_observe_export.py covers scrape-under-load
      # correctness; here the bar is the honest steady-state cost)
      def _Hammer():
        while not stop_scrape.wait(1.0):
          for path in ("/metrics", "/statusz", "/healthz"):
            try:
              with urllib.request.urlopen(eng.status_server.Url(path),
                                          timeout=5) as resp:
                resp.read()
              ok[path] = ok.get(path, 0) + 1
            except Exception:  # noqa: BLE001 - 503 healthz etc. is fine
              pass
      scraper = threading.Thread(target=_Hammer, daemon=True)
      scraper.start()
    t0 = time.perf_counter()
    handles = []
    for i in range(n_req):
      dt = t0 + arrivals[i] - time.perf_counter()
      if dt > 0:
        time.sleep(dt)
      handles.append(eng.Submit(prompts[i], int(max_news[i])))
    streams = tuple(tuple(h.Result(timeout=1200)) for h in handles)
    wall = time.perf_counter() - t0
    if scraper is not None:
      stop_scrape.set()
      scraper.join(timeout=10)
      # one synchronous post-replay round, outside the timed window: the
      # "scrape succeeds" guarantee must not depend on cadence phase. An
      # HTTP error status is still a successful scrape transaction.
      for path in ("/metrics", "/statusz", "/healthz"):
        try:
          with urllib.request.urlopen(eng.status_server.Url(path),
                                      timeout=5) as resp:
            resp.read()
        except urllib.error.HTTPError:
          pass
        ok[path] = ok.get(path, 0) + 1
    return eng, streams, wall

  # interleaved best-of-2 per mode: the stream replay is wall-clock timed
  # on a shared host, so a single run's ratio is noise-dominated; the min
  # wall per mode is the fair overhead comparison
  eng_on, streams_on, wall_on = _Play(True)
  stats_on = eng_on.Stats()
  # the traced run must yield one COMPLETE lifecycle per bench request
  # (+1 warmup), regardless of ring wraparound
  per_req = eng_on.trace.PerRequestMetrics()
  complete = sum(1 for m in per_req.values()
                 if m["finish_reason"] is not None and m["ttft_s"] is not None)
  assert complete >= n_req, (complete, n_req)
  trace_path = os.path.join(tempfile.mkdtemp(), "serving_trace.json")
  eng_on.trace.Export(trace_path)
  eng_on.Stop()

  eng_off, streams_off, wall_off = _Play(False)
  stats_off = eng_off.Stats()
  eng_off.Stop()

  eng2, streams_on2, wall_on2 = _Play(True)
  eng2.Stop()
  eng3, streams_off2, wall_off2 = _Play(False)
  eng3.Stop()
  wall_on = min(wall_on, wall_on2)
  wall_off = min(wall_off, wall_off2)

  # exporter-live replays: endpoints up and a scraper thread polling
  # /metrics+/statusz+/healthz. Each serve replay is INTERLEAVED with
  # fresh baseline + traced runs: whether a scrape round lands in a
  # GIL-heavy engine phase is phase-alignment luck, and host load drifts
  # over the bench's lifetime, so adjacent runs + min-wall per mode is
  # the only fair overhead comparison on a shared machine
  srv_walls, srv_streams = [], []
  scrape_ok = {}

  def _ServeRound():
    nonlocal wall_on, wall_off
    eng_s, s_streams, s_wall = _Play(True, serve=True)
    eng_s.Stop()
    srv_walls.append(s_wall)
    srv_streams.append(s_streams)
    for path, n in eng_s.scrape_ok.items():
      scrape_ok[path] = scrape_ok.get(path, 0) + n
    eng_b, b_streams, b_wall = _Play(False)
    eng_b.Stop()
    assert b_streams == streams_off
    wall_off = min(wall_off, b_wall)
    eng_t, t_streams, t_wall = _Play(True)
    eng_t.Stop()
    assert t_streams == streams_on
    wall_on = min(wall_on, t_wall)

  for _ in range(2):
    _ServeRound()
  # wall-clock minima are monotone, so extra rounds only sharpen the
  # floor estimate: keep pairing until both ratios clear the acceptance
  # bar or the round cap keeps total bench time bounded
  for _ in range(5):
    if (min(srv_walls) <= wall_off / 0.98 and
        wall_on <= wall_off / 0.98):
      break
    _ServeRound()
  wall_srv = min(srv_walls)
  # the ISSUE 13 acceptance bar: exporter live costs <= 2% tokens/sec,
  # and the scrape traffic actually succeeded against every endpoint
  assert wall_srv <= wall_off / 0.98, (
      f"exporter overhead above 2%: serve wall {wall_srv:.3f}s vs "
      f"baseline wall {wall_off:.3f}s")
  assert all(scrape_ok.get(p, 0) > 0
             for p in ("/metrics", "/statusz", "/healthz")), scrape_ok

  # tracing/serving may only change wall clock, never tokens
  assert streams_on == streams_off == streams_on2 == streams_off2, (
      "tracing changed decode results")
  assert all(s == streams_on for s in srv_streams), (
      "live status endpoints changed decode results")
  assert "trace" not in stats_off

  sys.path.insert(0, os.path.join(
      os.path.dirname(os.path.abspath(__file__)), "tools"))
  import trace_report
  summary = trace_report.Summary(trace_report.LoadTrace(trace_path))

  # two-replica fleet smoke: live engines scraped + merged like the
  # router (observe/aggregate.py; tools/fleet_report.py is the CLI)
  from lingvo_tpu.observe import aggregate as aggregate_lib
  fleet_engines = [
      engine_lib.ServingLoop(
          task, theta, page_size=page, num_pages=b_slots * pages_per_seq,
          max_batch=b_slots, max_seq_len=max_seq,
          prefill_chunk=16 if on_tpu else 4, serve_port=0).Start()
      for _ in range(2)]
  try:
    for k, eng in enumerate(fleet_engines):
      hs = [eng.Submit(prompts[j], 4) for j in range(2 + k)]
      for h in hs:
        h.Result(timeout=1200)
    docs = aggregate_lib.ScrapeAll(
        [f"127.0.0.1:{e.status_server.port}" for e in fleet_engines])
    merged = aggregate_lib.MergeStatusz(docs)
    per_replica_tokens = [
        e.Stats()["tokens_emitted"] for e in fleet_engines]
    fleet_tokens = merged["fleet"]["serving/tokens_emitted"]
    assert fleet_tokens == sum(per_replica_tokens), (
        fleet_tokens, per_replica_tokens)
    fleet = {
        "replicas": merged["replicas"],
        "tokens_emitted_per_replica": per_replica_tokens,
        "tokens_emitted_fleet": fleet_tokens,
        "least_loaded": aggregate_lib.LeastLoaded(docs),
    }
  finally:
    for eng in fleet_engines:
      eng.Stop()

  tps_on = total_useful / wall_on
  tps_off = total_useful / wall_off
  tps_srv = total_useful / wall_srv
  return {
      "requests": n_req,
      "useful_tokens": total_useful,
      "streams_identical": True,
      "tokens_per_sec_traced": round(tps_on, 1),
      "tokens_per_sec_untraced": round(tps_off, 1),
      # >= 0.98 is the acceptance bar: tracing is effectively free
      "tokens_per_sec_ratio": round(tps_on / max(tps_off, 1e-9), 3),
      "tokens_per_sec_exported": round(tps_srv, 1),
      # >= 0.98: the live endpoints + scraper load are effectively free
      "exporter_tokens_per_sec_ratio": round(
          tps_srv / max(tps_off, 1e-9), 3),
      "fleet": fleet,
      "trace": stats_on["trace"],
      "trace_export_path": trace_path,
      "latency_from_trace": {
          "ttft": summary["ttft"],
          "tpot": summary["tpot"],
          "queue_wait": summary["queue_wait"],
      },
      "compile": {
          name: {k: rec[k] for k in
                 ("compile_wall_s", "temp_bytes", "calls") if k in rec}
          for name, rec in stats_on["compile"].items()},
  }


def _BenchSpecDecode(jax, jnp, model_registry, on_tpu, variants=None):
  """Draft-and-verify speculative decoding vs the plain serving engine.

  The same seeded Poisson request stream (mixed prompt/output lengths,
  greedy sampling) is played in real time against the plain ServingLoop
  and against spec-decode engines (serving/spec_decode.py). Both decode
  greedily, so the spec engine's output streams must be BYTE-IDENTICAL
  to the baseline's — asserted here; speculation may only change wall
  clock, never tokens. Reports tokens_per_sec_speedup, the acceptance
  rate/histogram (the whole game: a rejected draft token is wasted
  draft+verify compute), p50/p99 latency, and rollback accounting.

  variants: [(draft_source, k)] or [(draft_source, k, w)] with
  draft_source in {"self", "model"} and w the draft-tree width (default 1
  = chain speculation); the default pair — chain k=8 vs the
  same-verify-width w=2 k=4 tree — reports `tree_vs_chain_speedup`, the
  tentpole's acceptance bar: at equal packed columns per row, sibling
  hedging must buy tokens/sec, not just acceptance depth. The sweep tool
  ladders the full (draft, k, w) grid.
  """
  from lingvo_tpu.serving import engine as engine_lib
  from lingvo_tpu.serving import spec_decode

  rng = np.random.RandomState(0)
  if on_tpu:
    n_req, b_slots, page, max_seq = 48, 8, 128, 1024
    p_lo, p_hi, o_lo, o_hi = 16, 256, 16, 256
    mean_gap_s = 0.005
  else:
    # decode-heavy output range: speculation only engages on pure-decode
    # iterations (mixed steps take the legacy path), so a prefill-bound
    # stream would measure Amdahl's law, not the verify machinery
    n_req, b_slots, page, max_seq = 24, 4, 8, 128
    p_lo, p_hi, o_lo, o_hi = 4, 32, 16, 64
    mean_gap_s = 0.005

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True
  if on_tpu:
    mp.task.model_dim = 512
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
  else:
    # same sizing rationale as _BenchServing: per-token model compute must
    # dominate host dispatch or the comparison measures the Python loop
    mp.task.model_dim = 256
    mp.task.num_layers = 4
    mp.task.num_heads = 4
    mp.task.hidden_dim = 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size
  depth = task.p.num_layers

  prompts = [rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
      np.int32) for _ in range(n_req)]
  max_news = rng.randint(o_lo, o_hi + 1, n_req)
  arrivals = np.concatenate(
      [[0.0], np.cumsum(rng.exponential(mean_gap_s, n_req - 1))])
  total_useful = int(np.sum(max_news))
  pages_per_seq = -(-max_seq // page)

  # independent draft model (the "model" variants): a much smaller pure
  # O(1)-state stack over the SAME vocab — pageless, so its decode rows
  # cost zero KV pages. Acceptance between two random-init models is NOT
  # predictive of a real distilled draft (both collapse to last-token
  # echo, so it skews high); the variant prices the catch-up/propose
  # machinery, and byte-identity holds at any acceptance.
  from lingvo_tpu.core import ssm as ssm_lib
  from lingvo_tpu.models.lm import layers as lm_layers
  dp = lm_layers.TransformerLm.Params().Set(
      name="draft", vocab_size=vocab, model_dim=64, num_layers=2,
      num_heads=2, hidden_dim=128, use_rotary=True,
      mixer_tpl=ssm_lib.GatedSSMLayer.Params().Set(state_dim=8,
                                                   chunk_size=4),
      mixer_atten_every_n=0)
  draft_task = dp.Instantiate()
  draft_task.FinalizePaths()
  draft_theta = draft_task.InstantiateVariables(jax.random.PRNGKey(7))

  def _MakeSpec(source, k, w=1):
    if source == "self":
      return spec_decode.SelfDraft(k=k, num_layers=1, w=w)
    return spec_decode.ModelDraft(draft_task, draft_theta, k=k, w=w)

  def _Play(spec):
    """Plays the stream in real time; returns (outputs, wall, lat, stats)."""
    eng = engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=b_slots * pages_per_seq,
        max_batch=b_slots, max_seq_len=max_seq,
        prefill_chunk=16 if on_tpu else 4, spec=spec)
    eng.Start()
    # warmup compiles every step program this engine owns (mixed, decode,
    # and — when spec — the draft + verify programs)
    eng.Submit([1, 2, 3], 8).Result(timeout=1200)
    t0 = time.perf_counter()
    handles = []
    for i in range(n_req):
      dt = t0 + arrivals[i] - time.perf_counter()
      if dt > 0:
        time.sleep(dt)
      handles.append(eng.Submit(prompts[i], int(max_news[i])))
    outs = [h.Result(timeout=1200) for h in handles]
    wall = time.perf_counter() - t0
    lat = np.array([h.finish_time - h.submit_time for h in handles])
    stats = eng.Stats()
    eng.Stop()
    return outs, wall, lat, stats

  def _LatStats(lat):
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 1),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 1),
        "mean_ms": round(float(np.mean(lat)) * 1e3, 1),
    }

  base_outs, base_wall, base_lat, base_stats = _Play(None)
  base_tps = total_useful / base_wall
  result = {
      "requests": n_req,
      "useful_tokens": total_useful,
      "prompt_len_range": [p_lo, p_hi],
      "output_len_range": [o_lo, o_hi],
      "mean_interarrival_ms": round(mean_gap_s * 1e3, 1),
      "slots": b_slots,
      "target_layers": depth,
      "paged_path": base_stats["paged_path"],
      "baseline": {
          "wall_s": round(base_wall, 3),
          "tokens_per_sec": round(base_tps, 1),
          "latency": _LatStats(base_lat),
          "steps": base_stats["steps"],
      },
      "variants": [],
  }
  for variant in (variants or [("self", 8), ("self", 4, 2)]):
    source, k = variant[0], variant[1]
    w = variant[2] if len(variant) > 2 else 1
    outs, wall, lat, stats = _Play(_MakeSpec(source, k, w))
    # the bar that makes the speedup honest: byte-identical greedy streams
    assert outs == base_outs, (
        f"spec({source}, k={k}, w={w}) diverged from greedy")
    tps = total_useful / wall
    drafted = stats["draft_tokens"]
    result["variants"].append({
        "draft": source,
        "k": k,
        "w": w,
        "draft_layers": 1 if source == "self" else draft_task.p.num_layers,
        "wall_s": round(wall, 3),
        "tokens_per_sec": round(tps, 1),
        "tokens_per_sec_speedup": round(tps / max(base_tps, 1e-9), 3),
        "latency": _LatStats(lat),
        "output_streams_identical": True,
        "steps": stats["steps"],
        "spec_cycles": stats["spec_cycles"],
        "spec_branches": stats["spec_branches"],
        "spec_width_clamps": stats["spec_width_clamps"],
        "acceptance_rate": round(
            stats["accepted_tokens"] / max(drafted, 1), 3),
        "accepted_len_hist": stats["accepted_len_hist"],
        "accepted_depth_hist": stats["accepted_depth_hist"],
        "rolled_back_tokens": stats["kv_pages"]["rolled_back_tokens"],
    })
  best = max(v["tokens_per_sec_speedup"] for v in result["variants"])
  result["tokens_per_sec_speedup"] = best
  chains = [v for v in result["variants"] if v["w"] == 1]
  trees = [v for v in result["variants"] if v["w"] > 1]
  if chains and trees:
    # the tentpole's bar: the best tree arm vs the best chain arm
    result["tree_vs_chain_speedup"] = round(
        max(t["tokens_per_sec"] for t in trees)
        / max(max(c["tokens_per_sec"] for c in chains), 1e-9), 3)
  return result


def _BenchQuantServing(jax, jnp, model_registry, on_tpu):
  """f32 vs int8-KV serving engines at the SAME HBM byte budget.

  Both engines (serving/engine.py + quant/) get a page pool priced at the
  bytes the f32 engine's pool costs; the int8 engine's smaller
  kv_bytes_per_token (per-page-per-head scale sidecars included) buys it
  ~3x the pages. The same seeded Poisson request stream is played against
  each in real time. Acceptance keys: `kv_bytes_per_token_ratio` (the
  compression the sidecars actually leave), `score_delta_mean_abs`
  (teacher-forced next-token log-prob delta through the quantized decode
  cache — plain ScoreSequences never reads the KV cache, so the delta is
  measured through ExtendStep), `greedy_tokens_match` on fixed prompts,
  and the int8 engine's tokens/sec, which must not fall below f32's.
  """
  from lingvo_tpu.quant import kv as kv_quant
  from lingvo_tpu.serving import engine as engine_lib

  rng = np.random.RandomState(0)
  if on_tpu:
    n_req, b_slots, page, max_seq = 32, 8, 128, 1024
    p_lo, p_hi, o_lo, o_hi = 16, 256, 16, 256
    mean_gap_s = 0.005
  else:
    n_req, b_slots, page, max_seq = 16, 4, 8, 64
    p_lo, p_hi, o_lo, o_hi = 4, 32, 2, 32
    mean_gap_s = 0.005

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True
  if on_tpu:
    mp.task.model_dim = 512
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
  else:
    mp.task.model_dim = 256
    mp.task.num_layers = 4
    mp.task.num_heads = 4
    mp.task.hidden_dim = 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size

  prompts = [rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
      np.int32) for _ in range(n_req)]
  max_news = rng.randint(o_lo, o_hi + 1, n_req)
  arrivals = np.concatenate(
      [[0.0], np.cumsum(rng.exponential(mean_gap_s, n_req - 1))])
  total_useful = int(np.sum(max_news))

  # equal-HBM sizing: the f32 engine's pool bytes are the budget; int8's
  # smaller per-token footprint converts the same bytes into more pages
  bpt_f32 = kv_quant.StackKvCensus(task)["kv_bytes_per_token"]
  bpt_int8 = kv_quant.StackKvCensus(task, "int8")["kv_bytes_per_token"]
  pages_per_seq = -(-max_seq // page)
  pages_f32 = b_slots * pages_per_seq
  budget_bytes = pages_f32 * page * bpt_f32
  pages_int8 = int(budget_bytes // (page * bpt_int8))

  fixed_rows = [[5, 9, 2, 33, 17], [7, 7, 7]]
  fixed_prompts = np.zeros((2, 5), np.int32)
  fixed_lens = np.array([5, 3], np.int32)
  for i, r in enumerate(fixed_rows):
    fixed_prompts[i, :len(r)] = r

  def _Play(kv_cache_dtype, num_pages):
    eng = engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=num_pages,
        max_batch=b_slots, max_seq_len=max_seq,
        prefill_chunk=16 if on_tpu else 4,
        kv_cache_dtype=kv_cache_dtype)
    # fixed-prompt greedy streams (also compiles both step programs, so
    # the timed stream below starts warm)
    greedy = np.asarray(eng.RunBatch(fixed_prompts, fixed_lens, 8))
    eng.Start()
    t0 = time.perf_counter()
    handles = []
    for i in range(n_req):
      dt = t0 + arrivals[i] - time.perf_counter()
      if dt > 0:
        time.sleep(dt)
      handles.append(eng.Submit(prompts[i], int(max_news[i])))
    for h in handles:
      h.Result(timeout=1200)
    wall = time.perf_counter() - t0
    lat = np.array([h.finish_time - h.submit_time for h in handles])
    stats = eng.Stats()
    eng.Stop()
    return greedy, wall, lat, stats

  g_f, wall_f, lat_f, stats_f = _Play(None, pages_f32)
  g_8, wall_8, lat_8, stats_8 = _Play("int8", pages_int8)

  # teacher-forced decode-path log-prob delta (the numerics-contract
  # number docs/quantized_serving.md bounds)
  mp.task.kv_cache_dtype = "int8"
  task8 = mp.task.Instantiate()
  task8.FinalizePaths()
  ids = jnp.asarray(rng.randint(1, vocab, size=(2, 24)), jnp.int32)

  def _Score(tk):
    @jax.jit
    def run(theta, ids):
      b, t = ids.shape
      states = tk.InitDecodeState(theta, b, t)

      def _Step(states, ids_t):
        logits, states = tk.ExtendStep(theta, ids_t[:, None], states)
        return states, jax.nn.log_softmax(logits.astype(jnp.float32), -1)

      _, logps = jax.lax.scan(_Step, states, ids.swapaxes(0, 1))
      logps = logps.swapaxes(0, 1)
      return jnp.take_along_axis(logps[:, :-1], ids[:, 1:, None],
                                 axis=-1)[..., 0]

    return np.asarray(run(theta, ids))

  score_delta = float(np.mean(np.abs(_Score(task8) - _Score(task))))

  def _Lat(lat):
    return {
        "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 1),
        "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 1),
    }

  tps_f = total_useful / wall_f
  tps_8 = total_useful / wall_8
  return {
      "requests": n_req,
      "useful_tokens": total_useful,
      "slots": b_slots,
      "page_size": page,
      "budget_bytes": budget_bytes,
      "kv_bytes_per_token": {"f32": bpt_f32, "int8": bpt_int8},
      "kv_bytes_per_token_ratio": round(bpt_f32 / bpt_int8, 3),
      "pages": {"f32": pages_f32, "int8": pages_int8},
      "greedy_tokens_match": bool(np.array_equal(g_f, g_8)),
      "score_delta_mean_abs": round(score_delta, 6),
      "f32_engine": {
          "paged_path": stats_f["paged_path"],
          "wall_s": round(wall_f, 3),
          "tokens_per_sec": round(tps_f, 1),
          "latency": _Lat(lat_f),
          "dense_fallback_steps": stats_f["dense_fallback_steps"],
      },
      "int8_engine": {
          "paged_path": stats_8["paged_path"],
          "wall_s": round(wall_8, 3),
          "tokens_per_sec": round(tps_8, 1),
          "latency": _Lat(lat_8),
          "dense_fallback_steps": stats_8["dense_fallback_steps"],
          "quantized_steps": stats_8["quantized_steps"],
          "kv_page_peak_utilization": round(
              stats_8["kv_pages"]["peak_in_use"]
              / stats_8["kv_pages"]["num_pages"], 3),
      },
      "tokens_per_sec_ratio_int8_vs_f32": round(tps_8 / max(tps_f, 1e-9), 3),
  }


def _BenchPrefixCache(jax, jnp, model_registry, on_tpu):
  """Global prefix cache win on a shared-system-prompt stream (ISSUE 14).

  A seeded Poisson stream where 90% of requests open with the same
  system prompt is played against two identical engines — prefix cache
  ON vs OFF — at the SAME page pool, sized well below slots x
  per-request footprint so admission concurrency is page-bound.
  Acceptance keys: `prefill_tokens_ratio` (cache off/on prompt tokens
  actually computed; the bar is >= 2x at 0.9 sharing), `slots_live_peak`
  (the cache engine must admit STRICTLY more concurrently at fixed HBM,
  because borrowed pages stop counting against the pool), and
  `streams_identical` (greedy token streams byte-identical cache on vs
  off — sharing may never shift a single token).
  """
  from lingvo_tpu.serving import engine as engine_lib

  rng = np.random.RandomState(0)
  if on_tpu:
    n_req, b_slots, page, max_seq = 32, 8, 128, 1024
    sys_len, t_lo, t_hi, o_lo, o_hi = 256, 32, 128, 32, 128
    mean_gap_s = 0.005
  else:
    n_req, b_slots, page, max_seq = 16, 4, 8, 64
    sys_len, t_lo, t_hi, o_lo, o_hi = 32, 4, 14, 8, 16
    mean_gap_s = 0.005

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True
  if on_tpu:
    mp.task.model_dim = 512
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
  else:
    mp.task.model_dim = 256
    mp.task.num_layers = 4
    mp.task.num_heads = 4
    mp.task.hidden_dim = 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size

  # 0.9 share fraction: most requests open with the same system prompt
  sys_prompt = rng.randint(1, vocab, sys_len).astype(np.int32)
  prompts = []
  for i in range(n_req):
    tail = rng.randint(1, vocab, rng.randint(t_lo, t_hi + 1)).astype(
        np.int32)
    if rng.rand() < 0.9:
      prompts.append(np.concatenate([sys_prompt, tail]))
    else:
      prompts.append(tail)
  max_news = rng.randint(o_lo, o_hi + 1, n_req)
  arrivals = np.concatenate(
      [[0.0], np.cumsum(rng.exponential(mean_gap_s, n_req - 1))])
  total_useful = int(np.sum(max_news))

  # page-bound pool: each shared-prompt request footprints ~full_pages
  # pages; give the pool roughly half of slots x footprint so the OFF
  # engine cannot fill its slots while the ON engine (whose borrowers are
  # charged only their uncached remainder) can
  full_pages = -(-(sys_len + t_hi + o_hi) // page)
  num_pages = (b_slots * full_pages) // 2

  def _Play(prefix_cache):
    eng = engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=num_pages,
        max_batch=b_slots, max_seq_len=max_seq,
        prefill_chunk=16 if on_tpu else 4,
        prefix_cache=prefix_cache)
    # warm both compile programs AND (on the cache engine) the tree, so
    # the timed stream measures steady-state sharing, not cold-start
    warm = np.zeros((1, sys_len), np.int32)
    warm[0] = sys_prompt
    eng.RunBatch(warm, np.array([sys_len], np.int32), 4)
    eng.Start()
    t0 = time.perf_counter()
    handles = []
    for i in range(n_req):
      dt = t0 + arrivals[i] - time.perf_counter()
      if dt > 0:
        time.sleep(dt)
      handles.append(eng.Submit(prompts[i], int(max_news[i])))
    streams = [h.Result(timeout=1200) for h in handles]
    wall = time.perf_counter() - t0
    stats = eng.Stats()
    eng.Stop()
    return streams, wall, stats

  s_off, wall_off, stats_off = _Play(None)
  s_on, wall_on, stats_on = _Play(True)

  pt_off = stats_off["prompt_tokens"]
  pt_on = stats_on["prompt_tokens"]
  peak_off = stats_off["scheduler"]["slots_live_peak"]
  peak_on = stats_on["scheduler"]["slots_live_peak"]
  return {
      "requests": n_req,
      "useful_tokens": total_useful,
      "share_fraction": 0.9,
      "system_prompt_tokens": sys_len,
      "slots": b_slots,
      "page_size": page,
      "num_pages": num_pages,
      "streams_identical": s_on == s_off,
      "prefill_tokens": {"off": pt_off, "on": pt_on},
      "prefill_tokens_ratio": round(pt_off / max(pt_on, 1), 3),
      "slots_live_peak": {"off": peak_off, "on": peak_on},
      "concurrency_strictly_higher": bool(peak_on > peak_off),
      "kv_page_peak": {"off": stats_off["kv_pages"]["peak_in_use"],
                       "on": stats_on["kv_pages"]["peak_in_use"]},
      "prefix_cache": stats_on["prefix_cache"],
      "off_engine": {"wall_s": round(wall_off, 3),
                     "tokens_per_sec": round(total_useful / wall_off, 1)},
      "on_engine": {"wall_s": round(wall_on, 3),
                    "tokens_per_sec": round(total_useful / wall_on, 1)},
  }


def _BenchFleet(jax, jnp, model_registry, on_tpu):
  """Disaggregated serving fleet: prefix router + prefill/decode split
  (ISSUE 19). Two arms, each against its honest baseline on an identical
  seeded request tape, greedy streams byte-compared in every arm:

  - **routing**: 4 chat sessions, each opening with its own long system
    prompt, into a 2-replica fleet whose per-replica page pools hold
    only ~2 of the 4 prompts. The prefix-aware router pins each session
    to one home, so the fleet's caches partition the working set;
    round-robin sprays every session across both replicas and thrashes
    both pools. Acceptance: `prefill_tokens_ratio` (round_robin /
    prefix prompt tokens actually computed; bar >= 1.5 at ~0.9 share
    fraction) and `streams_identical` across prefix, round_robin AND a
    single big-pool replica.
  - **disagg**: short interactive probes decode while long, length-
    varied prompts stream in. Unified = two step_mode='legacy' replicas
    doing both jobs (a mixed legacy step widens to prefill_chunk, so a
    long prefill genuinely stalls co-scheduled decodes); disagg = one
    prefill worker + one legacy decode replica receiving finished KV
    pages page-granularly (engine.AdoptPrefix), so the decode replica
    never computes more than a page-tail of prompt. Acceptance: probe
    `decode_p99_tpot_ratio` (disagg / unified; bar <= 1.1) and
    `streams_identical` between the arms.
  """
  from lingvo_tpu.serving import engine as engine_lib
  from lingvo_tpu.serving import fleet as fleet_lib

  rng = np.random.RandomState(0)
  if on_tpu:
    page, pool, big_pool, b_slots, chunk = 128, 24, 96, 1, 128
    sys_len, tail_len, max_new, max_seq = 512, 64, 32, 1024
    d_pool, d_slots, d_seq = 256, 4, 2048
    bg_lo, bg_hi, bg_new, n_bg, n_probe, probe_new = 128, 1024, 16, 12, 8, 32
  else:
    page, pool, big_pool, b_slots, chunk = 8, 12, 48, 1, 8
    sys_len, tail_len, max_new, max_seq = 32, 4, 8, 64
    d_pool, d_slots, d_seq = 48, 4, 96
    bg_lo, bg_hi, bg_new, n_bg, n_probe, probe_new = 8, 64, 4, 10, 8, 8

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True
  if on_tpu:
    mp.task.model_dim = 512
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
  else:
    mp.task.model_dim = 256
    mp.task.num_layers = 4
    mp.task.num_heads = 4
    mp.task.hidden_dim = 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size

  # -- routing arm ------------------------------------------------------------
  n_sessions = 4
  sys_prompts = [rng.randint(1, vocab, sys_len).astype(np.int32)
                 for _ in range(n_sessions)]

  def _Turn(s):
    tail = rng.randint(1, vocab, tail_len).astype(np.int32)
    return np.concatenate([sys_prompts[s], tail])

  openers = [_Turn(s) for s in range(n_sessions)]
  steady = []
  for i in range(20):   # 18 session turns + 2 unshared: 0.9 share fraction
    if i % 10 == 9:
      steady.append((rng.randint(1, vocab, sys_len + tail_len).astype(
          np.int32), None))
    else:
      steady.append((_Turn(i % n_sessions), i % n_sessions))
  # shuffled so round_robin's alternation can't accidentally partition the
  # sessions the way the prefix router does on purpose
  rng.shuffle(steady)
  share = (n_sessions + sum(1 for _, s in steady if s is not None)) / (
      n_sessions + len(steady))
  load_key = ("scheduler/queue_depth", "scheduler/slots_live")

  def _MkEng(np_pages):
    return engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=np_pages, max_batch=b_slots,
        max_seq_len=max_seq, prefill_chunk=chunk, prefix_cache=True)

  def _PlayRouting(policy, n_replicas=2, np_pages=None):
    np_pages = pool if np_pages is None else np_pages
    engines = {f"r{i}": _MkEng(np_pages) for i in range(n_replicas)}
    fl = fleet_lib.ServingFleet(engines, policy=policy,
                                load_key=load_key).Start()
    # opener burst: in-flight load spreads the sessions over the fleet
    hs = [fl.Submit(p, max_new, session=f"s{s}")
          for s, p in enumerate(openers)]
    streams = [h.Result(timeout=1200) for h in hs]
    for p, s in steady:   # steady state: sequential, fully deterministic
      h = fl.Submit(p, max_new, session=None if s is None else f"s{s}")
      streams.append(h.Result(timeout=1200))
    pt = sum(fl.Engine(lb).Stats()["prompt_tokens"] for lb in fl.order)
    emitted = {lb: fl.Engine(lb).Stats()["tokens_emitted"]
               for lb in fl.order}
    stats = fl.Stats()
    fl.Stop()
    return streams, pt, emitted, stats

  s_prefix, pt_prefix, em_prefix, fstats = _PlayRouting("prefix")
  s_rr, pt_rr, em_rr, _ = _PlayRouting("round_robin")
  s_single, pt_single, _, _ = _PlayRouting("prefix", n_replicas=1,
                                           np_pages=big_pool)
  ratio = pt_rr / max(pt_prefix, 1)

  # -- disaggregation arm -----------------------------------------------------
  bg_prompts = [rng.randint(1, vocab, int(L)).astype(np.int32)
                for L in rng.randint(bg_lo, bg_hi + 1, n_bg)]
  probe_prompts = [rng.randint(1, vocab, page - 1).astype(np.int32)
                   for _ in range(n_probe)]   # sub-page: never handed off

  def _MkLegacy():
    return engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=d_pool, max_batch=d_slots,
        max_seq_len=d_seq, prefill_chunk=chunk, prefix_cache=True,
        step_mode="legacy")

  def _PlayDisagg(disagg):
    if disagg:
      fl = fleet_lib.ServingFleet({"d0": _MkLegacy()},
                                  prefill={"p0": _MkLegacy()}).Start()
    else:
      fl = fleet_lib.ServingFleet({"u0": _MkLegacy(), "u1": _MkLegacy()},
                                  policy="round_robin").Start()
    streams, bg_handles, tpot = {}, [], []
    pi = 0
    for i, p in enumerate(bg_prompts):
      bg_handles.append((i, fl.Submit(p, bg_new)))
      if i % 2 == 1 and pi < n_probe:
        # probe while prefills are in flight: TPOT feels the interference
        t0 = time.perf_counter()
        h = fl.Submit(probe_prompts[pi], probe_new)
        streams[f"probe{pi}"] = h.Result(timeout=1200)
        tpot.append((time.perf_counter() - t0) / probe_new)
        pi += 1
    while pi < n_probe:
      t0 = time.perf_counter()
      h = fl.Submit(probe_prompts[pi], probe_new)
      streams[f"probe{pi}"] = h.Result(timeout=1200)
      tpot.append((time.perf_counter() - t0) / probe_new)
      pi += 1
    for i, h in bg_handles:
      streams[f"bg{i}"] = h.Result(timeout=1200)
    stats = fl.Stats()
    fl.Stop()
    return streams, np.asarray(tpot, np.float64), stats

  su, tu, _ = _PlayDisagg(False)
  sd, td, dstats = _PlayDisagg(True)
  u50, u99 = np.percentile(tu, 50), np.percentile(tu, 99)
  d50, d99 = np.percentile(td, 50), np.percentile(td, 99)

  return {
      "routing": {
          "sessions": n_sessions,
          "requests": n_sessions + len(steady),
          "share_fraction": round(share, 3),
          "system_prompt_tokens": sys_len,
          "page_size": page,
          "num_pages_per_replica": pool,
          "prefill_tokens": {"prefix": pt_prefix, "round_robin": pt_rr,
                             "single_big_pool": pt_single},
          "prefill_tokens_ratio": round(ratio, 3),
          "routing_win": bool(ratio >= 1.5),
          "streams_identical": bool(s_prefix == s_rr == s_single),
          "tokens_emitted": {"prefix": em_prefix, "round_robin": em_rr},
          "router": fstats["router"],
      },
      "disagg": {
          "probes": n_probe,
          "background_prompts": n_bg,
          "prompt_len_range": [int(bg_lo), int(bg_hi)],
          "probe_tpot_ms": {
              "unified": {"p50": round(u50 * 1e3, 3),
                          "p99": round(u99 * 1e3, 3)},
              "disagg": {"p50": round(d50 * 1e3, 3),
                         "p99": round(d99 * 1e3, 3)}},
          "decode_p99_tpot_ratio": round(d99 / max(u99, 1e-9), 3),
          "disagg_win": bool(d99 <= 1.1 * u99),
          "streams_identical": bool(su == sd),
          "handoffs": dstats["handoffs"],
          "handoff_pages": dstats["handoff_pages"],
          "handoff_fallbacks": dstats["handoff_fallbacks"],
      },
  }


def _BenchRaggedStep(jax, jnp, model_registry, on_tpu, budget=None):
  """One ragged step program vs the padded three-program engine (ISSUE 17).

  The same seeded mixed-length greedy stream (SelfDraft speculation on)
  is played against two engines that differ ONLY in `step_mode`:
  'ragged' packs every live row into one [T]-token program where each
  token is real work; 'legacy' alternates the padded [B, chunk] mixed
  program, the [B, 1] decode program and the [B, k+1] verify program.
  Two arms vary prompt-length VARIANCE (the padding driver: a ragged
  chunk pads every short row to the longest, and prefill steps starve
  spec cycles). Acceptance keys, on the high-variance arm:
  `waste_per_step_ratio` (padded-waste tokens per step, legacy/ragged;
  bar >= 2x), `tokens_per_sec_ratio` (bar >= 1.15x), `decode_p99_ms`
  (ragged p99 decode-step latency must not degrade as variance grows
  while legacy's does), and `streams_identical` per arm (the collapse
  may never move a token). `budget` overrides the ragged engine's
  per-step prefill token budget (tools/ragged_sweep.py ladders it).
  """
  from lingvo_tpu.serving import engine as engine_lib
  from lingvo_tpu.serving import scheduler as scheduler_lib
  from lingvo_tpu.serving import spec_decode

  if on_tpu:
    n_req, b_slots, page, max_seq, chunk = 32, 8, 128, 2048, 64
    lo_band, hi_band, o_lo, o_hi = (96, 128), (8, 768), 32, 96
  else:
    n_req, b_slots, page, max_seq, chunk = 12, 4, 8, 96, 8
    lo_band, hi_band, o_lo, o_hi = (10, 14), (2, 48), 8, 16
  spec_k = 3

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  mp.task.use_rotary = True
  if on_tpu:
    mp.task.model_dim, mp.task.num_heads, mp.task.hidden_dim = 512, 4, 1024
  else:
    mp.task.model_dim, mp.task.num_layers = 256, 4
    mp.task.num_heads, mp.task.hidden_dim = 4, 512
  task = mp.task.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  vocab = task.p.vocab_size

  full_pages = -(-(hi_band[1] + o_hi) // page)
  num_pages = b_slots * full_pages   # roomy pool: step SHAPE is the subject

  def _MakeStream(band, seed):
    rng = np.random.RandomState(seed)
    prompts = [rng.randint(1, vocab, rng.randint(band[0], band[1] + 1))
               .astype(np.int32) for _ in range(n_req)]
    return prompts, rng.randint(o_lo, o_hi + 1, n_req)

  def _Play(mode, prompts, max_news):
    eng = engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=num_pages,
        max_batch=b_slots, max_seq_len=max_seq, prefill_chunk=chunk,
        spec=spec_decode.SelfDraft(k=spec_k, num_layers=1),
        step_mode=mode,
        prefill_token_budget=budget if mode == "ragged" else None)
    # warm every compiled program (legacy: mixed + decode + verify) so
    # the timed stream measures steady state, not compiles
    warm = np.zeros((2, 2 * chunk), np.int32)
    warm[:] = np.arange(1, 2 * chunk + 1)
    eng.RunBatch(warm, np.array([2 * chunk, 2], np.int32), 6)
    handles = [eng.Submit(p, int(m), eos_id=None)
               for p, m in zip(prompts, max_news)]
    step_ms, decode_live = [], []
    t0 = time.perf_counter()
    while eng.sched.HasWork():
      decode_live.append(any(
          s is not None and s.state is scheduler_lib.SeqState.DECODE
          for s in eng.sched.slots))
      t1 = time.perf_counter()
      eng.StepOnce()
      step_ms.append((time.perf_counter() - t1) * 1e3)
    wall = time.perf_counter() - t0
    streams = [h.Result(timeout=0) for h in handles]
    stats = eng.Stats()
    # device tokens dispatched per step vs tokens that were real work
    if mode == "ragged":
      dispatched = stats["steps"] * eng._ragged_t
    else:
      verify = stats["spec_cycles"]
      pure = stats["decode_steps"] - verify
      dispatched = (stats["mixed_steps"] * b_slots * chunk
                    + pure * b_slots + verify * b_slots * (spec_k + 1))
    useful = (stats["prompt_tokens"] + stats["tokens_emitted"]
              + stats["draft_tokens"])
    dp99 = [t for t, d in zip(step_ms, decode_live) if d]
    return {
        "streams": streams,
        "wall_s": wall,
        "steps": stats["steps"],
        "tokens_per_sec": sum(len(s) for s in streams) / wall,
        "waste_per_step": (dispatched - useful) / max(stats["steps"], 1),
        "decode_p99_ms": float(np.percentile(dp99, 99)) if dp99 else 0.0,
        "spec_cycles": stats["spec_cycles"],
        "step_programs": stats["compile"]["step_programs"],
    }

  arms = {}
  for arm, band, seed in (("low_var", lo_band, 1), ("high_var", hi_band, 2)):
    prompts, max_news = _MakeStream(band, seed)
    r = _Play("ragged", prompts, max_news)
    l = _Play("legacy", prompts, max_news)
    arms[arm] = {
        "prompt_len_band": list(band),
        "streams_identical": r.pop("streams") == l.pop("streams"),
        "ragged": {k: round(v, 3) if isinstance(v, float) else v
                   for k, v in r.items()},
        "legacy": {k: round(v, 3) if isinstance(v, float) else v
                   for k, v in l.items()},
        "tokens_per_sec_ratio": round(
            r["tokens_per_sec"] / max(l["tokens_per_sec"], 1e-9), 3),
        "waste_per_step_ratio": round(
            l["waste_per_step"] / max(r["waste_per_step"], 1e-9), 3),
    }
  hv, lv = arms["high_var"], arms["low_var"]
  return {
      "requests": n_req, "slots": b_slots, "page_size": page,
      "prefill_chunk": chunk, "spec_k": spec_k,
      "prefill_token_budget": budget or chunk,
      "arms": arms,
      # acceptance: waste >= 2x lower, throughput >= 1.15x, and ragged
      # decode p99 must not blow up with prompt variance like legacy's
      "waste_ok": hv["waste_per_step_ratio"] >= 2.0,
      "throughput_ok": hv["tokens_per_sec_ratio"] >= 1.15,
      "decode_p99_ok": (hv["ragged"]["decode_p99_ms"]
                        <= 1.10 * hv["legacy"]["decode_p99_ms"]),
      "identical_ok": (hv["streams_identical"]
                       and lv["streams_identical"]),
      # the count-based waste ratio and byte-identity are valid anywhere;
      # the TIME bars (throughput, p99) only measure the claim on TPU,
      # where padded lanes cost real cycles and the Pallas kernel runs —
      # the CPU XLA twin pays its gathers without the lane win
      "valid_for_perf": bool(on_tpu),
  }


def _BenchFusedXent(jax, jnp, model_registry, on_tpu):
  """Dense vs fused blockwise LM-head xent (ops/fused_xent.py): full
  train-step time and peak memory at vocab 32k / 128k.

  The dense path's [B, T, V] logits (plus their f32 log-softmax copy) are
  the peak train-step activation at these vocabs and the one activation
  remat can't save; the fused path streams the vocab in
  `xent_block_size` chunks in both directions. Memory is read off the
  compiled executable (`memory_analysis().temp_size_in_bytes` — XLA's
  static temp-buffer plan, deterministic on CPU and TPU alike).
  """
  vocabs = (32768, 131072)
  block = 512 if on_tpu else 8192  # TPU: VMEM-sized Pallas blocks
  out = {
      "xent_block_size": block,
      # The fused bwd recomputes each block's logits (the flash-attention
      # time-for-memory trade): +1/3 head-gemm flops. On CPU f32 the head
      # gemm is compute-bound and the tiny trunk can't dilute it, so
      # step_time_ratio sits above 1 here; on TPU bf16 the dense head is
      # [B,T,V]-traffic-bound (bf16 logits + f32 cast + f32 log_probs
      # residuals) and the ratio is expected at or below 1.
      "note": "cpu step_time_ratio includes inherent bwd recompute",
  }
  for vocab in vocabs:
    per = {}
    for mode in ("dense", "fused"):
      mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                    "Train")
      mp.task.input = mp.input
      if on_tpu:
        mp.task.model_dim = 2048
        mp.task.num_layers = 4
        mp.task.num_heads = 16
        mp.task.hidden_dim = 8192
        mp.task.input.seq_len = 1024
        mp.task.input.batch_size = 8
        mp.task.remat_policy = "dots"
        mp.task.fprop_dtype = jnp.bfloat16
        from lingvo_tpu.core import attention as attention_lib
        mp.task.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
            use_flash_attention=True)
      else:
        mp.task.model_dim = 128
        mp.task.num_heads = 2
        mp.task.hidden_dim = 256
        mp.task.input.seq_len = 32
        mp.task.input.batch_size = 4
      mp.task.vocab_size = vocab
      mp.task.input.vocab_size = vocab
      mp.task.xent_block_size = block if mode == "fused" else 0
      task = mp.task.Instantiate()
      task.FinalizePaths()
      state = task.CreateTrainState(jax.random.PRNGKey(0))
      from lingvo_tpu.core import input_policy
      gen = input_policy.Instantiate(mp.input)
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      step_fn = jax.jit(task.TrainStep, donate_argnums=_DonateState(on_tpu))
      temp_mb = None
      try:
        # AOT-compile once and DISPATCH THROUGH THE EXECUTABLE: the jit
        # tracing cache doesn't see .lower().compile(), so calling
        # step_fn() afterwards would compile each config a second time.
        step_fn = step_fn.lower(state, batch).compile()
        temp_mb = round(
            step_fn.memory_analysis().temp_size_in_bytes / 1e6, 1)
      except Exception as e:  # noqa: BLE001
        print(f"bench: fused_xent memory_analysis unavailable: {e}",
              file=sys.stderr)

      def _Dispatch(_):
        nonlocal state
        state, step_out = step_fn(state, batch)
        return step_out

      t = _MarginalStepTime(
          _Dispatch, lambda o: float(o.metrics.loss[0]),
          *((3, 13) if on_tpu else (1, 3)))
      per[mode] = {"step_ms": round(t * 1e3, 2), "xla_temp_mb": temp_mb}
      del state, step_fn, batch
    entry = dict(per)
    entry["step_time_ratio"] = round(
        per["fused"]["step_ms"] / max(per["dense"]["step_ms"], 1e-9), 3)
    if per["dense"]["xla_temp_mb"] and per["fused"]["xla_temp_mb"]:
      entry["temp_mem_ratio"] = round(
          per["fused"]["xla_temp_mb"] / per["dense"]["xla_temp_mb"], 3)
    out[f"vocab_{vocab // 1024}k"] = entry
  return out


def _BenchInputPipeline(jax, jnp, model_registry, on_tpu):
  """Async device infeed vs sync host loop (runners/infeed.py).

  A tiny LM train loop is fed synthetic input whose per-batch host cost is
  tunable (a sleep standing in for tokenize/pack/augment work): at host
  cost ~= 0.5x / 1.0x the device step time, the sync path pays
  steps_per_loop * host_cost of device idle every loop while the async
  producer overlaps it with compute. Also asserts the pipelines consumed
  identical data: per-loop loss trajectories must match bitwise.
  """
  import shutil
  import tempfile

  from lingvo_tpu.core import input_policy
  from lingvo_tpu.runners import program as program_lib

  def _TaskParams():
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    if on_tpu:
      mp.task.model_dim = 512
      mp.task.num_heads = 4
      mp.task.hidden_dim = 2048
      mp.task.input.seq_len = 256
      mp.task.input.batch_size = 8
    else:
      mp.task.model_dim = 128
      mp.task.num_heads = 2
      mp.task.hidden_dim = 512
      mp.task.input.seq_len = 64
      mp.task.input.batch_size = 8
    return mp

  class _CostlyGen:
    """Wraps a generator, charging `cost_s` host seconds per batch."""

    def __init__(self, inner, cost_s=0.0):
      self._inner = inner
      self.cost_s = cost_s

    def GetPreprocessedInputBatch(self):
      if self.cost_s:
        time.sleep(self.cost_s)
      return self._inner.GetPreprocessedInputBatch()

    def GlobalBatchSize(self):
      return self._inner.GlobalBatchSize()

    def InfeedBatchSize(self):
      return self._inner.InfeedBatchSize()

  # bare device step time (the compute the input pipeline must keep fed)
  mp = _TaskParams()
  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  gen = input_policy.Instantiate(mp.input)
  batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
  step_fn = jax.jit(task.TrainStep, donate_argnums=_DonateState(on_tpu))

  def _Dispatch(_):
    nonlocal state
    state, out = step_fn(state, batch)
    return out

  step_s = _MarginalStepTime(_Dispatch, lambda o: float(o.metrics.loss[0]),
                             *((3, 13) if on_tpu else (2, 6)))
  del state, step_fn, batch

  spl, loops = 4, 6
  out = {
      "device_step_ms": round(step_s * 1e3, 3),
      "steps_per_loop": spl,
      "timed_loops": loops,
      "host_cost_model": "per-batch sleep (synthetic preprocessing)",
  }

  def _RunMode(async_on, host_cost):
    tmpdir = tempfile.mkdtemp(prefix="bench_infeed_")
    try:
      mp2 = _TaskParams()
      task2 = mp2.task.Instantiate()
      task2.FinalizePaths()
      st = task2.CreateTrainState(jax.random.PRNGKey(0))
      # host cost applies from the very first batch: the async producer's
      # prefetch during warmup pays the same per-batch cost the timed
      # window does, so the queue it starts with reflects steady state —
      # no zero-cost head start on the speedup claim
      cg = _CostlyGen(input_policy.Instantiate(mp2.input), host_cost)
      tp = program_lib.TrainProgram.Params().Set(
          task=mp2.task, logdir=tmpdir, name="bench",
          steps_per_loop=spl, on_device_loop=True,
          async_infeed=async_on, write_tensorboard=False)
      prog = program_lib.TrainProgram(tp, task=task2, input_generator=cg)
      st, _ = prog.Run(st)  # warmup: compiles the loop
      prog.Flush()
      t0 = time.perf_counter()
      waits = []
      for _ in range(loops):
        st, r = prog.Run(st)
        waits.append(r.get("infeed_wait_s", 0.0))
      prog.Flush()
      jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
      wall = time.perf_counter() - t0
      with open(os.path.join(tmpdir, "bench", "summaries.jsonl")) as f:
        losses = [(row["step"], row["loss"])
                  for row in map(json.loads, f) if row["step"] > spl]
      prog.Shutdown()
      return {
          "steps_per_sec": round(spl * loops / wall, 2),
          "wall_s": round(wall, 3),
          "infeed_wait_s_per_loop": round(float(np.mean(waits)), 4),
      }, losses
    finally:
      shutil.rmtree(tmpdir, ignore_errors=True)

  for ratio in (0.5, 1.0):
    host_cost = ratio * step_s
    sync, sync_losses = _RunMode(False, host_cost)
    asyn, async_losses = _RunMode(True, host_cost)
    # ideal: sync pays (step + host) per step; async pays max(step, host)
    ideal_speedup = (step_s + host_cost) / max(step_s, host_cost)
    speedup = asyn["steps_per_sec"] / max(sync["steps_per_sec"], 1e-9)
    overlap_eff = (speedup - 1.0) / max(ideal_speedup - 1.0, 1e-9)
    out[f"host_ratio_{ratio}"] = {
        "host_cost_ms_per_batch": round(host_cost * 1e3, 3),
        "sync": sync,
        "async": asyn,
        "async_speedup": round(speedup, 3),
        "ideal_speedup": round(ideal_speedup, 3),
        "overlap_efficiency": round(min(overlap_eff, 1.0), 3),
        "loss_trajectory_bitwise_equal": sync_losses == async_losses,
    }
  return out


def _BenchPipelinedExecutor(jax, jnp, model_registry, on_tpu):
  """Fully pipelined executor ladder (runners/executor.py, ISSUE 15).

  The lag-1 baseline (pipeline_depth=0) serializes once per cycle on the
  device: a blocking device_get(state.step) fences the loop, then the
  executor's host-side cycle work (metrics export, cadence decisions —
  modeled here as a tunable sleep at host-cost ratio 1.0 of the device
  loop) runs while the device idles, so each cycle costs L + H. With a
  k-deep dispatch window the host work overlaps the next dispatched
  loop: cycle cost -> max(L, H), ~2x at ratio 1.0. Asserts steps/sec
  monotone (with timing tolerance) in depth, >= 1.15x at depth 2 vs the
  lag-1 baseline, bitwise-equal loss trajectories, and a higher goodput
  productive share (the reclaimed badput shows up as `step` seconds
  instead of unaccounted `other`).
  """
  import shutil
  import tempfile

  from lingvo_tpu.core import input_policy
  from lingvo_tpu.observe import goodput as goodput_lib
  from lingvo_tpu.runners import executor as executor_lib
  from lingvo_tpu.runners import program as program_lib

  def _TaskParams():
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    if on_tpu:
      mp.task.model_dim = 512
      mp.task.num_heads = 4
      mp.task.hidden_dim = 2048
      mp.task.input.seq_len = 256
      mp.task.input.batch_size = 8
    else:
      mp.task.model_dim = 128
      mp.task.num_heads = 2
      mp.task.hidden_dim = 512
      mp.task.input.seq_len = 64
      mp.task.input.batch_size = 8
    return mp

  class _HostCostExecutor(executor_lib.ExecutorTpu):
    """Charges `host_cost_s` per exported metrics row — a stand-in for
    real per-cycle executor host work (dashboards, trial RPCs, cadence
    bookkeeping) that the pipelined loop overlaps with device compute."""
    host_cost_s = 0.0

    def _ExportMetrics(self, step, results):
      if self.host_cost_s:
        time.sleep(self.host_cost_s)
      super()._ExportMetrics(step, results)

  # bare device step time -> loop time L and the host cost H = 1.0 x L
  mp = _TaskParams()
  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  gen = input_policy.Instantiate(mp.input)
  batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
  step_fn = jax.jit(task.TrainStep, donate_argnums=_DonateState(on_tpu))

  def _Dispatch(_):
    nonlocal state
    state, out = step_fn(state, batch)
    return out

  step_s = _MarginalStepTime(_Dispatch, lambda o: float(o.metrics.loss[0]),
                             *((3, 13) if on_tpu else (2, 6)))
  del state, step_fn, batch

  # enough cycles that the pipelining effect (loops x H reclaimed)
  # dominates the fixed per-run overhead (orbax init, loop compile,
  # exit-time force save) that every rung pays identically
  spl, loops = 8, 20
  host_cost = spl * step_s  # ratio 1.0: H == device loop time L
  out = {
      "device_step_ms": round(step_s * 1e3, 3),
      "steps_per_loop": spl,
      "timed_loops": loops,
      "host_cost_ratio": 1.0,
      "host_cost_ms_per_cycle": round(host_cost * 1e3, 3),
      "host_cost_model": "per-cycle sleep in the executor's metrics export",
  }

  def _RunDepth(depth):
    tmpdir = tempfile.mkdtemp(prefix="bench_pipexec_")
    try:
      mp2 = _TaskParams()
      mp2.task.train.max_steps = spl * loops
      mp2.task.train.tpu_steps_per_loop = spl
      mp2.task.train.save_interval_steps = 10 ** 9
      task2 = mp2.task.Instantiate()
      task2.FinalizePaths()
      tp = program_lib.TrainProgram.Params().Set(
          task=mp2.task, logdir=tmpdir, name="bench",
          steps_per_loop=spl, on_device_loop=True,
          pipeline_depth=depth, write_tensorboard=False)
      sched = program_lib.SimpleProgramSchedule(
          program_lib.SimpleProgramSchedule.Params().Set(train_program=tp),
          task=task2,
          input_generators={"Train": input_policy.Instantiate(mp2.input)})
      ex = _HostCostExecutor(None, tmpdir, schedule=sched, task=task2)
      ex.host_cost_s = host_cost
      # pre-mark step 0 as saved: every rung skips the cadence save at the
      # top of cycle 1 and pays only the identical exit-time force save,
      # so the ladder isolates the dispatch-window effect
      ex._checkpointer._last_save_step = 0
      g0 = goodput_lib.Get().Snapshot()
      t0 = time.perf_counter()
      st = ex.Start()
      jax.block_until_ready(jax.tree_util.tree_leaves(st)[0])
      wall = time.perf_counter() - t0
      g1 = goodput_lib.Get().Snapshot()
      with open(os.path.join(tmpdir, "bench", "summaries.jsonl")) as f:
        losses = [(row["step"], row["loss"]) for row in map(json.loads, f)]
      step_delta = g1.get("step", 0.0) - g0.get("step", 0.0)
      return {
          "steps_per_sec": round(spl * loops / wall, 2),
          "wall_s": round(wall, 3),
          "goodput_step_s": round(step_delta, 3),
          "goodput_checkpoint_save_s": round(
              g1.get("checkpoint_save", 0.0)
              - g0.get("checkpoint_save", 0.0), 3),
          "goodput_step_share": round(step_delta / wall, 3),
      }, losses
    finally:
      shutil.rmtree(tmpdir, ignore_errors=True)

  _RunDepth(2)  # warmup rung: compile caches + orbax init, discarded
  ladder = {}
  losses_by_depth = {}
  for depth in (0, 1, 2, 4):
    ladder[depth], losses_by_depth[depth] = _RunDepth(depth)
    out[f"depth_{depth}"] = ladder[depth]

  sps = {d: ladder[d]["steps_per_sec"] for d in ladder}
  speedup = sps[2] / max(sps[0], 1e-9)
  out["depth2_speedup_vs_lag1"] = round(speedup, 3)
  out["ideal_speedup"] = 2.0  # (L + H) / max(L, H) at ratio 1.0
  out["loss_trajectory_bitwise_equal"] = all(
      losses_by_depth[d] == losses_by_depth[0] for d in (1, 2, 4))
  out["steps_per_sec_monotone"] = all(
      sps[b] >= 0.9 * sps[a]  # non-decreasing, with timing tolerance
      for a, b in ((0, 1), (1, 2), (2, 4)))
  assert out["loss_trajectory_bitwise_equal"], (
      "pipelining changed the math: per-loop losses diverged")
  assert out["steps_per_sec_monotone"], f"not monotone in depth: {sps}"
  assert speedup >= 1.15, (
      f"depth-2 speedup {speedup:.3f} < 1.15x vs lag-1 baseline ({sps})")
  assert (ladder[2]["goodput_step_share"]
          > ladder[0]["goodput_step_share"]), (
      "pipelined run shows no reclaimed badput in goodput/*", ladder)
  return out


def _BenchRingAttention(jax, jnp, on_tpu):
  """Long-context sp path: ring-attention decomposition at t=32k.

  Multi-chip hardware is unavailable here, so the per-device ring program
  is executed serially on one chip (`RingAttentionSingleDevice`: num_shards
  q-shards x KV visits with the flash kernel + lse merges — exactly each sp
  device's compute, without the overlapped ppermutes). With ideal ICI
  overlap the per-device step time is ~ ring_sim_total / num_shards; the
  KV rotation payload at these shapes (~17 MB/step vs ~45 GB/s+ per ICI
  link) transfers in well under one block's compute time.
  """
  from lingvo_tpu.parallel import ring_attention
  b, t, n, h = (1, 32768, 8, 128) if on_tpu else (1, 512, 2, 32)
  shards = 4
  q = jax.random.normal(jax.random.PRNGKey(0), (b, t, n, h), jnp.bfloat16)
  k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h), jnp.bfloat16)
  v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h), jnp.bfloat16)
  from lingvo_tpu.ops import flash_attention

  flash = jax.jit(lambda q, k, v: jnp.sum(
      flash_attention.FlashAttention(q, k, v, causal=True).astype(
          jnp.float32) ** 2))
  ring = jax.jit(lambda q, k, v: jnp.sum(
      ring_attention.RingAttentionSingleDevice(
          q, k, v, num_shards=shards, causal=True).astype(jnp.float32) ** 2))
  reps = (2, 8) if on_tpu else (1, 3)
  flash_t = _MarginalStepTime(lambda _: flash(q, k, v), float, *reps)
  ring_t = _MarginalStepTime(lambda _: ring(q, k, v), float, *reps)
  return {
      "shape_btnh": [b, t, n, h],
      "num_shards": shards,
      "flash_full_fwd_ms": round(flash_t * 1e3, 2),
      "ring_sim_total_fwd_ms": round(ring_t * 1e3, 2),
      "ring_per_device_est_ms": round(ring_t / shards * 1e3, 2),
      "ring_overhead_vs_flash": round(ring_t / flash_t, 3),
  }


def _BenchEmbedding(jax, jnp, on_tpu):
  """1M x 128 sharded-gather embedding: lookup + SGD update step (VERDICT r2
  Next #6). The one-hot path at this vocab would burn O(V*d) = 8.4 TFLOPs
  per 32k-token batch; the gather path is O(tokens*d)."""
  from lingvo_tpu.core import tpu_embedding_layers
  vocab, dim = (1_000_000, 128) if on_tpu else (10_000, 16)
  batch = (32, 1024) if on_tpu else (4, 64)
  p = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
      name="tbl", vocab_size=vocab, embedding_dim=dim,
      lookup_method="gather")
  tbl = p.Instantiate()
  tbl.FinalizePaths()
  theta = tbl.InstantiateVariables(jax.random.PRNGKey(0))
  ids = jax.random.randint(jax.random.PRNGKey(1), batch, 0, vocab)

  @jax.jit
  def step(theta, ids):
    def loss(th):
      return jnp.sum(tbl.EmbLookup(th, ids).astype(jnp.float32) ** 2)
    g = jax.grad(loss)(theta)
    new = jax.tree_util.tree_map(lambda w, gw: w - 0.01 * gw, theta, g)
    return new, loss(theta)

  holder = [theta]

  def _Dispatch(_):
    holder[0], out = step(holder[0], ids)
    return out

  reps = (3, 13) if on_tpu else (1, 3)
  t = _MarginalStepTime(_Dispatch, float, *reps)
  return {
      "vocab": vocab, "dim": dim, "tokens": int(np.prod(batch)),
      "lookup_update_ms": round(t * 1e3, 3),
      "tokens_per_sec": round(np.prod(batch) / t, 1),
  }


def _BenchMoE(jax, jnp, model_registry, on_tpu, peak):
  """64-expert MoE LM single-chip train step (VERDICT r1 item 1).

  MFU counts ACTIVE flops: dense params fully, expert FFNs at top-k/E
  utilization (the GShard accounting); routing/dispatch work is overhead,
  not model flops. Knobs overridable via BENCH_MOE_* env vars so
  `tools/moe_sweep.py` can sweep the design space with the same harness.
  """
  env = os.environ
  mp = model_registry.GetParams("lm.synthetic_packed_input.MoELmTiny",
                                "Train")
  mp.task.input = mp.input
  if on_tpu:
    # 64 experts has to fit a single 16G chip with f32 master weights +
    # f32 grads + bf16 casts: 3 MoE layers x 64 x 2 x (1024*2048) = 805M
    # expert params (3.2G f32)
    mp.task.model_dim = 1024
    mp.task.hidden_dim = 4096
    mp.task.moe_hidden_dim = 2048
    mp.task.num_heads = 16
    mp.task.num_layers = 6
    mp.task.num_experts = 64
    mp.task.moe_num_groups = int(env.get("BENCH_MOE_GROUPS", 8))
    mp.task.vocab_size = 32768
    mp.task.input.vocab_size = 32768
    mp.task.input.seq_len = 1024
    mp.task.input.batch_size = int(env.get("BENCH_MOE_BATCH", 8))
    mp.task.remat_policy = "dots"
    from lingvo_tpu.core import attention as attention_lib
    mp.task.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
        use_flash_attention=True)
  else:
    mp.task.num_experts = 8
    mp.task.input.seq_len = 32
    mp.task.input.batch_size = 2
  if env.get("BENCH_MOE_CAPACITY"):
    mp.task.moe_capacity_factor = float(env["BENCH_MOE_CAPACITY"])
  if env.get("BENCH_MOE_GATING"):
    mp.task.moe_gating_policy = env["BENCH_MOE_GATING"]
  if env.get("BENCH_MOE_DISPATCH"):
    mp.task.moe_dispatch_method = env["BENCH_MOE_DISPATCH"]
  mp.task.fprop_dtype = jnp.bfloat16
  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  from lingvo_tpu.core import input_policy
  gen = input_policy.Instantiate(mp.input)
  batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
  step_fn = jax.jit(task.TrainStep, donate_argnums=_DonateState(on_tpu))

  def _Dispatch(_):
    nonlocal state
    state, out = step_fn(state, batch)
    return out

  step = _MarginalStepTime(
      _Dispatch, lambda out: float(out.metrics.loss[0]),
      *( (3, 13) if on_tpu else (1, 3) ))
  ntok = int(np.prod(batch.ids.shape))
  from lingvo_tpu.core import py_utils
  p = mp.task
  n_params = py_utils.CountParams(state.theta)
  # Expert FFN weights straight from the instantiated theta (leaves under a
  # 'moe' scope named wi/wo), so the MFU accounting tracks the real config
  # instead of re-deriving interleave/shape assumptions (ADVICE r2).
  expert_params = sum(
      int(np.prod(np.shape(v))) for k, v in state.theta.FlattenItems()
      if ".moe." in f".{k}." and k.rsplit(".", 1)[-1] in ("wi", "wo"))
  gating = getattr(p, "moe_gating_policy", "top2")
  top_k = 1.0 if gating in ("sinkhorn", "hash") else 2.0
  dense_params = n_params - expert_params
  active = dense_params + expert_params * top_k / p.num_experts
  b, t = batch.ids.shape
  attn = 12.0 * b * t * t * p.model_dim * p.num_layers
  flops = 6.0 * active * ntok + attn
  mfu = flops / (step * peak)
  return {
      "num_experts": p.num_experts,
      "params_m": round(n_params / 1e6, 1),
      "active_params_m": round(active / 1e6, 1),
      "batch": int(b),
      "gating": gating,
      "step_time_ms": round(step * 1e3, 2),
      "tokens_per_sec": round(ntok / step, 1),
      "mfu": round(mfu, 4),
  }


def _BenchMixers(jax, jnp, model_registry, on_tpu):
  """Sequence-mixer family (docs/sequence_mixers.md): plain attention vs
  pure-SSM vs hybrid stacks on the same recipe geometry — train step time,
  measured decode tokens/sec, decode-state bytes across the 1k-32k ladder
  (the acceptance bar: FLAT for the SSM share), and how many concurrent
  sequences each variant fits in a fixed decode-HBM budget. Geometry and
  ladder logic live in tools/mixer_sweep.py so the standalone sweep and
  this section can't drift apart."""
  repo = os.path.dirname(os.path.abspath(__file__))
  tools_dir = os.path.join(repo, "tools")
  if tools_dir not in sys.path:
    sys.path.insert(0, tools_dir)
  import mixer_sweep
  from lingvo_tpu.core import input_policy

  out = {"seq_ladder": list(mixer_sweep.SEQ_LADDER)}
  for name, every_n in mixer_sweep.VARIANTS.items():
    res = mixer_sweep._Measure(jax, jnp, model_registry, name, every_n)
    mp, task = mixer_sweep._Build(jax, jnp, model_registry, every_n)
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = input_policy.Instantiate(mp.input)
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    step_fn = jax.jit(task.TrainStep, donate_argnums=_DonateState(on_tpu))
    holder = [state]

    def _Dispatch(_, step_fn=step_fn, holder=holder, batch=batch):
      holder[0], step_out = step_fn(holder[0], batch)
      return step_out

    t = _MarginalStepTime(_Dispatch, lambda o: float(o.metrics.loss[0]),
                          *((3, 13) if on_tpu else (1, 3)))
    res["train_step_ms"] = round(t * 1e3, 2)
    out[name] = res
    del state, holder, step_fn, batch
  # the two acceptance claims, surfaced as top-level booleans/ratios
  out["ssm_state_flat_1k_to_32k"] = out["ssm"]["state_flat"]
  out["hybrid_state_reduction_at_32k"] = round(
      out["attention"]["decode_state_bytes_per_seq"]["32768"]
      / max(out["hybrid"]["decode_state_bytes_per_seq"]["32768"], 1), 2)
  out["slots_vs_attention_at_fixed_hbm"] = {
      v: out[v]["slots_at_hbm_budget"]["slots"]
      for v in mixer_sweep.VARIANTS}
  return out


def _BenchMoEDispatchCompareInner(jax, jnp):
  """einsum vs shard_map MoE dispatch on an 8-device {data,expert,model}
  mesh: per-variant step time (fwd+bwd) plus the attribution parser's
  executed-collectives/step and ICI MB/device/step off the compiled HLO.
  Runs in the BENCH_ONLY=moe_dispatch subprocess (the parent bench process
  pins a single CPU device; the mesh needs 8)."""
  from lingvo_tpu.parallel import gshard, mesh as mesh_lib
  from tools import collective_attribution

  assert len(jax.devices()) >= 8, len(jax.devices())
  mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                           devices=jax.devices()[:8])
  b, t, d = 16, 64, 32

  def _Variant(dispatch_method):
    layer = gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=d, hidden_dim=2 * d, num_experts=8,
        num_groups=4, dispatch_method=dispatch_method).Instantiate()
    theta = layer.InstantiateVariables(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (b, t, d))
    with mesh_lib.MeshContext(mesh):
      theta = jax.device_put(theta,
                             mesh_lib.ThetaShardings(mesh, layer, theta))
      x = jax.device_put(
          x, jax.sharding.NamedSharding(mesh,
                                        jax.sharding.PartitionSpec("data")))

      def loss(th, x):
        return jnp.mean(jnp.square(layer.FProp(th, x)))

      fn = jax.jit(jax.value_and_grad(loss))
      hlo = fn.lower(theta, x).compile().as_text()
      for _ in range(3):  # warmup / compile
        val, _ = fn(theta, x)
      float(val)
      reps = 20
      t0 = time.perf_counter()
      for _ in range(reps):
        val, grad = fn(theta, x)
      jax.block_until_ready((val, grad))
      step_s = (time.perf_counter() - t0) / reps
    attr = collective_attribution.Analyze(hlo)
    return {
        "step_time_ms": round(step_s * 1e3, 3),
        "executed_per_step": attr["executed_per_step"],
        # partitioned-module shapes are per-device: bytes/step is the
        # per-device ICI payload
        "mb_per_device_per_step": {
            k: round(v / 1e6, 3)
            for k, v in attr["bytes_per_step"].items()},
    }

  out = {
      "mesh": {"data": 2, "expert": 2, "model": 2},
      "shape": {"batch": b, "seq": t, "dim": d, "experts": 8, "groups": 4},
      "einsum": _Variant("einsum"),
      "shard_map": _Variant("auto"),
  }
  sm, es = out["shard_map"], out["einsum"]
  out["shard_map_vs_einsum_time"] = round(
      sm["step_time_ms"] / max(es["step_time_ms"], 1e-9), 3)
  out["permutes_removed_per_step"] = (
      es["executed_per_step"].get("collective-permute", 0)
      - sm["executed_per_step"].get("collective-permute", 0))
  return out


def _BenchMoEDispatchCompare():
  """Parent-side wrapper: spawn the 8-virtual-device subprocess and collect
  its one JSON line."""
  env = dict(os.environ)
  env["BENCH_ONLY"] = "moe_dispatch"
  env["JAX_PLATFORMS"] = "cpu"
  flags = env.get("XLA_FLAGS", "")
  if "xla_force_host_platform_device_count" not in flags:
    env["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
  env.pop("PYTHONPATH", None)
  proc = subprocess.run(
      [sys.executable, os.path.abspath(__file__)], env=env,
      capture_output=True, text=True, timeout=1200)
  if proc.returncode != 0:
    tail = (proc.stderr or "").strip().splitlines()[-3:]
    return {"error": f"subprocess rc={proc.returncode}: {tail}"}
  return json.loads(proc.stdout.strip().splitlines()[-1])


def _BenchDense(jax, jnp, model_registry, on_tpu, peak):
  """Flagship dense-LM train step. Runs in its own frame so the ~671M-param
  f32 train state is garbage the moment it returns — round 2's official MoE
  sub-bench OOM'd because this state was still live (VERDICT r2 Missing #1).
  Returns (mfu, detail)."""
  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  if on_tpu:
    # ~670M params, MXU-friendly geometry (d=2048 beats d=1024 by ~12 MFU
    # points on v5e); 'dots' remat saves matmul outputs instead of
    # recomputing whole layers; the Pallas flash kernel handles the packed
    # input's segment mask in-kernel. Measured 0.457 MFU naive-attention,
    # 0.568 with flash (v5e).
    mp.task.model_dim = 2048
    mp.task.num_layers = 12
    mp.task.num_heads = 16
    mp.task.hidden_dim = 8192
    mp.task.vocab_size = 32768
    mp.task.input.vocab_size = 32768
    mp.task.input.seq_len = 1024
    mp.task.input.batch_size = 8
    mp.task.remat_policy = "dots"
    from lingvo_tpu.core import attention as attention_lib
    mp.task.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
        use_flash_attention=True)
    steps = 20
  else:
    mp.task.input.seq_len = 64
    mp.task.input.batch_size = 4
    steps = 10
  mp.task.fprop_dtype = jnp.bfloat16

  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  from lingvo_tpu.core import input_policy
  gen = input_policy.Instantiate(mp.input)
  batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)

  from lingvo_tpu.core import py_utils
  n_params = py_utils.CountParams(state.theta)
  emb_params = mp.task.vocab_size * mp.task.model_dim
  p = mp.task
  b, t = batch.ids.shape[0], batch.ids.shape[1]  # actual fed shape
  tokens = b * t
  # 6 * non-emb params per token (fwd 2x + bwd 4x) + softmax matmul
  # + attention scores/context (12 * B*T^2*D*L fwd+bwd).
  matmul_flops = 6.0 * (n_params - emb_params) * tokens
  softmax_flops = 6.0 * emb_params * tokens
  attn_flops = 12.0 * b * t * t * p.model_dim * p.num_layers
  flops_per_step = matmul_flops + softmax_flops + attn_flops

  step_fn = jax.jit(task.TrainStep, donate_argnums=_DonateState(on_tpu))
  # Compile ONCE; read XLA's cost analysis off the same executable as a
  # cross-check of the analytic FLOPs formula (None when unavailable).
  xla_flops = None
  try:
    from lingvo_tpu.core import computation_cost
    compiled = step_fn.lower(state, batch).compile()
    analysis = computation_cost.CostAnalysisOf(compiled)
    if "flops" in analysis:
      xla_flops = float(analysis["flops"])
  except Exception as e:  # noqa: BLE001
    print(f"bench: cost_analysis unavailable: {e}", file=sys.stderr)
  last_out = [None]

  def _Dispatch(_):
    nonlocal state
    state, out = step_fn(state, batch)
    last_out[0] = out
    return out

  step_time = _MarginalStepTime(
      _Dispatch, lambda out: float(out.metrics.loss[0]),
      *( (max(steps // 4, 2), steps) if on_tpu else (2, steps) ))

  mfu = flops_per_step / (step_time * peak)
  loss = float(last_out[0].metrics.loss[0])

  detail = {
      "params_m": round(n_params / 1e6, 1),
      "step_time_s": round(step_time, 4),
      "tokens_per_sec": round(tokens / step_time, 1),
      "flops_per_step_g": round(flops_per_step / 1e9, 1),
      # NOTE: XLA cost analysis counts a lax.scan (scan-over-layers) body
      # ONCE, not x num_layers, so this undercounts ~9x for the repeated
      # transformer; it's recorded as a lower-bound cross-check only.
      "xla_flops_per_step_g": (round(xla_flops / 1e9, 1)
                               if xla_flops is not None else None),
      "loss": round(loss, 3),
  }
  return mfu, detail


def main():
  _EnsureBackend()
  import gc
  import jax
  import jax.numpy as jnp
  # Persistent compile cache: over the tunneled backend a cold compile of the
  # three bench programs costs ~25 min; warm runs (incl. the driver's) reuse
  # this directory and finish in ~3 min.
  try:
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             ".jax_cache")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
  except Exception as e:  # noqa: BLE001
    print(f"bench: compile cache unavailable: {e}", file=sys.stderr)
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401

  dev = jax.devices()[0]
  on_tpu = dev.platform != "cpu"
  peak = _PeakFlops(dev)

  if os.environ.get("BENCH_ONLY") == "moe_dispatch":
    # Subprocess mode for the dispatch comparison (needs the 8-device mesh).
    print(json.dumps(_BenchMoEDispatchCompareInner(jax, jnp)))
    return

  if os.environ.get("BENCH_ONLY") == "moe":
    # Sweep mode (tools/moe_sweep.py): just the MoE sub-bench, one JSON line.
    moe = _BenchMoE(jax, jnp, model_registry, on_tpu, peak)
    moe["valid_for_mfu"] = bool(on_tpu)
    print(json.dumps(moe))
    if not on_tpu and not os.environ.get("BENCH_FORCE_CPU"):
      sys.exit(3)
    return

  mem_before = _MemSnapshot(dev)
  mfu, detail = _BenchDense(jax, jnp, model_registry, on_tpu, peak)
  detail["mem"] = _MemDelta(mem_before, _MemSnapshot(dev))
  detail["device"] = str(getattr(dev, "device_kind", dev.platform))
  detail["peak_tflops"] = peak / 1e12

  # Secondary benches: never let them kill the primary number. Each runs
  # after a gc pass so the previous bench's train state is actually freed
  # on-device (the dense f32 state + MoE state together OOM a 16G chip),
  # and each records a per-section peak-memory figure so this and future
  # memory optimisations have a trajectory in the BENCH json.
  sections = [
      ("flash_attention", lambda: _BenchFlashAttention(jax, jnp, on_tpu)),
      ("decode", lambda: _BenchDecode(jax, jnp, model_registry, on_tpu)),
      ("serving", lambda: _BenchServing(jax, jnp, model_registry, on_tpu)),
      ("multi_tenant",
       lambda: _BenchMultiTenant(jax, jnp, model_registry, on_tpu)),
      ("observability",
       lambda: _BenchObservability(jax, jnp, model_registry, on_tpu)),
      ("spec_decode",
       lambda: _BenchSpecDecode(jax, jnp, model_registry, on_tpu)),
      ("quant_serving",
       lambda: _BenchQuantServing(jax, jnp, model_registry, on_tpu)),
      ("prefix_cache",
       lambda: _BenchPrefixCache(jax, jnp, model_registry, on_tpu)),
      ("fleet", lambda: _BenchFleet(jax, jnp, model_registry, on_tpu)),
      ("ragged_step",
       lambda: _BenchRaggedStep(jax, jnp, model_registry, on_tpu)),
      ("fused_xent",
       lambda: _BenchFusedXent(jax, jnp, model_registry, on_tpu)),
      ("input_pipeline",
       lambda: _BenchInputPipeline(jax, jnp, model_registry, on_tpu)),
      ("pipelined_executor",
       lambda: _BenchPipelinedExecutor(jax, jnp, model_registry, on_tpu)),
      ("mixers", lambda: _BenchMixers(jax, jnp, model_registry, on_tpu)),
      ("moe", lambda: _BenchMoE(jax, jnp, model_registry, on_tpu, peak)),
      ("moe_dispatch", _BenchMoEDispatchCompare),
      ("ring_attention", lambda: _BenchRingAttention(jax, jnp, on_tpu)),
      ("embedding", lambda: _BenchEmbedding(jax, jnp, on_tpu)),
  ]
  for name, fn in sections:
    gc.collect()
    before = _MemSnapshot(dev)
    try:
      detail[name] = fn()
    except Exception as e:  # noqa: BLE001
      detail[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
    detail[name]["mem"] = _MemDelta(before, _MemSnapshot(dev))

  # A CPU run measures nothing about the 45%-MFU-on-TPU bar: stamp it
  # invalid and exit nonzero (unless CPU was explicitly requested) so the
  # driver can't record it as a TPU perf regression.
  detail["valid_for_mfu"] = bool(on_tpu)
  if _TPU_UNREACHABLE:
    detail["tpu_unreachable"] = True
  result = {
      "metric": "dense_lm_train_mfu",
      "value": round(mfu, 4),
      "unit": "mfu_fraction",
      "vs_baseline": round(mfu / 0.45, 4),
      "detail": detail,
  }
  print(json.dumps(result), flush=True)

  # The moment a TPU probe finally succeeds, run the MoE design-space sweep
  # unattended and write it into BASELINE.md — the tunnel windows are short
  # and there is no human in the loop (VERDICT r4 Next #1b). The primary
  # JSON line is already out, so a sweep crash can't cost the bench result.
  if on_tpu and os.environ.get("BENCH_SWEEP", "1") != "0":
    try:
      repo = os.path.dirname(os.path.abspath(__file__))
      sys.path.insert(0, os.path.join(repo, "tools"))
      import moe_sweep
      gc.collect()
      sweep = moe_sweep.RunSweep(
          budget_s=float(os.environ.get("BENCH_SWEEP_BUDGET_S", "1500")),
          out_path=os.path.join(repo, "MOE_SWEEP.jsonl"))
      moe_sweep.WriteBaselineSection(sweep, os.path.join(repo, "BASELINE.md"))
      print(f"bench: auto-sweep recorded {len(sweep)} variants to "
            "MOE_SWEEP.jsonl + BASELINE.md", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
      print(f"bench: auto-sweep failed: {e}", file=sys.stderr)

  if not on_tpu and not os.environ.get("BENCH_FORCE_CPU"):
    sys.exit(3)


if __name__ == "__main__":
  try:
    main()
  except Exception as e:  # noqa: BLE001
    # Partial-result contract: always emit one valid JSON line so the
    # driver records *something* instead of a traceback (round-1 failure).
    import traceback
    traceback.print_exc()
    print(json.dumps({
        "metric": "dense_lm_train_mfu",
        "value": 0.0,
        "unit": "mfu_fraction",
        "vs_baseline": 0.0,
        "detail": {"error": f"{type(e).__name__}: {e}"[:500],
                   "valid_for_mfu": False},
    }))
    sys.exit(4)
