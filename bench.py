"""Benchmark: dense-LM training MFU on the available accelerator.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "...", "vs_baseline": N}

The flagship path: bf16 TransformerLm (scan-over-layers) full train step
(fwd+bwd+Adafactor) on synthetic packed input. MFU = model FLOPs / (step
time * peak FLOPs). Baseline target: 45% MFU (BASELINE.md north star).

Model size auto-scales with the platform: a ~350M-param LM on TPU, a tiny
one on CPU (so the script always completes).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _PeakFlops(device) -> float:
  kind = getattr(device, "device_kind", "").lower()
  # bf16 peak per chip
  table = {
      "tpu v5 lite": 197e12,   # v5e
      "tpu v5e": 197e12,
      "tpu v5": 459e12,        # v5p
      "tpu v5p": 459e12,
      "tpu v4": 275e12,
      "tpu v6 lite": 918e12,   # v6e / trillium
      "tpu v6e": 918e12,
  }
  for k, v in sorted(table.items(), key=lambda kv: -len(kv[0])):
    if k in kind:
      return v
  if "tpu" in kind:
    return 197e12
  return float(os.environ.get("BENCH_PEAK_FLOPS", 2e11))  # cpu-ish


def main():
  import jax
  import jax.numpy as jnp
  import numpy as np
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401

  dev = jax.devices()[0]
  on_tpu = dev.platform != "cpu"
  peak = _PeakFlops(dev)

  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  if on_tpu:
    # ~350M params: fits v5e HBM with f32 master weights + Adafactor state.
    mp.task.model_dim = 1024
    mp.task.num_layers = 24
    mp.task.num_heads = 16
    mp.task.hidden_dim = 8192
    mp.task.vocab_size = 32768
    mp.task.input.vocab_size = 32768
    mp.task.input.seq_len = 1024
    mp.task.input.batch_size = 8
    steps = 20
  else:
    mp.task.input.seq_len = 64
    mp.task.input.batch_size = 4
    steps = 10
  mp.task.fprop_dtype = jnp.bfloat16

  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  gen = mp.input.Instantiate()
  batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)

  from lingvo_tpu.core import py_utils
  n_params = py_utils.CountParams(state.theta)
  emb_params = mp.task.vocab_size * mp.task.model_dim
  p = mp.task
  b, t = mp.task.input.batch_size, mp.task.input.seq_len
  tokens = b * t
  # 6 * non-emb params per token (fwd 2x + bwd 4x) + softmax matmul
  # + attention scores/context (12 * B*T^2*D*L fwd+bwd).
  matmul_flops = 6.0 * (n_params - emb_params) * tokens
  softmax_flops = 6.0 * emb_params * tokens
  attn_flops = 12.0 * b * t * t * p.model_dim * p.num_layers
  flops_per_step = matmul_flops + softmax_flops + attn_flops

  step_fn = jax.jit(task.TrainStep, donate_argnums=(0,))
  # warmup/compile
  state, out = step_fn(state, batch)
  jax.block_until_ready(jax.tree_util.tree_leaves(state.theta)[0])

  t0 = time.perf_counter()
  for _ in range(steps):
    state, out = step_fn(state, batch)
  jax.block_until_ready(jax.tree_util.tree_leaves(state.theta)[0])
  wall = time.perf_counter() - t0
  step_time = wall / steps

  mfu = flops_per_step / (step_time * peak)
  tokens_per_sec = tokens / step_time
  loss = float(out.metrics.loss[0])

  result = {
      "metric": "dense_lm_train_mfu",
      "value": round(mfu, 4),
      "unit": "mfu_fraction",
      "vs_baseline": round(mfu / 0.45, 4),
      "detail": {
          "device": str(getattr(dev, "device_kind", dev.platform)),
          "params_m": round(n_params / 1e6, 1),
          "step_time_s": round(step_time, 4),
          "tokens_per_sec": round(tokens_per_sec, 1),
          "flops_per_step_g": round(flops_per_step / 1e9, 1),
          "peak_tflops": peak / 1e12,
          "loss": round(loss, 3),
      },
  }
  print(json.dumps(result))


if __name__ == "__main__":
  main()
