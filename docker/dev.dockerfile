# Development image for lingvo_tpu (ref lingvo/docker/dev.dockerfile).
#
# Build:  docker build -f docker/dev.dockerfile -t lingvo-tpu-dev .
# Run:    docker run --rm -it lingvo-tpu-dev bash
# On Cloud TPU VMs, use the libtpu-enabled jax install instead (see below).

FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
    build-essential make g++ git && rm -rf /var/lib/apt/lists/*

WORKDIR /workspace/lingvo_tpu
COPY pyproject.toml README.md ./
COPY lingvo_tpu ./lingvo_tpu
COPY tools ./tools
COPY tests ./tests
COPY bench.py __graft_entry__.py ./

# CPU jax by default; on TPU VMs replace with:
#   pip install 'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html
RUN pip install --no-cache-dir -e .[tb,test] jax[cpu]

# build the native input-pipeline library once at image build
RUN make -C lingvo_tpu/ops/cc

CMD ["python", "-m", "pytest", "tests/", "-q"]
