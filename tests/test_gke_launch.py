"""GKE launcher verbs (VERDICT r3 weak #9): print/build/up/down/reload
dispatch, manifest content, and command synthesis under --dry_run. Ref
`lingvo/tools/gke_launch.py:398`."""

import importlib.util
import os

import pytest

_SPEC = importlib.util.spec_from_file_location(
    "gke_launch",
    os.path.join(os.path.dirname(os.path.dirname(__file__)),
                 "tools", "gke_launch.py"))
gke_launch = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(gke_launch)

_COMMON = ["--name=lm1", "--model=lm.synthetic_packed_input.DenseLm8B",
           "--image=gcr.io/proj/lingvo:live", "--logdir=gs://b/lm1"]


class TestGkeLaunch:

  def test_print_emits_manifests(self, tmp_path, capsys):
    out = tmp_path / "m.yaml"
    rc = gke_launch.main(
        ["print"] + _COMMON + ["--with_evaler", f"--output={out}"])
    assert rc == 0
    yaml = out.read_text()
    assert yaml.count("kind: Job") == 2         # train + evaler
    assert "kind: Deployment" in yaml           # tensorboard
    assert "--model=lm.synthetic_packed_input.DenseLm8B" in yaml
    assert "google.com/tpu: 4" in yaml
    assert "google.com/tpu: 1" in yaml          # evaler gets one chip

  def test_build_dry_run(self, capsys):
    rc = gke_launch.main(
        ["build", "--image=gcr.io/proj/lingvo:live", "--dry_run"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "docker build -t gcr.io/proj/lingvo:live" in err
    assert "docker push gcr.io/proj/lingvo:live" in err

  def test_up_dry_run_applies_manifest(self, capsys):
    rc = gke_launch.main(["up"] + _COMMON + ["--dry_run"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "kubectl apply -f" in err

  def test_up_with_build_orders_commands(self, capsys):
    rc = gke_launch.main(["up"] + _COMMON + ["--build", "--dry_run"])
    assert rc == 0
    err = capsys.readouterr().err
    assert err.index("docker build") < err.index("kubectl apply")

  def test_down_dry_run_deletes_all(self, capsys):
    rc = gke_launch.main(["down", "--name=lm1", "--dry_run"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "job/lm1-train" in err
    assert "job/lm1-evaler" in err
    assert "deployment/lm1-tensorboard" in err

  def test_reload_downs_then_ups(self, capsys):
    rc = gke_launch.main(["reload"] + _COMMON + ["--dry_run"])
    assert rc == 0
    err = capsys.readouterr().err
    assert err.index("kubectl delete") < err.index("kubectl apply")

  def test_missing_verb_rejected(self):
    with pytest.raises(SystemExit):
      gke_launch.main([])
