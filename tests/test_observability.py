"""TensorBoard summaries, profiler capture, warm-start rules (VERDICT r1
items 7 & 8; ref summary_utils.py, jax.profiler, checkpointer.py:214)."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import checkpointer as checkpointer_lib
from lingvo_tpu.core import summary_utils
from lingvo_tpu.core.nested_map import NestedMap


class TestSummaryWriter:

  def test_event_files_written(self, tmp_path):
    w = summary_utils.SummaryWriter(str(tmp_path))
    assert w.enabled
    w.Scalar("loss", 1.25, step=10)
    w.Scalars({"a": 1.0, "b": 2}, step=20, prefix="train/")
    w.Histogram("weights", np.random.randn(100), step=10)
    w.Image("img", np.random.rand(8, 8, 3), step=10)
    w.Text("note", "hello", step=10)
    w.Close()
    events = glob.glob(str(tmp_path / "events.out.tfevents.*"))
    assert events, os.listdir(tmp_path)
    assert os.path.getsize(events[0]) > 100

  def test_attention_summary(self, tmp_path):
    w = summary_utils.SummaryWriter(str(tmp_path))
    probs = jax.nn.softmax(jnp.ones((3, 2, 6, 9)), axis=-1)  # [B,N,T,S]
    summary_utils.AddAttentionSummary(w, "atten", probs, step=5)
    w.Close()
    assert glob.glob(str(tmp_path / "events.out.tfevents.*"))
    img = summary_utils.AttentionProbsToImage(np.asarray(probs[0, 0]))
    assert img.shape == (6, 9, 3)
    assert img.min() >= 0.0 and img.max() <= 1.0

  def test_step_rate_tracker(self):
    tracker = summary_utils.StepRateTracker()
    tracker.Update(0)
    import time
    time.sleep(0.05)
    rate = tracker.Update(10, examples_per_step=32)
    assert rate > 0
    assert tracker.examples_per_second > rate  # 32x examples per step


class TestProgramObservability:

  def _run(self, tmp_path, **program_overrides):
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    from lingvo_tpu.runners import program as program_lib

    mp = model_registry.GetParams("image.mnist.LeNet5", "Train")
    mp.task.input = mp.input
    mp.task.input.batch_size = 8
    mp.task.input.num_samples = 64
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    tp = program_lib.TrainProgram.Params().Set(
        task=mp.task, logdir=str(tmp_path), steps_per_loop=3,
        **program_overrides)
    prog = program_lib.TrainProgram(tp, task=task,
                                    input_generator=mp.input.Instantiate())
    state, result = prog.Run(state)
    return result

  def test_train_program_writes_tensorboard(self, tmp_path):
    result = self._run(tmp_path)
    assert "loss" in result
    assert glob.glob(str(tmp_path / "train" / "events.out.tfevents.*"))

  @pytest.mark.slow
  def test_profiler_capture(self, tmp_path):
    self._run(tmp_path, profiler_capture_every_n_runs=1)
    # jax.profiler writes plugins/profile/<ts>/*.trace.json.gz (+ .xplane.pb)
    traces = glob.glob(
        str(tmp_path / "train" / "plugins" / "profile" / "*" / "*"))
    assert traces, "no profiler trace captured"


class TestWarmStartRules:

  def test_regex_mapped_partial_restore(self, tmp_path):
    """Restore an LM's embedding into a differently-named target by rule."""
    src_dir = tmp_path / "src" / "train"
    # source "model": theta with two vars
    src_state = NestedMap(
        theta=NestedMap(
            emb=NestedMap(w=jnp.arange(12, dtype=jnp.float32).reshape(3, 4)),
            head=NestedMap(w=jnp.ones((4, 2)))),
        step=jnp.asarray(7, jnp.int32))
    ckpt = checkpointer_lib.Checkpointer(str(src_dir))
    ckpt.Save(7, src_state, force=True)
    ckpt.Close()

    # target model: same embedding under another path, bf16 dtype
    target = NestedMap(
        theta=NestedMap(
            encoder=NestedMap(
                tok_emb=NestedMap(
                    w=jnp.zeros((3, 4), jnp.bfloat16))),
            other=NestedMap(w=jnp.full((2, 2), 5.0))),
        step=jnp.asarray(0, jnp.int32))
    rules = {str(src_dir): [(r"encoder\.tok_emb\.(.*)", r"emb.\1")]}
    out = checkpointer_lib.ApplyInitFromCheckpointRules(target, rules)
    got = np.asarray(out.theta.encoder.tok_emb.w, np.float32)
    np.testing.assert_allclose(got, np.arange(12).reshape(3, 4), atol=1e-2)
    assert out.theta.encoder.tok_emb.w.dtype == jnp.bfloat16  # dtype cast
    np.testing.assert_allclose(np.asarray(out.theta.other.w), 5.0)  # untouched
    assert int(out.step) == 0  # warm start is not resumption

  def test_missing_source_var_raises(self, tmp_path):
    src_dir = tmp_path / "src" / "train"
    ckpt = checkpointer_lib.Checkpointer(str(src_dir))
    ckpt.Save(1, NestedMap(theta=NestedMap(a=jnp.zeros(2)),
                           step=jnp.asarray(1)), force=True)
    ckpt.Close()
    target = NestedMap(theta=NestedMap(b=jnp.zeros(2)), step=jnp.asarray(0))
    with pytest.raises(KeyError):
      checkpointer_lib.ApplyInitFromCheckpointRules(
          target, {str(src_dir): [(r"b", r"zzz")]})

  def test_shape_mismatch_raises(self, tmp_path):
    src_dir = tmp_path / "src" / "train"
    ckpt = checkpointer_lib.Checkpointer(str(src_dir))
    ckpt.Save(1, NestedMap(theta=NestedMap(a=jnp.zeros((2, 3))),
                           step=jnp.asarray(1)), force=True)
    ckpt.Close()
    target = NestedMap(theta=NestedMap(a=jnp.zeros((4, 4))),
                       step=jnp.asarray(0))
    with pytest.raises(ValueError, match="shape mismatch"):
      checkpointer_lib.ApplyInitFromCheckpointRules(
          target, {str(src_dir): [(r"a", r"a")]})

  def test_executor_applies_rules_on_fresh_init_only(self, tmp_path):
    """End to end: train model A, warm-start model B's matching layer."""
    import tests.test_executor_hardening as helpers
    from lingvo_tpu.runners import executor as executor_lib

    # model A: train briefly and checkpoint
    logdir_a = str(tmp_path / "a")
    sched, task, task_p = helpers._MakeScheduleAndTask(logdir_a, max_steps=10)
    ex = executor_lib.ExecutorTpu(task_p, logdir_a, schedule=sched, task=task)
    state_a = ex.Start()

    # model B: same architecture, warm start proj from A (rules set on the
    # params BEFORE instantiation — params freeze at Instantiate)
    logdir_b = str(tmp_path / "b")
    from lingvo_tpu.runners import program as program_lib
    task_bp = helpers._TaskParams(max_steps=10, steps_per_loop=5,
                                  save_interval=10)
    task_bp.train.init_from_checkpoint_rules = {
        os.path.join(logdir_a, "train"): [(r"proj\.(.*)", r"proj.\1")]}
    task_b = task_bp.Instantiate()
    task_b.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_bp, logdir=logdir_b, steps_per_loop=5)
    sched_b = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(train_program=train_p),
        task=task_b,
        input_generators={"Train": helpers._RegressionInput()})
    ex_b = executor_lib.ExecutorTpu(task_bp, logdir_b, schedule=sched_b,
                                    task=task_b)
    # intercept: check theta right after warm start by comparing first loss
    state_b = ex_b.Start()
    # B started from A's trained weights: its step-10 loss must beat a cold
    # start's first-loop loss by a wide margin (A already converged partway)
    import json
    first_a = json.loads(
        open(os.path.join(logdir_a, "metrics.jsonl")).readline())
    first_b = json.loads(
        open(os.path.join(logdir_b, "metrics.jsonl")).readline())
    assert first_b["train"]["loss"] < 0.7 * first_a["train"]["loss"], (
        first_a["train"]["loss"], first_b["train"]["loss"])
