"""O(1)-cache sequence mixers (ops/ssd_scan.py + core/ssm.py).

Covers docs/sequence_mixers.md:
- the four SsdScan lowerings agree: chunked XLA and associative-scan match
  the sequential reference, and the Pallas twin is BIT-identical to the
  chunked XLA path (outputs, final state, and every gradient) in interpret
  mode — the flash_decode twin-lowering contract,
- the masking contract: padded steps preserve the state bitwise, segment
  resets isolate packed sequences,
- GatedSSMLayer streaming equivalence: Prefill over the whole sequence is
  bitwise FProp, an ExtendStep chain matches FProp, chunked prefill + decode
  and PagedStep (with slot re-use reset) reproduce the same trajectory,
- gradients flow through every scan lowering and every layer weight,
- hybrid TransformerLm stacks (attention every Nth layer) decode through
  GShardDecode and the continuous-batching engine token-identically to the
  per-token ExtendStep reference; pure-SSM decode state is flat in max_len
  while hybrid KV state grows,
- pure-SSM stacks admit a full batch with a 1-page pool (pageless
  admission) where the attention twin queues — the more-concurrent-
  requests-at-fixed-HBM acceptance bar in miniature,
- temperature/top_k sampling: temperature 0 is token-identical to greedy,
  per-request seeds replay across batch contexts,
- larger-shape soaks are marked slow.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import py_utils, sampling, ssm
from lingvo_tpu.ops import ssd_scan

KEY = jax.random.PRNGKey(11)
B, T, N, H, S = 2, 13, 3, 8, 4   # deliberately ragged vs chunk sizes


def _ScanInputs(key=KEY, b=B, t=T, n=N, h=H, s=S, seed_scale=0.5):
  k1, k2, k3, k4 = jax.random.split(key, 4)
  decay_log = -jax.nn.softplus(jax.random.normal(k1, (b, t, n)))
  b_in = jax.random.normal(k2, (b, t, n, s)) * seed_scale
  c_in = jax.random.normal(k3, (b, t, n, s)) * seed_scale
  v = jax.random.normal(k4, (b, t, n, h)) * seed_scale
  return decay_log, b_in, c_in, v


class TestSsdScanOp:

  @pytest.mark.parametrize("lowering", ["chunked", "associative", "pallas"])
  @pytest.mark.parametrize("chunk", [4, 8])
  def test_lowerings_match_sequential(self, lowering, chunk):
    args = _ScanInputs()
    y_ref, s_ref = ssd_scan.SsdScan(*args, lowering="sequential")
    y, s_fin = ssd_scan.SsdScan(*args, chunk_size=chunk, lowering=lowering)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(s_fin), np.asarray(s_ref),
                               atol=1e-5)

  def test_chunked_equals_pallas_bitwise(self):
    """The twin-lowering contract: same _ChunkBody floats, same bits."""
    args = _ScanInputs()
    s0 = jax.random.normal(jax.random.PRNGKey(5), (B, N, H, S)) * 0.2
    y_x, s_x = ssd_scan.SsdScan(*args, s0=s0, chunk_size=4,
                                lowering="chunked")
    y_p, s_p = ssd_scan.SsdScan(*args, s0=s0, chunk_size=4,
                                lowering="pallas", interpret=True)
    np.testing.assert_array_equal(np.asarray(y_x), np.asarray(y_p))
    np.testing.assert_array_equal(np.asarray(s_x), np.asarray(s_p))

  def test_gradients_chunked_equals_pallas_bitwise(self):
    """custom_vjp backward (VJP of the chunked XLA path) == chunked grads."""
    args = _ScanInputs()
    s0 = jax.random.normal(jax.random.PRNGKey(6), (B, N, H, S)) * 0.2

    def loss(lowering):
      def f(dl, bb, cc, vv, s0):
        y, s_fin = ssd_scan.SsdScan(dl, bb, cc, vv, s0=s0, chunk_size=4,
                                    lowering=lowering, interpret=True)
        return jnp.sum(y * y) + jnp.sum(s_fin)
      return jax.grad(f, argnums=(0, 1, 2, 3, 4))(*args, s0)

    g_x = loss("chunked")
    g_p = loss("pallas")
    for gx, gp in zip(g_x, g_p):
      np.testing.assert_array_equal(np.asarray(gx), np.asarray(gp))
      assert np.isfinite(np.asarray(gx)).all()
      assert np.abs(np.asarray(gx)).max() > 0

  def test_initial_state_threading(self):
    """Nonzero s0 rides every lowering identically."""
    args = _ScanInputs()
    s0 = jax.random.normal(jax.random.PRNGKey(8), (B, N, H, S))
    y_ref, s_ref = ssd_scan.SsdScan(*args, s0=s0, lowering="sequential")
    for lowering in ("chunked", "associative"):
      y, s_fin = ssd_scan.SsdScan(*args, s0=s0, chunk_size=4,
                                  lowering=lowering)
      np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5)
      np.testing.assert_allclose(np.asarray(s_fin), np.asarray(s_ref),
                                 atol=1e-5)

  def test_padded_step_is_identity(self):
    """decay_log = 0 AND v = 0 -> the state passes through bitwise."""
    decay_log, b_in, c_in, v = _ScanInputs()
    # make steps 5..8 of every row padding
    pad = jnp.zeros((B, T, 1))
    pad = pad.at[:, 5:9].set(1.0)
    decay_log = decay_log * (1.0 - pad)
    v = v * (1.0 - pad[..., None])
    _, s_with = ssd_scan.SsdScan(decay_log[:, :9], b_in[:, :9], c_in[:, :9],
                                 v[:, :9], lowering="sequential")
    _, s_without = ssd_scan.SsdScan(decay_log[:, :5], b_in[:, :5],
                                    c_in[:, :5], v[:, :5],
                                    lowering="sequential")
    np.testing.assert_array_equal(np.asarray(s_with), np.asarray(s_without))

  def test_segment_reset_isolates(self):
    """RESET_LOG at a boundary: the tail behaves like a fresh sequence."""
    decay_log, b_in, c_in, v = _ScanInputs()
    t0 = 6
    decay_log = decay_log.at[:, t0].set(ssd_scan.RESET_LOG)
    y_packed, s_packed = ssd_scan.SsdScan(decay_log, b_in, c_in, v,
                                          chunk_size=4, lowering="chunked")
    y_fresh, s_fresh = ssd_scan.SsdScan(
        decay_log[:, t0:].at[:, 0].set(ssd_scan.RESET_LOG), b_in[:, t0:],
        c_in[:, t0:], v[:, t0:], chunk_size=4, lowering="chunked")
    np.testing.assert_allclose(np.asarray(y_packed[:, t0:]),
                               np.asarray(y_fresh), atol=1e-5)
    np.testing.assert_allclose(np.asarray(s_packed), np.asarray(s_fresh),
                               atol=1e-5)

  def test_supported_on_tpu_gate(self):
    assert ssd_scan.SupportedOnTpu(64, 128, 128)
    assert not ssd_scan.SupportedOnTpu(63, 128, 128)   # chunk % 8
    assert not ssd_scan.SupportedOnTpu(64, 96, 128)    # state % 128
    assert not ssd_scan.SupportedOnTpu(64, 128, 96)    # head % 128

  @pytest.mark.slow
  def test_soak_long_sequence_bitwise_twins(self):
    """T = 512 / chunk 64 at TPU-eligible dims: twins still bit-equal."""
    args = _ScanInputs(key=jax.random.PRNGKey(3), b=1, t=512, n=2, h=128,
                       s=128)
    y_x, s_x = ssd_scan.SsdScan(*args, chunk_size=64, lowering="chunked")
    y_p, s_p = ssd_scan.SsdScan(*args, chunk_size=64, lowering="pallas",
                                interpret=True)
    np.testing.assert_array_equal(np.asarray(y_x), np.asarray(y_p))
    np.testing.assert_array_equal(np.asarray(s_x), np.asarray(s_p))
    y_ref, s_ref = ssd_scan.SsdScan(*args, lowering="sequential")
    np.testing.assert_allclose(np.asarray(y_x), np.asarray(y_ref), atol=1e-4)
    np.testing.assert_allclose(np.asarray(s_x), np.asarray(s_ref), atol=1e-4)


# -- GatedSSMLayer ------------------------------------------------------------

D = 16


def _SsmLayer(**kw):
  p = ssm.GatedSSMLayer.Params().Set(
      name="ssm", input_dim=D, hidden_dim=D, num_heads=N, state_dim=S,
      chunk_size=4, **kw)
  layer = p.Instantiate()
  return layer, layer.InstantiateVariables(KEY)


class TestGatedSSMLayer:

  def test_prefill_matches_fprop_bitwise(self):
    """One whole-sequence Prefill == FProp on valid positions, bitwise."""
    layer, theta = _SsmLayer()
    x = jax.random.normal(KEY, (B, T, D))
    paddings = py_utils.PaddingsFromLengths(jnp.array([T, 9]), T)
    offline, _ = layer.FProp(theta, x, paddings=paddings, causal=True)
    states = layer.InitStates(theta, B, T)
    prefill, states = layer.Prefill(theta, x, states, paddings=paddings)
    valid = np.asarray(1.0 - paddings)[..., None]
    np.testing.assert_array_equal(np.asarray(offline) * valid,
                                  np.asarray(prefill) * valid)
    assert int(states.time_step) == T

  def test_extend_step_chain_matches_fprop(self):
    layer, theta = _SsmLayer()
    x = jax.random.normal(KEY, (B, T, D))
    offline, _ = layer.FProp(theta, x, causal=True)
    states = layer.InitStates(theta, B, T)
    outs = []
    for t in range(T):
      out_t, states = layer.ExtendStep(theta, x[:, t:t + 1], states)
      outs.append(out_t)
    streaming = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(offline), np.asarray(streaming),
                               atol=1e-5)

  def test_chunked_prefill_then_decode(self):
    """Prefill in two chunks + ExtendStep tail == one FProp."""
    layer, theta = _SsmLayer()
    x = jax.random.normal(KEY, (B, T, D))
    offline, _ = layer.FProp(theta, x, causal=True)
    states = layer.InitStates(theta, B, T)
    out1, states = layer.Prefill(theta, x[:, :5], states)
    out2, states = layer.Prefill(theta, x[:, 5:10], states)
    outs = [out1, out2]
    for t in range(10, T):
      out_t, states = layer.ExtendStep(theta, x[:, t:t + 1], states)
      outs.append(out_t)
    np.testing.assert_allclose(np.asarray(offline),
                               np.asarray(jnp.concatenate(outs, axis=1)),
                               atol=1e-5)

  def test_packed_segments_match_separate(self):
    """segment_ids reset the recurrence exactly at boundaries."""
    layer, theta = _SsmLayer()
    x = jax.random.normal(KEY, (1, 10, D))
    seg = jnp.array([[0, 0, 0, 0, 1, 1, 1, 1, 1, 1]])
    packed, _ = layer.FProp(theta, x, segment_ids=seg, causal=True)
    first, _ = layer.FProp(theta, x[:, :4], causal=True)
    second, _ = layer.FProp(theta, x[:, 4:], causal=True)
    np.testing.assert_allclose(np.asarray(packed[:, :4]), np.asarray(first),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(packed[:, 4:]), np.asarray(second),
                               atol=1e-5)

  def test_paged_step_matches_extend_chain(self):
    """PagedStep prefill chunk + decode steps == the ExtendStep trajectory;
    q_pos == 0 resets a re-used slot even if its state is garbage."""
    layer, theta = _SsmLayer()
    x = jax.random.normal(KEY, (B, 8, D))
    states = layer.InitStates(theta, B, 8)
    ref = []
    for t in range(8):
      out_t, states = layer.ExtendStep(theta, x[:, t:t + 1], states)
      ref.append(out_t)
    ref = jnp.concatenate(ref, axis=1)

    paged = layer.InitPagedStates(theta, num_pages=4, page_size=4,
                                  num_slots=B)
    # poison the slot states: the q_pos == 0 reset must erase this
    paged.state = paged.state + 777.0
    tables = jnp.zeros((B, 2), jnp.int32)
    out_pre, paged = layer.PagedStep(
        theta, x[:, :4], paged, tables, q_pos=jnp.zeros((B,), jnp.int32),
        in_len=jnp.full((B,), 4, jnp.int32))
    outs = [out_pre]
    for t in range(4, 8):
      out_t, paged = layer.PagedStep(
          theta, x[:, t:t + 1], paged, tables,
          q_pos=jnp.full((B,), t, jnp.int32),
          in_len=jnp.ones((B,), jnp.int32))
      outs.append(out_t)
    np.testing.assert_allclose(np.asarray(ref),
                               np.asarray(jnp.concatenate(outs, axis=1)),
                               atol=1e-5)

  def test_init_paged_states_requires_num_slots(self):
    layer, theta = _SsmLayer()
    with pytest.raises(AssertionError):
      layer.InitPagedStates(theta, num_pages=4, page_size=4)

  def test_gradients_flow_through_every_weight(self):
    layer, theta = _SsmLayer()
    x = jax.random.normal(KEY, (B, T, D))

    def loss(theta):
      out, _ = layer.FProp(theta, x, causal=True)
      return jnp.sum(out * out)

    grads = jax.grad(loss)(theta)
    leaves = jax.tree_util.tree_leaves(grads)
    assert len(leaves) == 13
    for g in leaves:
      assert np.isfinite(np.asarray(g)).all()
      assert np.abs(np.asarray(g)).max() > 0

  def test_unsupported_modes_raise(self):
    layer, theta = _SsmLayer()
    x = jax.random.normal(KEY, (B, T, D))
    with pytest.raises(ValueError):
      layer.FProp(theta, x, causal=False)
    with pytest.raises(NotImplementedError):
      layer.FProp(theta, x, atten_mask=jnp.zeros((1, 1, T, T)), causal=True)
    with pytest.raises(NotImplementedError):
      layer.FProp(theta, x, key_vec=x, value_vec=x, causal=True)

  def test_state_bytes_per_slot(self):
    layer, theta = _SsmLayer()
    assert layer.StateBytesPerSlot() == N * (D // N) * S * 4
    states = layer.InitStates(theta, B, max_len=4096)
    # O(1): max_len never enters the state shape
    assert states.state.nbytes == B * layer.StateBytesPerSlot()


# -- hybrid TransformerLm stacks ----------------------------------------------


def _HybridLmParams(every_n, use_repeat=True, num_layers=2):
  from lingvo_tpu.models.lm import layers as lm_layers
  p = lm_layers.TransformerLm.Params().Set(
      name="lm", vocab_size=64, model_dim=32, num_layers=num_layers,
      num_heads=2, hidden_dim=64, use_rotary=True,
      use_repeat_layer=use_repeat,
      mixer_tpl=ssm.GatedSSMLayer.Params().Set(state_dim=8, chunk_size=4),
      mixer_atten_every_n=every_n)
  return p


@pytest.fixture(scope="module")
def hybrid_lm():
  task = _HybridLmParams(every_n=2).Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  return task, theta


@pytest.fixture(scope="module")
def pure_ssm_lm():
  task = _HybridLmParams(every_n=0).Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  return task, theta


class TestHybridTransformerLm:

  @pytest.mark.parametrize("lm", ["hybrid_lm", "pure_ssm_lm"])
  def test_extend_chain_matches_fprop(self, lm, request):
    task, theta = request.getfixturevalue(lm)
    ids = jax.random.randint(KEY, (B, 8), 0, 64)
    batch = py_utils.NestedMap(
        ids=ids, labels=jnp.roll(ids, -1, axis=1),
        paddings=jnp.zeros((B, 8)), weights=jnp.ones((B, 8)))
    offline = task.ComputePredictions(theta, batch).logits
    states = task.InitDecodeState(theta, B, 8)
    outs = []
    for t in range(8):
      logits_t, states = task.ExtendStep(theta, ids[:, t:t + 1], states)
      outs.append(logits_t[:, None])
    streaming = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(offline), np.asarray(streaming),
                               atol=1e-4)

  @pytest.mark.parametrize("lm", ["hybrid_lm", "pure_ssm_lm"])
  def test_prefill_matches_extend_chain(self, lm, request):
    task, theta = request.getfixturevalue(lm)
    ids = jax.random.randint(KEY, (B, 8), 0, 64)
    states = task.InitDecodeState(theta, B, 8)
    ref = []
    for t in range(8):
      logits_t, states = task.ExtendStep(theta, ids[:, t:t + 1], states)
      ref.append(logits_t[:, None])
    ref = jnp.concatenate(ref, axis=1)
    states2 = task.InitDecodeState(theta, B, 8)
    logits, _ = task.Prefill(theta, ids, states2, live_len=8)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(logits),
                               atol=1e-4)

  def test_stacked_hybrid_matches_repeat_hybrid_shapes(self):
    """The stacked branch builds the same layer pattern as repeat."""
    task = _HybridLmParams(every_n=2, use_repeat=False).Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    ids = jax.random.randint(KEY, (B, 8), 0, 64)
    states = task.InitDecodeState(theta, B, 8)
    logits, _ = task.Prefill(theta, ids, states, live_len=8)
    assert logits.shape == (B, 8, 64)
    # layer 0 is the SSM mixer, layer 1 the attention layer
    stack = task.stack
    assert hasattr(stack.x_layers[0].self_atten.atten, "StateBytesPerSlot")
    assert not hasattr(stack.x_layers[1].self_atten.atten,
                       "StateBytesPerSlot")

  def test_decode_state_flat_for_ssm_grows_for_attention(self, hybrid_lm,
                                                         pure_ssm_lm):
    """The O(1) property, measured: pure-SSM decode state is max_len-
    independent; the hybrid's growth is entirely the attention share."""
    def state_bytes(task, theta, max_len):
      states = jax.eval_shape(
          lambda th: task.InitDecodeState(th, 4, max_len), theta)
      return sum(int(np.prod(x.shape)) * x.dtype.itemsize
                 for x in jax.tree_util.tree_leaves(states))

    task_h, theta_h = hybrid_lm
    task_s, theta_s = pure_ssm_lm
    assert state_bytes(task_s, theta_s, 64) == state_bytes(task_s, theta_s,
                                                          1024)
    h64, h1024 = state_bytes(task_h, theta_h, 64), state_bytes(
        task_h, theta_h, 1024)
    assert h1024 > h64
    # the growth is exactly the attention KV share: 1 layer x K+V x
    # [4, dT, 2, 16] f32
    assert h1024 - h64 == 2 * 4 * (1024 - 64) * 32 * 4

  def test_gradients_flow(self, hybrid_lm):
    task, theta = hybrid_lm
    ids = jax.random.randint(KEY, (B, 8), 0, 64)
    batch = py_utils.NestedMap(
        ids=ids, labels=jnp.roll(ids, -1, axis=1),
        paddings=jnp.zeros((B, 8)), weights=jnp.ones((B, 8)))

    def loss(theta):
      logits = task.ComputePredictions(theta, batch).logits
      return jnp.sum(jax.nn.logsumexp(logits, axis=-1))

    grads = jax.grad(loss)(theta)
    for g in jax.tree_util.tree_leaves(grads):
      assert np.isfinite(np.asarray(g)).all()


# -- GShardDecode + serving engine over hybrid stacks -------------------------


class TestHybridDecodePaths:

  def test_gshard_decode_matches_per_token_reference(self, hybrid_lm,
                                                     tmp_path):
    """The tentpole acceptance bar: the hybrid stack decodes through
    GShardDecode UNCHANGED, token-identical to a hand-rolled per-token
    greedy rollout."""
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.runners import gshard_decode

    task, theta = hybrid_lm
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    ckpt.Save(1, state, force=True)
    ckpt.Close()
    prompts = np.array([[5, 6, 7, 8], [9, 10, 11, 12]], np.int32)
    lens = np.array([4, 4], np.int32)
    max_new = 5
    driver = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "out.jsonl"),
        max_decode_steps=max_new, len_buckets=(4,))
    recs = driver.DecodeOnce(1, prompts, lens)

    # per-token reference: teacher-force the prompt, then greedy argmax
    states = task.InitDecodeState(state.theta, 2, 4 + max_new)
    logits = None
    for t in range(4):
      logits, states = task.ExtendStep(state.theta, prompts[:, t:t + 1],
                                       states)
    out = []
    for _ in range(max_new):
      nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
      out.append(np.asarray(nxt))
      logits, states = task.ExtendStep(state.theta, nxt[:, None], states)
    ref = np.stack(out, axis=1)
    for i, rec in enumerate(recs):
      assert rec["output_ids"] == list(ref[i]), i
    # the telemetry satellite rides the same call
    assert driver._last_telemetry["decode_state_bytes_per_seq"] > 0

  def test_engine_matches_gshard_decode(self, hybrid_lm, tmp_path):
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu.serving import engine as engine_lib

    task, theta = hybrid_lm
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    ckpt.Save(1, state, force=True)
    ckpt.Close()
    prompts = np.array([[5, 6, 7, 8], [9, 10, 0, 0], [11, 0, 0, 0]],
                       np.int32)
    lens = np.array([4, 2, 1], np.int32)
    driver = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "out.jsonl"), max_decode_steps=4)
    recs = driver.DecodeOnce(1, prompts, lens)
    eng = engine_lib.ServingLoop(
        task, state.theta, page_size=4, num_pages=8, max_batch=3,
        max_seq_len=8, prefill_chunk=4, default_max_new=4)
    assert eng.mixers == {"num_attention": 1, "num_ssm": 1,
                          "decode_state_bytes_per_slot":
                              eng.state_pool.bytes_per_slot}
    out = eng.RunBatch(prompts, lens, 4)
    for i, rec in enumerate(recs):
      assert list(out[i]) == rec["output_ids"], f"row {i}"
    stats = eng.Stats()
    assert stats["scheduler"]["needs_kv_pages"] is True
    assert stats["state_slots"]["peak_in_use"] == 3
    assert stats["state_slots"]["in_use"] == 0   # released on retirement

  def test_pure_ssm_pageless_admission(self, pure_ssm_lm):
    """Fixed-HBM acceptance in miniature: with a pool that only fits ONE
    attention sequence, the pure-SSM stack still runs the whole batch
    concurrently — admission is slot-bound, the allocator never charged."""
    from lingvo_tpu.serving import engine as engine_lib

    task, theta = pure_ssm_lm
    prompts = np.array([[5, 6, 7, 8], [9, 10, 11, 12], [13, 14, 15, 16]],
                       np.int32)
    lens = np.array([4, 4, 4], np.int32)
    eng = engine_lib.ServingLoop(
        task, theta, page_size=4, num_pages=2, max_batch=3,
        max_seq_len=8, prefill_chunk=4, default_max_new=4)
    assert eng.paged_path == "ssm"
    for i in range(3):
      eng.Submit(prompts[i], 4, eos_id=None)
    eng.StepOnce()
    stats = eng.Stats()
    assert stats["scheduler"]["slots_live"] == 3       # all admitted at once
    assert stats["kv_pages"]["peak_in_use"] == 0       # pool untouched
    # the attention twin under the SAME pool admits only one at a time
    atten_task = _HybridLmParams(every_n=1).Instantiate()
    atten_task.FinalizePaths()
    atten_theta = atten_task.InstantiateVariables(jax.random.PRNGKey(0))
    eng_a = engine_lib.ServingLoop(
        atten_task, atten_theta, page_size=4, num_pages=2, max_batch=3,
        max_seq_len=8, prefill_chunk=4, default_max_new=4)
    for i in range(3):
      eng_a.Submit(prompts[i], 4, eos_id=None)
    eng_a.StepOnce()
    assert eng_a.Stats()["scheduler"]["slots_live"] == 1

  def test_more_decode_tokens_per_pool(self, pure_ssm_lm):
    """And it finishes: 6 requests through 3 slots on a 2-page pool."""
    from lingvo_tpu.serving import engine as engine_lib

    task, theta = pure_ssm_lm
    eng = engine_lib.ServingLoop(
        task, theta, page_size=4, num_pages=2, max_batch=3,
        max_seq_len=8, prefill_chunk=4, default_max_new=3)
    handles = [eng.Submit([3 + i, 4 + i], 3, eos_id=None) for i in range(6)]
    while True:
      with eng._lock:
        if not eng.sched.HasWork():
          break
      eng.StepOnce()
    for h in handles:
      assert len(h.Result(timeout=0)) == 3
    assert eng.Stats()["scheduler"]["finished"] == 6


# -- sampling -----------------------------------------------------------------


class TestSampling:

  def test_temperature_zero_is_argmax(self):
    logits = jax.random.normal(KEY, (4, 32))
    got = sampling.SampleFromLogits(logits, KEY, temperature=0.0, top_k=3)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(jnp.argmax(logits, axis=-1)))

  def test_top_k_one_is_argmax_at_any_temperature(self):
    logits = jax.random.normal(KEY, (4, 32))
    got = sampling.SampleFromLogits(logits, KEY, temperature=7.0, top_k=1)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(jnp.argmax(logits, axis=-1)))

  def test_top_k_restricts_support(self):
    logits = jax.random.normal(KEY, (4, 32))
    top3 = np.asarray(jax.lax.top_k(logits, 3)[1])
    for i in range(30):
      got = np.asarray(sampling.SampleFromLogits(
          logits, jax.random.PRNGKey(i), temperature=2.0, top_k=3))
      for r in range(4):
        assert got[r] in top3[r]

  def test_row_seeds_make_rows_batch_independent(self):
    logits = jax.random.normal(KEY, (4, 32))
    seeds = jnp.array([7, 8, 9, 10], jnp.int32)
    full = sampling.SampleFromLogits(logits, KEY, temperature=1.0,
                                     row_seeds=seeds)
    sub = sampling.SampleFromLogits(logits[1:3], KEY, temperature=1.0,
                                    row_seeds=seeds[1:3])
    np.testing.assert_array_equal(np.asarray(full)[1:3], np.asarray(sub))

  def test_positions_vary_the_stream(self):
    logits = jnp.zeros((2, 64))   # uniform: draws depend only on the key
    seeds = jnp.array([5, 5], jnp.int32)
    a = sampling.SampleFromLogits(logits, KEY, temperature=1.0,
                                  row_seeds=seeds,
                                  positions=jnp.array([0, 1], jnp.int32))
    # same seed, different position -> (almost surely) different draw;
    # same seed, same position -> identical draw
    b = sampling.SampleFromLogits(logits, KEY, temperature=1.0,
                                  row_seeds=seeds,
                                  positions=jnp.array([0, 0], jnp.int32))
    assert int(a[0]) == int(b[0]) == int(b[1])

  def test_gshard_decode_temp0_with_topk_identical_to_greedy(self, tmp_path):
    """The satellite bar: sampling params at temperature 0 are a no-op."""
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.models.lm import layers as lm_layers
    from lingvo_tpu.runners import gshard_decode

    task = lm_layers.TransformerLm.Params().Set(
        name="lm", vocab_size=64, model_dim=32, num_layers=1, num_heads=2,
        hidden_dim=64, use_rotary=True).Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    ckpt.Save(1, state, force=True)
    ckpt.Close()
    prompts = np.array([[5, 6, 7, 8]], np.int32)
    lens = np.array([4], np.int32)
    greedy = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "g.jsonl"), max_decode_steps=4)
    sampled = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "s.jsonl"), max_decode_steps=4,
        temperature=0.0, top_k=5)
    r_g = greedy.DecodeOnce(1, prompts, lens)
    r_s = sampled.DecodeOnce(1, prompts, lens)
    assert r_g[0]["output_ids"] == r_s[0]["output_ids"]

  def test_engine_seeded_sampling_replays_across_batches(self, hybrid_lm):
    """Same per-request seed -> same continuation, alone or with
    neighbors in flight (the per-request stream satellite)."""
    from lingvo_tpu.serving import engine as engine_lib

    task, theta = hybrid_lm

    def drain(eng):
      while True:
        with eng._lock:
          if not eng.sched.HasWork():
            return
        eng.StepOnce()

    eng = engine_lib.ServingLoop(
        task, theta, page_size=4, num_pages=8, max_batch=3, max_seq_len=8,
        prefill_chunk=4, default_max_new=4, temperature=0.9, top_k=16)
    h_alone = eng.Submit([5, 6, 7], 4, eos_id=None, seed=42)
    drain(eng)
    alone = h_alone.Result(timeout=0)
    for i in range(2):   # neighbors with different seeds
      eng.Submit([9 + i, 10 + i], 4, eos_id=None, seed=100 + i)
    h_again = eng.Submit([5, 6, 7], 4, eos_id=None, seed=42)
    drain(eng)
    assert h_again.Result(timeout=0) == alone
