"""detection_3d: rotated IoU vs the independent numpy implementation,
residual coding round-trip, anchor assignment, oriented NMS, corner loss."""

import math

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.models.car import ap_metric, detection_3d

KEY = jax.random.PRNGKey(0)


def _RandBoxes7(key, n, spread=8.0):
  k1, k2, k3 = jax.random.split(key, 3)
  xyz = jax.random.uniform(k1, (n, 3), minval=0.0, maxval=spread)
  dims = jax.random.uniform(k2, (n, 3), minval=0.5, maxval=3.0)
  phi = jax.random.uniform(k3, (n, 1), minval=-math.pi, maxval=math.pi)
  return jnp.concatenate([xyz, dims, phi], -1)


class TestRotatedIou:

  def test_matches_numpy_reference(self):
    # the jax polygon-clip IoU must agree with the independent numpy
    # implementation used by the AP metric
    a = np.asarray(_RandBoxes7(KEY, 12))
    b = np.asarray(_RandBoxes7(jax.random.PRNGKey(1), 9))
    got = np.asarray(detection_3d.RotatedIou7DOF(jnp.asarray(a),
                                                 jnp.asarray(b)))
    for i in range(a.shape[0]):
      for j in range(b.shape[0]):
        want = ap_metric.RotatedIou(a[i], b[j])
        assert abs(got[i, j] - want) < 1e-4, (i, j, got[i, j], want)

  def test_identity_and_disjoint(self):
    boxes = jnp.asarray([[0.0, 0, 0, 2, 2, 2, 0.3],
                         [100.0, 100, 0, 2, 2, 2, 0.0]])
    iou = np.asarray(detection_3d.RotatedIou7DOF(boxes, boxes))
    np.testing.assert_allclose(np.diag(iou), 1.0, atol=1e-5)
    assert iou[0, 1] == 0.0

  def test_jits(self):
    a = _RandBoxes7(KEY, 4)
    out = jax.jit(detection_3d.RotatedIou7DOF)(a, a)
    assert out.shape == (4, 4)


class TestResidualCoding:

  def test_round_trip(self):
    anchors = _RandBoxes7(KEY, 20)
    gt = _RandBoxes7(jax.random.PRNGKey(1), 20)
    res = detection_3d.LocalizationResiduals(anchors, gt)
    back = detection_3d.ResidualsToBBoxes(anchors, res)
    # angle wraps into [-pi, pi); compare sin/cos
    np.testing.assert_allclose(np.asarray(back[..., :6]),
                               np.asarray(gt[..., :6]), atol=1e-4)
    np.testing.assert_allclose(np.sin(np.asarray(back[..., 6])),
                               np.sin(np.asarray(gt[..., 6])), atol=1e-4)

  def test_zero_residuals_reproduce_anchor(self):
    anchors = _RandBoxes7(KEY, 5)
    back = detection_3d.ResidualsToBBoxes(anchors, jnp.zeros((5, 7)))
    np.testing.assert_allclose(np.asarray(back[..., :6]),
                               np.asarray(anchors[..., :6]), atol=1e-5)


class TestAnchors:

  def test_dense_coordinates(self):
    coords = detection_3d.CreateDenseCoordinates([(0, 1, 2), (0, 2, 3)])
    assert coords.shape == (6, 2)
    np.testing.assert_allclose(np.asarray(coords[0]), [0, 0])
    np.testing.assert_allclose(np.asarray(coords[-1]), [1, 2])

  def test_make_anchor_boxes(self):
    centers = jnp.asarray([[0.0, 0, 0], [5, 5, 0]])
    boxes = detection_3d.MakeAnchorBoxes(
        centers, [[2.0, 1, 1], [4, 2, 2]], [0.0, math.pi / 2],
        [[0.0, 0, 0], [0, 0, 1.0]])
    assert boxes.shape == (2 * 2 * 2, 7)
    np.testing.assert_allclose(np.asarray(boxes[0]), [0, 0, 0, 2, 1, 1, 0])
    # second dim config carries its z offset
    np.testing.assert_allclose(np.asarray(boxes[2]),
                               [0, 0, 1, 4, 2, 2, 0])


class TestAssignAnchors:

  def _Setup(self):
    anchors = jnp.asarray([
        [0.0, 0, 0, 2, 2, 2, 0],     # on gt 0
        [5.0, 5, 0, 2, 2, 2, 0],     # on gt 1
        [50.0, 50, 0, 2, 2, 2, 0],   # background
        [1.2, 0, 0, 2, 2, 2, 0],     # partial overlap with gt 0 -> ignore
    ])
    gt = jnp.asarray([[0.0, 0, 0, 2, 2, 2, 0],
                      [5.0, 5, 0, 2, 2, 2, 0],
                      [0.0, 0, 0, 0.1, 0.1, 0.1, 0]])
    labels = jnp.asarray([1, 2, 1], jnp.int32)
    mask = jnp.asarray([1.0, 1.0, 0.0])  # gt 2 is padding
    return anchors, gt, labels, mask

  def test_fg_bg_ignore(self):
    anchors, gt, labels, mask = self._Setup()
    out = detection_3d.AssignAnchors(
        anchors, gt, labels, mask,
        foreground_assignment_threshold=0.5,
        background_assignment_threshold=0.1, force_match=False)
    got_labels = np.asarray(out.assigned_gt_labels)
    assert got_labels[0] == 1 and got_labels[1] == 2
    assert got_labels[2] == 0  # background
    np.testing.assert_allclose(np.asarray(out.assigned_cls_mask),
                               [1, 1, 1, 0])  # anchor 3 ignored
    np.testing.assert_allclose(np.asarray(out.assigned_reg_mask),
                               [1, 1, 0, 0])

  def test_force_match_rescues_unmatched_gt(self):
    # one gt whose best anchor is below the fg threshold still gets it
    anchors = jnp.asarray([[1.5, 0, 0, 2.0, 2, 2, 0],
                           [50.0, 50, 0, 2, 2, 2, 0]])
    gt = jnp.asarray([[0.0, 0, 0, 2.0, 2, 2, 0]])
    labels = jnp.asarray([1], jnp.int32)
    mask = jnp.asarray([1.0])
    no_force = detection_3d.AssignAnchors(
        anchors, gt, labels, mask, foreground_assignment_threshold=0.5,
        force_match=False)
    assert np.asarray(no_force.assigned_reg_mask).sum() == 0
    forced = detection_3d.AssignAnchors(
        anchors, gt, labels, mask, foreground_assignment_threshold=0.5,
        force_match=True)
    np.testing.assert_allclose(np.asarray(forced.assigned_reg_mask), [1, 0])
    assert np.asarray(forced.assigned_gt_labels)[0] == 1


class TestOrientedNMS:

  def test_suppresses_overlaps_keeps_distinct(self):
    boxes = jnp.asarray([
        [0.0, 0, 0, 2, 2, 2, 0.0],
        [0.1, 0, 0, 2, 2, 2, 0.05],   # near-duplicate of 0, lower score
        [8.0, 8, 0, 2, 2, 2, 0.0],
    ])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    idxs, mask = detection_3d.OrientedNMSIndices(
        boxes, scores, max_output_size=3, nms_iou_threshold=0.3)
    kept = [int(i) for i, m in zip(np.asarray(idxs), np.asarray(mask)) if m]
    assert kept == [0, 2]

  def test_score_threshold(self):
    boxes = jnp.asarray([[0.0, 0, 0, 2, 2, 2, 0.0],
                         [8.0, 8, 0, 2, 2, 2, 0.0]])
    scores = jnp.asarray([0.9, 0.005])
    _, mask = detection_3d.OrientedNMSIndices(
        boxes, scores, max_output_size=2, score_threshold=0.01)
    assert np.asarray(mask).sum() == 1

  def test_decode_with_nms_per_class(self):
    b, n, c = 2, 8, 3
    boxes = _RandBoxes7(KEY, b * n).reshape(b, n, 7)
    probs = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(2), (b, n, c)), -1)
    out = jax.jit(lambda bb, pp: detection_3d.DecodeWithNMS(
        bb, pp, max_boxes_per_class=4))(boxes, probs)
    assert out.bboxes.shape == (b, c, 4, 7)
    assert out.scores.shape == (b, c, 4)
    # background class emits nothing
    assert np.asarray(out.valid_mask)[:, 0].sum() == 0


class TestCornerLoss:

  def test_zero_for_exact_and_flipped(self):
    boxes = _RandBoxes7(KEY, 6)
    loss = detection_3d.CornerLoss(boxes, boxes)
    np.testing.assert_allclose(np.asarray(loss), 0.0, atol=1e-5)
    flipped = boxes.at[:, 6].add(math.pi)
    loss_f = detection_3d.CornerLoss(boxes, flipped, symmetric=True)
    np.testing.assert_allclose(np.asarray(loss_f), 0.0, atol=1e-3)
    loss_nf = detection_3d.CornerLoss(boxes, flipped, symmetric=False)
    assert np.asarray(loss_nf).min() > 0.1

  def test_scaled_huber(self):
    lab = jnp.zeros((3,))
    pred = jnp.asarray([0.5, 2.0, -2.0])
    loss = np.asarray(detection_3d.ScaledHuberLoss(lab, pred, delta=1.0))
    np.testing.assert_allclose(loss[0], 0.125, atol=1e-6)  # quadratic zone
    np.testing.assert_allclose(loss[1], 1.5, atol=1e-6)    # linear zone
    np.testing.assert_allclose(loss[2], 1.5, atol=1e-6)
