"""Real-data input path tests: subword tokenizers, file-based sequence input
generators (bucketing + packing), and end-to-end training on text fixtures
(VERDICT r1 item 2: "real data wired to tasks").

Mirrors the reference's tokenizer_ops_test / record_batcher_test semantics
plus a trainer_test-style integration run.
"""

import os

import numpy as np
import pytest

from lingvo_tpu.core import tokenizers
from lingvo_tpu.core.nested_map import NestedMap


@pytest.fixture(scope="module")
def wpm_vocab(tmp_path_factory):
  d = tmp_path_factory.mktemp("wpm")
  path = d / "vocab.txt"
  pieces = ["<pad>", "<unk>", "<s>", "</s>"]
  # full single-char coverage so any word segments (spm style)
  chars = "abcdefghijklmnopqrstuvwxyz"
  pieces += ["▁" + c for c in chars]
  pieces += list(chars)
  pieces += ["▁the", "▁cat", "▁dog", "s", "▁sat",
             "▁on", "▁mat"]
  path.write_text("\n".join(pieces))
  return str(path)


@pytest.fixture(scope="module")
def bpe_files(tmp_path_factory):
  d = tmp_path_factory.mktemp("bpe")
  codes = d / "codes.txt"
  vocab = d / "vocab.txt"
  codes.write_text("\n".join(["#version: 0.2", "t h", "th e</w>",
                              "c a", "ca t</w>", "d o", "do g</w>"]))
  chars = "abcdefghijklmnopqrstuvwxyz"
  toks = ["<unk>", "<s>", "</s>", "the</w>", "cat</w>", "dog</w>", "th",
          "ca", "do"]
  toks += list(chars) + [c + "</w>" for c in chars]
  vocab.write_text("\n".join(toks))
  return str(codes), str(vocab)


class TestTokenizerLayers:

  def test_wpm_teacher_forcing_layout(self, wpm_vocab):
    p = tokenizers.WpmTokenizer.Params().Set(
        vocab_filepath=wpm_vocab, target_sos_id=2, target_eos_id=3,
        unk_token="<unk>")
    tok = p.Instantiate()
    ids, labels, paddings = tok.StringsToIds(["the cats", "dog"], 8)
    assert ids.shape == (2, 8)
    # ids start with sos; labels end with eos at the sequence boundary
    assert ids[0, 0] == 2 and ids[1, 0] == 2
    n0 = int((1 - paddings[0]).sum())
    assert labels[0, n0 - 1] == 3
    # shifted relationship: ids[1:] == labels[:-1] within the sequence
    np.testing.assert_array_equal(ids[0, 1:n0], labels[0, :n0 - 1])
    out = tok.IdsToStrings(labels, lens=(1 - paddings).sum(-1))
    assert out == ["the cats", "dog"]

  def test_bpe_round_trip(self, bpe_files):
    codes, vocab = bpe_files
    p = tokenizers.BpeTokenizer.Params().Set(
        codes_filepath=codes, vocab_filepath=vocab, target_sos_id=1,
        target_eos_id=2)
    tok = p.Instantiate()
    ids, labels, paddings = tok.StringsToIds(["the cat dog"], 10)
    out = tok.IdsToStrings(labels, lens=(1 - paddings).sum(-1))
    assert out == ["the cat dog"]
    assert tok.vocab_size > 50

  def test_ascii_params_layer(self):
    tok = tokenizers.AsciiTokenizer.Params().Instantiate()
    ids, labels, paddings = tok.StringsToIds(["hi there"], 12)
    out = tok.IdsToStrings(labels, lens=(1 - paddings).sum(-1))
    assert out == ["hi there"]


@pytest.fixture(scope="module")
def lm_text_dir(tmp_path_factory):
  d = tmp_path_factory.mktemp("lmtext")
  rng = np.random.RandomState(0)
  words = ["the", "cat", "dog", "cats", "sat", "on", "mat"]
  for shard in range(2):
    lines = []
    for _ in range(200):
      n = rng.randint(2, 8)
      lines.append(" ".join(rng.choice(words) for _ in range(n)))
    (d / f"shard-{shard}.txt").write_text("\n".join(lines))
  return str(d)


class TestTextLmInput:

  def _params(self, lm_text_dir, wpm_vocab, packing):
    from lingvo_tpu.models.lm import input_generator
    return input_generator.TextLmInput.Params().Set(
        file_pattern=f"text:{lm_text_dir}/shard-*.txt",
        tokenizer=tokenizers.WpmTokenizer.Params().Set(
            vocab_filepath=wpm_vocab, target_sos_id=2, target_eos_id=3),
        seq_len=32,
        bucket_upper_bound=[32],
        bucket_batch_limit=[4],
        packing=packing,
        num_reader_threads=1)

  def test_unpacked_batches(self, lm_text_dir, wpm_vocab):
    gen = self._params(lm_text_dir, wpm_vocab, packing=False).Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.ids.shape == (4, 32)
    assert batch.labels.shape == (4, 32)
    # teacher forcing within rows: some non-padding, labels shifted
    n = int((1 - batch.paddings[0]).sum())
    assert n >= 3
    np.testing.assert_array_equal(batch.ids[0, 1:n], batch.labels[0, :n - 1])
    gen.Reset()

  def test_packed_batches_have_segments(self, lm_text_dir, wpm_vocab):
    gen = self._params(lm_text_dir, wpm_vocab, packing=True).Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.ids.shape == (4, 32)
    assert batch.segment_ids.shape == (4, 32)
    # packing actually happened: some row holds >1 segment
    assert batch.segment_ids.max() >= 2
    # segment_pos restarts at 0 within each segment
    row = np.asarray(batch.segment_ids[0])
    pos = np.asarray(batch.segment_pos[0])
    for seg in range(1, int(row.max()) + 1):
      sel = pos[row == seg]
      assert sel[0] == 0 and np.all(np.diff(sel) == 1)
    # paddings exactly where segment_ids == 0
    np.testing.assert_array_equal(
        np.asarray(batch.paddings), (np.asarray(batch.segment_ids) == 0))
    gen.Reset()

  def test_per_host_sharding_splits_files(self, lm_text_dir, wpm_vocab):
    p = self._params(lm_text_dir, wpm_vocab, packing=False)
    p.num_hosts, p.host_index = 2, 0
    gen = p.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.ids.shape == (4, 32)
    gen.Reset()


@pytest.fixture(scope="module")
def mt_text_dir(tmp_path_factory):
  d = tmp_path_factory.mktemp("mttext")
  rng = np.random.RandomState(0)
  words = ["the", "cat", "dog", "sat", "on", "mat"]
  lines = []
  for _ in range(300):
    n = rng.randint(2, 10)
    src = [rng.choice(words) for _ in range(n)]
    tgt = list(reversed(src))
    lines.append(" ".join(src) + "\t" + " ".join(tgt))
  (d / "train.tsv").write_text("\n".join(lines))
  return str(d)


class TestTextMtInput:

  def test_bucketed_batches(self, mt_text_dir, wpm_vocab):
    from lingvo_tpu.models.mt import input_generator
    p = input_generator.TextMtInput.Params().Set(
        file_pattern=f"text:{mt_text_dir}/train.tsv",
        tokenizer=tokenizers.WpmTokenizer.Params().Set(
            vocab_filepath=wpm_vocab, target_sos_id=2, target_eos_id=3),
        source_max_length=24, target_max_length=24,
        bucket_upper_bound=[8, 24],
        bucket_batch_limit=[8, 4],
        num_reader_threads=1)
    gen = p.Instantiate()
    seen_shapes = set()
    for _ in range(6):
      batch = gen.GetPreprocessedInputBatch()
      b, t = batch.src.ids.shape
      assert (b, t) in {(8, 8), (4, 24)}, (b, t)
      seen_shapes.add((b, t))
      assert batch.tgt.ids.shape == (b, t)
      assert batch.tgt.labels.shape == (b, t)
      # teacher forcing on the target side
      row_len = int((1 - batch.tgt.paddings[0]).sum())
      np.testing.assert_array_equal(batch.tgt.ids[0, 1:row_len],
                                    batch.tgt.labels[0, :row_len - 1])
    assert len(seen_shapes) >= 1
    gen.Reset()


class TestPrefetcherExhaustion:

  def test_exhausted_stream_never_blocks(self, lm_text_dir, wpm_vocab):
    """Regression: a finite stream consumed twice used to deadlock the
    second consumer (eval cycle 2 waiting on the dead filler thread)."""
    from lingvo_tpu.models.lm import input_generator
    p = input_generator.TextLmInput.Params().Set(
        file_pattern=f"text:{lm_text_dir}/shard-0.txt",
        tokenizer=tokenizers.WpmTokenizer.Params().Set(
            vocab_filepath=wpm_vocab, target_sos_id=2, target_eos_id=3),
        seq_len=32, bucket_upper_bound=[32], bucket_batch_limit=[4],
        packing=False, num_reader_threads=1, max_epochs=1, shuffle=False)
    gen = p.Instantiate()
    n = sum(1 for _ in gen)  # drain to exhaustion
    assert n >= 1
    # second pass on the exhausted generator must return instantly (empty)
    assert sum(1 for _ in gen) == 0
    # after Reset the stream is re-readable (finite eval re-runs)
    gen.Reset()
    assert sum(1 for _ in gen) == n
    gen.Reset()


class TestEndToEndRealData:

  def test_lm_trains_on_text_fixture(self, lm_text_dir, wpm_vocab):
    """trainer-level integration: loss decreases on real text (VERDICT #2)."""
    import jax
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401

    mp = model_registry.GetParams("lm.one_billion_wds.OneBWdsRealData",
                                  "Train")
    mp.task.input = mp.input
    # shrink to test size
    mp.task.model_dim = 32
    mp.task.num_layers = 2
    mp.task.num_heads = 2
    mp.task.hidden_dim = 64
    mp.task.vocab_size = 128
    mp.task.residual_dropout_prob = 0.0
    # the production config warms up over 4000 steps; flat LR for a 30-step test
    from lingvo_tpu.core import schedule as sched_lib
    mp.task.train.learner.learning_rate = 3e-3
    mp.task.train.learner.lr_schedule = sched_lib.Constant.Params()
    mp.input.Set(
        file_pattern=f"text:{lm_text_dir}/shard-*.txt",
        tokenizer=tokenizers.WpmTokenizer.Params().Set(
            vocab_filepath=wpm_vocab, target_sos_id=2, target_eos_id=3),
        seq_len=32, bucket_upper_bound=[32], bucket_batch_limit=[8],
        num_reader_threads=1)
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(30):
      import jax.numpy as jnp
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    gen.Reset()
    # real text has learnable structure (tiny vocab): loss must drop
    assert losses[-1] < 0.7 * losses[0], (losses[0], losses[-1])
