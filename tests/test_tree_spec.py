"""Tree speculation on the unified ragged step.

Covers docs/speculative_decoding.md (tree section):
- `core/ragged.py` tree descriptors: DFS depths, per-column ancestor
  bitmasks (incl. the 64-column hi-word split), `BuildRaggedRows` tree
  rows (pos_ids = q_pos + depth, anc masks, col_parent) next to chain
  rows that keep the bitwise-neutral sentinels,
- `SpecVerifyTree` acceptance: greedy picks the longest LAWFUL
  root-to-leaf argmax chain (leftmost sibling on ties, never a branch
  whose head mismatches), emits the target argmax chain itself; W == 1
  is bitwise `SpecVerifyTokens`; adversarial trees (empty/all-invalid,
  full acceptance with bonus); at temperature > 0 the full-acceptance
  bonus is bitwise the plain positional draw and (slow) the emitted
  marginal over i.i.d.-sampled siblings matches the target law,
- scheduler tree packing: `BuildRaggedStep(spec_w > 1)` rows of
  1 + row_w * row_k tokens with DFS parents, width-before-depth clamping
  under the packed-row cap (`width_clamps` counted on Stats()),
  per-request `spec_w` opt-down, and `CommitRaggedStep` rolling back
  row_w * row_k - m tree nodes,
- the engine bar: greedy tree-spec output streams BYTE-IDENTICAL to the
  non-speculative engine — SelfDraft and ModelDraft drafts, dense /
  hybrid-SSM (in-program KV repair + SSM column restore) / repeat-stack
  targets, int8 KV pools (scale-sidecar repair), prefix cache on, and
  per-request width/depth/opt-out mixing — all through EXACTLY ONE
  compiled step program; w == 1 engines reproduce chain speculation,
- tree telemetry: `spec_branches` / `spec_width_clamps` /
  `accepted_depth_hist` on engine Stats() (GShard mirror keys are
  asserted schema-wide in test_serving_engine.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import ragged, sampling
from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import scheduler as scheduler_lib
from lingvo_tpu.serving import spec_decode

from tests.test_spec_decode import (_Engine, _Instantiate, _LmParams,
                                    _RunStream, _Stream)


# -- tree descriptors (core/ragged.py) ----------------------------------------


class TestTreeDescriptors:

  def test_depths_and_ancestor_masks_w2_k2(self):
    # two branches of depth 2: drafts [b0d0, b0d1, b1d0, b1d1]
    parents = [-1, 0, -1, 2]
    np.testing.assert_array_equal(ragged.TreeDepths(parents), [1, 2, 1, 2])
    lo, hi = ragged.TreeAncestorMasks(parents)
    # col 0 root=bit0; col1=root|self; col2=col1|bit2; col3=root|bit3;
    # col4=col3|bit4
    np.testing.assert_array_equal(lo, [0b1, 0b11, 0b111, 0b1001, 0b11001])
    np.testing.assert_array_equal(hi, [0, 0, 0, 0, 0])

  def test_ancestor_masks_spill_into_hi_word(self):
    # a 35-deep chain-as-tree crosses the 32-bit boundary: columns >= 32
    # carry their ancestor bits in the hi word
    r = 35
    parents = np.arange(-1, r - 1)
    lo, hi = ragged.TreeAncestorMasks(parents)
    assert lo[31] == -1 and hi[31] == 0          # bits 0..31 all set
    assert lo[35] == -1 and hi[35] == 0b1111     # bits 32..35 in hi
    with pytest.raises(AssertionError):
      ragged.TreeAncestorMasks(np.arange(-1, ragged.MAX_TREE_COLS - 1))

  def test_build_ragged_rows_tree_next_to_chain(self):
    # row 0: w=2,k=2 tree at q_pos 10; row 1: plain 3-token chain at 4
    desc = ragged.BuildRaggedRows([5, 3], [10, 4], 8, 5,
                                  row_parents={0: [-1, 0, -1, 2]})
    # KV slots stay DFS-packed (collision-free): pos = q_pos + col
    np.testing.assert_array_equal(desc.pos[:5], [10, 11, 12, 13, 14])
    # logical/rotary positions follow tree DEPTH, branches repeat depths
    np.testing.assert_array_equal(desc.pos_ids[:5], [10, 11, 12, 11, 12])
    np.testing.assert_array_equal(desc.anc_lo[:5],
                                  [0b1, 0b11, 0b111, 0b1001, 0b11001])
    np.testing.assert_array_equal(desc.col_parent[0], [-1, 0, 1, 0, 3])
    # the chain row keeps the bitwise-neutral sentinels of the pre-tree
    # build: pos_ids == pos, anc == -1 (mask reads all-ones), parent c-1
    np.testing.assert_array_equal(desc.pos_ids[5:], desc.pos[5:])
    np.testing.assert_array_equal(desc.anc_lo[5:], [-1, -1, -1])
    np.testing.assert_array_equal(desc.anc_hi[5:], [-1, -1, -1])
    np.testing.assert_array_equal(desc.col_parent[1], [-1, 0, 1, 2, 3])


# -- SpecVerifyTree (core/sampling.py) ----------------------------------------


def _ChainBranches(b, w, k):
  """The engine's static branch table: branch bi's depth-d node bi*k+d."""
  return jnp.broadcast_to(
      jnp.arange(w * k, dtype=jnp.int32).reshape(1, w, k), (b, w, k))


class TestSpecVerifyTree:

  def _Greedy(self, logits, draft, w, k, valid=None):
    b = logits.shape[0]
    r = w * k
    out, m, br = sampling.SpecVerifyTree(
        jnp.asarray(logits), jnp.asarray(draft), _ChainBranches(b, w, k),
        jnp.zeros((b, r, logits.shape[-1])), jax.random.PRNGKey(0),
        draft_valid=None if valid is None else jnp.asarray(valid))
    return np.asarray(out), np.asarray(m), np.asarray(br)

  def test_greedy_accepts_longest_lawful_branch(self):
    # w=2, k=2 over the chain-layout: target argmax after column c is
    # token c+1 only along branch 1's path; branch 0 dies at its head
    b, w, k, v = 1, 2, 2, 16
    r = w * k
    logits = np.full((b, r + 1, v), -5.0, np.float32)
    logits[:, 0, 9] = 5.0      # root argmax: 9
    logits[:, 3, 6] = 5.0      # after b1d0 (draft 2, col 3): 6
    logits[:, 4, 7] = 5.0      # after b1d1 (draft 3, col 4): 7
    draft = np.array([[8, 6, 9, 6]], np.int32)   # b0 head 8 mismatches
    out, m, br = self._Greedy(logits, draft, w, k)
    assert int(m[0]) == 2 and int(br[0]) == 1
    # emitted tokens ARE the target argmax chain: 9 (accepted head),
    # 6 (accepted depth 2), 7 (bonus after the leaf)
    np.testing.assert_array_equal(out[0], [9, 6, 7])

  def test_greedy_never_jumps_branches_mid_path(self):
    # branch 0's head matches but its depth-2 node mismatches; branch 1's
    # depth-2 node WOULD match — a lawful walk must still stop at m=1 on
    # branch 0 (root-to-leaf paths only, no cross-branch grafting)
    b, w, k, v = 1, 2, 2, 16
    r = w * k
    logits = np.full((b, r + 1, v), -5.0, np.float32)
    logits[:, 0, 9] = 5.0      # root argmax: 9 == both heads
    logits[:, 1, 6] = 5.0      # after b0d0 (col 1): 6
    logits[:, 3, 6] = 5.0      # after b1d0 (col 3): 6
    draft = np.array([[9, 4, 9, 6]], np.int32)   # only b1 continues right
    out, m, br = self._Greedy(logits, draft, w, k)
    assert int(br[0]) == 0 and int(m[0]) == 1    # leftmost tie, then stop
    np.testing.assert_array_equal(out[0][:2], [9, 6])

  def test_greedy_sibling_ties_pick_leftmost(self):
    b, w, k, v = 1, 3, 1, 8
    logits = np.full((b, w + 1, v), -5.0, np.float32)
    logits[:, :, 2] = 5.0
    draft = np.array([[2, 2, 2]], np.int32)      # all heads tie
    _, m, br = self._Greedy(logits, draft, w, k)
    assert int(m[0]) == 1 and int(br[0]) == 0

  def test_empty_tree_emits_root_argmax(self):
    # all-invalid drafts (a row_k == 0 row riding a tree verify): m == 0
    # and column 0 carries the plain root argmax
    b, w, k, v = 2, 2, 2, 8
    logits = np.random.RandomState(0).randn(b, w * k + 1, v).astype(
        np.float32)
    draft = np.zeros((b, w * k), np.int32)
    out, m, _ = self._Greedy(logits, draft, w, k,
                             valid=np.zeros((b, w * k), bool))
    assert list(m) == [0, 0]
    np.testing.assert_array_equal(out[:, 0], logits[:, 0].argmax(-1))

  def test_full_acceptance_emits_bonus_at_leaf(self):
    # drafts equal the argmax chain along branch 0: m == k and the last
    # output column is the argmax AFTER the accepted leaf (the bonus)
    b, w, k, v = 1, 2, 3, 16
    r = w * k
    logits = np.full((b, r + 1, v), -5.0, np.float32)
    chain = [3, 4, 5, 6]                         # root, d1, d2, bonus
    logits[:, 0, chain[0]] = 5.0
    for d in range(k):
      logits[:, d + 1, chain[d + 1]] = 5.0       # branch 0 cols 1..k
    draft = np.array([[3, 4, 5, 9, 9, 9]], np.int32)
    out, m, br = self._Greedy(logits, draft, w, k)
    assert int(m[0]) == k and int(br[0]) == 0
    np.testing.assert_array_equal(out[0], chain)

  def test_w1_is_bitwise_spec_verify_tokens(self):
    # chain speculation is the degenerate tree: same outputs BITWISE at
    # temperature 0 and at temperature > 0 (same stream-key convention)
    b, k, v = 3, 4, 32
    rng = np.random.RandomState(5)
    tl = rng.randn(b, k + 1, v).astype(np.float32)
    ql = rng.randn(b, k, v).astype(np.float32)
    draft = rng.randint(0, v, (b, k)).astype(np.int32)
    valid = rng.rand(b, k) < 0.8
    key = jax.random.PRNGKey(3)
    seeds = jnp.asarray([2, 4, 8], jnp.int32)
    pos = jnp.asarray([0, 5, 11], jnp.int32)
    for temp in (0.0, 0.9):
      out_c, m_c = sampling.SpecVerifyTokens(
          jnp.asarray(tl), jnp.asarray(draft), jnp.asarray(ql), key,
          temperature=temp, top_k=0, row_seeds=seeds, row_pos=pos,
          draft_valid=jnp.asarray(valid))
      out_t, m_t, br = sampling.SpecVerifyTree(
          jnp.asarray(tl), jnp.asarray(draft), _ChainBranches(b, 1, k),
          jnp.asarray(ql), key, temperature=temp, top_k=0,
          row_seeds=seeds, row_pos=pos, draft_valid=jnp.asarray(valid))
      np.testing.assert_array_equal(np.asarray(m_c), np.asarray(m_t))
      assert list(np.asarray(br)) == [0] * b
      # the engine consumes out[:, :m+1]; columns past the cut are
      # unconsumed on both sides and need not agree
      for i, mi in enumerate(np.asarray(m_c)):
        np.testing.assert_array_equal(np.asarray(out_c)[i, :mi + 1],
                                      np.asarray(out_t)[i, :mi + 1],
                                      err_msg=f"temp={temp} row={i}")

  def test_temp_full_acceptance_bonus_is_positional_draw(self):
    # peaked target + matching drafts: every branch-0 path accepts, and
    # the bonus must be bitwise the legacy SampleFromLogits draw at
    # stream position row_pos + k
    b, w, k, v = 3, 2, 2, 16
    r = w * k
    rng = np.random.RandomState(7)
    tl = rng.randn(b, r + 1, v).astype(np.float32)
    ql = np.zeros((b, r, v), np.float32)
    chain_cols = [0, 1, 2]                       # branch 0's root path
    draft = np.zeros((b, r), np.int32)
    for d in range(k):
      tok = rng.randint(v, size=b)
      tl[np.arange(b), chain_cols[d], tok] += 100.0
      ql[np.arange(b), d, tok] += 100.0
      draft[:, d] = tok
    key = jax.random.PRNGKey(11)
    seeds = jnp.asarray([5, 6, 7], jnp.int32)
    pos = jnp.asarray([0, 3, 9], jnp.int32)
    out, m, _ = sampling.SpecVerifyTree(
        jnp.asarray(tl), jnp.asarray(draft), _ChainBranches(b, w, k),
        jnp.asarray(ql), key, temperature=0.7, top_k=0, row_seeds=seeds,
        row_pos=pos)
    assert list(np.asarray(m)) == [k] * b
    legacy = sampling.SampleFromLogits(
        jnp.asarray(tl[:, k]), key, temperature=0.7, row_seeds=seeds,
        positions=pos + k)
    np.testing.assert_array_equal(np.asarray(out[:, k]),
                                  np.asarray(legacy))


@pytest.mark.slow
class TestTreeResidualSamplingLaw:

  def test_emitted_marginal_matches_target_law_over_siblings(self):
    """Multi-round sibling rejection must still emit exactly softmax(p):
    empirical frequencies over many rows with w=2 draft-sampled sibling
    heads vs the target law (TV distance). Each sibling must be drawn
    from ITS OWN declared proposal head — that's the contract the
    residual update relies on."""
    b, w, v = 4000, 2, 6
    rng = np.random.RandomState(1)
    tl = np.tile(rng.randn(1, w + 1, v).astype(np.float32), (b, 1, 1))
    ql = np.tile(rng.randn(1, w, v).astype(np.float32), (b, 1, 1))
    draft = np.stack(
        [rng.choice(v, size=(b,),
                    p=np.exp(ql[0, i]) / np.exp(ql[0, i]).sum())
         for i in range(w)], axis=1).astype(np.int32)
    out, _, _ = sampling.SpecVerifyTree(
        jnp.asarray(tl), jnp.asarray(draft), _ChainBranches(b, w, 1),
        jnp.asarray(ql), jax.random.PRNGKey(9), temperature=1.0,
        top_k=0, row_seeds=jnp.arange(b, dtype=jnp.int32),
        row_pos=jnp.zeros((b,), jnp.int32))
    freq = np.bincount(np.asarray(out[:, 0]), minlength=v) / b
    p = np.exp(tl[0, 0]) / np.exp(tl[0, 0]).sum()
    assert np.abs(freq - p).sum() < 0.05   # total-variation tolerance


# -- scheduler tree packing (device-free) -------------------------------------


def _DecodingSched(reqs, slots=2, pages=24):
  alloc = kv_cache.PageAllocator(pages, 4)
  sched = scheduler_lib.Scheduler(slots, alloc, 8, 4)
  for r in reqs:
    sched.Submit(r)
  sched.Admit()
  while any(s is not None and s.state is scheduler_lib.SeqState.PREFILL
            for s in sched.slots):
    batch = sched.BuildRaggedStep(16, 4)
    sched.CommitRaggedStep(batch, np.full((16,), 7, np.int32))
  return sched, alloc


class TestTreeScheduler:

  def test_tree_row_packs_dfs_parents(self):
    sched, _ = _DecodingSched([
        scheduler_lib.Request("a", [1, 2], 16),            # full tree
        scheduler_lib.Request("b", [3, 4], 16, spec_w=1),  # chain opt-down
    ])
    batch = sched.BuildRaggedStep(16, 7, spec_k=2, spec_w=3)
    d = batch.rows_desc
    np.testing.assert_array_equal(d.row_len, [7, 3])
    np.testing.assert_array_equal(batch.row_k, [2, 2])
    np.testing.assert_array_equal(batch.row_w, [3, 1])
    # branch bi's depth-d node at column 1 + bi*rk + d, heads off the root
    np.testing.assert_array_equal(d.col_parent[0], [-1, 0, 1, 0, 3, 0, 5])
    # the chain row ships the bitwise-neutral pre-tree descriptors
    np.testing.assert_array_equal(d.col_parent[1], [-1, 0, 1, 2, 3, 4, 5])
    assert d.anc_lo[d.row_cols[1, 0]] == -1
    assert batch.width_clamps == 0 and batch.any_spec

  def test_width_clamps_before_depth(self):
    sched, _ = _DecodingSched([scheduler_lib.Request("a", [1, 2], 16)],
                              slots=1)
    # wmax 8 can't fit 1 + 4*3: width drops (4 -> 3 -> 2) before depth,
    # THEN depth re-expands into the freed columns ((8-1)//2 = 3)
    batch = sched.BuildRaggedStep(8, 8, spec_k=3, spec_w=4)
    assert int(batch.row_w[0]) == 2 and int(batch.row_k[0]) == 3
    assert int(batch.rows_desc.row_len[0]) == 7
    assert batch.width_clamps == 1
    assert sched.width_clamps == 1
    assert sched.Stats()["width_clamps"] == 1

  def test_stats_width_clamps_key_in_schema(self):
    sched, _ = _DecodingSched([scheduler_lib.Request("a", [1], 8)])
    assert set(sched.Stats()) == observe_schema.SCHEDULER_STATS_KEYS

  def test_budget_exhausted_tree_respects_max_new(self):
    # 2 tokens of max_new budget left => rk clamps to 2 before widths
    sched, _ = _DecodingSched([scheduler_lib.Request("a", [1, 2], 3)],
                              slots=1)
    batch = sched.BuildRaggedStep(16, 9, spec_k=4, spec_w=2)
    assert int(batch.row_k[0]) == 2 and int(batch.row_w[0]) == 2
    assert batch.width_clamps == 0

  def test_tree_writes_stay_inside_reserved_pages(self):
    """A wide tree near the end of its budget must shrink until its
    transient draft slots fit the pages reserved at admission — an
    unclamped row would scatter K/V through table entry 0 into pool
    page 0 (another sequence's page)."""
    sched, alloc = _DecodingSched(
        [scheduler_lib.Request("a", [1, 2, 3, 4, 5], 3)], slots=1)
    seq = sched._by_id["a"]
    # footprint: PagesFor(5 + 3) = 2 pages = 8 slots; feedback at slot 5
    # leaves room for only 2 draft slots -> width collapses to a chain
    batch = sched.BuildRaggedStep(16, 9, spec_k=2, spec_w=3)
    assert int(batch.row_w[0]) == 1 and int(batch.row_k[0]) == 2
    assert batch.width_clamps == 1
    cap_tok = len(alloc.PagesOf("a")) * 4
    assert int(seq.pos) + int(batch.rows_desc.row_len[0]) <= cap_tok

  def test_commit_rolls_back_losing_branches(self):
    sched, alloc = _DecodingSched([scheduler_lib.Request("a", [1, 2], 16)],
                                  slots=1)
    batch = sched.BuildRaggedStep(16, 7, spec_k=2, spec_w=3)
    seq = sched._by_id["a"]
    pos0 = seq.pos
    out = np.zeros((1, 3), np.int32)
    out[0, :2] = [5, 6]
    before = alloc.Stats()["rolled_back_tokens"]
    ev = sched.CommitRaggedStep(batch, np.zeros((16,), np.int32),
                                out_tokens=out,
                                accept_len=np.array([1], np.int32))
    # m=1 of row_w*row_k=6 nodes survive: 5 roll back, 2 tokens commit
    assert [t for _, t, _ in ev] == [5, 6]
    assert seq.pos == pos0 + 2
    assert alloc.Stats()["rolled_back_tokens"] - before == 5


# -- the engine bar: tree byte-identity through one program -------------------


class TestTreeEngine:

  def _Baseline(self, task, theta, reqs, **kw):
    return _RunStream(_Engine(task, theta, **kw), reqs)

  def _AssertTreeStats(self, eng, w):
    stats = eng.Stats()
    comp = stats["compile"]
    assert comp[observe_schema.COMPILE_CENSUS_KEY] == 1
    assert set(comp) & observe_schema.STEP_PROGRAM_NAMES == {"ragged"}
    assert stats["spec_branches"] >= w * (stats["spec_cycles"] > 0)
    # hist[m] counts per speculating ROW (several per cycle); its weighted
    # sum is exactly the accepted-token counter on the other surface
    hist = stats["accepted_depth_hist"]
    assert sum(m * n for m, n in enumerate(hist)) \
        == stats["accepted_tokens"]
    return stats

  def test_self_draft_tree_token_identical_census_one(self, tiny_lm):
    task, theta = tiny_lm
    reqs = _Stream(12, seed=7)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=2, w=2),
                  num_pages=48)
    assert _RunStream(eng, reqs) == base
    stats = self._AssertTreeStats(eng, w=2)
    assert stats["spec_cycles"] > 0
    assert stats["kv_pages"]["free"] == eng.num_pages
    assert stats["spec"]["w"] == 2

  def test_model_draft_tree_token_identical(self, tiny_lm, ssm_draft_lm):
    task, theta = tiny_lm
    dtask, dtheta = ssm_draft_lm
    reqs = _Stream(10, seed=8)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta,
                  spec_decode.ModelDraft(dtask, dtheta, k=3, w=2),
                  num_pages=48)
    assert _RunStream(eng, reqs) == base
    self._AssertTreeStats(eng, w=2)

  def test_hybrid_ssm_target_tree_token_identical(self, hybrid_lm,
                                                  ssm_draft_lm):
    """Hybrid SSM+attention target under BOTH draft sources: rejected
    branches must restore the SSM column state AND the in-program KV
    repair must land the accepted path on the canonical chain slots."""
    task, theta = hybrid_lm
    dtask, dtheta = ssm_draft_lm
    reqs = _Stream(8, seed=9)
    base = self._Baseline(task, theta, reqs)
    for spec in (spec_decode.SelfDraft(k=2, w=2),
                 spec_decode.ModelDraft(dtask, dtheta, k=2, w=3)):
      eng = _Engine(task, theta, spec, num_pages=48)
      assert _RunStream(eng, reqs) == base, spec.Describe()
      self._AssertTreeStats(eng, w=spec.w)

  def test_repeat_stack_target_tree_token_identical(self):
    """RepeatedTransformerLayer target: the KV-repair leaf-axis probe
    must find the page axis under the extra leading repeat axis."""
    task, theta = _Instantiate(
        _LmParams().Set(use_repeat_layer=True, num_layers=3))
    reqs = _Stream(6, seed=10)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=2, w=2),
                  num_pages=48)
    assert _RunStream(eng, reqs) == base
    self._AssertTreeStats(eng, w=2)

  def test_int8_kv_tree_token_identical(self, tiny_lm):
    """int8 KV pools: the repair scatter must move the quantized pages
    AND their per-page scale sidecars (offset axis != page axis + 1)."""
    task, theta = tiny_lm
    reqs = _Stream(8, seed=11)
    base = self._Baseline(task, theta, reqs, kv_cache_dtype="int8")
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=2, w=2),
                  kv_cache_dtype="int8", num_pages=48)
    assert _RunStream(eng, reqs) == base
    self._AssertTreeStats(eng, w=2)

  def test_prefix_cache_tree_token_identical(self, tiny_lm):
    """Tree verify over CoW-shared prefix pages: repair writes only ever
    target the row's private tail pages, so sharing survives."""
    task, theta = tiny_lm
    shared = [3, 4, 5, 6, 7, 8, 9, 10]
    reqs = [(shared + [i + 11], 5) for i in range(6)]
    base = self._Baseline(task, theta, reqs, prefix_cache=True)
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=2, w=2),
                  prefix_cache=True, num_pages=48)
    assert _RunStream(eng, reqs) == base
    stats = self._AssertTreeStats(eng, w=2)
    assert stats["prefix_hit_tokens"] > 0

  def test_w1_engine_reproduces_chain_engine(self, tiny_lm):
    """w == 1 keeps the EXACT chain step program: same outputs and same
    acceptance accounting as the pre-tree engine config."""
    task, theta = tiny_lm
    reqs = _Stream(10, seed=12)
    chain = _Engine(task, theta, spec_decode.SelfDraft(k=3))
    tree1 = _Engine(task, theta, spec_decode.SelfDraft(k=3, w=1))
    out_c = _RunStream(chain, reqs)
    out_t = _RunStream(tree1, reqs)
    assert out_c == out_t
    sc, st = chain.Stats(), tree1.Stats()
    for key in ("draft_tokens", "accepted_tokens", "accepted_len_hist",
                "spec_cycles", "tokens_emitted"):
      assert sc[key] == st[key], key
    assert st["spec_width_clamps"] == 0

  def test_per_request_knob_mixing_token_identical(self, tiny_lm):
    """spec_w=1 / spec_k=0 / narrow-tree / default rows ride the SAME
    packed steps without perturbing each other's streams."""
    task, theta = tiny_lm
    reqs = _Stream(8, seed=13)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=3, w=4),
                  num_pages=48)
    handles = []
    for i, (p, m) in enumerate(reqs):
      kw = [dict(spec_w=1), dict(spec_k=0),
            dict(spec_w=2, spec_k=1), {}][i % 4]
      handles.append(eng.Submit(p, m, eos_id=None, **kw))
    while eng.sched.HasWork():
      eng.StepOnce()
    assert [h.Result(timeout=0) for h in handles] == base
    self._AssertTreeStats(eng, w=1)
