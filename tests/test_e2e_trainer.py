"""End-to-end trainer/executor/checkpointer integration tests.

Mirrors the reference's `trainer_test.py` (`BaseTrainerTest:51`): run real
train/eval programs in-process on tiny models, verify loss goes down,
checkpoints round-trip, and registry-driven construction works for every
registered model (ref `models_test_helper.BaseModelsTest:96`).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core.nested_map import NestedMap


def _TinyMnistModelParams(tmp_path, max_steps=40):
  import lingvo_tpu.models.all_params  # noqa: F401
  mp = model_registry.GetParams("image.mnist.LeNet5", "Train")
  mp.task.input = mp.input
  mp.task.input.batch_size = 32
  mp.task.input.num_samples = 512
  mp.task.train.max_steps = max_steps
  mp.task.train.tpu_steps_per_loop = 10
  mp.task.train.save_interval_steps = 20
  return mp


class TestIdentityRegressionTask:
  """Tiny from-scratch task exercising BaseTask plumbing
  (ref trainer_test_utils IdentityRegressionTask)."""

  class _RegressionTask(base_model.BaseTask):

    @classmethod
    def Params(cls):
      p = super().Params()
      p.Define("dim", 4, "")
      return p

    def __init__(self, params):
      super().__init__(params)
      self.CreateChild(
          "proj",
          layers.ProjectionLayer.Params().Set(
              input_dim=self.p.dim, output_dim=self.p.dim))

    def ComputePredictions(self, theta, input_batch):
      return self.proj.FProp(theta.proj, input_batch.x)

    def ComputeLoss(self, theta, predictions, input_batch):
      err = jnp.mean(jnp.square(predictions - input_batch.y))
      b = input_batch.x.shape[0]
      return NestedMap(loss=(err, float(b))), NestedMap()

  def _task(self):
    p = self._RegressionTask.Params().Set(name="reg", dim=4)
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=0.1, optimizer=opt_lib.Adam.Params())
    return p.Instantiate()

  def test_train_step_reduces_loss(self):
    task = self._task()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    x = rng.randn(16, 4).astype("float32")
    batch = NestedMap(x=jnp.asarray(x), y=jnp.asarray(2 * x))
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(60):
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert losses[-1] < 0.1 * losses[0]
    assert int(state.step) == 60

  def test_ema_tracks_theta(self):
    p = self._RegressionTask.Params().Set(name="reg", dim=4)
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=0.5, optimizer=opt_lib.SGD.Params())
    p.train.ema_decay = 0.9
    task = p.Instantiate()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    assert "ema_theta" in state
    batch = NestedMap(x=jnp.ones((4, 4)), y=jnp.zeros((4, 4)))
    step = jax.jit(task.TrainStep)
    state2, _ = step(state, batch)
    # ema moved toward new theta but lags it
    w_new = state2.theta.proj.w
    w_ema = state2.ema_theta.proj.w
    w_old = state.theta.proj.w
    assert not np.allclose(w_ema, w_new)
    assert not np.allclose(w_ema, w_old)


class TestExecutorEndToEnd:

  def test_mnist_executor_train_and_resume(self, tmp_path):
    from lingvo_tpu.runners import executor as executor_lib
    from lingvo_tpu.runners import program as program_lib

    mp = _TinyMnistModelParams(tmp_path, max_steps=20)
    task = mp.task.Instantiate()
    task.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=mp.task, logdir=str(tmp_path), steps_per_loop=10)
    sched_p = program_lib.SimpleProgramSchedule.Params().Set(
        train_program=train_p)
    sched = program_lib.SimpleProgramSchedule(sched_p, task=task)
    execu = executor_lib.ExecutorTpu(mp, str(tmp_path), schedule=sched,
                                     task=task)
    state = execu.Start()
    assert int(jax.device_get(state.step)) == 20
    # metrics exported
    assert os.path.exists(tmp_path / "metrics.jsonl")
    assert os.path.exists(tmp_path / "trainer_params.txt")
    assert os.path.exists(tmp_path / "model_analysis.txt")

    # Resume: a fresh executor restores from step 20 and continues.
    mp2 = _TinyMnistModelParams(tmp_path, max_steps=30)
    task2 = mp2.task.Instantiate()
    task2.FinalizePaths()
    train_p2 = program_lib.TrainProgram.Params().Set(
        task=mp2.task, logdir=str(tmp_path), steps_per_loop=10)
    sched2 = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(
            train_program=train_p2), task=task2)
    execu2 = executor_lib.ExecutorTpu(mp2, str(tmp_path), schedule=sched2,
                                      task=task2)
    state2 = execu2.Start()
    assert int(jax.device_get(state2.step)) == 30


class TestCheckpointer:

  def test_save_restore_roundtrip(self, tmp_path):
    from lingvo_tpu.core import checkpointer as ck
    c = ck.Checkpointer(str(tmp_path / "ckpt"), save_interval_steps=5,
                        async_save=False)
    state = NestedMap(
        step=jnp.asarray(7, jnp.int32),
        theta=NestedMap(w=jnp.arange(6, dtype=jnp.float32).reshape(2, 3)))
    assert c.Save(0, state, force=True)
    template = state.Transform(jnp.zeros_like)
    restored, step = c.Restore(template)
    assert step == 0
    np.testing.assert_array_equal(restored.theta.w, state.theta.w)
    assert int(restored.step) == 7
    c.Close()

  def test_restore_or_init_without_checkpoint(self, tmp_path):
    from lingvo_tpu.core import checkpointer as ck
    c = ck.Checkpointer(str(tmp_path / "none"), async_save=False)
    state = NestedMap(w=jnp.ones(3))
    restored, step = c.Restore(state)
    assert step == 0
    np.testing.assert_array_equal(restored.w, state.w)
    c.Close()

  def test_sanity_check_rejects_nan(self, tmp_path):
    from lingvo_tpu.core import checkpointer as ck
    c = ck.Checkpointer(str(tmp_path / "bad"), async_save=False)
    state = NestedMap(w=jnp.array([1.0, np.nan]))
    with pytest.raises(ValueError, match="sanity"):
      c.Save(0, state, force=True)
    c.Close()

  def test_should_save_cadence(self, tmp_path):
    from lingvo_tpu.core import checkpointer as ck
    c = ck.Checkpointer(str(tmp_path / "cad"), save_interval_steps=100,
                        async_save=False)
    assert c.ShouldSave(0)
    assert not c.ShouldSave(55)
    assert c.ShouldSave(100)
    c.Close()


class TestRegistryModels:
  """Registry-wide smoke test (ref models_test_helper:96): every registered
  model's params must instantiate and declare variables."""

  def test_all_registered_models_instantiate(self):
    import lingvo_tpu.models.all_params  # noqa: F401
    models = model_registry.GetRegisteredModels()
    assert models, "registry is empty"
    for name in models:
      mp = model_registry.GetParams(name, "Train")
      task = mp.task.Instantiate()
      task.FinalizePaths()
      specs = task.VariableSpecs()
      assert len(specs.FlattenItems()) > 0, name


class TestMetricsFixes:

  def test_auc_tie_handling(self):
    from lingvo_tpu.core import metrics as metrics_lib
    m = metrics_lib.AUCMetric()
    m.Update(1, 0.5)
    m.Update(0, 0.5)
    assert m.value == pytest.approx(0.5)  # constant classifier -> 0.5
    m2 = metrics_lib.AUCMetric()
    for label, s in [(1, 0.9), (1, 0.8), (0, 0.2), (0, 0.1)]:
      m2.Update(label, s)
    assert m2.value == pytest.approx(1.0)  # perfect separation

  def test_epoch_batches_covers_tail(self):
    from lingvo_tpu.core import base_input_generator as big
    data = NestedMap(x=np.arange(10, dtype=np.float32))
    gen = big.InMemoryInputGenerator.Params().Set(
        name="g", data=data, batch_size=4, shuffle=False).Instantiate()
    batches = list(gen.EpochBatches())
    assert len(batches) == 3
    np.testing.assert_array_equal(batches[2].x, [8, 9, 0, 1])  # wrap-padded

  def test_schedule_zero_train_executions(self):
    from lingvo_tpu.runners import program as program_lib
    mp = _TinyMnistModelParams(None, max_steps=10)
    task = mp.task.Instantiate()
    task.FinalizePaths()
    import tempfile
    with tempfile.TemporaryDirectory() as d:
      train_p = program_lib.TrainProgram.Params().Set(
          task=mp.task, logdir=d, steps_per_loop=2)
      sched = program_lib.SimpleProgramSchedule(
          program_lib.SimpleProgramSchedule.Params().Set(
              train_program=train_p, train_executions_per_eval=0), task=task)
      state = task.CreateTrainState(jax.random.PRNGKey(0))
      state, results = sched.Run(state)
      assert "train" in results  # clamped to one execution, no crash


class TestTrainerCli:

  def test_inspect_params_and_model(self, tmp_path, capsys):
    from lingvo_tpu import trainer
    rc = trainer.main(["--model=image.mnist.LeNet5", "--mode=inspect_params"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "softmax" in out and "cls :" in out
    rc = trainer.main(["--model=image.mnist.LeNet5", "--mode=inspect_model"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "TOTAL" in out


class TestOnDeviceLoop:

  def test_on_device_loop_matches_host_loop(self, tmp_path):
    """steps_per_loop as ONE jitted scan == per-step host loop (theta and
    metrics), the reference's in-graph training loop idiom."""
    from lingvo_tpu.runners import program as program_lib
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input

    def run(on_device):
      task = mp.task.Instantiate()
      task.FinalizePaths()
      state = task.CreateTrainState(jax.random.PRNGKey(0))
      tp = program_lib.TrainProgram.Params().Set(
          task=mp.task, logdir=str(tmp_path / str(on_device)),
          steps_per_loop=6, on_device_loop=on_device)
      prog = program_lib.TrainProgram(
          tp, task=task, input_generator=mp.input.Instantiate())
      state, result = prog.Run(state)
      state, result = prog.Run(state)
      return state, result

    s1, r1 = run(False)
    s2, r2 = run(True)
    np.testing.assert_allclose(r1["loss"], r2["loss"], rtol=1e-4)
    assert int(jax.device_get(s2.step)) == 12
    for a, b in zip(jax.tree_util.tree_leaves(s1.theta),
                    jax.tree_util.tree_leaves(s2.theta)):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)
