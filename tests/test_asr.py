"""ASR stack tests: frontend, SpecAugment, conformer, CTC task, WER."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import conformer_layer, py_utils, spectrum_augmenter
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.asr import decoder_metrics as dm
from lingvo_tpu.models.asr import frontend as frontend_lib

KEY = jax.random.PRNGKey(9)


class TestFrontend:

  def test_logmel_shapes(self):
    p = frontend_lib.MelAsrFrontend.Params().Set(num_bins=40)
    fe = p.Instantiate()
    wav = jax.random.normal(KEY, (2, 16000))  # 1s at 16kHz
    feats, fpad = fe.FProp(NestedMap(), wav)
    assert feats.shape[0] == 2 and feats.shape[2] == 40
    assert feats.shape[1] == fpad.shape[1]
    assert np.all(np.isfinite(np.asarray(feats)))

  def test_pure_tone_peaks_at_expected_bin(self):
    p = frontend_lib.MelAsrFrontend.Params().Set(num_bins=40)
    fe = p.Instantiate()
    t = np.arange(16000) / 16000.0
    low = np.sin(2 * np.pi * 300 * t)[None].astype("float32")
    high = np.sin(2 * np.pi * 4000 * t)[None].astype("float32")
    f_low, _ = fe.FProp(NestedMap(), jnp.asarray(low))
    f_high, _ = fe.FProp(NestedMap(), jnp.asarray(high))
    assert int(np.argmax(np.asarray(f_low).mean(1))) < int(
        np.argmax(np.asarray(f_high).mean(1)))


class TestSpecAugment:

  def test_identity_in_eval(self):
    sa = spectrum_augmenter.SpectrumAugmenter.Params().Instantiate()
    x = jax.random.normal(KEY, (2, 20, 16))
    np.testing.assert_array_equal(sa.FProp(NestedMap(), x), x)  # no seed ctx

  def test_masks_in_train(self):
    sa = spectrum_augmenter.SpectrumAugmenter.Params().Set(
        freq_mask_max_bins=4, time_mask_max_frames=6).Instantiate()
    x = jnp.ones((2, 40, 16))
    with py_utils.StepSeedContext(jax.random.PRNGKey(0)):
      out = np.asarray(sa.FProp(NestedMap(), x))
    assert (out == 0).any()
    assert (out == 1).any()
    # deterministic per step seed
    with py_utils.StepSeedContext(jax.random.PRNGKey(0)):
      out2 = np.asarray(sa.FProp(NestedMap(), x))
    np.testing.assert_array_equal(out, out2)


class TestConformer:

  def test_block_shapes_and_padding(self):
    p = conformer_layer.ConformerLayer.Params().Set(
        name="conf", input_dim=16, atten_num_heads=2, kernel_size=8)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (2, 12, 16))
    paddings = py_utils.PaddingsFromLengths(jnp.array([12, 6]), 12)
    with py_utils.ForwardStateContext():
      out = layer.FProp(theta, x, paddings)
    assert out.shape == x.shape
    np.testing.assert_allclose(np.asarray(out[1, 6:]), 0.0, atol=1e-6)

  def test_causal_variant_no_future_leak(self):
    p = conformer_layer.ConformerLayer.Params().Set(
        name="conf", input_dim=16, atten_num_heads=2, kernel_size=4,
        causal=True)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (1, 10, 16))
    with py_utils.ForwardStateContext():
      out1 = layer.FProp(theta, x)
      out2 = layer.FProp(theta, x.at[:, 6:].set(9.0))
    np.testing.assert_allclose(np.asarray(out1[:, :6]),
                               np.asarray(out2[:, :6]), atol=1e-4)

  def test_lconv_depthwise(self):
    p = conformer_layer.LConvLayer.Params().Set(
        name="lconv", input_dim=8, kernel_size=4)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    with py_utils.ForwardStateContext():
      out = layer.FProp(theta, jax.random.normal(KEY, (2, 10, 8)))
    assert out.shape == (2, 10, 8)


class TestWer:

  def test_levenshtein(self):
    assert dm.LevenshteinDistance([1, 2, 3], [1, 2, 3]) == 0
    assert dm.LevenshteinDistance([1, 2, 3], [1, 3]) == 1
    assert dm.LevenshteinDistance([], [1, 2]) == 2
    assert dm.LevenshteinDistance([1, 2], [2, 1]) == 2

  def test_wer_metric(self):
    m = dm.WerMetric()
    m.Update([1, 2, 3, 4], [1, 2, 3, 4])
    m.Update([1, 2], [1, 5])  # 1 error / 2 ref tokens
    assert m.value == pytest.approx(1 / 6)


class TestCtcTask:

  def test_fprop_loss_and_decode(self):
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams(
        "asr.librispeech.LibrispeechConformerCtcTiny", "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    step = jax.jit(task.TrainStep)
    first = None
    for _ in range(60):
      state, out = step(state, batch)
      if first is None:
        first = float(out.metrics.loss[0])
    assert float(out.metrics.loss[0]) < 0.7 * first
    # decode pipeline produces a finite WER
    dec = jax.jit(task.Decode)(state.theta, batch)
    metrics = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(
        jax.tree_util.tree_map(np.asarray, dec), metrics)
    results = task.DecodeFinalize(metrics)
    assert 0.0 <= results["wer"] <= 2.0


class TestRnnt:

  def test_loss_matches_bruteforce_dp(self):
    from lingvo_tpu.models.asr import rnnt
    rng = np.random.RandomState(0)
    B, T, U, V = 3, 5, 4, 6
    logits = rng.randn(B, T, U + 1, V).astype(np.float32)
    labels = rng.randint(1, V, (B, U)).astype(np.int32)
    t_lens = np.array([5, 4, 3], np.int32)
    u_lens = np.array([4, 2, 3], np.int32)

    def brute(lgt, lab, t_len, u_len):
      lp = np.asarray(jax.nn.log_softmax(jnp.asarray(lgt), -1))
      NEG = -1e30
      alpha = np.full((t_len, u_len + 1), NEG)
      alpha[0, 0] = 0.0

      def la(a, b):
        m = max(a, b)
        return NEG if m <= NEG / 2 else m + np.log(
            np.exp(a - m) + np.exp(b - m))

      for t in range(t_len):
        for u in range(u_len + 1):
          if t == 0 and u == 0:
            continue
          v1 = alpha[t - 1, u] + lp[t - 1, u, 0] if t > 0 else NEG
          v2 = (alpha[t, u - 1] + lp[t, u - 1, lab[u - 1]]
                if u > 0 else NEG)
          alpha[t, u] = la(v1, v2)
      return -(alpha[t_len - 1, u_len] + lp[t_len - 1, u_len, 0])

    expect = np.array([brute(logits[i], labels[i], t_lens[i], u_lens[i])
                       for i in range(B)])
    got = np.asarray(rnnt.RnntLoss(
        jnp.asarray(logits), jnp.asarray(labels), jnp.asarray(t_lens),
        jnp.asarray(u_lens)))
    np.testing.assert_allclose(got, expect, atol=1e-4)

  def test_rnnt_trains_and_decodes(self):
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("asr.librispeech.LibrispeechRnntTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(15):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    dec = jax.jit(task.Decode)(state.theta, batch)
    assert dec.hyp_ids.shape[0] == 4
    m = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(jax.tree_util.tree_map(np.asarray, dec), m)
    res = task.DecodeFinalize(m)
    assert "wer" in res and res["num_utterances"] == 4.0


class TestAsrRealDataLoop:

  def test_wav_to_features_to_ctc_step(self, tmp_path):
    """tools/create_asr_features.py output feeds AsrRecordInput feeds the
    CTC task — the full real-data ASR loop."""
    import subprocess
    import sys
    import wave
    lines = []
    for i in range(6):
      wav = str(tmp_path / f"{i}.wav")
      with wave.open(wav, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(16000)
        t = np.arange(8000 + 2000 * i) / 16000.0
        pcm = (0.3 * np.sin(2 * np.pi * (300 + 60 * i) * t)
               * 32767).astype(np.int16)
        w.writeframes(pcm.tobytes())
      lines.append(f"{wav}\thello world {i}")
    manifest = tmp_path / "m.tsv"
    manifest.write_text("\n".join(lines))
    out = str(tmp_path / "shard.rio")
    import os
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env["JAX_PLATFORMS"] = "cpu"
    tool = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                        "tools", "create_asr_features.py")
    subprocess.run([sys.executable, tool, "--manifest", str(manifest),
                    "--output", out], check=True, env=env)

    from lingvo_tpu.models.asr import input_generator
    from lingvo_tpu.core import tokenizers
    p = input_generator.AsrRecordInput.Params().Set(
        file_pattern=f"recordio:{out}",
        tokenizer=tokenizers.AsciiTokenizer.Params(),
        bucket_upper_bound=[60, 120], bucket_batch_limit=[4, 2],
        num_reader_threads=1, shuffle=False, max_epochs=1)
    gen = p.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.features.shape[-1] == 80
    assert batch.tgt.ids.shape[0] == batch.features.shape[0]

    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams(
        "asr.librispeech.LibrispeechConformerCtcTiny", "Train")
    mp.task.input = mp.input
    mp.task.encoder.input_dim = 80
    mp.task.vocab_size = 80
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    state, outm = jax.jit(task.TrainStep)(state, batch.Transform(jnp.asarray))
    assert np.isfinite(float(outm.metrics.loss[0]))
    gen.Reset()
