"""Tests for core layers: shapes, numerics, dropout determinism, BN state.

Coverage model follows the reference's layers_test.py / bn_layers_test.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import layers, py_utils
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(1234)


def _init(p):
  layer = p.Instantiate()
  return layer, layer.InstantiateVariables(KEY)


class TestProjection:

  def test_shapes_and_activation(self):
    p = layers.ProjectionLayer.Params().Set(
        name="proj", input_dim=6, output_dim=3, activation="RELU")
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (4, 5, 6))
    out = layer.FProp(theta, x)
    assert out.shape == (4, 5, 3)
    assert float(out.min()) >= 0.0  # relu

  def test_padding_zeroes_output(self):
    p = layers.ProjectionLayer.Params().Set(
        name="proj", input_dim=4, output_dim=4, bias_init=5.0)
    layer, theta = _init(p)
    x = jnp.ones((2, 3, 4))
    paddings = jnp.array([[0.0, 0.0, 1.0], [0.0, 1.0, 1.0]])
    out = layer.FProp(theta, x, paddings)
    np.testing.assert_allclose(out[0, 2], 0.0)
    np.testing.assert_allclose(out[1, 1:], 0.0)
    assert abs(float(out[0, 0, 0])) > 0

  def test_weight_norm(self):
    p = layers.ProjectionLayer.Params().Set(
        name="proj", input_dim=4, output_dim=4, weight_norm=True)
    layer, theta = _init(p)
    # at init g=0 => effective w has unit column norms
    w = theta.w
    eff = (1.0 + theta.g) / jnp.linalg.norm(w, axis=0) * w
    np.testing.assert_allclose(jnp.linalg.norm(eff, axis=0), 1.0, rtol=1e-5)
    out = layer.FProp(theta, jnp.ones((2, 4)))
    assert out.shape == (2, 4)

  def test_feedforward_net(self):
    p = layers.FeedForwardNet.Params().Set(
        name="ffn", input_dim=8, hidden_layer_dims=[16, 4],
        activation=["RELU", "NONE"])
    layer, theta = _init(p)
    out = layer.FProp(theta, jnp.ones((2, 8)))
    assert out.shape == (2, 4)


class TestDropout:

  def test_eval_identity(self):
    p = layers.DeterministicDropoutLayer.Params().Set(keep_prob=0.5)
    layer, theta = _init(p)
    x = jnp.ones((4, 4))
    # no step-seed context -> identity
    np.testing.assert_array_equal(layer.FProp(theta, x), x)

  def test_train_deterministic(self):
    p = layers.DeterministicDropoutLayer.Params().Set(
        name="drop", keep_prob=0.5)
    layer, theta = _init(p)
    x = jnp.ones((1000,))

    def run(seed):
      with py_utils.StepSeedContext(jax.random.PRNGKey(seed)):
        return layer.FProp(theta, x)

    a, b, c = run(1), run(1), run(2)
    np.testing.assert_array_equal(a, b)  # same step seed -> same mask
    assert not np.array_equal(a, c)
    # unbiased scaling: mean stays ~1
    assert abs(float(a.mean()) - 1.0) < 0.1
    # dropped values are exactly 0, kept are 2.0
    assert set(np.unique(np.asarray(a))) <= {0.0, 2.0}

  def test_sibling_dropout_masks_differ(self):
    # Regression: two FFNs must not share dropout masks (path-derived seeds).
    from lingvo_tpu.core import base_layer

    class TwoFFN(base_layer.BaseLayer):

      def __init__(self, params):
        super().__init__(params)
        fp = layers.FeedForwardNet.Params().Set(
            input_dim=32, hidden_layer_dims=[32], dropout_prob=0.5)
        self.CreateChild("f1", fp.Copy())
        self.CreateChild("f2", fp.Copy())

      def FProp(self, theta, x):
        return self.f1.FProp(theta.f1, x), self.f2.FProp(theta.f2, x)

    layer = TwoFFN.Params().Set(name="m").Instantiate()
    theta = layer.InstantiateVariables(KEY)
    # same weights for both FFNs so differences come only from masks
    theta.f2 = theta.f1
    with py_utils.StepSeedContext(jax.random.PRNGKey(0)):
      o1, o2 = layer.FProp(theta, jnp.ones((8, 32)))
    assert not np.array_equal(np.asarray(o1), np.asarray(o2))

  def test_eval_context_disables(self):
    p = layers.DeterministicDropoutLayer.Params().Set(name="d", keep_prob=0.5)
    layer, theta = _init(p)
    x = jnp.ones((10,))
    with py_utils.StepSeedContext(jax.random.PRNGKey(0)):
      with py_utils.EvalContext():
        np.testing.assert_array_equal(layer.FProp(theta, x), x)


class TestNorms:

  def test_layernorm_normalizes(self):
    p = layers.LayerNorm.Params().Set(name="ln", input_dim=16)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (4, 16)) * 5 + 3
    out = layer.FProp(theta, x)
    np.testing.assert_allclose(np.mean(np.asarray(out), -1), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.std(np.asarray(out), -1), 1.0, atol=1e-2)

  def test_rmsnorm(self):
    p = layers.RmsNorm.Params().Set(name="rms", input_dim=8)
    layer, theta = _init(p)
    out = layer.FProp(theta, jax.random.normal(KEY, (2, 8)) * 10)
    rms = np.sqrt(np.mean(np.square(np.asarray(out)), -1))
    np.testing.assert_allclose(rms, 1.0, rtol=1e-3)

  def test_batchnorm_train_vs_eval(self):
    p = layers.BatchNormLayer.Params().Set(name="bn", dim=4, decay=0.5)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (32, 4)) * 3 + 7
    with py_utils.ForwardStateContext() as updates:
      out = layer.FProp(theta, x)
    # train mode: output normalized by batch stats
    np.testing.assert_allclose(np.mean(np.asarray(out), 0), 0.0, atol=1e-4)
    # moving stats updated functionally under the layer's unique path
    assert "bn/moving_mean" in updates
    mm = updates["bn/moving_mean"]
    np.testing.assert_allclose(
        mm, 0.5 * np.zeros(4) + 0.5 * np.mean(np.asarray(x), 0), rtol=1e-5)
    # eval mode uses (stale) moving stats -> different output
    with py_utils.EvalContext():
      out_eval = layer.FProp(theta, x)
    assert not np.allclose(out, out_eval)

  def test_batchnorm_respects_paddings(self):
    p = layers.BatchNormLayer.Params().Set(name="bn", dim=2)
    layer, theta = _init(p)
    x = jnp.stack([jnp.ones((4, 2)), 100 * jnp.ones((4, 2))], axis=0)
    paddings = jnp.array([[0.0] * 4, [1.0] * 4])  # 2nd seq fully padded
    with py_utils.ForwardStateContext() as updates:
      layer.FProp(theta, x, paddings)
    # mean must come only from the unpadded sequence (all ones)
    np.testing.assert_allclose(
        updates["bn/moving_mean"], (1 - p.decay) * 1.0, rtol=1e-4)

  def test_batchnorm_rank4_padded_count(self):
    # Regression: count must cover all reduced dims, not just masked ones.
    p = layers.BatchNormLayer.Params().Set(name="bn", dim=2, decay=0.0)
    layer, theta = _init(p)
    x = 5.0 * jnp.ones((2, 4, 3, 2))  # [b, t, w, c]
    paddings = jnp.zeros((2, 4))
    with py_utils.ForwardStateContext() as updates:
      layer.FProp(theta, x, paddings)
    np.testing.assert_allclose(updates["bn/moving_mean"], 5.0, rtol=1e-5)

  def test_sibling_bn_updates_do_not_collide(self):
    from lingvo_tpu.core import base_layer

    class TwoConv(base_layer.BaseLayer):

      def __init__(self, params):
        super().__init__(params)
        cp = layers.Conv2DLayer.Params().Set(filter_shape=(3, 3, 2, 2))
        self.CreateChild("c1", cp.Copy())
        self.CreateChild("c2", cp.Copy())

      def FProp(self, theta, x):
        return self.c2.FProp(theta.c2, self.c1.FProp(theta.c1, x))

    layer = TwoConv.Params().Set(name="m").Instantiate()
    theta = layer.InstantiateVariables(KEY)
    with py_utils.ForwardStateContext() as updates:
      layer.FProp(theta, jnp.ones((1, 4, 4, 2)))
    keys = sorted(updates)
    assert "m/c1/bn/moving_mean" in keys and "m/c2/bn/moving_mean" in keys
    # merge routes each update to its own theta slot
    new_theta = py_utils.ApplyForwardStateUpdates(theta, updates, layer)
    assert not np.allclose(new_theta.c1.bn.moving_variance,
                           theta.c1.bn.moving_variance)
    np.testing.assert_allclose(new_theta.c1.bn.moving_mean,
                               updates["m/c1/bn/moving_mean"])

  def test_groupnorm(self):
    p = layers.GroupNormLayer.Params().Set(name="gn", dim=8, num_groups=2)
    layer, theta = _init(p)
    out = layer.FProp(theta, jax.random.normal(KEY, (2, 5, 8)))
    assert out.shape == (2, 5, 8)


class TestConv:

  def test_conv2d_shapes(self):
    p = layers.Conv2DLayer.Params().Set(
        name="conv", filter_shape=(3, 3, 1, 8), filter_stride=(2, 2),
        batch_norm=False, has_bias=True)
    layer, theta = _init(p)
    out = layer.FProp(theta, jnp.ones((2, 28, 28, 1)))
    assert out.shape == (2, 14, 14, 8)

  def test_conv2d_with_paddings(self):
    p = layers.Conv2DLayer.Params().Set(
        name="conv", filter_shape=(3, 3, 4, 8), filter_stride=(2, 1),
        batch_norm=False)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (2, 10, 6, 4))
    paddings = py_utils.PaddingsFromLengths(jnp.array([10, 4]), 10)
    out, out_pad = layer.FProp(theta, x, paddings)
    assert out.shape == (2, 5, 6, 8)
    assert out_pad.shape == (2, 5)
    np.testing.assert_allclose(out[1, 3:], 0.0)  # padded region zeroed

  def test_conv2d_valid_padding_with_paddings(self):
    # Regression: VALID conv output is shorter than ceil(t/stride).
    p = layers.Conv2DLayer.Params().Set(
        name="conv", filter_shape=(3, 3, 2, 4), filter_stride=(2, 1),
        padding="VALID", batch_norm=False)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (2, 10, 6, 2))
    paddings = py_utils.PaddingsFromLengths(jnp.array([10, 6]), 10)
    out, out_pad = layer.FProp(theta, x, paddings)
    assert out.shape[1] == out_pad.shape[1] == 4

  def test_causal_conv_no_future_leak(self):
    p = layers.Conv2DLayer.Params().Set(
        name="conv", filter_shape=(3, 1, 2, 2), causal_convolution=True,
        batch_norm=False)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (1, 8, 1, 2))
    out1 = layer.FProp(theta, x)
    x2 = x.at[:, 5:].set(99.0)  # perturb the future
    out2 = layer.FProp(theta, x2)
    np.testing.assert_allclose(out1[:, :5], out2[:, :5], rtol=1e-5)

  def test_depthwise_causal_no_future_leak(self):
    # Regression: depthwise causal conv must left-pad like the base class.
    p = layers.DepthwiseConv2DLayer.Params().Set(
        name="dw", filter_shape=(3, 1, 2, 1), causal_convolution=True,
        batch_norm=False)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (1, 8, 1, 2))
    out1 = layer.FProp(theta, x)
    out2 = layer.FProp(theta, x.at[:, 5:].set(99.0))
    np.testing.assert_allclose(out1[:, :5], out2[:, :5], rtol=1e-5)

  def test_maxpool_padded_frames_lose(self):
    # Regression: zeroed padded frames must not beat negative activations.
    p = layers.MaxPoolLayer.Params().Set(
        name="mp", window_shape=(2, 1), window_stride=(2, 1))
    layer, theta = _init(p)
    x = -jnp.ones((1, 4, 1, 1))
    paddings = jnp.array([[0.0, 0.0, 0.0, 1.0]])
    out, out_pad = layer.FProp(theta, x, paddings)
    # window [t2, t3]: t3 is padded; max of valid = -1, then re-zeroed by
    # output paddings only if the output frame itself is padded (it isn't).
    assert float(out[0, 1, 0, 0]) == -1.0

  def test_depthwise(self):
    p = layers.DepthwiseConv2DLayer.Params().Set(
        name="dw", filter_shape=(3, 1, 4, 2), batch_norm=False)
    layer, theta = _init(p)
    out = layer.FProp(theta, jnp.ones((2, 6, 1, 4)))
    assert out.shape == (2, 6, 1, 8)

  def test_maxpool(self):
    p = layers.MaxPoolLayer.Params().Set(name="mp")
    layer, theta = _init(p)
    out = layer.FProp(theta, jnp.ones((2, 8, 8, 3)))
    assert out.shape == (2, 4, 4, 3)


class TestEmbeddingSoftmax:

  def test_embedding_gather_vs_matmul(self):
    pg = layers.SimpleEmbeddingLayer.Params().Set(
        name="emb", vocab_size=11, embedding_dim=6)
    pm = pg.Copy().Set(use_matmul=True)
    lg, tg = _init(pg)
    lm = pm.Instantiate()
    ids = jnp.array([[1, 2], [10, 0]])
    np.testing.assert_allclose(
        lg.EmbLookup(tg, ids), lm.EmbLookup(tg, ids), rtol=1e-5)

  def test_positional_embedding(self):
    p = layers.PositionalEmbeddingLayer.Params().Set(embedding_dim=8)
    layer, theta = _init(p)
    out = layer.FProp(theta, seq_length=5)
    assert out.shape == (5, 8)
    np.testing.assert_allclose(out[0, :4], 0.0, atol=1e-6)  # sin(0)=0
    np.testing.assert_allclose(out[0, 4:], 1.0, atol=1e-6)  # cos(0)=1

  def test_rotary_preserves_norm_and_relative(self):
    p = layers.RotaryPositionalEmbeddingLayer.Params().Set(embedding_dim=8)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (2, 6, 2, 8))
    out = layer.FProp(theta, x)
    np.testing.assert_allclose(
        jnp.linalg.norm(out, axis=-1), jnp.linalg.norm(x, axis=-1), rtol=1e-4)
    # relative property: <R(q,i), R(k,j)> depends only on i-j
    q = jax.random.normal(KEY, (1, 10, 1, 8))
    k = jax.random.normal(jax.random.PRNGKey(5), (1, 10, 1, 8))
    rq, rk = layer.FProp(theta, q), layer.FProp(theta, k)
    dot_03 = float(jnp.sum(rq[0, 0, 0] * rk[0, 3, 0]))
    q2 = jnp.roll(q, 2, axis=1)
    k2 = jnp.roll(k, 2, axis=1)
    rq2, rk2 = layer.FProp(theta, q2), layer.FProp(theta, k2)
    dot_25 = float(jnp.sum(rq2[0, 2, 0] * rk2[0, 5, 0]))
    assert abs(dot_03 - dot_25) < 1e-3

  def test_rotary_partial_rotation(self):
    p = layers.RotaryPositionalEmbeddingLayer.Params().Set(embedding_dim=4)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (1, 6, 2, 8))
    out = layer.FProp(theta, x)
    assert out.shape == x.shape
    # unrotated tail passes through untouched
    np.testing.assert_array_equal(out[..., 4:], x[..., 4:])
    assert not np.allclose(out[0, 1:, :, :4], x[0, 1:, :, :4])

  def test_softmax_xent(self):
    p = layers.SimpleFullSoftmax.Params().Set(
        name="sm", input_dim=8, num_classes=5)
    layer, theta = _init(p)
    x = jax.random.normal(KEY, (4, 8))
    ids = jnp.array([0, 1, 2, 3])
    out = layer.FProp(theta, x, class_ids=ids)
    assert out.logits.shape == (4, 5)
    assert out.per_example_xent.shape == (4,)
    # xent >= 0 and matches manual computation
    manual = -np.take_along_axis(
        np.asarray(out.log_probs), np.asarray(ids)[:, None], 1)[:, 0]
    np.testing.assert_allclose(out.per_example_xent, manual, rtol=1e-5)

  def test_label_smoothing_increases_xent_on_confident(self):
    p = layers.SimpleFullSoftmax.Params().Set(
        name="sm", input_dim=4, num_classes=4)
    layer, theta = _init(p)
    x = jnp.ones((2, 4))
    ids = jnp.array([1, 2])
    plain = layer.FProp(theta, x, class_ids=ids)
    smooth = layer.FProp(theta, x, class_ids=ids, label_smoothing=0.1)
    assert smooth.per_example_xent.shape == plain.per_example_xent.shape

  def test_shared_embedding_softmax(self):
    p = layers.SharedEmbeddingSoftmaxLayer.Params().Set(
        name="shared", vocab_size=12, embedding_dim=6)
    layer, theta = _init(p)
    ids = jnp.array([[0, 3]])
    emb = layer.EmbLookup(theta, ids)
    assert emb.shape == (1, 2, 6)
    out = layer.FProp(theta, emb, class_ids=ids)
    assert out.logits.shape == (1, 2, 12)

  def test_bf16_fprop_dtype(self):
    p = layers.SimpleFullSoftmax.Params().Set(
        name="sm", input_dim=8, num_classes=5, fprop_dtype=jnp.bfloat16)
    layer, theta = _init(p)
    out = layer.FProp(theta, jnp.ones((2, 8)), class_ids=jnp.array([0, 1]))
    assert out.logits.dtype == jnp.bfloat16
    assert out.per_example_xent.dtype == jnp.float32  # xent always f32
