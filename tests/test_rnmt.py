"""RNMT+ MT model tests."""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401


class TestRnmt:

  def _setup(self):
    mp = model_registry.GetParams("mt.wmt14_en_de.WmtEnDeRNMTPlusTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    return task, state, batch

  def test_trains(self):
    task, state, batch = self._setup()
    step = jax.jit(task.TrainStep, donate_argnums=(0,))
    losses = []
    for _ in range(8):
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

  def test_greedy_decode_and_bleu(self):
    task, state, batch = self._setup()
    out = jax.jit(task.Decode)(state.theta, batch)
    assert out.topk_ids.shape[1] == 1       # single greedy hyp
    metrics = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(out, metrics)
    res = task.DecodeFinalize(metrics)
    assert "corpus_bleu" in res

  def test_decode_stops_at_eos(self):
    task, state, batch = self._setup()
    out = task.Decode(state.theta, batch)
    ids = np.asarray(out.topk_ids)[:, 0, :]
    lens = np.asarray(out.topk_lens)[:, 0]
    eos = task.dec.p.eos_id
    for i in range(ids.shape[0]):
      # after the first eos, everything is eos (done rows freeze)
      where = np.where(ids[i] == eos)[0]
      if len(where):
        assert np.all(ids[i, where[0]:] == eos)
        assert lens[i] <= where[0] + 1
