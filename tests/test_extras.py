"""AdaGraft optimizer + entmax/Sinkhorn/reversible layers (ref lingvo/core
long tail: adagraft.py, entmax.py, differentiable_assignment.py,
reversible_layers.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import extras
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(31)


class TestEntmax:

  def test_simplex_and_sparsity(self):
    x = jnp.asarray([[2.0, 1.0, 0.1, -2.0, -3.0]])
    p = extras.Entmax15(x)
    np.testing.assert_allclose(float(p.sum()), 1.0, atol=1e-5)
    assert float(p[0, -1]) == 0.0  # sparse tail, unlike softmax
    assert float(p[0, 0]) > float(p[0, 1])  # order preserved

  def test_uniform_input_uniform_output(self):
    p = extras.Entmax15(jnp.zeros((1, 6)))
    np.testing.assert_allclose(np.asarray(p), 1.0 / 6, atol=1e-5)

  def test_differentiable_with_sparse_output(self):
    # regression: sqrt(0) off-support used to NaN the whole gradient for
    # any input whose entmax output is actually sparse
    x = jnp.asarray([[2.0, 1.0, 0.1, -2.0, -3.0]])
    assert float(extras.Entmax15(x)[0, -1]) == 0.0  # sparse indeed
    g = jax.grad(lambda x: extras.Entmax15(x)[0, 0])(x)
    assert np.all(np.isfinite(np.asarray(g))), np.asarray(g)
    g2 = jax.grad(lambda x: extras.Entmax15(x)[0, 0])(
        jnp.asarray([[1.0, 0.5, 0.0]]))
    assert np.all(np.isfinite(np.asarray(g2)))


class TestSinkhorn:

  def test_doubly_stochastic(self):
    s = jax.random.normal(KEY, (5, 5))
    a = extras.SinkhornAssignment(s, num_iters=60)
    np.testing.assert_allclose(np.asarray(a.sum(-1)), 1.0, atol=1e-3)
    np.testing.assert_allclose(np.asarray(a.sum(-2)), 1.0, atol=1e-3)

  def test_low_temperature_approaches_permutation(self):
    s = jnp.asarray([[10.0, 0.0], [0.0, 10.0]])
    a = extras.SinkhornAssignment(s, num_iters=50, temperature=0.1)
    np.testing.assert_allclose(np.asarray(a), np.eye(2), atol=1e-3)


class TestReversible:

  def _layer(self):
    fp = layers_lib.ProjectionLayer.Params().Set(
        name="f", input_dim=8, output_dim=8, activation="TANH")
    gp = layers_lib.ProjectionLayer.Params().Set(
        name="g", input_dim=8, output_dim=8, activation="TANH")
    rp = extras.ReversibleLayer.Params().Set(name="rev", f=fp, g=gp)
    layer = rp.Instantiate()
    layer.FinalizePaths()
    return layer, layer.InstantiateVariables(KEY)

  def test_exact_inverse(self):
    layer, theta = self._layer()
    x1 = jax.random.normal(KEY, (2, 8))
    x2 = jax.random.normal(jax.random.PRNGKey(2), (2, 8))
    y1, y2 = layer.FProp(theta, x1, x2)
    rx1, rx2 = layer.Reverse(theta, y1, y2)
    np.testing.assert_allclose(np.asarray(rx1), np.asarray(x1), atol=1e-5)
    np.testing.assert_allclose(np.asarray(rx2), np.asarray(x2), atol=1e-5)

  def test_gradients_match_plain_residual(self):
    layer, theta = self._layer()
    x1 = jax.random.normal(KEY, (2, 8))
    x2 = jax.random.normal(jax.random.PRNGKey(2), (2, 8))

    def loss_rev(theta, x1, x2):
      y1, y2 = layer.FProp(theta, x1, x2)
      return jnp.sum(y1 ** 2) + jnp.sum(y2 ** 2)

    def loss_ref(theta, x1, x2):
      y1 = x1 + layer.f.FProp(theta.f, x2)
      y2 = x2 + layer.g.FProp(theta.g, y1)
      return jnp.sum(y1 ** 2) + jnp.sum(y2 ** 2)

    g1 = jax.grad(loss_rev, argnums=(0, 1, 2))(theta, x1, x2)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(theta, x1, x2)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)

  def test_jittable(self):
    layer, theta = self._layer()
    x1 = jax.random.normal(KEY, (2, 8))
    y1, y2 = jax.jit(layer.FProp)(theta, x1, x1)
    assert np.all(np.isfinite(np.asarray(y1)))


class TestAdaGraft:

  def test_magnitude_from_one_direction_from_other(self):
    p = opt_lib.AdaGraft.Params().Set(
        magnitude_optimizer=opt_lib.SGD.Params(),
        direction_optimizer=opt_lib.Adam.Params())
    opt = p.Instantiate()
    opt.FinalizePaths()
    params = NestedMap(w=jnp.ones((4, 4)))
    state = opt.InitState(params)
    grads = NestedMap(w=jnp.full((4, 4), 0.5))
    new_params, state = jax.jit(opt.Update)(state, grads, params, 0.1, 0)
    delta = np.asarray(new_params.w - params.w)
    # magnitude == SGD step norm (lr * |g|)
    sgd_delta = -0.1 * np.full((4, 4), 0.5)
    np.testing.assert_allclose(np.linalg.norm(delta),
                               np.linalg.norm(sgd_delta), rtol=1e-5)

  def test_trains(self):
    p = opt_lib.AdaGraft.Params().Set(
        magnitude_optimizer=opt_lib.SGD.Params(),
        direction_optimizer=opt_lib.Adam.Params())
    opt = p.Instantiate()
    opt.FinalizePaths()
    params = NestedMap(w=jnp.ones((6, 3)))
    target = jax.random.normal(KEY, (6, 3))
    state = opt.InitState(params)
    update = jax.jit(opt.Update)
    for step in range(200):
      g = NestedMap(w=(params.w - target))
      params, state = update(state, g, params, 0.05, step)
    assert float(jnp.sum((params.w - target) ** 2)) < 0.05
