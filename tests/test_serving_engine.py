"""Continuous-batching serving engine: scheduler + KV block tables over
ragged paged decode.

Covers docs/serving_engine.md:
- the block-table paged decode kernel's XLA twin matches a dense softmax
  reference across ragged lengths (0, mid-page, capacity boundary) and is
  bit-identical to the Pallas kernel in interpret mode — including after
  pages are freed and reallocated to a different sequence,
- `BlockPrefill` matches the dense reference at arbitrary (q_pos, in_len)
  and returns exactly 0 for invalid queries,
- `PagedStep` chunked-prefill + decode reproduces the dense
  Prefill/ExtendStep logits on a left-aligned row,
- the page allocator packs low (min-heap), is all-or-nothing, idempotent
  on Free, and tracks peak occupancy,
- the scheduler's admit/prefill/decode/retire lifecycle (driven with
  fabricated sample arrays, no device), cancellation at both lifecycle
  stages, and graceful queueing on pool exhaustion,
- `ServingLoop.RunBatch` is token-identical to per-row dense greedy decode
  AND to batch-synchronous `GShardDecode.DecodeOnce`, with pages fully
  reclaimed after the batch drains,
- the async Submit/stream/Cancel front door, ineligible-config dense
  fallback visibility (`paged_path`, `dense_fallback_steps`), GShardDecode
  per-call telemetry, and a deterministic mixed-length soak (slow).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.ops import block_decode
from lingvo_tpu.serving import engine as engine_lib
from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import scheduler as scheduler_lib


# -- shared tiny LM (session-scoped `tiny_lm` fixture: conftest.py) ----------

from tests.conftest import TinyLmParams as _TinyLmParams  # noqa: E402


# one jitted ExtendStep per task and one memoized rollout per prompt: the
# whole file shares a single compiled reference program (fixed 32-slot
# cache; unwritten tail slots are position-masked, so length is free)
_REF_TOKENS = {}
_REF_EXT = {}
_REF_CACHE_LEN = 32


def _GreedyRef(task, theta, prompt, max_new):
  """Per-row dense greedy rollout (per-token ExtendStep argmax): the
  batch-free reference every engine output must match token-for-token."""
  key = (id(task), id(theta), tuple(int(t) for t in prompt), max_new)
  if key in _REF_TOKENS:
    return _REF_TOKENS[key]
  ext = _REF_EXT.get(id(task))
  if ext is None:
    ext = jax.jit(
        lambda th, ids_t, st: task.ExtendStep(th, ids_t, st))
    _REF_EXT[id(task)] = ext
  assert len(prompt) + max_new <= _REF_CACHE_LEN
  states = task.InitDecodeState(theta, 1, _REF_CACHE_LEN)
  logits = None
  for t in prompt:
    logits, states = ext(theta, jnp.asarray([[t]], jnp.int32), states)
  out = []
  for _ in range(max_new):
    nxt = int(np.argmax(np.asarray(logits[0])))
    out.append(nxt)
    logits, states = ext(theta, jnp.asarray([[nxt]], jnp.int32), states)
  _REF_TOKENS[key] = out
  return out


# -- kernel twins ------------------------------------------------------------


class TestBlockDecodeKernel:

  def _Inputs(self, b=4, t_pages=4, page=4, n=2, h=8, seed=0,
              extra_pages=1):
    rng = np.random.RandomState(seed)
    np_total = b * t_pages + extra_pages
    q = rng.randn(b, 1, n, h).astype(np.float32)
    k_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    v_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    # arbitrary disjoint physical pages per row — NOT identity, so a kernel
    # that ignores the table cannot pass
    tables = rng.permutation(np_total - extra_pages).reshape(
        b, t_pages).astype(np.int32)
    return q, k_pool, v_pool, tables

  @staticmethod
  def _DenseRef(q, k_pool, v_pool, tables, lens):
    """numpy masked softmax over the gathered dense view."""
    b, _, n, h = q.shape
    page = k_pool.shape[1]
    out = np.zeros_like(q)
    for i in range(b):
      ln = int(lens[i])
      if ln == 0:
        continue
      k = k_pool[tables[i]].reshape(-1, n, h)[:ln]        # [ln, N, H]
      v = v_pool[tables[i]].reshape(-1, n, h)[:ln]
      s = np.einsum("nh,snh->ns", q[i, 0], k)             # [N, ln]
      s = s - s.max(axis=-1, keepdims=True)
      p = np.exp(s)
      p /= p.sum(axis=-1, keepdims=True)
      out[i, 0] = np.einsum("ns,snh->nh", p, v)
    return out

  def test_xla_twin_matches_dense_reference(self):
    q, k_pool, v_pool, tables = self._Inputs()
    # 0 = inactive row, 3 = inside page 0, 9 = mid page 2, 16 = capacity
    lens = np.array([0, 3, 9, 16], np.int32)
    out = block_decode.BlockDecode(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(lens), page_size=4, lowering="xla")
    ref = self._DenseRef(q, k_pool, v_pool, tables, lens)
    np.testing.assert_allclose(np.asarray(out), ref, atol=5e-6)
    # the len-0 row is exactly zero, not NaN
    np.testing.assert_array_equal(np.asarray(out[0]), np.zeros_like(q[0]))

  def test_stale_table_entries_never_leak(self):
    """Entries past a row's live pages may point anywhere (freed/foreign
    pages); they must not change the output."""
    q, k_pool, v_pool, tables = self._Inputs()
    lens = np.array([3, 4, 5, 8], np.int32)   # nobody uses pages 2..3
    out1 = block_decode.BlockDecode(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(lens), page_size=4, lowering="xla")
    hostile = tables.copy()
    hostile[:, 2:] = np.arange(8).reshape(4, 2)   # alias other rows' pages
    out2 = block_decode.BlockDecode(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(hostile), jnp.asarray(lens), page_size=4, lowering="xla")
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))

  def test_twins_bitwise_equal_incl_page_reuse(self):
    """XLA == Pallas(interpret) bitwise, before AND after the allocator
    frees one sequence's pages and hands them to another (the pool bytes
    are overwritten in place — exactly what eviction + admission does)."""
    q, k_pool, v_pool, tables = self._Inputs(b=2, t_pages=2, page=8, n=1,
                                             h=8)
    k_pool = jnp.asarray(k_pool)
    v_pool = jnp.asarray(v_pool)
    lens = np.array([5, 16], np.int32)

    def _Both(kp, vp, tb, ln):
      out_x = block_decode.BlockDecode(
          jnp.asarray(q), kp, vp, jnp.asarray(tb), jnp.asarray(ln),
          page_size=8, lowering="xla")
      out_p = block_decode.BlockDecode(
          jnp.asarray(q), kp, vp, jnp.asarray(tb), jnp.asarray(ln),
          page_size=8, lowering="pallas", interpret=True)
      np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
      return np.asarray(out_x)

    _Both(k_pool, v_pool, tables, lens)

    # retire row 0 through a real allocator; its pages go to a new sequence
    alloc = kv_cache.PageAllocator(num_pages=4, page_size=8)
    alloc.Allocate("a", 2)
    alloc.Allocate("b", 2)
    assert sorted(alloc.PagesOf("a") + alloc.PagesOf("b")) == [0, 1, 2, 3]
    alloc.Free("a")
    reused = alloc.Allocate("c", 2)
    assert reused == [0, 1]   # min-heap: the freed low pages come back first
    rng = np.random.RandomState(7)
    for pg in reused:   # the new sequence overwrites the reused pages
      k_pool = k_pool.at[pg].set(rng.randn(8, 1, 8).astype(np.float32))
      v_pool = v_pool.at[pg].set(rng.randn(8, 1, 8).astype(np.float32))
    tables2 = np.array([reused, list(alloc.PagesOf("b"))], np.int32)
    out = _Both(k_pool, v_pool, tables2, np.array([12, 16], np.int32))
    ref = self._DenseRef(np.asarray(q), np.asarray(k_pool),
                         np.asarray(v_pool), tables2,
                         np.array([12, 16], np.int32))
    np.testing.assert_allclose(out, ref, atol=5e-6)

  @pytest.mark.slow
  def test_pallas_interpret_bitwise_sweep(self):
    """Twin equality across the length grid incl. 0 and capacity."""
    q, k_pool, v_pool, tables = self._Inputs(b=4, t_pages=2, page=8, n=1,
                                             h=8)
    for lens in ([0, 1, 8, 16], [16, 16, 16, 16], [0, 0, 0, 0],
                 [7, 9, 15, 3]):
      ln = np.asarray(lens, np.int32)
      out_x = block_decode.BlockDecode(
          jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
          jnp.asarray(tables), jnp.asarray(ln), page_size=8, lowering="xla")
      out_p = block_decode.BlockDecode(
          jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
          jnp.asarray(tables), jnp.asarray(ln), page_size=8,
          lowering="pallas", interpret=True)
      np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))

  def test_block_prefill_matches_dense_reference(self):
    b, c, n, h, page, t_pages = 3, 4, 2, 8, 4, 4
    rng = np.random.RandomState(3)
    np_total = b * t_pages + 1
    q = rng.randn(b, c, n, h).astype(np.float32)
    k_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    v_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    tables = rng.permutation(np_total - 1).reshape(b, t_pages).astype(
        np.int32)
    q_pos = np.array([0, 5, 9], np.int32)
    in_len = np.array([4, 3, 0], np.int32)   # row 2 is a dead row
    out = block_decode.BlockPrefill(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(q_pos), jnp.asarray(in_len),
        page_size=page)
    out = np.asarray(out)
    for i in range(b):
      k = k_pool[tables[i]].reshape(-1, n, h)
      v = v_pool[tables[i]].reshape(-1, n, h)
      for ci in range(c):
        if ci >= in_len[i]:   # invalid query: exactly zero
          np.testing.assert_array_equal(out[i, ci], np.zeros((n, h),
                                                             np.float32))
          continue
        end = int(q_pos[i]) + ci + 1     # attends slots <= q_pos + ci
        s = np.einsum("nh,snh->ns", q[i, ci], k[:end])
        s = s - s.max(axis=-1, keepdims=True)
        p = np.exp(s)
        p /= p.sum(axis=-1, keepdims=True)
        ref = np.einsum("ns,snh->nh", p, v[:end])
        np.testing.assert_allclose(out[i, ci], ref, atol=5e-6)


# -- PagedStep vs the dense decode path --------------------------------------


class TestPagedStepParity:

  def test_chunked_prefill_plus_decode_matches_dense(self, tiny_lm):
    """One left-aligned row through PagedStep (prefill chunks 4+2, then 3
    decode steps) reproduces dense Prefill/ExtendStep logits."""
    task, theta = tiny_lm
    prompt = [5, 9, 2, 33, 17, 4]
    page = 4
    paged_fn = jax.jit(task.PagedStep)
    dense_ext = jax.jit(lambda th, ids_t, st: task.ExtendStep(th, ids_t, st))
    tables = jnp.asarray([[0, 1, 2]], jnp.int32)      # capacity 12 slots
    states = task.InitPagedDecodeState(theta, 4, page)  # 3 pages + trash
    logits_paged = []
    pos = 0
    for chunk in ([5, 9, 2, 33], [17, 4]):
      ids = jnp.asarray([chunk + [0] * (4 - len(chunk))], jnp.int32)
      lg, states = paged_fn(theta, ids, states, tables,
                            jnp.asarray([pos], jnp.int32),
                            jnp.asarray([len(chunk)], jnp.int32))
      logits_paged.append(np.asarray(lg[0, :len(chunk)]))
      pos += len(chunk)
    paged_prompt_logits = np.concatenate(logits_paged, 0)   # [6, V]

    dense_states = task.InitDecodeState(theta, 1, len(prompt) + 3)
    dense_logits, dense_states = jax.jit(task.Prefill)(
        theta, jnp.asarray([prompt], jnp.int32), dense_states)
    np.testing.assert_allclose(paged_prompt_logits,
                               np.asarray(dense_logits[0]), atol=2e-5)

    nxt = int(np.argmax(paged_prompt_logits[-1]))
    for _ in range(3):
      lg, states = paged_fn(
          theta, jnp.asarray([[nxt]], jnp.int32), states, tables,
          jnp.asarray([pos], jnp.int32), jnp.asarray([1], jnp.int32))
      dl, dense_states = dense_ext(
          theta, jnp.asarray([[nxt]], jnp.int32), dense_states)
      np.testing.assert_allclose(np.asarray(lg[0, 0]), np.asarray(dl[0]),
                                 atol=2e-5)
      pos += 1
      nxt = int(np.argmax(np.asarray(lg[0, 0])))


# -- page allocator ----------------------------------------------------------


class TestPageAllocator:

  def test_packs_low_and_reuses_freed_pages_first(self):
    a = kv_cache.PageAllocator(num_pages=8, page_size=4)
    assert a.Allocate("x", 3) == [0, 1, 2]
    assert a.Allocate("y", 2) == [3, 4]
    a.Free("x")
    # freed low pages sink to the front of the heap: defrag by construction
    assert a.Allocate("z", 4) == [0, 1, 2, 5]
    assert a.num_free == 2 and a.num_in_use == 6

  def test_all_or_nothing_exhaustion(self):
    a = kv_cache.PageAllocator(num_pages=4, page_size=4)
    a.Allocate("x", 3)
    assert not a.CanAllocate(2)
    with pytest.raises(kv_cache.OutOfPages):
      a.Allocate("y", 2)
    # the failed call had no side effects
    assert a.num_free == 1 and "y" not in a._owned
    assert a.Allocate("y", 1) == [3]

  def test_free_is_idempotent_and_peak_tracks(self):
    a = kv_cache.PageAllocator(num_pages=4, page_size=4)
    a.Allocate("x", 4)
    assert a.peak_in_use == 4
    assert a.Free("x") == 4
    assert a.Free("x") == 0        # second free: no-op
    assert a.Free("never-seen") == 0
    assert a.num_free == 4
    assert a.peak_in_use == 4      # peak survives the drain
    assert a.Stats()["utilization"] == 0.0

  def test_pages_for_rounds_up(self):
    a = kv_cache.PageAllocator(num_pages=4, page_size=4)
    assert [a.PagesFor(n) for n in (1, 4, 5, 8, 9)] == [1, 1, 2, 2, 3]


# -- scheduler lifecycle (device-free) ---------------------------------------


def _MakeSched(slots=2, pages=8, page=4, table_pages=4, chunk=4):
  alloc = kv_cache.PageAllocator(pages, page)
  return scheduler_lib.Scheduler(slots, alloc, table_pages, chunk), alloc


def _Drive(sched, sampled_tok=7):
  """One admit → build → fabricated-sample → commit iteration."""
  sched.EvictCancelled()
  sched.Admit()
  batch = sched.BuildStep()
  if batch is None:
    return None, []
  sampled = np.full(batch.ids.shape, sampled_tok, np.int32)
  return batch, sched.CommitStep(batch, sampled)


class TestScheduler:

  def test_prefill_to_decode_to_length_finish(self):
    sched, alloc = _MakeSched()
    sched.Submit(scheduler_lib.Request("a", [1, 2, 3, 4, 5], 2))
    # step 1: mixed step consumes the first chunk (4 of 5 prompt tokens)
    batch, events = _Drive(sched)
    assert batch.mixed and batch.ids.shape == (2, 4)
    assert batch.prompt_tokens == 4 and events == []
    # step 2: last prompt token -> first sampled token
    batch, events = _Drive(sched)
    assert batch.in_len[0] == 1 and events == [("a", 7, False)]
    assert sched._by_id["a"].state is scheduler_lib.SeqState.DECODE
    # step 3: pure decode step (C == 1) hits max_new -> retire + free
    batch, events = _Drive(sched)
    assert not batch.mixed and batch.ids.shape == (2, 1)
    assert batch.ids[0, 0] == 7   # feeds back the last sampled token
    assert events == [("a", 7, True)]
    assert sched._by_id["a"].finish_reason == "length"
    assert alloc.num_free == alloc.num_pages
    assert sched.slots == [None, None]

  def test_eos_finishes_early(self):
    sched, alloc = _MakeSched()
    sched.Submit(scheduler_lib.Request("a", [1, 2], 10, eos_id=7))
    _, events = _Drive(sched, sampled_tok=7)
    assert events == [("a", 7, True)]
    assert sched._by_id["a"].finish_reason == "eos"
    assert alloc.num_free == alloc.num_pages

  def test_pool_exhaustion_queues_gracefully(self):
    # each request needs 2 pages; the 8-page pool holds 4 but only 2 slots
    sched, alloc = _MakeSched(slots=2, pages=3)
    for rid in ("a", "b", "c"):
      sched.Submit(scheduler_lib.Request(rid, [1, 2, 3, 4], 4))
    sched.Admit()
    # only "a" fits (2 pages); "b" head-of-line blocks on the last page
    assert [s and s.id for s in sched.slots] == ["a", None]
    assert [s.id for s in sched.waiting] == ["b", "c"]
    assert sched.Stats()["queue_depth"] == 2
    while sched._by_id["a"].state is not scheduler_lib.SeqState.FINISHED:
      _Drive(sched)
    # "a" freed its pages; "b" admitted on the very next boundary
    sched.Admit()
    assert any(s and s.id == "b" for s in sched.slots)

  def test_overlong_request_rejected(self):
    sched, _ = _MakeSched(table_pages=2)   # capacity 8 slots
    with pytest.raises(ValueError):
      sched.Submit(scheduler_lib.Request("a", [1] * 6, 4))
    assert sched.rejected_overlong == 1

  def test_cancel_queued_and_cancel_midflight(self):
    sched, alloc = _MakeSched()
    sched.Submit(scheduler_lib.Request("a", [1, 2], 8))
    sched.Submit(scheduler_lib.Request("b", [3, 4], 8))
    # queued cancel: retires immediately, never occupies a slot
    assert sched.Cancel("b")
    assert sched._by_id["b"].state is scheduler_lib.SeqState.CANCELLED
    assert not sched.Cancel("b")   # double-cancel: no
    _Drive(sched)                  # "a" now mid-flight (decoding)
    assert sched.Cancel("a")
    assert alloc.num_in_use > 0    # pages return at the boundary, not now
    evicted = sched.EvictCancelled()
    assert [s.id for s in evicted] == ["a"]
    assert alloc.num_free == alloc.num_pages
    assert sched.Stats()["cancelled"] == 2
    assert not sched.HasWork()

  def test_block_tables_rewritten_only_on_admit(self):
    sched, alloc = _MakeSched(slots=2, pages=8)
    sched.Submit(scheduler_lib.Request("a", [1, 2, 3, 4], 4))
    sched.Admit()
    row0 = sched.block_tables[0].copy()
    assert list(row0[:2]) == alloc.PagesOf("a")
    _Drive(sched)
    np.testing.assert_array_equal(sched.block_tables[0], row0)


# -- serving engine ----------------------------------------------------------


def _MakeEngine(task, theta, **kw):
  kw.setdefault("page_size", 4)
  kw.setdefault("num_pages", 16)
  kw.setdefault("max_batch", 4)
  kw.setdefault("max_seq_len", 32)
  kw.setdefault("prefill_chunk", 4)
  kw.setdefault("default_max_new", 6)
  return engine_lib.ServingLoop(task, theta, **kw)


class TestServingEngine:

  def test_runbatch_token_identical_to_dense_greedy(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta)
    prompts = np.zeros((4, 11), np.int32)
    rows = [[5, 9, 2, 33, 17, 4, 8, 1, 60, 3, 12], [7, 7, 7],
            [1, 2, 3, 4, 5, 6, 7], [44, 21, 9, 9, 2]]
    lens = np.array([len(r) for r in rows], np.int32)
    for i, r in enumerate(rows):
      prompts[i, :len(r)] = r
    out = eng.RunBatch(prompts, lens, 6)
    for i, r in enumerate(rows):
      assert list(out[i]) == _GreedyRef(task, theta, r, 6), f"row {i}"
    # the batch drained: every page is back, counters moved
    stats = eng.Stats()
    assert stats["kv_pages"]["free"] == eng.num_pages
    assert stats["kv_pages"]["peak_in_use"] > 0
    assert stats["scheduler"]["finished"] == 4
    assert stats["mixed_steps"] > 0 and stats["decode_steps"] > 0
    assert stats["tokens_emitted"] == 24
    assert stats["prompt_tokens"] == int(lens.sum())
    assert stats["paged_path"] == (
        "pallas" if jax.default_backend() == "tpu" else "xla")
    assert stats["dense_fallback_steps"] == 0

  def test_page_reuse_across_batches_stays_identical(self, tiny_lm):
    """A second RunBatch on the same engine decodes into recycled pages;
    outputs must not change."""
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta)
    prompts = np.array([[5, 9, 2, 33], [44, 21, 9, 9]], np.int32)
    lens = np.array([4, 4], np.int32)
    out1 = eng.RunBatch(prompts, lens, 6)
    out2 = eng.RunBatch(prompts, lens, 6)
    np.testing.assert_array_equal(out1, out2)

  def test_matches_batch_synchronous_gshard_decode(self, tmp_path):
    """The acceptance bar: continuous batching changes WHEN rows decode,
    never WHAT they decode — greedy tokens identical to GShardDecode."""
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    ckpt.Save(1, state, force=True)
    ckpt.Close()
    prompts = np.array([[5, 6, 7, 8], [9, 10, 0, 0], [11, 0, 0, 0]],
                       np.int32)
    lens = np.array([4, 2, 1], np.int32)

    driver = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "out.jsonl"), max_decode_steps=4)
    recs = driver.DecodeOnce(1, prompts, lens)
    telem = driver._last_telemetry
    assert telem is not None
    # the telemetry key set is single-sourced in observe/schema.py — the
    # exact-match assertion catches keys landing on only one surface
    assert set(telem) == set(observe_schema.GSHARD_TELEMETRY_KEYS)
    assert {"spec_branches", "spec_width_clamps",
            "accepted_depth_hist"} <= set(telem)
    # compiled-step-program census: one (p_len, t_max) bucket was used,
    # and this driver compiles a (prefill, sample) program pair per bucket
    assert telem["step_programs"] == 2
    # the telemetry dict is generated from observe.schema, so any key added
    # to one surface without the other fails here, not in a bench comparison
    assert list(telem) == list(observe_schema.GSHARD_TELEMETRY_KEYS)
    # both surfaces share the mirrored keys by construction
    assert observe_schema.SHARED_SERVING_KEYS <= set(telem)
    # batch-synchronous decode never speculates: the spec keys exist (the
    # engine-Stats mirror contract) but stay at their zero values
    assert telem["draft_tokens"] == 0
    assert telem["accepted_tokens"] == 0
    assert telem["accepted_len_hist"] == []
    # ...and never serves cached prefixes: same mirror contract
    assert telem["prefix_hit_tokens"] == 0
    assert telem["prefix_cache"]["enabled"] is False
    assert set(telem["prefix_cache"]) == (
        observe_schema.PREFIX_CACHE_STATS_KEYS)
    assert telem["prompt_tokens"] == 7 and telem["decode_tokens"] == 12
    assert telem["decode_state_bytes_per_seq"] > 0
    assert telem["tokens_per_sec"] > 0
    assert telem["kv_cache_dtype"] == "float32"
    assert telem["kv_bytes_per_token"] > 0
    assert telem["serve_int8_weights"] is False
    assert all(r["telemetry"] == telem for r in recs)

    eng = engine_lib.ServingLoop(
        task, state.theta, page_size=4, num_pages=8, max_batch=3,
        max_seq_len=8, prefill_chunk=4, default_max_new=4)
    out = eng.RunBatch(prompts, lens, 4)
    for i, rec in enumerate(recs):
      assert list(out[i]) == rec["output_ids"], f"row {i}"

  def test_async_submit_stream_and_stats(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta).Start()
    try:
      h1 = eng.Submit([5, 9, 2, 33, 17], 6)
      h2 = eng.Submit([7, 7, 7], 6)
      streamed = list(h1.Tokens(timeout=30))
      assert streamed == h1.Result(timeout=30)
      assert h1.Result(timeout=30) == _GreedyRef(task, theta,
                                                 [5, 9, 2, 33, 17], 6)
      assert h2.Result(timeout=30) == _GreedyRef(task, theta, [7, 7, 7], 6)
      assert h1.finish_reason == "length" and h1.done
      assert h1.first_token_time is not None
      assert h1.finish_time >= h1.first_token_time >= h1.submit_time
    finally:
      eng.Stop()
    assert eng.Stats()["kv_pages"]["free"] == eng.num_pages

  def test_exhaustion_queues_and_all_finish(self, tiny_lm):
    """More requests than slots AND pages: later requests queue (never
    crash) and run when pages free up."""
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta, num_pages=6, max_batch=2, max_seq_len=16)
    prompts = np.tile(np.array([[3, 1, 4]], np.int32), (5, 1))
    prompts += np.arange(5, dtype=np.int32)[:, None]   # distinct rows
    lens = np.full((5,), 3, np.int32)
    out = eng.RunBatch(prompts, lens, 5)
    for i in range(5):
      assert list(out[i]) == _GreedyRef(task, theta, list(prompts[i]), 5)
    stats = eng.Stats()
    assert stats["scheduler"]["finished"] == 5
    assert stats["kv_pages"]["free"] == 6

  def test_cancel_midstream_reclaims_pages(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta, num_pages=8, max_batch=2).Start()
    try:
      h = eng.Submit([5, 9, 2], 24)
      it = h.Tokens(timeout=30)
      got = [next(it) for _ in range(3)]
      assert h.Cancel()
      rest = list(it)   # stream terminates promptly after the cancel
      assert h.finish_reason == "cancelled" and h.done
      assert len(got) + len(rest) < 24
      # a request submitted after the cancel still runs to completion
      h2 = eng.Submit([7, 7, 7], 4)
      assert h2.Result(timeout=30) == _GreedyRef(task, theta, [7, 7, 7], 4)
    finally:
      eng.Stop()
    assert eng.Stats()["kv_pages"]["free"] == eng.num_pages

  def test_overcapacity_submit_rejected(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta, num_pages=4, max_seq_len=32)
    with pytest.raises(ValueError, match="could never be admitted"):
      eng.Submit([1, 2, 3], 30)   # needs 9 pages; the pool has 4

  def test_ineligible_config_falls_back_dense_and_visibly(self):
    """atten_logit_cap > 0 fails BlockDecodeEligible: the engine must
    still decode correctly (gather-dense fallback) AND say so."""
    from lingvo_tpu.core import attention as attention_lib
    p = _TinyLmParams()
    p.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
        atten_logit_cap=50.0)
    task = p.Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    eng = _MakeEngine(task, theta)
    assert eng.paged_path == "dense"
    prompts = np.array([[5, 9, 2, 33], [7, 7, 7, 0]], np.int32)
    lens = np.array([4, 3], np.int32)
    out = eng.RunBatch(prompts, lens, 4)
    assert list(out[0]) == _GreedyRef(task, theta, [5, 9, 2, 33], 4)
    assert list(out[1]) == _GreedyRef(task, theta, [7, 7, 7], 4)
    stats = eng.Stats()
    assert stats["paged_path"] == "dense"
    assert stats["dense_fallback_steps"] == stats["steps"] > 0


# -- deterministic mixed-length soak -----------------------------------------


@pytest.mark.slow
class TestSoak:

  def test_mixed_length_soak_token_identical(self, tiny_lm):
    """20 seeded ragged requests through 3 slots and a deliberately tight
    pool, submitted from a separate thread while the loop runs: every
    request must finish and match its per-row dense reference."""
    task, theta = tiny_lm
    rng = np.random.RandomState(0)
    reqs = []
    for _ in range(20):
      p_len = int(rng.randint(1, 12))
      max_new = int(rng.randint(1, 8))
      prompt = [int(t) for t in rng.randint(1, 64, size=p_len)]
      reqs.append((prompt, max_new))
    eng = engine_lib.ServingLoop(
        task, theta, page_size=4, num_pages=10, max_batch=3,
        max_seq_len=20, prefill_chunk=4, default_max_new=8).Start()
    handles = [None] * len(reqs)

    def _Submit():
      for i, (prompt, max_new) in enumerate(reqs):
        handles[i] = eng.Submit(prompt, max_new)

    t = threading.Thread(target=_Submit)
    t.start()
    t.join(timeout=60)
    try:
      for i, (prompt, max_new) in enumerate(reqs):
        got = handles[i].Result(timeout=120)
        assert got == _GreedyRef(task, theta, prompt, max_new), f"req {i}"
        assert handles[i].finish_reason == "length"
    finally:
      eng.Stop()
    stats = eng.Stats()
    assert stats["scheduler"]["finished"] == 20
    assert stats["kv_pages"]["free"] == 10
    assert stats["kv_pages"]["peak_in_use"] <= 10
