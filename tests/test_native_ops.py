"""Tests for the native C++ input pipeline (semantics ported from the
reference's record_yielder_test.cc / record_batcher_test.cc /
pack_ops_test.py / tokenizer_ops_test.py coverage)."""

import os

import numpy as np
import pytest

from lingvo_tpu.ops import native


@pytest.fixture(scope="module")
def lib():
  return native.Lib()


def _write_text_files(tmpdir, num_files=4, lines_per_file=25):
  paths = []
  n = 0
  for i in range(num_files):
    p = os.path.join(tmpdir, f"data-{i:03d}.txt")
    with open(p, "w") as f:
      for _ in range(lines_per_file):
        f.write(f"line{n}\n")
        n += 1
    paths.append(p)
  return paths, n


class TestRecordYielder:

  def test_single_epoch_covers_all_records(self, lib, tmp_path):
    _, total = _write_text_files(str(tmp_path))
    y = native.RecordYielder(
        f"text:{tmp_path}/data-*.txt", max_epochs=1, num_threads=3)
    records = list(y)
    assert len(records) == total
    assert sorted(records) == sorted(
        f"line{i}".encode() for i in range(total))
    assert y.epochs_completed >= 1
    y.Close()

  def test_shuffling_changes_order_but_not_content(self, lib, tmp_path):
    _, total = _write_text_files(str(tmp_path))
    y1 = native.RecordYielder(f"text:{tmp_path}/data-*.txt", seed=1,
                              max_epochs=1)
    y2 = native.RecordYielder(f"text:{tmp_path}/data-*.txt", seed=2,
                              max_epochs=1)
    r1, r2 = list(y1), list(y2)
    assert sorted(r1) == sorted(r2)
    assert r1 != r2  # different seeds -> different order (overwhelmingly)
    y1.Close()
    y2.Close()

  def test_repeats_forever_when_max_epochs_zero(self, lib, tmp_path):
    _, total = _write_text_files(str(tmp_path), num_files=2,
                                 lines_per_file=5)
    y = native.RecordYielder(f"text:{tmp_path}/data-*.txt", max_epochs=0)
    got = [y.Next() for _ in range(total * 3)]
    assert all(g is not None for g in got)
    assert y.epochs_completed >= 2
    y.Close()

  def test_sharding_partitions_files(self, lib, tmp_path):
    _write_text_files(str(tmp_path), num_files=4, lines_per_file=10)
    r0 = list(native.RecordYielder(
        f"text:{tmp_path}/data-*.txt", max_epochs=1, shard_index=0,
        num_shards=2))
    r1 = list(native.RecordYielder(
        f"text:{tmp_path}/data-*.txt", max_epochs=1, shard_index=1,
        num_shards=2))
    assert len(r0) == len(r1) == 20
    assert not (set(r0) & set(r1))

  def test_tfrecord_roundtrip(self, lib, tmp_path):
    import struct
    path = os.path.join(str(tmp_path), "data.tfrecord")
    payloads = [f"record-{i}".encode() for i in range(10)]
    with open(path, "wb") as f:
      for pl in payloads:
        f.write(struct.pack("<Q", len(pl)))
        f.write(b"\x00" * 4)
        f.write(pl)
        f.write(b"\x00" * 4)
    y = native.RecordYielder(f"tfrecord:{path}", max_epochs=1, shuffle=False,
                             num_threads=1)
    assert sorted(list(y)) == sorted(payloads)

  def test_weighted_mix(self, lib, tmp_path):
    for name, content in (("a.txt", "aaa"), ("b.txt", "bbb")):
      with open(os.path.join(str(tmp_path), name), "w") as f:
        for _ in range(500):
          f.write(content + "\n")
    import ctypes
    ya = native.RecordYielder(f"text:{tmp_path}/a.txt")
    yb = native.RecordYielder(f"text:{tmp_path}/b.txt")
    children = (ctypes.c_void_p * 2)(ya._handle, yb._handle)
    weights = (ctypes.c_double * 2)(0.8, 0.2)
    mix_handle = lib.LTMixYielderNew(children, weights, 2, 7)
    ya._handle = yb._handle = None  # ownership moved to the mix
    buf = ctypes.create_string_buffer(1024)
    src = ctypes.c_int32(0)
    counts = [0, 0]
    for _ in range(1000):
      n = lib.LTYielderNext(mix_handle, buf, 1024, ctypes.byref(src))
      assert n > 0
      counts[src.value] += 1
    lib.LTYielderFree(mix_handle)
    assert counts[0] > 3 * counts[1]  # ~4:1 ratio

  def test_empty_glob_raises(self, lib, tmp_path):
    with pytest.raises(ValueError, match="no files"):
      native.RecordYielder(f"text:{tmp_path}/missing-*.txt")

  def test_unknown_type_raises(self, lib, tmp_path):
    _write_text_files(str(tmp_path), num_files=1)
    with pytest.raises(ValueError):
      native.RecordYielder(f"tfrecords:{tmp_path}/data-*.txt")  # typo'd type

  def test_oversized_record_not_lost(self, lib, tmp_path):
    big = "x" * 5000
    with open(os.path.join(str(tmp_path), "big.txt"), "w") as f:
      f.write("small\n")
      f.write(big + "\n")
    y = native.RecordYielder(
        f"text:{tmp_path}/big.txt", max_epochs=1, shuffle=False,
        num_threads=1, max_record_bytes=64)
    records = list(y)
    assert len(records) == 2
    assert big.encode() in records  # survived the buffer growth

  def test_mix_renormalizes_after_exhaustion(self, lib, tmp_path):
    # high-weight child exhausts quickly; low-weight child must still drain.
    with open(os.path.join(str(tmp_path), "big_w.txt"), "w") as f:
      for i in range(5):
        f.write(f"a{i}\n")
    with open(os.path.join(str(tmp_path), "small_w.txt"), "w") as f:
      for i in range(100):
        f.write(f"b{i}\n")
    import ctypes
    ya = native.RecordYielder(f"text:{tmp_path}/big_w.txt", max_epochs=1)
    yb = native.RecordYielder(f"text:{tmp_path}/small_w.txt", max_epochs=1)
    children = (ctypes.c_void_p * 2)(ya._handle, yb._handle)
    weights = (ctypes.c_double * 2)(0.99, 0.01)
    mix = lib.LTMixYielderNew(children, weights, 2, 3)
    ya._handle = yb._handle = None
    buf = ctypes.create_string_buffer(1024)
    src = ctypes.c_int32(0)
    count = 0
    while lib.LTYielderNext(mix, buf, 1024, ctypes.byref(src)) >= 0:
      count += 1
    lib.LTYielderFree(mix)
    assert count == 105  # every record from both children

  def test_ascii_newline_roundtrip(self, lib):
    tok = native.AsciiTokenizer()
    ids, _ = tok.StringsToIds(["a\nb"], max_len=8)
    assert ids[0, 1] == 2  # <n_> id per the documented layout
    assert tok.IdsToStrings(ids)[0] == "a\nb"

  def test_iota_synthetic(self, lib):
    y = native.RecordYielder("iota:100", max_epochs=1, shuffle=False,
                             num_threads=1)
    recs = list(y)
    assert [int(r) for r in recs] == list(range(100))


class TestPacking:

  def test_pack_all_fit(self, lib):
    lens = [3, 4, 2, 5]
    row, off = native.PackSequences(lens, num_rows=2, time=8)
    assert (row >= 0).all()
    # verify no overlaps and within bounds
    used = {}
    for i, L in enumerate(lens):
      for t in range(off[i], off[i] + L):
        key = (int(row[i]), t)
        assert key not in used
        assert t < 8
        used[key] = i

  def test_pack_drops_when_full(self, lib):
    lens = [8, 8, 8]
    row, off = native.PackSequences(lens, num_rows=2, time=8)
    assert (row >= 0).sum() == 2
    assert (row == -1).sum() == 1

  def test_pack_oversized_dropped(self, lib):
    row, off = native.PackSequences([10], num_rows=4, time=8)
    assert row[0] == -1

  def test_apply_packing_produces_segments(self, lib):
    seqs = [np.array([5, 6, 7]), np.array([8, 9]), np.array([10])]
    row, off = native.PackSequences([3, 2, 1], num_rows=2, time=4)
    ids, seg_ids, seg_pos = native.ApplyPacking(seqs, row, off, 2, 4)
    # each sequence intact somewhere, with its own segment id and 0-based pos
    flat = ids.ravel().tolist()
    for seq in seqs:
      assert seq[0] in flat
    assert seg_ids.max() >= 1
    # positions restart per segment
    for r in range(2):
      for t in range(4):
        if seg_ids[r, t] > 0 and (t == 0 or seg_ids[r, t] != seg_ids[r, t - 1]):
          assert seg_pos[r, t] == 0


class TestTokenizers:

  def test_ascii_roundtrip(self, lib):
    tok = native.AsciiTokenizer()
    texts = ["hello world", "abc 123!"]
    ids, paddings = tok.StringsToIds(texts, max_len=16)
    assert ids.shape == (2, 16)
    assert ids[0, 11] == tok.eos_id  # appended eos
    out = tok.IdsToStrings(ids)
    assert out[0] == "hello world"
    assert out[1] == "abc 123!"

  def test_ascii_truncation_keeps_eos(self, lib):
    tok = native.AsciiTokenizer()
    ids, _ = tok.StringsToIds(["abcdefghij"], max_len=5)
    assert ids[0, 4] == tok.eos_id

  def test_vocab_tokenizer(self, lib, tmp_path):
    vocab = os.path.join(str(tmp_path), "vocab.txt")
    with open(vocab, "w") as f:
      f.write("<pad>\n<s>\n</s>\n<unk>\nthe\ncat\nsat\n")
    tok = native.VocabTokenizer(vocab)
    assert tok.vocab_size == 7
    ids, paddings = tok.StringsToIds(["the cat sat", "the dog sat"], 6)
    np.testing.assert_array_equal(ids[0, :3], [4, 5, 6])
    assert ids[1, 1] == 3  # unk
    out = tok.IdsToStrings(ids, lens=[3, 3])
    assert out[0] == "the cat sat"
    assert out[1] == "the <unk> sat"
