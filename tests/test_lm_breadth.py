"""BERT MLM, giant-LM configs, GShard streaming decode driver (VERDICT r1
item 9)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401
from lingvo_tpu.core.nested_map import NestedMap


class TestBert:

  def test_bert_learns_masked_prediction(self):
    mp = model_registry.GetParams("lm.wiki_bert.BertTiny", "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    step = jax.jit(task.TrainStep)
    losses, accs = [], []
    for _ in range(150):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
      accs.append(float(out.metrics.mlm_accuracy[0]))
    # pattern-structured data: masked tokens are predictable from context
    assert losses[-1] < 0.8 * losses[0], (losses[0], losses[-1])

  def test_bert_is_bidirectional(self):
    """MLM prediction at position i must see positions > i."""
    mp = model_registry.GetParams("lm.wiki_bert.BertTiny", "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    preds = task.ComputePredictions(theta, batch)
    # perturb the future: logits at position 0 must change
    batch2 = batch.Copy()
    batch2.ids = batch.ids.at[:, -8:].set(5)
    preds2 = task.ComputePredictions(theta, batch2)
    assert not np.allclose(np.asarray(preds.logits[:, 0]),
                           np.asarray(preds2.logits[:, 0]), atol=1e-5)

  def test_mlm_loss_only_on_masked_positions(self):
    mp = model_registry.GetParams("lm.wiki_bert.BertTiny", "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    preds = task.ComputePredictions(theta, batch)
    m1, _ = task.ComputeLoss(theta, preds, batch)
    # corrupting labels at UNmasked positions must not change the loss
    batch2 = batch.Copy()
    batch2.labels = jnp.where(batch.masked_weights > 0, batch.labels, 7)
    m2, _ = task.ComputeLoss(theta, preds, batch2)
    np.testing.assert_allclose(float(m1.loss[0]), float(m2.loss[0]),
                               rtol=1e-6)


class TestGiantConfigs:

  @pytest.mark.parametrize("name,expect_layers", [
      ("lm.synthetic_packed_input.DenseLm175B", 96),
      ("lm.synthetic_packed_input.DenseLm1T", 128),
  ])
  def test_params_instantiate_with_shapes(self, name, expect_layers):
    """Registry smoke test (ref models_test_helper stubbed-variable runs):
    full param trees build and variable specs have the advertised scale."""
    mp = model_registry.GetParams(name, "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    assert mp.task.num_layers == expect_layers
    specs = task.VariableSpecs()
    total = 0
    for _, wp in specs.FlattenItems():
      n = 1
      for d in wp.shape:
        n *= int(d)
      total += n
    if "175B" in name:
      assert total > 100e9, total
    else:
      assert total > 700e9, total


class TestGShardDecodeDriver:

  def test_decodes_every_new_checkpoint(self, tmp_path):
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu.core import checkpointer as checkpointer_lib

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()

    # write two "training" checkpoints
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    state.step = jnp.asarray(10, jnp.int32)
    ckpt.Save(10, state, force=True)
    state.step = jnp.asarray(20, jnp.int32)
    ckpt.Save(20, state, force=True)
    ckpt.Close()
    open(os.path.join(train_dir, "FINISHED"), "w").write("20")

    out_path = str(tmp_path / "decodes.jsonl")
    driver = gshard_decode.GShardDecode(
        task, train_dir, out_path, max_decode_steps=8,
        poll_interval_secs=0.1, timeout_secs=10.0)
    prompts = np.array([[5, 6, 7, 8], [9, 10, 11, 12]], np.int32)
    lens = np.array([4, 4], np.int32)
    driver.Run(prompts, lens)

    recs = [json.loads(l) for l in open(out_path)]
    assert recs, "no decodes written"
    assert recs[-1]["checkpoint_step"] == 20
    assert len(recs[-1]["output_ids"]) == 8
    assert recs[-1]["prompt_ids"] == [9, 10, 11, 12]

  def test_greedy_matches_argmax_rollout(self, tmp_path):
    """driver's jitted primed-cache sampler == naive re-encode rollout."""
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu.core import checkpointer as checkpointer_lib

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    ckpt.Save(1, state, force=True)
    ckpt.Close()

    driver = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "o.jsonl"), max_decode_steps=4)
    prompts = np.array([[5, 6, 7, 8]], np.int32)
    recs = driver.DecodeOnce(1, prompts, np.array([4], np.int32))
    got = recs[0]["output_ids"]

    # naive rollout: full forward each step
    theta = state.theta
    ids = list(prompts[0])
    for _ in range(4):
      batch = NestedMap(
          ids=jnp.asarray([ids], jnp.int32),
          labels=jnp.zeros((1, len(ids)), jnp.int32),
          paddings=jnp.zeros((1, len(ids)), jnp.float32))
      preds = task.ComputePredictions(theta, batch)
      ids.append(int(jnp.argmax(preds.logits[0, -1])))
    assert got == ids[4:], (got, ids[4:])

  def test_variable_length_prompts_match_per_length_batches(self, tmp_path):
    """VERDICT r2 Next #10: a batch of mixed-length prompts must produce
    the same continuations as separate per-length batches (right-aligned
    cache + left-pad masking)."""
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu.core import checkpointer as checkpointer_lib

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    ckpt.Save(1, state, force=True)
    ckpt.Close()

    driver = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "mixed.jsonl"), max_decode_steps=4)
    # mixed batch: lengths 4 and 2 (left-aligned input convention)
    prompts = np.array([[5, 6, 7, 8], [9, 10, 0, 0]], np.int32)
    recs = driver.DecodeOnce(1, prompts, np.array([4, 2], np.int32))

    d_full = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "full.jsonl"), max_decode_steps=4)
    rec_full = d_full.DecodeOnce(1, np.array([[5, 6, 7, 8]], np.int32),
                                 np.array([4], np.int32))
    d_short = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "short.jsonl"), max_decode_steps=4)
    rec_short = d_short.DecodeOnce(1, np.array([[9, 10]], np.int32),
                                   np.array([2], np.int32))

    assert recs[0]["output_ids"] == rec_full[0]["output_ids"]
    assert recs[1]["output_ids"] == rec_short[0]["output_ids"]
    assert recs[1]["prompt_ids"] == [9, 10]
