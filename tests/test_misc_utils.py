"""datasets reflection, CachedCall, RandomPermutationSequence."""

import numpy as np
import pytest

from lingvo_tpu import datasets
from lingvo_tpu.core import host_ops


class TestGetDatasets:

  def test_reflects_public_zero_arg_methods(self):
    class M:
      def Train(self):
        return 1

      def Test(self):
        return 2

      def Task(self):  # excluded: base interface
        return 3

      def _private(self):
        return 4

    assert datasets.GetDatasets(M) == ["Test", "Train"]

  def test_required_args_raise_when_strict(self):
    class M:
      def Train(self, x):
        return x

    assert datasets.GetDatasets(M) == []  # warn mode skips
    with pytest.raises(datasets.DatasetFunctionError):
      datasets.GetDatasets(M, warn_on_error=False)

  def test_registered_model_params(self):
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    cls = model_registry.GetClass("image.mnist.LeNet5")
    ds = datasets.GetDatasets(cls)
    assert "Train" in ds and "Test" in ds


class TestCachedCall:

  def test_calls_once(self):
    calls = []

    def fn():
      calls.append(1)
      return {"x": 42}

    cached = host_ops.CachedCall(fn)
    assert cached() == {"x": 42}
    assert cached() == {"x": 42}
    assert len(calls) == 1
    cached.Reset()
    cached()
    assert len(calls) == 2


class TestRandomPermutationSequence:

  def test_epoch_covers_all_ids_once(self):
    seq = host_ops.RandomPermutationSequence(num=10, batch=3, seed=5)
    seen = []
    with pytest.raises(StopIteration):
      while True:
        seen.extend(seq.GetNext().tolist())
    assert sorted(seen) == list(range(10))

  def test_repeat_reshuffles(self):
    seq = host_ops.RandomPermutationSequence(num=6, batch=6, repeat=True,
                                             seed=3)
    a = seq.GetNext()
    b = seq.GetNext()
    assert sorted(a.tolist()) == sorted(b.tolist()) == list(range(6))

  def test_deterministic_with_seed(self):
    a = host_ops.RandomPermutationSequence(num=8, batch=8, seed=7).GetNext()
    b = host_ops.RandomPermutationSequence(num=8, batch=8, seed=7).GetNext()
    np.testing.assert_array_equal(a, b)


class TestInputPolicy:

  def test_single_host_is_identity(self):
    from lingvo_tpu.core import input_policy
    from lingvo_tpu.models.lm import input_generator as lm_input
    p = lm_input.SyntheticLmInput.Params()
    assert input_policy.Apply(p) is p

  def test_multi_host_stamps_shard_params(self):
    from lingvo_tpu.core import cluster as cluster_lib
    from lingvo_tpu.core import input_policy
    from lingvo_tpu.models.lm import input_generator as lm_input

    cp = cluster_lib.Cluster.Params().Set(num_infeed_hosts=4,
                                          infeed_host_index=2)
    with cluster_lib.ClusterScope(cluster_lib.Cluster(cp)):
      p = input_policy.Apply(lm_input.SyntheticLmInput.Params())
    assert p.num_hosts == 4 and p.host_index == 2
    gen = p.Set(batch_size=2, seq_len=8, vocab_size=11).Instantiate()
    b = gen.GetPreprocessedInputBatch()
    assert b.ids.shape == (2, 8)
    assert gen.GlobalBatchSize() == 8  # 2 per host x 4 hosts
