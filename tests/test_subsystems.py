"""Quantization, pruning, distillation, symbolic dims, builder DSL, sharded
embedding (VERDICT r1 coverage rows 8/25/28/29/58)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import base_model
from lingvo_tpu.core import builder_layers
from lingvo_tpu.core import distillation_task
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import pruning
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import quant_utils
from lingvo_tpu.core import symbolic
from lingvo_tpu.core import tpu_embedding_layers
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(17)


class TestQuantization:

  def test_fake_quant_ste(self):
    x = jnp.linspace(-1.0, 1.0, 11)
    q = quant_utils.FakeQuant(x, scale=jnp.asarray(0.25), bits=8)
    # quantized to multiples of the scale
    np.testing.assert_allclose(np.asarray(q) % 0.25, 0.0, atol=1e-6)
    # straight-through: gradient is identity
    g = jax.grad(lambda x: jnp.sum(quant_utils.FakeQuant(x, 0.25)))(x)
    np.testing.assert_allclose(np.asarray(g), 1.0)

  def test_projection_with_qdomain_trains(self):
    p = layers_lib.ProjectionLayer.Params().Set(
        name="proj", input_dim=8, output_dim=8,
        qdomain=quant_utils.SymmetricQDomain.Params())
    layer = p.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (4, 8))
    with py_utils.ForwardStateContext() as fwd:
      out = layer.FProp(theta, x)
    assert out.shape == (4, 8)
    # activation range EMA was tracked via forward state
    assert any("range_act" in k for k in fwd)
    # quantization is coarse: outputs land on a lattice
    grads = jax.grad(lambda th: jnp.sum(
        layer.FProp(th, x) ** 2))(theta)
    assert float(sum(jnp.sum(jnp.abs(g))
                     for g in jax.tree.leaves(grads))) > 0

  def test_scheduled_clip_anneals(self):
    p = quant_utils.ScheduledClipQDomain.Params().Set(
        name="qd", start_cap=8.0, end_cap=1.0, clip_start_step=0,
        clip_end_step=100)
    qd = p.Instantiate()
    qd.FinalizePaths()
    theta = qd.InstantiateVariables(KEY)
    x = 5.0 * jnp.ones((4,))
    with py_utils.GlobalStepContext(jnp.asarray(0)):
      early = qd.QuantizeAct(theta, "act", x)
    with py_utils.GlobalStepContext(jnp.asarray(1000)):
      late = qd.QuantizeAct(theta, "act", x)
    assert float(early[0]) > 4.0   # loose cap keeps the value
    assert float(late[0]) <= 1.0   # tight cap clips it


class TestPruning:

  def _sched(self, **kw):
    return pruning.PruningSchedule(
        pruning.PruningSchedule.Params().Set(
            weight_regex=r".*w", final_sparsity=0.5, begin_step=0,
            end_step=100, **kw))

  def test_sparsity_ramp(self):
    s = self._sched()
    assert s.SparsityAt(0) == 0.0
    assert 0 < s.SparsityAt(50) < 0.5
    assert abs(s.SparsityAt(100) - 0.5) < 1e-6
    assert abs(s.SparsityAt(10**6) - 0.5) < 1e-6

  def test_masks_zero_smallest_magnitudes(self):
    s = self._sched()
    theta = NestedMap(proj=NestedMap(
        w=jnp.asarray([[0.1, -5.0], [3.0, -0.2]]),
        b=jnp.asarray([1.0, 2.0])))
    masks = pruning.ComputeMasks(theta, s, step=100)  # 50% sparsity
    np.testing.assert_array_equal(np.asarray(masks.proj.w),
                                  [[0.0, 1.0], [1.0, 0.0]])
    np.testing.assert_array_equal(np.asarray(masks.proj.b), [1.0, 1.0])
    pruned = pruning.ApplyMasks(theta, masks)
    assert float(pruned.proj.w[0, 0]) == 0.0
    assert abs(pruning.Sparsity(masks, s) - 0.5) < 1e-6

  def test_executor_prunes(self, tmp_path):
    import tests.test_executor_hardening as helpers
    from lingvo_tpu.runners import executor as executor_lib
    from lingvo_tpu.runners import program as program_lib
    task_p = helpers._TaskParams(max_steps=20, steps_per_loop=5)
    task_p.train.pruning = pruning.PruningSchedule.Params().Set(
        weight_regex=r"proj\.w", final_sparsity=0.5, begin_step=0,
        end_step=10, frequency=5)
    task = task_p.Instantiate()
    task.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_p, logdir=str(tmp_path), steps_per_loop=5)
    sched = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(train_program=train_p),
        task=task, input_generators={"Train": helpers._RegressionInput()})
    ex = executor_lib.ExecutorTpu(task_p, str(tmp_path), schedule=sched,
                                  task=task)
    state = ex.Start()
    w = np.asarray(state.theta.proj.w)
    sparsity = (w == 0).mean()
    assert sparsity >= 0.45, sparsity


class TestDistillation:

  def test_teacher_frozen_student_learns(self):
    student_p = base_model.BaseTask.Params()  # placeholder (unused)
    import tests.test_executor_hardening as helpers

    class _ClsTask(base_model.BaseTask):
      @classmethod
      def Params(cls):
        p = super().Params()
        p.Define("dim", 4, "")
        p.Define("nclass", 3, "")
        return p

      def __init__(self, params):
        super().__init__(params)
        self.CreateChild("proj", layers_lib.ProjectionLayer.Params().Set(
            input_dim=self.p.dim, output_dim=self.p.nclass))

      def ComputePredictions(self, theta, input_batch):
        return NestedMap(logits=self.proj.FProp(theta.proj, input_batch.x))

      def ComputeLoss(self, theta, predictions, input_batch):
        xent = layers_lib.XentLossFromLogits(
            predictions.logits, self.p.nclass,
            class_ids=input_batch.y).per_example_xent
        return NestedMap(loss=(jnp.mean(xent), 4.0)), NestedMap()

    p = distillation_task.DistillationTask.Params().Set(name="distill")
    p.teacher = _ClsTask.Params().Set(name="teacher")
    p.student = _ClsTask.Params().Set(name="student")
    p.distill_weight = 0.5
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=0.1, optimizer=opt_lib.Adam.Params())
    task = p.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(KEY)
    teacher_w0 = np.asarray(state.theta.teacher.proj.w).copy()
    rng = np.random.RandomState(0)
    x = rng.randn(16, 4).astype("float32")
    y = rng.randint(0, 3, 16)
    batch = NestedMap(x=jnp.asarray(x), y=jnp.asarray(y))
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(30):
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert losses[-1] < losses[0]
    # the teacher never moved
    np.testing.assert_array_equal(teacher_w0,
                                  np.asarray(state.theta.teacher.proj.w))
    # metrics expose both components
    assert "hard_loss" in out.metrics and "distill_loss" in out.metrics


class TestSymbolic:

  def test_symbol_resolution(self):
    d = symbolic.Symbol("model_dim")
    expr = 4 * d
    with symbolic.SymbolToValueMap({d: 256}):
      assert symbolic.EvalExpr(expr) == 1024
      assert symbolic.EvalExpr((d, 2 * d)) == (256, 512)
      assert symbolic.EvalExpr(7) == 7
    with pytest.raises(ValueError):
      symbolic.EvalExpr(expr)

  def test_nested_scopes_override(self):
    d = symbolic.Symbol("d")
    with symbolic.SymbolToValueMap({d: 8}):
      with symbolic.SymbolToValueMap({d: 16}):
        assert symbolic.EvalExpr(d) == 16
      assert symbolic.EvalExpr(d) == 8

  def test_shape_algebra(self):
    d = symbolic.Symbol("d")
    s = symbolic.Shape([2, d]) + symbolic.Shape([3 * d])
    assert len(s) == 3
    with symbolic.SymbolToValueMap({d: 4}):
      assert s.ToTuple() == (2, 4, 12)
    assert s.size == 2 * d * 3 * d


class TestBuilderDsl:

  def _proj(self, name, din, dout):
    return layers_lib.ProjectionLayer.Params().Set(
        name=name, input_dim=din, output_dim=dout, has_bias=False)

  def test_seq_par_graph(self):
    b = builder_layers.Builder()
    seq = b._Seq("seq", self._proj("p1", 4, 8), self._proj("p2", 8, 4))
    layer = seq.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (2, 4))
    out = layer.FProp(theta, x)
    assert out.shape == (2, 4)
    # manual composition matches
    manual = jnp.einsum("bi,io->bo", jnp.einsum("bi,io->bo", x,
                                                theta.sub[0].w),
                        theta.sub[1].w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(manual),
                               atol=1e-5)

    par = b._Par("par", self._proj("a", 4, 4), self._proj("c", 4, 4))
    pl = par.Instantiate()
    pl.FinalizePaths()
    ptheta = pl.InstantiateVariables(KEY)
    pout = pl.FProp(ptheta, x)
    expect = (jnp.einsum("bi,io->bo", x, ptheta.sub[0].w)
              + jnp.einsum("bi,io->bo", x, ptheta.sub[1].w))
    np.testing.assert_allclose(np.asarray(pout), np.asarray(expect),
                               atol=1e-5)

    graph = b._Graph(
        "g", ["x"], ["y"],
        ("x->h", self._proj("e1", 4, 8)),
        ("h->y", self._proj("e2", 8, 4)))
    gl = graph.Instantiate()
    gl.FinalizePaths()
    gtheta = gl.InstantiateVariables(KEY)
    gout = gl.FProp(gtheta, NestedMap(x=x))
    assert gout.y.shape == (2, 4)

  def test_soft_cond(self):
    p = builder_layers.SoftCondLayer.Params().Set(
        name="sc", sub=self._proj("e", 4, 4), num_experts=3, cond_dim=4)
    layer = p.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (2, 4))
    out = layer.FProp(theta, x)
    assert out.shape == (2, 4)


class TestShardedEmbedding:

  def test_lookup_and_combiner(self):
    p = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
        name="tbl", vocab_size=50, embedding_dim=8, combiner="mean")
    tbl = p.Instantiate()
    tbl.FinalizePaths()
    theta = tbl.InstantiateVariables(KEY)
    ids = jnp.asarray([[1, 2, 2], [3, 0, 0]], jnp.int32)
    emb = tbl.EmbLookup(theta, ids)
    assert emb.shape == (2, 3, 8)
    weights = jnp.asarray([[1.0, 1.0, 0.0], [1.0, 0.0, 0.0]])
    combined = tbl.MultivalentLookup(theta, ids, weights)
    expect0 = (np.asarray(theta.table[1]) + np.asarray(theta.table[2])) / 2
    np.testing.assert_allclose(np.asarray(combined[0]), expect0, atol=1e-5)

  def test_sharded_over_mesh(self):
    if len(jax.devices()) < 8:
      pytest.skip("needs 8 devices")
    from lingvo_tpu.parallel import mesh as mesh_lib
    p = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
        name="tbl", vocab_size=64, embedding_dim=8, shard_axis="data")
    tbl = p.Instantiate()
    tbl.FinalizePaths()
    theta = tbl.InstantiateVariables(KEY)
    mesh = mesh_lib.MakeMesh({"data": 8})
    sh = mesh_lib.ThetaShardings(mesh, tbl, theta)
    placed = jax.device_put(theta, sh)
    assert "data" in str(placed.table.sharding.spec)
    ids = jnp.asarray([[1], [63]], jnp.int32)
    out = jax.jit(tbl.EmbLookup)(placed, ids)
    np.testing.assert_allclose(np.asarray(out[0, 0]),
                               np.asarray(theta.table[1]), atol=1e-5)

  def test_gather_matches_one_hot_single_device(self):
    p0 = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
        name="tbl", vocab_size=50, embedding_dim=8)
    t_oh = p0.Copy().Set(lookup_method="one_hot").Instantiate()
    t_g = p0.Copy().Set(lookup_method="gather").Instantiate()
    theta = t_oh.InstantiateVariables(KEY)
    ids = jnp.asarray([[1, 49, 0], [7, 7, 12]], jnp.int32)
    np.testing.assert_allclose(
        np.asarray(t_oh.EmbLookup(theta, ids)),
        np.asarray(t_g.EmbLookup(theta, ids)), atol=1e-5)

  def test_sharded_gather_matches_one_hot_on_mesh(self):
    if len(jax.devices()) < 8:
      pytest.skip("needs 8 devices")
    from lingvo_tpu.parallel import mesh as mesh_lib
    p0 = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
        name="tbl", vocab_size=64, embedding_dim=8, shard_axis="data")
    t_oh = p0.Copy().Set(lookup_method="one_hot").Instantiate()
    t_g = p0.Copy().Set(lookup_method="gather").Instantiate()
    theta = t_oh.InstantiateVariables(KEY)
    mesh = mesh_lib.MakeMesh({"data": 8})
    placed = jax.device_put(theta, mesh_lib.ThetaShardings(mesh, t_oh, theta))
    ids = jnp.asarray([[0, 8, 63], [17, 17, 31]], jnp.int32)
    with mesh_lib.MeshContext(mesh):
      out_g = jax.jit(t_g.EmbLookup)(placed, ids)
      out_oh = jax.jit(t_oh.EmbLookup)(placed, ids)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_oh),
                               atol=1e-5)

  def test_sharded_gather_gradients_match_one_hot(self):
    if len(jax.devices()) < 8:
      pytest.skip("needs 8 devices")
    from lingvo_tpu.parallel import mesh as mesh_lib
    p0 = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
        name="tbl", vocab_size=64, embedding_dim=8, shard_axis="data")
    t_oh = p0.Copy().Set(lookup_method="one_hot").Instantiate()
    t_g = p0.Copy().Set(lookup_method="gather").Instantiate()
    theta = t_oh.InstantiateVariables(KEY)
    mesh = mesh_lib.MakeMesh({"data": 8})
    placed = jax.device_put(theta, mesh_lib.ThetaShardings(mesh, t_oh, theta))
    ids = jnp.asarray([[0, 8, 63], [17, 17, 31]], jnp.int32)

    def loss(layer):
      return lambda th: jnp.sum(layer.EmbLookup(th, ids) ** 2)

    with mesh_lib.MeshContext(mesh):
      g_g = jax.jit(jax.grad(loss(t_g)))(placed)
      g_oh = jax.jit(jax.grad(loss(t_oh)))(placed)
    np.testing.assert_allclose(np.asarray(g_g.table),
                               np.asarray(g_oh.table), atol=1e-4)

  def test_per_table_optimizer_rules(self):
    from lingvo_tpu.core import optimizer as opt_lib
    tp = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
        vocab_size=10, embedding_dim=4)
    p = tpu_embedding_layers.TpuEmbeddingCollection.Params().Set(
        name="coll",
        tables=[("words", tp.Copy().Set(
            optimizer=opt_lib.Adagrad.Params())), ("cats", tp.Copy())],
        feature_to_table={"query": "words", "category": "cats"})
    coll = p.Instantiate()
    coll.FinalizePaths()
    rules = coll.OptimizerRules(opt_lib.SGD.Params())
    comp = opt_lib.CompositeOptimizer.Params().Set(
        name="comp", optimizer_map=rules).Instantiate()
    theta = coll.InstantiateVariables(KEY)
    state = comp.InitState(theta)
    # words table routes to Adagrad (index 0), cats to the SGD default
    assert comp._RouteIndex("table_words.table") == 0
    assert comp._RouteIndex("table_cats.table") == 1
    # one update step must change the words table via the Adagrad rule
    grads = theta.Transform(jnp.ones_like)
    new_theta, _ = comp.Update(state, grads, theta, 0.1, jnp.zeros((),
                                                                  jnp.int32))
    assert not np.allclose(np.asarray(new_theta.table_words.table),
                           np.asarray(theta.table_words.table))

  def test_collection_routes_features(self):
    tp = tpu_embedding_layers.ShardedEmbeddingTable.Params().Set(
        vocab_size=10, embedding_dim=4)
    p = tpu_embedding_layers.TpuEmbeddingCollection.Params().Set(
        name="coll",
        tables=[("words", tp.Copy()), ("cats", tp.Copy())],
        feature_to_table={"query": "words", "doc": "words",
                          "category": "cats"})
    coll = p.Instantiate()
    coll.FinalizePaths()
    theta = coll.InstantiateVariables(KEY)
    feats = NestedMap(query=jnp.asarray([1, 2]), doc=jnp.asarray([3]),
                      category=jnp.asarray([4]))
    out = coll.EmbLookup(theta, feats)
    assert out.query.shape == (2, 4)
    # query and doc share the words table
    np.testing.assert_allclose(
        np.asarray(out.doc[0]),
        np.asarray(theta.table_words.table[3]), atol=1e-5)


class TestQuantizationDepth:
  """PassiveAsym / per-channel / int8 serving path (quant_utils additions)."""

  def test_asym_domain_tracks_min_max(self):
    from lingvo_tpu.core import py_utils
    dom = quant_utils.PassiveAsymQDomain.Params().Set(
        name="q", ema_decay=0.5).Instantiate()
    dom.FinalizePaths()
    theta = dom.InstantiateVariables(jax.random.PRNGKey(0))
    x = jnp.linspace(0.0, 4.0, 32).reshape(4, 8)  # one-sided range
    with py_utils.ForwardStateContext() as upd:
      q = dom.QuantizeAct(theta, "act", x)
    assert q.shape == x.shape
    # min stays near 0, max moves toward 4
    keys = list(upd.keys())
    assert any("min_act" in k for k in keys)
    assert any("max_act" in k for k in keys)
    mx = [v for k, v in upd.items() if "max_act" in k][0]
    assert float(mx) > 1.0
    # quantization error bounded by one step
    with py_utils.EvalContext():
      q_eval = dom.QuantizeAct(theta, "act", x)
    step = 1.0 / (2.0 ** 8 - 1)
    assert float(jnp.max(jnp.abs(q_eval - jnp.clip(x, 0.0, 1.0)))) < 4 * step + 1e-3

  def test_per_channel_scales_differ(self):
    dom = quant_utils.PerChannelSymmetricQDomain.Params().Set(
        name="q").Instantiate()
    dom.FinalizePaths()
    theta = dom.InstantiateVariables(jax.random.PRNGKey(0))
    w = jnp.stack([jnp.ones(4) * 0.01, jnp.ones(4) * 10.0], axis=1)  # [4, 2]
    q = dom.QuantizeWeight(theta, w)
    # small-magnitude channel keeps resolution (per-tensor would crush it)
    np.testing.assert_allclose(np.asarray(q[:, 0]), 0.01, rtol=0.02)

  def test_int8_einsum_close_to_float(self):
    w = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 16))
    w_int8, scale = quant_utils.Int8QuantizeWeight(w)
    assert w_int8.dtype == jnp.int8
    y_int8 = quant_utils.Int8Einsum(x, w_int8, scale)
    y_ref = x @ w
    err = float(jnp.max(jnp.abs(y_int8 - y_ref)) / jnp.max(jnp.abs(y_ref)))
    assert err < 0.05, err

  def test_qat_matches_int8_deployment(self):
    """Per-channel QAT simulation == actual int8 weight dequantization."""
    dom = quant_utils.PerChannelSymmetricQDomain.Params().Set(
        name="q").Instantiate()
    dom.FinalizePaths()
    theta = dom.InstantiateVariables(jax.random.PRNGKey(0))
    w = jax.random.normal(jax.random.PRNGKey(3), (8, 4))
    w_qat = dom.QuantizeWeight(theta, w)
    w_int8, scale = quant_utils.Int8QuantizeWeight(w)
    w_deploy = w_int8.astype(jnp.float32) * scale
    np.testing.assert_allclose(np.asarray(w_qat), np.asarray(w_deploy),
                               atol=1e-6)
