"""Waymo-format car pipeline (VERDICT r3 Missing #4): frame parsing with
speed/difficulty extras, 5-dim points, e2e PointPillars training over the
native yielder, and difficulty-sliced breakdown AP. Ref
`lingvo/tasks/car/waymo/waymo_open_input_generator.py`,
`tasks/car/params/waymo.py`."""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401
from lingvo_tpu.models.car import breakdown_metric, waymo_input


def _WriteFrames(path, num_frames=24, seed=0):
  """Tiny Waymo-format fixture: vehicles on a ground plane with points
  concentrated inside the boxes so the detector has signal."""
  rng = np.random.RandomState(seed)
  with open(path, "w") as f:
    for _ in range(num_frames):
      labels = []
      pts = []
      for _ in range(rng.randint(1, 4)):
        cx, cy = rng.uniform(-12, 12, 2)
        heading = rng.uniform(-math.pi, math.pi)
        box = [cx, cy, 1.0, 4.5, 2.0, 1.6, heading]
        n_in = rng.randint(3, 30)
        labels.append({
            "box": [round(v, 3) for v in box],
            "type": "TYPE_VEHICLE",
            "num_points": n_in,
            "speed": [round(rng.uniform(-5, 5), 2), 0.0],
        })
        for _ in range(n_in):
          px = cx + rng.uniform(-2, 2)
          py = cy + rng.uniform(-1, 1)
          pts.append([round(px, 3), round(py, 3),
                      round(rng.uniform(0.2, 1.8), 3),
                      round(rng.uniform(0, 1), 3),
                      round(rng.uniform(0, 1), 3)])
      for _ in range(40):  # background clutter
        pts.append([round(rng.uniform(-15, 15), 3),
                    round(rng.uniform(-15, 15), 3),
                    round(rng.uniform(0, 3), 3), 0.1, 0.1])
      f.write(json.dumps({
          "points": pts, "labels": labels,
          "run_segment": "seg-0", "time_of_day": "Day",
          "weather": "sunny"}) + "\n")
    f.write("not json\n")                    # malformed: dropped
    f.write(json.dumps({"points": [[1, 2]]}) + "\n")  # bad dims: dropped


class TestWaymoInput:

  def test_parse_label(self):
    lab = {"box": [1, 2, 0.5, 4, 2, 1.5, 0.3], "type": "TYPE_VEHICLE",
           "num_points": 3, "speed": [1.5, -0.5]}
    box, cls, npts, diff, speed = waymo_input.ParseWaymoLabel(lab, 4)
    assert cls == 1 and npts == 3
    assert diff == 2  # <= 5 points derives LEVEL_2
    np.testing.assert_allclose(speed, [1.5, -0.5])
    # out-of-split class dropped
    assert waymo_input.ParseWaymoLabel(
        {"box": [1, 2, 0.5, 4, 2, 1.5, 0.3], "type": "TYPE_SIGN"}, 1) is None

  def test_file_input_emits_views_and_extras(self, tmp_path):
    path = tmp_path / "frames.jsonl"
    _WriteFrames(path)
    p = waymo_input.WaymoSceneInputGenerator.Params().Set(
        batch_size=2, file_pattern=f"text:{path}", num_classes=1,
        max_points=128, max_objects=8, grid_size=8,
        grid_range_x=(-16.0, 16.0), grid_range_y=(-16.0, 16.0),
        max_pillars=32, points_per_pillar=8)
    gen = p.Instantiate()
    b = gen.GetPreprocessedInputBatch()
    assert b.lasers.shape == (2, 128, 5)  # 5-dim waymo points
    assert b.pillar_points.shape == (2, 32, 8, 5)
    assert b.gt_boxes.shape == (2, 8, 7)
    assert b.gt_difficulty.shape == (2, 8)
    assert b.gt_speed.shape == (2, 8, 2)
    assert (b.cls_targets >= 0).all()
    # at least one frame has a vehicle target on the grid
    assert (np.asarray(b.reg_weights).sum() > 0)

  def test_multi_laser_record(self):
    p = waymo_input.WaymoSceneInputGenerator.Params().Set(
        batch_size=2, file_pattern="text:/dev/null", num_classes=4,
        max_points=16, max_objects=4, grid_size=4,
        grid_range_x=(-8.0, 8.0), grid_range_y=(-8.0, 8.0),
        max_pillars=8, points_per_pillar=4)
    gen = p.Instantiate()
    rec = json.dumps({
        "lasers": {"TOP": [[1, 1, 0.5, 0.2, 0.1]],
                   "REAR": [[-2, 0, 0.5, 0.3, 0.2]]},
        "labels": []}).encode()
    ex = gen.ProcessRecord(rec)
    assert int((1.0 - ex.laser_paddings).sum()) == 2
    assert gen.ProcessRecord(b"[1,2]") is None
    assert gen.ProcessRecord(b'{"points": [[1]]}') is None


class TestWaymoPointPillars:

  def test_trains_and_decodes(self, tmp_path):
    path = tmp_path / "frames.jsonl"
    _WriteFrames(path)

    mp = model_registry.GetParams("car.waymo.PointPillarsWaymoTiny",
                                  "Train")
    mp.input.file_pattern = f"text:{path}"
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(50):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert losses[-1] < 0.8 * losses[0], (losses[0], losses[-1])

    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    dec = jax.jit(task.Decode)(state.theta, batch)
    m = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(jax.tree_util.tree_map(np.asarray, dec), m)
    res = task.DecodeFinalize(m)
    assert "cell_precision" in res and "cell_recall" in res


class TestDeepFusion:

  def _frames_with_camera(self, path, num_frames=24):
    """Fixture where the camera view carries the object layout too."""
    rng = np.random.RandomState(3)
    import json as _json
    with open(path, "w") as f:
      for _ in range(num_frames):
        labels, pts = [], []
        cam = np.zeros((32, 32, 3), np.float32)
        for _ in range(rng.randint(1, 3)):
          cx, cy = rng.uniform(-12, 12, 2)
          labels.append({"box": [float(cx), float(cy), 1.0, 4.5, 2.0,
                                 1.6, 0.0],
                         "type": 1, "num_points": 10})
          for _ in range(10):
            pts.append([float(cx + rng.uniform(-2, 2)),
                        float(cy + rng.uniform(-1, 1)),
                        1.0, 0.5, 0.5])
          px = int((cx + 16) / 32 * 31)
          py = int((cy + 16) / 32 * 31)
          cam[py, px] = 1.0
        f.write(_json.dumps({
            "points": pts, "labels": labels,
            "camera": cam.reshape(-1).round(2).tolist()}) + "\n")

  @pytest.mark.slow
  def test_fusion_trains_and_uses_camera(self, tmp_path):
    path = tmp_path / "frames.jsonl"
    self._frames_with_camera(path)
    mp = model_registry.GetParams("car.waymo.DeepFusionWaymoTiny", "Train")
    mp.input.file_pattern = f"text:{path}"
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    assert batch.camera.shape == (2, 32, 32, 3)

    # camera input influences predictions (fusion is live, not a no-op)
    preds = jax.jit(task.ComputePredictions)(state.theta, batch)
    batch2 = batch.DeepCopy()
    batch2.camera = batch2.camera + 1.0
    preds2 = jax.jit(task.ComputePredictions)(state.theta, batch2)
    assert not np.allclose(np.asarray(preds.cls_logits),
                           np.asarray(preds2.cls_logits))

    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(50):
      b = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, b)
      losses.append(float(out.metrics.loss[0]))
    assert losses[-1] < 0.8 * losses[0], (losses[0], losses[-1])
    # camera tower receives gradient
    grads = jax.grad(lambda th: task.ComputeLoss(
        th, task.ComputePredictions(th, batch), batch)[0].loss[0])(
            state.theta)
    gsum = float(sum(jnp.sum(jnp.abs(g)) for g in
                     jax.tree.leaves(grads.camera_featurizer)))
    assert gsum > 0


class TestByDifficulty:

  def test_bins_by_difficulty_column(self):
    m = breakdown_metric.ByDifficulty()
    gt = np.array([[0, 0, 0, 4, 2, 1.5, 0.0, 1],     # LEVEL_1
                   [20, 20, 0, 4, 2, 1.5, 0.0, 2]])  # LEVEL_2
    pred = gt[:, :7].copy()
    m.Update(pred, np.array([0.9, 0.8]), gt,
             pred_classes=np.array([1, 1]), gt_classes=np.array([1, 1]))
    vals = m.value
    assert vals["level_1"] == 1.0 and vals["level_2"] == 1.0
