"""MFU cross-check: the analytic FLOPs formula used by bench.py must agree
with XLA's own cost analysis of the compiled train step (VERDICT r3 weak #3
— previously reported side by side but never asserted).

Config is 2 unrolled layers (no scan: `lax.scan` bodies are counted once by
cost analysis, which would undercount a repeated stack) with matmul-dominant
geometry, so the 6ND + softmax + attention formula should match XLA's count
to within 10%.
"""

import jax
import jax.numpy as jnp

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401
from lingvo_tpu.core import computation_cost, input_policy, py_utils


def _AnalyticTrainStepFlops(task_p, n_params, batch):
  """bench.py's formula (bench.py _BenchDense): 6*(N-emb)*tokens matmul +
  6*emb*tokens softmax + 12*B*T^2*D*L attention."""
  b, t = batch.ids.shape
  tokens = b * t
  emb_params = task_p.vocab_size * task_p.model_dim
  matmul = 6.0 * (n_params - emb_params) * tokens
  softmax = 6.0 * emb_params * tokens
  attn = 12.0 * b * t * t * task_p.model_dim * task_p.num_layers
  return matmul + softmax + attn


class TestMfuCrossCheck:

  def test_xla_flops_match_analytic_within_10pct(self):
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    mp.task.model_dim = 256
    mp.task.num_layers = 2
    mp.task.num_heads = 4
    mp.task.hidden_dim = 1024
    mp.task.vocab_size = 1024
    mp.task.input.vocab_size = 1024
    mp.task.input.seq_len = 128
    mp.task.input.batch_size = 2
    mp.task.use_repeat_layer = False  # unrolled: cost analysis sees all L
    mp.task.remat_policy = "none"

    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = input_policy.Instantiate(mp.input)
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)

    n_params = py_utils.CountParams(state.theta)
    analytic = _AnalyticTrainStepFlops(mp.task, n_params, batch)

    analysis = computation_cost.TrainStepCost(task, state, batch)
    assert "flops" in analysis, f"cost_analysis has no flops: {analysis}"
    xla = float(analysis["flops"])

    # Matmul-dominant geometry: elementwise/optimizer overhead in the XLA
    # count and gather-vs-matmul embedding differences stay inside 10%.
    ratio = xla / analytic
    assert 0.9 <= ratio <= 1.1, (
        f"XLA flops {xla:.3g} vs analytic {analytic:.3g} (ratio "
        f"{ratio:.3f}) — the bench MFU formula has drifted")
