"""Multi-host (multi-process) distributed smoke test.

Exercises the control plane the reference runs over gRPC
(`trainer.py:256-278` tf.distribute.Server + cluster specs): two REAL
processes join via `cluster.InitDistributed` (jax.distributed), build a
global mesh spanning both hosts' devices, feed per-host batch shards
through `jax.make_array_from_process_local_data` (the InfeedContextScope
per-host-sharding equivalent, SURVEY §2.9), and run a jitted global-sum —
verifying cross-process collectives and that each host only touched its
own shard.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import numpy as np
import jax
import jax.numpy as jnp

from lingvo_tpu.core import cluster

pid = int(sys.argv[1])
port = sys.argv[2]
cluster.InitDistributed(coordinator_address=f"localhost:{port}",
                        num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()  # 2 local x 2 procs

from jax.sharding import Mesh, NamedSharding, PartitionSpec
mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))
sharding = NamedSharding(mesh, PartitionSpec("data"))

# per-host data: host p contributes rows filled with (p+1)
local = np.full((2, 3), float(pid + 1), np.float32)
global_arr = jax.make_array_from_process_local_data(sharding, local, (4, 3))

@jax.jit
def global_sum(x):
  return jnp.sum(x)

total = float(global_sum(global_arr))
# rows: host0 -> 2 rows of 1s, host1 -> 2 rows of 2s => sum = 2*3*1 + 2*3*2
assert total == 18.0, total
print(f"proc{pid} OK total={total}", flush=True)
"""


class TestMultiProcessDistributed:

  def test_two_process_psum(self, tmp_path):
    import socket
    with socket.socket() as s:
      s.bind(("", 0))
      port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env)
        for i in range(2)
    ]
    outs = []
    for p in procs:
      try:
        out, _ = p.communicate(timeout=180)
      except subprocess.TimeoutExpired:
        for q in procs:
          q.kill()
        pytest.fail("distributed workers hung")
      outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
      assert p.returncode == 0, f"proc{i} failed:\n{out[-2000:]}"
      assert f"proc{i} OK" in out
