"""Multi-host (multi-process) distributed smoke test.

Exercises the control plane the reference runs over gRPC
(`trainer.py:256-278` tf.distribute.Server + cluster specs): two REAL
processes join via `cluster.InitDistributed` (jax.distributed), build a
global mesh spanning both hosts' devices, feed per-host batch shards
through `jax.make_array_from_process_local_data` (the InfeedContextScope
per-host-sharding equivalent, SURVEY §2.9), and run a jitted global-sum —
verifying cross-process collectives and that each host only touched its
own shard.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import numpy as np
import jax
import jax.numpy as jnp

from lingvo_tpu.core import cluster

pid = int(sys.argv[1])
port = sys.argv[2]
cluster.InitDistributed(coordinator_address=f"localhost:{port}",
                        num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()  # 2 local x 2 procs

from jax.sharding import Mesh, NamedSharding, PartitionSpec
mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))
sharding = NamedSharding(mesh, PartitionSpec("data"))

# per-host data: host p contributes rows filled with (p+1)
local = np.full((2, 3), float(pid + 1), np.float32)
global_arr = jax.make_array_from_process_local_data(sharding, local, (4, 3))

@jax.jit
def global_sum(x):
  return jnp.sum(x)

total = float(global_sum(global_arr))
# rows: host0 -> 2 rows of 1s, host1 -> 2 rows of 2s => sum = 2*3*1 + 2*3*2
assert total == 18.0, total
print(f"proc{pid} OK total={total}", flush=True)
"""


_TRAIN_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import numpy as np
import jax
import jax.numpy as jnp

from lingvo_tpu.core import checkpointer as ckpt_lib
from lingvo_tpu.core import cluster
from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401
from lingvo_tpu.parallel import mesh as mesh_lib

pid = int(sys.argv[1])
port = sys.argv[2]
workdir = sys.argv[3]
cluster.InitDistributed(coordinator_address=f"localhost:{port}",
                        num_processes=2, process_id=pid)
assert jax.process_count() == 2 and jax.device_count() == 4

from jax.sharding import Mesh, NamedSharding, PartitionSpec
mesh = Mesh(np.asarray(jax.devices()).reshape(4), ("data",))

mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                              "Train")
mp.task.input = mp.input
mp.task.input.batch_size = 4   # global; 2 rows per process
task = mp.task.Instantiate()
task.FinalizePaths()
state = task.CreateTrainState(jax.random.PRNGKey(0))
shardings = mesh_lib.TrainStateShardings(mesh, task, state,
                                         fsdp_axis="data")
state = jax.device_put(state, shardings)

gen = mp.task.input.Set(seed=100 + pid).Instantiate()
data_sharding = NamedSharding(mesh, PartitionSpec("data"))

def GlobalBatch():
  # per-host input shard -> global array (InfeedContextScope equivalent)
  local = gen.GetPreprocessedInputBatch()
  half = jax.tree_util.tree_map(lambda a: np.asarray(a)[:2], dict(local))
  return local.Pack([
      jax.make_array_from_process_local_data(
          data_sharding, leaf, (4,) + leaf.shape[1:])
      for leaf in jax.tree_util.tree_leaves(half)])

step_fn = jax.jit(task.TrainStep, donate_argnums=(0,))
loss = None
for _ in range(3):
  state, out = step_fn(state, GlobalBatch())
  loss = float(out.metrics.loss[0])

checksum = float(sum(jnp.sum(l.astype(jnp.float32))
                     for l in jax.tree_util.tree_leaves(state.theta)))
ckpt = ckpt_lib.Checkpointer(os.path.join(workdir, "ckpt"),
                             async_save=False)
assert ckpt.Save(3, state, force=True)
ckpt.WaitUntilFinished()
if pid == 0:
  with open(os.path.join(workdir, "summary.json"), "w") as f:
    json.dump({"checksum": checksum, "loss": loss}, f)
print(f"proc{pid} OK loss={loss}", flush=True)
"""

_RESTORE_WORKER = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
# DIFFERENT topology: one process, 8 devices, 2D mesh
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import numpy as np
import jax
import jax.numpy as jnp

from lingvo_tpu.core import checkpointer as ckpt_lib
from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401
from lingvo_tpu.parallel import mesh as mesh_lib

workdir = sys.argv[1]
mesh = mesh_lib.MakeMesh({"data": 2, "model": 4})

mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                              "Train")
mp.task.input = mp.input
mp.task.input.batch_size = 4
task = mp.task.Instantiate()
task.FinalizePaths()

abstract = jax.eval_shape(task.CreateTrainState, jax.random.PRNGKey(0))
shardings = mesh_lib.TrainStateShardings(mesh, task, abstract,
                                         fsdp_axis="data")
template = jax.tree_util.tree_map(
    lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
    abstract, shardings)

ckpt = ckpt_lib.Checkpointer(os.path.join(workdir, "ckpt"))
state, start_step = ckpt.Restore(template)
assert start_step == 3, start_step

checksum = float(sum(jnp.sum(l.astype(jnp.float32))
                     for l in jax.tree_util.tree_leaves(state.theta)))
saved = json.load(open(os.path.join(workdir, "summary.json")))
np.testing.assert_allclose(checksum, saved["checksum"], rtol=1e-5)

# training continues on the new topology
gen = mp.task.input.Instantiate()
batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
state, out = jax.jit(task.TrainStep, donate_argnums=(0,))(state, batch)
assert int(state.step) == 4
assert np.isfinite(float(out.metrics.loss[0]))
print("restore OK", flush=True)
"""


_BACKEND_LIMIT = "Multiprocess computations aren't implemented on the CPU backend"
# set once a worker pair hits the limitation: later tests skip without
# paying the multi-second subprocess launch just to rediscover it
_BACKEND_UNSUPPORTED = False


def _CleanEnv():
  env = dict(os.environ)
  env.pop("PYTHONPATH", None)
  env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
  return env


def _RunPair(script_path, extra_args, timeout=420):
  global _BACKEND_UNSUPPORTED
  if _BACKEND_UNSUPPORTED:
    pytest.skip("CPU backend lacks multiprocess collectives "
                "(jaxlib build limitation)")
  import socket
  with socket.socket() as s:
    s.bind(("", 0))
    port = s.getsockname()[1]
  procs = [
      subprocess.Popen(
          [sys.executable, str(script_path), str(i), str(port)] + extra_args,
          stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
          env=_CleanEnv())
      for i in range(2)
  ]
  outs = []
  for p in procs:
    try:
      out, _ = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
      for q in procs:
        q.kill()
      pytest.fail("distributed workers hung")
    outs.append(out)
  for i, (p, out) in enumerate(zip(procs, outs)):
    if p.returncode != 0 and _BACKEND_LIMIT in out:
      # jaxlib built without cross-process CPU collectives: the control
      # plane (jax.distributed handshake, device enumeration) worked, but
      # the data plane can't run on this build. Environmental, not a repo
      # regression — see ROADMAP "known environment limits".
      _BACKEND_UNSUPPORTED = True
      pytest.skip("CPU backend lacks multiprocess collectives "
                  "(jaxlib build limitation)")
    assert p.returncode == 0, f"proc{i} failed:\n{out[-3000:]}"
    assert f"proc{i} OK" in out
  return outs


_CLI_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
pid, port, logdir = sys.argv[1], sys.argv[2], sys.argv[3]
mode = sys.argv[4] if len(sys.argv) > 4 else "train"
from lingvo_tpu import trainer
args = [
    "--model=lm.synthetic_packed_input.DenseLmTiny",
    f"--logdir={logdir}", f"--mode={mode}", "--max_steps=3",
    f"--coordinator_address=localhost:{port}",
    "--num_processes=2", f"--process_id={pid}",
]
rc = trainer.main(args)  # default --job takes the inline path for eval
assert rc == 0, rc
print(f"proc{pid} OK", flush=True)
"""


class TestMultiProcessDistributed:

  def test_two_process_psum(self, tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    _RunPair(script, [])

  def test_trainer_cli_two_process_train(self, tmp_path):
    """The full CLI path under 2 processes (trainer -> executor ->
    programs): distributed init, per-host input shards joined into global
    batches over the auto data mesh, collective checkpoint save, and
    single-writer logdir artifacts."""
    script = tmp_path / "cli_worker.py"
    script.write_text(_CLI_WORKER)
    logdir = tmp_path / "run"
    _RunPair(script, [str(logdir)])
    assert (logdir / "train" / "FINISHED").exists()
    assert (logdir / "trainer_params.txt").exists()
    assert (logdir / "metrics.jsonl").exists()
    import orbax.checkpoint as ocp
    mgr = ocp.CheckpointManager(str(logdir / "train"))
    assert mgr.latest_step() is not None
    mgr.close()
    # 2-process --mode=eval against the trained logdir: restored state is
    # placed onto the mesh, finite eval streams coordinate across hosts
    _RunPair(script, [str(logdir), "eval"])
    # eval actually ran and its single writer produced the artifact
    assert (logdir / "eval_test" / "summaries.jsonl").exists()

  def test_train_save_restore_new_topology(self, tmp_path):
    """E2E multi-host hardening (VERDICT r3 next #5): 2-process FSDP
    train -> orbax save -> restore single-process on an 8-device 2D mesh
    (resharded) -> training continues. Ref executor.py:247-294 semantics +
    the orbax different-topology restore trap."""
    script = tmp_path / "train_worker.py"
    script.write_text(_TRAIN_WORKER)
    _RunPair(script, [str(tmp_path)])

    restore = tmp_path / "restore_worker.py"
    restore.write_text(_RESTORE_WORKER)
    proc = subprocess.run(
        [sys.executable, str(restore), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_CleanEnv(), timeout=420)
    assert proc.returncode == 0, proc.stdout[-3000:]
    assert "restore OK" in proc.stdout
