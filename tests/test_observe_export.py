"""Fleet-facing telemetry (observe/export|goodput|watchdog|aggregate):
endpoint exposition + parse-back, goodput/MFU accounting, stall watchdog
trips, fleet merge, and the live-engine/executor endpoint integration."""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from lingvo_tpu import observe
from lingvo_tpu.observe import aggregate
from lingvo_tpu.observe import export as export_lib
from lingvo_tpu.observe import goodput as goodput_lib
from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.observe import watchdog as watchdog_lib


def _Get(url, timeout=10.0):
  """(status code, body str) — 4xx/5xx don't raise."""
  try:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
      return resp.status, resp.read().decode("utf-8")
  except urllib.error.HTTPError as e:
    return e.code, e.read().decode("utf-8")


def _ParsePrometheus(text):
  """Prometheus text -> ({name: value}, {name: {label_part: value}})."""
  plain, labeled = {}, {}
  for line in text.splitlines():
    if not line or line.startswith("#"):
      continue
    name_part, value = line.rsplit(" ", 1)
    if "{" in name_part:
      name, labels = name_part.split("{", 1)
      labeled.setdefault(name, {})[labels.rstrip("}")] = value
    else:
      plain[name_part] = float(value)
  return plain, labeled


class _FakeClock:
  def __init__(self, t=100.0):
    self.t = t

  def __call__(self):
    return self.t


class _FakeProfileWindow:
  """ProfileWindow stand-in with the same arm/tick/close surface — the
  real one drives the (seconds-per-start/stop, process-singleton) jax
  profiler, which test_observe.py covers."""

  def __init__(self, logdir, steps=0):
    self.logdir, self.steps_remaining, self.stopped = logdir, steps, False

  def Start(self):
    return self

  def Stop(self):
    self.stopped = True

  def StepDone(self):
    self.steps_remaining -= 1
    return self.steps_remaining <= 0


# -- Prometheus exposition ----------------------------------------------------


class TestPrometheusText:

  def test_metric_name_sanitization(self):
    assert export_lib.MetricName("serving/ttft_s") == "serving_ttft_s"
    assert export_lib.MetricName("a b-c.d") == "a_b_c_d"
    assert export_lib.MetricName("0weird") == "_0weird"

  def test_parse_back_counters_gauges_histograms_strings(self):
    reg = observe.MetricsRegistry("t")
    reg.Counter("serving/steps").Inc(7)
    reg.Gauge("serving/queue_depth").Set(3)
    reg.Gauge("serving/kv_dtype").Set("int8")
    reg.SectionFn("scheduler", lambda: {"active": 2, "paged": True})
    h = reg.Histogram("serving/ttft_s", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
      h.Observe(v)

    text = export_lib.PrometheusText(reg.Snapshot(), reg.Describe())
    plain, labeled = _ParsePrometheus(text)

    assert plain["serving_steps"] == 7
    assert plain["serving_queue_depth"] == 3
    assert plain["scheduler_active"] == 2
    assert plain["scheduler_paged"] == 1          # bool -> 0/1 gauge
    assert labeled["serving_kv_dtype_info"] == {'value="int8"': "1"}
    # histogram: cumulative buckets, +Inf == count
    b = labeled["serving_ttft_s_bucket"]
    assert b['le="0.1"'] == "1"
    assert b['le="1.0"'] == "3"
    assert b['le="10.0"'] == "4"
    assert b['le="+Inf"'] == "5"
    assert plain["serving_ttft_s_count"] == 5
    assert plain["serving_ttft_s_sum"] == pytest.approx(56.05)
    # TYPE lines carry the Describe() kind
    assert "# TYPE serving_steps counter" in text
    assert "# TYPE serving_queue_depth gauge" in text

  def test_snapshot_only_keys_fall_back_to_gauge(self):
    # a section key absent from Describe() (e.g. a merged snapshot)
    assert export_lib.KindOf("nope/x", {}) == "gauge"
    assert export_lib.KindOf("s/x", {"s": "section"}) == "gauge"
    assert export_lib.KindOf("c", {"c": "counter"}) == "counter"

  def test_build_info_matches_schema(self):
    info = export_lib.BuildInfo()
    assert set(info) == set(observe_schema.BUILD_INFO_KEYS)
    assert info["jax_version"] == jax.__version__


class TestHistogramQuantiles:

  def test_interpolated_quantiles(self):
    reg = observe.MetricsRegistry("t")
    h = reg.Histogram("lat", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
      h.Observe(v)
    q = observe.HistogramQuantiles(reg.Snapshot()["lat"], qs=(0.5, 0.99))
    # rank 2.5 lands in bucket (0.1, 1.0] holding obs #2..3:
    # 0.1 + 0.9 * (2.5 - 1) / 2 = 0.775
    assert q[0.5] == pytest.approx(0.775)
    assert q[0.99] == pytest.approx(10.0)   # overflow clamps to top bound

  def test_empty_histogram(self):
    reg = observe.MetricsRegistry("t")
    reg.Histogram("lat", bounds=(1.0,))
    q = observe.HistogramQuantiles(reg.Snapshot()["lat"])
    assert q == {0.5: 0.0, 0.99: 0.0}

  def test_summary_writer_emits_quantiles(self, tmp_path):
    from lingvo_tpu.core import summary_utils
    reg = observe.MetricsRegistry("t")
    h = reg.Histogram("lat", bounds=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
      h.Observe(v)
    w = summary_utils.SummaryWriter(str(tmp_path), enabled=False)
    written = {}
    w.Scalars = lambda values, step, prefix="": written.update(values)
    w.FromRegistry(reg, step=1)
    assert written["lat/count"] == 5
    assert written["lat/p50"] == pytest.approx(0.775)
    assert written["lat/p99"] == pytest.approx(10.0)


# -- StatusServer endpoints ---------------------------------------------------


class TestStatusServer:

  def test_endpoints_roundtrip(self):
    reg = observe.MetricsRegistry("t")
    reg.Counter("serving/steps").Inc(3)
    srv = export_lib.StatusServer(
        0, registry=reg, name="unit",
        statusz_fn=lambda: {"compile": {"step": {"calls": 1}}}).Start()
    try:
      code, body = _Get(srv.Url("/metrics"))
      assert code == 200
      plain, _ = _ParsePrometheus(body)
      assert plain["serving_steps"] == 3

      code, body = _Get(srv.Url("/statusz"))
      assert code == 200
      doc = observe_schema.ValidateStatusz(json.loads(body))
      assert doc["name"] == "unit"
      assert doc["snapshot"]["serving/steps"] == 3
      assert doc["stats"]["compile"]["step"]["calls"] == 1

      assert _Get(srv.Url("/traces"))[0] == 404     # no TraceRecorder
      code, body = _Get(srv.Url("/healthz"))
      assert code == 200 and json.loads(body) == {
          "healthy": True, "watchdog": False}
      assert _Get(srv.Url("/nope"))[0] == 404
    finally:
      srv.Stop()

  def test_statusz_fn_error_returns_500_not_crash(self):
    srv = export_lib.StatusServer(
        0, registry=observe.MetricsRegistry("t"),
        statusz_fn=lambda: 1 / 0).Start()
    try:
      code, body = _Get(srv.Url("/statusz"))
      assert code == 500 and "ZeroDivisionError" in body
      assert _Get(srv.Url("/metrics"))[0] == 200    # server survives
    finally:
      srv.Stop()

  def test_healthz_flips_on_stall_and_arms_capture(self, tmp_path,
                                                   monkeypatch):
    # stub the flight recorder: the real jax profiler costs seconds per
    # start/stop and is covered by test_observe.py; this test owns the
    # watchdog arm/tick/close lifecycle only
    monkeypatch.setattr(watchdog_lib.profile_lib, "ProfileWindow",
                        _FakeProfileWindow)
    clock = _FakeClock()
    reg = observe.MetricsRegistry("t")
    wd = watchdog_lib.StallWatchdog(
        reg, min_interval_s=0.1, stall_factor=10.0,
        capture_logdir=str(tmp_path), clock=clock)
    srv = export_lib.StatusServer(0, registry=reg, watchdog=wd).Start()
    try:
      for _ in range(3):
        clock.t += 0.2
        wd.Beat()
      assert _Get(srv.Url("/healthz"))[0] == 200
      clock.t += 100.0   # the loop hangs; only the scrape thread runs
      code, body = _Get(srv.Url("/healthz"))
      assert code == 503
      stats = json.loads(body)
      assert stats["healthy"] is False
      assert "no_heartbeat" in stats["tripped"]
      assert stats["capture_armed"] is True       # flight recorder armed
      assert reg.Snapshot()["watchdog/trips_total"] == 1
      assert reg.Snapshot()["watchdog/trips_no_heartbeat"] == 1
      # two normal-pace beats clear it (the first beat's 100s step is
      # itself a genuine step_regression)
      clock.t += 0.2
      wd.Beat()
      clock.t += 0.2
      wd.Beat()
      assert _Get(srv.Url("/healthz"))[0] == 200
      assert reg.Snapshot()["watchdog/trips_total"] == 2  # once per episode
    finally:
      srv.Stop()
      if wd.capture is not None:   # close the still-armed flight recorder:
        wd.capture.Stop()          # the jax profiler is a process singleton


# -- goodput + MFU ------------------------------------------------------------


class TestGoodput:

  def test_buckets_sum_to_wall(self):
    clock = _FakeClock(0.0)
    reg = observe.MetricsRegistry("t")
    gp = goodput_lib.GoodputTracker(registry=reg, clock=clock)
    with gp.Track("compile"):
      clock.t += 3.0
    with gp.Track("step"):
      clock.t += 6.0
    gp.Add("infeed_wait", 1.0)   # attributed without advancing the clock
    clock.t += 3.0               # unaccounted wall -> lands in `other`
    stats = gp.Stats()
    assert set(stats) == set(observe_schema.GOODPUT_STATS_KEYS)
    assert stats["compile_s"] == pytest.approx(3.0)
    assert stats["step_s"] == pytest.approx(6.0)
    assert stats["infeed_wait_s"] == pytest.approx(1.0)
    assert stats["other_s"] == pytest.approx(2.0)   # 10 accounted, 12 wall
    assert stats["wall_s"] == pytest.approx(12.0)
    bucket_sum = sum(stats[f"{b}_s"] for b in observe_schema.GOODPUT_BUCKETS)
    assert bucket_sum == pytest.approx(stats["wall_s"])
    assert stats["productive_ratio"] == pytest.approx(0.5)
    # registered as a lazy section
    assert reg.Snapshot()["goodput/step_s"] == pytest.approx(6.0)

  def test_unknown_bucket_asserts(self):
    gp = goodput_lib.GoodputTracker(clock=_FakeClock())
    with pytest.raises(AssertionError):
      gp.Add("lunch", 1.0)

  def test_publish_mfu(self):
    reg = observe.MetricsRegistry("t")
    reg.Gauge("train/train_steps_per_second").Set(2.0)
    goodput_lib.PublishMfu(reg, flops_per_step=25.0, peak_flops=100.0)
    snap = reg.Snapshot()
    assert snap["train/flops_per_step"] == 25.0
    assert snap["train/mfu"] == pytest.approx(0.5)   # 25*2/100
    reg.Gauge("train/train_steps_per_second").Set(None)  # not yet tracked
    assert reg.Snapshot()["train/mfu"] == 0.0

  def test_track_excluding_compile(self):
    clock = _FakeClock(0.0)
    gp = goodput_lib.GoodputTracker(clock=clock)
    with gp.TrackExcludingCompile("step"):
      clock.t += 5.0
      gp.Add("compile", 2.0)   # a lazy jit compile observed mid-window
    stats = gp.Stats()
    assert stats["step_s"] == pytest.approx(3.0)   # 5 wall - 2 compile
    assert stats["compile_s"] == pytest.approx(2.0)
    # more compile than wall (clock skew) clamps at zero, never negative
    with gp.TrackExcludingCompile("eval"):
      clock.t += 1.0
      gp.Add("compile", 4.0)
    assert gp.Stats()["eval_s"] == 0.0

  def test_jax_compile_listener_feeds_global_tracker(self):
    saved = goodput_lib._TRACKER
    gp = goodput_lib.GoodputTracker(clock=_FakeClock())
    goodput_lib._TRACKER = gp
    try:
      goodput_lib._OnJaxEvent(
          "/jax/core/compile/backend_compile_duration", 2.5)
      goodput_lib._OnJaxEvent("/jax/core/something_else", 9.0)
      assert gp.Stats()["compile_s"] == pytest.approx(2.5)
    finally:
      goodput_lib._TRACKER = saved

  def test_peak_flops_lookup(self):
    assert goodput_lib.PeakFlopsPerDevice("TPU v4") == 275e12
    assert goodput_lib.PeakFlopsPerDevice("TPU v5p slice") == 459e12
    assert (goodput_lib.PeakFlopsPerDevice("weird accelerator")
            == goodput_lib.DEFAULT_PEAK_FLOPS)


class TestWatchdog:

  def test_close_drops_armed_capture(self, tmp_path, monkeypatch):
    monkeypatch.setattr(watchdog_lib.profile_lib, "ProfileWindow",
                        _FakeProfileWindow)
    clock = _FakeClock()
    wd = watchdog_lib.StallWatchdog(
        min_interval_s=0.1, capture_logdir=str(tmp_path), clock=clock)
    for _ in range(3):
      clock.t += 0.2
      wd.Beat()
    clock.t += 100.0
    assert wd.Check()["healthy"] is False
    armed = wd.capture
    assert armed is not None               # flight recorder armed
    wd.Close()                             # teardown mid-window
    assert wd.capture is None and armed.stopped   # singleton released

  def test_step_regression_and_recovery(self):
    clock = _FakeClock()
    wd = watchdog_lib.StallWatchdog(clock=clock, regression_factor=4.0)
    for _ in range(5):
      wd.Beat(step_time_s=0.2)
    assert wd.Check()["healthy"] is True
    wd.Beat(step_time_s=2.0)   # 10x the EMA
    stats = wd.Check()
    assert stats["healthy"] is False and "step_regression" in stats["tripped"]
    wd.Beat(step_time_s=0.2)
    assert wd.Check()["healthy"] is True

  def test_queue_stall_trip_and_drain(self):
    clock = _FakeClock()
    wd = watchdog_lib.StallWatchdog(clock=clock, queue_window=3)
    for depth, retired in ((1, 0), (3, 0), (6, 0)):
      wd.ObserveQueue(depth, retired)
    stats = wd.Check()
    assert stats["healthy"] is False and "queue_stall" in stats["tripped"]
    wd.ObserveQueue(2, 5)   # retirement resumed
    assert wd.Check()["healthy"] is True

  def test_idle_refresh_is_not_a_stall(self):
    # a loop with no work keeps liveness fresh via Idle() without
    # polluting the step-time EMA
    clock = _FakeClock()
    wd = watchdog_lib.StallWatchdog(clock=clock, stall_factor=10.0,
                                    min_interval_s=1.0)
    wd.Beat(step_time_s=0.01)
    ema = wd.Stats()["step_ema_s"]
    for _ in range(40):   # 200s of idle, way past the 10s trip window
      clock.t += 5.0
      wd.Idle()
    stats = wd.Check()
    assert stats["healthy"] is True and stats["trips"] == 0
    assert stats["step_ema_s"] == ema   # idle never fed the EMA
    # but a hung loop (no Idle ticks either) still trips
    clock.t += 50.0
    stats = wd.Check()
    assert stats["healthy"] is False and "no_heartbeat" in stats["tripped"]

  def test_stats_keys_match_schema(self):
    wd = watchdog_lib.StallWatchdog(clock=_FakeClock())
    assert set(wd.Stats()) == set(observe_schema.WATCHDOG_STATS_KEYS)


# -- fleet aggregation --------------------------------------------------------


def _Replica(label, tokens, depth):
  reg = observe.MetricsRegistry(label)
  reg.Counter("serving/tokens_emitted").Inc(tokens)
  reg.SectionFn("scheduler", lambda: {"queue_depth": depth})
  h = reg.Histogram("serving/ttft_s", bounds=(0.1, 1.0))
  for _ in range(tokens):
    h.Observe(0.5)
  return label, reg.Snapshot(), reg.Describe()


class TestAggregate:

  def test_merge_snapshots(self):
    merged = aggregate.MergeSnapshots([_Replica("a", 5, 1),
                                       _Replica("b", 7, 4)])
    assert merged["replicas"] == ["a", "b"]
    assert merged["fleet"]["serving/tokens_emitted"] == 12   # counters sum
    hist = merged["fleet"]["serving/ttft_s"]
    assert hist["count"] == 12 and hist["counts"][1] == 12   # buckets merge
    # gauges/sections stay per-replica
    assert merged["per_replica"]["a"]["scheduler/queue_depth"] == 1
    assert merged["per_replica"]["b"]["scheduler/queue_depth"] == 4

  def test_incompatible_hist_bounds_keep_larger(self):
    a = {"count": 9, "sum": 1.0, "mean": 0.1, "bounds": [1.0],
         "counts": [9, 0]}
    b = {"count": 2, "sum": 1.0, "mean": 0.5, "bounds": [2.0],
         "counts": [2, 0]}
    assert aggregate._MergeHist(a, b)["count"] == 9

  def test_least_loaded_and_statusz_merge(self):
    docs = {}
    for label, tokens, depth in (("a", 5, 1), ("b", 7, 4)):
      _, snap, desc = _Replica(label, tokens, depth)
      docs[label] = {"name": label, "build": export_lib.BuildInfo(),
                     "snapshot": snap, "describe": desc, "stats": None}
    docs["dead"] = {"error": "URLError: refused"}
    assert aggregate.LeastLoaded(docs) == "a"
    merged = aggregate.MergeStatusz(docs)     # error replica skipped
    assert merged["replicas"] == ["a", "b"]
    assert aggregate.LeastLoaded({"dead": {"error": "x"}}) is None

  def test_fleet_report_tool(self):
    from tools import fleet_report
    docs = {}
    for label, tokens, depth in (("a", 5, 1), ("b", 7, 4)):
      _, snap, desc = _Replica(label, tokens, depth)
      docs[label] = {"name": label, "build": export_lib.BuildInfo(),
                     "snapshot": snap, "describe": desc, "stats": None}
    docs["c"] = {"error": "URLError: connection refused"}
    report = fleet_report.FleetReport(docs)
    assert "2 live, 1 unreachable" in report
    assert "serving/tokens_emitted" in report and "12" in report
    assert "least-loaded replica" in report and "a" in report
    assert "DOWN c" in report
    assert "jain fairness" in report

  def test_fleet_report_fairness_and_utilization(self):
    from tools import fleet_report
    assert fleet_report.JainFairness([]) == 1.0
    assert fleet_report.JainFairness([0, 0]) == 1.0      # idle fleet: fair
    assert fleet_report.JainFairness([5, 5, 5]) == 1.0
    assert abs(fleet_report.JainFairness([9, 0, 0]) - 1 / 3) < 1e-9
    docs = {
        "a": {"snapshot": {"serving/tokens_emitted": 30,
                           "serving/prompt_tokens": 90,
                           "scheduler/queue_depth": 2}},
        "b": {"snapshot": {"serving/tokens_emitted": 10,
                           "serving/prompt_tokens": 10}},
        "dead": {"error": "URLError: refused"},          # never a row
    }
    util = fleet_report.Utilization(docs)
    assert set(util["per_replica"]) == {"a", "b"}
    assert util["per_replica"]["a"]["decode_share"] == 0.75
    assert util["per_replica"]["b"]["prefill_share"] == 0.1
    assert util["per_replica"]["b"]["queue_depth"] == 0  # missing -> 0
    assert abs(util["decode_fairness"]
               - fleet_report.JainFairness([30, 10])) < 1e-9
    assert util["prefill_fairness"] < util["decode_fairness"]  # 90/10 skew

  def test_scrape_validates_against_live_server(self):
    reg = observe.MetricsRegistry("t")
    reg.Counter("serving/steps").Inc(1)
    srv = export_lib.StatusServer(0, registry=reg, name="scrapee").Start()
    try:
      doc = aggregate.Scrape(f"{srv.host}:{srv.port}")   # bare host:port
      assert doc["name"] == "scrapee"
      docs = aggregate.ScrapeAll([srv.Url("/statusz"),
                                  "127.0.0.1:1/statusz"])
      assert sum("error" in d for d in docs.values()) == 1
    finally:
      srv.Stop()


class TestTraceReportMerged:

  def _Trace(self, base_ms):
    reqs = {str(i): {"slot": i, "prompt_tokens": 3, "tokens": 4, "pages": 2,
                     "queue_wait_s": 0.001, "ttft_s": base_ms * 1e-3,
                     "tpot_s": base_ms * 1e-3 / 4,
                     "total_s": base_ms * 2e-3, "finish_reason": "length"}
            for i in range(1, 4)}
    return {"traceEvents": [], "perRequest": reqs}

  def test_merged_per_replica_table(self, tmp_path):
    from tools import trace_report
    paths = []
    for label, base in (("a", 10.0), ("b", 30.0)):
      path = str(tmp_path / f"{label}.json")
      with open(path, "w") as f:
        json.dump(self._Trace(base), f)
      paths.append(path)
    report = trace_report.MergedReport(
        {p: trace_report.LoadTrace(p) for p in paths})
    lines = report.splitlines()
    assert any("FLEET" in l for l in lines)
    rows = [l for l in lines if l.endswith(tuple("0123456789"))
            and not l.startswith("-")]
    assert len(rows) >= 3                       # 2 replicas + fleet
    assert trace_report.main(paths) == 0        # multi-file CLI path
    assert trace_report.main([]) == 2


# -- live integration: serving engine + executor endpoints --------------------


def _TinyLmParams():
  from lingvo_tpu.models.lm import layers as lm_layers
  return lm_layers.TransformerLm.Params().Set(
      name="lm", vocab_size=64, model_dim=32, num_layers=2, num_heads=2,
      hidden_dim=64, use_rotary=True)


@pytest.fixture(scope="module")
def tiny_lm():
  task = _TinyLmParams().Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  return task, theta


class TestLiveEngineEndpoints:

  def test_engine_serves_all_endpoints(self, tiny_lm):
    from lingvo_tpu.serving import engine as engine_lib
    task, theta = tiny_lm
    eng = engine_lib.ServingLoop(
        task, theta, page_size=4, num_pages=16, max_batch=2,
        max_seq_len=32, prefill_chunk=4, default_max_new=4,
        serve_port=0, watchdog=True)
    eng.Start()
    try:
      tokens = eng.Submit([1, 2, 3], 3).Result(timeout=600)
      assert tokens
      url = eng.status_server.Url

      code, body = _Get(url("/metrics"))
      assert code == 200
      plain, labeled = _ParsePrometheus(body)
      # every schema engine counter is a Prometheus series
      for key in observe_schema.ENGINE_COUNTER_KEYS:
        assert f"serving_{key}" in plain, key
      assert plain["serving_tokens_emitted"] >= len(tokens)

      code, body = _Get(url("/statusz"))
      assert code == 200
      doc = observe_schema.ValidateStatusz(json.loads(body))
      assert doc["name"] == "serving"
      stats = doc["stats"]                      # engine Stats(), validated
      observe_schema.ValidateEngineStats(stats)
      assert stats["compile"]                   # compile records present
      assert stats["watchdog"]["beats"] > 0

      code, body = _Get(url("/traces"))
      assert code == 200 and "traceEvents" in json.loads(body)
      assert _Get(url("/healthz"))[0] == 200
      port = eng.status_server.port
    finally:
      eng.Stop()
    assert eng.status_server is None            # Stop() closed the server
    with pytest.raises(Exception):
      _Get(f"http://127.0.0.1:{port}/healthz", timeout=0.5)

  def test_idle_engine_stays_healthy(self, tiny_lm):
    # no traffic is not a stall: the engine loop ticks Idle() while
    # waiting for work, so /healthz stays 200 past the trip window
    from lingvo_tpu.serving import engine as engine_lib
    task, theta = tiny_lm
    wd = watchdog_lib.StallWatchdog(stall_factor=2.0, min_interval_s=0.05)
    eng = engine_lib.ServingLoop(
        task, theta, page_size=4, num_pages=16, max_batch=2,
        max_seq_len=32, prefill_chunk=4, default_max_new=4,
        serve_port=0, watchdog=wd)
    eng.Start()
    try:
      eng.Submit([1, 2, 3], 3).Result(timeout=600)
      time.sleep(0.5)   # >> the ~0.1s no_heartbeat window, but idle
      code, _ = _Get(eng.status_server.Url("/healthz"))
      assert code == 200
      assert wd.Check()["healthy"] is True
    finally:
      eng.Stop()


class TestTrainGoodputMfu:

  def test_short_train_run_publishes_goodput_and_mfu(self, tmp_path):
    import tests.test_executor_hardening as helpers
    from lingvo_tpu.runners import executor as executor_lib
    logdir = str(tmp_path)
    sched, task, _ = helpers._MakeScheduleAndTask(
        logdir, max_steps=10, steps_per_loop=5)
    prev = goodput_lib.Get().Stats()
    scraped = {}
    real_run = sched.Run
    holder = {}

    def _ScrapingRun(state):
      if not scraped:                            # scrape mid-run, once
        code, body = _Get(holder["ex"].status_server.Url("/statusz"))
        scraped["code"], scraped["doc"] = code, json.loads(body)
      return real_run(state)

    sched.Run = _ScrapingRun
    ex = executor_lib.ExecutorTpu(
        helpers._TaskParams(max_steps=10, steps_per_loop=5), logdir,
        schedule=sched, task=task, precompile=True, serve_port=0)
    holder["ex"] = ex
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 10

    # mid-run /statusz: valid doc with the train program's compile records
    assert scraped["code"] == 200
    doc = observe_schema.ValidateStatusz(scraped["doc"])
    assert doc["name"] == "executor"
    recs = doc["stats"]["compile"]["train"]
    assert "step" in recs and recs["step"]["compile_wall_s"] > 0
    assert recs["step"].get("flops", 0) > 0
    # server stopped with the main loop
    assert ex.status_server is None
    # the watchdog auto-created by serve_port beat once per schedule Run
    assert ex.watchdog is not None
    wd = ex.watchdog.Stats()
    assert wd["beats"] >= 2 and wd["healthy"] is True

    # process-global registry: mfu + rate + goodput section all present
    snap = observe.Default().Snapshot()
    assert snap["train/flops_per_step"] > 0
    assert snap["train/peak_flops"] > 0
    assert snap["train/mfu"] >= 0
    assert snap["train/train_steps_per_second"] is not None

    # goodput: this run added productive step time and compile time, and
    # the buckets still partition the wall clock
    cur = goodput_lib.Get().Stats()
    assert cur["step_s"] > prev["step_s"]
    assert cur["compile_s"] > prev["compile_s"]     # precompile tracked
    assert cur["checkpoint_save_s"] >= prev["checkpoint_save_s"]
    bucket_sum = sum(cur[f"{b}_s"] for b in observe_schema.GOODPUT_BUCKETS)
    assert bucket_sum == pytest.approx(cur["wall_s"], rel=1e-3, abs=1e-3)
    assert 0.0 < cur["productive_ratio"] <= 1.0


# -- slow: byte-identical streams with endpoints + scraper live ---------------


@pytest.mark.slow
class TestExporterNonInterference:

  def test_streams_byte_identical_under_scrape_load(self, tiny_lm):
    from lingvo_tpu.serving import engine as engine_lib
    task, theta = tiny_lm
    kw = dict(page_size=4, num_pages=32, max_batch=3, max_seq_len=32,
              prefill_chunk=4, default_max_new=6)
    prompts = [np.random.RandomState(i).randint(1, 63, size=4).tolist()
               for i in range(8)]

    def _RunAll(eng, scrape=False):
      eng.Start()
      stop = threading.Event()
      scraper = None
      if scrape:
        def _Hammer():
          while not stop.is_set():
            _Get(eng.status_server.Url("/metrics"))
            _Get(eng.status_server.Url("/statusz"))
        scraper = threading.Thread(target=_Hammer, daemon=True)
        scraper.start()
      try:
        handles = [eng.Submit(p, 6, seed=i) for i, p in enumerate(prompts)]
        return [h.Result(timeout=600) for h in handles]
      finally:
        stop.set()
        if scraper is not None:
          scraper.join(timeout=10)
        eng.Stop()

    baseline = _RunAll(engine_lib.ServingLoop(task, theta, **kw))
    observed = _RunAll(
        engine_lib.ServingLoop(task, theta, serve_port=0, watchdog=True,
                               **kw), scrape=True)
    assert observed == baseline     # telemetry cannot change the tokens
