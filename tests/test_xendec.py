"""XEnDec crossover training (ref `lingvo/tasks/mt/model.py:401`
TransformerXEnDecModel, arXiv:2106.04060): lambda accounting, crossover
loss wiring, and end-to-end training on the tiny WMT fixture."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401


def _build(name):
  mp = model_registry.GetParams(name, "Train")
  mp.task.input = mp.input
  task = mp.task.Instantiate()
  task.FinalizePaths()
  gen = mp.input.Instantiate()
  return task, gen


class TestXEnDec:

  def test_target_lambdas_sum_to_one(self):
    task, gen = _build("mt.wmt14_en_de.WmtEnDeXEnDecTiny")
    b, s, t = 4, 6, 5
    atten = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(0), (b, t, s)), -1)
    other_atten = jax.nn.softmax(
        jax.random.normal(jax.random.PRNGKey(1), (b, t, s)), -1)
    src_pad = (jnp.zeros((b, s)), jnp.zeros((b, s)))
    tgt_pad = (jnp.zeros((b, t)), jnp.zeros((b, t)))
    mask = jnp.asarray(
        np.random.RandomState(0).randint(0, 2, (b, s)), jnp.float32)
    other_lam = mask * (1.0 - src_pad[1])
    src_lam = ((1.0 - other_lam) * (1.0 - src_pad[0]), other_lam)
    input_lam, label_lam = task._TargetLambdas(
        (atten, other_atten), src_lam, src_pad, tgt_pad)
    np.testing.assert_allclose(
        np.asarray(label_lam[0] + label_lam[1]), 1.0, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(input_lam[0] + input_lam[1]), 1.0, atol=1e-5)

  def test_lambdas_zero_on_both_pad_positions(self):
    """Positions padded in BOTH parents carry no mixture-loss weight
    (a (0,1) split there would train on pad labels)."""
    task, _ = _build("mt.wmt14_en_de.WmtEnDeXEnDecTiny")
    b, s, t = 2, 4, 5
    atten = jnp.full((b, t, s), 1.0 / s)
    tgt_pad0 = jnp.zeros((b, t)).at[:, 3:].set(1.0)
    tgt_pad1 = jnp.zeros((b, t)).at[:, 2:].set(1.0)
    src_pad = (jnp.zeros((b, s)), jnp.zeros((b, s)))
    src_lam = (jnp.full((b, s), 0.5), jnp.full((b, s), 0.5))
    _, label_lam = task._TargetLambdas(
        (atten, atten), src_lam, src_pad, (tgt_pad0, tgt_pad1))
    both_pad = np.asarray(tgt_pad0 * tgt_pad1) > 0.5
    total = np.asarray(label_lam[0] + label_lam[1])
    assert np.allclose(total[both_pad], 0.0)
    assert np.allclose(total[~both_pad], 1.0, atol=1e-5)

  @pytest.mark.slow
  def test_loss_has_clean_and_mix_terms(self):
    task, gen = _build("mt.wmt14_en_de.WmtEnDeXEnDecTiny")
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    state, out = jax.jit(task.TrainStep)(state, batch)
    m = out.metrics
    assert "clean_loss" in m and "mix_loss" in m
    clean = float(m.clean_loss[0])
    mix = float(m.mix_loss[0])
    total = float(m.loss[0])
    assert np.isfinite(clean) and np.isfinite(mix)
    w_mix = task.p.loss_mix_weight
    np.testing.assert_allclose(total, clean + w_mix * mix, rtol=1e-4)

  def test_trains_on_tiny_fixture(self):
    task, gen = _build("mt.wmt14_en_de.WmtEnDeXEnDecTiny")
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(250):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.clean_loss[0]))
    assert np.mean(losses[-10:]) < 0.85 * np.mean(losses[:10]), (
        losses[0], losses[-1])

  @pytest.mark.slow
  def test_eval_path_is_plain_transformer(self):
    from lingvo_tpu.core import py_utils
    task, gen = _build("mt.wmt14_en_de.WmtEnDeXEnDecTiny")
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    with py_utils.EvalContext():
      preds = task.ComputePredictions(state.theta, batch)
      metrics, _ = task.ComputeLoss(state.theta, preds, batch)
    assert "mix_loss" not in metrics
    # beam decode works unchanged
    dec = jax.jit(task.Decode)(state.theta, batch)
    assert dec.topk_ids.shape[0] == batch.src.ids.shape[0]
