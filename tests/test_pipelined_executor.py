"""Fully pipelined executor (ISSUE 15): k-deep dispatch window, host-side
step tracking, async checkpoint save, telemetry-driven cadence.

Contracts under test:
- loss trajectories are BITWISE identical sync vs depth-0 vs depth-1 vs
  depth-2 at the same seed (pipelining reorders telemetry, never math);
- `pipeline_depth=0` is the exact legacy lag-1 path (kill switch);
- NaN-stop fires within <= pipeline_depth loops of the offending loop;
- no blocking `jax.device_get` on the steady-state cycle path;
- transient-failure recovery drains the dispatch window and resumes;
- SaveAsync/Restore barrier ordering + worker-error surfacing;
- the goodput `checkpoint_save` bucket counts only actual writes;
- the watchdog beats on loop COMPLETION, so a stalled device flips
  /healthz even while the pipelined host keeps dispatching;
- the producer-placement probe (satellite of this PR).
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from lingvo_tpu.core import checkpointer as checkpointer_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.observe import watchdog as watchdog_lib
from lingvo_tpu.runners import executor as executor_lib
from lingvo_tpu.runners import infeed as infeed_lib
from lingvo_tpu.runners import program as program_lib

from tests.test_executor_hardening import (_RegressionInput, _TaskParams)


def _MakeExecutor(logdir, *, pipeline_depth=2, async_infeed=True,
                  max_steps=30, steps_per_loop=5, save_interval=10,
                  input_gen=None, **ex_kw):
  task_p = _TaskParams(max_steps=max_steps, steps_per_loop=steps_per_loop,
                       save_interval=save_interval)
  task = task_p.Instantiate()
  task.FinalizePaths()
  train_p = program_lib.TrainProgram.Params().Set(
      task=task_p, logdir=logdir, steps_per_loop=steps_per_loop,
      async_infeed=async_infeed, pipeline_depth=pipeline_depth)
  sched = program_lib.SimpleProgramSchedule(
      program_lib.SimpleProgramSchedule.Params().Set(train_program=train_p),
      task=task,
      input_generators={"Train": input_gen or _RegressionInput(seed=0)})
  ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task,
                                **ex_kw)
  return ex, sched


def _Summaries(logdir):
  with open(os.path.join(logdir, "train", "summaries.jsonl")) as f:
    return [json.loads(l) for l in f]


class TestBitwiseTrajectory:

  def test_trajectory_identical_across_depths(self, tmp_path):
    """Same seed => bitwise-equal per-loop losses and final weights for
    sync, depth-0 (legacy lag-1), depth-1, and depth-2 executors."""
    runs = {}
    for tag, kw in [("sync", dict(async_infeed=False)),
                    ("depth0", dict(pipeline_depth=0)),
                    ("depth1", dict(pipeline_depth=1)),
                    ("depth2", dict(pipeline_depth=2))]:
      logdir = str(tmp_path / tag)
      ex, _ = _MakeExecutor(logdir, **kw)
      state = ex.Start()
      rows = _Summaries(logdir)
      runs[tag] = (
          [(r["step"], r["loss"]) for r in rows],
          jax.device_get(state.theta),
      )
    ref_traj, ref_theta = runs["sync"]
    assert [s for s, _ in ref_traj] == [5, 10, 15, 20, 25, 30]
    for tag in ("depth0", "depth1", "depth2"):
      traj, theta = runs[tag]
      assert traj == ref_traj, tag  # bitwise: JSON round-trips exactly
      for (pa, la), (pb, lb) in zip(ref_theta.FlattenItems(),
                                    theta.FlattenItems()):
        assert pa == pb
        assert np.array_equal(la, lb), (tag, pa)

  def test_kill_switch_uses_legacy_window(self, tmp_path):
    """pipeline_depth=0 runs the byte-exact PR 5 path: the legacy lag-1
    slot is exercised, the k-deep deque stays untouched, and host-side
    step tracking never engages (the executor still fetches the device
    step every cycle)."""
    ex, sched = _MakeExecutor(str(tmp_path), pipeline_depth=0)
    assert ex._PipelineDepth() == 0
    seen = {"legacy": 0}
    prog = sched.train_program
    orig_run = prog._RunAsync

    def _Spy(state):
      out = orig_run(state)
      if prog._pending_telemetry is not None:
        seen["legacy"] += 1
      return out

    prog._RunAsync = _Spy
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 30
    assert seen["legacy"] > 0          # lag-1 slot in use
    assert not prog._pending           # k-deep window never engaged
    assert prog._host_step is None     # host step tracking never seeded

  def test_pipelined_keeps_window_depth(self, tmp_path):
    """At depth 2 the dispatch window really goes >1 deep and backpressure
    caps it: PendingLoops() never exceeds pipeline_depth at Run exit."""
    ex, sched = _MakeExecutor(str(tmp_path), pipeline_depth=2, max_steps=40)
    prog = sched.train_program
    depths = []
    orig_run = prog._RunAsync

    def _Spy(state):
      out = orig_run(state)
      depths.append(prog.PendingLoops())
      return out

    prog._RunAsync = _Spy
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 40
    assert max(depths) <= 2
    # the first Run blocks for its own result (window 0); later runs are
    # free to leave loops in flight
    assert depths[0] == 0


class TestSteadyStateDeviceFetch:

  def test_no_device_get_on_cycle_path(self, tmp_path, monkeypatch):
    """The pipelined executor never fetches the device step on the cycle
    path: host tracking is seeded from the restore fence's already-host
    step, and every later step is arithmetic. (The program-side
    device_get seed is only a fallback for direct Run() callers.)"""
    calls = []
    real = jax.device_get

    def _Counted(x):
      calls.append(x)
      return real(x)

    monkeypatch.setattr(jax, "device_get", _Counted)
    ex, _ = _MakeExecutor(str(tmp_path), pipeline_depth=2, max_steps=50,
                          save_interval=10)
    ex.Start()
    monkeypatch.undo()
    assert len(calls) == 0, [type(c) for c in calls]


class TestCadenceStaleness:

  @pytest.mark.parametrize("depth,max_step", [(1, 15), (2, 20)])
  def test_nan_stop_within_depth_loops(self, tmp_path, depth, max_step):
    """NaN enters at loop 2 (steps 6-10); the stop decision lands within
    <= pipeline_depth loops of it."""

    class _NanInput(_RegressionInput):
      def __init__(self, nan_from_pull, **kw):
        super().__init__(**kw)
        self.pulls = 0
        self._nan_from = nan_from_pull

      def GetPreprocessedInputBatch(self):
        self.pulls += 1
        b = super().GetPreprocessedInputBatch()
        if self.pulls >= self._nan_from:
          b.y = b.y + np.float32("nan")
        return b

    ex, _ = _MakeExecutor(str(tmp_path), pipeline_depth=depth,
                          max_steps=100, save_interval=100,
                          input_gen=_NanInput(6, seed=0),
                          max_train_retries=0)
    state = ex.Start()
    assert int(jax.device_get(state.step)) <= max_step

  def test_trial_stop_fires_at_cycle_boundary(self, tmp_path):
    """trial.ShouldStop is polled every cycle with the host-tracked step,
    so a stop request halts the pipelined run at the next boundary."""
    from lingvo_tpu.core import base_trial

    class _StopAfter3(base_trial.NoOpTrial):
      def __init__(self):
        self.calls = 0

      def ShouldStop(self):
        self.calls += 1
        return self.calls >= 3

    trial = _StopAfter3()
    ex, _ = _MakeExecutor(str(tmp_path), pipeline_depth=2, max_steps=100,
                          save_interval=100, trial=trial)
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 15  # stopped at cycle 3

  def test_recovery_drains_window_and_completes(self, tmp_path):
    """A transient producer death mid-window: recovery drains the k-deep
    dispatch window, restores the checkpoint (crossing the async-save
    barrier), re-seeds the host step, and the run still finishes."""

    class _FailingInput(_RegressionInput):
      def __init__(self, fail_at, **kw):
        super().__init__(**kw)
        self.pulls = 0
        self._fail_at = fail_at

      def GetPreprocessedInputBatch(self):
        self.pulls += 1
        if self.pulls == self._fail_at:
          raise RuntimeError("UNAVAILABLE: reader died")
        return super().GetPreprocessedInputBatch()

    gen = _FailingInput(17, seed=0)
    ex, sched = _MakeExecutor(str(tmp_path), pipeline_depth=2, max_steps=30,
                              save_interval=5, input_gen=gen)
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 30
    assert gen.pulls > 17                       # producer restarted
    assert not sched.train_program._pending     # window fully drained


class TestAsyncCheckpointSave:

  def _State(self, v=1.0):
    import jax.numpy as jnp
    return NestedMap(theta=NestedMap(w=jnp.full((4,), v, jnp.float32)),
                     step=jnp.asarray(7, jnp.int32))

  def test_save_async_visible_after_barrier(self, tmp_path):
    ck = checkpointer_lib.Checkpointer(str(tmp_path), save_interval_steps=1)
    state = self._State(3.0)
    assert ck.SaveAsync(7, state)
    # Restore crosses the WaitForPendingSave barrier: the write is visible
    restored, step = ck.Restore(self._State(0.0))
    assert step == 7
    assert np.array_equal(np.asarray(restored.theta.w),
                          np.full((4,), 3.0, np.float32))
    ck.Close()

  def test_cadence_noop_schedules_nothing(self, tmp_path):
    ck = checkpointer_lib.Checkpointer(str(tmp_path),
                                       save_interval_steps=10)
    st = self._State()
    assert ck.SaveAsync(10, st)
    assert not ck.SaveAsync(13, st)   # off-cadence: no write scheduled
    assert not ck.SaveAsync(10, st)   # same step: no duplicate write
    ck.Close()
    assert ck.LatestStep() == 10

  def test_worker_error_surfaces_at_barrier(self, tmp_path):
    ck = checkpointer_lib.Checkpointer(str(tmp_path), save_interval_steps=1)
    import jax.numpy as jnp
    bad = NestedMap(theta=NestedMap(w=jnp.full((4,), np.nan, jnp.float32)),
                    step=jnp.asarray(1, jnp.int32))
    assert ck.SaveAsync(1, bad)   # snapshot + submit succeed...
    with pytest.raises(ValueError, match="non-finite"):
      ck.WaitForPendingSave()     # ...the sanity failure lands at the fence
    # the barrier is one-shot: after surfacing, the checkpointer is usable
    assert ck.SaveAsync(2, self._State())
    ck.Close()
    assert ck.LatestStep() == 2

  def test_goodput_counts_only_actual_writes(self, tmp_path):
    class _Tracker:
      def __init__(self):
        self.entered = []

      def Track(self, bucket):
        import contextlib

        @contextlib.contextmanager
        def _Cm():
          self.entered.append(bucket)
          yield
        return _Cm()

    tr = _Tracker()
    ck = checkpointer_lib.Checkpointer(str(tmp_path),
                                       save_interval_steps=10, goodput=tr)
    st = self._State()
    assert not ck.Save(3, st)        # cadence no-op: zero badput entries
    assert not ck.SaveAsync(7, st)
    assert tr.entered == []
    assert ck.Save(10, st)
    assert ck.SaveAsync(20, st)
    assert tr.entered == ["checkpoint_save", "checkpoint_save"]
    ck.Close()


class TestWatchdogBeatsOnCompletion:

  def test_stalled_device_flips_healthz(self, tmp_path):
    """Dispatch keeps running while loop COMPLETION stalls: no beats =>
    the watchdog trips no_heartbeat within its window, even though the
    pipelined host is still dispatching. Completion resumes => healthy."""
    clock = [0.0]
    wd = watchdog_lib.StallWatchdog(stall_factor=10.0, min_interval_s=1.0,
                                    clock=lambda: clock[0])
    task_p = _TaskParams(max_steps=1000, steps_per_loop=5,
                         save_interval=1000)
    task = task_p.Instantiate()
    task.FinalizePaths()
    prog = program_lib.TrainProgram(
        program_lib.TrainProgram.Params().Set(
            task=task_p, logdir=str(tmp_path), steps_per_loop=5,
            pipeline_depth=2),
        task=task, input_generator=_RegressionInput(seed=0))
    prog.SetLoopDoneCallback(wd.Beat)
    gate = threading.Event()
    gate.set()
    orig_finalize = prog._FinalizeLoop

    def _GatedFinalize(*a, **kw):
      gate.wait(timeout=30)
      return orig_finalize(*a, **kw)

    prog._FinalizeLoop = _GatedFinalize
    try:
      state = task.CreateTrainState(jax.random.PRNGKey(0))
      state, _ = prog.Run(state)            # first loop completes -> beat
      deadline = time.time() + 10
      while wd.Stats()["beats"] < 1 and time.time() < deadline:
        time.sleep(0.01)
      beats_before = wd.Stats()["beats"]
      assert beats_before >= 1
      gate.clear()                          # "device" stalls from here on
      state, _ = prog.Run(state)            # dispatch still succeeds...
      assert wd.Stats()["beats"] == beats_before  # ...but must NOT beat
      clock[0] += 60.0                      # stall_factor x interval passes
      wd.Check()
      assert not wd.healthy                 # /healthz flips within window
      gate.set()                            # device recovers
      prog.Flush()
      assert wd.Stats()["beats"] > beats_before
      wd.Check()
      assert wd.healthy
    finally:
      gate.set()
      prog.Shutdown()


class TestPlacementProbe:

  def test_passing_probe_returns_true(self):
    assert infeed_lib.ProbeProducerPlacement(probe_fn=lambda: None)

  def test_failing_probe_returns_false(self):
    def _Boom():
      raise RuntimeError("off-main-thread placement unsupported")

    assert not infeed_lib.ProbeProducerPlacement(probe_fn=_Boom)

  def test_hanging_probe_returns_false(self):
    ev = threading.Event()
    try:
      assert not infeed_lib.ProbeProducerPlacement(
          probe_fn=lambda: ev.wait(30), timeout_s=0.2)
    finally:
      ev.set()   # unblock the daemon probe thread

  def test_knob_overrides_probe(self, tmp_path):
    task_p = _TaskParams()
    task = task_p.Instantiate()
    task.FinalizePaths()
    prog = program_lib.TrainProgram(
        program_lib.TrainProgram.Params().Set(
            task=task_p, logdir=str(tmp_path),
            infeed_place_on_device=False),
        task=task, input_generator=_RegressionInput())
    assert prog._PlaceInProducer() is False   # explicit knob wins
    prog.p.infeed_place_on_device = True
    assert prog._PlaceInProducer() is True
    prog.p.infeed_place_on_device = None      # auto: single-process => True
    assert prog._PlaceInProducer() is True
