"""Global prefix cache: CoW KV page sharing across requests.

Three layers, mirroring the implementation split:

- `TestRefcountedAllocator`: kv_cache.PageAllocator's new reference
  machinery in isolation — Share/Retain/Release refcounts, per-reference
  Free, copy-on-write splits, and the AssertExclusive write guard.
- `TestPrefixTree`: prefix_cache.PrefixCache over a bare allocator —
  pure Probe vs NoteAdmitted counters, canonical inserts, LRU eviction
  (leaves-first, pinned pages immune), invalidation and Bind mismatch.
- `TestPrefixEngine`: the full serving loop — the contract that matters
  is BYTE-IDENTICAL token streams: warm (cache hit) == cold (miss) ==
  dense greedy reference == cache-off legacy engine, across bf16, int8
  scale-sidecar pools, and speculative decoding on a shared prefix
  (verify-step writes run under AssertExclusive, so a rollback that
  touched a shared page would fail loudly, not corrupt silently).
"""

import jax
import numpy as np
import pytest

from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.serving import engine as engine_lib
from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import prefix_cache as prefix_cache_lib
from lingvo_tpu.serving import spec_decode

from tests.test_serving_engine import _GreedyRef, _TinyLmParams
# (the session-scoped `tiny_lm` fixture resolves from tests/conftest.py)


# -- allocator refcounts ------------------------------------------------------


class TestRefcountedAllocator:

  def test_share_adds_references_and_free_drops_one(self):
    alloc = kv_cache.PageAllocator(8, 4)
    pages = alloc.Allocate("a", 2)
    assert all(alloc.RefCount(p) == 1 for p in pages)
    assert alloc.shared_pages == 0
    alloc.Share("b", pages)
    assert all(alloc.RefCount(p) == 2 for p in pages)
    assert alloc.shared_pages == 2
    assert alloc.num_free == 6          # sharing is free of pool charge
    assert alloc.Stats()["shared_pages"] == 2
    alloc.Free("a")
    assert all(alloc.RefCount(p) == 1 for p in pages)
    assert alloc.num_free == 6          # b still holds them
    alloc.Free("b")
    assert alloc.num_free == 8
    assert alloc.shared_pages == 0

  def test_share_empty_is_a_noop(self):
    alloc = kv_cache.PageAllocator(4, 4)
    alloc.Share("ghost", [])
    assert alloc.Stats()["num_sequences"] == 0
    with pytest.raises(KeyError):
      alloc.PagesOf("ghost")

  def test_cow_splits_shared_page_in_place(self):
    alloc = kv_cache.PageAllocator(8, 4)
    a = alloc.Allocate("a", 2)
    assert alloc.CopyOnWrite("a", 0) is None   # exclusive: no split
    alloc.Share("b", a)
    pair = alloc.CopyOnWrite("b", 1)
    assert pair is not None
    old, new = pair
    assert old == a[1] and new not in a
    assert alloc.PagesOf("b") == [a[0], new]   # spliced at logical idx 1
    assert alloc.PagesOf("a") == a             # writer untouched
    assert alloc.RefCount(old) == 1 and alloc.RefCount(new) == 1
    assert alloc.shared_pages == 1             # only a[0] still shared

  def test_cow_out_of_pages_has_no_side_effects(self):
    alloc = kv_cache.PageAllocator(2, 4)
    a = alloc.Allocate("a", 2)
    alloc.Share("b", a)
    with pytest.raises(kv_cache.OutOfPages):
      alloc.CopyOnWrite("b", 0)
    assert alloc.PagesOf("b") == a
    assert all(alloc.RefCount(p) == 2 for p in a)

  def test_retain_release_and_double_free_assert(self):
    alloc = kv_cache.PageAllocator(2, 4)
    (pg,) = alloc.Allocate("a", 1)
    alloc.Retain(pg)                    # ownerless cache reference
    alloc.Free("a")
    assert alloc.RefCount(pg) == 1 and alloc.num_free == 1
    alloc.Release(pg)
    assert alloc.num_free == 2
    with pytest.raises(AssertionError):
      alloc.Release(pg)                 # double free is loud
    with pytest.raises(AssertionError):
      alloc.Retain(pg)                  # cannot retain a free page

  def test_assert_exclusive_guards_shared_write_ranges(self):
    alloc = kv_cache.PageAllocator(8, 4)
    a = alloc.Allocate("a", 2)
    alloc.AssertExclusive("a", 0, 8)    # exclusive everywhere: fine
    alloc.Share("b", [a[0]])
    with pytest.raises(AssertionError):
      alloc.AssertExclusive("a", 0, 4)  # page 0 now shared
    alloc.AssertExclusive("a", 4, 4)    # page 1 still exclusive
    alloc.AssertExclusive("a", 0, 0)    # empty write range: no-op
    alloc.AssertExclusive("a", 4, 100)  # range clamps to owned pages


# -- prefix tree --------------------------------------------------------------


def _Cached(alloc, cache, prompt):
  """Simulates a writer sequence that prefilled `prompt` then retired:
  the cache's Retain is what keeps the pages alive past the Free."""
  wid = object()
  pages = alloc.Allocate(wid, len(prompt) // alloc.page_size)
  cache.Insert(prompt, pages)
  alloc.Free(wid)
  return pages


class TestPrefixTree:

  def _Fixture(self, num_pages=16, page_size=4, **kw):
    alloc = kv_cache.PageAllocator(num_pages, page_size)
    return alloc, prefix_cache_lib.PrefixCache(alloc, None, **kw)

  def test_probe_is_pure_and_note_admitted_counts(self):
    alloc, cache = self._Fixture()
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    pages = _Cached(alloc, cache, prompt)
    assert cache.cached_pages == 2
    for _ in range(5):                  # admission re-probes every step
      got, matched = cache.Probe(prompt)
      assert got == pages and matched == 8
    assert cache.hits == 0 and cache.misses == 0 and cache.hit_tokens == 0
    # partial prefix matches only full pages
    got, matched = cache.Probe(prompt[:6] + [99, 99])
    assert got == pages[:1] and matched == 4
    # full-cover hit still recomputes the last prompt token
    cache.NoteAdmitted(prompt, 8)
    assert cache.hits == 1 and cache.hit_tokens == 7
    cache.NoteAdmitted([9, 9, 9, 9], 0)
    assert cache.misses == 1 and cache.hit_tokens == 7

  def test_insert_keeps_first_writers_pages_canonical(self):
    alloc, cache = self._Fixture()
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    first = _Cached(alloc, cache, prompt)
    free_before = alloc.num_free
    second = _Cached(alloc, cache, prompt)   # identical prefix, new pages
    assert first != second
    got, _ = cache.Probe(prompt)
    assert got == first                      # first writer stays canonical
    assert cache.cached_pages == 2
    assert alloc.num_free == free_before     # duplicates fully released

  def test_evict_lru_leaves_first_and_pinned_pages_survive(self):
    alloc, cache = self._Fixture()
    p1 = [1, 2, 3, 4, 5, 6, 7, 8]
    p2 = [9, 10, 11, 12]
    pages1 = _Cached(alloc, cache, p1)
    _Cached(alloc, cache, p2)
    cache.NoteAdmitted(p1, 8)                # p1 is now most-recent
    assert cache.EvictLru(1) == 1
    assert cache.cached_pages == 2           # LRU victim was p2's page
    assert cache.Probe(p2)[1] == 0
    assert cache.Probe(p1)[1] == 8
    # pinned by a borrower: refcount 2 pages are not evictable
    alloc.Share("s", pages1)
    assert cache.EvictLru(5) == 0
    alloc.Free("s")
    # leaves-first: both nodes go once unpinned, deep node before parent
    assert cache.EvictLru(5) == 2
    assert cache.cached_pages == 0 and cache.evictions == 3
    assert alloc.num_free == alloc.num_pages

  def test_invalidate_and_bind_mismatch(self):
    alloc, cache = self._Fixture()
    _Cached(alloc, cache, [1, 2, 3, 4, 5, 6, 7, 8])
    assert cache.Invalidate() == 2
    assert cache.cached_pages == 0 and cache.evictions == 2
    assert alloc.num_free == alloc.num_pages
    # same pool, same dtype: Bind keeps entries
    _Cached(alloc, cache, [1, 2, 3, 4])
    cache.Bind(alloc, None)
    assert cache.cached_pages == 1
    # dtype flip: an int8 page never serves a bf16 probe
    cache.Bind(alloc, "int8")
    assert cache.cached_pages == 0
    # allocator identity flip: page ids are meaningless across pools
    _Cached(alloc, cache, [1, 2, 3, 4])
    cache.Bind(kv_cache.PageAllocator(16, 4), "int8")
    assert cache.cached_pages == 0

  def test_max_pages_cap_evicts_then_stops(self):
    alloc, cache = self._Fixture(max_pages=1)
    wid = object()
    pages = alloc.Allocate(wid, 2)
    cache.Insert([1, 2, 3, 4, 5, 6, 7, 8], pages)
    # writer still holds both pages -> nothing evictable -> prefix-complete
    # insert stops at the cap instead of overshooting
    assert cache.cached_pages == 1
    alloc.Free(wid)
    _Cached(alloc, cache, [21, 22, 23, 24])
    assert cache.cached_pages == 1           # cap held via LRU eviction
    assert cache.evictions == 1
    assert cache.Probe([21, 22, 23, 24])[1] == 4

  def test_stats_key_set_matches_schema(self):
    _, cache = self._Fixture()
    assert set(cache.Stats()) == observe_schema.PREFIX_CACHE_STATS_KEYS
    assert cache.Stats()["enabled"] is True
    disabled = observe_schema.DisabledPrefixCacheStats()
    assert set(disabled) == observe_schema.PREFIX_CACHE_STATS_KEYS
    assert disabled["enabled"] is False

  def test_mark_stale_hides_pages_until_insert_refreshes(self):
    alloc, cache = self._Fixture()
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    _Cached(alloc, cache, prompt)
    assert cache.MarkStale() == 2
    # stale pages are NEVER served: probe and peek see nothing
    assert cache.Probe(prompt) == ([], 0)
    assert cache.PeekHitTokens(prompt) == 0
    # ... but the tree structure (and its pages) survive
    assert cache.cached_pages == 2
    assert cache.Stats()["stale_pages"] == 2
    free_before = alloc.num_free
    # re-prefilling the same prompt refreshes the nodes IN PLACE: new
    # pages take over, old pages return to the pool, no tree growth
    new = _Cached(alloc, cache, prompt)
    got, matched = cache.Probe(prompt)
    assert got == new and matched == 8
    st = cache.Stats()
    assert st["stale_pages"] == 0 and st["refreshed_pages"] == 2
    assert cache.cached_pages == 2 and cache.evictions == 0
    assert alloc.num_free == free_before     # swap, not leak

  def test_mark_stale_partial_refresh_serves_fresh_prefix_only(self):
    alloc, cache = self._Fixture()
    _Cached(alloc, cache, [1, 2, 3, 4, 5, 6, 7, 8])
    cache.MarkStale()
    _Cached(alloc, cache, [1, 2, 3, 4])      # refresh only the first page
    got, matched = cache.Probe([1, 2, 3, 4, 5, 6, 7, 8])
    assert len(got) == 1 and matched == 4    # walk stops at the stale child
    st = cache.Stats()
    assert st["stale_pages"] == 1 and st["refreshed_pages"] == 1

  def test_mark_stale_twice_and_eviction_still_collects(self):
    alloc, cache = self._Fixture()
    _Cached(alloc, cache, [1, 2, 3, 4, 5, 6, 7, 8])
    assert cache.MarkStale() == 2
    assert cache.MarkStale() == 2            # idempotent-ish: still stale
    assert cache.Stats()["stale_pages"] == 2
    # stale entries remain ordinary LRU citizens for memory pressure
    assert cache.EvictLru(5) == 2
    assert cache.cached_pages == 0
    assert alloc.num_free == alloc.num_pages
    assert cache.MarkStale() == 0            # empty tree: nothing to mark


# -- serving engine -----------------------------------------------------------


def _MakeEngine(task, theta, **kw):
  kw.setdefault("page_size", 4)
  kw.setdefault("num_pages", 16)
  kw.setdefault("max_batch", 2)
  kw.setdefault("max_seq_len", 32)
  kw.setdefault("prefill_chunk", 4)
  kw.setdefault("default_max_new", 6)
  kw.setdefault("prefix_cache", True)
  return engine_lib.ServingLoop(task, theta, **kw)


def _Run(eng, prompt, max_new):
  """Drives one request inline (deterministic: no loop thread)."""
  h = eng.Submit(list(prompt), max_new)
  while not h.done:
    eng.StepOnce()
  return h.Result(timeout=0)


_PROMPT = [5, 9, 2, 33, 17, 4, 11, 3]   # page-aligned: 2 full pages at ps=4


class TestPrefixEngine:

  def test_cold_then_warm_streams_byte_identical(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta)
    ref = _GreedyRef(task, theta, _PROMPT, 6)
    cold = _Run(eng, _PROMPT, 6)
    assert cold == ref
    pc = eng.Stats()["prefix_cache"]
    assert pc["misses"] == 1 and pc["hits"] == 0
    assert pc["cached_pages"] == 2 and pc["cached_tokens"] == 8
    warm = _Run(eng, _PROMPT, 6)
    assert warm == cold                      # THE contract: bit-exact reuse
    stats = eng.Stats()
    pc = stats["prefix_cache"]
    # full-cover match: last prompt token recomputes, so 7 tokens skipped
    # and exactly the final shared page is copy-on-write'd
    assert pc["hits"] == 1 and pc["hit_tokens"] == 7
    assert pc["cow_copies"] == 1
    assert stats["prefix_hit_tokens"] == 7
    # both requests drained; only the cache's retains keep pages resident
    assert pc["cached_pages"] == 2
    assert stats["kv_pages"]["free"] == eng.num_pages - 2

  def test_cache_on_matches_cache_off_legacy(self, tiny_lm):
    task, theta = tiny_lm
    eng_off = _MakeEngine(task, theta, prefix_cache=None)
    eng_on = _MakeEngine(task, theta)
    assert eng_off.prefix_cache is None
    assert eng_off.Stats()["prefix_cache"]["enabled"] is False
    for prompt in (_PROMPT, [7, 7, 7], _PROMPT):
      assert _Run(eng_on, prompt, 5) == _Run(eng_off, prompt, 5)

  def test_mid_page_divergence_stays_isolated(self, tiny_lm):
    """Two prompts sharing one full page then diverging mid-page: the
    borrower must never see the writer's tail tokens (its divergent page
    is private — the cache only ever hands out full pages)."""
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta)
    a = [5, 9, 2, 33, 17, 4]                 # 1 full page + 2-token tail
    b = [5, 9, 2, 33, 7, 8]                  # same page 0, different tail
    out_a = _Run(eng, a, 6)
    out_b = _Run(eng, b, 6)
    assert out_a == _GreedyRef(task, theta, a, 6)
    assert out_b == _GreedyRef(task, theta, b, 6)
    pc = eng.Stats()["prefix_cache"]
    assert pc["hits"] == 1 and pc["hit_tokens"] == 4
    assert pc["cow_copies"] == 0             # divergence page was private
    # and the writer's stream is reproducible after the borrower ran
    assert _Run(eng, a, 6) == out_a

  def test_eviction_under_pool_pressure(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta, num_pages=4, max_batch=1, max_seq_len=16)
    p1, p2 = _PROMPT, [40, 41, 42, 43, 44, 45, 46, 47]
    assert _Run(eng, p1, 4) == _GreedyRef(task, theta, p1, 4)
    stats = eng.Stats()
    assert stats["prefix_cache"]["cached_pages"] == 2
    assert stats["kv_pages"]["free"] == 2    # cache holds 2 of 4 pages
    # p2 needs 3 pages -> admission must evict a cached page to proceed
    assert _Run(eng, p2, 4) == _GreedyRef(task, theta, p2, 4)
    pc = eng.Stats()["prefix_cache"]
    assert pc["evictions"] >= 1
    assert pc["misses"] == 2

  def test_int8_scale_sidecar_pages_shared(self, tiny_lm):
    """Warm int8 hits reuse quantized K/V pages AND their f32 scale
    sidecars; parity target is the int8 cache-off engine (int8 rounding
    shifts tokens vs the dense reference, sharing must not shift more)."""
    task, theta = tiny_lm
    eng8 = _MakeEngine(task, theta, kv_cache_dtype="int8")
    eng8_off = _MakeEngine(task, theta, kv_cache_dtype="int8",
                           prefix_cache=None)
    assert eng8.kv_cache_dtype == "int8"
    ref = _Run(eng8_off, _PROMPT, 6)
    cold = _Run(eng8, _PROMPT, 6)
    warm = _Run(eng8, _PROMPT, 6)
    assert cold == ref and warm == ref
    pc = eng8.Stats()["prefix_cache"]
    assert pc["hits"] == 1 and pc["cow_copies"] == 1

  def test_spec_decode_on_shared_prefix(self, tiny_lm):
    """Regression for the rollback audit: speculative verify writes (and
    their rejected-tail re-writes after rollback) run under
    AssertExclusive, so a rollback into a shared page would assert. The
    warm spec stream must equal the cold one and the dense reference."""
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta, num_pages=24, max_batch=2,
                      spec=spec_decode.SelfDraft(k=4, num_layers=1),
                      default_max_new=8)
    ref = _GreedyRef(task, theta, _PROMPT, 8)
    cold = _Run(eng, _PROMPT, 8)
    warm = _Run(eng, _PROMPT, 8)
    assert cold == ref and warm == ref
    stats = eng.Stats()
    assert stats["prefix_cache"]["hits"] == 1
    assert stats["spec_cycles"] > 0          # spec path actually ran

  def test_ssm_stack_is_rejected(self):
    from lingvo_tpu.core import ssm
    p = _TinyLmParams(
        mixer_tpl=ssm.GatedSSMLayer.Params().Set(state_dim=8, chunk_size=4),
        mixer_atten_every_n=2)
    task = p.Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="attention-only"):
      _MakeEngine(task, theta)

  def test_update_theta_invalidates_cache(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta)
    cold = _Run(eng, _PROMPT, 6)
    assert eng.Stats()["prefix_cache"]["cached_pages"] == 2
    eng.UpdateTheta(theta)                   # checkpoint swap: all KV stale
    pc = eng.Stats()["prefix_cache"]
    assert pc["cached_pages"] == 0 and pc["evictions"] == 2
    # next identical request is a miss, and (same theta) byte-identical
    assert _Run(eng, _PROMPT, 6) == cold
    assert eng.Stats()["prefix_cache"]["misses"] == 2

  def test_update_theta_persists_tree_and_recovers_hits(self, tiny_lm,
                                                        tiny_lm_swapped):
    task, theta = tiny_lm
    _, theta2 = tiny_lm_swapped
    eng = _MakeEngine(task, theta, prefix_swap_persist=True)
    _Run(eng, _PROMPT, 6)
    eng.UpdateTheta(theta2)                  # swap: tree kept, pages stale
    pc = eng.Stats()["prefix_cache"]
    assert pc["cached_pages"] == 2 and pc["evictions"] == 0
    assert pc["stale_pages"] == 2
    # post-swap stream is the NEW theta's reference (stale KV never
    # served): a miss that re-prefills and refreshes the tree in place
    ref2 = _GreedyRef(task, theta2, _PROMPT, 6)
    assert _Run(eng, _PROMPT, 6) == ref2
    pc = eng.Stats()["prefix_cache"]
    assert pc["stale_pages"] == 0 and pc["refreshed_pages"] == 2
    assert pc["cached_pages"] == 2
    # ... and the NEXT request hits warm again: no cold tree restart
    assert _Run(eng, _PROMPT, 6) == ref2
    pc = eng.Stats()["prefix_cache"]
    assert pc["hit_tokens"] == 7 and pc["hits"] == 1

  def test_update_theta_persist_flag_overrides_per_call(self, tiny_lm):
    task, theta = tiny_lm
    # engine default persists; the per-call knob can force a hard drop
    eng = _MakeEngine(task, theta, prefix_swap_persist=True)
    _Run(eng, _PROMPT, 6)
    eng.UpdateTheta(theta, persist_prefix=False)
    assert eng.Stats()["prefix_cache"]["cached_pages"] == 0
    # and the reverse: a default-Invalidate engine can persist on demand
    eng2 = _MakeEngine(task, theta)
    _Run(eng2, _PROMPT, 6)
    eng2.UpdateTheta(theta, persist_prefix=True)
    pc = eng2.Stats()["prefix_cache"]
    assert pc["cached_pages"] == 2 and pc["stale_pages"] == 2

  def test_swap_under_load_post_swap_streams_byte_identical(
      self, tiny_lm, tiny_lm_swapped):
    """UpdateTheta with admitted AND queued work in flight: everything
    completes, and every request admitted after the swap decodes the new
    theta's exact greedy stream off the persisted (refreshed) tree."""
    task, theta = tiny_lm
    _, theta2 = tiny_lm_swapped
    eng = _MakeEngine(task, theta, prefix_swap_persist=True)
    _Run(eng, _PROMPT, 6)                    # warm the tree pre-swap
    inflight = eng.Submit(list(_PROMPT), 6)
    queued = eng.Submit(list(_PROMPT), 6)
    eng.StepOnce()                           # admit `inflight` (batch=2
    eng.StepOnce()                           # holds both), decode a bit
    eng.UpdateTheta(theta2)
    assert eng.Stats()["prefix_cache"]["stale_pages"] == 2
    while not (inflight.done and queued.done):
      eng.StepOnce()
    # in-flight work finished (mixed-theta streams: only length holds)
    assert len(inflight.Result(timeout=0)) == 6
    assert len(queued.Result(timeout=0)) == 6
    ref2 = _GreedyRef(task, theta2, _PROMPT, 6)
    assert _Run(eng, _PROMPT, 6) == ref2     # re-prefill, refresh
    assert _Run(eng, _PROMPT, 6) == ref2     # warm hit on the new pages
    pc = eng.Stats()["prefix_cache"]
    assert pc["refreshed_pages"] >= 2 and pc["stale_pages"] == 0

  def test_stats_schema_and_midflight_sharing(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MakeEngine(task, theta)
    _Run(eng, _PROMPT, 6)
    h = eng.Submit(list(_PROMPT), 6)
    eng.StepOnce()                           # admit the warm request
    mid = eng.Stats()
    assert mid["kv_pages"]["shared_pages"] >= 1   # page 0: seq + cache
    assert mid["scheduler"]["slots_live"] == 1
    while not h.done:
      eng.StepOnce()
    stats = eng.Stats()
    observe_schema.ValidateEngineStats(stats)
    assert stats["prefix_cache"]["enabled"] is True
    assert set(stats["prefix_cache"]) == observe_schema.PREFIX_CACHE_STATS_KEYS
    assert stats["scheduler"]["slots_live_peak"] >= 1
    assert stats["kv_pages"]["shared_pages"] == 0  # drained: only cache refs
