"""StackedRecurrent pipeline == sequential stacked RNNs; sendrecv helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.parallel import sendrecv, stacked_recurrent

KEY = jax.random.PRNGKey(13)
B, T, D, L = 2, 7, 4, 3


def _mk_stack():
  p = stacked_recurrent.StackedRecurrent.Params().Set(
      name="stack", num_stages=L,
      cell=rnn_cell.LSTMCellSimple.Params().Set(num_input_nodes=D,
                                                num_output_nodes=D))
  layer = p.Instantiate()
  layer.FinalizePaths()
  return layer, layer.InstantiateVariables(KEY)


class TestStackedRecurrent:

  def test_matches_sequential(self):
    layer, theta = _mk_stack()
    x = jax.random.normal(KEY, (B, T, D))
    pads = jnp.zeros((B, T))
    out, _ = layer.FProp(theta, x, pads)
    assert out.shape == (B, T, D)

    # sequential reference: run each stage's cell over the full sequence
    cur = x
    for s in range(L):
      theta_s = jax.tree_util.tree_map(lambda w: w[s], theta.cell)
      state = layer.cell.InitState(B)
      outs = []
      for t in range(T):
        state = layer.cell.FProp(theta_s, state, cur[:, t], pads[:, t])
        outs.append(layer.cell.GetOutput(state))
      cur = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(cur), rtol=1e-5,
                               atol=1e-5)

  def test_padding_freezes(self):
    layer, theta = _mk_stack()
    x = jax.random.normal(KEY, (B, T, D))
    pads = jnp.zeros((B, T)).at[:, 4:].set(1.0)
    out_full, states_full = layer.FProp(theta, x, pads)
    # changing padded-region inputs must not change anything
    x2 = x.at[:, 4:].set(33.0)
    out2, _ = layer.FProp(theta, x2, pads)
    np.testing.assert_allclose(np.asarray(out_full[:, :4]),
                               np.asarray(out2[:, :4]), atol=1e-5)

  def test_jit_and_grad(self):
    layer, theta = _mk_stack()
    x = jax.random.normal(KEY, (B, T, D))

    def loss(th):
      out, _ = layer.FProp(th, x)
      return jnp.sum(out ** 2)

    g = jax.jit(jax.grad(loss))(theta)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    assert any(float(jnp.sum(jnp.abs(l))) > 0 for l in leaves)


class TestSendRecv:

  def test_shift_moves_shard_data(self):
    from lingvo_tpu.parallel.mesh import ShardMap as shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("x",))
    x = jnp.arange(4.0)

    shifted = jax.jit(shard_map(
        lambda v: sendrecv.Shift(v, "x", 1),
        mesh=mesh, in_specs=P("x"), out_specs=P("x")))(x)
    # shard i's value lands on shard i+1; shard 0 receives zeros
    np.testing.assert_allclose(np.asarray(shifted), [0.0, 0.0, 1.0, 2.0])

    wrapped = jax.jit(shard_map(
        lambda v: sendrecv.Shift(v, "x", 1, wrap=True),
        mesh=mesh, in_specs=P("x"), out_specs=P("x")))(x)
    np.testing.assert_allclose(np.asarray(wrapped), [3.0, 0.0, 1.0, 2.0])

  def test_explicit_pairs(self):
    from lingvo_tpu.parallel.mesh import ShardMap as shard_map
    from jax.sharding import Mesh, PartitionSpec as P
    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("x",))
    x = jnp.arange(4.0)
    out = jax.jit(shard_map(
        lambda v: sendrecv.SendRecv(v, [(0, 3), (3, 0)], "x"),
        mesh=mesh, in_specs=P("x"), out_specs=P("x")))(x)
    np.testing.assert_allclose(np.asarray(out), [3.0, 0.0, 0.0, 0.0])
