"""One ragged step program (serving/scheduler.py + serving/engine.py).

Covers docs/ragged_step.md:
- ragged-vs-legacy BYTE-IDENTITY: seeded mixed-length request streams
  produce token-for-token identical outputs on `step_mode='ragged'` and
  `step_mode='legacy'` engines — greedy across draft sources (none /
  SelfDraft / ModelDraft) and target shapes (dense / hybrid-SSM), with
  the prefix cache on and off; temperature > 0 without a draft source is
  byte-identical too (per-token draws are position-indexed, so packing
  never moves a request's sampling stream),
- the compiled-program census: one serving lifetime with admissions,
  prefill/decode overlap, spec cycles, a cancellation and retirements
  compiles EXACTLY ONE step program (`Stats()["compile"]` census == 1,
  name "ragged", no fallback), where the legacy trio compiles three,
- `BuildRaggedStep` packing: decode rows mandatory-first with per-row
  draft clamps, prefill consuming the leftover budget, zero-length rows
  riding with their true q_pos (the SSM-reset trigger is q_pos == 0),
  and `CommitRaggedStep` rollback accounting (rejected tails and
  eos-truncated accepted prefixes) on the page pool,
- cached-prefix-first admission (the scheduler's `_NextWaiting` window):
  under pool pressure the cached follower admits before the uncached
  FIFO head, lifting prefix-cache hit_tokens over strict FIFO, counted
  by `prefix_ordered_admissions`.
"""

import numpy as np
import pytest

from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.serving import engine as engine_lib
from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import prefix_cache as prefix_cache_lib
from lingvo_tpu.serving import scheduler as scheduler_lib
from lingvo_tpu.serving import spec_decode

from tests.test_spec_decode import (_Instantiate, _LmParams, _Stream,
                                    _RunStream)  # noqa: F401
# tiny_lm / hybrid_lm / ssm_draft_lm fixtures: session-scoped in conftest.py


def _Engine(task, theta, spec=None, *, step_mode="ragged", **kw):
  kw.setdefault("page_size", 4)
  kw.setdefault("num_pages", 24)
  kw.setdefault("max_batch", 3)
  kw.setdefault("max_seq_len", 32)
  kw.setdefault("prefill_chunk", 4)
  kw.setdefault("default_max_new", 8)
  return engine_lib.ServingLoop(task, theta, spec=spec, step_mode=step_mode,
                                **kw)


def _BothModes(task, theta, reqs, spec_fn=None, **kw):
  """Runs one stream through a ragged and a legacy engine; returns both."""
  outs = {}
  for mode in ("ragged", "legacy"):
    spec = spec_fn() if spec_fn is not None else None
    eng = _Engine(task, theta, spec, step_mode=mode, **kw)
    outs[mode] = (_RunStream(eng, reqs), eng)
  return outs


# -- ragged vs legacy byte-identity -------------------------------------------


class TestRaggedLegacyByteIdentity:

  def test_greedy_dense_nospec_prefix_on_and_off(self, tiny_lm):
    """Greedy, no draft source — with a repeated-prompt stream so the
    prefix cache actually shares pages in the cache-on arm."""
    task, theta = tiny_lm
    shared = ([7, 3, 7, 3, 7, 3, 7, 3, 7], 4)  # > 2 full pages of prompt
    reqs = [shared] + _Stream(12, seed=11) + [shared]
    # the first copy retires (and inserts its pages) long before the
    # last admits, so the cache-on arm sees a real hit + CoW split
    for cache in (False, True):
      outs = _BothModes(task, theta, reqs, prefix_cache=cache)
      assert outs["ragged"][0] == outs["legacy"][0], f"prefix_cache={cache}"
      if cache:
        for _, eng in outs.values():
          assert eng.Stats()["prefix_cache"]["hit_tokens"] > 0

  def test_greedy_self_draft_ragged_matches_legacy(self, tiny_lm):
    task, theta = tiny_lm
    reqs = _Stream(10, seed=12)
    outs = _BothModes(
        task, theta, reqs,
        spec_fn=lambda: spec_decode.SelfDraft(k=3, num_layers=1))
    assert outs["ragged"][0] == outs["legacy"][0]
    for _, eng in outs.values():
      assert eng.Stats()["spec_cycles"] > 0
    # the unified step speculates WHILE neighbors prefill; legacy defers
    # spec cycles to pure-decode steps — so ragged never cycles less
    assert (outs["ragged"][1].Stats()["spec_cycles"]
            >= outs["legacy"][1].Stats()["spec_cycles"])

  def test_greedy_model_draft_hybrid_target(self, hybrid_lm, ssm_draft_lm):
    """Hybrid-SSM target (trajectory restore on the real path) driven by
    an independent pageless draft model."""
    task, theta = hybrid_lm
    dtask, dtheta = ssm_draft_lm
    reqs = _Stream(8, seed=13)
    outs = _BothModes(
        task, theta, reqs,
        spec_fn=lambda: spec_decode.ModelDraft(dtask, dtheta, k=2))
    assert outs["ragged"][0] == outs["legacy"][0]
    assert outs["ragged"][1].Stats()["spec_cycles"] > 0

  def test_temp_gt0_dense_nospec_byte_identical(self, tiny_lm):
    """temperature > 0: every draw is keyed by (row seed, output
    position), never by step index or slot — so the ragged packing must
    reproduce the legacy stream bitwise, not just in distribution."""
    task, theta = tiny_lm
    reqs = _Stream(10, seed=14)
    outs = _BothModes(task, theta, reqs, temperature=0.8, top_k=8,
                      sample_seed=7)
    assert outs["ragged"][0] == outs["legacy"][0]

  @pytest.mark.slow
  def test_greedy_hybrid_nospec_and_repeat_stack_draft(self, hybrid_lm):
    """Matrix tail: hybrid-SSM without a draft source (zero-length rows
    must not reset SSM states) and a RepeatedTransformerLayer target
    under early-exit self-speculation."""
    task, theta = hybrid_lm
    reqs = _Stream(10, seed=15)
    outs = _BothModes(task, theta, reqs)
    assert outs["ragged"][0] == outs["legacy"][0]
    rtask, rtheta = _Instantiate(
        _LmParams().Set(use_repeat_layer=True, num_layers=3))
    reqs = _Stream(8, seed=16)
    outs = _BothModes(
        rtask, rtheta, reqs,
        spec_fn=lambda: spec_decode.SelfDraft(k=3, num_layers=1))
    assert outs["ragged"][0] == outs["legacy"][0]

  @pytest.mark.slow
  def test_temp_gt0_spec_replays(self, tiny_lm):
    """temperature > 0 WITH a draft source is distribution-preserving,
    not legacy-byte-identical (the verify coin at a position replaces
    the plain draw there) — the contract is seeded replay determinism."""
    task, theta = tiny_lm
    reqs = _Stream(8, seed=17)
    runs = []
    for _ in range(2):
      eng = _Engine(task, theta, spec_decode.SelfDraft(k=3, num_layers=1),
                    temperature=0.7, top_k=8, sample_seed=21)
      runs.append(_RunStream(eng, reqs))
    assert runs[0] == runs[1]


# -- compiled-step-program census ---------------------------------------------


class TestStepProgramCensus:

  def test_ragged_compiles_exactly_one_step_program(self, tiny_lm):
    """A full lifecycle — staggered admissions, prefill/decode overlap,
    spec cycles, a cancellation, retirements — dispatches through ONE
    compiled program."""
    task, theta = tiny_lm
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=3, num_layers=1),
                  prefix_cache=True)
    h1 = eng.Submit([5, 6, 7, 8, 9, 10, 11], 8, eos_id=None)
    h2 = eng.Submit([3, 1], 6, eos_id=None)
    for _ in range(3):           # overlap: h1 still prefilling, h2 decoding
      eng.StepOnce()
    h3 = eng.Submit([2, 2, 2], 6, eos_id=None)
    victim = eng.Submit([4, 4, 4, 4], 6, eos_id=None)
    eng.StepOnce()
    eng.Cancel(victim.id)
    while eng.sched.HasWork():
      eng.StepOnce()
    for h in (h1, h2, h3):
      assert len(h.Result(timeout=0)) > 0
    stats = eng.Stats()
    comp = stats["compile"]
    assert comp[observe_schema.COMPILE_CENSUS_KEY] == 1
    assert set(comp) & observe_schema.STEP_PROGRAM_NAMES == {"ragged"}
    assert comp["ragged"]["calls"] > 0
    assert "fallback" not in comp["ragged"]
    # the lifecycle really was mixed: prefill rode decode steps and spec
    # cycles ran — all through that one program
    assert stats["mixed_steps"] > 0
    assert stats["spec_cycles"] > 0
    assert stats["scheduler"]["cancelled"] == 1
    assert stats["scheduler"]["finished"] == 3

  def test_legacy_trio_still_compiles_three(self, tiny_lm):
    """The comparison baseline keeps its three shapes — the 3 -> 1
    collapse is observable in the census, not just asserted in docs."""
    task, theta = tiny_lm
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=3, num_layers=1),
                  step_mode="legacy")
    _RunStream(eng, _Stream(4, seed=18))
    _RunStream(eng, [([5, 6], 3)], spec_k=0)   # opt-out -> plain decode
    comp = eng.Stats()["compile"]
    assert (set(comp) & observe_schema.STEP_PROGRAM_NAMES
            == {"decode", "mixed", "spec_verify"})
    assert comp[observe_schema.COMPILE_CENSUS_KEY] == 3


# -- BuildRaggedStep / CommitRaggedStep (device-free) -------------------------


def _MakeSched(slots=3, pages=24, page=4, table_pages=8, chunk=4, **kw):
  alloc = kv_cache.PageAllocator(pages, page)
  return scheduler_lib.Scheduler(slots, alloc, table_pages, chunk, **kw), alloc


def _Prefill(sched):
  """Drives ragged steps with fabricated draws until every live row has
  finished its prompt (or everything retired)."""
  while True:
    sched.Admit()
    batch = sched.BuildRaggedStep(16, 4)
    if batch is None:
      return
    sched.CommitRaggedStep(batch, np.full((16,), 7, np.int32))
    live = [s for s in sched.slots if s is not None]
    if all(s.state is scheduler_lib.SeqState.DECODE for s in live):
      return


class TestBuildRaggedStep:

  def test_decode_first_prefill_takes_leftover(self):
    sched, alloc = _MakeSched()
    sched.Submit(scheduler_lib.Request("a", [1, 2], 8))       # -> decode
    sched.Submit(scheduler_lib.Request("b", list(range(1, 11)), 4))
    sched.Admit()
    b1 = sched.BuildRaggedStep(8, 4, spec_k=2)
    sched.CommitRaggedStep(b1, np.full((8,), 7, np.int32))
    assert sched._by_id["a"].state is scheduler_lib.SeqState.DECODE
    # a decodes (spec_k=2 -> 3 tokens), b prefills with the leftover 5,
    # capped at wmax=4
    b2 = sched.BuildRaggedStep(8, 4, spec_k=2)
    np.testing.assert_array_equal(b2.rows_desc.row_len[:2], [3, 4])
    assert b2.row_k[0] == 2 and b2.any_spec and b2.mixed
    assert b2.prompt_tokens == 4
    # packed-token invariants: pos == row_q_pos[row] + col, trailing pad
    d = b2.rows_desc
    for tkn in range(8):
      if not d.valid[tkn]:
        continue
      r = d.row_of[tkn]
      assert d.pos[tkn] == d.row_q_pos[r] + d.col_of[tkn]
    assert d.valid.sum() == 7
    # the decode row's feedback token rides column 0; draft columns
    # stay zero until the engine fills Draft() proposals in
    assert b2.tok_ids[d.row_cols[0, 0]] == 7
    assert b2.ids[0, 0] == 7 and b2.in_len[0] == 1 and b2.in_len[1] == 0

  def test_zero_length_row_keeps_true_q_pos(self):
    """A live row that fits no budget this step must ride with its real
    q_pos: q_pos == 0 is the SSM state-reset trigger, so an idle row at
    pos > 0 advertising 0 would wipe its recurrent state."""
    sched, _ = _MakeSched(slots=2)
    sched.Submit(scheduler_lib.Request("a", list(range(1, 7)), 4))
    sched.Submit(scheduler_lib.Request("b", list(range(1, 7)), 4))
    sched.Admit()
    batch = sched.BuildRaggedStep(4, 4)   # budget covers only row a
    np.testing.assert_array_equal(batch.rows_desc.row_len, [4, 0])
    assert batch.rows_desc.row_q_pos[1] == 0  # b truly at pos 0 (prefill)
    sched.CommitRaggedStep(batch, np.full((4,), 7, np.int32))
    batch = sched.BuildRaggedStep(4, 4)
    np.testing.assert_array_equal(batch.rows_desc.row_len, [2, 2])
    assert batch.rows_desc.row_q_pos[0] == 4  # a rides at its true pos

  def test_spec_commit_rolls_back_rejected_and_eos_tail(self):
    sched, alloc = _MakeSched(slots=1)
    sched.Submit(scheduler_lib.Request("a", [1, 2, 3], 8, eos_id=9))
    _Prefill(sched)
    batch = sched.BuildRaggedStep(8, 4, spec_k=3)
    assert batch.row_k[0] == 3
    # verify accepted 2 of 3 drafts: cursor rolled back over the tail
    out = np.zeros((1, 4), np.int32)
    out[0, :3] = [5, 6, 7]
    before = alloc.Stats()["rolled_back_tokens"]
    ev = sched.CommitRaggedStep(batch, np.zeros((8,), np.int32),
                                out_tokens=out,
                                accept_len=np.array([2], np.int32))
    assert [t for _, t, _ in ev] == [5, 6, 7]
    assert alloc.Stats()["rolled_back_tokens"] - before == 1
    # eos INSIDE the accepted prefix: retire at eos, roll back the rest
    batch = sched.BuildRaggedStep(8, 4, spec_k=3)
    out[0, :3] = [5, 9, 7]
    before = alloc.Stats()["rolled_back_tokens"]
    ev = sched.CommitRaggedStep(batch, np.zeros((8,), np.int32),
                                out_tokens=out,
                                accept_len=np.array([3], np.int32))
    assert ev[-1] == ("a", 9, True)
    assert alloc.Stats()["rolled_back_tokens"] - before == 2
    assert sched.slots[0] is None


# -- cached-prefix-first admission --------------------------------------------


class TestPrefixOrderedAdmission:

  def _Pressured(self, ordered: bool) -> scheduler_lib.Scheduler:
    """A pool sized so the uncached head and the cached follower don't
    both fit: admission order decides whether the cached pages get
    reused (ordered) or sit behind the head (FIFO)."""
    alloc = kv_cache.PageAllocator(6, 4)
    cache = prefix_cache_lib.PrefixCache(alloc, None)
    sched = scheduler_lib.Scheduler(2, alloc, 4, 4, prefix_cache=cache)
    if not ordered:
      sched._NextWaiting = lambda: 0     # strict FIFO baseline
    # prime: run one request to completion so its prompt's full pages
    # land in the cache (retained there after retirement)
    prime = list(range(1, 9))            # 8 tokens = 2 full pages
    sched.Submit(scheduler_lib.Request("prime", prime, 1))
    _Prefill(sched)                      # max_new=1: retires at prefill end
    assert sched.slots[0] is None and cache.Stats()["cached_pages"] == 2
    # pressure: a big uncached head, then a follower matching the prime
    sched.Submit(scheduler_lib.Request("head", [30 + i for i in range(12)], 4))
    sched.Submit(scheduler_lib.Request("tail", prime, 4))
    sched.Admit()
    return sched

  def test_cached_follower_beats_uncached_head_under_pressure(self):
    ordered = self._Pressured(ordered=True)
    fifo = self._Pressured(ordered=False)
    o_hits = ordered.prefix_cache.Stats()["hit_tokens"]
    f_hits = fifo.prefix_cache.Stats()["hit_tokens"]
    assert o_hits > f_hits            # the whole point of the reorder
    assert o_hits == 7                # prime prompt minus the last token
    assert ordered.prefix_ordered_admissions == 1
    assert fifo.prefix_ordered_admissions == 0
    assert ordered.Stats()["prefix_ordered_admissions"] == 1
    # ordered: the cached tail is live; FIFO burned the pool on the head
    live = [s.id for s in ordered.slots if s is not None]
    assert "tail" in live
    flive = [s.id for s in fifo.slots if s is not None]
    assert flive == ["head"]

  def test_fifo_head_never_starves(self):
    """When the cache-ordered pick does not fit, the true FIFO head
    still gets its legacy try — reorder never starves the head."""
    alloc = kv_cache.PageAllocator(4, 4)
    cache = prefix_cache_lib.PrefixCache(alloc, None)
    sched = scheduler_lib.Scheduler(1, alloc, 4, 4, prefix_cache=cache)
    prime = list(range(1, 9))
    sched.Submit(scheduler_lib.Request("prime", prime, 1))
    _Prefill(sched)
    # head fits only if nothing else does; follower matches the cache
    # but needs MORE pages than remain free
    sched.Submit(scheduler_lib.Request("head", [40, 41], 2))
    sched.Submit(scheduler_lib.Request("tail", prime + [50, 51], 4))
    sched.Admit()
    live = [s.id for s in sched.slots if s is not None]
    assert live == ["head"]
    assert sched.prefix_ordered_admissions == 0
