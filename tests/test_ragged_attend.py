"""ops/ragged_block_attend.py: the unified ragged kernel's twin contract.

The op that collapses decode / chunked prefill / spec-verify into one
program must hold the same guarantees each specialized op held:
- XLA twin == Pallas(interpret) BITWISE, including dead-page clamp,
  q_len=1 degenerate rows, q_end=0 padding tokens, and page reuse after a
  real allocator eviction;
- stale block-table entries (freed/foreign pages) never leak into output;
- an all-decode token pack reproduces `BlockDecode` bit for bit and a
  prefill pack reproduces `BlockPrefill` (same `_PageAttend` float-op
  sequence) — the "three programs become views of one op" claim, at the
  op level;
- the int8 path stays bitwise-twinned through the shared `_DequantPages`.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lingvo_tpu.core import ragged
from lingvo_tpu.ops import block_decode
from lingvo_tpu.ops import ragged_block_attend
from lingvo_tpu.quant import kv as kv_quant
from lingvo_tpu.serving import kv_cache


def _QuantizePools(k_pool, v_pool):
  k8, ks = kv_quant.QuantizeKv(jnp.asarray(k_pool))
  v8, vs = kv_quant.QuantizeKv(jnp.asarray(v_pool))
  return (k8, jnp.swapaxes(ks, 1, 2).astype(jnp.float32),
          v8, jnp.swapaxes(vs, 1, 2).astype(jnp.float32))


class TestRaggedAttend:

  def _Inputs(self, b=3, t_pages=2, page=8, n=1, h=8, t=8, seed=0):
    rng = np.random.RandomState(seed)
    np_total = b * t_pages + 1
    q = rng.randn(t, n, h).astype(np.float32)
    k_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    v_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    tables = rng.permutation(np_total - 1).reshape(b, t_pages).astype(
        np.int32)
    return q, k_pool, v_pool, tables

  @staticmethod
  def _DenseRef(q, k_pool, v_pool, tables, row_of, q_end):
    """numpy masked softmax per packed token over its row's gathered view."""
    t, n, h = q.shape
    out = np.zeros_like(q)
    for ti in range(t):
      end = int(q_end[ti])
      if end == 0:
        continue
      row = int(row_of[ti])
      k = k_pool[tables[row]].reshape(-1, n, h)[:end]
      v = v_pool[tables[row]].reshape(-1, n, h)[:end]
      s = np.einsum("nh,snh->ns", q[ti], k)
      s = s - s.max(axis=-1, keepdims=True)
      p = np.exp(s)
      p /= p.sum(axis=-1, keepdims=True)
      out[ti] = np.einsum("ns,snh->nh", p, v)
    return out

  def _Both(self, q, kp, vp, tables, row_of, q_end, page=8, **kw):
    out_x = ragged_block_attend.RaggedAttend(
        jnp.asarray(q), kp, vp, jnp.asarray(tables), jnp.asarray(row_of),
        jnp.asarray(q_end), page_size=page, lowering="xla", **kw)
    out_p = ragged_block_attend.RaggedAttend(
        jnp.asarray(q), kp, vp, jnp.asarray(tables), jnp.asarray(row_of),
        jnp.asarray(q_end), page_size=page, lowering="pallas",
        interpret=True, **kw)
    np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
    return np.asarray(out_x)

  def test_mixed_rows_match_dense_reference(self):
    """One pack spanning the full row spectrum: a q_len=1 decode token, a
    3-token prefill chunk, a 3-token verify window, and a padding token."""
    q, k_pool, v_pool, tables = self._Inputs()
    #       decode row0 | prefill row1 (slots 4,5,6) | verify row2 | pad
    row_of = np.array([0, 1, 1, 1, 2, 2, 2, 0], np.int32)
    q_end = np.array([9, 5, 6, 7, 12, 13, 14, 0], np.int32)
    out = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), tables,
                     row_of, q_end)
    ref = self._DenseRef(q, k_pool, v_pool, tables, row_of, q_end)
    np.testing.assert_allclose(out, ref, atol=5e-6)
    # the padding token is exactly zero, not NaN
    np.testing.assert_array_equal(out[7], np.zeros_like(out[7]))

  def test_stale_table_entries_never_leak(self):
    """Table entries past a token's horizon may point anywhere (freed or
    foreign pages); they must not change the output."""
    q, k_pool, v_pool, tables = self._Inputs()
    row_of = np.array([0, 1, 1, 2, 2, 2, 0, 1], np.int32)
    q_end = np.array([3, 5, 6, 2, 3, 4, 4, 7], np.int32)  # page 1 dead
    out1 = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), tables,
                      row_of, q_end)
    hostile = tables.copy()
    hostile[:, 1] = [tables[1, 0], tables[2, 0], tables[0, 0]]  # alias
    out2 = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), hostile,
                      row_of, q_end)
    np.testing.assert_array_equal(out1, out2)

  def test_all_decode_pack_bitwise_equals_block_decode(self):
    """T tokens with one token per row reproduce BlockDecode exactly —
    the decode program was already this op."""
    q, k_pool, v_pool, tables = self._Inputs(b=3, t=3)
    lens = np.array([5, 16, 1], np.int32)
    row_of = np.arange(3, dtype=np.int32)
    out_r = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), tables,
                       row_of, lens)
    out_b = block_decode.BlockDecode(
        jnp.asarray(q)[:, None], jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(lens), page_size=8, lowering="xla")
    np.testing.assert_array_equal(out_r, np.asarray(out_b)[:, 0])

  def test_prefill_pack_bitwise_equals_block_prefill(self):
    """A packed prefill chunk reproduces BlockPrefill exactly — causal
    masking within the chunk is just each token's shorter horizon."""
    q, k_pool, v_pool, tables = self._Inputs(b=2, t=6)
    q_pos = np.array([2, 8], np.int32)
    in_len = np.array([3, 3], np.int32)
    row_of = np.array([0, 0, 0, 1, 1, 1], np.int32)
    q_end = np.array([3, 4, 5, 9, 10, 11], np.int32)   # q_pos + c + 1
    out_r = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), tables,
                       row_of, q_end)
    out_p = block_decode.BlockPrefill(
        jnp.asarray(q).reshape(2, 3, 1, 8), jnp.asarray(k_pool),
        jnp.asarray(v_pool), jnp.asarray(tables), jnp.asarray(q_pos),
        jnp.asarray(in_len), page_size=8)
    np.testing.assert_allclose(out_r.reshape(2, 3, 1, 8), np.asarray(out_p),
                               atol=5e-6)

  def test_twins_bitwise_equal_incl_page_reuse(self):
    """XLA == Pallas(interpret) bitwise before AND after a real allocator
    frees one sequence's pages and hands them to another (pool bytes
    overwritten in place — exactly what eviction + admission does)."""
    q, k_pool, v_pool, tables = self._Inputs(b=2, t=5)
    k_pool = jnp.asarray(k_pool)
    v_pool = jnp.asarray(v_pool)
    row_of = np.array([0, 1, 1, 1, 0], np.int32)
    q_end = np.array([5, 14, 15, 16, 0], np.int32)
    self._Both(q, k_pool, v_pool, tables, row_of, q_end)

    alloc = kv_cache.PageAllocator(num_pages=4, page_size=8)
    alloc.Allocate("a", 2)
    alloc.Allocate("b", 2)
    alloc.Free("a")
    reused = alloc.Allocate("c", 2)
    assert reused == [0, 1]
    rng = np.random.RandomState(7)
    for pg in reused:
      k_pool = k_pool.at[pg].set(rng.randn(8, 1, 8).astype(np.float32))
      v_pool = v_pool.at[pg].set(rng.randn(8, 1, 8).astype(np.float32))
    tables2 = np.array([reused, list(alloc.PagesOf("b"))], np.int32)
    q_end2 = np.array([10, 14, 15, 16, 12], np.int32)
    row_of2 = np.array([0, 1, 1, 1, 0], np.int32)
    out = self._Both(q, k_pool, v_pool, tables2, row_of2, q_end2)
    ref = self._DenseRef(q, np.asarray(k_pool), np.asarray(v_pool),
                         tables2, row_of2, q_end2)
    np.testing.assert_allclose(out, ref, atol=5e-6)

  def test_int8_twins_bitwise_and_match_float_on_dequant(self):
    """int8 XLA == int8 Pallas(interpret) bitwise, and both == the float
    kernel run on elementwise-dequantized pools: dequantize-on-read is the
    ONLY thing the quantized path adds."""
    q, k_pool, v_pool, tables = self._Inputs()
    k8, ks, v8, vs = _QuantizePools(k_pool, v_pool)
    kf = kv_quant.DequantKv(k8.swapaxes(1, 2), ks).swapaxes(1, 2)
    vf = kv_quant.DequantKv(v8.swapaxes(1, 2), vs).swapaxes(1, 2)
    row_of = np.array([0, 1, 1, 1, 2, 2, 2, 0], np.int32)
    q_end = np.array([9, 5, 6, 7, 12, 13, 14, 0], np.int32)
    out_q = self._Both(q, k8, v8, tables, row_of, q_end,
                       k_scale=ks, v_scale=vs)
    out_f = ragged_block_attend.RaggedAttend(
        jnp.asarray(q), kf, vf, jnp.asarray(tables), jnp.asarray(row_of),
        jnp.asarray(q_end), page_size=8, lowering="xla")
    np.testing.assert_array_equal(out_q, np.asarray(out_f))

  @pytest.mark.slow
  def test_twin_sweep_over_horizon_grid(self):
    """Twin equality across horizon grids incl. 0, 1, and capacity."""
    q, k_pool, v_pool, tables = self._Inputs(b=4, t_pages=2, t=4)
    row_of = np.arange(4, dtype=np.int32)
    for ends in ([0, 1, 8, 16], [16, 16, 16, 16], [0, 0, 0, 0],
                 [7, 9, 15, 3]):
      self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), tables,
                 row_of, np.asarray(ends, np.int32))

  def test_supported_on_tpu_gate_is_shared(self):
    assert ragged_block_attend.SupportedOnTpu(128, 128)
    assert not ragged_block_attend.SupportedOnTpu(8, 128)
    assert not ragged_block_attend.SupportedOnTpu(128, 8)


class TestAncestorMaskedAttend:
  """Per-token in-step ancestor visibility (tree speculation).

  A tree token's horizon is its causal window MINUS in-window slots that
  are not on its root path: slot s is visible iff s < q_end and (s below
  the row's step window, or bit (s - q_start) of the token's ancestor
  mask is set). Chain rows ship the -1/-1 sentinel masks and must stay
  BITWISE the unmasked kernel."""

  _Inputs = TestRaggedAttend._Inputs
  _Both = TestRaggedAttend._Both

  @staticmethod
  def _TreeRow(q_pos, parents):
    """Per-token (q_end, q_start, lo, hi) for one DFS-packed tree row."""
    lo, hi = ragged.TreeAncestorMasks(parents)
    n = len(parents) + 1
    q_end = q_pos + 1 + np.arange(n)          # own DFS slot inclusive
    q_start = np.full((n,), q_pos, np.int32)
    return q_end.astype(np.int32), q_start, lo, hi

  @staticmethod
  def _MaskedDenseRef(q, k_pool, v_pool, tables, row_of, q_end, q_start,
                      lo, hi):
    t, n, h = q.shape
    out = np.zeros_like(q)
    for ti in range(t):
      end = int(q_end[ti])
      if end == 0:
        continue
      mask = (np.int64(np.uint32(lo[ti]))
              | (np.int64(np.uint32(hi[ti])) << 32))
      slots = np.arange(end)
      c = np.clip(slots - int(q_start[ti]), 0, 63)
      keep = ((mask >> c) & 1).astype(bool)
      kk = k_pool[tables[int(row_of[ti])]].reshape(-1, n, h)[:end][keep]
      vv = v_pool[tables[int(row_of[ti])]].reshape(-1, n, h)[:end][keep]
      s = np.einsum("nh,snh->ns", q[ti], kk)
      s = s - s.max(axis=-1, keepdims=True)
      p = np.exp(s)
      p /= p.sum(axis=-1, keepdims=True)
      out[ti] = np.einsum("ns,snh->nh", p, vv)
    return out

  def test_tree_row_matches_masked_dense_reference(self):
    """A w=2,k=2 tree row next to a plain decode row: each tree token
    sees the committed prefix + its own root path, never its siblings;
    XLA == Pallas(interpret) bitwise throughout."""
    q, k_pool, v_pool, tables = self._Inputs()
    parents = [-1, 0, -1, 2]
    t_end, t_start, t_lo, t_hi = self._TreeRow(6, parents)
    row_of = np.array([0] * 5 + [1, 0, 0], np.int32)
    q_end = np.concatenate([t_end, [9, 0, 0]]).astype(np.int32)
    q_start = np.concatenate([t_start, [0, 0, 0]]).astype(np.int32)
    lo = np.concatenate([t_lo, [-1, -1, -1]]).astype(np.int32)
    hi = np.concatenate([t_hi, [-1, -1, -1]]).astype(np.int32)
    out = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), tables,
                     row_of, q_end, q_start=q_start, anc_lo=lo, anc_hi=hi)
    ref = self._MaskedDenseRef(q, k_pool, v_pool, tables, row_of, q_end,
                               q_start, lo, hi)
    np.testing.assert_allclose(out, ref, atol=5e-6)
    # the two branches are built over the same prefix but must differ
    # (each excludes the other's slots); padding stays exactly zero
    assert not np.array_equal(out[2], out[4])
    np.testing.assert_array_equal(out[7], np.zeros_like(out[7]))

  def test_chain_sentinels_bitwise_equal_unmasked(self):
    """-1/-1 masks with any q_start reproduce the unmasked kernel BIT FOR
    BIT on a mixed decode/prefill/verify pack — the no-regression proof
    for every pre-tree serving shape."""
    q, k_pool, v_pool, tables = self._Inputs()
    row_of = np.array([0, 1, 1, 1, 2, 2, 2, 0], np.int32)
    q_end = np.array([9, 5, 6, 7, 12, 13, 14, 0], np.int32)
    q_start = np.array([8, 2, 2, 2, 9, 9, 9, 0], np.int32)
    neg = np.full((8,), -1, np.int32)
    base = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool), tables,
                      row_of, q_end)
    masked = self._Both(q, jnp.asarray(k_pool), jnp.asarray(v_pool),
                        tables, row_of, q_end, q_start=q_start,
                        anc_lo=neg, anc_hi=neg)
    np.testing.assert_array_equal(base, masked)

  def test_masked_twins_bitwise_incl_page_reuse(self):
    """XLA == Pallas(interpret) bitwise on ancestor-masked packs before
    AND after a real allocator eviction hands one row's pages to another
    (the _Both helper asserts the twin equality on every call)."""
    q, k_pool, v_pool, tables = self._Inputs(b=2, t=5)
    k_pool = jnp.asarray(k_pool)
    v_pool = jnp.asarray(v_pool)
    parents = [-1, 0, 1, -1]                     # a 3-chain + 1 sibling
    t_end, t_start, lo5, hi5 = self._TreeRow(8, parents)
    row_of = np.array([0] * 5, np.int32)
    self._Both(q, k_pool, v_pool, tables, row_of, t_end,
               q_start=t_start, anc_lo=lo5, anc_hi=hi5)
    alloc = kv_cache.PageAllocator(num_pages=4, page_size=8)
    alloc.Allocate("a", 2)
    alloc.Allocate("b", 2)
    alloc.Free("a")
    reused = alloc.Allocate("c", 2)
    rng = np.random.RandomState(7)
    for pg in reused:
      k_pool = k_pool.at[pg].set(rng.randn(8, 1, 8).astype(np.float32))
      v_pool = v_pool.at[pg].set(rng.randn(8, 1, 8).astype(np.float32))
    tables2 = np.array([reused, list(alloc.PagesOf("b"))], np.int32)
    out = self._Both(q, k_pool, v_pool, tables2, row_of, t_end,
                     q_start=t_start, anc_lo=lo5, anc_hi=hi5)
    ref = self._MaskedDenseRef(q, np.asarray(k_pool), np.asarray(v_pool),
                               tables2, row_of, t_end, t_start, lo5, hi5)
    np.testing.assert_allclose(out, ref, atol=5e-6)

  def test_int8_masked_twins_bitwise(self):
    """The int8 path composes with ancestor masks: quantized XLA ==
    quantized Pallas(interpret) bitwise, both == the float kernel on
    dequantized pools."""
    q, k_pool, v_pool, tables = self._Inputs()
    k8, ks, v8, vs = _QuantizePools(k_pool, v_pool)
    kf = kv_quant.DequantKv(k8.swapaxes(1, 2), ks).swapaxes(1, 2)
    vf = kv_quant.DequantKv(v8.swapaxes(1, 2), vs).swapaxes(1, 2)
    parents = [-1, 0, -1, 2]
    t_end, t_start, t_lo, t_hi = self._TreeRow(6, parents)
    row_of = np.array([0] * 5 + [1, 1, 1], np.int32)
    q_end = np.concatenate([t_end, [5, 6, 7]]).astype(np.int32)
    q_start = np.concatenate([t_start, [4, 4, 4]]).astype(np.int32)
    lo = np.concatenate([t_lo, [-1, -1, -1]]).astype(np.int32)
    hi = np.concatenate([t_hi, [-1, -1, -1]]).astype(np.int32)
    out_q = self._Both(q, k8, v8, tables, row_of, q_end, k_scale=ks,
                       v_scale=vs, q_start=q_start, anc_lo=lo, anc_hi=hi)
    out_f = ragged_block_attend.RaggedAttend(
        jnp.asarray(q), kf, vf, jnp.asarray(tables), jnp.asarray(row_of),
        jnp.asarray(q_end), page_size=8, lowering="xla",
        q_start=jnp.asarray(q_start), anc_lo=jnp.asarray(lo),
        anc_hi=jnp.asarray(hi))
    np.testing.assert_array_equal(out_q, np.asarray(out_f))
