"""Async device infeed + deferred telemetry (runners/infeed.py; ref
CreateTpuEnqueueOps double-buffering, base_input_generator.py:446): batch
order and loss trajectories bit-identical to the sync path, producer
exceptions reach the executor retry path, clean Reset/shutdown across
program schedules, deferred-summary Flush ordering, and the async_infeed
kill switch."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.runners import executor as executor_lib
from lingvo_tpu.runners import infeed as infeed_lib
from lingvo_tpu.runners import program as program_lib

from tests.test_executor_hardening import (_MakeScheduleAndTask,
                                           _RegressionInput, _RegressionTask,
                                           _TaskParams)


class _CountedInput(_RegressionInput):
  """Deterministic regression input that counts (and can fail) pulls."""

  def __init__(self, fail_at=None, fail_msg="UNAVAILABLE: reader died",
               **kw):
    super().__init__(**kw)
    self.pulls = 0
    self._fail_at = fail_at
    self._fail_msg = fail_msg

  def GetPreprocessedInputBatch(self):
    self.pulls += 1
    if self._fail_at is not None and self.pulls == self._fail_at:
      raise RuntimeError(self._fail_msg)
    return super().GetPreprocessedInputBatch()


def _ProducerThreads():
  return [t for t in threading.enumerate() if "-producer" in t.name]


class TestDeviceInfeed:

  def test_bit_identical_order(self):
    """The consumed sequence equals calling the generator inline."""
    ref = _RegressionInput(seed=7)
    want = [ref.GetPreprocessedInputBatch() for _ in range(8)]
    gen = _RegressionInput(seed=7)

    def it():
      while True:
        yield gen.GetPreprocessedInputBatch()

    feed = infeed_lib.DeviceInfeed(it, depth=3)
    try:
      for k in range(8):
        got = feed.Get()
        np.testing.assert_array_equal(got.x, want[k].x)
        np.testing.assert_array_equal(got.y, want[k].y)
    finally:
      feed.Stop()

  def test_end_of_stream_latches_and_reset_restarts(self):
    def make_iter():
      return iter([NestedMap(x=np.ones(2)), NestedMap(x=np.zeros(2))])

    feed = infeed_lib.DeviceInfeed(make_iter, depth=2)
    assert feed.Get() is not None
    assert feed.Get() is not None
    assert feed.Get() is None
    assert feed.Get() is None  # latched: a second eval cycle must not hang
    feed.Reset()
    assert feed.Get() is not None  # fresh make_iter() after Reset
    feed.Stop()

  def test_producer_exception_propagates_and_latches(self):
    def it():
      yield NestedMap(x=np.ones(2))
      raise RuntimeError("UNAVAILABLE: socket closed")

    feed = infeed_lib.DeviceInfeed(it, depth=2)
    assert feed.Get() is not None
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
      feed.Get()
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
      feed.Get()  # latched, not end-of-data
    assert not feed.healthy
    feed.Reset()
    assert feed.healthy
    feed.Stop()

  def test_stop_joins_producer_thread(self):
    feed = infeed_lib.DeviceInfeed(
        lambda: iter(NestedMap(x=np.ones(2)) for _ in range(10**6)),
        depth=2, name="t-stop")
    feed.Get()
    assert any("t-stop" in t.name for t in _ProducerThreads())
    feed.Stop()
    deadline = time.time() + 5
    while time.time() < deadline and any(
        "t-stop" in t.name for t in _ProducerThreads()):
      time.sleep(0.02)
    assert not any("t-stop" in t.name for t in _ProducerThreads())


def _MakeProg(tmp_path, name, gen, async_infeed, on_device_loop,
              steps_per_loop=3, **overrides):
  task_p = _TaskParams(max_steps=100, steps_per_loop=steps_per_loop)
  task = task_p.Instantiate()
  task.FinalizePaths()
  tp = program_lib.TrainProgram.Params().Set(
      task=task_p, logdir=str(tmp_path / name), name=name,
      steps_per_loop=steps_per_loop, async_infeed=async_infeed,
      on_device_loop=on_device_loop, write_tensorboard=False, **overrides)
  prog = program_lib.TrainProgram(tp, task=task, input_generator=gen)
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  return prog, state


class TestTrainProgramAsync:

  @pytest.mark.parametrize("on_device_loop", [False, True])
  def test_loss_trajectory_bit_identical(self, tmp_path, on_device_loop):
    """Async vs sync over 4 loops: same batches, same device programs =>
    bitwise-equal losses and final theta (the GSPMD contract is untouched:
    identical placement, identical programs)."""
    losses = {}
    thetas = {}
    for mode in ("sync", "async"):
      gen = _CountedInput(seed=3)
      prog, state = _MakeProg(tmp_path, f"{mode}_{on_device_loop}", gen,
                              async_infeed=(mode == "async"),
                              on_device_loop=on_device_loop)
      seen = []
      for _ in range(4):
        state, result = prog.Run(state)
        seen.append(result["loss"])
      final = prog.Flush()
      if final is not None:
        seen.append(final["loss"])
      prog.Shutdown()
      # the per-Run result stream may lag/repeat by design; compare the
      # per-loop summaries, which carry exactly one entry per loop
      path = os.path.join(str(tmp_path / f"{mode}_{on_device_loop}"),
                          f"{mode}_{on_device_loop}", "summaries.jsonl")
      with open(path) as f:
        rows = [json.loads(line) for line in f]
      losses[mode] = [(r["step"], r["loss"]) for r in rows]
      thetas[mode] = jax.device_get(state.theta)
    assert losses["sync"] == losses["async"]  # bitwise: json round-trip
    for a, b in zip(jax.tree_util.tree_leaves(thetas["sync"]),
                    jax.tree_util.tree_leaves(thetas["async"])):
      np.testing.assert_array_equal(a, b)

  def test_kill_switch_restores_legacy_flow(self, tmp_path):
    """async_infeed=False never constructs infeed/telemetry machinery."""
    gen = _CountedInput(seed=1)
    prog, state = _MakeProg(tmp_path, "kill", gen, async_infeed=False,
                            on_device_loop=True)
    before = set(_ProducerThreads())
    state, result = prog.Run(state)
    assert prog._infeed is None and prog._telemetry is None
    assert prog._pending_telemetry is None
    assert set(_ProducerThreads()) == before
    # sync accounting keys still present (loop wall attribution satellite)
    assert "infeed_wait_s" in result and "host_overhead_s" in result
    assert gen.pulls == 3  # exactly steps_per_loop: no background prefetch
    prog.Shutdown()

  def test_result_lag_bounded_by_one_loop(self, tmp_path):
    gen = _CountedInput(seed=5)
    prog, state = _MakeProg(tmp_path, "lag", gen, async_infeed=True,
                            on_device_loop=True)
    state, r1 = prog.Run(state)       # first Run blocks for its own result
    assert "loss" in r1 and np.isfinite(r1["loss"])
    state, r2 = prog.Run(state)       # steady state: most recent COMPLETED
    assert "loss" in r2
    final = prog.Flush()              # lands loop 2's telemetry
    assert final is not None and "loss" in final
    path = os.path.join(str(tmp_path / "lag"), "lag", "summaries.jsonl")
    with open(path) as f:
      steps = [json.loads(l)["step"] for l in f]
    assert steps == [3, 6]            # one summary per loop, in order
    prog.Shutdown()

  def test_deferred_result_carries_accounting(self, tmp_path):
    gen = _CountedInput(seed=2)
    prog, state = _MakeProg(tmp_path, "acct", gen, async_infeed=True,
                            on_device_loop=True)
    state, result = prog.Run(state)
    for key in ("infeed_wait_s", "host_overhead_s", "infeed_queue_depth",
                "steps_per_second", "examples_per_second"):
      assert key in result, key
    prog.Shutdown()

  def test_input_stats_exported(self, tmp_path):
    class _StatsInput(_CountedInput):
      def InputStats(self):
        return {"records": 123, "dropped_too_long": 1}

    gen = _StatsInput(seed=2)
    prog, state = _MakeProg(tmp_path, "stats", gen, async_infeed=True,
                            on_device_loop=True)
    state, result = prog.Run(state)
    assert result["input_records"] == 123
    assert result["input_dropped_too_long"] == 1
    prog.Shutdown()

  def test_producer_exception_reaches_run(self, tmp_path):
    gen = _CountedInput(seed=0, fail_at=5)
    prog, state = _MakeProg(tmp_path, "fail", gen, async_infeed=True,
                            on_device_loop=True)
    state, _ = prog.Run(state)  # loop 1 consumes pulls 1..3
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
      for _ in range(3):
        state, _ = prog.Run(state)
    prog.Shutdown()


class TestExecutorIntegration:

  def test_transient_input_failure_recovers(self, tmp_path):
    """A transient producer death propagates into the executor's retry
    path, which restores the checkpoint, resets the infeed, and finishes."""
    logdir = str(tmp_path)
    task_p = _TaskParams(max_steps=30, steps_per_loop=5, save_interval=5)
    task = task_p.Instantiate()
    task.FinalizePaths()
    gen = _CountedInput(seed=0, fail_at=12)  # dies mid-loop 3
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_p, logdir=logdir, steps_per_loop=5)
    sched = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(train_program=train_p),
        task=task, input_generators={"Train": gen})
    ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task)
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 30
    assert gen.pulls > 12  # the producer really did die and restart

  def test_train_eval_train_schedule_clean_lifecycle(self, tmp_path):
    """Two full train->eval cycles: deferred telemetry flushes at program
    boundaries (current-loop results, ordered summaries), eval infeeds are
    throwaway per Run, and executor shutdown leaves no producer threads."""
    logdir = str(tmp_path)
    task_p = _TaskParams(max_steps=20, steps_per_loop=5)
    task = task_p.Instantiate()
    task.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_p, logdir=logdir, steps_per_loop=5, on_device_loop=True)
    eval_p = program_lib.EvalProgram.Params().Set(
        task=task_p, logdir=logdir, name="eval_test", steps_per_loop=2)
    sched = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(
            train_program=train_p, eval_programs=[eval_p]),
        task=task,
        input_generators={"Train": _RegressionInput(seed=0),
                          "Test": _RegressionInput(seed=9)})
    before = set(_ProducerThreads())
    ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task)
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 20
    # boundary Flush => metrics.jsonl carries the CURRENT cycle's train
    # loss at every step (no lag when eval programs run)
    with open(os.path.join(logdir, "metrics.jsonl")) as f:
      rows = [json.loads(l) for l in f]
    assert [r["step"] for r in rows] == [5, 10, 15, 20]
    assert all("loss" in r["train"] and "loss" in r["eval_test"]
               for r in rows)
    # train summaries landed for every loop, in step order
    with open(os.path.join(logdir, "train", "summaries.jsonl")) as f:
      steps = [json.loads(l)["step"] for l in f]
    assert steps == [5, 10, 15, 20]
    # executor Shutdown stopped all infeed producers it started
    deadline = time.time() + 5
    while time.time() < deadline and set(_ProducerThreads()) - before:
      time.sleep(0.02)
    assert not (set(_ProducerThreads()) - before)

  def test_nan_stop_still_fires_with_lagged_results(self, tmp_path):
    """NaN train loss stops the run within the documented staleness bound:
    <= pipeline_depth loops behind the offending loop (depth defaults to
    2; the pipelined executor polls the completed-result stream, so the
    NaN is seen as soon as backpressure or a poll resolves its loop)."""

    class _NanInput(_RegressionInput):
      def __init__(self, nan_from_pull, **kw):
        super().__init__(**kw)
        self.pulls = 0
        self._nan_from = nan_from_pull

      def GetPreprocessedInputBatch(self):
        self.pulls += 1
        b = super().GetPreprocessedInputBatch()
        if self.pulls >= self._nan_from:
          b.y = b.y + np.float32("nan")
        return b

    logdir = str(tmp_path)
    task_p = _TaskParams(max_steps=100, steps_per_loop=5, save_interval=100)
    task = task_p.Instantiate()
    task.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_p, logdir=logdir, steps_per_loop=5)
    sched = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(train_program=train_p),
        task=task, input_generators={"Train": _NanInput(6, seed=0)})
    ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task,
                                  max_train_retries=0)
    state = ex.Start()
    # NaN enters at loop 2 (steps 6-10); staleness <= pipeline_depth (2)
    # loops => the stop decision lands by the end of loop 4 (step 20)
    assert int(jax.device_get(state.step)) <= 20

  def test_nan_in_final_loop_reaches_trial_via_flush(self, tmp_path):
    """A NaN in the LAST loop before max_steps is only ever seen by the
    exit-time Flush (the lag-1 return path never surfaces it) — the
    executor must still report the trial infeasible."""
    from lingvo_tpu.core import base_trial

    class _RecordingTrial(base_trial.NoOpTrial):
      def __init__(self):
        self.done = None

      def ReportDone(self, infeasible=False, reason=""):
        if self.done is None or infeasible:
          self.done = (infeasible, reason)

    class _NanTailInput(_RegressionInput):
      def __init__(self, nan_from_pull, **kw):
        super().__init__(**kw)
        self.pulls = 0
        self._nan_from = nan_from_pull

      def GetPreprocessedInputBatch(self):
        self.pulls += 1
        b = super().GetPreprocessedInputBatch()
        if self.pulls >= self._nan_from:
          b.y = b.y + np.float32("nan")
        return b

    logdir = str(tmp_path)
    task_p = _TaskParams(max_steps=10, steps_per_loop=5, save_interval=100)
    task = task_p.Instantiate()
    task.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_p, logdir=logdir, steps_per_loop=5)
    sched = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(train_program=train_p),
        task=task,
        input_generators={"Train": _NanTailInput(6, seed=0)})  # loop 2 only
    trial = _RecordingTrial()
    ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task,
                                  trial=trial, max_train_retries=0)
    ex.Start()
    assert trial.done == (True, "nan_loss")


class TestEvalProgramInfeed:

  def test_eval_matches_sync_and_stops_cleanly(self, tmp_path):
    results = {}
    for mode in (False, True):
      task_p = _TaskParams()
      task = task_p.Instantiate()
      task.FinalizePaths()
      ep = program_lib.EvalProgram.Params().Set(
          task=task_p, logdir=str(tmp_path / str(mode)), name="eval_test",
          steps_per_loop=3, async_infeed=mode, write_tensorboard=False)
      prog = program_lib.EvalProgram(ep, task=task,
                                     input_generator=_RegressionInput(seed=4))
      state = task.CreateTrainState(jax.random.PRNGKey(0))
      before = set(_ProducerThreads())
      _, r = prog.Run(state)
      results[mode] = r["loss"]
      deadline = time.time() + 5
      while time.time() < deadline and set(_ProducerThreads()) - before:
        time.sleep(0.02)
      assert not (set(_ProducerThreads()) - before)  # stopped in finally
    assert results[False] == results[True]  # same batches, same program
