"""Evolved Transformer, CCT, LocalSelfAttentionXL, SingleShardFullSoftmax."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import attention_variants, cct, evolved_transformer, layers
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(3)
B, T, D = 2, 12, 16


def _mk(p):
  layer = p.Instantiate()
  layer.FinalizePaths()
  return layer, layer.InstantiateVariables(KEY)


class TestEvolvedTransformer:

  def test_encoder_branched_convs_shapes_and_padding(self):
    layer, theta = _mk(
        evolved_transformer.EvolvedTransformerEncoderBranchedConvsLayer
        .Params().Set(name="enc_bc", input_dim=D))
    x = jax.random.normal(KEY, (B, T, D))
    pads = jnp.zeros((B, T)).at[:, T // 2:].set(1.0)
    out = layer.FProp(theta, x, pads)
    assert out.shape == (B, T, D)
    # padded positions are zeroed
    np.testing.assert_allclose(np.asarray(out[:, T // 2:]), 0.0, atol=1e-6)

  def test_decoder_branched_convs_causal(self):
    """Future inputs must not affect past outputs (causal convs)."""
    layer, theta = _mk(
        evolved_transformer.EvolvedTransformerDecoderBranchedConvsLayer
        .Params().Set(name="dec_bc", input_dim=D))
    x = jax.random.normal(KEY, (B, T, D))
    out1 = layer.FProp(theta, x)
    x2 = x.at[:, -1].set(100.0)  # perturb final position only
    out2 = layer.FProp(theta, x2)
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5)

  def test_encoder_layer_end_to_end(self):
    layer, theta = _mk(
        evolved_transformer.EvolvedTransformerEncoderLayer.Params().Set(
            name="enc", input_dim=D, num_heads=2))
    x = jax.random.normal(KEY, (B, T, D))
    out = layer.FProp(theta, x, jnp.zeros((B, T)))
    assert out.shape == (B, T, D)
    assert np.all(np.isfinite(np.asarray(out)))

  def test_decoder_layer_causal_with_cross_attention(self):
    layer, theta = _mk(
        evolved_transformer.EvolvedTransformerDecoderLayer.Params().Set(
            name="dec", input_dim=D, num_heads=2))
    x = jax.random.normal(KEY, (B, T, D))
    aux = jax.random.normal(jax.random.PRNGKey(9), (B, 7, D))
    out1 = layer.FProp(theta, x, jnp.zeros((B, T)), aux_vecs=aux,
                       aux_paddings=jnp.zeros((B, 7)))
    assert out1.shape == (B, T, D)
    # causality through the whole layer
    x2 = x.at[:, -1].set(5.0)
    out2 = layer.FProp(theta, x2, jnp.zeros((B, T)), aux_vecs=aux,
                       aux_paddings=jnp.zeros((B, 7)))
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-4)

  def test_grads_flow(self):
    layer, theta = _mk(
        evolved_transformer.EvolvedTransformerEncoderLayer.Params().Set(
            name="enc", input_dim=D, num_heads=2))
    x = jax.random.normal(KEY, (B, T, D))

    def loss(th):
      return jnp.sum(layer.FProp(th, x, jnp.zeros((B, T))) ** 2)

    g = jax.grad(loss)(theta)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    nonzero = sum(float(jnp.sum(jnp.abs(l))) > 0 for l in leaves)
    assert nonzero >= len(leaves) - 2  # biases may start at exact 0 grad


class TestCCT:

  def test_gating_train_continuous_eval_discrete(self):
    gate, theta = _mk(cct.CCTGatingNetwork.Params().Set(
        name="g", input_dim=D, num_outputs=3, noise_std=0.0))
    x = jax.random.normal(KEY, (B, T, D))
    g_train = gate.FProp(theta, x)
    assert g_train.shape == (B, T, 3)
    assert np.all((np.asarray(g_train) > 0) & (np.asarray(g_train) < 1))
    with py_utils.EvalContext():
      g_eval = np.asarray(gate.FProp(theta, x))
    assert set(np.unique(g_eval)).issubset({0.0, 1.0})

  def test_attention_layer_gates_output(self):
    layer, theta = _mk(cct.CCTAttentionLayer.Params().Set(
        name="att", input_dim=D, num_heads=2, is_masked=True))
    x = jax.random.normal(KEY, (B, T, D))
    out, gates = layer.FProp(theta, x, paddings=jnp.zeros((B, T)))
    assert out.shape == (B, T, D)
    assert gates.query_gate.shape == (B, T, 1)

  def test_ffn_blocks_gated_and_aux_loss(self):
    layer, theta = _mk(cct.CCTFeedForwardLayer.Params().Set(
        name="ff", input_dim=D, hidden_dim=32, num_blocks=4,
        gate_loss_weight=0.1))
    x = jax.random.normal(KEY, (B, T, D))
    with py_utils.AuxLossContext() as aux:
      out, gates = layer.FProp(theta, x, jnp.zeros((B, T)))
    assert out.shape == (B, T, D)
    assert gates.shape == (B, T, 4)
    assert len(aux) == 1  # budget loss emitted

  def test_eval_zero_gate_blocks_contribute_nothing(self):
    layer, theta = _mk(cct.CCTFeedForwardLayer.Params().Set(
        name="ff", input_dim=D, hidden_dim=32, num_blocks=2))
    x = jax.random.normal(KEY, (B, T, D))
    with py_utils.EvalContext():
      out, gates = layer.FProp(theta, x, jnp.zeros((B, T)))
    g = np.asarray(gates)
    # recompute manually: zeroing gated-off blocks reproduces the output
    assert set(np.unique(g)).issubset({0.0, 1.0})


class TestLocalSelfAttentionXL:

  def _mk_xl(self, **kw):
    return _mk(attention_variants.LocalSelfAttentionXL.Params().Set(
        name="xl", input_dim=D, hidden_dim=D, num_heads=2, block_size=4,
        left_context=4, right_context=0, use_rotary_position_emb=False, **kw))

  def test_shapes_and_causality(self):
    layer, theta = self._mk_xl()
    x = jax.random.normal(KEY, (B, T, D))
    out1, _ = layer.FProp(theta, x, paddings=jnp.zeros((B, T)))
    assert out1.shape == (B, T, D)
    x2 = x.at[:, -1].set(9.0)
    out2, _ = layer.FProp(theta, x2, paddings=jnp.zeros((B, T)))
    np.testing.assert_allclose(
        np.asarray(out1[:, :-1]), np.asarray(out2[:, :-1]), atol=1e-5)

  def test_bias_math_matches_reference_loop(self):
    """The einsum bias == an explicit per-head loop (guards subscript
    typos: einsum is case-sensitive, so 'nh' vs 'NH' silently sums out
    the head axes instead of contracting them)."""
    import math as pymath
    layer, theta = self._mk_xl()
    w = layer.p.block_size
    n, h = layer.p.num_heads, layer._dim_per_head
    B, L = 1, 2
    qb = jax.random.normal(KEY, (B, L, w, n, h))
    kb = jax.random.normal(jax.random.PRNGKey(8), (B, L, 3 * w, n, h))
    rel = (jnp.arange(3 * w)[None, :] - w) - jnp.arange(w)[:, None]
    out = layer._AddRelPositionBias(theta, qb, kb, rel,
                                    jnp.zeros((B, L, n, w, 3 * w)))
    # reference: loop over heads/positions
    th = theta
    scale = 1.0 / pymath.sqrt(h)
    sin_emb = attention_variants._SinusoidRelEmbedding(
        jnp.arange(-(2 * w - 1), 2 * w), layer.p.input_dim)
    r = jnp.einsum("rd,dnh->rnh", sin_emb, th.w_rel)
    expect = np.zeros((B, L, n, w, 3 * w), np.float32)
    for ni in range(n):
      for qi in range(w):
        for ki in range(3 * w):
          ridx = int(rel[qi, ki]) + 2 * w - 1
          content = scale * float(th.u_bias[ni] @ kb[0, 0, ki, ni])
          pos = float((qb[0, 0, qi, ni] + scale * th.v_bias[ni])
                      @ r[ridx, ni])
          expect[0, 0, ni, qi, ki] = content + pos
    np.testing.assert_allclose(np.asarray(out)[:, :1], expect[:, :1],
                               rtol=2e-4, atol=2e-4)

  def test_position_bias_changes_logits(self):
    """XL bias must make outputs differ from the plain local attention with
    identical projection weights."""
    from lingvo_tpu.core import attention as attention_lib
    xl, xl_theta = self._mk_xl()
    plain, plain_theta = _mk(
        attention_lib.LocalSelfAttention.Params().Set(
            name="xl", input_dim=D, hidden_dim=D, num_heads=2, block_size=4,
            left_context=4, right_context=0,
            use_rotary_position_emb=False))
    # share the common projection weights
    for k in ("w_query", "w_key", "w_value", "w_post",
              "b_query", "b_key", "b_value", "b_post"):
      if k in plain_theta:
        xl_theta[k] = plain_theta[k]
    x = jax.random.normal(KEY, (B, T, D))
    out_xl, _ = xl.FProp(xl_theta, x, paddings=jnp.zeros((B, T)))
    out_plain, _ = plain.FProp(plain_theta, x, paddings=jnp.zeros((B, T)))
    assert not np.allclose(np.asarray(out_xl), np.asarray(out_plain))


class TestSingleShardFullSoftmax:

  def test_chunked_matches_unchunked(self):
    V = 50
    p_full = layers.SingleShardFullSoftmax.Params().Set(
        name="sm", input_dim=D, num_classes=V, chunk_size=0, random_seed=7)
    p_chunk = p_full.Copy().Set(chunk_size=5)
    full, theta = _mk(p_full)
    chunk, theta2 = _mk(p_chunk)
    x = jax.random.normal(KEY, (B, T, D))
    ids = jax.random.randint(KEY, (B, T), 0, V)
    out_full = full.FProp(theta, x, class_ids=ids)
    out_chunk = chunk.FProp(theta2, x, class_ids=ids)
    np.testing.assert_allclose(
        np.asarray(out_full.per_example_xent),
        np.asarray(out_chunk.per_example_xent), rtol=1e-5, atol=1e-5)

  def test_chunked_with_nondivisible_batch(self):
    V = 20
    sm, theta = _mk(layers.SingleShardFullSoftmax.Params().Set(
        name="sm", input_dim=D, num_classes=V, chunk_size=7))
    x = jax.random.normal(KEY, (3, 5, D))  # 15 rows, not divisible by 7
    ids = jax.random.randint(KEY, (3, 5), 0, V)
    out = sm.FProp(theta, x, class_ids=ids)
    assert out.per_example_xent.shape == (3, 5)
    assert np.all(np.isfinite(np.asarray(out.per_example_xent)))
