"""Quantized RNN-cell / attention / conformer-conv domains (VERDICT r3
Missing #2, round-3 task #8): QDomain hooks matching the reference's
placement — `lingvo/core/rnn_cell.py:279-297,578-645` (weight /
fullyconnected / c_state / m_state domains in LSTMCellSimple),
`lingvo/core/attention.py:440` (qsoftmax), `batch_major_attention.py:303`
(projection TrackQWeight) — plus int8-deployment equivalence.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import attention as attention_lib
from lingvo_tpu.core import conformer_layer
from lingvo_tpu.core import quant_utils
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core import rnn_layers

KEY = jax.random.PRNGKey(0)


def _QuantLstmParams(**kw):
  """LSTM with the full reference domain placement; every activation
  domain is stateless (scan-safe)."""
  return rnn_cell.LSTMCellSimple.Params().Set(
      name="lstm",
      num_input_nodes=8,
      num_output_nodes=8,
      qdomain_weight=quant_utils.PerChannelSymmetricQDomain.Params().Set(
          act_names=()),
      qdomain_fullyconnected=quant_utils.ScheduledClipQDomain.Params().Set(
          start_cap=8.0, end_cap=8.0),
      qdomain_c_state=quant_utils.FixedRangeQDomain.Params().Set(
          range_min=-10.0, range_max=10.0),
      qdomain_m_state=quant_utils.FixedRangeQDomain.Params().Set(
          range_min=-1.0, range_max=1.0),
      **kw)


class TestQuantizedLstm:

  def test_quantized_cell_fprop_close_to_float(self):
    qp = _QuantLstmParams()
    fp = rnn_cell.LSTMCellSimple.Params().Set(
        name="lstm", num_input_nodes=8, num_output_nodes=8)
    qcell = qp.Instantiate()
    qcell.FinalizePaths()
    fcell = fp.Instantiate()
    fcell.FinalizePaths()
    qtheta = qcell.InstantiateVariables(KEY)
    ftheta = fcell.InstantiateVariables(KEY)  # same seed -> same wm/b
    np.testing.assert_allclose(np.asarray(qtheta.wm), np.asarray(ftheta.wm))

    x = 0.5 * jax.random.normal(jax.random.PRNGKey(1), (4, 8))
    state0 = qcell.InitState(4)
    qs = qcell.FProp(qtheta, state0, x)
    fs = fcell.FProp(ftheta, state0, x)
    # 8-bit fake quant perturbs but tracks the float math
    assert float(jnp.max(jnp.abs(qs.m - fs.m))) < 0.1
    assert not np.allclose(np.asarray(qs.m), np.asarray(fs.m))

  def test_quantized_lstm_trains_under_scan(self):
    """The stateless domains must survive lax.scan (FRNN) + grad."""
    p = rnn_layers.FRNN.Params().Set(name="frnn", cell=_QuantLstmParams())
    layer = p.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 8))

    def _Loss(th):
      out, _ = layer.FProp(th, x)
      return jnp.sum(out ** 2)

    loss, grads = jax.jit(jax.value_and_grad(_Loss))(theta)
    assert np.isfinite(float(loss))
    gsum = float(sum(jnp.sum(jnp.abs(g)) for g in jax.tree.leaves(grads)))
    assert np.isfinite(gsum) and gsum > 0

  def test_lstm_qat_matches_int8_deployment(self):
    """QAT weight simulation == dequantized int8 serving weight (the same
    guarantee the projection layer test gives, now for the gate matmul)."""
    qp = _QuantLstmParams()
    cell = qp.Instantiate()
    cell.FinalizePaths()
    theta = cell.InstantiateVariables(KEY)
    w_qat = cell._QWeight(theta, "weight", theta.wm)
    w_int8, scale = quant_utils.Int8QuantizeWeight(theta.wm, per_channel=True)
    w_deploy = w_int8.astype(jnp.float32) * scale
    np.testing.assert_allclose(np.asarray(w_qat), np.asarray(w_deploy),
                               atol=1e-6)

  def test_layer_norm_variant_quantizes_weight(self):
    p = _QuantLstmParams()
    lp = rnn_cell.LayerNormalizedLSTMCellSimple.Params().Set(
        name="lnlstm", num_input_nodes=8, num_output_nodes=8,
        qdomain_weight=p.qdomain_weight)
    cell = lp.Instantiate()
    cell.FinalizePaths()
    theta = cell.InstantiateVariables(KEY)
    state = cell.FProp(theta, cell.InitState(2),
                       jax.random.normal(KEY, (2, 8)))
    assert np.all(np.isfinite(np.asarray(state.m)))


class TestQuantizedAttention:

  def _mha(self, **kw):
    p = attention_lib.MultiHeadedAttention.Params().Set(
        name="mha", input_dim=16, hidden_dim=16, num_heads=2, **kw)
    layer = p.Instantiate()
    layer.FinalizePaths()
    return layer, layer.InstantiateVariables(KEY)

  def test_softmax_domain_quantizes_probs(self):
    layer, theta = self._mha(
        qdomain_softmax=quant_utils.FixedRangeQDomain.Params().Set(
            range_min=0.0, range_max=1.0))
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 6, 16))
    out, probs = layer.FProp(theta, x)
    assert out.shape == (2, 6, 16) and probs is not None
    # probs land on the 8-bit lattice over [0, 1]
    lattice = np.asarray(probs, np.float64) * 255.0
    np.testing.assert_allclose(lattice, np.round(lattice), atol=1e-3)
    np.testing.assert_allclose(np.asarray(jnp.sum(probs, -1)), 1.0, atol=0.05)

  def test_weight_domain_perturbs_but_tracks_float(self):
    qlayer, qtheta = self._mha(
        qdomain_weight=quant_utils.PerChannelSymmetricQDomain.Params().Set(
            act_names=()))
    flayer, ftheta = self._mha()
    np.testing.assert_allclose(
        np.asarray(qtheta.w_query), np.asarray(ftheta.w_query))
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 6, 16))
    qout, _ = qlayer.FProp(qtheta, x)
    fout, _ = flayer.FProp(ftheta, x)
    assert float(jnp.max(jnp.abs(qout - fout))) < 0.1
    assert not np.allclose(np.asarray(qout), np.asarray(fout))

  def test_softmax_domain_disables_flash(self):
    layer, _ = self._mha(
        use_flash_attention=True,
        qdomain_softmax=quant_utils.FixedRangeQDomain.Params().Set(
            range_min=0.0, range_max=1.0))
    assert not layer._FlashEligible(None, None, False, 64)

  def test_quantized_extend_step_matches_fprop(self):
    """Incremental decode must see the same quantized weights/probs."""
    layer, theta = self._mha(
        use_bias=False,
        qdomain_weight=quant_utils.PerChannelSymmetricQDomain.Params().Set(
            act_names=()),
        qdomain_softmax=quant_utils.FixedRangeQDomain.Params().Set(
            range_min=0.0, range_max=1.0))
    x = 0.3 * jax.random.normal(jax.random.PRNGKey(5), (1, 4, 16))
    full, _ = layer.FProp(theta, x, atten_mask=attention_lib.CausalMask(4))
    states = layer.InitStates(theta, 1, 4)
    outs = []
    for t in range(4):
      o, states = layer.ExtendStep(theta, x[:, t:t + 1], states)
      outs.append(o)
    inc = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(inc),
                               atol=2e-5, rtol=2e-3)


class TestQuantizedAttentionVariants:

  def test_xl_softmax_domain_quantizes_probs(self):
    from lingvo_tpu.core import attention_variants
    p = attention_variants.TransformerXLAttention.Params().Set(
        name="xl", input_dim=16, hidden_dim=16, num_heads=2,
        qdomain_softmax=quant_utils.FixedRangeQDomain.Params().Set(
            range_min=0.0, range_max=1.0))
    layer = p.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 6, 16))
    _, probs = layer.FProp(theta, x)
    lattice = np.asarray(probs, np.float64) * 255.0
    np.testing.assert_allclose(lattice, np.round(lattice), atol=1e-3)


class TestQuantizedConformerConv:

  def test_lconv_quantized_stream_equals_offline(self):
    p = conformer_layer.LConvLayer.Params().Set(
        name="lconv", input_dim=8, kernel_size=4, causal=True,
        conv_norm="ln",
        qdomain=quant_utils.PerChannelSymmetricQDomain.Params().Set(
            act_names=()))
    layer = p.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(jax.random.PRNGKey(6), (2, 8, 8))
    offline = layer.FProp(theta, x)
    states = layer.InitStreamStates(2)
    chunks = []
    for c in range(0, 8, 4):
      y, states = layer.StreamStep(theta, x[:, c:c + 4], None, states)
      chunks.append(y)
    streamed = jnp.concatenate(chunks, axis=1)
    np.testing.assert_allclose(np.asarray(offline), np.asarray(streamed),
                               atol=1e-5)
