"""In-loop summary tests (ref tpu_summary_test coverage)."""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import tpu_summary
from lingvo_tpu.core.nested_map import NestedMap


class TestTpuSummary:

  def test_inactive_is_noop(self):
    assert not tpu_summary.enabled()
    tpu_summary.scalar("x", 1.0)  # must not raise

  def test_scalar_mean_merge(self):
    with tpu_summary.Context() as collected:
      tpu_summary.scalar("a", 1.0)
      tpu_summary.scalar("a", 3.0)
      tpu_summary.scalar("b", 5.0)
    merged = tpu_summary.Merged(collected)
    assert float(merged.a) == 2.0
    assert float(merged.b) == 5.0

  def test_tensor_last_wins(self):
    with tpu_summary.Context() as collected:
      tpu_summary.tensor("t", jnp.zeros((3,)))
      tpu_summary.tensor("t", jnp.ones((3,)))
    merged = tpu_summary.Merged(collected)
    np.testing.assert_allclose(np.asarray(merged.t), np.ones(3))

  def test_under_jit(self):
    """Summaries emitted inside a jitted fn flow out as results."""

    def fn(x):
      with tpu_summary.Context() as collected:
        tpu_summary.scalar("mean_x", jnp.mean(x))
        y = x * 2
      return y, tpu_summary.Merged(collected)

    y, summaries = jax.jit(fn)(jnp.arange(4.0))
    assert float(summaries.mean_x) == 1.5

  def test_scoped_names_are_sanitized(self):
    with tpu_summary.Context() as collected:
      tpu_summary.scalar("moe/load_balance.aux", 2.0)
    merged = tpu_summary.Merged(collected)
    assert float(merged.moe_load_balance_aux) == 2.0

  def test_train_program_accumulates_summaries(self, tmp_path):
    """Scoped tpu_summary scalars flow through TrainProgram in BOTH loop
    modes (the program path crashed on 'summary/x' NestedMap keys before)."""
    import numpy as np
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    from lingvo_tpu.core import base_model
    from lingvo_tpu.runners import program as program_lib

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input

    orig_fprop = mp.task.cls.FProp

    class _SummaryLm(mp.task.cls):

      def FProp(self, theta, batch):
        tpu_summary.scalar("lm/ids.mean", jnp.mean(
            batch.ids.astype(jnp.float32)))
        return orig_fprop(self, theta, batch)

    mp.task.SetClass(_SummaryLm)
    for on_device in (False, True):
      task = mp.task.Instantiate()
      task.FinalizePaths()
      state = task.CreateTrainState(jax.random.PRNGKey(0))
      tp = program_lib.TrainProgram.Params().Set(
          task=mp.task, logdir=str(tmp_path / str(on_device)),
          steps_per_loop=2, on_device_loop=on_device)
      prog = program_lib.TrainProgram(
          tp, task=task, input_generator=mp.input.Instantiate())
      _, result = prog.Run(state)
      assert "summary_lm_ids_mean" in result, (on_device, result.keys())
      assert np.isfinite(result["summary_lm_ids_mean"])

  def test_train_step_emits_summaries(self):
    """tpu_summary.scalar inside a task FProp lands in TrainStep output."""
    from lingvo_tpu.core import base_model
    from lingvo_tpu.core.nested_map import NestedMap as NM

    class _Task(base_model.BaseTask):

      def FProp(self, theta, batch):
        tpu_summary.scalar("inner_norm", jnp.sum(batch.x))
        loss = jnp.mean(batch.x) * theta.dummy_w[0]
        return NM(loss=(loss, 1.0)), NM()

      def _CreateChildrenHook(self):
        super()._CreateChildrenHook()
        from lingvo_tpu.core.py_utils import WeightParams, WeightInit
        self.CreateVariable(
            "dummy_w", WeightParams((1,), WeightInit.Constant(1.0),
                                    jnp.float32))

    p = _Task.Params().Set(name="t")
    task = p.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    batch = NM(x=jnp.ones((2, 3)))
    _, out = jax.jit(task.TrainStep)(state, batch)
    assert "summaries" in out
    assert float(out.summaries.inner_norm) == 6.0
