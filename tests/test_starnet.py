"""StarNet detector tests (ref starnet_test coverage)."""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401
from lingvo_tpu.models.car import starnet

KEY = jax.random.PRNGKey(19)


class TestFps:

  def test_spreads_and_avoids_padding(self):
    pts = jnp.array([[[0, 0, 0, 0], [10, 0, 0, 0], [0.1, 0, 0, 0],
                      [99, 99, 99, 0]]], jnp.float32)
    pads = jnp.array([[0, 0, 0, 1]], jnp.float32)
    idx = starnet.FarthestPointSampling(pts, pads, 2)
    picked = set(np.asarray(idx)[0].tolist())
    assert 3 not in picked          # padded point never selected
    assert {0, 1} <= picked or {1, 2} <= picked  # far pair chosen


class TestStarNetModel:

  def _setup(self):
    mp = model_registry.GetParams("car.kitti.StarNetCarTiny", "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    return task, state, batch, gen

  def test_train_step_decreases_loss(self):
    task, state, batch, gen = self._setup()
    step = jax.jit(task.TrainStep, donate_argnums=(0,))
    losses = []
    for _ in range(10):
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]

  def test_decode_and_ap_metric(self):
    task, state, batch, gen = self._setup()
    out = jax.jit(task.Decode)(state.theta, batch)
    assert out.boxes.shape[-1] == 7
    metrics = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(out, metrics)
    res = task.DecodeFinalize(metrics)
    assert 0.0 <= res["ap"] <= 1.0

  def test_assignment_radius(self):
    task, state, batch, gen = self._setup()
    centers = jnp.array([[[1.0, 1.0], [5.0, 5.0]]])
    gt_boxes = jnp.zeros((1, 2, 7)).at[0, 0, :2].set(
        jnp.array([1.2, 1.0])).at[0, 1, :2].set(jnp.array([30.0, 30.0]))
    gt_classes = jnp.array([[1, 2]])
    fg, box, cls = task._AssignTargets(centers, gt_boxes, gt_classes)
    assert bool(fg[0, 0]) and not bool(fg[0, 1])
    assert int(cls[0, 0]) == 1 and int(cls[0, 1]) == 0
