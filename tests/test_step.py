"""Step API tests (ref step_test.py / steps/*_test.py coverage)."""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import layers, rnn_cell, rnn_layers, seq_attention, step
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(7)
B, T, D, H = 2, 6, 4, 5


def _Materialize(p):
  s = p.Instantiate()
  s.FinalizePaths()
  return s, s.InstantiateVariables(KEY)


class TestRnnStep:

  def test_matches_frnn(self):
    """Driving an RnnStep over a sequence == FRNN.FProp on the same weights."""
    cell_p = rnn_cell.LSTMCellSimple.Params().Set(
        num_input_nodes=D, num_output_nodes=H, random_seed=3)
    frnn, frnn_theta = _Materialize(
        rnn_layers.FRNN.Params().Set(name="frnn", cell=cell_p.Copy()))
    st, st_theta = _Materialize(
        step.RnnStep.Params().Set(name="frnn", cell=cell_p.Copy()))
    # random_seed pins the var init so both copies share weights
    np.testing.assert_allclose(
        np.asarray(frnn_theta.cell.wm), np.asarray(st_theta.cell.wm))

    x = jax.random.normal(KEY, (B, T, D))
    pad = jnp.zeros((B, T))
    ref_out, _ = frnn.FProp(frnn_theta, x, pad)

    prepared = st.PrepareExternalInputs(st_theta, NestedMap())
    outs, _ = step.RunOverSequence(st, st_theta, prepared, x, pad)
    np.testing.assert_allclose(
        np.asarray(ref_out), np.asarray(outs.output), rtol=1e-5, atol=1e-5)


class TestStackStep:

  def test_residual_stack(self):
    cell_p = rnn_cell.LSTMCellSimple.Params().Set(
        num_input_nodes=D, num_output_nodes=D)
    p = step.RnnStackStep(cell_p, num_layers=3, residual_start=1)
    p.name = "stack"
    st, theta = _Materialize(p)
    prepared = st.PrepareExternalInputs(theta, NestedMap())
    state = st.ZeroState(theta, prepared, B)
    assert len(state.sub) == 3
    out, state1 = st.FProp(theta, prepared,
                           NestedMap(inputs=[jnp.ones((B, D))]),
                           jnp.zeros((B,)), state)
    assert out.output.shape == (B, D)
    # residual changes output vs no-residual stack
    p2 = step.RnnStackStep(cell_p, num_layers=3, residual_start=-1)
    p2.name = "stack"
    st2, theta2 = _Materialize(p2)
    prepared2 = st2.PrepareExternalInputs(theta2, NestedMap())
    out2, _ = st2.FProp(theta2, prepared2,
                        NestedMap(inputs=[jnp.ones((B, D))]),
                        jnp.zeros((B,)), st2.ZeroState(theta2, prepared2, B))
    assert not np.allclose(np.asarray(out.output), np.asarray(out2.output))


class TestParallelStep:

  def test_concat_outputs(self):
    cell_p = rnn_cell.GRUCell.Params().Set(
        num_input_nodes=D, num_output_nodes=H)
    p = step.ParallelStep.Params().Set(
        name="par",
        sub=[step.RnnStep.Params().Set(cell=cell_p.Copy()) for _ in range(2)])
    st, theta = _Materialize(p)
    prepared = st.PrepareExternalInputs(theta, NestedMap())
    state = st.ZeroState(theta, prepared, B)
    out, _ = st.FProp(theta, prepared, NestedMap(inputs=[jnp.ones((B, D))]),
                      jnp.zeros((B,)), state)
    assert out.output.shape == (B, 2 * H)


class TestIteratorStep:

  def test_iterates_time_dim(self):
    st, theta = _Materialize(step.IteratorStep.Params().Set(name="it"))
    x = jax.random.normal(KEY, (B, T, D))
    pad = jnp.zeros((B, T))
    prepared = st.PrepareExternalInputs(
        theta, NestedMap(inputs=x, paddings=pad))
    state = st.ZeroState(theta, prepared, B)
    for t in range(3):
      out, state = st.FProp(theta, prepared, NestedMap(inputs=[]),
                            None, state)
      np.testing.assert_allclose(np.asarray(out.output), np.asarray(x[:, t]))


class TestAttentionStep:

  def test_context_over_source(self):
    atten_p = seq_attention.AdditiveAttention.Params().Set(
        source_dim=D, query_dim=H, hidden_dim=6)
    st, theta = _Materialize(
        step.AttentionStep.Params().Set(name="att", atten=atten_p))
    src = jax.random.normal(KEY, (B, T, D))
    pad = jnp.zeros((B, T))
    prepared = st.PrepareExternalInputs(
        theta, NestedMap(src=src, paddings=pad))
    state = st.ZeroState(theta, prepared, B)
    q = jax.random.normal(KEY, (B, H))
    out, state1 = st.FProp(theta, prepared, NestedMap(inputs=[q]),
                           jnp.zeros((B,)), state)
    assert out.context.shape == (B, D)
    assert out.probs.shape == (B, T)
    np.testing.assert_allclose(np.asarray(jnp.sum(out.probs, -1)),
                               np.ones(B), rtol=1e-5)


class TestEmbeddingAndStateless:

  def test_embedding_step(self):
    emb_p = layers.SimpleEmbeddingLayer.Params().Set(
        vocab_size=11, embedding_dim=D)
    st, theta = _Materialize(
        step.EmbeddingStep.Params().Set(name="emb", emb=emb_p))
    prepared = st.PrepareExternalInputs(theta, NestedMap())
    state = st.ZeroState(theta, prepared, B)
    out, _ = st.FProp(theta, prepared,
                      NestedMap(inputs=[jnp.array([1, 2])]), None, state)
    assert out.output.shape == (B, D)

  def test_stateless_layer_step(self):
    fc = layers.FCLayer.Params().Set(input_dim=D, output_dim=H)
    st, theta = _Materialize(
        step.StatelessLayerStep.Params().Set(name="fc", layer=fc))
    prepared = st.PrepareExternalInputs(theta, NestedMap())
    out, _ = st.FProp(theta, prepared,
                      NestedMap(inputs=[jnp.ones((B, D))]), None,
                      st.ZeroState(theta, prepared, B))
    assert out.output.shape == (B, H)


class TestComposition:

  def test_scan_full_decoder_loop(self):
    """Embedding -> RNN -> attention composed as steps, run under scan."""
    cell_p = rnn_cell.LSTMCellSimple.Params().Set(
        num_input_nodes=D, num_output_nodes=H)
    stack = step.StackStep.Params().Set(
        name="dec",
        sub=[step.RnnStep.Params().Set(cell=cell_p)])
    st, theta = _Materialize(stack)
    x = jax.random.normal(KEY, (B, T, D))
    pad = jnp.zeros((B, T))
    prepared = st.PrepareExternalInputs(theta, NestedMap())
    outs, final = jax.jit(
        lambda th, x, pad: step.RunOverSequence(
            st, th, prepared, x, pad))(theta, x, pad)
    assert outs.output.shape == (B, T, H)
    assert np.all(np.isfinite(np.asarray(outs.output)))
