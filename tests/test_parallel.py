"""Parallelism tests on the 8-virtual-device CPU mesh.

Strictly stronger than the reference's strategy (SURVEY.md §4: sharding
annotations checked on CPU without real partitioning) — these run REAL SPMD
partitioning on fake devices: DP gradient equivalence, TP sharded layers,
MoE gating math + dispatch, ring attention vs full attention, pipeline vs
sequential.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.parallel import gshard, mesh as mesh_lib, pipeline, ring_attention

KEY = jax.random.PRNGKey(11)


def _RequireDevices(n):
  if len(jax.devices()) < n:
    pytest.skip(f"needs {n} devices")


class TestMesh:

  def test_make_mesh_with_wildcard(self):
    _RequireDevices(8)
    m = mesh_lib.MakeMesh({"data": -1, "model": 2})
    assert m.shape["data"] == 4 and m.shape["model"] == 2

  def test_spec_from_split_dims(self):
    spec = mesh_lib.SpecFromSplitDims((None, "model", ("data", "model")))
    assert spec == PartitionSpec(None, "model", ("data", "model"))

  def test_sharding_for_weight_skips_nondividing(self):
    _RequireDevices(8)
    m = mesh_lib.MakeMesh({"data": 4, "model": 2})
    wp = py_utils.WeightParams((7, 64), tensor_split_dims_mapping=("model",
                                                                  None))
    s = mesh_lib.ShardingForWeight(m, wp)
    assert s.spec == PartitionSpec(None, None)  # 7 % 2 != 0 -> replicated
    wp2 = py_utils.WeightParams((8, 64), tensor_split_dims_mapping=("model",
                                                                   None))
    assert mesh_lib.ShardingForWeight(m, wp2).spec == PartitionSpec(
        "model", None)

  def test_missing_axis_dropped(self):
    _RequireDevices(8)
    m = mesh_lib.MakeMesh({"data": 8})
    wp = py_utils.WeightParams((16, 16),
                               tensor_split_dims_mapping=("model", None))
    assert mesh_lib.ShardingForWeight(m, wp).spec == PartitionSpec(None, None)


class TestDataParallelEquivalence:
  """DP over 8 devices must produce the same update as single-device."""

  def test_dp_train_step_matches_single_device(self):
    _RequireDevices(8)
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    mp.task.input.batch_size = 8
    task = mp.task.Instantiate()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)

    # single device
    step = jax.jit(task.TrainStep)
    s1, out1 = step(state, batch)

    # 8-way DP: shard batch over 'data', replicate state
    m = mesh_lib.MakeMesh({"data": 8})
    sharded_batch = mesh_lib.PutBatch(m, batch)
    repl = jax.device_put(
        state, NamedSharding(m, PartitionSpec()))
    s2, out2 = jax.jit(task.TrainStep)(repl, sharded_batch)
    np.testing.assert_allclose(
        float(out1.metrics.loss[0]), float(out2.metrics.loss[0]), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.theta),
                    jax.tree_util.tree_leaves(s2.theta)):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


class TestTensorParallel:

  def test_tp_sharded_lm_matches_replicated(self):
    _RequireDevices(8)
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    m1, _ = jax.jit(task.EvalStep)(theta, batch)

    mesh = mesh_lib.MakeMesh({"data": 2, "model": 4})
    shardings = mesh_lib.ThetaShardings(mesh, task, theta)
    theta_sharded = jax.device_put(theta, shardings)
    # verify at least one weight actually sharded over 'model'
    flat = dict(theta_sharded.FlattenItems())
    atten_w = [v for k, v in flat.items() if k.endswith("w_query")]
    assert atten_w and "model" in str(atten_w[0].sharding.spec)
    batch_sharded = mesh_lib.PutBatch(mesh, batch)
    m2, _ = jax.jit(task.EvalStep)(theta_sharded, batch_sharded)
    np.testing.assert_allclose(
        float(m1.loss[0]), float(m2.loss[0]), rtol=1e-4)

  def test_train_state_shardings(self):
    _RequireDevices(8)
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    mesh = mesh_lib.MakeMesh({"data": 2, "model": 4})
    shardings = mesh_lib.TrainStateShardings(mesh, task, state)
    assert state.IsCompatible(shardings)
    # theta leaves with 'model' annotation got model-sharded specs
    flat = dict(shardings.FlattenItems())
    stacked_wq = [v for k, v in flat.items()
                  if "theta" in k and k.endswith("w_query")]
    assert stacked_wq and "model" in str(stacked_wq[0].spec)
    # device_put works end to end
    placed = jax.device_put(state, shardings)
    assert placed.step.sharding.is_fully_replicated


class TestMoE:

  def test_top2_gating_properties(self):
    g, s, e = 2, 16, 4
    logits = jax.random.normal(KEY, (g, s, e))
    out = gshard.Top2Gating(logits, None, capacity_factor=2.0)
    c = out.combine_tensor.shape[-1]
    assert c == 8  # ceil(16/4*2)
    # each token's combine weights sum to ~1 (two experts, renormalized)
    sums = np.asarray(out.combine_tensor.sum(axis=(2, 3)))
    np.testing.assert_allclose(sums, 1.0, atol=1e-5)
    # dispatch: <= 2 experts per token; <= capacity tokens per expert slot
    token_experts = np.asarray(
        (out.dispatch_tensor.sum(3) > 0).sum(-1))
    assert token_experts.max() <= 2
    slot_usage = np.asarray(out.dispatch_tensor.sum(1))  # [G,E,C]
    assert slot_usage.max() <= 1.0 + 1e-6  # one token per (expert, slot)
    assert float(out.aux_loss) > 0

  def test_top2_gating_capacity_drops(self):
    # all tokens prefer expert 0 -> capacity forces drops
    g, s, e = 1, 16, 4
    logits = jnp.zeros((g, s, e)).at[:, :, 0].set(10.0)
    out = gshard.Top2Gating(logits, None, capacity_factor=1.0)
    c = out.combine_tensor.shape[-1]  # ceil(16/4) = 4
    routed_to_0 = np.asarray(out.dispatch_tensor[:, :, 0, :].sum())
    assert routed_to_0 <= c  # capacity respected

  def test_expert_choice_gating_properties(self):
    """Expert-choice (arXiv:2202.09368): every expert exactly fills its
    capacity with real tokens, no aux loss, combine weights = scores."""
    g, s, e = 2, 16, 4
    logits = jax.random.normal(KEY, (g, s, e))
    out = gshard.ExpertChoiceGating(logits, None, capacity_factor=2.0)
    c = out.capacity
    # perfect balance: each expert serves exactly C tokens
    per_expert = np.asarray(out.dispatch_tensor.sum(axis=(1, 3)))  # [G,E]
    np.testing.assert_array_equal(per_expert, c)
    assert float(out.aux_loss) == 0.0
    # combine weights are the router scores of the chosen pairs
    scores = np.asarray(jax.nn.softmax(logits, -1))
    comb = np.asarray(out.combine_tensor.sum(-1))                 # [G,S,E]
    chosen = comb > 0
    np.testing.assert_allclose(comb[chosen], scores[chosen], atol=1e-6)

  def test_expert_choice_respects_paddings(self):
    g, s, e = 1, 8, 2
    logits = jax.random.normal(KEY, (g, s, e))
    paddings = jnp.zeros((g, s)).at[:, 4:].set(1.0)
    out = gshard.ExpertChoiceGating(logits, paddings, capacity_factor=1.0)
    # padded tokens are never selected
    np.testing.assert_allclose(
        np.asarray(out.combine_tensor[:, 4:]).sum(), 0.0, atol=1e-6)

  def test_top2_gating_respects_paddings(self):
    g, s, e = 1, 8, 2
    logits = jax.random.normal(KEY, (g, s, e))
    paddings = jnp.zeros((g, s)).at[:, 4:].set(1.0)
    out = gshard.Top2Gating(logits, paddings)
    np.testing.assert_allclose(
        np.asarray(out.combine_tensor[:, 4:]).sum(), 0.0, atol=1e-6)

  def test_moe_layer_fprop_and_aux_loss(self):
    p = gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=16, hidden_dim=32, num_experts=4, num_groups=2)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (2, 8, 16))
    with py_utils.AuxLossContext() as aux:
      out = layer.FProp(theta, x)
    assert out.shape == x.shape
    assert len(aux) == 1 and float(list(aux.values())[0]) > 0

  def test_moe_sharded_matches_replicated(self):
    _RequireDevices(8)
    p = gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=16, hidden_dim=32, num_experts=8, num_groups=2,
        capacity_factor=8.0)  # high capacity: no drops => exact equality
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (2, 8, 16))
    out1 = jax.jit(layer.FProp)(theta, x)
    mesh = mesh_lib.MakeMesh({"data": 1, "expert": 8})
    shardings = mesh_lib.ThetaShardings(mesh, layer, theta)
    theta_s = jax.device_put(theta, shardings)
    assert "expert" in str(theta_s.wi.sharding.spec)
    x_s = jax.device_put(x, NamedSharding(mesh, PartitionSpec()))
    with mesh_lib.MeshContext(mesh):
      out2 = jax.jit(layer.FProp)(theta_s, x_s)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=2e-5)

  def test_indexed_dispatch_matches_einsum_all_policies(self):
    # The gather/scatter dispatch is the same routing as the one-hot
    # einsums; outputs must match bit-for-bit-ish for every gating policy
    # (incl. with drops: capacity_factor=1.0 forces over-capacity tokens).
    for policy in ("top2", "sinkhorn", "hash", "expert_choice"):
      p0 = gshard.MoEFeedForwardLayer.Params().Set(
          name="moe", input_dim=16, hidden_dim=32, num_experts=4,
          num_groups=2, capacity_factor=1.0, gating_policy=policy)
      layer_e = p0.Copy().Set(dispatch_method="einsum").Instantiate()
      layer_i = p0.Copy().Set(dispatch_method="indexed").Instantiate()
      theta = layer_e.InstantiateVariables(KEY)
      x = jax.random.normal(KEY, (2, 8, 16))
      ids = jax.random.randint(KEY, (2, 8), 0, 100)
      out_e = jax.jit(layer_e.FProp)(theta, x, token_ids=ids)
      out_i = jax.jit(layer_i.FProp)(theta, x, token_ids=ids)
      np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_i),
                                 atol=1e-5, err_msg=policy)

  def test_indexed_dispatch_gradients_match_einsum(self):
    p0 = gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=16, hidden_dim=32, num_experts=4,
        num_groups=2, capacity_factor=1.5)
    layer_e = p0.Copy().Set(dispatch_method="einsum").Instantiate()
    layer_i = p0.Copy().Set(dispatch_method="indexed").Instantiate()
    theta = layer_e.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (2, 8, 16))

    def loss(layer):
      return lambda th, xx: jnp.sum(layer.FProp(th, xx) ** 2)

    ge = jax.jit(jax.grad(loss(layer_e)))(theta, x)
    gi = jax.jit(jax.grad(loss(layer_i)))(theta, x)
    for (k, a), (_, b) in zip(ge.FlattenItems(), gi.FlattenItems()):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                 err_msg=k)

  def test_moe_in_train_step_gets_aux_loss_metric(self):
    from lingvo_tpu.core import base_model, learner as learner_lib
    from lingvo_tpu.core import optimizer as opt_lib

    class MoETask(base_model.BaseTask):

      def __init__(self, params):
        super().__init__(params)
        self.CreateChild(
            "moe",
            gshard.MoEFeedForwardLayer.Params().Set(
                input_dim=8, hidden_dim=16, num_experts=2))

      def ComputePredictions(self, theta, input_batch):
        return self.moe.FProp(theta.moe, input_batch.x)

      def ComputeLoss(self, theta, predictions, input_batch):
        loss = jnp.mean(jnp.square(predictions))
        return NestedMap(loss=(loss, 1.0)), NestedMap()

    p = MoETask.Params().Set(name="moetask")
    p.train.learner = learner_lib.Learner.Params().Set(
        optimizer=opt_lib.SGD.Params())
    task = p.Instantiate()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    batch = NestedMap(x=jax.random.normal(KEY, (2, 4, 8)))
    state2, out = jax.jit(task.TrainStep)(state, batch)
    assert "aux_loss" in out.metrics
    assert float(out.metrics.aux_loss[0]) > 0


class TestMoEInScan:

  def test_moe_inside_repeated_layer_train_step(self):
    # Regression: aux losses emitted inside lax.scan must not leak tracers.
    from lingvo_tpu.core import base_model, learner as learner_lib
    from lingvo_tpu.core import optimizer as opt_lib
    from lingvo_tpu.core import transformer

    class MoELmTask(base_model.BaseTask):

      def __init__(self, params):
        super().__init__(params)
        body = gshard.MoETransformerLayer.Params().Set(
            input_dim=8, num_heads=2,
            moe_tpl=gshard.MoEFeedForwardLayer.Params().Set(
                hidden_dim=16, num_experts=2))
        self.CreateChild(
            "stack",
            transformer.RepeatedTransformerLayer.Params().Set(
                num_layers=2, body=body, per_layer_checkpoint=False))

      def ComputePredictions(self, theta, input_batch):
        return self.stack.FProp(theta.stack, input_batch.x)

      def ComputeLoss(self, theta, predictions, input_batch):
        return NestedMap(
            loss=(jnp.mean(jnp.square(predictions)), 1.0)), NestedMap()

    p = MoELmTask.Params().Set(name="moelm")
    p.train.learner = learner_lib.Learner.Params().Set(
        optimizer=opt_lib.SGD.Params())
    task = p.Instantiate()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    batch = NestedMap(x=jax.random.normal(KEY, (2, 4, 8)))
    state2, out = jax.jit(task.TrainStep)(state, batch)
    assert "aux_loss" in out.metrics
    assert np.isfinite(float(out.metrics.aux_loss[0]))
    assert float(out.metrics.aux_loss[0]) > 0

  def test_random_policy_falls_back_in_eval(self):
    p = gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=8, hidden_dim=16, num_experts=2,
        second_expert_policy="random")
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (1, 4, 8))
    with py_utils.EvalContext():
      out = layer.FProp(theta, x)  # must not assert
    assert out.shape == x.shape
    # and with a step seed in train mode, sampling path works
    with py_utils.StepSeedContext(jax.random.PRNGKey(1)):
      out2 = layer.FProp(theta, x)
    assert np.all(np.isfinite(np.asarray(out2)))


class TestUlyssesAttention:
  """Head-scatter all-to-all SP (SURVEY §5's optional Ulysses, arXiv:
  2309.14509): exactness + gradients vs plain attention on the mesh."""

  def _Ref(self, q, k, v, causal):
    import math
    h = q.shape[-1]
    s = jnp.einsum("bqnh,bknh->bnqk", q / math.sqrt(h), k)
    if causal:
      t = q.shape[1]
      s = jnp.where(jnp.tril(jnp.ones((t, t), jnp.bool_))[None, None], s,
                    -jnp.inf)
    return jnp.einsum("bnqk,bknh->bqnh", jax.nn.softmax(s, -1), v)

  def test_matches_full_attention(self):
    _RequireDevices(8)
    from lingvo_tpu.parallel import ulysses
    mesh = mesh_lib.MakeMesh({"seq": 4, "data": 2})
    b, t, n, h = 2, 32, 4, 8  # n % seq == 0
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    for causal in (True, False):
      out = ulysses.UlyssesAttention(q, k, v, mesh=mesh, causal=causal)
      np.testing.assert_allclose(
          np.asarray(out), np.asarray(self._Ref(q, k, v, causal)),
          atol=2e-5)

  @pytest.mark.slow
  def test_gradients_match_full_attention(self):
    _RequireDevices(8)
    from lingvo_tpu.parallel import ulysses
    mesh = mesh_lib.MakeMesh({"seq": 4, "data": 2})
    b, t, n, h = 2, 16, 4, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    w = jax.random.normal(jax.random.PRNGKey(3), (b, t, n, h))

    def sp_loss(q, k, v):
      out = ulysses.UlyssesAttention(q, k, v, mesh=mesh, causal=True)
      return jnp.sum(out.astype(jnp.float32) * w)

    def ref_loss(q, k, v):
      return jnp.sum(self._Ref(q, k, v, True).astype(jnp.float32) * w)

    g_sp = jax.grad(sp_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_sp, g_ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=3e-5)

  def test_rejects_indivisible_heads(self):
    _RequireDevices(8)
    from lingvo_tpu.parallel import ulysses
    mesh = mesh_lib.MakeMesh({"seq": 4, "data": 2})
    q = jnp.zeros((1, 16, 3, 8))  # 3 heads, 4-way seq axis
    with pytest.raises(ValueError, match="divisible"):
      ulysses.UlyssesAttention(q, q, q, mesh=mesh)


class TestRingAttention:

  def test_matches_full_attention_causal(self):
    _RequireDevices(8)
    mesh = mesh_lib.MakeMesh({"seq": 8})
    b, t, n, h = 2, 32, 2, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))

    out_ring = ring_attention.RingAttention(q, k, v, mesh=mesh, causal=True)

    # reference: plain causal attention
    import math
    s = jnp.einsum("bqnh,bknh->bnqk", q / math.sqrt(h), k)
    mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
    s = jnp.where(mask[None, None], s, -jnp.inf)
    probs = jax.nn.softmax(s, axis=-1)
    out_ref = jnp.einsum("bnqk,bknh->bqnh", probs, v)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_ref), atol=2e-5)

  def test_matches_full_attention_bidirectional(self):
    _RequireDevices(8)
    mesh = mesh_lib.MakeMesh({"seq": 4, "data": 2})
    b, t, n, h = 2, 16, 2, 4
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    out_ring = ring_attention.RingAttention(q, k, v, mesh=mesh, causal=False)
    import math
    s = jnp.einsum("bqnh,bknh->bnqk", q / math.sqrt(h), k)
    probs = jax.nn.softmax(s, axis=-1)
    out_ref = jnp.einsum("bnqk,bknh->bqnh", probs, v)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_ref), atol=2e-5)

  @pytest.mark.slow
  def test_gradients_match_full_attention(self):
    # The whole ring is one custom_vjp (second ring pass rotating dK/dV
    # with their blocks); gradients must match plain attention.
    _RequireDevices(8)
    import math
    mesh = mesh_lib.MakeMesh({"seq": 8})
    b, t, n, h = 2, 32, 2, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    w = jax.random.normal(jax.random.PRNGKey(3), (b, t, n, h))

    def ring_loss(q, k, v):
      out = ring_attention.RingAttention(q, k, v, mesh=mesh, causal=True)
      return jnp.sum(out.astype(jnp.float32) * w)

    def ref_loss(q, k, v):
      s = jnp.einsum("bqnh,bknh->bnqk", q / math.sqrt(h), k)
      mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
      s = jnp.where(mask[None, None], s, -jnp.inf)
      probs = jax.nn.softmax(s, axis=-1)
      out = jnp.einsum("bnqk,bknh->bqnh", probs, v)
      return jnp.sum(out.astype(jnp.float32) * w)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, r, nm in zip(g_ring, g_ref, "qkv"):
      np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=3e-5,
                                 err_msg=nm)

  @pytest.mark.slow
  def test_single_device_decomposition_matches(self):
    # the bench's sp-simulation path is the same math as full attention
    import math
    b, t, n, h = 2, 64, 2, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    out = ring_attention.RingAttentionSingleDevice(q, k, v, num_shards=4)
    s = jnp.einsum("bqnh,bknh->bnqk", q / math.sqrt(h), k)
    mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
    s = jnp.where(mask[None, None], s, -jnp.inf)
    probs = jax.nn.softmax(s, axis=-1)
    out_ref = jnp.einsum("bnqk,bknh->bqnh", probs, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=2e-5)


class TestPipeline:

  def _body(self):
    from lingvo_tpu.core import transformer
    return transformer.TransformerLayer.Params().Set(
        input_dim=8, num_heads=2, hidden_dim=16, mask_self_atten=True)

  def test_pipeline_matches_sequential(self):
    p = pipeline.PipelinedLayer.Params().Set(
        name="pipe", num_stages=4, num_microbatches=4, body=self._body())
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (8, 6, 8))

    out_pipe = jax.jit(layer.FProp)(theta, x)

    # sequential reference: run the 4 stage bodies in order
    body = self._body().Set(name="body").Instantiate()
    seq = x
    for i in range(4):
      theta_i = jax.tree_util.tree_map(lambda s: s[i], theta.body)
      seq = body.FProp(theta_i, seq)
    np.testing.assert_allclose(
        np.asarray(out_pipe), np.asarray(seq), atol=1e-4)

  def test_pipeline_sharded_over_stage_axis(self):
    _RequireDevices(8)
    p = pipeline.PipelinedLayer.Params().Set(
        name="pipe", num_stages=4, num_microbatches=2, body=self._body())
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    mesh = mesh_lib.MakeMesh({"stage": 4, "data": 2})
    # stack dim 0 shards over 'stage'
    theta_s = jax.tree_util.tree_map(
        lambda w: jax.device_put(
            w, NamedSharding(
                mesh,
                PartitionSpec("stage", *([None] * (w.ndim - 1))))), theta)
    x = jax.random.normal(KEY, (4, 6, 8))
    x_s = jax.device_put(
        x, NamedSharding(mesh, PartitionSpec("data", None, None)))
    out = jax.jit(layer.FProp)(theta_s, x_s)
    out_ref = jax.jit(layer.FProp)(theta, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               atol=1e-4)


class TestMoEAtScale:
  """VERDICT r1 item 3: prove the dispatch actually lowers to all-to-all,
  auto num_groups, explicit shard_map path, hash gating, token shuffle."""

  def _moe(self, **kw):
    p = gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=16, hidden_dim=32, num_experts=8,
        capacity_factor=8.0, **kw)
    layer = p.Instantiate()
    return layer, layer.InstantiateVariables(KEY)

  def test_compiled_hlo_contains_all_to_all(self):
    _RequireDevices(8)
    layer, theta = self._moe(num_groups=8)
    x = jax.random.normal(KEY, (2, 32, 16))
    mesh = mesh_lib.MakeMesh({"data": 1, "expert": 8})
    theta_s = jax.device_put(theta, mesh_lib.ThetaShardings(mesh, layer,
                                                            theta))
    x_s = jax.device_put(x, NamedSharding(mesh, PartitionSpec()))
    with mesh_lib.MeshContext(mesh):
      compiled = jax.jit(layer.FProp).lower(theta_s, x_s).compile()
    hlo = compiled.as_text()
    assert "all-to-all" in hlo, "dispatch did not lower to all-to-all"

  def test_shard_map_dispatch_matches_einsum_path(self):
    _RequireDevices(8)
    layer, theta = self._moe(num_groups=8)
    sm_layer, _ = self._moe(num_groups=8, dispatch_via_shard_map=True)
    x = jax.random.normal(KEY, (2, 32, 16))
    mesh = mesh_lib.MakeMesh({"data": 1, "expert": 8})
    theta_s = jax.device_put(theta, mesh_lib.ThetaShardings(mesh, layer,
                                                            theta))
    x_s = jax.device_put(x, NamedSharding(mesh, PartitionSpec()))
    with mesh_lib.MeshContext(mesh):
      out_einsum = jax.jit(layer.FProp)(theta_s, x_s)
      out_sm = jax.jit(sm_layer.FProp)(theta_s, x_s)
      # the explicit path must contain a literal all-to-all too
      hlo = jax.jit(sm_layer.FProp).lower(theta_s, x_s).compile().as_text()
    assert "all-to-all" in hlo
    np.testing.assert_allclose(np.asarray(out_einsum), np.asarray(out_sm),
                               atol=2e-5)

  def test_auto_num_groups_uses_mesh(self):
    _RequireDevices(8)
    layer, theta = self._moe()  # num_groups=0 (auto)
    x = jax.random.normal(KEY, (4, 16, 16))
    mesh = mesh_lib.MakeMesh({"data": 1, "expert": 8})
    with mesh_lib.MeshContext(mesh):
      assert layer._NumGroups(4, 16) == 8  # = expert axis size
    # without a mesh: min(b, 8) clamped to a divisor of b*t
    assert layer._NumGroups(4, 16) == 4
    assert layer._NumGroups(3, 5) == 3
    out = jax.jit(layer.FProp)(theta, x)
    assert out.shape == x.shape

  def test_hash_gating_routes_by_id(self):
    layer, theta = self._moe(gating_policy="hash", num_groups=2)
    x = jax.random.normal(KEY, (2, 16, 16))
    ids = jax.random.randint(KEY, (2, 16), 0, 1000)
    out = layer.FProp(theta, x, token_ids=ids)
    assert out.shape == x.shape
    # same ids -> same routing -> same output; different ids -> different
    out2 = layer.FProp(theta, x, token_ids=ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out2), atol=1e-6)
    ids3 = ids + 1
    out3 = layer.FProp(theta, x, token_ids=ids3)
    assert not np.allclose(np.asarray(out), np.asarray(out3), atol=1e-4)
    # hash gating has no aux loss
    with py_utils.AuxLossContext() as aux:
      layer.FProp(theta, x, token_ids=ids)
    assert float(list(aux.values())[0]) == 0.0

  def test_token_shuffle_is_noop_with_ample_capacity(self):
    # with capacity >= tokens nothing is dropped, so shuffled gating must
    # give exactly the unshuffled result (permutation round-trips).
    layer, theta = self._moe(shuffle_tokens=True, num_groups=2)
    plain, _ = self._moe(num_groups=2)
    x = jax.random.normal(KEY, (2, 16, 16))
    with py_utils.StepSeedContext(jax.random.PRNGKey(5)):
      out_shuf = layer.FProp(theta, x)
    out_plain = plain.FProp(theta, x)
    np.testing.assert_allclose(np.asarray(out_shuf), np.asarray(out_plain),
                               atol=2e-5)

  def test_token_shuffle_unbiases_drops(self):
    # capacity_factor 0.25: only 1/4 of tokens fit. Unshuffled, survivors
    # are always the earliest tokens; shuffled, later tokens survive too.
    g, s, e = 1, 32, 2
    logits = jnp.zeros((g, s, e)).at[:, :, 0].set(5.0)
    out_plain = gshard.Top2Gating(logits, None, capacity_factor=0.25)
    kept_plain = np.asarray(out_plain.dispatch_tensor.sum((2, 3)))[0]
    perm, inv = gshard.TokenShufflePerm((g, s), jax.random.PRNGKey(3))
    logits_shuf = gshard._TakeAlongS(logits, perm)
    out_shuf = gshard.Top2Gating(logits_shuf, None, capacity_factor=0.25)
    disp = gshard._TakeAlongS(out_shuf.dispatch_tensor, inv)
    kept_shuf = np.asarray(disp.sum((2, 3)))[0]
    # plain = prefix bias: only the first c tokens survive (both experts)
    assert (kept_plain[:4] > 0).all() and kept_plain[4:].sum() == 0
    # shuffled: survivors are exactly the tokens the permutation put first —
    # the drop pattern follows the shuffle, not data position
    expect = set(np.asarray(perm)[0][:4].tolist())
    assert set(np.nonzero(kept_shuf)[0].tolist()) == expect

  def test_hash_gating_through_lm_stack(self):
    # production path: token_ids must reach the MoE layer via the stack
    # (TransformerLm -> Repeated/Stacked -> DenseMoEBlock -> MoE FFN)
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("lm.synthetic_packed_input.MoELmTiny",
                                  "Train")
    mp.task.input = mp.input
    mp.task.input.seq_len = 16
    mp.task.input.batch_size = 2
    mp.task.moe_gating_policy = "hash"
    task = mp.task.Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    metrics, _ = task.EvalStep(theta, batch)
    assert np.isfinite(float(metrics.loss[0]))


class TestSinkhornGating:

  def test_balanced_routing_under_skewed_logits(self):
    # all tokens prefer expert 0; Sinkhorn's balanced plan must spread them
    g, s, e = 1, 16, 4
    logits = jax.random.normal(KEY, (g, s, e)) * 0.1
    logits = logits.at[:, :, 0].add(5.0)
    out = gshard.SinkhornGating(logits, None, capacity_factor=2.0,
                                num_iters=20)
    per_expert = np.asarray(out.dispatch_tensor.sum(axis=(1, 3)))[0]  # [E]
    # top-2 greedy would put min(c, 16) on expert 0 and 0 on some others;
    # the OT plan must assign every expert a nontrivial share
    assert per_expert.min() >= 2, per_expert
    assert float(out.aux_loss) == 0.0

  def test_combine_weights_and_capacity(self):
    g, s, e = 2, 12, 3
    logits = jax.random.normal(jax.random.PRNGKey(7), (g, s, e))
    out = gshard.SinkhornGating(logits, None, capacity_factor=1.0)
    c = out.combine_tensor.shape[-1]
    assert c == 4  # ceil(12/3*1)
    slot_usage = np.asarray(out.dispatch_tensor.sum(1))  # [G,E,C]
    assert slot_usage.max() <= 1.0 + 1e-6
    # top-1: each surviving token uses exactly one expert slot, with the
    # softmax gate prob as its weight (in (0, 1))
    w = np.asarray(out.combine_tensor.sum(axis=(2, 3)))
    assert (w >= 0).all() and (w <= 1.0 + 1e-6).all()

  def test_paddings_excluded(self):
    g, s, e = 1, 8, 2
    logits = jax.random.normal(KEY, (g, s, e))
    paddings = jnp.zeros((g, s)).at[:, 6:].set(1.0)
    out = gshard.SinkhornGating(logits, paddings)
    np.testing.assert_allclose(
        np.asarray(out.combine_tensor[:, 6:]).sum(), 0.0, atol=1e-6)

  def test_moe_layer_with_sinkhorn_policy_trains(self):
    p = gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=16, hidden_dim=32, num_experts=4,
        num_groups=2, gating_policy="sinkhorn")
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, 16))

    def loss(th, x):
      return jnp.mean(jnp.square(layer.FProp(th, x)))

    g = jax.jit(jax.grad(loss))(theta, x)
    # router gets gradients through the gate values
    assert float(jnp.sum(jnp.abs(g.gating))) > 0

  def test_sinkhorn_balance_survives_heavy_padding(self):
    # 75% padding + skewed logits: real tokens must still spread, and pad
    # rows must carry ~zero plan mass (the masked-Sinkhorn property)
    g, s, e = 1, 16, 4
    logits = jax.random.normal(KEY, (g, s, e)) * 0.1
    logits = logits.at[:, :, 0].add(5.0)
    paddings = jnp.zeros((g, s)).at[:, 4:].set(1.0)  # 4 real tokens
    out = gshard.SinkhornGating(logits, paddings, capacity_factor=2.0,
                                num_iters=25)
    per_expert = np.asarray(out.dispatch_tensor[:, :4].sum(axis=(1, 3)))[0]
    # 4 real tokens over 4 experts, balanced plan -> roughly one each
    assert per_expert.max() <= 2 and per_expert.min() >= 0
    assert per_expert.sum() == 4
    # no single expert hogs all real tokens despite +5 logit skew
    assert per_expert.max() < 4, per_expert
