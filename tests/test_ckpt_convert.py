"""TF-checkpoint conversion story: name normalization (tools side) and
ImportNpzCheckpoint (framework side)."""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import checkpointer
from lingvo_tpu.core.nested_map import NestedMap

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import convert_tf_checkpoint as conv  # noqa: E402


class TestNameMapping:

  def test_normalize_strips_prefix_var_suffix_and_slashes(self):
    assert conv.NormalizeName("librispeech/enc/conv_0/w/var",
                              "librispeech/") == "enc.conv_0.w"
    assert conv.NormalizeName(
        "model/emb/.ATTRIBUTES/VARIABLE_VALUE") == "model.emb"

  def test_rules_first_match_wins(self):
    rules = conv.ParseRules(r"enc\.conv_(\d+)\.w=enc.convs.\1.kernel;"
                            r"enc\..*=DROPPED")
    assert conv.ApplyRules("enc.conv_2.w", rules) == "enc.convs.2.kernel"
    assert conv.ApplyRules("enc.proj.w", rules) == "DROPPED"
    assert conv.ApplyRules("dec.w", rules) == "dec.w"  # pass-through

  def test_rule_regex_may_contain_commas(self):
    # ';' is the pair separator precisely so {m,n} quantifiers survive
    rules = conv.ParseRules(r"enc\.l_(\d{1,2})\.w=enc.layers.\1.w")
    assert conv.ApplyRules("enc.l_12.w", rules) == "enc.layers.12.w"

  def test_convert_writes_npz(self, tmp_path):
    out = str(tmp_path / "conv.npz")
    items = [("m/enc/w/var", np.ones((2, 3), np.float64)),
             ("m/dec/w/var", np.zeros((4,), np.float32))]
    n = conv.Convert(items, out, "m/", conv.ParseRules(""), "float32")
    assert n == 2
    loaded = np.load(out)
    assert set(loaded.files) == {"enc.w", "dec.w"}
    assert loaded["enc.w"].dtype == np.float32

  def test_convert_rejects_colliding_names(self, tmp_path):
    items = [("a/w", np.ones(1)), ("a/w/var", np.ones(1))]
    with pytest.raises(ValueError, match="map to"):
      conv.Convert(items, str(tmp_path / "x.npz"), "",
                   conv.ParseRules(""), None)


def _State():
  return NestedMap(
      theta=NestedMap(enc=NestedMap(w=jnp.zeros((2, 3), jnp.bfloat16)),
                      head=NestedMap(w=jnp.zeros((3,)))),
      ema_theta=NestedMap(enc=NestedMap(w=jnp.zeros((2, 3), jnp.bfloat16)),
                          head=NestedMap(w=jnp.zeros((3,)))),
      step=jnp.zeros((), jnp.int32))


class TestImportNpz:

  def test_identity_mapping_partial_load(self, tmp_path):
    path = str(tmp_path / "c.npz")
    np.savez(path, **{"enc.w": np.full((2, 3), 7.0)})
    state = checkpointer.ImportNpzCheckpoint(_State(), path)
    np.testing.assert_array_equal(np.asarray(state.theta.enc.w,
                                             dtype=np.float32), 7.0)
    assert state.theta.enc.w.dtype == jnp.bfloat16  # cast to target dtype
    np.testing.assert_array_equal(np.asarray(state.theta.head.w), 0.0)
    # ema mirrors the warm value
    np.testing.assert_array_equal(
        np.asarray(state.ema_theta.enc.w, dtype=np.float32), 7.0)

  def test_rules_mapping(self, tmp_path):
    path = str(tmp_path / "c.npz")
    np.savez(path, **{"source_encoder.w": np.full((2, 3), 3.0)})
    state = checkpointer.ImportNpzCheckpoint(
        _State(), path, rules=[(r"enc\.(.*)", r"source_encoder.\1")])
    np.testing.assert_array_equal(
        np.asarray(state.theta.enc.w, dtype=np.float32), 3.0)

  def test_rule_with_missing_source_raises(self, tmp_path):
    path = str(tmp_path / "c.npz")
    np.savez(path, **{"other.w": np.ones((2, 3))})
    with pytest.raises(KeyError, match="not in"):
      checkpointer.ImportNpzCheckpoint(
          _State(), path, rules=[(r"enc\.(.*)", r"missing.\1")])

  def test_shape_mismatch_raises(self, tmp_path):
    path = str(tmp_path / "c.npz")
    np.savez(path, **{"enc.w": np.ones((9, 9))})
    with pytest.raises(ValueError, match="shape mismatch"):
      checkpointer.ImportNpzCheckpoint(_State(), path)


class TestExecutorNpzWarmStart:

  def test_fresh_run_imports_npz(self, tmp_path):
    import tests.test_executor_hardening as helpers
    from lingvo_tpu.runners import executor as executor_lib
    from lingvo_tpu.runners import program as program_lib

    # fabricate a "converted reference checkpoint" for the proj layer
    probe = helpers._TaskParams().Instantiate()
    probe.FinalizePaths()
    theta = probe.InstantiateVariables(jax.random.PRNGKey(0))
    npz = str(tmp_path / "ref.npz")
    w = np.full(np.shape(theta.proj.w), 0.5, np.float32)
    b = np.zeros(np.shape(theta.proj.b), np.float32)
    np.savez(npz, **{"proj.w": w, "proj.b": b})

    logdir = str(tmp_path / "run")
    task_p = helpers._TaskParams(max_steps=5, steps_per_loop=5)
    task_p.train.init_from_npz = npz
    task = task_p.Instantiate()
    task.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_p, logdir=logdir, steps_per_loop=5)
    sched = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(train_program=train_p),
        task=task, input_generators={"Train": helpers._RegressionInput()})
    ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task)
    captured = {}
    orig = ex._MainLoop

    def _Spy(state, start_step):
      captured["w"] = np.asarray(state.theta.proj.w)
      return orig(state, start_step)

    ex._MainLoop = _Spy
    ex.Start()
    np.testing.assert_array_equal(captured["w"], 0.5)


class TestModelVariableFilter:

  def test_tf1_lingvo_naming(self):
    assert conv.IsModelVariable("lm/stack/w/var")
    assert not conv.IsModelVariable("lm/stack/w/var/Adam")
    assert not conv.IsModelVariable("lm/stack/w/var/Adam_1")
    assert not conv.IsModelVariable("lm/stack/w/var/Adafactor_1")
    assert not conv.IsModelVariable("global_step")

  def test_tf2_object_naming(self):
    assert conv.IsModelVariable(
        "model/emb/.ATTRIBUTES/VARIABLE_VALUE")
    assert not conv.IsModelVariable(
        "model/emb/.OPTIMIZER_SLOT/optimizer/m/.ATTRIBUTES/VARIABLE_VALUE")
