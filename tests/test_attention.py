"""Attention + transformer tests.

Key properties (mirroring the reference's batch_major_attention_test):
- causal masking: no future leakage
- ExtendStep decode == FProp offline (streaming equivalence, ref
  stream_step_test_base)
- LocalSelfAttention == full attention when the window covers everything
- packed segment masks isolate sequences
- RepeatedTransformerLayer(scan) == StackedTransformerLayers with same
  per-layer weights
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import attention, py_utils, transformer
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(7)
B, T, D, N = 2, 12, 16, 4


def _mha(**kw):
  p = attention.MultiHeadedAttention.Params().Set(
      name="mha", input_dim=D, hidden_dim=D, num_heads=N, **kw)
  layer = p.Instantiate()
  return layer, layer.InstantiateVariables(KEY)


class TestMultiHeadedAttention:

  def test_shapes(self):
    layer, theta = _mha()
    x = jax.random.normal(KEY, (B, T, D))
    out, probs = layer.FProp(theta, x)
    assert out.shape == (B, T, D)
    assert probs.shape == (B, N, T, T)
    np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, rtol=1e-3)

  def test_key_paddings_ignored(self):
    layer, theta = _mha()
    x = jax.random.normal(KEY, (B, T, D))
    paddings = py_utils.PaddingsFromLengths(jnp.array([T, 5]), T)
    out1, probs = layer.FProp(theta, x, paddings=paddings)
    x2 = x.at[1, 5:].set(777.0)  # garbage in padded keys of seq 1
    out2, _ = layer.FProp(theta, x2, paddings=paddings)
    np.testing.assert_allclose(out1[1, :5], out2[1, :5], atol=1e-4)
    np.testing.assert_allclose(np.asarray(probs[1, :, :, 5:]), 0.0, atol=1e-6)

  def test_causal_mask_no_future(self):
    layer, theta = _mha()
    x = jax.random.normal(KEY, (1, T, D))
    mask = attention.CausalMask(T)
    out1, _ = layer.FProp(theta, x, atten_mask=mask)
    x2 = x.at[:, 6:].set(-5.0)
    out2, _ = layer.FProp(theta, x2, atten_mask=mask)
    np.testing.assert_allclose(out1[:, :6], out2[:, :6], atol=1e-4)

  def test_extend_step_matches_fprop(self):
    layer, theta = _mha(use_rotary_position_emb=True)
    x = jax.random.normal(KEY, (B, T, D))
    offline, _ = layer.FProp(theta, x, atten_mask=attention.CausalMask(T))
    states = layer.InitStates(theta, B, T)
    outs = []
    for t in range(T):
      step_out, states = layer.ExtendStep(theta, x[:, t:t + 1], states)
      outs.append(step_out)
    streaming = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(
        np.asarray(offline), np.asarray(streaming), atol=2e-4)

  def test_segment_mask_isolates_sequences(self):
    layer, theta = _mha()
    x = jax.random.normal(KEY, (1, 8, D))
    seg = jnp.array([[0, 0, 0, 0, 1, 1, 1, 1]])
    out1, probs = layer.FProp(theta, x, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(probs[0, :, :4, 4:]), 0.0, atol=1e-6)
    # perturbing segment 1 leaves segment 0 outputs unchanged
    x2 = x.at[:, 4:].set(99.0)
    out2, _ = layer.FProp(theta, x2, segment_ids=seg)
    np.testing.assert_allclose(out1[:, :4], out2[:, :4], atol=1e-4)

  def test_relative_position_bias(self):
    layer, theta = _mha(rel_pos_emb_dim=8, rel_pos_max_distance=4)
    assert theta.rel_pos_bias.shape == (N, 9)
    x = jax.random.normal(KEY, (B, T, D))
    out, _ = layer.FProp(theta, x)
    assert out.shape == (B, T, D)

  def test_cross_attention_dims(self):
    p = attention.MultiHeadedAttention.Params().Set(
        name="xatt", input_dim=D, source_dim=24, hidden_dim=D, num_heads=N)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    q = jax.random.normal(KEY, (B, 5, D))
    kv = jax.random.normal(KEY, (B, 9, 24))
    out, probs = layer.FProp(theta, q, key_vec=kv)
    assert out.shape == (B, 5, D)
    assert probs.shape == (B, N, 5, 9)


class TestLocalAndChunkwise:

  def test_local_equals_full_when_window_covers(self):
    pl = attention.LocalSelfAttention.Params().Set(
        name="local", input_dim=D, hidden_dim=D, num_heads=N,
        block_size=T, left_context=T + 1, right_context=0)
    local = pl.Instantiate()
    theta = local.InstantiateVariables(KEY)
    full = attention.MultiHeadedAttention.Params().Set(
        name="local", input_dim=D, hidden_dim=D, num_heads=N).Instantiate()
    x = jax.random.normal(KEY, (B, T, D))
    out_local, _ = local.FProp(theta, x)
    out_full, _ = full.FProp(theta, x, atten_mask=attention.CausalMask(T))
    np.testing.assert_allclose(
        np.asarray(out_local), np.asarray(out_full), atol=2e-4)

  def test_local_window_limit(self):
    pl = attention.LocalSelfAttention.Params().Set(
        name="local", input_dim=D, hidden_dim=D, num_heads=N,
        block_size=4, left_context=3, right_context=0)
    local = pl.Instantiate()
    theta = local.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (1, T, D))
    out1, _ = local.FProp(theta, x)
    # perturbing position 0 must not affect position 8 (distance 8 > 3)
    x2 = x.at[:, 0].set(50.0)
    out2, _ = local.FProp(theta, x2)
    np.testing.assert_allclose(out1[:, 8:], out2[:, 8:], atol=1e-4)
    # but must affect position 1 (distance 1)
    assert not np.allclose(out1[:, 1], out2[:, 1], atol=1e-4)

  def test_local_respects_paddings(self):
    pl = attention.LocalSelfAttention.Params().Set(
        name="local", input_dim=D, hidden_dim=D, num_heads=N,
        block_size=4, left_context=4, right_context=2)
    local = pl.Instantiate()
    theta = local.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (2, 10, D))
    paddings = py_utils.PaddingsFromLengths(jnp.array([10, 6]), 10)
    out1, _ = local.FProp(theta, x, paddings=paddings)
    x2 = x.at[1, 6:].set(123.0)
    out2, _ = local.FProp(theta, x2, paddings=paddings)
    np.testing.assert_allclose(np.asarray(out1[1, :6]),
                               np.asarray(out2[1, :6]), atol=1e-4)
    np.testing.assert_allclose(np.asarray(out1[1, 6:]), 0.0, atol=1e-6)

  def test_local_segment_ids_block_cross_segment_leak(self):
    # Regression (ADVICE r1): packed segments used to attend across segment
    # boundaries within a window. Perturbing segment 1 must not change
    # segment 2's outputs even though they share a window.
    pl = attention.LocalSelfAttention.Params().Set(
        name="local", input_dim=D, hidden_dim=D, num_heads=N,
        block_size=4, left_context=4, right_context=0)
    local = pl.Instantiate()
    theta = local.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (1, T, D))
    seg = jnp.concatenate(
        [jnp.full((1, 6), 1, jnp.int32), jnp.full((1, T - 6), 2, jnp.int32)],
        axis=1)
    out1, _ = local.FProp(theta, x, segment_ids=seg)
    x2 = x.at[:, 5].set(77.0)  # last position of segment 1
    out2, _ = local.FProp(theta, x2, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out1[:, 6:]),
                               np.asarray(out2[:, 6:]), atol=1e-4)
    # within segment 1 the perturbation must still propagate
    assert not np.allclose(out1[:, 5], out2[:, 5], atol=1e-4)
    # dense atten_mask is not representable in the windowed layout
    with pytest.raises(NotImplementedError):
      local.FProp(theta, x, atten_mask=attention.CausalMask(T))

  def test_chunkwise_segment_ids_block_cross_segment_leak(self):
    pc = attention.ChunkwiseSelfAttention.Params().Set(
        name="chunk", input_dim=D, hidden_dim=D, num_heads=N, chunk_size=4,
        causal=False)
    chunk = pc.Instantiate()
    theta = chunk.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (1, 8, D))
    seg = jnp.array([[1, 1, 2, 2, 2, 2, 3, 3]], jnp.int32)
    out1, _ = chunk.FProp(theta, x, segment_ids=seg)
    x2 = x.at[:, 1].set(77.0)  # segment 1, chunk 0
    out2, _ = chunk.FProp(theta, x2, segment_ids=seg)
    # segment 2 positions in the same chunk (2, 3) must be unaffected
    np.testing.assert_allclose(np.asarray(out1[:, 2:4]),
                               np.asarray(out2[:, 2:4]), atol=1e-4)
    assert not np.allclose(out1[:, 0], out2[:, 0], atol=1e-4)
    with pytest.raises(NotImplementedError):
      chunk.FProp(theta, x, atten_mask=attention.CausalMask(8))

  def test_chunkwise_no_cross_chunk(self):
    pc = attention.ChunkwiseSelfAttention.Params().Set(
        name="chunk", input_dim=D, hidden_dim=D, num_heads=N, chunk_size=4)
    chunk = pc.Instantiate()
    theta = chunk.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (1, 8, D))
    out1, _ = chunk.FProp(theta, x)
    x2 = x.at[:, 0:4].set(9.0)  # perturb chunk 0
    out2, _ = chunk.FProp(theta, x2)
    np.testing.assert_allclose(out1[:, 4:], out2[:, 4:], atol=1e-4)


class TestTransformer:

  def _layer_p(self, **kw):
    return transformer.TransformerLayer.Params().Set(
        name="xf", input_dim=D, num_heads=N, hidden_dim=32, **kw)

  def test_decoder_layer_fprop_extendstep(self):
    p = self._layer_p(mask_self_atten=True)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, T, D))
    offline = layer.FProp(theta, x)
    states = layer.InitStates(theta, B, T)
    outs = []
    for t in range(T):
      o, states = layer.ExtendStep(theta, x[:, t:t + 1], states)
      outs.append(o)
    np.testing.assert_allclose(
        np.asarray(offline), np.asarray(jnp.concatenate(outs, 1)), atol=3e-4)

  def test_encoder_decoder_cross_attention(self):
    p = self._layer_p(mask_self_atten=True, has_aux_atten=True)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    tgt = jax.random.normal(KEY, (B, 5, D))
    src = jax.random.normal(KEY, (B, 9, D))
    src_pad = py_utils.PaddingsFromLengths(jnp.array([9, 4]), 9)
    out = layer.FProp(theta, tgt, aux_vecs=src, aux_paddings=src_pad)
    assert out.shape == (B, 5, D)
    src2 = src.at[1, 4:].set(55.0)
    out2 = layer.FProp(theta, tgt, aux_vecs=src2, aux_paddings=src_pad)
    np.testing.assert_allclose(np.asarray(out[1]), np.asarray(out2[1]),
                               atol=1e-4)

  def test_stacked_layers(self):
    p = transformer.StackedTransformerLayers.Params().Set(
        name="stack", num_layers=3, input_dim=D,
        transformer_layer_params_tpl=self._layer_p(mask_self_atten=True))
    stack = p.Instantiate()
    theta = stack.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, T, D))
    out = stack.FProp(theta, x)
    assert out.shape == (B, T, D)
    # streaming equivalence through the whole stack
    states = stack.InitStates(theta, B, T)
    outs = []
    for t in range(T):
      o, states = stack.ExtendStep(theta, x[:, t:t + 1], states)
      outs.append(o)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(jnp.concatenate(outs, 1)), atol=5e-4)

  def test_repeated_matches_stacked(self):
    body = self._layer_p(mask_self_atten=True)
    rep_p = transformer.RepeatedTransformerLayer.Params().Set(
        name="rep", num_layers=3, body=body.Copy(),
        per_layer_checkpoint=False)
    rep = rep_p.Instantiate()
    rep_theta = rep.InstantiateVariables(KEY)
    assert rep_theta.body.fflayer.ffn_in.w.shape[0] == 3  # stacked

    # Build a stacked version with the SAME weights, layer by layer.
    stack_p = transformer.StackedTransformerLayers.Params().Set(
        name="stack", num_layers=3, input_dim=D,
        transformer_layer_params_tpl=body.Copy(), final_ln=False)
    stack = stack_p.Instantiate()
    stack_theta = stack.InstantiateVariables(KEY)
    for i in range(3):
      stack_theta.x_layers[i] = jax.tree_util.tree_map(
          lambda s: s[i], rep_theta.body)
    x = jax.random.normal(KEY, (B, T, D))
    out_rep = rep.FProp(rep_theta, x)
    out_stack = stack.FProp(stack_theta, x)
    np.testing.assert_allclose(
        np.asarray(out_rep), np.asarray(out_stack), atol=2e-5)

  def test_repeated_extend_step(self):
    body = self._layer_p(mask_self_atten=True)
    rep = transformer.RepeatedTransformerLayer.Params().Set(
        name="rep", num_layers=2, body=body).Instantiate()
    theta = rep.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, T, D))
    offline = rep.FProp(theta, x)
    states = rep.InitStates(theta, B, T)
    outs = []
    for t in range(T):
      o, states = rep.ExtendStep(theta, x[:, t:t + 1], states)
      outs.append(o)
    np.testing.assert_allclose(
        np.asarray(offline), np.asarray(jnp.concatenate(outs, 1)), atol=5e-4)

  def test_repeated_dropout_differs_per_layer(self):
    body = self._layer_p(mask_self_atten=True)
    body.tr_atten_tpl.residual_dropout_prob = 0.5
    rep = transformer.RepeatedTransformerLayer.Params().Set(
        name="rep", num_layers=2, body=body,
        per_layer_checkpoint=False).Instantiate()
    theta = rep.InstantiateVariables(KEY)
    # Make both layers' weights identical: same input -> layer outputs
    # differ iff dropout masks differ.
    tied = jax.tree_util.tree_map(
        lambda s: jnp.broadcast_to(s[0:1], s.shape), theta.body)
    theta = NestedMap(body=tied)
    x = jnp.ones((1, 4, D))
    with py_utils.StepSeedContext(jax.random.PRNGKey(3)):
      out_a = rep.FProp(theta, x)
    with py_utils.StepSeedContext(jax.random.PRNGKey(3)):
      out_b = rep.FProp(theta, x)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))

  def test_ffn_gated_activation(self):
    p = transformer.TransformerFeedForwardLayer.Params().Set(
        name="ffn", input_dim=D, hidden_dim=32, activation="SILU",
        use_gated_activation=True)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    out = layer.FProp(theta, jax.random.normal(KEY, (B, T, D)))
    assert out.shape == (B, T, D)
    assert "ffn_gate" in theta
