"""Beam search + MT task tests.

Beam-search properties (mirroring the reference's beam_search_helper_test /
flat_beam_search semantics): best-first ordering, EOS termination, beam>
greedy score, state reordering correctness. MT: teacher-forced training
learns the synthetic task; decode produces BLEU > 0 against references.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import beam_search as bs_lib
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(3)


def _MarkovStepFn(trans):
  """Step fn for a fixed Markov chain: log_probs depend only on last id."""

  def step_fn(states, ids_t):
    logits = jnp.log(trans[ids_t[:, 0]] + 1e-9)
    return logits, states

  return step_fn


class TestBeamSearch:

  def _Chain(self, vocab=6):
    # deterministic-ish chain: token i -> i+1 with p=.7, ->eos(2) p=.2, rest
    t = np.full((vocab, vocab), 0.01)
    for i in range(vocab):
      t[i, (i + 1) % vocab] += 0.7
      t[i, 2] += 0.2
    return jnp.asarray(t / t.sum(-1, keepdims=True))

  def test_greedy_follows_argmax(self):
    p = bs_lib.GreedySearchHelper.Params().Set(
        target_seq_len=5, target_sos_id=1, target_eos_id=2)
    helper = bs_lib.GreedySearchHelper(p)
    out = helper.Search(2, NestedMap(), _MarkovStepFn(self._Chain()))
    # from sos=1: 2 is eos... argmax from 1 is 2? chain: 1->2 w/ .7+.2.
    # ids[0] should be eos immediately
    assert out.hyp_ids.shape == (2, 5)
    assert int(out.hyp_ids[0, 0]) == 2  # eos right away

  def test_beam_returns_sorted_scores(self):
    p = bs_lib.BeamSearchHelper.Params().Set(
        num_hyps_per_beam=4, target_seq_len=6, target_sos_id=0,
        target_eos_id=2, valid_eos_max_logit_delta=100.0,
        length_normalization=0.0)
    helper = bs_lib.BeamSearchHelper(p)
    out = helper.Search(3, NestedMap(), _MarkovStepFn(self._Chain()))
    scores = np.asarray(out.topk_scores)
    assert np.all(np.diff(scores, axis=1) <= 1e-6)  # descending
    assert out.topk_ids.shape == (3, 4, 6)
    # all hyps end with eos padding
    lens = np.asarray(out.topk_lens)
    ids = np.asarray(out.topk_ids)
    for b in range(3):
      for k in range(4):
        assert np.all(ids[b, k, lens[b, k]:] == 2)

  def test_beam_beats_greedy_on_score(self):
    """Beam-4 top hyp log-prob >= greedy hyp log-prob on a random model."""
    vocab = 10
    rng = np.random.RandomState(0)
    trans = jnp.asarray(rng.dirichlet(np.ones(vocab) * 0.3, size=vocab))
    step_fn = _MarkovStepFn(trans)

    def hyp_logprob(ids, lens, b=0):
      lp = 0.0
      prev = 1
      for t in range(int(lens)):
        lp += float(jnp.log(trans[prev, int(ids[t])] + 1e-9))
        prev = int(ids[t])
      return lp

    gp = bs_lib.GreedySearchHelper.Params().Set(
        target_seq_len=6, target_sos_id=1, target_eos_id=2)
    g_out = bs_lib.GreedySearchHelper(gp).Search(1, NestedMap(), step_fn)
    bp = bs_lib.BeamSearchHelper.Params().Set(
        num_hyps_per_beam=4, target_seq_len=6, target_sos_id=1,
        target_eos_id=2, length_normalization=0.0,
        valid_eos_max_logit_delta=100.0)
    b_out = bs_lib.BeamSearchHelper(bp).Search(1, NestedMap(), step_fn)
    g_lp = hyp_logprob(np.asarray(g_out.hyp_ids[0]),
                       np.asarray(g_out.hyp_lens[0]))
    b_lp = hyp_logprob(np.asarray(b_out.topk_ids[0, 0]),
                       np.asarray(b_out.topk_lens[0, 0]))
    assert b_lp >= g_lp - 1e-5

  def test_state_reordering(self):
    """States must follow their hypotheses through beam reordering."""
    vocab = 8

    def step_fn(states, ids_t):
      # each hyp's 'memory' accumulates its token history sum; logits prefer
      # continuing with the same token as before (sticky), making distinct
      # beams carry distinct states.
      logits = jax.nn.one_hot(ids_t[:, 0], vocab) * 2.0
      new_states = NestedMap(acc=states.acc + ids_t[:, 0])
      return logits, new_states

    p = bs_lib.BeamSearchHelper.Params().Set(
        num_hyps_per_beam=3, target_seq_len=4, target_sos_id=3,
        target_eos_id=0, valid_eos_max_logit_delta=100.0)
    helper = bs_lib.BeamSearchHelper(p)
    out = helper.Search(2, NestedMap(acc=jnp.zeros(6, jnp.int32)), step_fn)
    assert out.topk_ids.shape == (2, 3, 4)

  def test_gather_beams_paged_cache_matches_dense(self):
    """Beam reorder of a paged KV-cache view == paged view of the dense
    reorder: the paged flash-decode path stores the cache in the same
    dense [B*K, S, N, H] layout (pages are a read-side blocking of the
    time axis), so _GatherBeams needs no paged-specific handling."""
    b, k, s, n, h, ps = 2, 3, 16, 2, 4, 4
    cache = jax.random.normal(KEY, (b * k, s, n, h))
    parent = jnp.asarray([[2, 0, 1], [1, 1, 0]], jnp.int32)
    dense = bs_lib._GatherBeams(NestedMap(key=cache), parent, b, k).key
    paged_view = cache.reshape(b * k, s // ps, ps, n, h)
    paged = bs_lib._GatherBeams(NestedMap(key=paged_view), parent, b, k).key
    np.testing.assert_array_equal(
        np.asarray(paged), np.asarray(dense.reshape(b * k, s // ps, ps, n, h)))

  def test_sampler_temperature_zero_is_greedy(self):
    trans = self._Chain()
    sp = bs_lib.TargetSequenceSampler.Params().Set(
        target_seq_len=5, target_sos_id=1, target_eos_id=2, temperature=0.0)
    out = bs_lib.TargetSequenceSampler(sp).Sample(
        KEY, 2, NestedMap(), _MarkovStepFn(trans))
    gp = bs_lib.GreedySearchHelper.Params().Set(
        target_seq_len=5, target_sos_id=1, target_eos_id=2)
    g = bs_lib.GreedySearchHelper(gp).Search(2, NestedMap(),
                                             _MarkovStepFn(trans))
    np.testing.assert_array_equal(np.asarray(out.ids), np.asarray(g.hyp_ids))

  def test_sampler_topk(self):
    trans = self._Chain()
    sp = bs_lib.TargetSequenceSampler.Params().Set(
        target_seq_len=8, target_sos_id=1, target_eos_id=2, temperature=1.0,
        top_k=2)
    out = bs_lib.TargetSequenceSampler(sp).Sample(
        KEY, 4, NestedMap(), _MarkovStepFn(trans))
    assert out.ids.shape == (4, 8)


class TestMtTask:

  def _task_and_gen(self):
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("mt.wmt14_en_de.WmtEnDeTransformerTiny",
                                  "Train")
    mp.task.input = mp.input
    return mp.task.Instantiate(), mp.input.Instantiate()

  def test_fprop_and_overfit(self):
    task, gen = self._task_and_gen()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    step = jax.jit(task.TrainStep)
    first = None
    for _ in range(150):
      state, out = step(state, batch)
      if first is None:
        first = float(out.metrics.loss[0])
    final = float(out.metrics.loss[0])
    assert final < 0.7 * first, (first, final)

  def test_decode_and_bleu_pipeline(self):
    task, gen = self._task_and_gen()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    out = jax.jit(task.Decode)(theta, batch)
    assert out.topk_ids.shape[1] == 4  # beam width
    dm = task.CreateDecoderMetrics()
    host_out = jax.tree_util.tree_map(np.asarray, out)
    task.PostProcessDecodeOut(host_out, dm)
    results = task.DecodeFinalize(dm)
    assert "corpus_bleu" in results
    assert results["examples"] > 0


class TestMergeBeamSearchOutputs:

  def test_merge_dedupes_and_sorts(self):
    from lingvo_tpu.core import beam_search
    from lingvo_tpu.core.nested_map import NestedMap
    import jax.numpy as jnp
    import numpy as np
    # decoder A: hyps [1,2] (score -1), [3,4,5] (score -3)
    # decoder B: hyps [1,2] (score -2, duplicate), [7] (score -0.5)
    a = NestedMap(
        topk_ids=jnp.array([[[1, 2, 0], [3, 4, 5]]]),
        topk_lens=jnp.array([[2, 3]]),
        topk_scores=jnp.array([[-1.0, -3.0]]))
    b = NestedMap(
        topk_ids=jnp.array([[[1, 2, 9], [7, 0, 0]]]),  # trailing junk ignored
        topk_lens=jnp.array([[2, 1]]),
        topk_scores=jnp.array([[-2.0, -0.5]]))
    out = beam_search.MergeBeamSearchOutputs(3, [a, b])
    np.testing.assert_array_equal(np.asarray(out.topk_scores[0]),
                                  [-0.5, -1.0, -3.0])
    np.testing.assert_array_equal(np.asarray(out.topk_ids[0, 0, :1]), [7])
    np.testing.assert_array_equal(np.asarray(out.topk_ids[0, 1, :2]), [1, 2])

  def test_jit_compatible(self):
    import jax
    from lingvo_tpu.core import beam_search
    from lingvo_tpu.core.nested_map import NestedMap
    import jax.numpy as jnp
    a = NestedMap(topk_ids=jnp.zeros((2, 4, 8), jnp.int32),
                  topk_lens=jnp.ones((2, 4), jnp.int32),
                  topk_scores=jnp.arange(8.0).reshape(2, 4))
    out = jax.jit(lambda a: beam_search.MergeBeamSearchOutputs(2, [a, a]))(a)
    assert out.topk_ids.shape == (2, 2, 8)

  def test_merge_blanks_padding_slots(self):
    from lingvo_tpu.core import beam_search
    from lingvo_tpu.core.nested_map import NestedMap
    import jax.numpy as jnp
    import numpy as np
    # both decoders agree on the single hyp; asking for 3 leaves 2 blank
    a = NestedMap(topk_ids=jnp.array([[[5, 6, 0]]]),
                  topk_lens=jnp.array([[2]]),
                  topk_scores=jnp.array([[-1.0]]))
    out = beam_search.MergeBeamSearchOutputs(3, [a, a])
    # documented fixed layout even when the pool is smaller than requested
    assert out.topk_ids.shape == (1, 3, 3)
    assert np.isneginf(np.asarray(out.topk_scores[0, 1:])).all()
    np.testing.assert_array_equal(np.asarray(out.topk_ids[0, 1:]), 0)
    np.testing.assert_array_equal(np.asarray(out.topk_lens[0, 1:]), 0)
