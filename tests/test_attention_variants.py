"""XL relative attention, Performer FAVOR+, routing attention, funnel
(VERDICT r1 item 9; ref batch_major_attention.py:2233/2125/4458/8162)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import attention, attention_variants
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(13)
B, T, D, N = 2, 16, 16, 4


def _make(cls, **kw):
  p = cls.Params().Set(name="att", input_dim=D, hidden_dim=D, num_heads=N,
                       **kw)
  layer = p.Instantiate()
  return layer, layer.InstantiateVariables(KEY)


class TestTransformerXL:

  def test_shapes_and_causality(self):
    layer, theta = _make(attention_variants.TransformerXLAttention)
    x = jax.random.normal(KEY, (B, T, D))
    out, probs = layer.FProp(theta, x, causal=True)
    assert out.shape == (B, T, D)
    # future positions must carry zero probability
    upper = np.triu(np.ones((T, T)), k=1).astype(bool)
    assert np.asarray(probs)[..., upper].max() < 1e-6

  def test_rel_shift_gather_matches_bruteforce(self):
    """The take_along_axis rel-shift must equal the direct per-(i,j)
    computation of (q + v) . r_{i-j}."""
    layer, theta = _make(attention_variants.TransformerXLAttention)
    t = 6
    x = jax.random.normal(KEY, (1, t, D))
    q = layer._HeadsProj(theta, "query", x)
    rel = layer._SinusoidRel(t)
    th = layer.CastTheta(theta)
    r = jnp.einsum("rd,dnh->rnh", rel.astype(q.dtype), th.w_rel)
    bd_full = jnp.einsum("btnh,rnh->bntr", q + th.v_bias, r)
    idx = (t - 1) - (jnp.arange(t)[:, None] - jnp.arange(t)[None, :])
    bd = jnp.take_along_axis(
        bd_full, jnp.broadcast_to(idx[None, None], (1, N, t, t)), axis=-1)
    # brute force: r index for (i, j) is (t-1) - (i-j)
    for i in range(t):
      for j in range(t):
        expect = jnp.einsum("nh,nh->n", q[0, i] + th.v_bias,
                            r[(t - 1) - (i - j)])
        np.testing.assert_allclose(np.asarray(bd[0, :, i, j]),
                                   np.asarray(expect), atol=1e-5)

  def test_zero_rel_matches_plain_attention(self):
    """With w_rel/u/v zeroed, XL collapses to plain scaled dot-product."""
    layer, theta = _make(attention_variants.TransformerXLAttention,
                         enable_per_dim_scale=False)
    theta.w_rel = jnp.zeros_like(theta.w_rel)
    plain = attention.MultiHeadedAttention.Params().Set(
        name="att", input_dim=D, hidden_dim=D, num_heads=N,
        enable_per_dim_scale=False).Instantiate()
    theta_plain = NestedMap({k: v for k, v in theta.items()
                             if k not in ("w_rel", "u_bias", "v_bias")})
    x = jax.random.normal(KEY, (B, T, D))
    out_xl, _ = layer.FProp(theta, x, causal=True)
    out_pl, _ = plain.FProp(theta_plain, x, causal=True)
    np.testing.assert_allclose(np.asarray(out_xl), np.asarray(out_pl),
                               atol=2e-4)

  def test_respects_paddings(self):
    layer, theta = _make(attention_variants.TransformerXLAttention)
    x = jax.random.normal(KEY, (B, T, D))
    pads = jnp.zeros((B, T)).at[:, 10:].set(1.0)
    _, probs = layer.FProp(theta, x, paddings=pads)
    assert np.asarray(probs)[:, :, :, 10:].max() < 1e-6


class TestPerformer:

  def test_approximates_softmax_attention(self):
    # with many random features, FAVOR+ approaches exact softmax attention
    layer, theta = _make(attention_variants.PerformerAttention,
                         num_random_features=2048,
                         enable_per_dim_scale=False)
    exact = attention.MultiHeadedAttention.Params().Set(
        name="att", input_dim=D, hidden_dim=D, num_heads=N,
        enable_per_dim_scale=False).Instantiate()
    x = 0.3 * jax.random.normal(KEY, (B, T, D))
    out_f, _ = layer.FProp(theta, x)
    out_e, _ = exact.FProp(theta, x)
    err = np.abs(np.asarray(out_f) - np.asarray(out_e)).max()
    assert err < 0.05, err

  def test_causal_no_future_leak(self):
    layer, theta = _make(attention_variants.PerformerAttention,
                         num_random_features=64)
    x = jax.random.normal(KEY, (1, T, D))
    out1, _ = layer.FProp(theta, x, causal=True)
    x2 = x.at[:, 10:].set(9.0)  # perturb the future
    out2, _ = layer.FProp(theta, x2, causal=True)
    np.testing.assert_allclose(np.asarray(out1[:, :10]),
                               np.asarray(out2[:, :10]), atol=1e-4)

  def test_linear_memory_long_sequence(self):
    # 8k tokens: the [T, T] matrix would be 64M floats; FAVOR runs fine
    layer, theta = _make(attention_variants.PerformerAttention,
                         num_random_features=32)
    x = jax.random.normal(KEY, (1, 8192, D))
    out, probs = jax.jit(lambda t, x: layer.FProp(t, x))(theta, x)
    assert out.shape == (1, 8192, D)
    assert probs is None  # never materialized


class TestRoutingAttention:

  def test_single_cluster_full_window_matches_full_attention(self):
    layer, theta = _make(attention_variants.RoutingAttention,
                         num_clusters=1, attention_window=T)
    full = attention.MultiHeadedAttention.Params().Set(
        name="att", input_dim=D, hidden_dim=D, num_heads=N).Instantiate()
    # routing has an extra 'centroids' var; reuse shared projection weights
    x = jax.random.normal(KEY, (B, T, D))
    out_r, _ = layer.FProp(theta, x)
    theta_full = NestedMap(
        {k: v for k, v in theta.items() if k != "centroids"})
    out_f, _ = full.FProp(theta_full, x)
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_f),
                               atol=2e-4)

  def test_causal_and_shapes(self):
    layer, theta = _make(attention_variants.RoutingAttention,
                         num_clusters=4)
    x = jax.random.normal(KEY, (1, T, D))
    out1, _ = layer.FProp(theta, x, causal=True)
    assert out1.shape == (1, T, D)
    x2 = x.at[:, -1].set(7.0)
    out2, _ = layer.FProp(theta, x2, causal=True)
    np.testing.assert_allclose(np.asarray(out1[:, :8]),
                               np.asarray(out2[:, :8]), atol=1e-4)


class TestFunnel:

  def test_pool_and_upsample_shapes(self):
    pool = attention_variants.FunnelPoolingLayer.Params().Set(
        name="pool", stride=2).Instantiate()
    up = attention_variants.FunnelUpsampleLayer.Params().Set(
        name="up", stride=2).Instantiate()
    x = jax.random.normal(KEY, (B, 10, D))
    pads = jnp.zeros((B, 10)).at[1, 7:].set(1.0)
    pooled, ppads = pool.FProp(NestedMap(), x, pads)
    assert pooled.shape == (B, 5, D)
    # row 1: frames 7.. padded -> pooled frame 3 half-padded (valid),
    # pooled frame 4 fully padded
    assert ppads[1, 4] == 1.0 and ppads[1, 3] == 0.0
    restored = up.FProp(NestedMap(), pooled, target_len=10)
    assert restored.shape == (B, 10, D)

  def test_mean_pool_values(self):
    pool = attention_variants.FunnelPoolingLayer.Params().Set(
        name="pool", stride=2).Instantiate()
    x = jnp.asarray([[[1.0], [3.0], [5.0], [7.0]]])
    pooled, _ = pool.FProp(NestedMap(), x)
    np.testing.assert_allclose(np.asarray(pooled[0, :, 0]), [2.0, 6.0])
