"""Car augmentation library: geometry invariants, composition, generator
hook (ref input_preprocessors.py test strategy: each transform preserves
point-in-box membership and label alignment)."""

import json
import math
import os

import numpy as np
import pytest

from lingvo_tpu.models.car import augmentation as aug
from lingvo_tpu.core.nested_map import NestedMap


def _Scene(seed=0, n_pts=200, boxes=None):
  rng = np.random.default_rng(seed)
  pts = rng.uniform(-10, 10, size=(n_pts, 4)).astype(np.float32)
  if boxes is None:
    boxes = np.array([
        [3.0, 2.0, 0.0, 2.0, 1.5, 1.2, 0.3],
        [-4.0, -5.0, 0.5, 3.0, 2.0, 1.5, -0.7],
    ], np.float32)
  classes = np.arange(1, len(boxes) + 1, dtype=np.int32)
  # plant a few points inside each box so membership is non-trivial
  planted = []
  for b in boxes:
    local = rng.uniform(-0.4, 0.4, size=(5, 3)) * b[3:6]
    inside = local @ aug.RotZ(float(b[6])).T + b[:3]  # box frame -> world
    planted.append(np.concatenate(
        [inside, np.ones((5, 1))], axis=1).astype(np.float32))
  pts = np.concatenate([pts] + planted, axis=0)
  return aug.MakeScene(pts, boxes, classes)


def _Membership(scene):
  return aug.PointsInBoxes(scene.points, scene.boxes)


class TestGeometry:

  def test_points_in_boxes_axis_aligned(self):
    boxes = np.array([[0, 0, 0, 2, 2, 2, 0.0]], np.float32)
    pts = np.array([[0, 0, 0, 1], [0.9, 0.9, 0.9, 1], [1.1, 0, 0, 1]],
                   np.float32)
    m = aug.PointsInBoxes(pts, boxes)
    assert m[:, 0].tolist() == [True, True, False]

  def test_points_in_boxes_rotated(self):
    # box rotated 45deg: corner-distance points flip membership
    boxes = np.array([[0, 0, 0, 2, 2, 2, math.pi / 4]], np.float32)
    pts = np.array([[1.2, 0, 0, 1], [0.9, 0.9, 0, 1]], np.float32)
    m = aug.PointsInBoxes(pts, boxes)
    # (1.2, 0) is inside the rotated box (box-frame coords ~(.85, -.85));
    # (0.9, 0.9) is at box-frame (1.27, 0) -> outside
    assert m[:, 0].tolist() == [True, False]

  def test_bev_overlap_detects_rotated_collision(self):
    a = np.array([[0, 0, 0, 4, 1, 1, 0.0]], np.float32)
    b_hit = np.array([[0, 1.5, 0, 4, 1, 1, math.pi / 2]], np.float32)
    b_miss = np.array([[3.0, 3.0, 0, 1, 1, 1, 0.3]], np.float32)
    assert aug.BevBoxOverlap(a, b_hit)[0, 0]
    assert not aug.BevBoxOverlap(a, b_miss)[0, 0]

  def test_bev_overlap_needs_both_axes(self):
    # diagonal neighbors where axis-aligned bounding boxes overlap but the
    # rotated rectangles don't: SAT on the rotated axes must separate them
    a = np.array([[0, 0, 0, 4, 0.5, 1, math.pi / 4]], np.float32)
    b = np.array([[1.8, -1.8, 0, 4, 0.5, 1, math.pi / 4]], np.float32)
    assert not aug.BevBoxOverlap(a, b)[0, 0]


class TestWorldTransforms:

  @pytest.mark.parametrize("make", [
      lambda: aug.RandomWorldRotationAboutZAxis.Params(),
      lambda: aug.RandomFlipY.Params().Set(flip_probability=1.0),
      lambda: aug.WorldScaling.Params().Set(scaling=(0.8, 1.2)),
      lambda: aug.GlobalTranslateNoise.Params(),
  ])
  def test_membership_preserved(self, make):
    scene = _Scene()
    before = _Membership(scene)
    out = make().Instantiate().Apply(scene, np.random.default_rng(1))
    after = _Membership(out)
    np.testing.assert_array_equal(before, after)
    np.testing.assert_array_equal(out.classes, scene.classes)

  def test_rotation_rotates(self):
    scene = _Scene()
    a = aug.RandomWorldRotationAboutZAxis.Params().Set(
        max_rotation=1.0).Instantiate()
    out = a.Apply(scene, np.random.default_rng(3))
    assert not np.allclose(out.points[:, :2], scene.points[:, :2])
    # z and features untouched by a z-rotation
    np.testing.assert_allclose(out.points[:, 2:], scene.points[:, 2:])
    # radii preserved
    np.testing.assert_allclose(
        np.linalg.norm(out.points[:, :2], axis=1),
        np.linalg.norm(scene.points[:, :2], axis=1), rtol=1e-5)

  def test_flip_negates_y_and_phi(self):
    scene = _Scene()
    a = aug.RandomFlipY.Params().Set(flip_probability=1.0).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    np.testing.assert_allclose(out.points[:, 1], -scene.points[:, 1])
    np.testing.assert_allclose(out.boxes[:, 6], -scene.boxes[:, 6])

  def test_flip_prob_zero_is_identity(self):
    scene = _Scene()
    a = aug.RandomFlipY.Params().Set(flip_probability=0.0).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    np.testing.assert_array_equal(out.points, scene.points)

  def test_scaling_scales_dimensions(self):
    scene = _Scene()
    a = aug.WorldScaling.Params().Set(scaling=(2.0, 2.0)).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    np.testing.assert_allclose(out.boxes[:, :6], scene.boxes[:, :6] * 2.0,
                               rtol=1e-6)
    np.testing.assert_allclose(out.boxes[:, 6], scene.boxes[:, 6])


class TestPointTransforms:

  def test_random_drop(self):
    scene = _Scene(n_pts=2000)
    a = aug.RandomDropLaserPoints.Params().Set(keep_prob=0.5).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    frac = out.points.shape[0] / scene.points.shape[0]
    assert 0.4 < frac < 0.6
    np.testing.assert_array_equal(out.boxes, scene.boxes)

  def test_frustum_dropout_drops_cone(self):
    scene = _Scene(n_pts=3000)
    a = aug.FrustumDropout.Params().Set(
        theta_width=0.5, keep_prob=0.0).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    assert out.points.shape[0] < scene.points.shape[0]
    # surviving points: none within the dropped azimuth window of the
    # removed ones is hard to assert exactly (random pick); instead check
    # the drop is angular-coherent: dropped points span < the full circle
    dropped = scene.points.shape[0] - out.points.shape[0]
    assert dropped >= 1

  def test_frustum_dropout_far_keeps_near(self):
    # two points same azimuth, one near one far: 'far' mode with the near
    # point picked must keep the near point
    pts = np.array([[1.0, 0, 0, 1], [9.0, 0, 0, 1]], np.float32)
    scene = aug.MakeScene(pts, np.zeros((0, 7)), np.zeros((0,)))
    a = aug.FrustumDropout.Params().Set(
        theta_width=0.2, keep_prob=0.0, drop_type="far").Instantiate()
    # try seeds until the pick lands on index 0 (near)
    for seed in range(20):
      out = a.Apply(scene, np.random.default_rng(seed))
      if out.points.shape[0] == 1:
        assert out.points[0, 0] == 1.0
        return
    pytest.fail("no seed picked the near point")


class TestBoxTransforms:

  def test_bbox_transform_moves_points_with_box(self):
    scene = _Scene()
    before = _Membership(scene)
    a = aug.RandomBBoxTransform.Params().Set(
        max_rotation=0.5, noise_std=(1.0, 1.0, 0.0)).Instantiate()
    out = a.Apply(scene, np.random.default_rng(2))
    after = _Membership(out)
    # membership of planted interior points survives the per-box move
    np.testing.assert_array_equal(before, after)
    assert not np.allclose(out.boxes, scene.boxes)

  def test_gt_augmentor_pastes_and_carves(self):
    scene = _Scene()
    db = [{"box": [8.0, 8.0, 0.0, 2.0, 2.0, 1.0, 0.1], "class": 3,
           "points": np.array([[8.0, 8.0, 0.0, 1.0],
                               [8.2, 8.1, 0.1, 1.0]], np.float32)}]
    a = aug.GroundTruthAugmentor.Params().Set(
        db=db, num_to_add=1).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    assert out.boxes.shape[0] == scene.boxes.shape[0] + 1
    assert out.classes[-1] == 3
    np.testing.assert_allclose(out.boxes[-1], db[0]["box"], rtol=1e-6)
    # db points present
    assert (out.points[:, :3] == np.array([8.0, 8.0, 0.0])).all(1).any()

  def test_gt_augmentor_rejects_collisions(self):
    scene = _Scene()
    # db entry right on top of an existing box
    db = [{"box": scene.boxes[0].tolist(), "class": 3,
           "points": np.ones((3, 4), np.float32)}]
    a = aug.GroundTruthAugmentor.Params().Set(
        db=db, num_to_add=1).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    assert out.boxes.shape[0] == scene.boxes.shape[0]

  def test_build_gt_db(self):
    scene = _Scene()
    db = aug.BuildGroundTruthDb([scene], min_points=1)
    assert len(db) == 2  # both boxes have 5 planted points
    for e in db:
      assert e["points"].shape[0] >= 5


class TestFilters:

  def test_filter_by_num_points(self):
    scene = _Scene()
    # add an empty box far away
    boxes = np.concatenate(
        [scene.boxes, [[50.0, 50.0, 0, 1, 1, 1, 0]]]).astype(np.float32)
    scene = aug._With(scene, boxes=boxes,
                      classes=np.array([1, 2, 3], np.int32))
    a = aug.FilterGroundTruthByNumPoints.Params().Set(
        min_num_points=1).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    assert out.boxes.shape[0] == 2
    assert out.classes.tolist() == [1, 2]

  def test_drop_boxes_out_of_range(self):
    scene = _Scene()
    a = aug.DropBoxesOutOfRange.Params().Set(
        keep_x_range=(0.0, 10.0)).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    assert (out.boxes[:, 0] >= 0).all()
    assert out.boxes.shape[0] == 1  # box at x=-4 dropped

  def test_drop_points_out_of_range(self):
    scene = _Scene()
    a = aug.DropPointsOutOfRange.Params().Set(
        keep_z_range=(-1.0, 1.0)).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    assert (np.abs(out.points[:, 2]) <= 1.0).all()

  def test_difficulty_tracks_filtering(self):
    scene = _Scene()
    scene.difficulty = np.array([0, 2], np.int32)
    a = aug.DropBoxesOutOfRange.Params().Set(
        keep_x_range=(0.0, 10.0)).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    assert out.difficulty.tolist() == [0]


class TestComposition:

  def test_random_apply_prob1(self):
    scene = _Scene()
    a = aug.RandomApply.Params().Set(
        prob=1.0,
        subprocessor=aug.RandomFlipY.Params().Set(
            flip_probability=1.0)).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    np.testing.assert_allclose(out.points[:, 1], -scene.points[:, 1])

  def test_random_apply_prob0(self):
    scene = _Scene()
    a = aug.RandomApply.Params().Set(
        prob=0.0,
        subprocessor=aug.RandomFlipY.Params().Set(
            flip_probability=1.0)).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    np.testing.assert_array_equal(out.points, scene.points)

  def test_random_choice_applies_exactly_one(self):
    scene = _Scene()
    a = aug.RandomChoice.Params().Set(subprocessors=[
        aug.WorldScaling.Params().Set(scaling=(2.0, 2.0)),
        aug.WorldScaling.Params().Set(scaling=(3.0, 3.0)),
    ]).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    ratio = out.boxes[0, 3] / scene.boxes[0, 3]
    assert abs(ratio - 2.0) < 1e-5 or abs(ratio - 3.0) < 1e-5

  def test_sequence_order(self):
    scene = _Scene()
    a = aug.Sequence.Params().Set(subprocessors=[
        aug.WorldScaling.Params().Set(scaling=(2.0, 2.0)),
        aug.GlobalTranslateNoise.Params().Set(noise_std=(0.0, 0.0, 0.0)),
    ]).Instantiate()
    out = a.Apply(scene, np.random.default_rng(0))
    np.testing.assert_allclose(out.boxes[:, 3:6], scene.boxes[:, 3:6] * 2,
                               rtol=1e-6)

  def test_pipeline_deterministic_per_seed(self):
    scene = _Scene()
    pipe = aug.BuildPipeline([
        aug.RandomWorldRotationAboutZAxis.Params(),
        aug.RandomFlipY.Params(),
        aug.RandomDropLaserPoints.Params().Set(keep_prob=0.9),
    ])
    o1 = aug.ApplyPipeline(pipe, scene, seed=7)
    o2 = aug.ApplyPipeline(pipe, scene, seed=7)
    o3 = aug.ApplyPipeline(pipe, scene, seed=8)
    np.testing.assert_array_equal(o1.points, o2.points)
    assert (o1.points.shape != o3.points.shape
            or not np.allclose(o1.points, o3.points))


class TestGeneratorHook:

  def _WriteScenes(self, tmp_path, n=4):
    path = os.path.join(tmp_path, "scenes.jsonl")
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
      for i in range(n):
        pts = rng.uniform(0, 16, size=(64, 4)).astype(np.float32)
        # one car per scene, axis-aligned, with interior points
        box_center = [8.0 + i * 0.5, 8.0, 0.0]
        interior = (rng.uniform(-0.3, 0.3, size=(6, 3))
                    * [3.0, 1.5, 1.4] + box_center)
        pts = np.concatenate(
            [pts, np.concatenate([interior, np.ones((6, 1))], 1)],
            axis=0).astype(np.float32)
        label = (f"Car 0.0 0 0.0 300 150 400 250 1.4 1.5 3.0 "
                 f"{-box_center[1]:.1f} {1.4 / 2:.1f} {box_center[0]:.1f} "
                 f"{-np.pi / 2:.4f}")
        f.write(json.dumps({"points": pts.tolist(),
                            "labels": [label]}) + "\n")
    return path

  def test_kitti_generator_with_augmentors(self, tmp_path):
    from lingvo_tpu.models.car import kitti_input
    path = self._WriteScenes(str(tmp_path))
    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        file_pattern=path, batch_size=2,
        augmentors=[
            aug.RandomWorldRotationAboutZAxis.Params().Set(
                max_rotation=0.3),
            aug.RandomFlipY.Params(),
            aug.RandomDropLaserPoints.Params().Set(keep_prob=0.9),
        ])
    gen = p.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.gt_boxes.shape == (2, 8, 7)
    assert np.isfinite(np.asarray(batch.lasers)).all()
    # the gt box survived augmentation (class 1 = Car present)
    assert (np.asarray(batch.gt_classes) == 1).any()

  def test_waymo_generator_with_augmentors(self, tmp_path):
    from lingvo_tpu.models.car import waymo_input
    path = os.path.join(str(tmp_path), "frames.jsonl")
    rng = np.random.default_rng(1)
    with open(path, "w") as f:
      for _ in range(3):
        pts = rng.uniform(-20, 20, size=(128, 5)).astype(np.float32)
        f.write(json.dumps({
            "points": pts.tolist(),
            "labels": [{"box": [5.0, 2.0, 0.0, 4.0, 2.0, 1.6, 0.2],
                        "type": "TYPE_VEHICLE", "num_points": 9,
                        "speed": [1.0, 0.5]}],
        }) + "\n")
    p = waymo_input.WaymoSceneInputGenerator.Params().Set(
        file_pattern=path, batch_size=2, max_points=256,
        augmentors=[aug.RandomFlipY.Params().Set(flip_probability=1.0)])
    gen = p.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    # flip negated the box y center; speed/num_points survive
    got = np.asarray(batch.gt_boxes)
    rows = np.asarray(batch.gt_classes) == 1
    assert rows.any()
    assert np.allclose(got[rows][:, 1], -2.0, atol=1e-5)
    assert (np.asarray(batch.gt_num_points)[rows] == 9).all()
