"""Speculative decoding (serving/spec_decode.py + friends).

Covers docs/speculative_decoding.md:
- `SpecVerifyTokens` greedy acceptance: longest matching prefix, ragged
  `draft_valid` masking, and out_tokens == the target argmax chain (the
  bitwise-identity primitive); at temperature > 0 the all-accepted bonus
  draw is bitwise the legacy `SampleFromLogits` draw at that stream
  position and forced rejections land in the residual support,
- `GatedSSMLayer.PagedStep(collect_col_states=True)` returns per-column
  states matching the chained single-token decode path (snapshot), and
  `_SelectAcceptedCols` restores the chosen column (restore),
- scheduler `BuildVerifyStep` raggedness (opt-out rows ride with
  in_len == 1, draft length clamped to the remaining token budget) and
  `CommitVerifyStep` cursor rollback + eos retirement mid-prefix, with
  `rolled_back_tokens` accounted on the page pool,
- the engine bar: greedy spec output streams TOKEN-IDENTICAL to the
  non-speculative engine on a seeded 20-request mixed-length stream, for
  BOTH draft sources (early-exit self-speculation and an independent
  pageless SSM draft model), including hybrid-SSM targets (state
  rollback on the real path) and draft-state catch-up after long
  neighbor prefills,
- acceptance telemetry: `draft_tokens` / `accepted_tokens` /
  `accepted_len_hist` in engine Stats(), zero/empty on legacy engines,
- (slow) residual speculative sampling preserves the per-position output
  law at temperature > 0.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import sampling, ssm
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.serving import engine as engine_lib
from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import scheduler as scheduler_lib
from lingvo_tpu.serving import spec_decode


# -- shared tiny models: session-scoped fixtures live in conftest.py ----------

from tests.conftest import InstantiateLm as _Instantiate  # noqa: E402
from tests.conftest import TinyLmParams as _LmParams  # noqa: E402


def _Engine(task, theta, spec=None, *, max_batch=3, num_pages=24,
            max_seq_len=32, **kw):
  return engine_lib.ServingLoop(
      task, theta, page_size=4, num_pages=num_pages, max_batch=max_batch,
      max_seq_len=max_seq_len, prefill_chunk=4, default_max_new=8,
      spec=spec, **kw)


def _Stream(n=20, seed=0, max_len=10, max_new=6):
  """Seeded mixed-length request stream (prompt, max_new) pairs."""
  rng = np.random.RandomState(seed)
  reqs = []
  for _ in range(n):
    p_len = int(rng.randint(1, max_len))
    reqs.append(([int(t) for t in rng.randint(1, 64, size=p_len)],
                 int(rng.randint(1, max_new))))
  return reqs


def _RunStream(eng, reqs, **submit_kw):
  """Submits a whole stream, drives the loop inline, returns the outputs."""
  handles = [eng.Submit(p, m, eos_id=None, **submit_kw) for p, m in reqs]
  while eng.sched.HasWork():
    eng.StepOnce()
  return [h.Result(timeout=0) for h in handles]


# -- SpecVerifyTokens ---------------------------------------------------------


class TestSpecVerifyTokens:

  def test_greedy_accepts_longest_matching_prefix(self):
    # target argmax chain per column is token (col + 1); draft matches
    # cols 0,1 then diverges, so accept_len == 2
    b, c, v = 2, 4, 8
    logits = np.full((b, c, v), -5.0, np.float32)
    for j in range(c):
      logits[:, j, j + 1] = 5.0
    draft = np.array([[1, 2, 7], [1, 5, 3]], np.int32)
    out, alen = sampling.SpecVerifyTokens(
        jnp.asarray(logits), jnp.asarray(draft), jnp.zeros((b, 3, v)),
        jax.random.PRNGKey(0))
    # out is the argmax chain itself regardless of the proposals
    np.testing.assert_array_equal(np.asarray(out),
                                  [[1, 2, 3, 4], [1, 2, 3, 4]])
    assert list(np.asarray(alen)) == [2, 1]

  def test_greedy_draft_valid_masks_ragged_tails(self):
    b, c, v = 1, 4, 8
    logits = np.full((b, c, v), -5.0, np.float32)
    logits[:, :, 2] = 5.0                       # argmax chain: 2,2,2,2
    draft = np.array([[2, 2, 2]], np.int32)     # all would match...
    valid = np.array([[True, False, False]])    # ...but the row_k was 1
    _, alen = sampling.SpecVerifyTokens(
        jnp.asarray(logits), jnp.asarray(draft), jnp.zeros((b, 3, v)),
        jax.random.PRNGKey(0), draft_valid=jnp.asarray(valid))
    assert int(alen[0]) == 1

  def test_bonus_draw_bitwise_matches_legacy_stream(self):
    # all proposals accepted (draft == target argmax under a peaked
    # target): the bonus token at the last column must be the EXACT
    # SampleFromLogits draw the non-spec engine makes at that position
    b, k, v = 3, 2, 16
    rng = np.random.RandomState(3)
    tl = rng.randn(b, k + 1, v).astype(np.float32)
    tl[:, :k] += 100.0 * np.eye(v)[rng.randint(v, size=(b, k))]
    draft = np.argmax(tl[:, :k], axis=-1).astype(np.int32)
    key = jax.random.PRNGKey(11)
    seeds = np.array([5, 6, 7], np.int32)
    pos = np.array([0, 3, 9], np.int32)
    out, alen = sampling.SpecVerifyTokens(
        jnp.asarray(tl), jnp.asarray(draft), jnp.asarray(tl[:, :k]),
        key, temperature=0.7, top_k=0, row_seeds=jnp.asarray(seeds),
        row_pos=jnp.asarray(pos))
    assert list(np.asarray(alen)) == [k] * b
    legacy = sampling.SampleFromLogits(
        jnp.asarray(tl[:, k]), key, temperature=0.7,
        row_seeds=jnp.asarray(seeds), positions=jnp.asarray(pos + k))
    np.testing.assert_array_equal(np.asarray(out[:, k]),
                                  np.asarray(legacy))

  def test_forced_rejection_samples_from_residual_support(self):
    # the draft proposes a token the (top-k-masked) target gives zero
    # mass: p(d) == 0 forces rejection, and the replacement must come
    # from the residual support {t : p(t) > q(t)}
    b, v = 4, 8
    tl = np.full((b, 2, v), -1.0, np.float32)
    tl[:, :, 0] = 8.0                     # target mass ~all on token 0
    ql = np.full((b, 1, v), -1.0, np.float32)
    ql[:, :, 5] = 8.0                     # draft mass ~all on token 5
    draft = np.full((b, 1), 5, np.int32)
    out, alen = sampling.SpecVerifyTokens(
        jnp.asarray(tl), jnp.asarray(draft), jnp.asarray(ql),
        jax.random.PRNGKey(2), temperature=1.0, top_k=2,
        row_seeds=jnp.arange(b, dtype=jnp.int32),
        row_pos=jnp.zeros((b,), jnp.int32))
    assert list(np.asarray(alen)) == [0] * b
    assert all(int(t) == 0 for t in np.asarray(out[:, 0]))


# -- SSM per-column state collection + rollback -------------------------------


class TestSsmColStates:

  def _Layer(self):
    p = ssm.GatedSSMLayer.Params().Set(
        name="s", input_dim=16, hidden_dim=16, num_heads=2, state_dim=4,
        chunk_size=4)
    return _Instantiate(p, seed=4)

  def test_col_states_match_single_token_chain(self):
    layer, theta = self._Layer()
    b, c = 3, 5
    x = jax.random.normal(jax.random.PRNGKey(7), (b, c, 16))
    states = layer.InitPagedStates(theta, 2, 4, b)
    tables = jnp.zeros((b, 1), jnp.int32)
    q_pos = jnp.array([4, 4, 4], jnp.int32)   # != 0: no device-side reset
    in_len = jnp.array([c, 3, 0], jnp.int32)
    out_c, ns = layer.PagedStep(theta, x, states, tables, q_pos, in_len,
                                collect_col_states=True)
    assert "col_states" in ns and ns.col_states.shape[1] == c
    # the final state IS the last column's snapshot (same computation)
    np.testing.assert_array_equal(np.asarray(ns.state),
                                  np.asarray(ns.col_states[:, -1]))
    # masked columns must leave the state untouched: row 1 (in_len 3)
    # freezes after col 2, row 2 (in_len 0) never moves
    np.testing.assert_array_equal(np.asarray(ns.col_states[1, 2]),
                                  np.asarray(ns.col_states[1, 4]))
    np.testing.assert_array_equal(np.asarray(ns.col_states[2, 0]),
                                  np.asarray(ns.col_states[2, 4]))
    np.testing.assert_array_equal(np.asarray(ns.col_states[2, 4]),
                                  np.asarray(states.state[2]))
    # reference: C single-token PagedSteps (the legacy decode path). The
    # projections batch over C in collect mode, so cross-path agreement is
    # float-tolerance, not bitwise — same bar the mixed prefill+decode
    # step already meets vs per-token decode
    ref = states
    out_ref = []
    for j in range(c):
      oj, ref = layer.PagedStep(theta, x[:, j:j + 1], ref, tables,
                                q_pos + j,
                                (in_len > j).astype(jnp.int32))
      out_ref.append(oj[:, 0])
      np.testing.assert_allclose(np.asarray(ns.col_states[:, j]),
                                 np.asarray(ref.state),
                                 rtol=1e-5, atol=1e-6, err_msg=f"col {j}")
    np.testing.assert_allclose(np.asarray(out_c),
                               np.asarray(jnp.stack(out_ref, 1)),
                               rtol=1e-5, atol=1e-5)

  def test_select_accepted_cols_restores_snapshot(self):
    b, c, n, h, s = 4, 3, 2, 3, 5
    cols = np.arange(b * c * n * h * s, dtype=np.float32).reshape(
        b, c, n, h, s)
    tree = NestedMap(
        layer=NestedMap(state=jnp.asarray(cols[:, -1]),
                        col_states=jnp.asarray(cols)),
        passthrough=[NestedMap(pool=jnp.ones((2, 2)))])
    alen = jnp.array([0, 2, 1, 0], jnp.int32)
    out = spec_decode._SelectAcceptedCols(tree, alen)
    assert "col_states" not in out.layer       # trajectory stripped
    for i, m in enumerate([0, 2, 1, 0]):
      np.testing.assert_array_equal(np.asarray(out.layer.state[i]),
                                    cols[i, m])
    # unrelated leaves (paged KV pools) pass through untouched
    np.testing.assert_array_equal(np.asarray(out.passthrough[0].pool),
                                  np.ones((2, 2)))


# -- scheduler verify-step lifecycle (device-free) ----------------------------


def _DecodingSched(reqs, slots=2):
  """Admits reqs and fast-forwards every row to DECODE with one token out."""
  alloc = kv_cache.PageAllocator(16, 4)
  sched = scheduler_lib.Scheduler(slots, alloc, 4, 4)
  for r in reqs:
    sched.Submit(r)
  sched.Admit()
  while any(s is not None and s.state is scheduler_lib.SeqState.PREFILL
            for s in sched.slots):
    batch = sched.BuildStep()
    sched.CommitStep(batch, np.full(batch.ids.shape, 7, np.int32))
  return sched, alloc


class TestVerifySchedulerLifecycle:

  def test_build_verify_raggedness_and_optout(self):
    sched, _ = _DecodingSched([
        scheduler_lib.Request("a", [1, 2, 3], 8),            # full k
        scheduler_lib.Request("b", [4, 5], 8, spec_k=0),     # opted out
    ])
    vb = sched.BuildVerifyStep(k=4)
    assert vb is not None and vb.ids.shape == (2, 5)
    assert list(vb.row_k) == [4, 0] and list(vb.in_len) == [5, 1]
    assert vb.ids[0, 0] == 7 and vb.ids[1, 0] == 7   # last emitted token
    assert list(vb.q_pos) == [3, 2]

  def test_build_verify_clamps_to_remaining_budget(self):
    # max_new == 2 and one token already out: only 1 more may ever be
    # written, so row_k must clamp to 1 (KV writes stay inside the pages
    # reserved at admission)
    sched, _ = _DecodingSched([scheduler_lib.Request("a", [1, 2], 2)])
    vb = sched.BuildVerifyStep(k=4)
    assert list(vb.row_k)[0] == 1 and list(vb.in_len)[0] == 2

  def test_build_verify_none_during_prefill_or_all_optout(self):
    alloc = kv_cache.PageAllocator(16, 4)
    sched = scheduler_lib.Scheduler(2, alloc, 4, 4)
    sched.Submit(scheduler_lib.Request("a", [1, 2, 3, 4, 5, 6], 4))
    sched.Admit()
    assert sched.BuildVerifyStep(k=4) is None   # still prefilling
    sched2, _ = _DecodingSched(
        [scheduler_lib.Request("b", [1], 8, spec_k=0)])
    assert sched2.BuildVerifyStep(k=4) is None  # nobody speculates

  def test_commit_rolls_back_rejected_tail(self):
    sched, alloc = _DecodingSched([scheduler_lib.Request("a", [1, 2], 8)])
    seq = sched._by_id["a"]
    pos0 = seq.pos
    vb = sched.BuildVerifyStep(k=4)
    out = np.array([[11, 12, 13, 14, 15]], np.int32)
    events = sched.CommitVerifyStep(vb, out, np.array([2], np.int32))
    # 2 accepted + 1 correction committed; 2 drafted tokens rolled back
    assert events == [("a", 11, False), ("a", 12, False), ("a", 13, False)]
    assert seq.pos == pos0 + 3 and seq.out[-3:] == [11, 12, 13]
    assert alloc.rolled_back_tokens == 2
    assert alloc.Stats()["rolled_back_tokens"] == 2

  def test_commit_eos_mid_prefix_retires_and_rolls_back(self):
    sched, alloc = _DecodingSched(
        [scheduler_lib.Request("a", [1, 2], 8, eos_id=12)])
    vb = sched.BuildVerifyStep(k=4)
    out = np.array([[11, 12, 13, 14, 15]], np.int32)
    events = sched.CommitVerifyStep(vb, out, np.array([4], np.int32))
    # eos at the 2nd committed token: stream truncates there, the row
    # retires, its pages free, and the 3 unconsumed accepted tokens are
    # rolled back on top of the 0 rejected ones
    assert events == [("a", 11, False), ("a", 12, True)]
    assert sched._by_id["a"].finish_reason == "eos"
    assert alloc.num_free == alloc.num_pages
    assert alloc.rolled_back_tokens == 3

  def test_commit_max_new_truncates_prefix(self):
    sched, alloc = _DecodingSched([scheduler_lib.Request("a", [1, 2], 3)])
    vb = sched.BuildVerifyStep(k=4)   # row_k clamps to 3 - 1 = 2
    assert list(vb.row_k)[0] == 2
    out = np.array([[11, 12, 13, 0, 0]], np.int32)
    events = sched.CommitVerifyStep(vb, out, np.array([2], np.int32))
    assert [e[1] for e in events] == [11, 12]
    assert events[-1][2] and sched._by_id["a"].finish_reason == "length"
    assert alloc.rolled_back_tokens == 1   # the never-emitted correction


# -- the engine bar: token identity + telemetry -------------------------------


class TestSpecEngine:

  def _Baseline(self, task, theta, reqs):
    return _RunStream(_Engine(task, theta), reqs)

  def test_self_draft_20_request_stream_token_identical(self, tiny_lm):
    task, theta = tiny_lm
    reqs = _Stream(20)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=4, num_layers=1))
    assert _RunStream(eng, reqs) == base
    stats = eng.Stats()
    assert stats["spec_cycles"] > 0
    assert stats["draft_tokens"] >= stats["accepted_tokens"] >= 0
    assert sum(m * n for m, n in enumerate(stats["accepted_len_hist"])) \
        == stats["accepted_tokens"]
    assert stats["kv_pages"]["free"] == eng.num_pages

  def test_model_draft_20_request_stream_token_identical(self, tiny_lm,
                                                         ssm_draft_lm):
    task, theta = tiny_lm
    dtask, dtheta = ssm_draft_lm
    reqs = _Stream(20, seed=1)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.ModelDraft(dtask, dtheta, k=4))
    assert _RunStream(eng, reqs) == base
    stats = eng.Stats()
    assert stats["spec_cycles"] > 0 and stats["draft_tokens"] > 0

  def test_hybrid_target_rollback_token_identical(self, hybrid_lm):
    """Hybrid SSM+attention target: rejected verify columns must roll the
    recurrent state back (snapshot-and-restore on the real path)."""
    task, theta = hybrid_lm
    reqs = _Stream(8, seed=2)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=4, num_layers=1))
    assert _RunStream(eng, reqs) == base
    stats = eng.Stats()
    # a 1-layer draft of a 2-layer hybrid WILL mispredict sometimes;
    # identity above proves those rejections restored the SSM state
    assert stats["spec_cycles"] > 0

  def test_repeat_stack_prefix_draft_token_identical(self):
    """RepeatedTransformerLayer target: the early-exit prefix slices the
    scanned theta/states to the leading repeats, suffix states pass
    through untouched."""
    task, theta = _Instantiate(
        _LmParams().Set(use_repeat_layer=True, num_layers=3))
    reqs = _Stream(6, seed=6)
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=3, num_layers=1))
    assert _RunStream(eng, reqs) == base
    assert eng.Stats()["spec_cycles"] > 0

  def test_full_depth_self_draft_accepts_everything(self, tiny_lm):
    """num_layers == full depth makes the draft argmax == target argmax,
    so greedy acceptance must be total (up to budget clamps)."""
    task, theta = tiny_lm
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=4, num_layers=2))
    prompts = np.array([[5, 6, 7, 8], [9, 10, 0, 0]], np.int32)
    out = eng.RunBatch(prompts, np.array([4, 2], np.int32), 8)
    base = _Engine(task, theta).RunBatch(
        prompts, np.array([4, 2], np.int32), 8)
    np.testing.assert_array_equal(out, base)
    stats = eng.Stats()
    assert stats["accepted_tokens"] == stats["draft_tokens"] > 0

  def test_model_draft_drains_backlog_after_long_prefill(self, tiny_lm,
                                                         ssm_draft_lm):
    """A decode row riding many mixed steps (neighbor prefilling a long
    prompt) accumulates draft-state backlog > k+1; the drain path must
    catch up without breaking identity."""
    task, theta = tiny_lm
    dtask, dtheta = ssm_draft_lm
    long_prompt = [int(t) for t in
                   np.random.RandomState(5).randint(1, 64, size=24)]
    reqs = [([3, 1, 4], 16), (long_prompt, 4)]
    base = self._Baseline(task, theta, reqs)
    eng = _Engine(task, theta, spec_decode.ModelDraft(dtask, dtheta, k=2),
                  max_batch=2, num_pages=32, max_seq_len=40)
    assert _RunStream(eng, reqs) == base

  def test_eos_mid_verify_on_engine(self, tiny_lm):
    """eos emitted inside an accepted prefix: spec engine must truncate
    exactly where the non-spec engine stops."""
    task, theta = tiny_lm
    base_eng = _Engine(task, theta)
    h = base_eng.Submit([5, 6, 7, 8], 8, eos_id=None)
    while base_eng.sched.HasWork():
      base_eng.StepOnce()
    ref = h.Result(timeout=0)
    eos = ref[2]   # a token the model verifiably emits mid-stream
    truncated = ref[:ref.index(eos) + 1]
    for spec in (spec_decode.SelfDraft(k=4, num_layers=2),
                 spec_decode.SelfDraft(k=4, num_layers=1)):
      eng = _Engine(task, theta, spec)
      h2 = eng.Submit([5, 6, 7, 8], 8, eos_id=eos)
      while eng.sched.HasWork():
        eng.StepOnce()
      assert h2.Result(timeout=0) == truncated
      assert h2.finish_reason == "eos"
      assert eng.Stats()["kv_pages"]["free"] == eng.num_pages

  def test_stats_telemetry_surface(self, tiny_lm):
    from lingvo_tpu.observe import schema as observe_schema
    task, theta = tiny_lm
    legacy = _Engine(task, theta)
    stats = legacy.Stats()
    observe_schema.ValidateEngineStats(stats)
    # the keys exist on EVERY engine; legacy engines pin them at zero
    assert stats["spec_cycles"] == 0 and stats["draft_tokens"] == 0
    assert stats["accepted_tokens"] == 0
    assert stats["accepted_len_hist"] == [] and "spec" not in stats
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=3, num_layers=1))
    eng.RunBatch(np.array([[5, 6]], np.int32), np.array([2], np.int32), 6)
    stats = eng.Stats()
    observe_schema.ValidateEngineStats(stats)
    assert stats["spec"] == {"draft": "self", "k": 3, "w": 1,
                             "num_layers": 1}
    assert len(stats["accepted_len_hist"]) == 4   # k + 1 buckets
    assert sum(stats["accepted_len_hist"]) == stats["spec_cycles"]

  def test_rollback_counter_consistent_with_acceptance(self, tiny_lm):
    task, theta = tiny_lm
    eng = _Engine(task, theta, spec_decode.SelfDraft(k=4, num_layers=1))
    reqs = _Stream(6, seed=3)
    _RunStream(eng, reqs)
    stats = eng.Stats()
    rejected = stats["draft_tokens"] - stats["accepted_tokens"]
    # rolled_back >= rejected: every rejected draft rolls back, plus any
    # accepted-but-eos/budget-truncated corrections
    assert stats["kv_pages"]["rolled_back_tokens"] >= rejected

  def test_model_draft_rejects_paged_draft_models(self, tiny_lm):
    task, theta = tiny_lm
    with pytest.raises(AssertionError, match="pageless"):
      _Engine(task, theta, spec_decode.ModelDraft(task, theta, k=2))


# -- residual speculative sampling law (slow) ---------------------------------


@pytest.mark.slow
class TestResidualSamplingLaw:

  def test_emitted_marginal_matches_target_law(self):
    """Accept-or-residual must emit exactly softmax(p) at each position:
    empirical frequencies over many independent rows vs the target law."""
    b, v = 4000, 6
    rng = np.random.RandomState(0)
    tl = np.tile(rng.randn(1, 2, v).astype(np.float32), (b, 1, 1))
    ql = np.tile(rng.randn(1, 1, v).astype(np.float32), (b, 1, 1))
    # draft proposals drawn from q's own law so acceptance is realistic
    qp = np.exp(ql[0, 0]) / np.exp(ql[0, 0]).sum()
    draft = rng.choice(v, size=(b, 1), p=qp).astype(np.int32)
    out, _ = sampling.SpecVerifyTokens(
        jnp.asarray(tl), jnp.asarray(draft), jnp.asarray(ql),
        jax.random.PRNGKey(9), temperature=1.0, top_k=0,
        row_seeds=jnp.arange(b, dtype=jnp.int32),
        row_pos=jnp.zeros((b,), jnp.int32))
    freq = np.bincount(np.asarray(out[:, 0]), minlength=v) / b
    p = np.exp(tl[0, 0]) / np.exp(tl[0, 0]).sum()
    assert np.abs(freq - p).sum() < 0.05   # total-variation tolerance

  def test_spec_engine_temp_gt0_runs_and_replays(self, tiny_lm):
    task, theta = tiny_lm
    reqs = _Stream(6, seed=4)
    outs = []
    for _ in range(2):
      eng = _Engine(task, theta,
                    spec_decode.SelfDraft(k=3, num_layers=1),
                    temperature=0.8, top_k=8, sample_seed=13)
      outs.append(_RunStream(eng, reqs))
    assert outs[0] == outs[1]   # engine-level replayability survives spec
