"""Registry-wide smoke test (VERDICT r3 Missing #6): one generated check per
registered model config x dataset, with stubbed (abstract) variables.

Mirrors `lingvo/core/models_test_helper.py:96,172`
(CreateTestMethodsForAllRegisteredModels + _StubOutCreateVariable): the
reference instantiates every registered model's params with initializer
stubs to catch param/shape wiring errors across the whole zoo without real
compute. Here `VariableSpecs()` (pure shape math) plus
`jax.eval_shape(CreateTrainState)` (abstract trace: full variable creation,
learner/optimizer state trees, EMA) give the same insurance — every
registered config must build its task and trace its state tree.
"""

import jax
import numpy as np
import pytest

from lingvo_tpu import datasets as datasets_lib
from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401 — populates the registry

# Giant-LM configs whose abstract state trace is slow/huge; their sharded
# train step is already AOT-validated every round by
# __graft_entry__.dryrun_multichip, so specs-only here.
_SPECS_ONLY = ("8B", "128B", "175B", "1T")


def _AllModelDatasetPairs():
  pairs = []
  for name, cls in sorted(model_registry.GetRegisteredModels().items()):
    for ds in datasets_lib.GetDatasets(cls, warn_on_error=False):
      pairs.append((name, ds))
  return pairs


_PAIRS = _AllModelDatasetPairs()


def test_registry_is_populated():
  assert len(_PAIRS) >= 20, _PAIRS


@pytest.mark.parametrize("name,ds", _PAIRS,
                         ids=[f"{n}:{d}" for n, d in _PAIRS])
def test_registered_config_builds_and_traces(name, ds):
  mp = model_registry.GetParams(name, ds)
  mp.task.input = mp.input
  task = mp.task.Instantiate()
  task.FinalizePaths()

  specs = task.VariableSpecs()
  flat = specs.FlattenItems()
  assert flat, f"{name}:{ds} has no variables"
  for path, spec in flat:
    assert all(int(d) >= 0 for d in spec.shape), (name, path, spec.shape)

  n_params = sum(int(np.prod(spec.shape)) for _, spec in flat)
  assert n_params > 0

  if any(tag in name for tag in _SPECS_ONLY):
    return
  # Abstract state creation: catches optimizer-slot / learner wiring errors
  # (shape mismatches raise inside the trace; nothing is materialized).
  state = jax.eval_shape(task.CreateTrainState, jax.random.PRNGKey(0))
  assert "theta" in state and "opt_states" in state
