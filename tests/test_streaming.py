"""Streaming (chunked) == offline equivalence tests for conformer/conv
(VERDICT r1 item 5; ref `stream_step_test_base.py` — the critical ASR
streaming property)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import attention, conformer_layer
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(9)
B, T, D = 2, 24, 16
CHUNK = 4


def _stream(layer, theta, x, paddings, init_states):
  outs = []
  states = init_states
  for s in range(0, T, CHUNK):
    out, states = layer.StreamStep(theta, x[:, s:s + CHUNK],
                                   paddings[:, s:s + CHUNK], states)
    outs.append(out)
  return jnp.concatenate(outs, axis=1)


class TestStreamingEquivalence:

  def test_lconv_streaming_equals_offline(self):
    p = conformer_layer.LConvLayer.Params().Set(
        name="lconv", input_dim=D, kernel_size=8, causal=True,
        conv_norm="ln")
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, T, D))
    paddings = jnp.zeros((B, T)).at[1, 20:].set(1.0)
    offline = layer.FProp(theta, x, paddings)
    streamed = _stream(layer, theta, x, paddings,
                       layer.InitStreamStates(B))
    np.testing.assert_allclose(np.asarray(offline), np.asarray(streamed),
                               atol=2e-5)

  @pytest.mark.parametrize("left_context", [4, 9])
  def test_windowed_attention_streaming_equals_local(self, left_context):
    # streaming MHA window == offline LocalSelfAttention(left, right=0)
    pl = attention.LocalSelfAttention.Params().Set(
        name="att", input_dim=D, hidden_dim=D, num_heads=4,
        block_size=max(left_context - 1, CHUNK),
        left_context=left_context, right_context=0,
        use_rotary_position_emb=True)
    layer = pl.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, T, D))
    paddings = jnp.zeros((B, T)).at[0, 21:].set(1.0)
    offline, _ = layer.FProp(theta, x, paddings=paddings)
    streamed = _stream(layer, theta, x, paddings,
                       layer.InitStreamStates(B, left_context))
    np.testing.assert_allclose(np.asarray(offline), np.asarray(streamed),
                               atol=3e-5)

  def test_conformer_streaming_equals_offline(self):
    p = conformer_layer.ConformerLayer.Params().Set(
        name="conf", input_dim=D, atten_num_heads=4, kernel_size=8,
        causal=True, atten_left_context=8)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, T, D))
    paddings = jnp.zeros((B, T)).at[1, 18:].set(1.0)
    offline = layer.FProp(theta, x, paddings)
    streamed = _stream(layer, theta, x, paddings,
                       layer.InitStreamStates(B))
    np.testing.assert_allclose(np.asarray(offline), np.asarray(streamed),
                               atol=5e-5)

  def test_conformer_streaming_is_jittable(self):
    p = conformer_layer.ConformerLayer.Params().Set(
        name="conf", input_dim=D, atten_num_heads=2, kernel_size=4,
        causal=True, atten_left_context=4)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, CHUNK, D))
    paddings = jnp.zeros((B, CHUNK))
    states = layer.InitStreamStates(B)
    step = jax.jit(layer.StreamStep)
    out1, states = step(theta, x, paddings, states)
    out2, states = step(theta, x, paddings, states)
    assert out1.shape == (B, CHUNK, D)
    assert np.all(np.isfinite(np.asarray(out2)))
