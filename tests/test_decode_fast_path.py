"""Decode fast path: chunked prefill + length-aware paged flash decode.

Covers docs/decode_fast_path.md:
- chunked prefill writes the same KV cache as the per-token ExtendStep
  scan (layer-0 bitwise; deeper layers to float tolerance at live slots —
  the [C, S] context matmul blocks differently than C matvecs, and that
  ulp noise feeds the next layer's projections) and reproduces its logits
  at every real prompt position,
- the paged ExtendStep read (`decode_page_size`) matches the dense path,
- the flash-decode XLA twin matches a dense softmax reference and is
  bit-identical to the Pallas kernel in interpret mode (slow),
- decode-shape bucketing reuses one compiled program across ragged
  prompt widths without changing outputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import attention as attention_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.ops import flash_decode


def _TinyLm(use_repeat_layer=True, use_rotary=True, decode_page_size=0):
  from lingvo_tpu.models.lm import layers as lm_layers
  p = lm_layers.TransformerLm.Params().Set(
      name="lm", vocab_size=64, model_dim=32, num_layers=2, num_heads=2,
      hidden_dim=64, use_repeat_layer=use_repeat_layer, use_rotary=use_rotary)
  if decode_page_size:
    p.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
        decode_page_size=decode_page_size)
  task = p.Instantiate()
  task.FinalizePaths()
  return task


def _RaggedCachePaddings(p_len, total, lens):
  slot = jnp.arange(total)[None, :]
  return (slot < (p_len - lens)[:, None]).astype(jnp.float32)


class TestChunkedPrefill:

  @pytest.mark.parametrize("use_rotary", [True, False])
  @pytest.mark.parametrize("use_repeat_layer", [True, False])
  def test_prefill_matches_per_token_prime(self, use_rotary,
                                           use_repeat_layer):
    """One Prefill pass == P sequential ExtendSteps: same cache, same
    logits at real (non-left-pad) prompt positions, ragged lengths."""
    task = _TinyLm(use_repeat_layer=use_repeat_layer, use_rotary=use_rotary)
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    b, p_len, t_max = 2, 8, 4
    total = p_len + t_max
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, p_len), 1, 64)
    lens = jnp.asarray([p_len, 5])
    pad = _RaggedCachePaddings(p_len, total, lens)

    ext = jax.jit(lambda ids_t, states: task.ExtendStep(
        theta, ids_t, states, cache_paddings=pad))
    states = task.InitDecodeState(theta, b, total)
    step_logits = []
    for t in range(p_len):
      lt, states = ext(ids[:, t:t + 1], states)
      step_logits.append(lt)
    prime_logits = jnp.stack(step_logits, 1)

    states2 = task.InitDecodeState(theta, b, total)
    pre_logits, states2 = task.Prefill(theta, ids, states2,
                                       cache_paddings=pad)

    # K/V caches: layer 0 is bitwise identical (projections + rotary are
    # per-position); deeper layers inherit ulp noise from the previous
    # layer's batched-vs-per-token context matmul. Left-pad slots hold
    # path-dependent garbage (fully-masked rows see different unwritten
    # caches) and are excluded — they are masked from attention forever.
    live = (jnp.arange(total)[None, :] >= (p_len - lens)[:, None])
    live = live.astype(jnp.float32)[:, :, None, None]      # [B, S, 1, 1]
    flat1 = jax.tree_util.tree_leaves(states)
    flat2 = jax.tree_util.tree_leaves(states2)
    for a, b_ in zip(flat1, flat2):
      if a.ndim == 5:    # repeat-layer stacked leaf [L, B, S, N, H]
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b_[0]))
        np.testing.assert_allclose(np.asarray(a * live[None]),
                                   np.asarray(b_ * live[None]), atol=1e-4)
      elif a.ndim == 4:  # per-layer leaf [B, S, N, H]
        np.testing.assert_allclose(np.asarray(a * live),
                                   np.asarray(b_ * live), atol=1e-4)
      else:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))

    # logits at real positions match to float tolerance; greedy
    # continuations (what the driver emits) are identical
    valid = (jnp.arange(p_len)[None, :] >= (p_len - lens)[:, None])
    err = jnp.abs(prime_logits - pre_logits) * valid[:, :, None]
    assert float(jnp.max(err)) < 2e-5, float(jnp.max(err))
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(prime_logits[:, -1], -1)),
        np.asarray(jnp.argmax(pre_logits[:, -1], -1)))

  def test_multi_chunk_prefill_matches_single_pass(self):
    task = _TinyLm()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    b, p_len = 2, 8
    total = 12
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, p_len), 1, 64)
    states1 = task.InitDecodeState(theta, b, total)
    one, states1 = task.Prefill(theta, ids, states1)
    states2 = task.InitDecodeState(theta, b, total)
    la, states2 = task.Prefill(theta, ids[:, :5], states2)
    lb, states2 = task.Prefill(theta, ids[:, 5:], states2)
    two = jnp.concatenate([la, lb], axis=1)
    np.testing.assert_allclose(np.asarray(one), np.asarray(two), atol=2e-5)
    # time_step advanced to p_len (leaf is [L]-shaped under repeat-layer)
    assert np.all(np.asarray(jax.tree_util.tree_leaves(states2)[1]) == p_len)

  def test_live_len_trimmed_read_matches_full_cache_read(self):
    """live_len only removes exact-zero (masked) softmax contributions, so
    the trimmed attention read must match the full-cache read, and the
    written KV cache must be identical."""
    task = _TinyLm()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    b, p_len, total = 2, 6, 24
    ids = jax.random.randint(jax.random.PRNGKey(1), (b, p_len), 1, 64)
    full_states = task.InitDecodeState(theta, b, total)
    full, full_states = task.Prefill(theta, ids, full_states)
    trim_states = task.InitDecodeState(theta, b, total)
    la, trim_states = task.Prefill(theta, ids[:, :4], trim_states,
                                   live_len=4)
    lb, trim_states = task.Prefill(theta, ids[:, 4:], trim_states,
                                   live_len=p_len)
    trimmed = jnp.concatenate([la, lb], axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(trimmed),
                               atol=2e-5)
    for fl, tl in zip(jax.tree_util.tree_leaves(full_states),
                      jax.tree_util.tree_leaves(trim_states)):
      np.testing.assert_array_equal(np.asarray(fl), np.asarray(tl))

  def test_prefill_then_extend_matches_pure_extend_rollout(self):
    """End-to-end greedy: prefill + sampled ExtendSteps == all-ExtendStep."""
    task = _TinyLm()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    b, p_len, t_max = 2, 6, 5
    total = p_len + t_max
    ids = jax.random.randint(jax.random.PRNGKey(2), (b, p_len), 1, 64)

    ext = jax.jit(lambda ids_t, states: task.ExtendStep(theta, ids_t, states))

    def rollout(prime_fn):
      states = task.InitDecodeState(theta, b, total)
      logits, states = prime_fn(states)
      out = []
      for _ in range(t_max):
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        out.append(nxt)
        logits, states = ext(nxt[:, None], states)
      return np.stack([np.asarray(o) for o in out], 1)

    def legacy(states):
      logits = None
      for t in range(p_len):
        logits, states = ext(ids[:, t:t + 1], states)
      return logits, states

    def fast(states):
      logits, states = task.Prefill(theta, ids, states)
      return logits[:, -1, :], states

    np.testing.assert_array_equal(rollout(legacy), rollout(fast))


class TestPagedExtendStep:

  def _PrimedStates(self, task, theta, b, p_len, total):
    ids = jax.random.randint(jax.random.PRNGKey(3), (b, p_len), 1, 64)
    states = task.InitDecodeState(theta, b, total)
    logits, states = task.Prefill(theta, ids, states)
    return logits[:, -1, :], states

  def test_paged_matches_dense_extend_step(self):
    """decode_page_size > 0 reproduces the dense-cache read; page_size=0
    (default) IS the legacy branch, so existing decode tests pin it."""
    b, p_len, t_max = 2, 8, 8
    total = p_len + t_max  # 16 slots = 4 pages of 4
    dense = _TinyLm(decode_page_size=0)
    paged = _TinyLm(decode_page_size=4)
    theta = dense.InstantiateVariables(jax.random.PRNGKey(0))
    logits_d, st_d = self._PrimedStates(dense, theta, b, p_len, total)
    logits_p, st_p = self._PrimedStates(paged, theta, b, p_len, total)
    np.testing.assert_array_equal(np.asarray(logits_d), np.asarray(logits_p))
    ext_d = jax.jit(lambda i, s: dense.ExtendStep(theta, i, s))
    ext_p = jax.jit(lambda i, s: paged.ExtendStep(theta, i, s))
    for _ in range(t_max):
      nxt = jnp.argmax(logits_d, -1).astype(jnp.int32)
      logits_d, st_d = ext_d(nxt[:, None], st_d)
      logits_p, st_p = ext_p(nxt[:, None], st_p)
      np.testing.assert_allclose(np.asarray(logits_d), np.asarray(logits_p),
                                 atol=1e-5)
      np.testing.assert_array_equal(
          np.asarray(jnp.argmax(logits_d, -1)),
          np.asarray(jnp.argmax(logits_p, -1)))

  def test_non_divisible_max_len_falls_back_to_dense(self):
    # total=15 not divisible by page 4: eligibility gate must take the
    # dense branch rather than crash
    task = _TinyLm(decode_page_size=4)
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    states = task.InitDecodeState(theta, 2, 15)
    logits, states = task.ExtendStep(
        theta, jnp.ones((2, 1), jnp.int32), states)
    assert logits.shape == (2, 64)
    assert np.all(np.isfinite(np.asarray(logits)))


class TestFlashDecodeKernel:

  def _Inputs(self, b=2, s=32, n=2, h=16):
    q = jax.random.normal(jax.random.PRNGKey(0), (b, 1, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, s, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, s, n, h))
    pad = jnp.zeros((b, s)).at[0, :3].set(1.0)
    return q, k, v, pad

  @staticmethod
  def _DenseRef(q, k, v, t, pad):
    s_len = k.shape[1]
    s = jnp.einsum("BTNH,BSNH->BNTS", q, k).astype(jnp.float32)
    slot = jnp.arange(s_len)[None, None, None, :]
    mask = jnp.where(slot <= t, 0.0, -1e30) + pad[:, None, None, :] * -1e30
    p = jax.nn.softmax(jnp.maximum(s + mask, -1e30), -1)
    return jnp.einsum("BNTS,BSNH->BTNH", p, v)

  @pytest.mark.parametrize("t", [5, 8, 17, 31])
  def test_xla_twin_matches_dense_reference(self, t):
    q, k, v, pad = self._Inputs()
    out = flash_decode.FlashDecode(
        q, k, v, jnp.asarray(t, jnp.int32), page_size=8, cache_paddings=pad,
        lowering="xla")
    ref = self._DenseRef(q, k, v, t, pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)

  def test_xla_twin_full_cache_boundary(self):
    # out-of-contract t >= S must not re-read the clamped last page: the
    # live-page count is clamped to num_pages, so the answer equals dense
    # attention over every slot (what the Pallas grid computes).
    q, k, v, pad = self._Inputs()
    s = k.shape[1]
    for t in [s, s + 5]:
      out = flash_decode.FlashDecode(
          q, k, v, jnp.asarray(t, jnp.int32), page_size=8,
          cache_paddings=pad, lowering="xla")
      ref = self._DenseRef(q, k, v, t, pad)
      np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)

  def test_xla_twin_jits_with_dynamic_time_step(self):
    q, k, v, _ = self._Inputs()
    f = jax.jit(lambda t: flash_decode.FlashDecode(
        q, k, v, t, page_size=8, lowering="xla"))
    for t in [0, 9, 31]:
      out = f(jnp.asarray(t, jnp.int32))
      ref = self._DenseRef(q, k, v, t, jnp.zeros(k.shape[:2]))
      np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)

  @pytest.mark.slow
  def test_pallas_interpret_bitwise_equals_xla_twin(self):
    # one tiny shape: interpret mode costs ~8-10 ms per grid step on CPU
    q, k, v, pad = self._Inputs(b=1, s=16, n=1, h=8)
    for t in [0, 7, 8, 15]:
      ts = jnp.asarray(t, jnp.int32)
      out_x = flash_decode.FlashDecode(
          q, k, v, ts, page_size=8, cache_paddings=pad, lowering="xla")
      out_p = flash_decode.FlashDecode(
          q, k, v, ts, page_size=8, cache_paddings=pad, lowering="pallas",
          interpret=True)
      np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))


class TestDecodeBucketing:

  def test_round_up_to_bucket(self):
    buckets = (16, 32, 64)
    assert py_utils.RoundUpToBucket(1, buckets) == 16
    assert py_utils.RoundUpToBucket(16, buckets) == 16
    assert py_utils.RoundUpToBucket(17, buckets) == 32
    assert py_utils.RoundUpToBucket(64, buckets) == 64
    assert py_utils.RoundUpToBucket(65, buckets) == 65  # beyond: exact size
    with pytest.raises(ValueError):
      py_utils.RoundUpToBucket(-1, buckets)

  def test_ragged_prompt_widths_share_one_program(self, tmp_path):
    """Prompt widths 4 and 7 both bucket to 16: one compiled decode fn,
    continuations identical to exact-width programs."""
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    ckpt.Save(1, state, force=True)
    ckpt.Close()

    driver = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "a.jsonl"), max_decode_steps=4)
    r1 = driver.DecodeOnce(1, np.array([[5, 6, 7, 8]], np.int32),
                           np.array([4], np.int32))
    r2 = driver.DecodeOnce(1, np.array([[5, 6, 7, 8, 9, 10, 11]], np.int32),
                           np.array([7], np.int32))
    assert len(driver._decode_fns) == 1, driver._decode_fns.keys()

    exact = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "b.jsonl"), max_decode_steps=4,
        len_buckets=(4, 7))
    e1 = exact.DecodeOnce(1, np.array([[5, 6, 7, 8]], np.int32),
                          np.array([4], np.int32))
    e2 = exact.DecodeOnce(1, np.array([[5, 6, 7, 8, 9, 10, 11]], np.int32),
                          np.array([7], np.int32))
    assert len(exact._decode_fns) == 2
    assert r1[0]["output_ids"] == e1[0]["output_ids"]
    assert r2[0]["output_ids"] == e2[0]["output_ids"]

  def test_legacy_prime_flag_matches_fast_path(self, tmp_path):
    """use_legacy_prime=True (the old per-token scan) emits the same
    greedy continuations as chunked prefill."""
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    ckpt.Save(1, state, force=True)
    ckpt.Close()
    prompts = np.array([[5, 6, 7, 8], [9, 10, 0, 0]], np.int32)
    lens = np.array([4, 2], np.int32)

    fast = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "f.jsonl"), max_decode_steps=4)
    legacy = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "l.jsonl"), max_decode_steps=4,
        use_legacy_prime=True)
    rf = fast.DecodeOnce(1, prompts, lens)
    rl = legacy.DecodeOnce(1, prompts, lens)
    for a, b in zip(rf, rl):
      assert a["output_ids"] == b["output_ids"]
