"""LM task tests: forward shapes, loss masking, decode, overfit sanity."""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu import model_registry
from lingvo_tpu.core.nested_map import NestedMap


def _tiny_task():
  import lingvo_tpu.models.all_params  # noqa: F401
  mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                "Train")
  mp.task.input = mp.input
  return mp.task.Instantiate(), mp.input.Instantiate()


class TestTransformerLm:

  def test_fprop_shapes_and_metrics(self):
    task, gen = _tiny_task()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    metrics, per_example = task.EvalStep(theta, batch)
    assert metrics.loss[0].shape == ()
    assert float(metrics.loss[0]) > 0
    assert "fraction_of_correct_next_step_preds" in metrics
    assert per_example.xent.shape == batch.ids.shape

  def test_padded_positions_excluded_from_loss(self):
    task, gen = _tiny_task()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    m1, _ = task.EvalStep(theta, batch)
    # pad out the second half; garbage the ids there
    b, t = batch.ids.shape
    batch2 = batch.DeepCopy()
    batch2.paddings = batch.paddings.at[:, t // 2:].set(1.0)
    batch2.ids = batch.ids.at[:, t // 2:].set(1)
    m2a, _ = task.EvalStep(theta, batch2)
    batch3 = batch2.DeepCopy()
    batch3.ids = batch2.ids.at[:, t // 2:].set(7)
    m2b, _ = task.EvalStep(theta, batch3)
    # loss identical regardless of padded-content (causal: padded ids only
    # influence padded positions' predictions, which are excluded)
    np.testing.assert_allclose(
        float(m2a.loss[0]), float(m2b.loss[0]), rtol=1e-5)
    assert float(m2a.loss[1]) < float(m1.loss[1])  # fewer weight tokens

  def test_train_overfits_single_batch(self):
    task, gen = _tiny_task()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    step = jax.jit(task.TrainStep)
    first = None
    for i in range(120):
      state, out = step(state, batch)
      if first is None:
        first = float(out.metrics.loss[0])
    final = float(out.metrics.loss[0])
    assert final < 0.8 * first, (first, final)

  def test_extend_step_decode_matches_fprop_logits(self):
    task, gen = _tiny_task()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    # unpacked batch for decode comparison
    ids = batch.ids[:, :16]
    full_batch = NestedMap(
        ids=ids, labels=batch.labels[:, :16],
        paddings=jnp.zeros_like(batch.paddings[:, :16]))
    import lingvo_tpu.core.py_utils as py_utils
    with py_utils.EvalContext():
      preds = task.ComputePredictions(theta, full_batch)
      states = task.InitDecodeState(theta, ids.shape[0], 16)
      logits_steps = []
      for t in range(16):
        logits_t, states = task.ExtendStep(theta, ids[:, t:t + 1], states)
        logits_steps.append(logits_t)
    streaming = jnp.stack(logits_steps, axis=1)
    np.testing.assert_allclose(
        np.asarray(preds.logits), np.asarray(streaming), atol=3e-3)

  def test_packed_vs_unpacked_segments(self):
    """Packed batch of 2 segments == 2 separate unpacked rows."""
    task, gen = _tiny_task()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    t = 16
    rng = np.random.RandomState(0)
    seq_a = rng.randint(1, 100, t + 1)
    seq_b = rng.randint(1, 100, t + 1)
    packed = NestedMap(
        ids=jnp.asarray(np.concatenate([seq_a[:-1], seq_b[:-1]])[None]),
        labels=jnp.asarray(np.concatenate([seq_a[1:], seq_b[1:]])[None]),
        paddings=jnp.zeros((1, 2 * t)),
        segment_ids=jnp.asarray(
            np.concatenate([np.ones(t), 2 * np.ones(t)])[None].astype("int32")),
        segment_pos=jnp.asarray(
            np.concatenate([np.arange(t), np.arange(t)])[None].astype("int32")))
    unpacked = NestedMap(
        ids=jnp.asarray(np.stack([seq_a[:-1], seq_b[:-1]])),
        labels=jnp.asarray(np.stack([seq_a[1:], seq_b[1:]])),
        paddings=jnp.zeros((2, t)))
    m_packed, _ = task.EvalStep(theta, packed)
    m_unpacked, _ = task.EvalStep(theta, unpacked)
    np.testing.assert_allclose(
        float(m_packed.loss[0]), float(m_unpacked.loss[0]), rtol=2e-3)
