"""Unified observability layer: metrics registry, per-request traces,
profiler/compile hooks, and the shared telemetry schema.

Covers docs/observability.md:
- `MetricsRegistry` kinds (counter / gauge / lazy gauge / section /
  histogram), atomic flat snapshots, monotonic-delta semantics, callback
  replacement, and error isolation (a broken stats provider never kills a
  snapshot),
- `TraceRecorder` lifecycle ordering (submit < admit < first token <
  retire) with an injected deterministic clock, ring wraparound WITHOUT
  open-request loss, derived per-request metrics (queue wait, TTFT,
  per-output-token latency, spec acceptance),
- `ChromeTrace()` export is valid Chrome trace-event JSON: round-trips
  through json, timestamps are monotonic in file order, and every
  duration B has its matching E on the same tid in stack order,
- `ProfileWindow` degrades to a no-op (never raises) when the profiler
  is unavailable; `CompileLog` AOT-compiles once, dispatches through the
  stored executable, and permanently falls back on non-jit callables,
- the shared schema validates both serving surfaces and round-trips
  telemetry through a registry,
- `tools/trace_report.py` summarizes an exported trace,
- a seeded Poisson soak on a live tiny engine leaves a COMPLETE trace for
  every request, schema-valid Stats(), compile records, and correct
  registry deltas (slow).
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import observe
from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.observe import trace as trace_lib

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import trace_report  # noqa: E402


# -- metrics registry --------------------------------------------------------


class TestMetricsRegistry:

  def test_counter_monotonic_and_get_or_create(self):
    reg = observe.MetricsRegistry("t")
    c = reg.Counter("serving/steps")
    c.Inc()
    c.Inc(4)
    assert c.value == 5
    # get-or-create: same name is the same object
    assert reg.Counter("serving/steps") is c
    with pytest.raises(AssertionError):
      c.Inc(-1)

  def test_gauge_and_lazy_gauge_replacement(self):
    reg = observe.MetricsRegistry("t")
    reg.Gauge("serving/kv_cache_dtype").Set("int8")
    box = {"v": 1}
    reg.GaugeFn("lazy", lambda: box["v"])
    assert reg.Snapshot()["lazy"] == 1
    box["v"] = 7
    assert reg.Snapshot()["lazy"] == 7          # evaluated at snapshot time
    reg.GaugeFn("lazy", lambda: 42)             # re-register REPLACES
    snap = reg.Snapshot()
    assert snap["lazy"] == 42
    assert snap["serving/kv_cache_dtype"] == "int8"

  def test_section_fn_splices_and_replaces(self):
    reg = observe.MetricsRegistry("t")
    reg.SectionFn("scheduler", lambda: {"queue_depth": 3, "slots": 2})
    snap = reg.Snapshot()
    assert snap["scheduler/queue_depth"] == 3 and snap["scheduler/slots"] == 2
    reg.SectionFn("scheduler", lambda: {"queue_depth": 0})
    snap = reg.Snapshot()
    assert snap["scheduler/queue_depth"] == 0
    assert "scheduler/slots" not in snap

  def test_callback_error_isolation(self):
    reg = observe.MetricsRegistry("t")
    reg.Counter("ok").Inc()

    def _Boom():
      raise RuntimeError("provider died")

    reg.GaugeFn("bad_gauge", _Boom)
    reg.SectionFn("bad_section", _Boom)
    snap = reg.Snapshot()                       # must not raise
    assert snap["ok"] == 1
    assert "provider died" in snap["bad_gauge"]
    assert "provider died" in snap["bad_section"]

  def test_histogram_buckets_and_snapshot_form(self):
    reg = observe.MetricsRegistry("t")
    h = reg.Histogram("lat", bounds=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
      h.Observe(v)
    snap = reg.Snapshot()["lat"]
    assert snap["count"] == 5
    assert snap["counts"] == [1, 2, 1, 1]       # last bucket = overflow
    assert snap["bounds"] == [0.01, 0.1, 1.0]
    np.testing.assert_allclose(snap["sum"], 5.605)
    np.testing.assert_allclose(snap["mean"], 5.605 / 5)

  def test_delta_semantics(self):
    reg = observe.MetricsRegistry("t")
    c = reg.Counter("serving/tokens")
    g = reg.Gauge("level")
    h = reg.Histogram("lat", bounds=(1.0,))
    c.Inc(10)
    g.Set(100)
    h.Observe(0.5)
    prev = reg.Snapshot()
    c.Inc(7)
    g.Set(3)
    h.Observe(2.0)
    d = reg.Delta(prev)
    assert d["serving/tokens"] == 7             # counters subtract
    assert d["level"] == 3                      # gauges report current level
    assert d["lat"]["count"] == 1               # histograms subtract
    assert d["lat"]["counts"] == [0, 1]
    np.testing.assert_allclose(d["lat"]["sum"], 2.0)
    # a metric born after `prev` reports its full value
    reg.Counter("new").Inc(5)
    assert reg.Delta(prev)["new"] == 5

  def test_describe_kinds(self):
    reg = observe.MetricsRegistry("t")
    reg.Counter("c")
    reg.Gauge("g")
    reg.GaugeFn("gf", lambda: 0)
    reg.SectionFn("s", dict)
    reg.Histogram("h")
    assert reg.Describe() == {"c": "counter", "g": "gauge", "gf": "gauge_fn",
                              "s": "section", "h": "histogram"}


# -- trace recorder (deterministic injected clock) ---------------------------


class _FakeClock:
  """Monotonic fake clock: each call advances by `step` seconds."""

  def __init__(self, step=0.001):
    self.now = 0.0
    self.step = step

  def __call__(self):
    self.now += self.step
    return self.now


def _ScriptedLifecycle(rec, req_id=1, tokens=4):
  rec.Submit(req_id, prompt_tokens=5, max_new=tokens)
  rec.Admit(req_id, slot=0, pages=2)
  rec.PrefillChunk(req_id, 4)
  rec.PrefillChunk(req_id, 1)
  for _ in range(tokens):
    rec.Token(req_id)
  rec.Retire(req_id, "length", pages_freed=2)


class TestTraceRecorder:

  def test_lifecycle_ordering_and_derived_metrics(self):
    clock = _FakeClock(step=0.001)
    rec = trace_lib.TraceRecorder(clock=clock)
    _ScriptedLifecycle(rec, req_id=7, tokens=4)
    r = rec.Get(7)
    assert r.complete
    # the ordering satellite: submit < admit < first token < retire
    assert r.submit_ts < r.admit_ts < r.first_token_ts < r.retire_ts
    m = r.Metrics()
    # fake clock ticks 1ms/event: submit@t1, admit@t2, chunks@t3,t4,
    # tokens@t5..t8, retire@t9
    np.testing.assert_allclose(m["queue_wait_s"], 0.001)
    np.testing.assert_allclose(m["ttft_s"], 0.004)
    np.testing.assert_allclose(m["tpot_s"], 0.001)  # (t8 - t5) / 3
    np.testing.assert_allclose(m["total_s"], 0.008)
    assert m["tokens"] == 4 and m["prompt_tokens"] == 5
    assert m["prefill_chunks"] == 2 and m["pages"] == 2
    assert m["finish_reason"] == "length"
    assert "spec_cycles" not in m               # no spec fields w/o drafting

  def test_spec_fields_and_acceptance(self):
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    rec.Submit(1, 2, 8)
    rec.Admit(1, 0, 1)
    rec.SpecVerify(1, drafted=4, accepted=3)
    rec.Token(1, n=4)                           # 3 accepted + 1 corrected
    rec.Rollback(1, 1)
    rec.SpecVerify(1, drafted=4, accepted=4)
    rec.Token(1, n=4)
    rec.Retire(1, "eos")
    m = rec.Get(1).Metrics()
    assert m["tokens"] == 8
    assert m["spec_cycles"] == 2 and m["draft_tokens"] == 8
    assert m["accepted_tokens"] == 7 and m["rolled_back_tokens"] == 1
    np.testing.assert_allclose(m["spec_acceptance"], 7 / 8)

  def test_ring_wraparound_keeps_open_requests(self):
    """The wraparound satellite: a tiny ring drops raw events, but the
    open request's record survives untouched."""
    rec = trace_lib.TraceRecorder(capacity=8, clock=_FakeClock())
    rec.Submit(1, 3, 1000)
    rec.Admit(1, 0, 4)
    for _ in range(500):
      rec.Token(1)
    stats = rec.Stats()
    assert stats["events_buffered"] == 8
    assert stats["events_dropped"] == 502 - 8
    assert stats["requests_open"] == 1
    r = rec.Get(1)                              # record survived the ring
    assert r.submit_ts is not None and r.admit_ts is not None
    assert r.tokens == 500 and r.prompt_tokens == 3
    rec.Retire(1, "length", 4)
    assert rec.Get(1).complete
    assert rec.Stats()["requests_completed"] == 1
    assert rec.Stats()["requests_open"] == 0

  def test_completed_ring_evicts_oldest_only(self):
    rec = trace_lib.TraceRecorder(completed_capacity=2, clock=_FakeClock())
    for rid in (1, 2, 3):
      rec.Submit(rid, 1, 1)
      rec.Retire(rid, "eos")
    reqs = rec.Requests()
    assert set(reqs) == {2, 3}                  # 1 evicted (oldest)

  def test_events_for_retired_request_keep_raw_only(self):
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    rec.Token(99)                               # never submitted
    assert rec.Get(99) is None
    assert rec.Events()[-1][1] == "token"       # raw event still in ring

  def test_trace_stats_schema(self):
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    assert set(rec.Stats()) == observe_schema.TRACE_STATS_KEYS


def _CheckChromeTrace(trace):
  """Shared validity checks: json round-trip, monotonic ts in file order,
  matched B/E pairs per tid in stack order."""
  trace = json.loads(json.dumps(trace))         # must round-trip
  events = trace["traceEvents"]
  assert events, "empty trace"
  last_ts = -float("inf")
  stacks = {}
  for e in events:
    assert e["ph"] in ("M", "B", "E", "i"), e
    if e["ph"] == "M":
      continue
    assert e["ts"] >= last_ts, f"ts went backwards at {e}"
    last_ts = e["ts"]
    if e["ph"] == "B":
      stacks.setdefault(e["tid"], []).append(e["name"])
    elif e["ph"] == "E":
      stack = stacks.get(e["tid"])
      assert stack, f"E without B on tid {e['tid']}: {e}"
      stack.pop()
  for tid, stack in stacks.items():
    assert not stack, f"unclosed B events on tid {tid}: {stack}"
  return trace


class TestChromeTraceExport:

  def test_valid_json_monotonic_matched_pairs(self, tmp_path):
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    _ScriptedLifecycle(rec, req_id=1)
    _ScriptedLifecycle(rec, req_id=2)
    path = str(tmp_path / "trace.json")
    exported = rec.Export(path)
    with open(path) as f:
      trace = json.load(f)                      # file itself parses
    assert trace == json.loads(json.dumps(exported))
    trace = _CheckChromeTrace(trace)
    names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "B"]
    for rid in (1, 2):
      for phase in ("queued", "prefill", "decode"):
        assert f"req {rid} {phase}" in names
    assert set(trace["perRequest"]) == {"1", "2"}
    assert trace["perRequest"]["1"]["total_s"] is not None

  def test_open_request_emits_no_unmatched_b(self):
    """A still-running request has no decode E yet — the exporter must
    skip the open phase rather than write an unmatched B."""
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    rec.Submit(1, 2, 8)
    rec.Admit(1, 0, 1)
    rec.Token(1)                                # decode started, not done
    trace = _CheckChromeTrace(rec.ChromeTrace())
    names = [e["name"] for e in trace["traceEvents"] if e["ph"] == "B"]
    assert "req 1 queued" in names and "req 1 prefill" in names
    assert "req 1 decode" not in names          # open phase skipped
    assert trace["perRequest"]["1"]["total_s"] is None

  def test_cancelled_while_queued_lands_on_queue_row(self):
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    rec.Submit(1, 2, 8)
    rec.Retire(1, "cancelled")
    trace = _CheckChromeTrace(rec.ChromeTrace())
    queued = [e for e in trace["traceEvents"]
              if e["ph"] == "B" and e["name"] == "req 1 queued"]
    assert queued and queued[0]["tid"] == trace_lib._QUEUE_ONLY_TID

  def test_spec_instants_present(self):
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    rec.Submit(1, 2, 8)
    rec.Admit(1, 3, 1)
    rec.SpecVerify(1, 4, 2)
    rec.Rollback(1, 2)
    trace = _CheckChromeTrace(rec.ChromeTrace())
    instants = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "i"}
    assert instants["spec_verify req 1"]["args"] == {"drafted": 4,
                                                     "accepted": 2}
    assert instants["rollback req 1"]["args"] == {"tokens": 2}
    assert instants["spec_verify req 1"]["tid"] == 3


# -- profiler window + compile log -------------------------------------------


class TestProfileWindow:

  def test_degrades_to_noop_when_profiler_broken(self, monkeypatch):
    def _Boom(*a, **k):
      raise RuntimeError("no profiler here")

    monkeypatch.setattr(jax.profiler, "start_trace", _Boom)
    w = observe.ProfileWindow("/nonexistent", steps=3)
    w.Start()                                   # must not raise
    assert not w.active
    assert "no profiler here" in w.error
    assert w.StepDone() is True                 # errored window closes fast
    with observe.ProfileWindow("/nonexistent") as w2:  # ctx mgr too
      assert not w2.active

  def test_step_window_counts_down(self, tmp_path, monkeypatch):
    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: calls.__setitem__(
                            "start", calls["start"] + 1))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.__setitem__("stop", calls["stop"] + 1))
    w = observe.ProfileWindow(str(tmp_path), steps=2)
    w.Start()
    w.Start()                                   # idempotent
    assert calls["start"] == 1 and w.active
    assert w.StepDone() is False
    assert w.StepDone() is True                 # window closed at N steps
    assert calls["stop"] == 1 and not w.active
    w.Stop()                                    # idempotent
    assert calls["stop"] == 1


class TestCompileLog:

  def test_jit_fn_compiles_once_and_dispatches(self):
    reg = observe.MetricsRegistry("t")
    log = observe.CompileLog(registry=reg, namespace="compile")
    traces = {"n": 0}

    @jax.jit
    def f(x):
      traces["n"] += 1
      return x * 2

    x = jnp.arange(4, dtype=jnp.float32)
    for _ in range(3):
      np.testing.assert_array_equal(np.asarray(log.Call("f", f, x)),
                                    np.asarray(x) * 2)
    rec = log.Records()["f"]
    assert traces["n"] == 1                     # AOT-compiled exactly once
    assert rec["calls"] == 3
    assert rec["compile_wall_s"] > 0
    assert "fallback" not in rec
    snap = reg.Snapshot()
    assert snap["compile/f_compile_wall_s"] == rec["compile_wall_s"]

  def test_non_jit_fn_falls_back_forever(self):
    log = observe.CompileLog()
    assert log.Call("plain", lambda x: x + 1, 41) == 42
    assert log.Call("plain", lambda x: x + 1, 1) == 2
    rec = log.Records()["plain"]
    assert rec["fallback"] == "not a jit wrapper (no .lower)"

  def test_dispatch_aval_mismatch_falls_back(self):
    log = observe.CompileLog()

    @jax.jit
    def f(x):
      return x + 1

    x32 = jnp.arange(4, dtype=jnp.float32)
    log.Call("f", f, x32)                       # compiled for f32[4]
    out = log.Call("f", f, jnp.arange(8, dtype=jnp.int32))  # wrong aval
    np.testing.assert_array_equal(np.asarray(out), np.arange(8) + 1)
    assert log.Records()["f"]["fallback"].startswith("dispatch:")
    # permanent: subsequent calls take the plain path and still work
    np.testing.assert_array_equal(np.asarray(log.Call("f", f, x32)),
                                  np.asarray(x32) + 1)


# -- shared schema -----------------------------------------------------------


class TestSchema:

  def _Telemetry(self, **overrides):
    vals = {k: 0 for k in observe_schema.GSHARD_TELEMETRY_KEYS}
    vals.update(kv_cache_dtype="float32", serve_int8_weights=False,
                accepted_len_hist=[])
    vals.update(overrides)
    return vals

  def test_telemetry_exact_key_set_enforced(self):
    telem = observe_schema.GShardTelemetry(**self._Telemetry())
    assert list(telem) == list(observe_schema.GSHARD_TELEMETRY_KEYS)
    with pytest.raises(AssertionError, match="missing"):
      vals = self._Telemetry()
      del vals["prefill_s"]
      observe_schema.GShardTelemetry(**vals)
    with pytest.raises(AssertionError, match="not in schema"):
      observe_schema.GShardTelemetry(**self._Telemetry(bogus=1))

  def test_publish_then_read_back_round_trips(self):
    reg = observe.MetricsRegistry("t")
    telem = observe_schema.GShardTelemetry(
        **self._Telemetry(tokens_per_sec=123.0, kv_cache_dtype="int8"))
    observe_schema.PublishTelemetry(reg, telem)
    back = observe_schema.TelemetryFromRegistry(reg)
    assert back == telem                        # registry is source of truth
    assert reg.Snapshot()["serving/tokens_per_sec"] == 123.0

  def test_validate_engine_stats_rejects_drift(self):
    good = {k: 0 for k in observe_schema.ENGINE_STATS_REQUIRED}
    # sections with validated inner key sets need real shapes
    good["prefix_cache"] = observe_schema.DisabledPrefixCacheStats()
    good["kv_pages"] = {k: 0 for k in observe_schema.KV_PAGES_REQUIRED}
    observe_schema.ValidateEngineStats(good)
    observe_schema.ValidateEngineStats({**good, "trace": {}})  # optional ok
    with pytest.raises(AssertionError, match="missing"):
      observe_schema.ValidateEngineStats(
          {k: v for k, v in list(good.items())[1:]})
    with pytest.raises(AssertionError, match="not in schema"):
      observe_schema.ValidateEngineStats({**good, "renegade_key": 1})
    # inner-section drift is a failure too, not just top-level drift
    with pytest.raises(AssertionError, match="prefix_cache"):
      observe_schema.ValidateEngineStats(
          {**good, "prefix_cache": {**good["prefix_cache"], "bogus": 1}})
    with pytest.raises(AssertionError, match="kv_pages"):
      observe_schema.ValidateEngineStats({**good, "kv_pages": {}})


# -- trace_report tool -------------------------------------------------------


class TestTraceReport:

  def _Exported(self, tmp_path):
    rec = trace_lib.TraceRecorder(clock=_FakeClock())
    _ScriptedLifecycle(rec, req_id=1)
    _ScriptedLifecycle(rec, req_id=2, tokens=3)
    path = str(tmp_path / "trace.json")
    rec.Export(path)
    return path

  def test_summary_and_report(self, tmp_path):
    path = self._Exported(tmp_path)
    trace = trace_report.LoadTrace(path)
    s = trace_report.Summary(trace)
    assert s["requests"] == 2 and s["complete"] == 2
    assert s["tokens"] == 7
    assert s["ttft"]["n"] == 2 and s["ttft"]["p50_ms"] > 0
    assert s["queue_wait_hist_ms"]
    report = trace_report.Report(trace)
    assert "ttft_ms" in report and "queue wait histogram" in report
    assert trace_report.main([path]) == 0

  def test_rejects_foreign_trace(self, tmp_path):
    path = str(tmp_path / "foreign.json")
    with open(path, "w") as f:
      json.dump({"traceEvents": []}, f)
    with pytest.raises(ValueError, match="perRequest"):
      trace_report.LoadTrace(path)
    assert trace_report.main([]) == 2


# -- live engine soak (seeded Poisson arrivals) ------------------------------


def _TinyLmParams():
  from lingvo_tpu.models.lm import layers as lm_layers
  return lm_layers.TransformerLm.Params().Set(
      name="lm", vocab_size=64, model_dim=32, num_layers=2, num_heads=2,
      hidden_dim=64, use_rotary=True)


@pytest.fixture(scope="module")
def tiny_lm():
  task = _TinyLmParams().Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  return task, theta


@pytest.mark.slow
class TestEngineObservabilitySoak:

  def test_poisson_soak_complete_traces_for_every_request(self, tiny_lm,
                                                          tmp_path):
    from lingvo_tpu.serving import engine as engine_lib
    task, theta = tiny_lm
    eng = engine_lib.ServingLoop(
        task, theta, page_size=4, num_pages=32, max_batch=3,
        max_seq_len=32, prefill_chunk=4, default_max_new=4)
    rng = np.random.RandomState(0)
    eng.Start()
    try:
      # warmup compiles outside the measured/validated window
      eng.Submit([1, 2, 3], 2).Result(timeout=600)
      prev = eng.metrics.Snapshot()
      handles = []
      for _ in range(10):
        plen = int(rng.randint(2, 8))
        max_new = int(rng.randint(2, 8))
        prompt = rng.randint(1, 63, size=plen).tolist()
        handles.append(eng.Submit(prompt, max_new))
        time.sleep(float(rng.exponential(0.003)))
      results = [h.Result(timeout=600) for h in handles]
    finally:
      eng.Stop()

    # requests may finish early on eos, so count what actually streamed
    streamed = sum(len(r) for r in results)
    assert all(results)

    stats = observe_schema.ValidateEngineStats(eng.Stats())
    assert stats["tokens_emitted"] >= streamed
    assert set(stats["scheduler"]) == observe_schema.SCHEDULER_STATS_KEYS
    assert observe_schema.KV_PAGES_REQUIRED <= set(stats["kv_pages"])
    assert set(stats["trace"]) == observe_schema.TRACE_STATS_KEYS

    # the soak satellite: a COMPLETE lifecycle trace for every request
    reqs = eng.trace.Requests()
    assert len(reqs) == 11                      # warmup + 10 soak requests
    for rid in [h.id for h in handles]:
      r = reqs[rid]
      assert r.complete, f"request {rid} has an incomplete trace"
      assert r.submit_ts < r.admit_ts < r.first_token_ts <= r.retire_ts
      assert r.tokens == len(results[rid - 2])  # req ids start after warmup
      assert r.finish_reason in ("length", "eos")
      assert r.prefill_chunks >= 1
    assert eng.trace.Stats()["requests_open"] == 0

    # compile records: THE unified step program ran through the AOT path
    # — and it is the only step program this engine ever compiled
    assert stats["compile"]["ragged"]["calls"] > 0
    assert "fallback" not in stats["compile"]["ragged"]
    assert stats["compile"]["step_programs"] == 1

    # registry delta over the soak window matches the streamed tokens
    delta = eng.metrics.Delta(prev)
    assert delta["serving/tokens_emitted"] == streamed
    assert delta["serving/ttft_s"]["count"] == 10
    assert delta["serving/queue_wait_s"]["count"] == 10

    # exported trace: valid Chrome JSON, consumable by trace_report
    path = str(tmp_path / "soak_trace.json")
    _CheckChromeTrace(eng.trace.Export(path))
    s = trace_report.Summary(trace_report.LoadTrace(path))
    assert s["requests"] == 11 and s["complete"] == 11
    assert s["ttft"]["n"] == 11
