"""Tests for NestedMap, Params, py_utils, BaseLayer, registry.

Mirrors the coverage intent of the reference's `hyperparams_test.py`,
`nested_map` tests and `base_layer_test.py` (serialize/parse round-trip,
copy/freeze semantics, deterministic seeds).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import base_layer, hyperparams, py_utils
from lingvo_tpu.core.nested_map import NestedMap


class TestNestedMap:

  def test_attr_access(self):
    m = NestedMap(a=1)
    m.b = NestedMap(c=2)
    assert m.a == 1 and m.b.c == 2
    del m.a
    assert "a" not in m

  def test_reserved_key_rejected(self):
    with pytest.raises(ValueError):
      NestedMap(Flatten=1)
    with pytest.raises(ValueError):
      NestedMap(items=1)

  def test_pytree_roundtrip(self):
    m = NestedMap(b=jnp.ones(2), a=NestedMap(x=jnp.zeros(3)), c=[1, 2])
    leaves, treedef = jax.tree_util.tree_flatten(m)
    m2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert isinstance(m2, NestedMap) and isinstance(m2.a, NestedMap)
    assert m.IsCompatible(m2)

  def test_flatten_sorted_order(self):
    m = NestedMap(b=2, a=1, c=3)
    assert m.Flatten() == [1, 2, 3]
    assert [k for k, _ in m.FlattenItems()] == ["a", "b", "c"]

  def test_pack(self):
    m = NestedMap(a=1, b=NestedMap(c=2, d=[3, 4]))
    packed = m.Pack([10, 20, 30, 40])
    assert packed.a == 10 and packed.b.c == 20 and packed.b.d == [30, 40]

  def test_transform_filter(self):
    m = NestedMap(a=1, b=NestedMap(c=2, d=3))
    doubled = m.Transform(lambda x: x * 2)
    assert doubled.b.d == 6
    kept = m.FilterKeyVal(lambda k, v: v > 1)
    assert "a" not in kept and kept.b.c == 2

  def test_get_set_dotted(self):
    m = NestedMap()
    m.Set("a.b.c", 5)
    assert m.Get("a.b.c") == 5
    assert m.Get("a.b.missing", 42) == 42

  def test_jit_through(self):
    m = NestedMap(x=jnp.ones(3), y=jnp.full(3, 2.0))

    @jax.jit
    def f(nm):
      return NestedMap(z=nm.x + nm.y)

    np.testing.assert_allclose(f(m).z, 3.0)


class TestParams:

  def _MakeParams(self):
    p = hyperparams.Params()
    p.Define("alpha", 1.0, "A float.")
    p.Define("name", "foo", "A string.")
    sub = hyperparams.Params()
    sub.Define("beta", [1, 2], "A list.")
    p.Define("sub", sub, "Nested.")
    return p

  def test_define_get_set(self):
    p = self._MakeParams()
    assert p.alpha == 1.0
    p.alpha = 2.0
    p.Set(sub__beta=[3])
    assert p.alpha == 2.0 and p.sub.beta == [3]

  def test_unknown_param_raises(self):
    p = self._MakeParams()
    with pytest.raises(AttributeError):
      p.gamma = 1
    with pytest.raises(AttributeError):
      _ = p.gamma
    with pytest.raises(AttributeError):
      p.Define("alpha", 2, "dup")

  def test_copy_is_deep(self):
    p = self._MakeParams()
    q = p.Copy()
    q.sub.beta.append(99)
    assert p.sub.beta == [1, 2]
    assert p == self._MakeParams()
    assert q != p

  def test_freeze(self):
    p = self._MakeParams().Freeze()
    with pytest.raises(TypeError):
      p.alpha = 3
    with pytest.raises(TypeError):
      p.sub.beta = []

  def test_text_roundtrip(self):
    p = self._MakeParams()
    p.alpha = 3.5
    p.sub.beta = [7, 8]
    text = p.ToText()
    q = self._MakeParams().FromText(text)
    assert q.alpha == 3.5 and q.sub.beta == [7, 8] and q.name == "foo"
    assert q == p

  def test_instantiable(self):

    class Thing:

      @classmethod
      def Params(cls):
        p = hyperparams.InstantiableParams(cls)
        p.Define("x", 5, "")
        return p

      def __init__(self, p):
        self.x = p.x

    p = Thing.Params()
    p.x = 9
    assert p.Instantiate().x == 9
    assert "cls : type/" in p.ToText()
    q = p.Copy()
    assert q.cls is Thing and q.x == 9


class TestPyUtils:

  def test_seed_stability(self):
    s1 = py_utils.GenerateSeedFromName("model/layer/w")
    s2 = py_utils.GenerateSeedFromName("model/layer/w")
    s3 = py_utils.GenerateSeedFromName("model/layer/b")
    assert s1 == s2 and s1 != s3

  def test_init_methods(self):
    key = jax.random.PRNGKey(0)
    for method in ("gaussian", "uniform", "xavier", "constant",
                   "gaussian_sqrt_dim", "uniform_sqrt_dim",
                   "truncated_gaussian", "gaussian_sqrt_fanin",
                   "truncated_gaussian_sqrt_fanin", "uniform_unit_scaling"):
      wp = py_utils.WeightParams(
          shape=(4, 8), init=py_utils.WeightInit(method, 0.5))
      w = py_utils.InitWeight(key, wp)
      assert w.shape == (4, 8)
      assert bool(jnp.all(jnp.isfinite(w)))
    const = py_utils.InitWeight(
        key, py_utils.WeightParams((3,), py_utils.WeightInit.Constant(2.0)))
    np.testing.assert_allclose(const, 2.0)

  def test_paddings(self):
    lengths = jnp.array([2, 4])
    pad = py_utils.PaddingsFromLengths(lengths, 4)
    np.testing.assert_allclose(pad, [[0, 0, 1, 1], [0, 0, 0, 0]])
    np.testing.assert_array_equal(py_utils.LengthsFromPaddings(pad), [2, 4])
    x = jnp.ones((2, 4, 3))
    masked = py_utils.ApplyPadding(pad, x)
    assert float(masked[0, 3, 0]) == 0.0 and float(masked[1, 3, 0]) == 1.0

  def test_has_shape(self):
    x = jnp.zeros((2, 3))
    py_utils.HasShape(x, (2, 3))
    py_utils.HasShape(x, (-1, 3))
    with pytest.raises(ValueError):
      py_utils.HasShape(x, (3, 2))

  def test_global_norm_finite(self):
    tree = NestedMap(a=jnp.ones(4), b=NestedMap(c=2 * jnp.ones(3)))
    np.testing.assert_allclose(py_utils.GlobalNorm(tree), np.sqrt(4 + 12))
    assert bool(py_utils.IsFinite(tree))
    tree.a = jnp.array([1.0, np.nan, 1.0, 1.0])
    assert not bool(py_utils.IsFinite(tree))


class _Linear(base_layer.BaseLayer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "")
    p.Define("output_dim", 0, "")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateVariable(
        "w",
        py_utils.WeightParams(
            shape=(p.input_dim, p.output_dim), init=p.params_init,
            dtype=p.dtype))
    self.CreateVariable(
        "b",
        py_utils.WeightParams(
            shape=(p.output_dim,), init=py_utils.WeightInit.Constant(0.0),
            dtype=p.dtype))

  def FProp(self, theta, x):
    theta = self.CastTheta(theta)
    return jnp.dot(self.ToFPropDtype(x), theta.w) + theta.b


class _MLP(base_layer.BaseLayer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("dims", [], "")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    layers = []
    for i in range(len(p.dims) - 1):
      layers.append(_Linear.Params().Set(
          input_dim=p.dims[i], output_dim=p.dims[i + 1]))
    self.CreateChildren("fc", layers)

  def FProp(self, theta, x):
    for i, layer in enumerate(self.fc):
      x = layer.FProp(theta.fc[i], x)
      x = jax.nn.relu(x)
    return x


class TestBaseLayer:

  def test_variable_creation_and_fprop(self):
    p = _MLP.Params().Set(name="mlp", dims=[4, 8, 2])
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(jax.random.PRNGKey(0))
    assert theta.fc[0].w.shape == (4, 8)
    assert theta.fc[1].w.shape == (8, 2)
    out = layer.FProp(theta, jnp.ones((3, 4)))
    assert out.shape == (3, 2)

  def test_deterministic_init(self):
    p = _MLP.Params().Set(name="mlp", dims=[4, 8, 2])
    l1, l2 = p.Instantiate(), p.Instantiate()
    t1 = l1.InstantiateVariables(jax.random.PRNGKey(7))
    t2 = l2.InstantiateVariables(jax.random.PRNGKey(7))
    for a, b in zip(t1.Flatten(), t2.Flatten()):
      np.testing.assert_array_equal(a, b)
    t3 = l1.InstantiateVariables(jax.random.PRNGKey(8))
    assert not np.allclose(t1.fc[0].w, t3.fc[0].w)

  def test_fprop_dtype_propagation(self):
    p = _MLP.Params().Set(name="mlp", dims=[4, 4], fprop_dtype=jnp.bfloat16)
    layer = p.Instantiate()
    assert layer.fc[0].p.fprop_dtype == jnp.bfloat16
    theta = layer.InstantiateVariables(jax.random.PRNGKey(0))
    out = layer.fc[0].FProp(theta.fc[0], jnp.ones((2, 4)))
    assert out.dtype == jnp.bfloat16

  def test_params_frozen_after_init(self):
    p = _Linear.Params().Set(name="lin", input_dim=2, output_dim=2)
    layer = p.Instantiate()
    with pytest.raises(TypeError):
      layer.p.input_dim = 5

  def test_variable_specs_tree(self):
    p = _MLP.Params().Set(name="mlp", dims=[4, 8, 2])
    specs = p.Instantiate().VariableSpecs()
    assert specs.fc[0].w.shape == (4, 8)


class TestInputGenerators:

  def test_in_memory_repeat_false_yields_tail(self):
    from lingvo_tpu.core import base_input_generator as big
    data = NestedMap(x=np.arange(10, dtype=np.float32))
    p = big.InMemoryInputGenerator.Params().Set(
        name="in", data=data, batch_size=4, shuffle=False, repeat=False,
        require_sequential_order=True)
    gen = p.Instantiate()
    batches = list(gen)
    # 10 examples, bs 4 -> 3 batches; last one wrap-padded to static shape.
    assert len(batches) == 3
    np.testing.assert_array_equal(batches[0].x, [0, 1, 2, 3])
    np.testing.assert_array_equal(batches[2].x, [8, 9, 0, 1])

  def test_in_memory_repeat_loops_and_reshuffles(self):
    from lingvo_tpu.core import base_input_generator as big
    data = NestedMap(x=np.arange(8, dtype=np.float32))
    p = big.InMemoryInputGenerator.Params().Set(
        name="in", data=data, batch_size=8, seed=3)
    gen = p.Instantiate()
    it = iter(gen)
    first = next(it).x.copy()
    second = next(it).x.copy()
    assert sorted(first) == sorted(second) == list(range(8))
    assert not np.array_equal(first, second)  # reshuffled


class TestRegistry:

  def test_register_and_lookup(self):
    from lingvo_tpu import model_registry
    from lingvo_tpu.core import base_model_params

    class FakeParams(base_model_params.SingleTaskModelParams):

      def Train(self):
        p = hyperparams.Params()
        p.Define("batch", 8, "")
        return p

    registered = model_registry._RegisterModel(FakeParams, task_hint="test")
    key = registered._registry_key
    try:
      assert model_registry.GetClass(key) is FakeParams
      with pytest.raises(LookupError):
        model_registry.GetClass("no.such.Model")
    finally:
      # Don't pollute the process-global registry for other tests.
      model_registry._MODEL_REGISTRY.pop(key, None)
