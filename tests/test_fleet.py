"""Disaggregated serving fleet: router scoring, session pinning,
failover, page handoff, theta-swap persistence (serving/fleet.py,
serving/router.py).

Fast tests keep fleets to 2-3 tiny engines and a handful of tokens; the
multi-replica Poisson soak is `slow` (standalone-fast variants cover
each mechanism individually). Byte-identity is THE contract everywhere:
whatever the router, failover, or handoff did, every request's greedy
stream must equal the single-replica dense reference."""

import time

import pytest

from lingvo_tpu.observe import aggregate
from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.parallel import mesh as mesh_lib
from lingvo_tpu.serving import engine as engine_lib
from lingvo_tpu.serving import fleet as fleet_lib
from lingvo_tpu.serving import router as router_lib

from tests.test_serving_engine import _GreedyRef
# (the session-scoped `tiny_lm` fixture resolves from tests/conftest.py)


# -- shadow radix index (pure host state) -------------------------------------


class TestShadowPrefixIndex:

  def _Mk(self, **kw):
    return router_lib.ShadowPrefixIndex(4, **kw)

  def test_note_then_expected_hit_full_pages_only(self):
    idx = self._Mk()
    idx.NoteRouted("r0", [1, 2, 3, 4, 5, 6, 7, 8])
    assert idx.ExpectedHitTokens("r0", [1, 2, 3, 4, 9, 9, 9, 9]) == 4
    # full cover caps at len-1 (last token always recomputes)
    assert idx.ExpectedHitTokens("r0", [1, 2, 3, 4, 5, 6, 7, 8]) == 7
    # the other replica never saw this prefix
    assert idx.ExpectedHitTokens("r1", [1, 2, 3, 4, 5, 6, 7, 8]) == 0
    # partial pages don't count
    assert idx.ExpectedHitTokens("r0", [1, 2, 3]) == 0
    assert idx.nodes == 2

  def test_drop_replica_prunes_exclusive_paths(self):
    idx = self._Mk()
    idx.NoteRouted("r0", [1, 2, 3, 4, 5, 6, 7, 8])
    idx.NoteRouted("r1", [1, 2, 3, 4])       # shares the first chunk
    idx.DropReplica("r0")
    assert idx.ExpectedHitTokens("r0", [1, 2, 3, 4, 5]) == 0
    assert idx.ExpectedHitTokens("r1", [1, 2, 3, 4, 9]) == 4
    assert idx.nodes == 1                    # r0-only deep node pruned

  def test_max_nodes_evicts_lru_leaf(self):
    idx = self._Mk(max_nodes=2)
    idx.NoteRouted("r0", [1, 2, 3, 4])
    idx.NoteRouted("r0", [5, 6, 7, 8])
    idx.NoteRouted("r0", [1, 2, 3, 4])       # refresh: now most recent
    idx.NoteRouted("r0", [9, 9, 9, 9])       # evicts the [5,6,7,8] leaf
    assert idx.ExpectedHitTokens("r0", [5, 6, 7, 8, 0]) == 0
    assert idx.ExpectedHitTokens("r0", [1, 2, 3, 4, 0]) == 4
    assert idx.evictions == 1 and idx.nodes == 2

  def test_clear(self):
    idx = self._Mk()
    idx.NoteRouted("r0", [1, 2, 3, 4])
    idx.Clear()
    assert idx.nodes == 0
    assert idx.ExpectedHitTokens("r0", [1, 2, 3, 4, 0]) == 0


# -- router scoring (fabricated snapshots) ------------------------------------


def _Snaps(**depths):
  return {lb: ({"scheduler/queue_depth": d} if d is not None else None)
          for lb, d in depths.items()}


class TestPrefixRouter:

  def _Mk(self, order=("r0", "r1"), **kw):
    return router_lib.PrefixRouter(4, order, **kw)

  def test_tie_breaks_on_declared_order_not_dict_order(self):
    r = self._Mk()
    # dict literal lists r1 first; declared order must win the tie
    snaps = {"r1": {"scheduler/queue_depth": 0},
             "r0": {"scheduler/queue_depth": 0}}
    assert r.Route([1, 2, 3, 4], snaps) == "r0"

  def test_prefix_holder_beats_mild_load(self):
    r = self._Mk()
    p = [1, 2, 3, 4, 5, 6, 7, 8, 9]
    r.shadow.NoteRouted("r1", p)
    # r1 holds 8 prefix tokens; 1 queued request costs page_size=4
    assert r.Route(p, _Snaps(r0=0, r1=1)) == "r1"
    assert r.prefix_routed == 1
    # drowning load flips it back
    assert r.Route(p, _Snaps(r0=0, r1=5)) == "r0"
    assert r.balanced_routed == 1

  def test_down_replica_routes_around_and_all_down_raises(self):
    r = self._Mk()
    p = [1, 2, 3, 4]
    r.shadow.NoteRouted("r0", p)             # best score... but DOWN
    assert r.Route(p, _Snaps(r0=None, r1=3)) == "r1"
    with pytest.raises(RuntimeError):
      r.Route(p, _Snaps(r0=None, r1=None))

  def test_session_pins_and_repins_after_death(self):
    r = self._Mk()
    p = [1, 2, 3, 4, 5]
    home = r.Route(p, _Snaps(r0=0, r1=0), session="s")
    assert home == "r0"
    # heavy load elsewhere can't break the pin while the home is UP
    assert r.Route(p, _Snaps(r0=9, r1=0), session="s") == "r0"
    assert r.pinned_routed == 1 and r.sessions_pinned == 1
    r.OnReplicaDown("r0")
    assert r.Route(p, _Snaps(r0=None, r1=0), session="s") == "r1"
    assert r.rerouted_down == 1
    # re-pinned: follows the new home now
    assert r.Route(p, _Snaps(r0=None, r1=0), session="s") == "r1"
    assert r.pinned_routed == 2

  def test_load_key_sequence_sums_in_system_load(self):
    r = self._Mk(load_key=("scheduler/queue_depth", "scheduler/slots_live"))
    # r0 has nothing queued but 3 admitted; r1 has 1 queued, 0 admitted
    snaps = {"r0": {"scheduler/queue_depth": 0, "scheduler/slots_live": 3},
             "r1": {"scheduler/queue_depth": 1}}
    assert r.Route([1, 2, 3, 4], snaps) == "r1"

  def test_note_false_leaves_shadow_untouched(self):
    r = self._Mk()
    p = [1, 2, 3, 4]
    lb = r.Route(p, _Snaps(r0=0, r1=0), note=False)
    assert r.shadow.ExpectedHitTokens(lb, p + [9]) == 0
    assert r.shadow.nodes == 0

  def test_theta_swap_clears_shadow_only_without_persistence(self):
    r = self._Mk()
    r.shadow.NoteRouted("r0", [1, 2, 3, 4])
    r.OnThetaSwap(persisted=True)
    assert r.shadow.nodes == 1
    r.OnThetaSwap(persisted=False)
    assert r.shadow.nodes == 0

  def test_stats_schema_exact(self):
    r = self._Mk()
    r.Route([1, 2, 3, 4], _Snaps(r0=0, r1=0), session="s")
    assert set(r.Stats()) == observe_schema.ROUTER_STATS_KEYS


class TestAggregateRouting:

  def test_least_loaded_deterministic_tie_break(self):
    docs = {"b": {"snapshot": {"q": 1}}, "a": {"snapshot": {"q": 1}}}
    assert aggregate.LeastLoaded(docs, load_key="q") == "a"   # sorted
    assert aggregate.LeastLoaded(docs, load_key="q",
                                 order=["b", "a"]) == "b"     # declared

  def test_least_loaded_skips_down_and_non_numeric(self):
    docs = {"a": {"error": "dead"},
            "b": {"snapshot": {"q": True}},    # bool is not a load
            "c": {"snapshot": {"q": 7}}}
    assert aggregate.LeastLoaded(docs, load_key="q") == "c"
    assert aggregate.LeastLoaded({"a": {"error": "x"}}, load_key="q") is None

  def test_live_labels_orders_and_filters(self):
    docs = {"b": {"snapshot": {}}, "a": {"error": "x"}, "c": {"snapshot": {}}}
    assert aggregate.LiveLabels(docs) == ["b", "c"]
    assert aggregate.LiveLabels(docs, order=["c", "a", "b"]) == ["c", "b"]


# -- fleet end-to-end (tiny engines) ------------------------------------------


_P1 = [5, 9, 2, 33, 17, 4, 11, 3, 22, 6]    # 2 full pages + 2-token tail
_P2 = [7, 7, 7, 12, 31, 2, 9, 40, 1]        # distinct session prefix


def _MkEngine(task, theta, **kw):
  kw.setdefault("page_size", 4)
  kw.setdefault("num_pages", 16)
  kw.setdefault("max_batch", 2)
  kw.setdefault("max_seq_len", 32)
  kw.setdefault("prefill_chunk", 4)
  kw.setdefault("prefix_cache", True)
  kw.setdefault("trace", False)
  return engine_lib.ServingLoop(task, theta, **kw)


def _WaitTokens(eng, n, timeout=60.0):
  deadline = time.monotonic() + timeout
  while time.monotonic() < deadline:
    if eng.Stats()["tokens_emitted"] >= n:
      return
    time.sleep(0.005)
  raise TimeoutError("engine never emitted enough tokens")


class TestFleetRouting:

  def test_sessions_pin_and_streams_match_reference(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet(
        {"r0": _MkEngine(task, theta), "r1": _MkEngine(task, theta)}).Start()
    try:
      handles = []
      for _ in range(2):                     # two turns per session
        handles.append((fl.Submit(list(_P1), 5, session="sA"), _P1))
        handles.append((fl.Submit(list(_P2), 5, session="sB"), _P2))
      for h, p in handles:
        assert h.Result(timeout=120) == _GreedyRef(task, theta, p, 5)
      homes = {h.session: set() for h, _ in handles}
      for h, _ in handles:
        homes[h.session].add(h.replica)
      # a session never migrates while its home is up
      assert all(len(v) == 1 for v in homes.values()), homes
      st = fl.Stats()
      assert set(st) == observe_schema.FLEET_STATS_KEYS
      assert st["router"]["pinned_routed"] == 2
      assert st["requests"] == 4 and st["failovers"] == 0
    finally:
      fl.Stop()

  def test_streams_identical_across_routing_policies(self, tiny_lm):
    task, theta = tiny_lm
    outs = {}
    for policy in ("prefix", "round_robin", "least_loaded"):
      fl = fleet_lib.ServingFleet(
          {"r0": _MkEngine(task, theta), "r1": _MkEngine(task, theta)},
          policy=policy).Start()
      try:
        hs = [fl.Submit(list(p), 5) for p in (_P1, _P2, _P1)]
        outs[policy] = [h.Result(timeout=120) for h in hs]
      finally:
        fl.Stop()
    ref = [_GreedyRef(task, theta, p, 5) for p in (_P1, _P2, _P1)]
    for policy, got in outs.items():
      assert got == ref, policy              # byte-identical across policies

  def test_round_robin_alternates_over_up_replicas(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet(
        {"r0": _MkEngine(task, theta), "r1": _MkEngine(task, theta)},
        policy="round_robin").Start()
    try:
      hs = [fl.Submit(list(_P1), 2) for _ in range(4)]
      for h in hs:
        h.Result(timeout=120)
      assert [h.replica for h in hs] == ["r0", "r1", "r0", "r1"]
    finally:
      fl.Stop()


class TestFleetFailover:

  def test_kill_pinned_replica_resubmits_queued_and_inflight(self, tiny_lm):
    task, theta = tiny_lm
    # max_batch=1: the 3rd same-session request is queued-but-unadmitted
    fl = fleet_lib.ServingFleet(
        {"r0": _MkEngine(task, theta, max_batch=1),
         "r1": _MkEngine(task, theta, max_batch=1)}).Start()
    try:
      hs = [fl.Submit(list(_P1), 12, session="s") for _ in range(3)]
      home = hs[0].replica
      _WaitTokens(fl.Engine(home), 2)        # mid-stream, not pre-admission
      fl.KillReplica(home)
      ref = _GreedyRef(task, theta, _P1, 12)
      for h in hs:
        assert h.Result(timeout=120) == ref  # regenerated byte-identically
      sibling = ({"r0", "r1"} - {home}).pop()
      assert all(h.replica == sibling for h in hs)
      st = fl.Stats()
      assert st["failovers"] == 1 and st["resubmitted_requests"] == 3
      assert st["replicas_up"] == 1 and st["replicas_down"] == 1
      # the session re-pins: its next turn goes straight to the sibling
      h = fl.Submit(list(_P1), 3, session="s")
      assert h.Result(timeout=120) == _GreedyRef(task, theta, _P1, 3)
      assert h.replica == sibling
      assert st["router"]["rerouted_down"] >= 1
    finally:
      fl.Stop()

  def test_all_replicas_down_raises_on_submit(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet({"r0": _MkEngine(task, theta)}).Start()
    try:
      fl.KillReplica("r0")
      with pytest.raises(RuntimeError):
        fl.Submit(list(_P1), 2)
    finally:
      fl.Stop()


class TestDisaggregation:

  def test_prefill_worker_absorbs_prompt_decode_gets_tail(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet(
        {"d0": _MkEngine(task, theta)},
        prefill={"p0": _MkEngine(task, theta)}).Start()
    try:
      hs = [(fl.Submit(list(_P1), 5), _P1), (fl.Submit(list(_P2), 5), _P2)]
      for h, p in hs:
        assert h.Result(timeout=120) == _GreedyRef(task, theta, p, 5)
      d0, p0 = fl.Engine("d0"), fl.Engine("p0")
      # the decode replica only ever prefilled the uncached tails:
      # _P1 leaves 10-8=2, _P2 leaves 9-8=1 (min p0 clamp keeps >=1)
      assert d0.Stats()["prompt_tokens"] <= 4
      assert p0.Stats()["prompt_tokens"] == len(_P1) + len(_P2)
      st = fl.Stats()
      assert st["handoffs"] == 2 and st["handoff_pages"] == 4
      assert st["handoff_fallbacks"] == 0
    finally:
      fl.Stop()

  def test_warm_decode_prefix_skips_the_handoff(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet(
        {"d0": _MkEngine(task, theta)},
        prefill={"p0": _MkEngine(task, theta)}).Start()
    try:
      ref = _GreedyRef(task, theta, _P1, 5)
      assert fl.Submit(list(_P1), 5).Result(timeout=120) == ref
      assert fl.Submit(list(_P1), 5).Result(timeout=120) == ref
      st = fl.Stats()
      # the second submit found its prefix already on d0: no second trip
      assert st["handoffs"] == 1 and st["requests"] == 2
      assert fl.Engine("d0").Stats()["prefix_cache"]["hits"] >= 1
    finally:
      fl.Stop()

  def test_dead_prefill_worker_falls_back_to_cold_decode(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet(
        {"d0": _MkEngine(task, theta)},
        prefill={"p0": _MkEngine(task, theta)}).Start()
    try:
      fl.KillReplica("p0")
      h = fl.Submit(list(_P1), 5)
      assert h.Result(timeout=120) == _GreedyRef(task, theta, _P1, 5)
      st = fl.Stats()
      assert st["handoffs"] == 0             # nobody left to prefill
    finally:
      fl.Stop()

  def test_adopt_prefix_requires_caches_and_content(self, tiny_lm):
    task, theta = tiny_lm
    donor = _MkEngine(task, theta)
    recv = _MkEngine(task, theta)
    cacheless = _MkEngine(task, theta, prefix_cache=None)
    assert cacheless.AdoptPrefix(list(_P1), donor) == 0
    assert recv.AdoptPrefix(list(_P1), donor) == 0   # donor cold
    donor.Start()
    donor.Submit(list(_P1), max_new_tokens=1).Result(timeout=120)
    assert recv.AdoptPrefix(list(_P1), donor) == 8
    assert recv.AdoptPrefix(list(_P1), donor) == 0   # already warm: no churn
    recv.Start()
    out = recv.Submit(list(_P1), 5).Result(timeout=120)
    assert out == _GreedyRef(task, theta, _P1, 5)
    pc = recv.Stats()["prefix_cache"]
    assert pc["hits"] == 1 and pc["hit_tokens"] == 8
    assert recv.Stats()["prompt_tokens"] == 2        # tail only
    donor.Stop()
    recv.Stop()


class TestSendRecvChannel:

  def test_send_pages_moves_blocks_between_shards(self, tiny_lm):
    import jax
    import numpy as np
    if len(jax.devices()) < 2:
      pytest.skip("needs >= 2 devices for a real ppermute")
    task, theta = tiny_lm
    m = mesh_lib.MakeMesh({"fleet": 2}, devices=jax.devices()[:2])
    ch = fleet_lib.SendRecvChannel(m, "fleet", src=0, dst=1)
    blocks = [np.arange(24, dtype=np.float32).reshape(2, 3, 4),
              np.full((2, 5), 7, np.int32)]   # int sidecar rides along
    out = ch.Transfer(blocks)
    for got, want in zip(out, blocks):
      assert np.array_equal(np.asarray(got), want)
    # and the end-to-end handoff through the channel stays byte-exact
    donor, recv = _MkEngine(task, theta), _MkEngine(task, theta)
    donor.Start()
    donor.Submit(list(_P1), max_new_tokens=1).Result(timeout=120)
    assert recv.AdoptPrefix(list(_P1), donor, channel=ch) == 8
    recv.Start()
    assert recv.Submit(list(_P1), 5).Result(timeout=120) == _GreedyRef(
        task, theta, _P1, 5)
    donor.Stop()
    recv.Stop()


class TestFleetThetaSwap:

  def test_hot_swap_mid_traffic_with_tree_persistence(self, tiny_lm,
                                                      tiny_lm_swapped):
    task, theta = tiny_lm
    _, theta2 = tiny_lm_swapped
    # the swap must be observable: _P2 decodes differently under theta2
    assert _GreedyRef(task, theta, _P2, 4) != _GreedyRef(task, theta2, _P2, 4)
    fl = fleet_lib.ServingFleet(
        {"r0": _MkEngine(task, theta, prefix_swap_persist=True),
         "r1": _MkEngine(task, theta, prefix_swap_persist=True)}).Start()
    try:
      pre = fl.Submit(list(_P2), 4, session="s")
      assert pre.Result(timeout=120) == _GreedyRef(task, theta, _P2, 4)
      home = pre.replica
      inflight = fl.Submit(list(_P2), 12, session="s")
      _WaitTokens(fl.Engine(home), 5)
      fl.UpdateTheta(theta2)                 # swap with traffic in the air
      # the radix tree survived the swap (stale, not dropped) ...
      pc = fl.Engine(home).Stats()["prefix_cache"]
      assert pc["cached_pages"] == 2 and pc["stale_pages"] == 2
      assert fl.Stats()["router"]["shadow_nodes"] > 0
      assert fl.Stats()["theta_swaps"] == 1
      # ... in-flight work completes; post-swap streams are the new
      # theta's reference, byte-identical
      assert len(inflight.Result(timeout=120)) == 12
      post = fl.Submit(list(_P2), 4, session="s")
      assert post.Result(timeout=120) == _GreedyRef(task, theta2, _P2, 4)
      pc = fl.Engine(home).Stats()["prefix_cache"]
      assert pc["refreshed_pages"] == 2 and pc["stale_pages"] == 0
      # hit_tokens recover without a cold tree restart
      again = fl.Submit(list(_P2), 4, session="s")
      assert again.Result(timeout=120) == _GreedyRef(task, theta2, _P2, 4)
      assert fl.Engine(home).Stats()["prefix_cache"]["hit_tokens"] >= 7
    finally:
      fl.Stop()


class TestFleetExport:

  def test_fleet_statusz_scrape_carries_router_section(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet({"r0": _MkEngine(task, theta)},
                                serve_port=0).Start()
    try:
      fl.Submit(list(_P1), 2).Result(timeout=120)
      url = f"http://{fl.status_server.host}:{fl.status_server.port}"
      doc = aggregate.Scrape(url)
      assert set(doc["stats"]) == observe_schema.FLEET_STATS_KEYS
      assert set(doc["stats"]["router"]) == observe_schema.ROUTER_STATS_KEYS
      assert doc["snapshot"]["router/requests_routed"] == 1
    finally:
      fl.Stop()


@pytest.mark.slow
class TestFleetSoak:

  def test_poisson_soak_with_swap_and_failover(self, tiny_lm,
                                               tiny_lm_swapped):
    """The everything-at-once lifecycle: seeded arrivals over 3 replicas,
    a persisted theta swap and a replica kill mid-stream, every stream
    byte-identical to its theta's reference at the time of submit."""
    import numpy as np
    task, theta = tiny_lm
    _, theta2 = tiny_lm_swapped
    fl = fleet_lib.ServingFleet(
        {f"r{i}": _MkEngine(task, theta, prefix_swap_persist=True)
         for i in range(3)}).Start()
    rng = np.random.RandomState(0)
    prompts = [_P1, _P2, _P1[:4] + _P2[:4]]
    try:
      phase1 = []
      for i in range(9):
        p = prompts[i % 3]
        phase1.append((fl.Submit(list(p), 6, session=f"s{i % 3}"), p))
        time.sleep(float(rng.exponential(0.01)))
      for h, p in phase1:
        assert h.Result(timeout=120) == _GreedyRef(task, theta, p, 6)
      fl.UpdateTheta(theta2)
      phase2 = []
      for i in range(9):
        p = prompts[i % 3]
        phase2.append((fl.Submit(list(p), 6, session=f"s{i % 3}"), p))
        if i == 3:
          fl.KillReplica(phase2[0][0].replica)
        time.sleep(float(rng.exponential(0.01)))
      for h, p in phase2:
        assert h.Result(timeout=120) == _GreedyRef(task, theta2, p, 6)
      st = fl.Stats()
      assert st["failovers"] == 1 and st["theta_swaps"] == 1
      assert st["requests"] == 18
    finally:
      fl.Stop()
