"""Tests for schedules, optimizers, learner (ref optimizer_test/learner_test)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.core.nested_map import NestedMap


class TestSchedules:

  def _v(self, p, step):
    return float(p.Instantiate().Value(step))

  def test_constant(self):
    assert self._v(sched_lib.Constant.Params().Set(value=0.5), 100) == 0.5

  def test_piecewise(self):
    p = sched_lib.PiecewiseConstant.Params().Set(
        boundaries=[10, 20], values=[1.0, 0.1, 0.01])
    assert self._v(p, 0) == 1.0
    assert self._v(p, 10) == pytest.approx(0.1)
    assert self._v(p, 25) == pytest.approx(0.01)

  def test_transformer_schedule(self):
    p = sched_lib.TransformerSchedule.Params().Set(
        warmup_steps=100, model_dim=64)
    peak_region = self._v(p, 99)
    late = self._v(p, 10000)
    early = self._v(p, 0)
    assert early < peak_region and late < peak_region
    # rsqrt decay after warmup
    assert self._v(p, 400) == pytest.approx(64**-0.5 * 401**-0.5, rel=1e-3)

  def test_cosine(self):
    p = sched_lib.LinearRampupCosineDecay.Params().Set(
        warmup_steps=10, total_steps=100, min_ratio=0.1, max=2.0)
    assert self._v(p, 0) == 0.0
    assert self._v(p, 10) == pytest.approx(2.0, rel=1e-3)
    assert self._v(p, 100) == pytest.approx(0.2, rel=1e-3)

  def test_linear_rampup_exp_decay(self):
    p = sched_lib.LinearRampupExponentialDecay.Params().Set(
        warmup=10, decay_start=20, decay_end=30, max=1.0, min=0.1)
    assert self._v(p, 5) == pytest.approx(0.5)
    assert self._v(p, 15) == 1.0
    assert self._v(p, 30) == pytest.approx(0.1, rel=1e-4)


def _quadratic_problem(opt_params, steps=60, lr=0.1):
  """Minimize ||w - target||^2 with the given optimizer; returns final dist."""
  target = jnp.array([1.0, -2.0, 3.0])
  params = NestedMap(w=jnp.zeros(3))
  opt = opt_params.Instantiate()
  state = opt.InitState(params)

  def loss_fn(p):
    return jnp.sum(jnp.square(p.w - target))

  @jax.jit
  def step_fn(params, state, i):
    grads = jax.grad(loss_fn)(params)
    return opt.Update(state, grads, params, lr, i)

  for i in range(steps):
    params, state = step_fn(params, state, i)
  return float(jnp.linalg.norm(params.w - target))


class TestOptimizers:

  def test_sgd_converges(self):
    assert _quadratic_problem(opt_lib.SGD.Params()) < 1e-3

  def test_momentum_converges(self):
    assert _quadratic_problem(
        opt_lib.Momentum.Params(), steps=200, lr=0.02) < 1e-2

  def test_adam_converges(self):
    assert _quadratic_problem(opt_lib.Adam.Params(), steps=300, lr=0.1) < 1e-2

  def test_adagrad_converges(self):
    assert _quadratic_problem(
        opt_lib.Adagrad.Params(), steps=400, lr=1.0) < 1e-2

  def test_rmsprop_converges(self):
    assert _quadratic_problem(
        opt_lib.RMSProp.Params().Set(epsilon=1e-8), steps=300, lr=0.05) < 0.05

  def test_adamw_decays_weights(self):
    params = NestedMap(w=jnp.ones(4) * 10)
    opt = opt_lib.AdamW.Params().Set(weight_decay=0.1).Instantiate()
    state = opt.InitState(params)
    zero_g = NestedMap(w=jnp.zeros(4))
    new_params, _ = opt.Update(state, zero_g, params, 0.1, 0)
    assert float(new_params.w[0]) < 10.0  # decay applied with zero grads

  def test_adafactor_factored_state_shapes(self):
    params = NestedMap(
        big=jnp.zeros((256, 512)), small=jnp.zeros((4, 4)), vec=jnp.zeros(300))
    opt = opt_lib.Adafactor.Params().Instantiate()
    state = opt.InitState(params)
    assert state.slots.big.vr.shape == (256,)
    assert state.slots.big.vc.shape == (512,)
    assert "v" in state.slots.small and state.slots.small.v.shape == (4, 4)
    assert state.slots.vec.v.shape == (300,)

  def test_adafactor_converges(self):
    p = opt_lib.Adafactor.Params().Set(
        multiply_by_parameter_scale=False, factored=False)
    assert _quadratic_problem(p, steps=400, lr=0.05) < 0.05

  def test_accumulator_applies_every_n(self):
    params = NestedMap(w=jnp.zeros(2))
    opt = opt_lib.Accumulator.Params().Set(
        optimizer_tpl=opt_lib.SGD.Params(), accum_steps=3).Instantiate()
    state = opt.InitState(params)
    g = NestedMap(w=jnp.ones(2) * 3.0)
    for i in range(2):
      params, state = opt.Update(state, g, params, 0.1, i)
      np.testing.assert_allclose(params.w, 0.0)  # no update yet
    params, state = opt.Update(state, g, params, 0.1, 2)
    np.testing.assert_allclose(params.w, -0.3)  # mean grad 3.0 * lr 0.1
    assert int(state.count) == 0

  def test_composite_routes_by_regex(self):
    params = NestedMap(
        emb=NestedMap(w=jnp.ones(3)), body=NestedMap(w=jnp.ones(3)))
    p = opt_lib.CompositeOptimizer.Params().Set(optimizer_map=[
        (r"emb\.", opt_lib.SGD.Params(), 10.0),
        (r".*", opt_lib.SGD.Params(), 1.0),
    ])
    opt = p.Instantiate()
    state = opt.InitState(params)
    g = params.Transform(jnp.ones_like)
    new_params, _ = opt.Update(state, g, params, 0.01, 0)
    np.testing.assert_allclose(new_params.emb.w, 1.0 - 0.1)  # 10x lr
    np.testing.assert_allclose(new_params.body.w, 1.0 - 0.01)


class TestLearner:

  def _learner(self, **kw):
    p = learner_lib.Learner.Params().Set(
        name="learner", learning_rate=0.1,
        optimizer=opt_lib.SGD.Params(), **kw)
    return p.Instantiate()

  def test_basic_apply(self):
    lrn = self._learner()
    theta = NestedMap(w=jnp.ones(3))
    grads = NestedMap(w=jnp.ones(3))
    state = lrn.InitState(theta)
    new_theta, _, stats = lrn.Apply(theta, grads, 0, state)
    np.testing.assert_allclose(new_theta.w, 0.9)
    assert float(stats.grad_norm) == pytest.approx(np.sqrt(3), rel=1e-5)
    assert float(stats.skipped_step) == 0.0

  def test_nan_skip(self):
    lrn = self._learner()
    theta = NestedMap(w=jnp.ones(3))
    grads = NestedMap(w=jnp.array([1.0, np.nan, 1.0]))
    state = lrn.InitState(theta)
    new_theta, _, stats = lrn.Apply(theta, grads, 0, state)
    np.testing.assert_allclose(new_theta.w, 1.0)  # unchanged
    assert float(stats.skipped_step) == 1.0

  def test_global_norm_clip(self):
    lrn = self._learner(clip_gradient_norm_to_value=1.0)
    theta = NestedMap(w=jnp.zeros(4))
    grads = NestedMap(w=jnp.ones(4) * 10)  # norm 20
    state = lrn.InitState(theta)
    new_theta, _, stats = lrn.Apply(theta, grads, 0, state)
    # grads scaled to norm 1 -> each element 0.5; step = lr * 0.5
    np.testing.assert_allclose(new_theta.w, -0.1 * 0.5, rtol=1e-5)

  def test_clip_to_zero_rejects_outlier(self):
    lrn = self._learner(grad_norm_to_clip_to_zero=5.0)
    theta = NestedMap(w=jnp.ones(2))
    state = lrn.InitState(theta)
    ok_theta, _, _ = lrn.Apply(theta, NestedMap(w=jnp.ones(2)), 0, state)
    assert not np.allclose(ok_theta.w, 1.0)
    big_theta, _, stats = lrn.Apply(theta, NestedMap(w=jnp.ones(2) * 100), 0,
                                    state)
    np.testing.assert_allclose(big_theta.w, 1.0)
    assert float(stats.skipped_step) == 1.0

  def test_trainable_filter(self):
    from lingvo_tpu.core.py_utils import WeightParams
    lrn = self._learner(bprop_variable_exclusion=r"frozen")
    assert lrn.TrainableFilter("model.body.w")
    assert not lrn.TrainableFilter("model.frozen.w")
    wp = WeightParams((2,), collections=("non_trainable",))
    assert not lrn.TrainableFilter("model.bn.moving_mean", wp)

  def test_lr_schedule_composition(self):
    import lingvo_tpu.core.schedule as sched
    lrn = self._learner(
        lr_schedule=sched.PiecewiseConstant.Params().Set(
            boundaries=[10], values=[1.0, 0.5]))
    assert float(lrn.LearningRate(0)) == pytest.approx(0.1)
    assert float(lrn.LearningRate(20)) == pytest.approx(0.05)

  def test_jit_apply(self):
    lrn = self._learner()
    theta = NestedMap(w=jnp.ones(3))
    state = lrn.InitState(theta)

    @jax.jit
    def step(theta, state, grads, i):
      return lrn.Apply(theta, grads, i, state)

    new_theta, new_state, stats = step(theta, state,
                                       NestedMap(w=jnp.ones(3)), 0)
    np.testing.assert_allclose(new_theta.w, 0.9)


class TestDistributedShampoo:

  def test_converges_on_quadratic(self):
    from lingvo_tpu.core import optimizer as opt_lib
    p = opt_lib.DistributedShampoo.Params().Set(statistics_compute_steps=2)
    opt = p.Instantiate()
    params = NestedMap(w=jnp.ones((8, 4)), b=jnp.ones((4,)))
    state = opt.InitState(params)
    target = jax.random.normal(jax.random.PRNGKey(0), (8, 4))
    update = jax.jit(opt.Update)
    losses = []
    for step in range(60):
      g = NestedMap(w=(params.w - target), b=jnp.zeros((4,)))
      params, state = update(state, g, params, 0.3, step)
      losses.append(float(jnp.sum((params.w - target) ** 2)))
    assert losses[-1] < 1e-3 * losses[0], (losses[0], losses[-1])

  def test_oversized_and_vector_fall_back_to_adagrad(self):
    from lingvo_tpu.core import optimizer as opt_lib
    p = opt_lib.DistributedShampoo.Params().Set(block_size=4)
    opt = p.Instantiate()
    params = NestedMap(big=jnp.ones((8, 8)), vec=jnp.ones((5,)))
    state = opt.InitState(params)
    # factors for non-preconditioned leaves are scalar placeholders
    assert state.stat_l.big.shape == ()
    assert state.stat_l.vec.shape == ()
    g = NestedMap(big=jnp.ones((8, 8)), vec=jnp.ones((5,)))
    params2, state = jax.jit(opt.Update)(state, g, params, 0.1, 0)
    assert float(params2.big[0, 0]) < 1.0  # still updated (diag AdaGrad)

  def test_trains_a_real_task(self):
    from lingvo_tpu.core import optimizer as opt_lib
    import sys, os
    sys.path.insert(0, os.path.dirname(__file__))
    import test_executor_hardening as helpers
    task_p = helpers._TaskParams(lr=0.1)
    task_p.train.learner.optimizer = (
        opt_lib.DistributedShampoo.Params().Set(statistics_compute_steps=5))
    task = task_p.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = helpers._RegressionInput()
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(40):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert losses[-1] < 0.2 * losses[0], (losses[0], losses[-1])


class TestMlPerfLog:

  def test_mllog_lines(self, tmp_path):
    from lingvo_tpu.core import ml_perf_log
    import json
    path = str(tmp_path / "log.txt")
    logger = ml_perf_log.MlPerfLogger(path, benchmark="bert")
    logger.Print(ml_perf_log.RUN_START)
    logger.Print(ml_perf_log.EVAL_ACCURACY, 0.71, metadata={"step": 100})
    logger.Print(ml_perf_log.RUN_STOP, metadata={"status": "success"})
    logger.Close()
    lines = open(path).read().splitlines()
    assert all(l.startswith(":::MLLOG ") for l in lines)
    recs = [json.loads(l[len(":::MLLOG "):]) for l in lines]
    keys = [r["key"] for r in recs]
    assert keys[0] == "submission_benchmark"
    run_start = next(r for r in recs if r["key"] == "run_start")
    assert run_start["event_type"] == "INTERVAL_START"
    acc = next(r for r in recs if r["key"] == "eval_accuracy")
    assert acc["value"] == 0.71 and acc["metadata"]["step"] == 100
