"""Flash attention kernel tests (interpret mode on CPU): exactness vs plain
attention, causal masking, gradients through the custom VJP."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.ops import flash_attention

KEY = jax.random.PRNGKey(21)


def _ref(q, k, v, causal):
  b, t, n, h = q.shape
  s = jnp.einsum("bqnh,bknh->bnqk", q, k) / math.sqrt(h)
  if causal:
    mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
    s = jnp.where(mask[None, None], s, -1e30)
  p = jax.nn.softmax(s, axis=-1)
  return jnp.einsum("bnqk,bknh->bqnh", p, v)


class TestFlashAttention:

  @pytest.mark.parametrize("causal", [True, False])
  def test_matches_reference(self, causal):
    b, t, n, h = 2, 64, 2, 16
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    out = flash_attention.FlashAttention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, causal)), atol=2e-5)

  @pytest.mark.parametrize("block_q,block_k", [(32, 16), (16, 32)])
  def test_causal_mismatched_blocks(self, block_q, block_k):
    # Regression (ADVICE r1): block_q > block_k causal used to skip valid
    # past key blocks (max abs err ~0.99); both orderings must be exact.
    b, t, n, h = 2, 64, 1, 16
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(11), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(12), (b, t, n, h))
    out = flash_attention.FlashAttention(
        q, k, v, causal=True, block_q=block_q, block_k=block_k,
        interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=2e-5)

  @pytest.mark.parametrize("causal", [True, False])
  @pytest.mark.parametrize("block_q,block_k", [(32, 16), (16, 32)])
  def test_gradients_mismatched_blocks(self, causal, block_q, block_k):
    b, t, n, h = 1, 64, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))

    def loss_flash(q, k, v):
      return jnp.sum(jnp.square(flash_attention.FlashAttention(
          q, k, v, causal=causal, block_q=block_q, block_k=block_k,
          interpret=True)))

    def loss_ref(q, k, v):
      return jnp.sum(jnp.square(_ref(q, k, v, causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)

  def test_blocks_do_not_change_result(self):
    b, t, n, h = 1, 64, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    out1 = flash_attention.FlashAttention(
        q, q, q, block_q=64, block_k=64, interpret=True)
    out2 = flash_attention.FlashAttention(
        q, q, q, block_q=16, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=2e-5)

  def test_gradients_match_reference(self):
    b, t, n, h = 1, 32, 2, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))

    def loss_flash(q, k, v):
      return jnp.sum(jnp.square(flash_attention.FlashAttention(
          q, k, v, block_q=16, block_k=16, interpret=True)))

    def loss_ref(q, k, v):
      return jnp.sum(jnp.square(_ref(q, k, v, True)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)

  def test_mha_flash_path_matches_einsum_path(self):
    from lingvo_tpu.core import attention
    p = attention.MultiHeadedAttention.Params().Set(
        name="mha", input_dim=16, hidden_dim=16, num_heads=2,
        use_flash_attention=True)
    flash = p.Instantiate()
    theta = flash.InstantiateVariables(KEY)
    plain = p.Copy().Set(use_flash_attention=False).Instantiate()
    x = jax.random.normal(KEY, (2, 32, 16))
    out_flash, probs = flash.FProp(theta, x, causal=True)
    assert probs is None  # flash path returns no probability matrix
    out_plain, _ = plain.FProp(theta, x, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_plain), atol=2e-5)
    # paddings now ride the flash path too (as the kernel's segment mask);
    # outputs must agree with the einsum path at every non-pad position
    # (pad positions are loss-masked garbage on both paths)
    pad = jnp.zeros((2, 32)).at[1, 20:].set(1.0)
    out_f2, probs2 = flash.FProp(theta, x, paddings=pad, causal=True)
    out_p2, _ = plain.FProp(theta, x, paddings=pad, causal=True)
    assert probs2 is None
    keep = np.asarray(1.0 - pad)[:, :, None]
    np.testing.assert_allclose(
        np.asarray(out_f2) * keep, np.asarray(out_p2) * keep, atol=2e-5)

  def test_nondivisible_by_128_autofits_blocks(self):
    # Regression: t=160 (multiple of 16, not 128) must not crash.
    b, t, n, h = 1, 160, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    out = flash_attention.FlashAttention(q, q, q, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, q, q, True)), atol=2e-5)

  def test_local_attention_accepts_causal_kwarg(self):
    # Regression: atten_tpl overrides must survive the causal= plumbing.
    from lingvo_tpu.core import attention, transformer
    p = transformer.TransformerLayer.Params().Set(
        name="xf", input_dim=16, num_heads=2, hidden_dim=32,
        mask_self_atten=True)
    p.tr_atten_tpl.atten_tpl = attention.LocalSelfAttention.Params().Set(
        block_size=8, left_context=8)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    out = layer.FProp(theta, jax.random.normal(KEY, (2, 16, 16)))
    assert out.shape == (2, 16, 16)

  def test_jit_and_bf16(self):
    b, t, n, h = 1, 32, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h), jnp.bfloat16)
    out = jax.jit(lambda q: flash_attention.FlashAttention(
        q, q, q, block_q=16, block_k=16, interpret=True))(q)
    assert out.dtype == jnp.bfloat16
    assert np.all(np.isfinite(np.asarray(out, np.float32)))


class TestShapeHeuristic:
  """Small off-TPU shapes auto-fall back to plain XLA (interpret-mode grid
  overhead dwarfs the compute); explicit interpret=True keeps the kernel."""

  def test_selected_lowering(self):
    # CPU backend here: small shape -> xla, big -> pallas-interpret
    assert flash_attention.SelectedLowering(256, 2, 32) == "xla"
    assert flash_attention.SelectedLowering(4096, 16, 128) == (
        "pallas-interpret")
    assert flash_attention.SelectedLowering(
        256, 2, 32, interpret=True) == "pallas-interpret"
    assert flash_attention.SelectedLowering(
        256, 2, 32, interpret=False) == "pallas"

  def test_auto_fallback_matches_reference(self):
    b, t, n, h = 1, 64, 2, 16
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    out = flash_attention.FlashAttention(q, k, v, causal=True)  # auto: xla
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=2e-5)

  def test_auto_fallback_grads_match_reference(self):
    b, t, n, h = 1, 32, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))

    def loss_auto(q):
      return jnp.sum(flash_attention.FlashAttention(q, q, q) ** 2)

    def loss_ref(q):
      return jnp.sum(_ref(q, q, q, True) ** 2)

    np.testing.assert_allclose(
        np.asarray(jax.grad(loss_auto)(q)),
        np.asarray(jax.grad(loss_ref)(q)), atol=1e-4)

  def test_auto_fallback_segment_ids(self):
    b, t, n, h = 1, 32, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    seg = jnp.concatenate(
        [jnp.full((16,), 1), jnp.full((16,), 2)])[None, :].astype(jnp.int32)
    out = flash_attention.FlashAttention(q, q, q, causal=True,
                                         segment_ids=seg)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref_seg(q, q, q, seg, True)), atol=2e-5)


def _ref_seg(q, k, v, seg, causal):
  b, t, n, h = q.shape
  s = jnp.einsum("bqnh,bknh->bnqk", q, k) / math.sqrt(h)
  mask = seg[:, :, None] == seg[:, None, :]              # [b, t, t]
  if causal:
    mask = mask & jnp.tril(jnp.ones((t, t), jnp.bool_))[None]
  s = jnp.where(mask[:, None], s, -1e30)
  p = jax.nn.softmax(s, axis=-1)
  return jnp.einsum("bnqk,bknh->bqnh", p, v)


class TestFlashSegmentIds:
  """Packed-input segment masking in the fused kernel."""

  def _qkv(self, b=2, t=64, n=2, h=16):
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    return q, k, v

  @pytest.mark.parametrize("causal", [True, False])
  def test_matches_segment_masked_reference(self, causal):
    q, k, v = self._qkv()
    t = q.shape[1]
    # 3 segments + trailing padding (id 0)
    seg = jnp.concatenate([
        jnp.full((t // 4,), 1), jnp.full((t // 4,), 2),
        jnp.full((t // 4,), 3), jnp.full((t // 4,), 0)])[None, :]
    seg = jnp.tile(seg, (q.shape[0], 1)).astype(jnp.int32)
    out = flash_attention.FlashAttention(
        q, k, v, causal=causal, segment_ids=seg, block_q=16, block_k=16,
        interpret=True)
    ref = _ref_seg(q, k, v, seg, causal)
    # only compare non-pad positions (pad attends pad in both; ref is
    # identical there too, but keep the contract narrow)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

  def test_fully_masked_early_blocks(self):
    # a query in the LAST segment sees zero unmasked keys in k-block 0 —
    # the online-softmax NEG_INF guard must keep those p exactly 0
    q, k, v = self._qkv(b=1, t=64)
    seg = jnp.concatenate(
        [jnp.full((32,), 1), jnp.full((32,), 2)])[None, :].astype(jnp.int32)
    out = flash_attention.FlashAttention(
        q, k, v, causal=True, segment_ids=seg, block_q=16, block_k=16,
        interpret=True)
    ref = _ref_seg(q, k, v, seg, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    assert np.all(np.isfinite(np.asarray(out)))

  def test_gradients_match_segment_reference(self):
    q, k, v = self._qkv(b=1, t=48)
    seg = jnp.concatenate(
        [jnp.full((16,), 1), jnp.full((16,), 2),
         jnp.full((16,), 0)])[None, :].astype(jnp.int32)

    def flash_loss(q, k, v):
      return jnp.sum(flash_attention.FlashAttention(
          q, k, v, causal=True, segment_ids=seg, block_q=16, block_k=16,
          interpret=True) ** 2)

    def ref_loss(q, k, v):
      return jnp.sum(_ref_seg(q, k, v, seg, True) ** 2)

    gf = jax.grad(flash_loss, (0, 1, 2))(q, k, v)
    gr = jax.grad(ref_loss, (0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

  def test_mha_packed_flash_matches_einsum_path(self):
    from lingvo_tpu.core import attention as attention_lib
    b, t, d, n = 2, 64, 32, 2
    x = jax.random.normal(jax.random.PRNGKey(5), (b, t, d))
    seg = jnp.concatenate(
        [jnp.full((t // 2,), 1), jnp.full((t // 2,), 2)])[None, :]
    seg = jnp.tile(seg, (b, 1)).astype(jnp.int32)
    paddings = (seg == 0).astype(jnp.float32)
    mk = lambda flash: attention_lib.MultiHeadedAttention.Params().Set(
        name="mha", input_dim=d, hidden_dim=d, num_heads=n,
        use_flash_attention=flash).Instantiate()
    m_f, m_e = mk(True), mk(False)
    theta = m_f.InstantiateVariables(jax.random.PRNGKey(6))
    of, probs_f = m_f.FProp(theta, x, segment_ids=seg, paddings=paddings,
                            causal=True)
    oe, _ = m_e.FProp(theta, x, segment_ids=seg, paddings=paddings,
                      causal=True)
    assert probs_f is None  # flash path engaged despite segs/paddings
    np.testing.assert_allclose(np.asarray(of), np.asarray(oe), atol=2e-5)
