"""Flash attention kernel tests (interpret mode on CPU): exactness vs plain
attention, causal masking, gradients through the custom VJP."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.ops import flash_attention

KEY = jax.random.PRNGKey(21)


def _ref(q, k, v, causal):
  b, t, n, h = q.shape
  s = jnp.einsum("bqnh,bknh->bnqk", q, k) / math.sqrt(h)
  if causal:
    mask = jnp.tril(jnp.ones((t, t), jnp.bool_))
    s = jnp.where(mask[None, None], s, -1e30)
  p = jax.nn.softmax(s, axis=-1)
  return jnp.einsum("bnqk,bknh->bqnh", p, v)


class TestFlashAttention:

  @pytest.mark.parametrize("causal", [True, False])
  def test_matches_reference(self, causal):
    b, t, n, h = 2, 64, 2, 16
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))
    out = flash_attention.FlashAttention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, causal)), atol=2e-5)

  @pytest.mark.parametrize("block_q,block_k", [(32, 16), (16, 32)])
  def test_causal_mismatched_blocks(self, block_q, block_k):
    # Regression (ADVICE r1): block_q > block_k causal used to skip valid
    # past key blocks (max abs err ~0.99); both orderings must be exact.
    b, t, n, h = 2, 64, 1, 16
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(11), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(12), (b, t, n, h))
    out = flash_attention.FlashAttention(
        q, k, v, causal=True, block_q=block_q, block_k=block_k,
        interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, k, v, True)), atol=2e-5)

  @pytest.mark.parametrize("causal", [True, False])
  @pytest.mark.parametrize("block_q,block_k", [(32, 16), (16, 32)])
  def test_gradients_mismatched_blocks(self, causal, block_q, block_k):
    b, t, n, h = 1, 64, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))

    def loss_flash(q, k, v):
      return jnp.sum(jnp.square(flash_attention.FlashAttention(
          q, k, v, causal=causal, block_q=block_q, block_k=block_k,
          interpret=True)))

    def loss_ref(q, k, v):
      return jnp.sum(jnp.square(_ref(q, k, v, causal)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)

  def test_blocks_do_not_change_result(self):
    b, t, n, h = 1, 64, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    out1 = flash_attention.FlashAttention(
        q, q, q, block_q=64, block_k=64, interpret=True)
    out2 = flash_attention.FlashAttention(
        q, q, q, block_q=16, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=2e-5)

  def test_gradients_match_reference(self):
    b, t, n, h = 1, 32, 2, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    k = jax.random.normal(jax.random.PRNGKey(1), (b, t, n, h))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, t, n, h))

    def loss_flash(q, k, v):
      return jnp.sum(jnp.square(flash_attention.FlashAttention(
          q, k, v, block_q=16, block_k=16, interpret=True)))

    def loss_ref(q, k, v):
      return jnp.sum(jnp.square(_ref(q, k, v, True)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_flash, g_ref):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=1e-4)

  def test_mha_flash_path_matches_einsum_path(self):
    from lingvo_tpu.core import attention
    p = attention.MultiHeadedAttention.Params().Set(
        name="mha", input_dim=16, hidden_dim=16, num_heads=2,
        use_flash_attention=True)
    flash = p.Instantiate()
    theta = flash.InstantiateVariables(KEY)
    plain = p.Copy().Set(use_flash_attention=False).Instantiate()
    x = jax.random.normal(KEY, (2, 32, 16))
    out_flash, probs = flash.FProp(theta, x, causal=True)
    assert probs is None  # flash path returns no probability matrix
    out_plain, _ = plain.FProp(theta, x, causal=True)
    np.testing.assert_allclose(
        np.asarray(out_flash), np.asarray(out_plain), atol=2e-5)
    # paddings force the fallback path (still correct, probs returned)
    pad = jnp.zeros((2, 32)).at[1, 20:].set(1.0)
    out_f2, probs2 = flash.FProp(theta, x, paddings=pad, causal=True)
    out_p2, _ = plain.FProp(theta, x, paddings=pad, causal=True)
    assert probs2 is not None
    np.testing.assert_allclose(
        np.asarray(out_f2), np.asarray(out_p2), atol=2e-5)

  def test_nondivisible_by_128_autofits_blocks(self):
    # Regression: t=160 (multiple of 16, not 128) must not crash.
    b, t, n, h = 1, 160, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h))
    out = flash_attention.FlashAttention(q, q, q, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ref(q, q, q, True)), atol=2e-5)

  def test_local_attention_accepts_causal_kwarg(self):
    # Regression: atten_tpl overrides must survive the causal= plumbing.
    from lingvo_tpu.core import attention, transformer
    p = transformer.TransformerLayer.Params().Set(
        name="xf", input_dim=16, num_heads=2, hidden_dim=32,
        mask_self_atten=True)
    p.tr_atten_tpl.atten_tpl = attention.LocalSelfAttention.Params().Set(
        block_size=8, left_context=8)
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    out = layer.FProp(theta, jax.random.normal(KEY, (2, 16, 16)))
    assert out.shape == (2, 16, 16)

  def test_jit_and_bf16(self):
    b, t, n, h = 1, 32, 1, 8
    q = jax.random.normal(KEY, (b, t, n, h), jnp.bfloat16)
    out = jax.jit(lambda q: flash_attention.FlashAttention(
        q, q, q, block_q=16, block_k=16, interpret=True))(q)
    assert out.dtype == jnp.bfloat16
    assert np.all(np.isfinite(np.asarray(out, np.float32)))
