"""Tests for the from-scratch SentencePiece model reader/tokenizer."""

import numpy as np
import pytest

from lingvo_tpu.core import sentencepiece as spm
from lingvo_tpu.core import tokenizers


def _TinyUnigramModel():
  # Hand-built vocab: specials, chars, and two multi-char pieces that
  # Viterbi should prefer over per-char segmentation.
  pieces = [
      ("<unk>", 0.0, spm.UNKNOWN),
      ("<s>", 0.0, spm.CONTROL),
      ("</s>", 0.0, spm.CONTROL),
      ("▁", -3.0, spm.NORMAL),
      ("h", -4.0, spm.NORMAL),
      ("e", -4.0, spm.NORMAL),
      ("l", -4.0, spm.NORMAL),
      ("o", -4.0, spm.NORMAL),
      ("w", -4.0, spm.NORMAL),
      ("r", -4.0, spm.NORMAL),
      ("d", -4.0, spm.NORMAL),
      ("▁hello", -5.0, spm.NORMAL),
      ("▁world", -5.5, spm.NORMAL),
  ]
  return spm.SentencePieceModel(pieces, model_type=spm.UNIGRAM, unk_id=0,
                                bos_id=1, eos_id=2)


class TestProtoRoundTrip:

  def test_bytes_round_trip(self):
    m = _TinyUnigramModel()
    m2 = spm.SentencePieceModel.FromBytes(m.ToBytes())
    assert m2.pieces == [(p, pytest.approx(s), t) for p, s, t in m.pieces]
    assert (m2.model_type, m2.unk_id, m2.bos_id, m2.eos_id, m2.pad_id) == (
        spm.UNIGRAM, 0, 1, 2, -1)

  def test_file_round_trip(self, tmp_path):
    path = str(tmp_path / "tiny.model")
    _TinyUnigramModel().Save(path)
    m = spm.SentencePieceModel.FromFile(path)
    assert m.vocab_size == 13
    assert m.EncodeAsPieces("hello") == ["▁hello"]

  def test_negative_pad_id_survives(self):
    m = _TinyUnigramModel()
    m.pad_id = -1
    assert spm.SentencePieceModel.FromBytes(m.ToBytes()).pad_id == -1


class TestUnigramSegmentation:

  def test_viterbi_prefers_whole_word(self):
    m = _TinyUnigramModel()
    # score(▁hello)=-5 beats ▁+h+e+l+l+o = -3-4*5 = -23
    assert m.EncodeAsPieces("hello world") == ["▁hello", "▁world"]

  def test_falls_back_to_chars(self):
    m = _TinyUnigramModel()
    assert m.EncodeAsPieces("hole") == ["▁", "h", "o", "l", "e"]

  def test_unknown_char_gets_unk_id(self):
    m = _TinyUnigramModel()
    ids = m.EncodeAsIds("hz")
    # ▁, h, then z → unk
    assert ids[-1] == m.unk_id

  def test_whitespace_normalization(self):
    m = _TinyUnigramModel()
    assert m.EncodeAsPieces("  hello   world  ") == ["▁hello", "▁world"]

  def test_decode_round_trip(self):
    m = _TinyUnigramModel()
    assert m.DecodeIds(m.EncodeAsIds("hello world")) == "hello world"

  def test_decode_skips_control(self):
    m = _TinyUnigramModel()
    ids = [1] + m.EncodeAsIds("hello") + [2]
    assert m.DecodeIds(ids) == "hello"


class TestByteFallback:

  def test_oov_char_becomes_bytes_and_back(self):
    pieces = ([("<unk>", 0.0, spm.UNKNOWN), ("<s>", 0.0, spm.CONTROL),
               ("</s>", 0.0, spm.CONTROL)]
              + [(f"<0x{b:02X}>", -8.0, spm.BYTE) for b in range(256)]
              + [("▁", -2.0, spm.NORMAL), ("a", -2.0, spm.NORMAL)])
    m = spm.SentencePieceModel(pieces)
    ids = m.EncodeAsIds("aé")  # é not in vocab → 2 utf-8 byte pieces
    byte_ids = [i for i in ids if m.pieces[i][2] == spm.BYTE]
    assert len(byte_ids) == 2
    assert m.DecodeIds(ids) == "aé"


class TestBpeMode:

  def test_merge_order_follows_scores(self):
    pieces = [
        ("<unk>", 0.0, spm.UNKNOWN), ("<s>", 0.0, spm.CONTROL),
        ("</s>", 0.0, spm.CONTROL),
        ("▁", -1.0, spm.NORMAL), ("a", -1.0, spm.NORMAL),
        ("b", -1.0, spm.NORMAL), ("ab", -0.5, spm.NORMAL),
        ("▁ab", -0.25, spm.NORMAL),
    ]
    m = spm.SentencePieceModel(pieces, model_type=spm.BPE)
    assert m.EncodeAsPieces("ab") == ["▁ab"]
    assert m.EncodeAsPieces("abb") == ["▁ab", "b"]


class TestTinyTrainer:

  def test_vocab_size_is_hard_cap(self):
    corpus = ["abcdefghij klmnop qrstuv wxyz"]
    m = spm.TrainUnigramModel(corpus, vocab_size=10)
    assert m.vocab_size <= 10
    # byte pieces that don't fit raise instead of overflowing
    with pytest.raises(ValueError, match="cannot even hold"):
      spm.TrainUnigramModel(corpus, vocab_size=100, byte_fallback=True)

  def test_specials_order_sets_ids(self):
    m = spm.TrainUnigramModel(["a b"], vocab_size=32,
                              specials=("<pad>", "<s>", "</s>", "<unk>"))
    assert (m.pad_id, m.bos_id, m.eos_id, m.unk_id) == (0, 1, 2, 3)
    with pytest.raises(ValueError, match="<unk>"):
      spm.TrainUnigramModel(["a"], vocab_size=32, specials=("<s>",))

  def test_trained_model_round_trips(self, tmp_path):
    corpus = ["the cat sat on the mat", "the dog sat on the log"] * 5
    m = spm.TrainUnigramModel(corpus, vocab_size=64)
    assert m.vocab_size <= 64
    path = str(tmp_path / "trained.model")
    m.Save(path)
    m2 = spm.SentencePieceModel.FromFile(path)
    text = "the cat sat"
    assert m2.DecodeIds(m2.EncodeAsIds(text)) == text
    # frequent word "the" should be a single piece
    assert "▁the" in m2.EncodeAsPieces("the cat")


class TestTokenizerLayer:

  def test_strings_to_ids_framing(self, tmp_path):
    path = str(tmp_path / "tiny.model")
    _TinyUnigramModel().Save(path)
    tok = tokenizers.SentencePieceTokenizer.Params().Set(
        vocab_filepath=path).Instantiate()
    ids, labels, paddings = tok.StringsToIds(["hello world"], 8)
    # special ids resolved lazily from the model file's TrainerSpec
    assert tok.p.target_sos_id == 1 and tok.p.target_eos_id == 2
    assert ids[0, 0] == 1  # sos
    n = int((1.0 - paddings[0]).sum()) - 1
    assert labels[0, n] == 2  # eos
    np.testing.assert_array_equal(ids[0, 1:n + 1], labels[0, :n])
    assert tok.IdsToStrings(labels, np.array([n + 1]))[0] == "hello world"
