"""StaticMap, MlPerfSubword, inspect_utils, decoder_lib, and regex
cross-task variable sharing (SURVEY §2 micro-components)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import decoder_lib
from lingvo_tpu.core import host_ops
from lingvo_tpu.core import hyperparams
from lingvo_tpu.core import inspect_utils
from lingvo_tpu.core import multitask_model
from lingvo_tpu.core.nested_map import NestedMap


class TestStaticMap:

  def test_round_trip_with_default_ids(self):
    m = host_ops.StaticMap(["car", "ped", "cyc"])
    np.testing.assert_array_equal(m.StrToId(["ped", "car"]), [1, 0])
    assert list(m.IdToStr([2, 0])) == ["cyc", "car"]

  def test_explicit_ids_and_unk(self):
    m = host_ops.StaticMap(["a", "b"], ids=[10, 20], unk_id=-7,
                           unk_token="<?>")
    np.testing.assert_array_equal(m.StrToId([["a", "x"], ["b", "b"]]),
                                  [[10, -7], [20, 20]])
    assert m.IdToStr([99]).tolist() == ["<?>"]

  def test_duplicate_keys_rejected(self):
    with pytest.raises(ValueError, match="duplicate"):
      host_ops.StaticMap(["a", "a"])


class TestMlPerfSubword:

  def test_decode_joins_words_and_glues_punctuation(self):
    vocab = ["'Wie_'", "'geht'", "'s_'", "'?_'", "'dir_'"]
    sub = host_ops.MlPerfSubword(vocab_lines=vocab)
    # "Wie_" + "geht" + "s_" -> fragments Wie | gehts | ... spaces only
    # between alnum fragments; "?" glues to the previous word
    assert sub.Decode([0, 1, 2, 4, 3]) == "Wie gehts dir?"

  def test_out_of_range_id_raises(self):
    sub = host_ops.MlPerfSubword(vocab_lines=["'a_'"])
    with pytest.raises(IndexError):
      sub.Decode([1])


class TestInspectUtils:

  def test_define_params_reflects_signature(self):
    def fn(alpha, beta=2.5, gamma="g"):
      return (alpha, beta, gamma)

    p = hyperparams.Params()
    inspect_utils.DefineParams(fn, p)
    assert p.alpha is None and p.beta == 2.5 and p.gamma == "g"
    p.alpha = 7
    assert inspect_utils.CallWithParams(fn, p) == (7, 2.5, "g")
    assert inspect_utils.CallWithParams(fn, p, beta=9) == (7, 9, "g")

  def test_construct_with_params_skips_self(self):
    class Thing:
      def __init__(self, x, y=3):
        self.xy = (x, y)

    p = hyperparams.Params()
    inspect_utils.DefineParams(Thing.__init__, p, bound=True)
    p.x = 1
    assert inspect_utils.ConstructWithParams(Thing, p).xy == (1, 3)

  def test_ignores_var_args(self):
    def fn(a, *args, **kwargs):
      return a

    p = hyperparams.Params()
    inspect_utils.DefineParams(fn, p)
    assert p.GetKeys() == ["a"]


class TestDecoderLib:

  def test_kv_pairs_round_trip(self, tmp_path):
    path = str(tmp_path / "decode_out.pkl")
    pairs = [("ex1", {"hyp": "a b", "score": 0.5}), ("ex2", {"hyp": "c"})]
    decoder_lib.WriteKeyValuePairs(path, pairs)
    assert decoder_lib.ReadKeyValuePairs(path) == pairs

  def test_serialize_outputs_round_trip(self):
    nmap = NestedMap(
        ids=np.arange(6, dtype=np.int32).reshape(2, 3),
        scores=np.array([0.5, -1.0], np.float32),
        nested=NestedMap(x=np.ones((2,), np.float64)))
    data = decoder_lib.SerializeOutputs(nmap)
    out = decoder_lib.DeserializeOutputs(data)
    np.testing.assert_array_equal(out.ids, nmap.ids)
    np.testing.assert_array_equal(out.nested.x, nmap.nested.x)
    np.testing.assert_allclose(out.scores, nmap.scores)


def _TwoTaskStates():
  k = jax.random.PRNGKey(0)
  ka, kb = jax.random.split(k)
  mk = lambda key: NestedMap(
      theta=NestedMap(
          enc=NestedMap(w=jax.random.normal(key, (3, 3))),
          head=NestedMap(w=jax.random.normal(jax.random.fold_in(key, 1),
                                             (3, 2)))),
      step=jnp.zeros((), jnp.int32))
  return NestedMap(a=mk(ka), b=mk(kb))


class TestSharedVariableRules:

  def test_unify_makes_shared_leaves_identical(self):
    rules = multitask_model.SharedVariableRules(
        [(r"enc\.(.*)", r"shared_enc.\1")])
    states = _TwoTaskStates()
    before_b_head = np.asarray(states.b.theta.head.w)
    states = rules.UnifyStates(states)
    np.testing.assert_array_equal(np.asarray(states.a.theta.enc.w),
                                  np.asarray(states.b.theta.enc.w))
    # non-matching paths stay private
    np.testing.assert_array_equal(np.asarray(states.b.theta.head.w),
                                  before_b_head)
    assert not np.array_equal(np.asarray(states.a.theta.head.w),
                              np.asarray(states.b.theta.head.w))

  def test_propagate_pushes_trainer_values(self):
    rules = multitask_model.SharedVariableRules(
        [(r"enc\.(.*)", r"shared_enc.\1")])
    states = rules.UnifyStates(_TwoTaskStates())
    states.a.theta.enc.w = states.a.theta.enc.w + 1.0
    states = rules.Propagate(states, "a")
    np.testing.assert_array_equal(np.asarray(states.a.theta.enc.w),
                                  np.asarray(states.b.theta.enc.w))

  def test_propagate_reties_diverged_leaves_within_trainer(self):
    # one task maps TWO of its own paths to one key; after they diverge in
    # training, Propagate must re-tie them everywhere (incl. the trainer)
    rules = multitask_model.SharedVariableRules(
        [(r"(enc|head)\.w", r"shared.w")])
    states = NestedMap(
        a=NestedMap(theta=NestedMap(enc=NestedMap(w=jnp.zeros((2,))),
                                    head=NestedMap(w=jnp.zeros((2,))))),
        b=NestedMap(theta=NestedMap(enc=NestedMap(w=jnp.ones((2,))),
                                    head=NestedMap(w=jnp.ones((2,))))))
    states = rules.UnifyStates(states)
    states.a.theta.enc.w = jnp.full((2,), 5.0)
    states.a.theta.head.w = jnp.full((2,), 9.0)  # diverged within task a
    states = rules.Propagate(states, "a")
    for leaf in (states.a.theta.enc.w, states.a.theta.head.w,
                 states.b.theta.enc.w, states.b.theta.head.w):
      np.testing.assert_array_equal(np.asarray(leaf), [5.0, 5.0])

  def test_shape_mismatch_fails_loudly(self):
    rules = multitask_model.SharedVariableRules([(r".*", "everything")])
    states = _TwoTaskStates()
    with pytest.raises(ValueError, match="pairs"):
      rules.UnifyStates(states)


class TestMultiTaskSharingEndToEnd:

  def test_shared_encoder_stays_in_sync_through_schedule(self, tmp_path):
    from lingvo_tpu.core import task_scheduler
    from lingvo_tpu.runners import program as program_lib
    from tests.test_executor_hardening import (_RegressionInput, _TaskParams)
    import lingvo_tpu.core.hyperparams as hp

    logdir = str(tmp_path)
    task_ps = {"a": _TaskParams("a"), "b": _TaskParams("b")}
    tasks, gens = {}, {}
    train_programs = hp.Params()
    for name, tp_ in task_ps.items():
      tasks[name] = tp_.Instantiate()
      tasks[name].FinalizePaths()
      train_programs.Define(
          name,
          program_lib.TrainProgram.Params().Set(
              task=tp_, logdir=logdir, name=f"train_{name}",
              steps_per_loop=3), "")
      gens[(name, "Train")] = _RegressionInput(seed=hash(name) % 100)
    sched_p = program_lib.MultiTaskProgramSchedule.Params().Set(
        task_schedule=task_scheduler.ConstantScheduler.Params().Set(
            task_probs=[("a", 0.5), ("b", 0.5)], seed=3),
        train_programs=train_programs,
        variable_renaming_rules=[(r"proj\.(.*)", r"shared_proj.\1")])
    sched = program_lib.MultiTaskProgramSchedule(sched_p, tasks=tasks,
                                                 input_generators=gens)
    state = sched.CreateTrainState(jax.random.PRNGKey(0))
    wa = np.asarray(state.tasks.GetItem("a").theta.proj.w)
    wb = np.asarray(state.tasks.GetItem("b").theta.proj.w)
    np.testing.assert_array_equal(wa, wb)  # unified at init
    for _ in range(4):
      state, _ = sched.Run(state)
      wa = np.asarray(jax.device_get(state.tasks.GetItem("a").theta.proj.w))
      wb = np.asarray(jax.device_get(state.tasks.GetItem("b").theta.proj.w))
      np.testing.assert_array_equal(wa, wb)  # in sync after every cycle
    # and training actually changed the shared weights
    w0 = np.asarray(
        sched.CreateTrainState(jax.random.PRNGKey(0)).tasks.GetItem(
            "a").theta.proj.w)
    assert not np.array_equal(wa, w0)


class TestInspectUtilsBoundCollision:

  def test_callable_param_named_bound_is_forwarded(self):
    def fn(bound, x=1):
      return (bound, x)

    p = hyperparams.Params()
    inspect_utils.DefineParams(fn, p)
    p.bound = 42
    assert inspect_utils.CallWithParams(fn, p) == (42, 1)
    assert inspect_utils.CallWithParams(fn, p, bound=7) == (7, 1)
