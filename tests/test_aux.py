"""Tests: serving export/predictor, early stop, task scheduler, cluster."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import cluster as cluster_lib
from lingvo_tpu.core import early_stop, task_scheduler
from lingvo_tpu.core.nested_map import NestedMap


class TestServingExport:

  def test_export_and_predict_roundtrip(self, tmp_path):
    from lingvo_tpu.core import base_model, layers, learner as learner_lib
    from lingvo_tpu.serving import export as export_lib

    class TinyTask(base_model.BaseTask):

      def __init__(self, params):
        super().__init__(params)
        self.CreateChild(
            "proj",
            layers.ProjectionLayer.Params().Set(input_dim=4, output_dim=2))

      def ComputePredictions(self, theta, input_batch):
        return self.proj.FProp(theta.proj, input_batch.x)

      def ComputeLoss(self, theta, predictions, input_batch):
        return NestedMap(loss=(jnp.mean(predictions), 1.0)), NestedMap()

      def Inference(self):
        example = NestedMap(x=jnp.ones((3, 4)))

        def default_fn(theta, inputs):
          return NestedMap(out=self.proj.FProp(theta.proj, inputs.x))

        return {"default": (default_fn, example)}

    task = TinyTask.Params().Set(name="tiny").Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    export_dir = str(tmp_path / "export")
    manifest = export_lib.InferenceGraphExporter.Export(task, theta,
                                                        export_dir)
    assert "default" in manifest["subgraphs"]
    assert os.path.exists(os.path.join(export_dir, "default.stablehlo"))

    predictor = export_lib.Predictor(export_dir)
    assert predictor.subgraph_names == ["default"]
    x = NestedMap(x=jnp.full((3, 4), 2.0))
    out = predictor.Run("default", x)
    expected = task.ComputePredictions(theta, x)
    np.testing.assert_allclose(np.asarray(out["out"]), np.asarray(expected),
                               rtol=1e-5)
    assert predictor.Int8Weights() is None  # float export

    # int8 deployment export: weights frozen to the dequantized int8 grid
    # + the true low-bit artifact in the bundle
    int8_dir = str(tmp_path / "export_int8")
    manifest8 = export_lib.InferenceGraphExporter.Export(
        task, theta, int8_dir, quantize_int8=True)
    assert manifest8["quantize_int8"]
    assert "proj.w" in manifest8["int8_weights"]
    p8 = export_lib.Predictor(int8_dir)
    out8 = p8.Run("default", x)
    # close to float serving (8-bit per-channel error only)
    np.testing.assert_allclose(np.asarray(out8["out"]),
                               np.asarray(expected), atol=0.05)
    art = p8.Int8Weights()
    w8 = art["proj.w"]["w_int8"]
    scale = art["proj.w"]["scale"]
    assert np.asarray(w8).dtype == np.int8
    # the artifact dequantizes to exactly what the graph serves
    from lingvo_tpu.core import quant_utils
    y_int8 = quant_utils.Int8Einsum(
        jnp.asarray(x.x), jnp.asarray(w8), jnp.asarray(scale))
    np.testing.assert_allclose(np.asarray(y_int8) +
                               np.asarray(p8._theta.proj.b),
                               np.asarray(out8["out"]), atol=0.05)


class TestEarlyStop:

  def test_best_step_and_plateau(self, tmp_path):
    mh = early_stop.MetricHistory(str(tmp_path), "eval", "loss")
    values = [(100, 5.0), (200, 4.0), (300, 3.5), (400, 3.6), (500, 3.55)]
    for s, v in values:
      mh.ConditionalAppend(s, v)
    best, last = early_stop.BestStep(mh.path)
    assert (best, last) == (300, 500)
    es = early_stop.EarlyStop(early_stop.EarlyStop.Params().Set(
        window=150, metric_history=mh))
    assert es.Stop(500)  # 500-300 > 150
    es2 = early_stop.EarlyStop(early_stop.EarlyStop.Params().Set(
        window=300, metric_history=mh))
    assert not es2.Stop(500)

  def test_tolerance(self, tmp_path):
    mh = early_stop.MetricHistory(str(tmp_path), "e", "m")
    for s, v in [(1, 1.0), (2, 0.999), (3, 0.9)]:
      mh.ConditionalAppend(s, v)
    best, _ = early_stop.BestStep(mh.path, tolerance=0.05)
    assert best == 3  # 0.999 not enough improvement; 0.9 is

  def test_maximize_mode(self, tmp_path):
    mh = early_stop.MetricHistory(str(tmp_path), "e", "bleu",
                                  minimize=False)
    for s, v in [(1, 10.0), (2, 20.0), (3, 15.0)]:
      mh.ConditionalAppend(s, v)
    best, _ = early_stop.BestStep(mh.path, minimize=False)
    assert best == 2


class TestTaskScheduler:

  def test_constant(self):
    p = task_scheduler.ConstantScheduler.Params().Set(
        task_probs=[("a", 0.9), ("b", 0.1)], seed=0)
    s = p.Instantiate()
    picks = [s.Sample(0) for _ in range(300)]
    assert picks.count("a") > 2 * picks.count("b")

  def test_exponential_anneals(self):
    p = task_scheduler.ExponentialScheduler.Params().Set(
        task_probs=[("a", 1.0), ("b", 0.0)],
        task_probs_final=[("a", 0.0), ("b", 1.0)], alpha=1e-3, seed=0)
    s = p.Instantiate()
    s.Sample(0)
    early = s.cur_probs.copy()
    s.Sample(10000)
    late = s.cur_probs
    assert early[0] > 0.9 and late[1] > 0.9

  def test_adaptive_prefers_lagging(self):
    p = task_scheduler.AdaptiveScheduler.Params().Set(
        targets=[("a", 1.0), ("b", 1.0)], seed=0)
    s = p.Instantiate()
    s.ReportMetric("a", 5.0)  # far from target
    s.ReportMetric("b", 1.0)  # at target
    s.Sample(0)
    assert s.cur_probs[0] > s.cur_probs[1]


class TestCluster:

  def test_current_and_scope(self):
    default = cluster_lib.Current()
    assert default.p.job == "executor_tpu"
    p = cluster_lib.Cluster.Params().Set(job="decoder")
    with cluster_lib.ClusterScope(cluster_lib.Cluster(p)) as c:
      assert cluster_lib.Current() is c
      assert not cluster_lib.Current().add_summary
    assert cluster_lib.Current().p.job == "executor_tpu"

  def test_set_eval(self):
    assert not cluster_lib.Current().do_eval
    with cluster_lib.SetEval():
      assert cluster_lib.Current().do_eval

  def test_topology_and_mesh(self):
    c = cluster_lib.Current()
    assert c.num_devices >= 1
    shard, num = c.InputShardParams()
    assert 0 <= shard < num
    mesh = c.MakeMesh()
    assert mesh.devices.size == c.num_devices

  def test_trial_noop(self):
    from lingvo_tpu.core import base_trial
    t = base_trial.NoOpTrial()
    assert t.OverrideModelParams({"x": 1}) == {"x": 1}
    assert not t.ReportEvalMeasure(0, {})
    assert not t.ShouldStop()
