"""Insertion framework tests (ref insertion_test.py coverage)."""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import insertion

KEY = jax.random.PRNGKey(17)


class TestSequenceUtils:

  def test_trim_last_token(self):
    x = jnp.array([[1, 2, 3, 0], [4, 5, 0, 0]])
    pads = jnp.array([[0, 0, 0, 1], [0, 0, 1, 1]], jnp.float32)
    y, ypads = insertion.SequenceTrimLastToken(x, pads)
    np.testing.assert_array_equal(np.asarray(y), [[1, 2, 0, 0], [4, 0, 0, 0]])
    np.testing.assert_array_equal(np.asarray(ypads),
                                  [[0, 0, 1, 1], [0, 1, 1, 1]])

  def test_append_token(self):
    x = jnp.array([[1, 2, 0, 0]])
    pads = jnp.array([[0, 0, 1, 1]], jnp.float32)
    y, ypads = insertion.SequenceAppendToken(x, pads, 9)
    np.testing.assert_array_equal(np.asarray(y), [[1, 2, 9, 0]])
    np.testing.assert_array_equal(np.asarray(ypads), [[0, 0, 0, 1]])

  def test_append_token_extend(self):
    x = jnp.array([[1, 2]])
    pads = jnp.zeros((1, 2), jnp.float32)
    y, ypads = insertion.SequenceAppendToken(x, pads, 9, extend=True)
    np.testing.assert_array_equal(np.asarray(y), [[1, 2, 9]])
    np.testing.assert_array_equal(np.asarray(ypads), [[0, 0, 0]])

  def test_concat(self):
    x = jnp.array([[1, 2, 0]])
    xp = jnp.array([[0, 0, 1]], jnp.float32)
    y = jnp.array([[7, 8]])
    yp = jnp.array([[0, 1]], jnp.float32)
    z, zp = insertion.SequenceConcat(x, xp, y, yp)
    np.testing.assert_array_equal(np.asarray(z), [[1, 2, 7, 0, 0]])
    np.testing.assert_array_equal(np.asarray(zp), [[0, 0, 0, 1, 1]])


class TestSymbolInsertionLayer:

  def _mk(self):
    layer = insertion.SymbolInsertionLayer.Params().Set(
        name="ins").Instantiate()
    layer.FinalizePaths()
    return layer

  def test_canvas_is_subset_preserving_order(self):
    layer = self._mk()
    x = jnp.array([[11, 12, 13, 14, 15, 16], [21, 22, 23, 0, 0, 0]])
    pads = jnp.array([[0, 0, 0, 0, 0, 0], [0, 0, 0, 1, 1, 1]], jnp.float32)
    out = layer.FProp(None, x, pads, key=KEY)
    c = np.asarray(out.canvas)
    cp = np.asarray(out.canvas_paddings)
    for b in range(2):
      valid = c[b][cp[b] == 0]
      # canvas tokens appear in x's order
      src = list(np.asarray(x)[b])
      idx = [src.index(v) for v in valid]
      assert idx == sorted(idx)
      assert len(valid) >= 1

  def test_force_last_token_in_canvas(self):
    layer = self._mk()
    x = jnp.array([[11, 12, 13, 14]])
    pads = jnp.zeros((1, 4), jnp.float32)
    for seed in range(5):
      out = layer.FProp(None, x, pads, key=jax.random.PRNGKey(seed))
      valid = np.asarray(out.canvas)[0][np.asarray(out.canvas_paddings)[0]
                                        == 0]
      assert 14 in valid  # last token always observed

  def test_targets_cover_unobserved_tokens(self):
    layer = self._mk()
    x = jnp.array([[11, 12, 13, 14, 15]])
    pads = jnp.zeros((1, 5), jnp.float32)
    out = layer.FProp(None, x, pads, eos_id=2, key=KEY)
    tt = np.asarray(out.target_tokens)[0]
    tw = np.asarray(out.target_weights)[0]
    canvas_valid = np.asarray(out.canvas)[0][
        np.asarray(out.canvas_paddings)[0] == 0]
    xs = np.asarray(x)[0]
    for i, tok in enumerate(xs):
      if tok in canvas_valid:
        assert tt[i] == 2  # observed -> eos target
      else:
        assert tt[i] == tok and tw[i] == 1.0  # real insertion target

  def test_jits(self):
    layer = self._mk()
    x = jnp.array([[11, 12, 13, 14]])
    pads = jnp.zeros((1, 4), jnp.float32)
    out = jax.jit(lambda x, p: layer.FProp(None, x, p, key=KEY))(x, pads)
    assert out.canvas.shape == (1, 4)

  def test_slots_monotonic(self):
    layer = self._mk()
    x = jnp.arange(1, 9)[None, :]
    pads = jnp.zeros((1, 8), jnp.float32)
    out = layer.FProp(None, x, pads, key=KEY)
    slots = np.asarray(out.target_slots)[0]
    assert np.all(np.diff(slots) >= 0)
