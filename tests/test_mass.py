"""MASS pretraining recipe (VERDICT r3 Missing #3): the registered config
consumes core/mass.py, masked-span reconstruction loss decreases, and
fine-tuning the MT task from MASS-pretrained weights beats cold start on
the tiny WMT fixture. Ref `lingvo/core/ops/mass_op.cc:1`,
`lingvo/tasks/mt/params/` MASS configs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401


def _build(name):
  mp = model_registry.GetParams(name, "Train")
  mp.task.input = mp.input
  task = mp.task.Instantiate()
  task.FinalizePaths()
  gen = mp.input.Instantiate()
  return task, gen


def _run(task, gen, state, steps):
  step = jax.jit(task.TrainStep)
  losses = []
  for _ in range(steps):
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    state, out = step(state, batch)
    losses.append(float(out.metrics.loss[0]))
  return state, losses


class TestMassPretraining:

  def test_mass_batch_layout(self):
    from lingvo_tpu.models.mt import input_generator as mt_input
    p = mt_input.SyntheticMassInput.Params().Set(
        batch_size=4, seq_len=12, vocab_size=32)
    gen = p.Instantiate()
    b = gen.GetPreprocessedInputBatch()
    mask_id = 31
    # encoder input has the masked span
    assert (b.src.ids == mask_id).any()
    # loss positions (non-pad target) sit exactly on the masked span
    span = (1.0 - b.tgt.paddings)
    for i in range(4):
      n = int((1.0 - b.src.paddings[i]).sum())
      src_row = b.src.ids[i, :n]
      span_row = span[i, :n]
      np.testing.assert_array_equal(src_row == mask_id, span_row == 1.0)
      # labels on the span are the original (unmasked) tokens
      assert (b.tgt.labels[i, :n][span_row == 1.0] != mask_id).all()

  def test_mass_file_input(self, tmp_path):
    """File-backed MASS: monolingual text lines through the native yielder
    + tokenizer + MassExample (the reference's GenericInput + mass_op.cc
    chain)."""
    from lingvo_tpu.core import tokenizers
    from lingvo_tpu.models.mt import input_generator as mt_input
    path = tmp_path / "mono.txt"
    with open(path, "w") as f:
      for i in range(40):
        f.write("the quick brown fox %d jumps high\n" % i)
    p = mt_input.MassFileInput.Params().Set(
        batch_size=4, max_length=48,
        tokenizer=tokenizers.AsciiTokenizer.Params(),
        file_pattern=f"text:{path}",
        bucket_upper_bound=[48], bucket_batch_limit=[4])
    gen = p.Instantiate()
    b = gen.GetPreprocessedInputBatch()
    mask_id = 75  # ascii vocab_size - 1
    assert b.src.ids.shape == b.tgt.labels.shape
    assert (b.src.ids == mask_id).any()
    # span (non-pad target) positions carry real reconstruction labels
    span = 1.0 - b.tgt.paddings
    assert (span * (b.tgt.labels != mask_id)).sum() > 0

  def test_reconstruction_loss_decreases(self):
    task, gen = _build("mt.wmt14_en_de.WmtEnDeMassPretrainTiny")
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    state, losses = _run(task, gen, state, 250)
    assert np.mean(losses[-10:]) < 0.75 * np.mean(losses[:10]), (
        losses[:10], losses[-10:])

  @pytest.mark.slow
  def test_finetune_beats_cold_start(self):
    """Pretrain MASS, warm-start the domain-matched MT task (strided
    sources, the distribution the pretraining saw — as real MASS pairs
    monolingual news pretraining with news translation): the warm run must
    beat cold start both early and at the horizon."""
    mass_task, mass_gen = _build("mt.wmt14_en_de.WmtEnDeMassPretrainTiny")
    mass_state = mass_task.CreateTrainState(jax.random.PRNGKey(0))
    mass_state, _ = _run(mass_task, mass_gen, mass_state, 250)

    mt_task, mt_gen = _build("mt.wmt14_en_de.WmtEnDeMassFinetuneTiny")
    ft_steps = 200

    # cold start
    cold_state = mt_task.CreateTrainState(jax.random.PRNGKey(1))
    _, cold_losses = _run(mt_task, mt_gen, cold_state, ft_steps)

    # warm start: same architecture, adopt the pretrained theta wholesale
    gen2 = model_registry.GetParams(
        "mt.wmt14_en_de.WmtEnDeMassFinetuneTiny",
        "Train").input.Instantiate()
    warm_state = mt_task.CreateTrainState(jax.random.PRNGKey(1))
    warm_state.theta = jax.tree_util.tree_map(
        lambda x: x, mass_state.theta)
    _, warm_losses = _run(mt_task, gen2, warm_state, ft_steps)

    # pretrained weights give a large head start...
    assert np.mean(warm_losses[:20]) < np.mean(cold_losses[:20]) - 0.5, (
        np.mean(warm_losses[:20]), np.mean(cold_losses[:20]))
    # ...and still lead at the horizon
    cold = np.mean(cold_losses[-20:])
    warm = np.mean(warm_losses[-20:])
    assert warm < cold, (warm, cold)
