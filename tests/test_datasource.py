"""DataSource + SequenceBatcher tests (ref datasource_test /
record_batcher_test semantics)."""

import os

import numpy as np

from lingvo_tpu.core import datasource
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.ops import native


def _write_lines(tmp_path, name, lines):
  p = os.path.join(str(tmp_path), name)
  with open(p, "w") as f:
    for line in lines:
      f.write(line + "\n")
  return p


class TestSimpleDataSource:

  def test_single_pattern(self, tmp_path):
    _write_lines(tmp_path, "a.txt", [f"r{i}" for i in range(20)])
    p = datasource.SimpleDataSource.Params().Set(
        file_pattern=f"text:{tmp_path}/a.txt", max_epochs=1)
    records = list(p.Instantiate())
    assert sorted(records) == sorted(f"r{i}".encode() for i in range(20))

  def test_weighted_mix(self, tmp_path):
    _write_lines(tmp_path, "a.txt", ["a"] * 400)
    _write_lines(tmp_path, "b.txt", ["b"] * 400)
    p = datasource.SimpleDataSource.Params().Set(
        file_pattern=[f"text:{tmp_path}/a.txt", f"text:{tmp_path}/b.txt"],
        weights=[3.0, 1.0])
    it = iter(p.Instantiate())
    got = [next(it) for _ in range(400)]
    na, nb = got.count(b"a"), got.count(b"b")
    assert na > 2 * nb


class TestSequenceBatcher:

  def test_bucketing_and_padding(self, tmp_path):
    # records are "n" -> sequence of length n
    _write_lines(tmp_path, "d.txt",
                 [str(n) for n in [2, 3, 7, 8, 2, 3, 7, 8, 2, 2]])
    src = datasource.SimpleDataSource.Params().Set(
        file_pattern=f"text:{tmp_path}/d.txt", max_epochs=1,
        shuffle=False, num_threads=1).Instantiate()

    def processor(rec):
      n = int(rec)
      return NestedMap(
          bucket_key=n,
          ids=np.arange(n, dtype=np.int32),
          paddings=np.zeros(n, np.float32))

    batcher = datasource.SequenceBatcher(
        src, processor, bucket_upper_bound=[4, 8], bucket_batch_limit=[4, 2])
    batches = list(batcher)
    # bucket0 (len<=4): 6 examples -> one full batch of 4 + flush of 2
    # bucket1 (len<=8): 4 examples -> two batches of 2
    shapes = sorted([tuple(b.ids.shape) for b in batches])
    assert (4, 4) in shapes
    assert (2, 8) in shapes
    for b in batches:
      assert b.ids.shape[1] in (4, 8)
      # paddings are 1.0 in padded region
      if b.ids.shape == (4, 4):
        row_lens = (1.0 - b.paddings).sum(1)
        assert row_lens.max() <= 4

  def test_oversized_dropped(self, tmp_path):
    _write_lines(tmp_path, "d.txt", ["12", "3"])
    src = datasource.SimpleDataSource.Params().Set(
        file_pattern=f"text:{tmp_path}/d.txt", max_epochs=1,
        shuffle=False, num_threads=1).Instantiate()

    def processor(rec):
      n = int(rec)
      return NestedMap(bucket_key=n, ids=np.zeros(n, np.int32))

    batches = list(
        datasource.SequenceBatcher(src, processor, [8], [4]))
    assert len(batches) == 1
    assert batches[0].ids.shape == (1, 8)  # only the len-3 record survived


class TestBatcherFlushAndStats:

  def test_flush_every_n_and_stats(self):
    """Rare buckets flush after N records (ref record_batcher.cc flush
    timeouts) and the batcher tracks stats."""
    from lingvo_tpu.core import datasource as ds
    import numpy as np
    from lingvo_tpu.core.nested_map import NestedMap

    # 20 short records, one rare long record early, one overlong record
    records = [b"s"] * 10 + [b"L"] + [b"s"] * 10 + [b"XXL"]

    def processor(rec):
      n = {b"s": 2, b"L": 8, b"XXL": 99}[rec]
      return NestedMap(ids=np.arange(n, dtype=np.int32),
                       paddings=np.zeros(n, np.float32), bucket_key=n)

    batcher = ds.SequenceBatcher(
        records, processor, bucket_upper_bound=[4, 10],
        bucket_batch_limit=[4, 4], flush_every_n=6)
    emitted = []
    long_flush_position = None
    for i, b in enumerate(batcher):
      emitted.append(b)
      if b.ids.shape[1] == 10 and long_flush_position is None:
        long_flush_position = batcher.stats["records"]
    # the lone long record was flushed partial (batch size 1) MID-STREAM
    # (after ~6 records of unrelated traffic), not by the end-of-stream
    # final flush at record 22
    long_batches = [b for b in emitted if b.ids.shape[1] == 10]
    assert long_batches and long_batches[0].ids.shape[0] == 1
    assert long_flush_position is not None and long_flush_position < 22, (
        long_flush_position)
    assert batcher.stats["dropped_too_long"] == 1
    assert batcher.stats["flushed_partial"] >= 1
    assert batcher.stats["records"] == 22
    assert batcher.stats["batches"] == len(emitted)
