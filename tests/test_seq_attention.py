"""Seq2seq attention family + LAS decoder tests (VERDICT r1 item 5; ref
attention.py:547/1015/2334/2900/3267/3608 and tasks/asr/decoder.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import beam_search as beam_search_lib
from lingvo_tpu.core import seq_attention
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(3)
B, T, DS, DQ, H = 2, 10, 12, 8, 16


def _packed(atten, theta, paddings=None):
  src = jax.random.normal(KEY, (B, T, DS))
  pads = paddings if paddings is not None else jnp.zeros((B, T))
  return atten.PackSource(theta, src, pads), src


def _make(cls, **kw):
  p = cls.Params().Set(name="att", source_dim=DS, query_dim=DQ, hidden_dim=H,
                       **kw)
  att = p.Instantiate()
  return att, att.InstantiateVariables(KEY)


class TestAttentionFamily:

  @pytest.mark.parametrize("cls", [
      seq_attention.AdditiveAttention,
      seq_attention.DotProductAttention,
      seq_attention.LocationSensitiveAttention,
      seq_attention.MonotonicAttention,
      seq_attention.GmmMonotonicAttention,
  ])
  def test_shapes_and_prob_simplex(self, cls):
    att, theta = _make(cls)
    packed, _ = _packed(att, theta)
    state = att.ZeroAttentionState(B, T)
    q = jax.random.normal(KEY, (B, DQ))
    ctx, probs, state2 = att.ComputeContextVector(theta, packed, q, state)
    assert ctx.shape == (B, DS)
    assert probs.shape == (B, T)
    np.testing.assert_allclose(np.asarray(probs.sum(-1)), 1.0, atol=1e-4)
    # state must be scan-compatible: same structure and shapes
    assert jax.tree_util.tree_structure(state) == \
        jax.tree_util.tree_structure(state2)

  @pytest.mark.parametrize("cls", [
      seq_attention.AdditiveAttention,
      seq_attention.LocationSensitiveAttention,
  ])
  def test_respects_source_paddings(self, cls):
    att, theta = _make(cls)
    pads = jnp.zeros((B, T)).at[:, 6:].set(1.0)
    packed, _ = _packed(att, theta, paddings=pads)
    state = att.ZeroAttentionState(B, T)
    q = jax.random.normal(KEY, (B, DQ))
    _, probs, _ = att.ComputeContextVector(theta, packed, q, state)
    np.testing.assert_allclose(np.asarray(probs[:, 6:]).sum(), 0.0,
                               atol=1e-6)

  def test_location_sensitive_state_advances(self):
    att, theta = _make(seq_attention.LocationSensitiveAttention)
    packed, _ = _packed(att, theta)
    state = att.ZeroAttentionState(B, T)
    q = jax.random.normal(KEY, (B, DQ))
    _, probs1, state = att.ComputeContextVector(theta, packed, q, state)
    # the conv features see probs1 now — state must carry them
    np.testing.assert_allclose(np.asarray(state.prev_probs),
                               np.asarray(probs1), atol=1e-6)
    assert float(state.cum_probs.sum()) > float(probs1.sum()) - 1e-4

  def test_monotonic_alignment_moves_forward(self):
    att, theta = _make(seq_attention.MonotonicAttention)
    packed, _ = _packed(att, theta)
    state = att.ZeroAttentionState(B, T)
    pos = jnp.arange(T, dtype=jnp.float32)[None, :]
    centers = []
    q = jax.random.normal(KEY, (B, DQ))
    for _ in range(4):
      _, probs, state = att.ComputeContextVector(theta, packed, q, state)
      centers.append(float((probs * pos).sum(-1).mean()))
    # expected position is non-decreasing (monotonicity)
    assert all(b >= a - 1e-4 for a, b in zip(centers, centers[1:])), centers

  def test_gmm_means_move_forward(self):
    att, theta = _make(seq_attention.GmmMonotonicAttention)
    packed, _ = _packed(att, theta)
    state = att.ZeroAttentionState(B, T)
    q = jax.random.normal(KEY, (B, DQ))
    _, _, s1 = att.ComputeContextVector(theta, packed, q, state)
    _, _, s2 = att.ComputeContextVector(theta, packed, q, s1)
    assert np.all(np.asarray(s2.mu) > np.asarray(s1.mu) - 1e-6)

  def test_merger_ops(self):
    ctxs = [jnp.ones((B, 4)), 3.0 * jnp.ones((B, 4))]
    for op, expect in [("mean", 2.0), ("sum", 4.0)]:
      m = seq_attention.MergerLayer.Params().Set(
          name="m", merger_op=op).Instantiate()
      out = m.FProp(NestedMap(), ctxs)
      np.testing.assert_allclose(np.asarray(out), expect, atol=1e-6)
    m = seq_attention.MergerLayer.Params().Set(
        name="m", merger_op="concat").Instantiate()
    assert m.FProp(NestedMap(), ctxs).shape == (B, 8)
    p = seq_attention.MergerLayer.Params().Set(
        name="m", merger_op="weighted_sum", num_sources=2, source_dim=4)
    m = p.Instantiate()
    theta = m.InstantiateVariables(KEY)
    out = m.FProp(theta, ctxs)
    np.testing.assert_allclose(np.asarray(out), 2.0, atol=1e-5)  # equal init

  def test_multi_source_attention(self):
    p = seq_attention.MultiSourceAttention.Params().Set(name="ms")
    p.source_atten_tpls = [
        ("audio", seq_attention.AdditiveAttention.Params().Set(
            source_dim=DS, query_dim=DQ, hidden_dim=H)),
        ("video", seq_attention.DotProductAttention.Params().Set(
            source_dim=DS, query_dim=DQ, hidden_dim=H)),
    ]
    ms = p.Instantiate()
    theta = ms.InstantiateVariables(KEY)
    sources = NestedMap(audio=jax.random.normal(KEY, (B, T, DS)),
                        video=jax.random.normal(KEY, (B, 6, DS)))
    pads = NestedMap(audio=jnp.zeros((B, T)), video=jnp.zeros((B, 6)))
    packed = ms.PackSource(theta, sources, pads)
    state = ms.ZeroAttentionState(B, {"audio": T, "video": 6})
    ctx, probs, state2 = ms.ComputeContextVector(
        theta, packed, jax.random.normal(KEY, (B, DQ)), state)
    assert ctx.shape == (B, DS)
    assert probs.shape == (B, T)


class TestCoveragePenalty:

  def test_coverage_penalty_changes_ranking_inputs(self):
    """Beam search accepts a 3-output step_fn and applies the penalty."""
    vocab, src_len = 8, 5

    def _step(states, ids):
      b = ids.shape[0]
      logits = jnp.tile(
          jnp.log(jnp.arange(1, vocab + 1, dtype=jnp.float32))[None],
          (b, 1))
      # attention always on frame 0 -> poor coverage
      atten = jnp.zeros((b, src_len)).at[:, 0].set(1.0)
      return logits, states, atten

    p = beam_search_lib.BeamSearchHelper.Params().Set(
        num_hyps_per_beam=2, target_seq_len=4, coverage_penalty=0.0)
    res0 = p.Instantiate().Search(
        1, NestedMap(x=jnp.zeros((2, 1))), _step, src_len=src_len)
    p2 = p.Copy().Set(coverage_penalty=0.5)
    res1 = p2.Instantiate().Search(
        1, NestedMap(x=jnp.zeros((2, 1))), _step, src_len=src_len,
        src_paddings=jnp.zeros((1, src_len)))
    # same ids, but scores now include the (negative) coverage term
    np.testing.assert_array_equal(np.asarray(res0.topk_ids),
                                  np.asarray(res1.topk_ids))
    assert float(res1.topk_scores[0, 0]) < float(res0.topk_scores[0, 0])


class TestLasModel:

  def test_las_trains_and_decodes_wer(self):
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("asr.librispeech.LibrispeechLasTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    step = jax.jit(task.TrainStep)
    losses = []
    for _ in range(15):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    dec = jax.jit(task.Decode)(state.theta, batch)
    assert dec.topk_ids.shape[1] == 4  # beam width
    metrics = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(
        jax.tree_util.tree_map(np.asarray, dec), metrics)
    result = task.DecodeFinalize(metrics)
    assert "wer" in result and result["num_utterances"] == 4.0
