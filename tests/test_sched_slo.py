"""SLO-aware multi-tenant scheduling: preemption by KV page spill.

Covers docs/multi_tenant_scheduling.md (ISSUE 20):
- `kv_cache.HostPageStore` bookkeeping and the allocator's spill surface
  (PrivatePages / SpillPrivate / HoleCount / FillHoles, hole-aware Free),
- `TokenBucket` per-tenant quotas with an injectable clock, and
  QuotaExceeded raised at Submit on both the engine and fleet surfaces,
- the device-free priority scheduler lifecycle: class-ordered admission,
  weighted-fair tenants, victim selection, preemption, re-admission from
  the spilled cursor, PREEMPTED cancellation,
- spill→restore is BITWISE per paged leaf (including int8 scale
  sidecars) via the engine's jitted gather/scatter,
- greedy streams are byte-identical preempted-vs-unpreempted on plain
  attention, hybrid-SSM (state rows ride along), repeat-stack, int8-KV,
  and mid-spec-cycle engines, and under scheduler_mode='fifo' vs legacy
  default,
- preempting a request that borrows shared prefix pages spills only its
  PRIVATE pages — the cache's nodes stay valid and keep hitting,
- fleet failover resubmits a PREEMPTED request like any other,
- the stats surfaces: SCHEDULER_STATS_KEYS exact match, per-class
  queue-wait histograms, router class-aware load routing.
"""

import time

import numpy as np
import pytest

from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.serving import engine as engine_lib
from lingvo_tpu.serving import fleet as fleet_lib
from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import router as router_lib
from lingvo_tpu.serving import scheduler as scheduler_lib
from lingvo_tpu.serving import spec_decode

from tests.conftest import TinyLmParams, InstantiateLm  # noqa: E402
from tests.test_serving_engine import _GreedyRef  # noqa: E402


# -- host tier + allocator spill surface (device-free) ------------------------


class TestHostPageStore:

  def test_put_pop_roundtrip_and_counters(self):
    store = kv_cache.HostPageStore()
    blocks = [np.arange(8, dtype=np.float32), np.ones(4, np.int8)]
    row = [np.full(3, 7.0, np.float32)]
    store.Put("a", [0, 2], blocks, row)
    assert "a" in store and len(store) == 1
    st = store.Stats()
    assert st["spilled_pages"] == 2 and st["entries"] == 1
    assert st["host_bytes"] == 8 * 4 + 4 + 3 * 4
    assert st["peak_host_bytes"] == st["host_bytes"]
    entry = store.Pop("a")
    assert entry.logical_idxs == [0, 2]
    np.testing.assert_array_equal(entry.blocks[0], blocks[0])
    np.testing.assert_array_equal(entry.state_row[0], row[0])
    st = store.Stats()
    assert st["restored_pages"] == 2 and st["host_bytes"] == 0
    assert st["entries"] == 0 and "a" not in store

  def test_drop_is_not_a_restore(self):
    store = kv_cache.HostPageStore()
    store.Put("a", [1], [np.zeros(4, np.float32)])
    store.Drop("a")
    st = store.Stats()
    assert st["restored_pages"] == 0 and st["host_bytes"] == 0

  def test_double_spill_asserts(self):
    store = kv_cache.HostPageStore()
    store.Put("a", [0], None)
    with pytest.raises(AssertionError):
      store.Put("a", [1], None)


class TestAllocatorSpill:

  def test_spill_private_leaves_shared_and_fills_holes_fresh(self):
    alloc = kv_cache.PageAllocator(num_pages=8, page_size=4)
    alloc.Allocate("donor", 2)
    donor_pages = alloc.PagesOf("donor")
    alloc.Share("s", donor_pages)          # borrowed: refcount 2
    alloc.Allocate("s", 2)                 # private tail
    pages = alloc.PagesOf("s")
    # 2 shared + 2 private; only data pages within 12 tokens (3 pages)
    priv = alloc.PrivatePages("s", 12)
    assert [li for li, _ in priv] == [2]
    assert alloc.SpillPrivate("s") == 2    # both private pages freed
    assert alloc.HoleCount("s") == 2
    assert alloc.PagesOf("s")[:2] == pages[:2]   # shared pages untouched
    filled = alloc.FillHoles("s")
    assert [li for li, _ in filled] == [2, 3]
    assert alloc.HoleCount("s") == 0
    for _, pg in filled:
      assert alloc.RefCount(pg) == 1

  def test_fill_holes_all_or_nothing_under_exhaustion(self):
    alloc = kv_cache.PageAllocator(num_pages=4, page_size=4)
    alloc.Allocate("a", 3)
    alloc.SpillPrivate("a")                # 3 holes, 4 free
    alloc.Allocate("b", 2)                 # squeeze: 2 free < 3 holes
    free_before = alloc.num_free
    with pytest.raises(kv_cache.OutOfPages):
      alloc.FillHoles("a")
    assert alloc.num_free == free_before   # no partial fill
    assert alloc.HoleCount("a") == 3

  def test_free_skips_holes(self):
    alloc = kv_cache.PageAllocator(num_pages=4, page_size=4)
    alloc.Allocate("a", 3)
    alloc.SpillPrivate("a")
    assert alloc.Free("a") == 0            # all holes: nothing device-side
    assert alloc.num_free == 4
    assert "a" not in alloc._owned


class TestTokenBucket:

  def test_refill_is_rate_times_elapsed(self):
    now = [0.0]
    b = scheduler_lib.TokenBucket(rate=10.0, burst=20.0,
                                  clock=lambda: now[0])
    assert b.TryTake(20) and not b.TryTake(1)
    now[0] = 1.0                           # +10 tokens
    assert b.TryTake(10) and not b.TryTake(1)
    now[0] = 100.0                         # clamped at burst
    assert b.level == pytest.approx(20.0)


# -- device-free priority scheduler lifecycle ---------------------------------


def _MkSched(**kw):
  kw.setdefault("scheduler_mode", "priority")
  alloc = kw.pop("alloc", None) or kv_cache.PageAllocator(8, 4)
  return scheduler_lib.Scheduler(kw.pop("slots", 2), alloc,
                                 table_pages=4, prefill_chunk=8, **kw), alloc


class TestPrioritySchedulerLifecycle:

  def test_preempt_park_readmit_resumes_cursor(self):
    sched, alloc = _MkSched()
    for i in range(2):
      sched.Submit(scheduler_lib.Request(i, [1, 2, 3, 4], 8, priority=0))
    low = sched.Admit()
    assert [s.id for s in low] == [0, 1]
    for s in low:                          # simulate decode progress
      s.pos, s.state, s.out = 4, scheduler_lib.SeqState.DECODE, [5, 6]
    sched.Submit(scheduler_lib.Request(9, [1] * 8, 8, priority=5))
    adm = sched.Admit()
    assert [s.id for s in adm] == [9]
    assert sched.preemptions == 1
    victim = sched.preempted[0]
    assert victim.state is scheduler_lib.SeqState.PREEMPTED
    assert victim.slot is None and victim.id in sched.host_store
    assert victim.draft_pos == 0           # draft replays on restore
    # retire the high-pri request -> victim restores at its old cursor
    hp = sched._by_id[9]
    sched.slots[hp.slot] = None
    alloc.Free(hp.id)
    hp.state, hp.slot = scheduler_lib.SeqState.FINISHED, None
    back = sched.Admit()
    assert [s.id for s in back] == [victim.id]
    assert victim.state is scheduler_lib.SeqState.DECODE
    assert victim.pos == 4 and victim.out == [5, 6]
    assert sched.restores == 1 and not sched.preempted

  def test_victim_is_lowest_class_least_progress(self):
    sched, _ = _MkSched(slots=3, alloc=kv_cache.PageAllocator(16, 4))
    for i, (pr, ntok) in enumerate([(1, 1), (0, 3), (0, 1)]):
      sched.Submit(scheduler_lib.Request(i, [1, 2, 3, 4], 8, priority=pr))
    live = sched.Admit()
    for s, n in zip(live, [1, 3, 1]):
      s.pos, s.state = 4, scheduler_lib.SeqState.DECODE
      s.out = list(range(n))
    sched.Submit(scheduler_lib.Request(9, [1] * 8, 8, priority=5))
    sched.Admit()
    # class 0 outranks class 1 as victim; fewest tokens wins in-class
    assert [s.id for s in sched.preempted] == [2]

  def test_same_class_never_preempts(self):
    sched, _ = _MkSched()
    for i in range(2):
      sched.Submit(scheduler_lib.Request(i, [1, 2, 3, 4], 8, priority=3))
    for s in sched.Admit():
      s.pos, s.state = 4, scheduler_lib.SeqState.DECODE
    sched.Submit(scheduler_lib.Request(9, [1, 2], 4, priority=3))
    assert sched.Admit() == []             # equal class: waits, no thrash
    assert sched.preemptions == 0

  def test_weighted_fair_tenants_within_class(self):
    sched, _ = _MkSched(slots=1, alloc=kv_cache.PageAllocator(32, 4),
                        tenant_weights={"heavy": 4.0})
    # all same class; 'heavy' has 4x weight -> 4x the admitted service
    ids = []
    for i, tn in enumerate(["light", "heavy", "heavy", "light", "heavy"]):
      sched.Submit(scheduler_lib.Request(i, [1, 2], 2, tenant=tn))
      ids.append((i, tn))
    order = []
    while sched.HasWork():
      adm = sched.Admit()
      if not adm:
        break
      seq = adm[0]
      order.append(seq.id)
      sched.slots[seq.slot] = None         # instant-retire to free the slot
      sched.alloc.Free(seq.id)
      seq.state, seq.slot = scheduler_lib.SeqState.FINISHED, None
    # first admit is arrival-tied (0 service each); after 'light' serves
    # once, 'heavy' (weight 4) wins repeatedly until its service/weight
    # catches up
    assert order[0] == 0 and order[1:4] == [1, 2, 4]

  def test_cancel_preempted_drops_host_entry(self):
    sched, alloc = _MkSched()
    for i in range(2):
      sched.Submit(scheduler_lib.Request(i, [1, 2, 3, 4], 8))
    for s in sched.Admit():
      s.pos, s.state = 4, scheduler_lib.SeqState.DECODE
    sched.Submit(scheduler_lib.Request(9, [1] * 8, 8, priority=5))
    sched.Admit()
    victim_id = sched.preempted[0].id
    assert sched.Cancel(victim_id)
    assert victim_id not in sched.host_store
    assert not sched.preempted
    # refs on any pages are gone: cancel again is a no-op
    assert not sched.Cancel(victim_id)

  def test_quota_rejects_at_submit(self):
    now = [0.0]
    sched, _ = _MkSched(tenant_quotas={"t": (1.0, 10.0)}, clock=lambda: now[0])
    sched.Submit(scheduler_lib.Request(0, [1, 2], 6, tenant="t"))
    with pytest.raises(scheduler_lib.QuotaExceeded):
      sched.Submit(scheduler_lib.Request(1, [1, 2], 6, tenant="t"))
    assert sched.quota_rejections == 1
    now[0] = 8.0                           # rate 1/s refills the bucket
    sched.Submit(scheduler_lib.Request(2, [1, 2], 6, tenant="t"))
    # untracked tenants are never charged
    sched.Submit(scheduler_lib.Request(3, [1, 2], 6, tenant="other"))

  def test_stats_key_set_matches_schema(self):
    sched, _ = _MkSched()
    st = sched.Stats()
    assert set(st) == observe_schema.SCHEDULER_STATS_KEYS
    assert st["scheduler_mode"] == "priority"
    fifo = scheduler_lib.Scheduler(2, kv_cache.PageAllocator(8, 4), 4, 8)
    st = fifo.Stats()
    assert set(st) == observe_schema.SCHEDULER_STATS_KEYS
    assert st["scheduler_mode"] == "fifo" and st["preemptions"] == 0


# -- engine: bitwise spill/restore + byte-identical streams -------------------


def _MkEngine(task, theta, **kw):
  kw.setdefault("page_size", 4)
  kw.setdefault("num_pages", 10)
  kw.setdefault("max_batch", 2)
  kw.setdefault("max_seq_len", 32)
  kw.setdefault("trace", False)
  return engine_lib.ServingLoop(task, theta, **kw)


def _PlayWithProbe(task, theta, mode, probe, bulk_new=12, pre_steps=4, **kw):
  """Two saturating low-pri requests; optionally a high-pri probe after
  pre_steps steps (driven inline — deterministic preemption point)."""
  eng = _MkEngine(task, theta, scheduler_mode=mode, **kw)
  h1 = eng.Submit([1, 2, 3, 4], bulk_new, eos_id=None)
  h2 = eng.Submit([5, 6, 7, 8], bulk_new, eos_id=None)
  for _ in range(pre_steps):
    eng.StepOnce()
  hp = (eng.Submit([9, 10, 11, 12], 6, eos_id=None, priority=5)
        if probe else None)
  while eng.sched.HasWork():
    eng.StepOnce()
  out = [h1.Result(0), h2.Result(0)]
  sched_stats = eng.Stats()["scheduler"]
  probe_out = hp.Result(0) if hp else None
  return out, probe_out, sched_stats, eng


class TestPreemptionByteIdentity:

  def test_attention_stack(self, tiny_lm):
    task, theta = tiny_lm
    base, _, st0, _ = _PlayWithProbe(task, theta, "fifo", False)
    assert st0["preemptions"] == 0
    pre, probe_out, st, _ = _PlayWithProbe(task, theta, "priority", True)
    assert st["preemptions"] >= 1 and st["restores"] >= 1
    assert st["spilled_pages"] >= 1 and st["restored_pages"] >= 1
    assert base == pre                     # preemption never shifts a token
    assert probe_out == _GreedyRef(task, theta, [9, 10, 11, 12], 6)
    # fifo mode == the engine's legacy default mode, byte for byte
    legacy, _, _, _ = _PlayWithProbe(task, theta, "fifo", False)
    assert legacy == base

  def test_hybrid_ssm_state_rows_ride_along(self, hybrid_lm):
    task, theta = hybrid_lm
    base, _, _, _ = _PlayWithProbe(task, theta, "fifo", False)
    pre, _, st, _ = _PlayWithProbe(task, theta, "priority", True)
    assert st["preemptions"] >= 1
    assert base == pre

  @pytest.mark.slow
  def test_repeat_stack_leaves(self):
    task, theta = InstantiateLm(TinyLmParams(every_n=2, use_repeat=True))
    base, _, _, _ = _PlayWithProbe(task, theta, "fifo", False)
    pre, _, st, _ = _PlayWithProbe(task, theta, "priority", True)
    assert st["preemptions"] >= 1
    assert base == pre

  @pytest.mark.slow
  def test_int8_kv_scale_sidecars(self, tiny_lm):
    task, theta = tiny_lm
    base, _, _, _ = _PlayWithProbe(task, theta, "fifo", False,
                                   kv_cache_dtype="int8")
    pre, _, st, _ = _PlayWithProbe(task, theta, "priority", True,
                                   kv_cache_dtype="int8")
    assert st["preemptions"] >= 1
    assert base == pre

  def test_preempt_mid_spec_cycle(self, tiny_lm):
    task, theta = tiny_lm
    spec = lambda: spec_decode.SelfDraft(k=3, num_layers=1)  # noqa: E731
    kw = dict(bulk_new=20, pre_steps=2, num_pages=16)
    base, _, _, _ = _PlayWithProbe(task, theta, "fifo", False, spec=spec(),
                                   **kw)
    pre, _, st, _ = _PlayWithProbe(task, theta, "priority", True,
                                   spec=spec(), **kw)
    assert st["preemptions"] >= 1
    assert base == pre                     # rollback cursors survive spill

  def test_spill_restore_bitwise_per_leaf(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MkEngine(task, theta, scheduler_mode="priority")
    eng.Submit([1, 2, 3, 4, 5, 6], 4, eos_id=None)
    for _ in range(3):
      eng.StepOnce()
    pages = eng.alloc.PagesOf(1)
    blocks = eng._SpillPages(pages)
    assert blocks and all(isinstance(b, np.ndarray) for b in blocks)
    eng._RestorePages(pages, blocks)       # scatter back in place
    again = eng._SpillPages(pages)
    for a, b in zip(blocks, again):
      np.testing.assert_array_equal(a, b)  # bitwise round trip

  def test_state_row_bitwise_roundtrip(self, hybrid_lm):
    task, theta = hybrid_lm
    eng = _MkEngine(task, theta, scheduler_mode="priority")
    eng.Submit([1, 2, 3, 4], 4, eos_id=None)
    for _ in range(3):
      eng.StepOnce()
    rows = eng._SpillStateRow(0)
    assert rows                            # hybrid stack has state leaves
    eng._RestoreStateRow(1, rows)          # land in a DIFFERENT slot
    moved = eng._SpillStateRow(1)
    for a, b in zip(rows, moved):
      np.testing.assert_array_equal(a, b)


class TestSharedPrefixPreemption:

  def test_only_private_pages_spill_cache_stays_valid(self, tiny_lm):
    task, theta = tiny_lm
    sys_prompt = [3, 1, 4, 1, 5, 9, 2, 6]   # two full pages
    eng = _MkEngine(task, theta, scheduler_mode="priority",
                    prefix_cache=True, num_pages=12)
    # warm the cache with the shared prefix
    h0 = eng.Submit(list(sys_prompt), 4, eos_id=None)
    while eng.sched.HasWork():
      eng.StepOnce()
    h0.Result(0)
    cached_before = eng.prefix_cache.Stats()["cached_pages"]
    assert cached_before >= 2
    # two borrowers fill both slots
    h1 = eng.Submit(list(sys_prompt) + [7], 8, eos_id=None)
    h2 = eng.Submit(list(sys_prompt) + [8], 8, eos_id=None)
    for _ in range(4):
      eng.StepOnce()
    assert eng.Stats()["prefix_hit_tokens"] >= 2 * len(sys_prompt)
    hp = eng.Submit([9, 10, 11], 4, eos_id=None, priority=5)
    while eng.sched.HasWork():
      eng.StepOnce()
    st = eng.Stats()["scheduler"]
    assert st["preemptions"] >= 1
    # shared pages never spilled: the victim kept its refs, so every
    # cached page stayed device-resident and the cache node count held
    assert eng.prefix_cache.Stats()["cached_pages"] == cached_before
    # streams match the dense reference (restored KV bitwise)
    assert h1.Result(0) == _GreedyRef(task, theta, sys_prompt + [7], 8)
    assert h2.Result(0) == _GreedyRef(task, theta, sys_prompt + [8], 8)
    hp.Result(0)


class TestEngineQuotaAndHistograms:

  def test_engine_submit_quota_raises_before_handle(self, tiny_lm):
    task, theta = tiny_lm
    eng = _MkEngine(task, theta, scheduler_mode="priority",
                    tenant_quotas={"t": (0.0, 20.0)})
    eng.Submit([1, 2], 8, tenant="t")
    with pytest.raises(scheduler_lib.QuotaExceeded):
      eng.Submit([1, 2], 16, tenant="t")
    assert len(eng._handles) == 1          # no orphan handle created
    assert eng.Stats()["scheduler"]["quota_rejections"] == 1

  def test_per_class_queue_wait_histograms(self, tiny_lm):
    task, theta = tiny_lm
    _, _, _, eng = _PlayWithProbe(task, theta, "priority", True)
    snap = eng.metrics.Snapshot()
    assert any(k.startswith("serving/queue_wait_s_c0") for k in snap), (
        sorted(k for k in snap if "queue_wait" in k))
    assert any(k.startswith("serving/queue_wait_s_c5") for k in snap)
    # the router's class-aware load key flattens out of the scheduler
    # section for every engine (fifo ones just always read 0)
    assert "scheduler/queue_depth_high" in snap


# -- router + fleet threading -------------------------------------------------


class TestRouterPriorityLoad:

  def test_priority_routes_on_class_aware_load(self):
    r = router_lib.PrefixRouter(4, ["a", "b"], pin_sessions=False)
    snaps = {
        "a": {"scheduler/queue_depth": 0, "scheduler/queue_depth_high": 3},
        "b": {"scheduler/queue_depth": 5, "scheduler/queue_depth_high": 0},
    }
    # default class reads raw queue depth: a (0) beats b (5)
    assert r.Route([1, 2], snaps) == "a"
    # priority class reads parked-above-default work: b (0) beats a (3)
    assert r.Route([1, 2], snaps, priority=5) == "b"
    st = r.Stats()
    assert set(st) == observe_schema.ROUTER_STATS_KEYS
    assert st["priority_routed"] == 1

  def test_missing_key_falls_back_to_load_keys(self):
    r = router_lib.PrefixRouter(4, ["a", "b"], pin_sessions=False)
    snaps = {"a": {"scheduler/queue_depth": 5},
             "b": {"scheduler/queue_depth": 0}}
    assert r.Route([1, 2], snaps, priority=5) == "b"


class TestFleetPreemption:

  def test_failover_resubmits_preempted_request(self, tiny_lm):
    task, theta = tiny_lm
    mk = lambda: _MkEngine(task, theta, max_batch=1,  # noqa: E731
                           scheduler_mode="priority")
    fl = fleet_lib.ServingFleet({"r0": mk(), "r1": mk()},
                                policy="round_robin").Start()
    try:
      hb0 = fl.Submit([1, 2, 3, 4], 12)                    # -> r0
      hb1 = fl.Submit([5, 6, 7, 8], 12)                    # -> r1
      hp = fl.Submit([9, 10, 11, 12], 12, priority=5)      # -> r0: preempts
      r0 = fl.Engine("r0")
      deadline = time.monotonic() + 60
      while time.monotonic() < deadline:
        if r0.Stats()["scheduler"]["preemptions"] >= 1:
          break
        time.sleep(0.005)
      else:
        raise TimeoutError("r0 never preempted")
      fl.KillReplica("r0")   # hb0 (or hp) may be PREEMPTED right now
      assert hb0.Result(timeout=120) == _GreedyRef(task, theta,
                                                   [1, 2, 3, 4], 12)
      assert hb1.Result(timeout=120) == _GreedyRef(task, theta,
                                                   [5, 6, 7, 8], 12)
      assert hp.Result(timeout=120) == _GreedyRef(task, theta,
                                                  [9, 10, 11, 12], 12)
      st = fl.Stats()
      assert set(st) == observe_schema.FLEET_STATS_KEYS
      assert st["failovers"] == 1 and st["resubmitted_requests"] >= 1
      assert st["priority_requests"] == 1
    finally:
      fl.Stop()

  def test_fleet_quota_counts_and_propagates(self, tiny_lm):
    task, theta = tiny_lm
    fl = fleet_lib.ServingFleet(
        {"r0": _MkEngine(task, theta, scheduler_mode="priority",
                         tenant_quotas={"t": (0.0, 20.0)})}).Start()
    try:
      h = fl.Submit([1, 2], 8, tenant="t")
      with pytest.raises(scheduler_lib.QuotaExceeded):
        fl.Submit([1, 2], 16, tenant="t")
      assert fl.Stats()["quota_rejections"] == 1
      h.Result(timeout=120)
    finally:
      fl.Stop()


# -- multi-tenant soak (slow) -------------------------------------------------


@pytest.mark.slow
class TestMultiTenantSoak:

  def test_saturated_mixed_stream_byte_identical(self, tiny_lm):
    task, theta = tiny_lm
    rng = np.random.RandomState(0)
    reqs = []
    for i in range(14):
      prompt = [int(t) for t in rng.randint(1, 60, rng.randint(2, 8))]
      pr = 5 if i % 5 == 4 else 0
      # vip probes arrive mid-flight (after `at` engine steps) so the
      # priority arms must preempt running bulk work, not just reorder
      at = 3 + 2 * (i // 5) if pr else 0
      reqs.append((at, prompt, int(rng.randint(4, 12)), pr,
                   "vip" if pr else "bulk"))

    def _Play(mode):
      eng = _MkEngine(task, theta, scheduler_mode=mode, max_batch=2)
      hs, step, pending = [None] * len(reqs), 0, sorted(
          range(len(reqs)), key=lambda i: reqs[i][0])
      while pending or eng.sched.HasWork():
        while pending and reqs[pending[0]][0] <= step:
          i = pending.pop(0)
          _at, p, n, pr, tn = reqs[i]
          hs[i] = eng.Submit(list(p), n, eos_id=None, priority=pr, tenant=tn)
        if eng.sched.HasWork():
          eng.StepOnce()
        step += 1
      out = [h.Result(0) for h in hs]
      return out, eng.Stats()["scheduler"]

    fifo, _ = _Play("fifo")
    prio, st = _Play("priority")
    assert fifo == prio
    assert st["preemptions"] >= 1          # the mix actually preempted
    for (_at, p, n, _pr, _tn), toks in zip(reqs, fifo):
      assert toks == _GreedyRef(task, theta, p, n)
