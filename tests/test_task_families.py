"""Punctuator, milan, car task families (VERDICT r1 coverage rows 70/73/75)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401


def _train(name, steps, overrides=None):
  mp = model_registry.GetParams(name, "Train")
  mp.task.input = mp.input
  if overrides:
    overrides(mp)
  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  gen = mp.input.Instantiate()
  step = jax.jit(task.TrainStep)
  losses = []
  out = None
  for _ in range(steps):
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    state, out = step(state, batch)
    losses.append(float(out.metrics.loss[0]))
  return task, state, losses, out, gen


class TestPunctuator:

  def test_trains_and_decodes(self):
    task, state, losses, _, gen = _train(
        "punctuator.codelab.TransformerModelTiny", 100)
    assert losses[-1] < 0.9 * losses[0], (losses[0], losses[-1])
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    dec = jax.jit(task.Decode)(state.theta, batch)
    assert dec.topk_ids.shape[0] == batch.src.ids.shape[0]


class TestMilan:

  def test_real_towers_retrieval_learns(self):
    """Conv image tower + transformer text tower over sprite images
    (VERDICT r3 Missing #1): retrieval on HELD-OUT pairs, so the towers
    must actually encode pixels and tokens, not memorize."""
    task, state, losses, out, _ = _train(
        "milan.dual_encoder.MilanImageText", 80)
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    assert float(out.metrics.recall_at_1[0]) > 0.5
    # held-out eval distribution (different seed)
    mp = model_registry.GetParams("milan.dual_encoder.MilanImageText",
                                  "Test")
    test_gen = mp.input.Instantiate()
    batch = test_gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    dec = jax.jit(task.Decode)(state.theta, batch)
    m = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(jax.tree_util.tree_map(np.asarray, dec), m)
    res = task.DecodeFinalize(m)
    assert res["recall_at_1"] > 0.5, res

  def test_file_input_reads_paired_records(self, tmp_path):
    """MilanFileInput over the native yielder: JSON-lines records ->
    batches the real-tower task consumes."""
    import json
    from lingvo_tpu.models.milan import input_generator as mi
    rng = np.random.RandomState(0)
    path = tmp_path / "pairs.jsonl"
    with open(path, "w") as f:
      for i in range(32):
        img = rng.randn(8, 8, 3).round(3)
        f.write(json.dumps({
            "image": img.reshape(-1).tolist(), "image_shape": [8, 8, 3],
            "text_ids": [int(i % 5) + 1, int(i % 7) + 1]}) + "\n")
      f.write("not json\n")              # malformed: must be dropped
      f.write(json.dumps([1, 2]) + "\n")  # wrong type: dropped
    p = mi.MilanFileInput.Params().Set(
        batch_size=4, image_size=8, text_len=4,
        file_pattern=f"text:{path}")
    gen = p.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.image.shape == (4, 8, 8, 3)
    assert batch.text_ids.shape == (4, 4)
    assert batch.text_paddings.shape == (4, 4)
    assert (batch.text_ids >= 0).all()

  def test_padded_flush_rows_excluded(self):
    """Padded rows in a finite-epoch flush batch (all-padding text) must
    not serve as contrastive examples or count in recall."""
    from lingvo_tpu.core.nested_map import NestedMap
    mp = model_registry.GetParams("milan.dual_encoder.MilanImageText",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    b = batch.image.shape[0]
    # fabricate a flush batch: last half is padding rows
    half = b // 2
    batch.image[half:] = 0.0
    batch.text_ids[half:] = 0
    batch.text_paddings[half:] = 1.0
    jbatch = batch.Transform(jnp.asarray)
    preds = jax.jit(task.ComputePredictions)(state.theta, jbatch)
    assert np.allclose(np.asarray(preds.example_weights[:half]), 1.0)
    assert np.allclose(np.asarray(preds.example_weights[half:]), 0.0)
    metrics, _ = task.ComputeLoss(state.theta, preds, jbatch)
    assert float(metrics.loss[1]) == half  # weight counts real rows only
    assert np.isfinite(float(metrics.loss[0]))
    # decode recall averages over real rows only
    dec = jax.jit(task.Decode)(state.theta, jbatch)
    m = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(jax.tree_util.tree_map(np.asarray, dec), m)
    assert m["recall_at_1"].total_weight == half

  def test_contrastive_retrieval_learns(self):
    task, state, losses, out, gen = _train("milan.dual_encoder.MilanDualEncoder", 60)
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    # in-batch retrieval recall improves well past chance (1/64)
    assert float(out.metrics.recall_at_1[0]) > 0.2
    # decode path: recall metrics over the similarity matrix
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    dec = jax.jit(task.Decode)(state.theta, batch)
    m = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(
        jax.tree_util.tree_map(np.asarray, dec), m)
    res = task.DecodeFinalize(m)
    assert res["recall_at_1"] > 0.2


class TestCar:

  def test_detector_trains_and_decodes(self):
    task, state, losses, out, gen = _train("car.kitti.PointPillarsCar", 50)
    assert losses[-1] < 0.8 * losses[0], (losses[0], losses[-1])
    assert "cls_loss" in out.metrics and "reg_loss" in out.metrics
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    dec = jax.jit(task.Decode)(state.theta, batch)
    assert dec.boxes.shape[-1] == 7
    m = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(
        jax.tree_util.tree_map(np.asarray, dec), m)
    res = task.DecodeFinalize(m)
    assert "cell_precision" in res and "cell_recall" in res

  def test_featurizer_ignores_padded_points(self):
    from lingvo_tpu.models.car import pillars
    p = pillars.PillarFeaturizer.Params().Set(
        name="feat", point_dim=4, feature_dim=8)
    layer = p.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(jax.random.PRNGKey(0))
    pts = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 4, 4))
    pads = jnp.zeros((1, 2, 4)).at[0, 0, 2:].set(1.0)
    out1 = layer.FProp(theta, pts, pads)
    pts2 = pts.at[0, 0, 2:].set(99.0)  # only padded points changed
    out2 = layer.FProp(theta, pts2, pads)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                               atol=1e-6)
    # fully-padded pillar pools to exactly zero
    pads3 = jnp.ones((1, 2, 4))
    out3 = layer.FProp(theta, pts, pads3)
    np.testing.assert_allclose(np.asarray(out3), 0.0, atol=1e-6)


class TestRotatedIouAp:

  def test_rotated_iou_exact_cases(self):
    from lingvo_tpu.models.car import ap_metric as ap
    assert abs(ap.RotatedIou([0, 0, 2, 2, 0], [0, 0, 2, 2, 0]) - 1.0) < 1e-6
    assert ap.RotatedIou([0, 0, 2, 2, 0], [10, 10, 2, 2, 0]) == 0.0
    # half-shifted axis-aligned squares: inter 2, union 6
    assert abs(ap.RotatedIou([0, 0, 2, 2, 0], [1, 0, 2, 2, 0]) - 1/3) < 1e-6
    # 45-degree rotated square vs itself: octagon intersection, known value
    iou45 = ap.RotatedIou([0, 0, 2, 2, 0], [0, 0, 2, 2, np.pi / 4])
    inter = 8 * (2 ** 0.5) - 8
    expect = inter / (8 - inter)
    assert abs(iou45 - expect) < 1e-3

  def test_ap_metric_matching(self):
    from lingvo_tpu.models.car import ap_metric as ap
    m = ap.ApMetric(iou_threshold=0.5)
    gt = np.array([[0, 0, 2, 2, 0], [5, 5, 2, 2, 0]])
    preds = np.array([[0.1, 0, 2, 2, 0], [5, 5.1, 2, 2, 0], [9, 9, 2, 2, 0]])
    m.Update(preds, np.array([0.9, 0.8, 0.7]), gt)
    assert m.value == 1.0  # both gt found before the false positive
    # a second scene with a missed gt drags AP below 1
    m.Update(np.zeros((0, 5)), np.zeros((0,)), np.array([[3, 3, 2, 2, 0]]))
    assert m.value < 1.0

  def test_car_decode_reports_ap(self):
    task, state, _, _, gen = _train("car.kitti.PointPillarsCar", 30)
    import jax
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    dec = jax.jit(task.Decode)(state.theta, batch)
    m = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(jax.tree_util.tree_map(np.asarray, dec), m)
    res = task.DecodeFinalize(m)
    assert "ap" in res and 0.0 <= res["ap"] <= 1.0
