"""Sampled softmax, EinsumEmbedding, StackingOverTime, ConvLSTM,
FRNNWithAttention, new datasources, MASS (VERDICT r1 P-row closures)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import datasource, layers as layers_lib, mass, py_utils
from lingvo_tpu.core import rnn_cell, rnn_layers, seq_attention
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(23)


class TestSampledSoftmax:

  def _make(self, num_sampled=16, vocab=64, dim=8):
    p = layers_lib.SampledSoftmax.Params().Set(
        name="ss", input_dim=dim, num_classes=vocab,
        num_sampled=num_sampled)
    layer = p.Instantiate()
    layer.FinalizePaths()
    return layer, layer.InstantiateVariables(KEY)

  def test_eval_falls_back_to_full_softmax(self):
    layer, theta = self._make()
    x = jax.random.normal(KEY, (4, 8))
    ids = jnp.asarray([1, 2, 3, 4])
    # no step seed -> full softmax; must equal explicit full xent
    xent = layer.XentLossFromInputs(theta, x, ids)
    full = layers_lib.XentLossFromLogits(
        layer.Logits(theta, x).astype(jnp.float32), 64,
        class_ids=ids).per_example_xent
    np.testing.assert_allclose(np.asarray(xent), np.asarray(full),
                               atol=1e-5)

  def test_sampled_loss_tracks_true_logit(self):
    """Raising the true class's weight must lower the sampled xent (the
    estimator optimizes the real objective); absolute values differ from
    the full xent by construction (negatives are a sampled subset)."""
    layer, theta = self._make(num_sampled=32, vocab=512)
    x = jax.random.normal(KEY, (8, 8))
    ids = jnp.asarray([7] * 8)
    with py_utils.StepSeedContext(jax.random.PRNGKey(5)):
      base = float(layer.XentLossFromInputs(theta, x, ids).mean())
    theta2 = theta.DeepCopy()
    theta2.w = theta2.w.at[7].set(theta2.w[7] + 0.5 * x.mean(0))
    with py_utils.StepSeedContext(jax.random.PRNGKey(5)):
      better = float(layer.XentLossFromInputs(theta2, x, ids).mean())
    assert better < base, (base, better)

  def test_training_signal_reduces_sampled_loss(self):
    layer, theta = self._make(num_sampled=32, vocab=64)
    x = jax.random.normal(KEY, (32, 8))
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, 32))

    def loss_fn(theta, key):
      with py_utils.StepSeedContext(key):
        return jnp.mean(layer.XentLossFromInputs(theta, x, ids))

    import optax
    opt = optax.adam(1e-2)
    opt_state = opt.init(theta)
    losses = []
    for i in range(100):
      loss, grads = jax.value_and_grad(loss_fn)(theta, jax.random.PRNGKey(i))
      updates, opt_state = opt.update(grads, opt_state)
      theta = optax.apply_updates(theta, updates)
      losses.append(float(loss))
    # full-softmax loss must ALSO have dropped (the estimate trains the
    # real objective, not just the sampled one)
    full = layers_lib.XentLossFromLogits(
        layer.Logits(theta, x).astype(jnp.float32), 64,
        class_ids=ids).per_example_xent
    # started near log(64) ~ 4.16; sampled training must have cut it deeply
    assert float(full.mean()) < 1.5, float(full.mean())

  def test_lm_sampled_training_materializes_no_logits(self):
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    mp.task.softmax_num_sampled = 32
    task = mp.task.Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()
    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    with py_utils.StepSeedContext(jax.random.PRNGKey(1)):
      preds = task.ComputePredictions(theta, batch)
    assert "hidden" in preds and "logits" not in preds
    # eval path still yields full logits
    with py_utils.EvalContext():
      preds_eval = task.ComputePredictions(theta, batch)
    assert preds_eval.logits.shape[-1] == mp.task.vocab_size
    # one jitted train step end to end
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    state, out = jax.jit(task.TrainStep)(state, batch)
    assert np.isfinite(float(out.metrics.loss[0]))


class TestEinsumEmbedding:

  def test_matches_gather_embedding(self):
    p = layers_lib.EinsumEmbeddingLayer.Params().Set(
        name="emb", vocab_size=16, embedding_dim=8)
    layer = p.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    ids = jnp.asarray([[0, 5], [15, 3]])
    out = layer.EmbLookup(theta, ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(theta.emb)[np.asarray(ids)],
                               atol=1e-6)


class TestStackingOverTime:

  def test_stack_and_subsample(self):
    p = layers_lib.StackingOverTime.Params().Set(
        name="stack", left_context=0, right_context=2, stride=3)
    layer = p.Instantiate()
    x = jnp.arange(12, dtype=jnp.float32).reshape(1, 12, 1)
    pads = jnp.zeros((1, 12)).at[0, 9:].set(1.0)
    out, opads = layer.FProp(NestedMap(), x, pads)
    assert out.shape == (1, 4, 3)
    # frame 0 stacks inputs [0, 1, 2]
    np.testing.assert_allclose(np.asarray(out[0, 0]), [0.0, 1.0, 2.0])
    # frame 1 starts at t=3
    np.testing.assert_allclose(np.asarray(out[0, 1]), [3.0, 4.0, 5.0])
    # output padding follows the center (start) frame
    np.testing.assert_allclose(np.asarray(opads[0]), [0, 0, 0, 1])


class TestConvLstm:

  def test_shapes_and_padding(self):
    p = rnn_cell.ConvLSTMCell.Params().Set(
        name="clstm", inputs_shape=[4, 4, 3], cell_shape=[4, 4, 8])
    cell = p.Instantiate()
    cell.FinalizePaths()
    theta = cell.InstantiateVariables(KEY)
    st = cell.InitState(2)
    x = jax.random.normal(KEY, (2, 4, 4, 3))
    st1 = cell.FProp(theta, st, x)
    assert cell.GetOutput(st1).shape == (2, 4, 4, 8)
    # a padded step must not move the state
    st2 = cell.FProp(theta, st1, x, padding=jnp.asarray([1.0, 0.0]))
    np.testing.assert_allclose(np.asarray(st2.m[0]), np.asarray(st1.m[0]),
                               atol=1e-6)
    assert not np.allclose(np.asarray(st2.m[1]), np.asarray(st1.m[1]))


class TestFrnnWithAttention:

  def test_runs_and_attends(self):
    fp = rnn_layers.FRNNWithAttention.Params().Set(name="fa")
    fp.cell = rnn_cell.LSTMCellSimple.Params().Set(
        num_input_nodes=8 + 12, num_output_nodes=6)
    fp.attention = seq_attention.AdditiveAttention.Params().Set(
        source_dim=12, query_dim=6, hidden_dim=8)
    layer = fp.Instantiate()
    layer.FinalizePaths()
    theta = layer.InstantiateVariables(KEY)
    src = jax.random.normal(KEY, (2, 5, 12))
    srcp = jnp.zeros((2, 5)).at[1, 3:].set(1.0)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 7, 8))
    outs, ctxs, final = layer.FProp(theta, src, srcp, x)
    assert outs.shape == (2, 7, 6) and ctxs.shape == (2, 7, 12)
    # perturbing a padded source frame must not change anything
    src2 = src.at[1, 4].set(50.0)
    outs2, _, _ = layer.FProp(theta, src2, srcp, x)
    np.testing.assert_allclose(np.asarray(outs), np.asarray(outs2),
                               atol=1e-5)


class TestDataSources:

  def test_cross_batch_mixing(self, tmp_path):
    for name, tok in [("a", "aa"), ("b", "bb")]:
      (tmp_path / f"{name}.txt").write_text("\n".join([tok] * 50) + "\n")
    p = datasource.CrossBatchMixingDataSource.Params().Set(
        weights=[0.5, 0.5], seed=7)
    for name in ("a", "b"):
      p.sub.append(datasource.SimpleDataSource.Params().Set(
          file_pattern=f"text:{tmp_path}/{name}.txt", shuffle=False,
          max_epochs=1, num_threads=1))
    recs = [r.decode() for r in p.Instantiate()]
    assert len(recs) == 100
    # both sources appear, interleaved within the stream
    first_half = recs[:50]
    assert "aa" in first_half and "bb" in first_half

  def test_prefixed_datasource(self, tmp_path):
    sub = tmp_path / "data"
    sub.mkdir()
    (sub / "x.txt").write_text("hello\n")
    p = datasource.PrefixedDataSource.Params().Set(
        file_pattern_prefix=str(tmp_path),
        sub=datasource.SimpleDataSource.Params().Set(
            file_pattern="text:data/x.txt", shuffle=False, max_epochs=1,
            num_threads=1))
    recs = list(p.Instantiate())
    assert recs == [b"hello"]

  def test_tfds_source_raises_without_package(self):
    p = datasource.TfdsDataSource.Params().Set(dataset="lm1b")
    try:
      import tensorflow_datasets  # noqa: F401
      pytest.skip("tfds installed; adapter exercised in real runs")
    except ImportError:
      with pytest.raises(ImportError, match="tensorflow_datasets"):
        next(iter(p.Instantiate()))


class TestMass:

  def test_mass_example_structure(self):
    ids = np.arange(10) + 5
    ex = mass.MassExample(ids, mask_id=3, seed=1, mask_ratio=0.5)
    s, e = ex.span
    assert e - s == 5
    # source masks exactly the span
    np.testing.assert_array_equal(ex.src.ids[s:e], 3)
    np.testing.assert_array_equal(ex.src.ids[:s], ids[:s])
    # labels are the original sequence; weights mark the span
    np.testing.assert_array_equal(ex.tgt.labels, ids)
    assert ex.tgt.weights.sum() == 5
    # decoder input inside the span is the shifted original
    np.testing.assert_array_equal(ex.tgt.ids[s + 1:e], ids[s:e - 1])
    assert ex.tgt.ids[s] == 3
    # deterministic per seed
    ex2 = mass.MassExample(ids, mask_id=3, seed=1, mask_ratio=0.5)
    assert ex2.span == ex.span
