"""Compile-level assertions on the round-5 MoE dispatch program: joint
('data','expert') group sharding, shard_map all-to-all engagement, no
collective-permute resharding storm, named remat boundaries
(docs/moe_collectives.md)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.parallel import gshard, mesh as mesh_lib


def _CollectiveDefs(hlo: str):
  """Defining-instruction opcode counts (the attribution parser's rule)."""
  counts = {}
  inst = re.compile(
      r"[}\])]\s+(all-to-all|all-gather|all-reduce|reduce-scatter|"
      r"collective-permute)(-start|-done)?\(")
  for line in hlo.splitlines():
    if not re.match(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=", line):
      continue
    m = inst.search(line)
    if m and m.group(2) != "-done":
      counts[m.group(1)] = counts.get(m.group(1), 0) + 1
  return counts


def _MoeLayer(num_experts=4, num_groups=0, **kw):
  p = gshard.MoEFeedForwardLayer.Params().Set(
      name="moe", input_dim=16, hidden_dim=32, num_experts=num_experts,
      num_groups=num_groups, **kw)
  layer = p.Instantiate()
  theta = layer.InstantiateVariables(jax.random.PRNGKey(0))
  return layer, theta


class TestJointGroupSharding:

  def setup_method(self, _):
    if len(jax.devices()) < 8:
      pytest.skip("needs the 8-device CPU mesh")

  def _Lower(self, mesh_axes, num_groups=0, batch=8, **kw):
    mesh = mesh_lib.MakeMesh(mesh_axes, devices=jax.devices()[:8])
    layer, theta = _MoeLayer(num_groups=num_groups, **kw)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, 8, 16))
    with mesh_lib.MeshContext(mesh):
      theta = jax.device_put(theta,
                             mesh_lib.ThetaShardings(mesh, layer, theta))
      x = jax.device_put(
          x, jax.sharding.NamedSharding(
              mesh, jax.sharding.PartitionSpec(
                  "data" if "data" in mesh_axes else None)))

      def loss(th, x):
        return jnp.mean(jnp.square(layer.FProp(th, x)))

      fn = jax.jit(jax.value_and_grad(loss))
      hlo = fn.lower(theta, x).compile().as_text()
      val, grad = fn(theta, x)
    return hlo, float(val), grad

  def test_auto_groups_is_data_times_expert(self):
    mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                             devices=jax.devices()[:8])
    layer, _ = _MoeLayer()
    with mesh_lib.MeshContext(mesh):
      assert layer._NumGroups(8, 8) == 4
      assert layer._GroupAxes() == ("data", "expert")

  def test_dispatch_all_to_all_no_permute_storm(self):
    hlo, val, grad = self._Lower({"data": 2, "expert": 2, "model": 2})
    counts = _CollectiveDefs(hlo)
    assert counts.get("all-to-all", 0) >= 2, counts  # dispatch + combine
    # the round-4 einsum fallback produced ~49 collective-permutes; the
    # explicit path needs none (a handful from unrelated CPU lowering
    # details are tolerated)
    assert counts.get("collective-permute", 0) <= 4, counts
    assert np.isfinite(val)
    assert all(np.isfinite(l).all() for l in jax.tree_util.tree_leaves(grad))

  def test_matches_single_device(self):
    # the sharded program computes the same loss as one device
    layer, theta = _MoeLayer(num_groups=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
    ref = float(jnp.mean(jnp.square(layer.FProp(theta, x))))
    hlo, val, _ = self._Lower({"data": 2, "expert": 2, "model": 2},
                              num_groups=4)
    np.testing.assert_allclose(val, ref, rtol=1e-5)

  def test_expert_only_mesh_still_works(self):
    hlo, val, _ = self._Lower({"expert": 8})
    assert _CollectiveDefs(hlo).get("all-to-all", 0) >= 2
    assert np.isfinite(val)

  def test_fwd_only_hlo_has_all_to_all(self):
    # the forward program alone (no value_and_grad) must already carry the
    # dispatch + combine all-to-all pair
    mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                             devices=jax.devices()[:8])
    layer, theta = _MoeLayer()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
    with mesh_lib.MeshContext(mesh):
      theta = jax.device_put(theta,
                             mesh_lib.ThetaShardings(mesh, layer, theta))
      x = jax.device_put(
          x, jax.sharding.NamedSharding(mesh,
                                        jax.sharding.PartitionSpec("data")))
      fwd = jax.jit(lambda th, x: layer.FProp(th, x))
      hlo = fwd.lower(theta, x).compile().as_text()
    counts = _CollectiveDefs(hlo)
    assert counts.get("all-to-all", 0) >= 2, counts
    assert counts.get("collective-permute", 0) <= 2, counts

  def test_shard_map_matches_einsum_dispatch(self):
    # same theta through both lowerings on the same mesh: the explicit
    # shard_map all-to-all must agree with the GSPMD-inferred einsum path
    # in BOTH the forward value and the gradients
    mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                             devices=jax.devices()[:8])
    sm_layer, theta = _MoeLayer(num_groups=4)
    es_layer, _ = _MoeLayer(num_groups=4, dispatch_method="einsum")
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
    with mesh_lib.MeshContext(mesh):
      theta = jax.device_put(theta,
                             mesh_lib.ThetaShardings(mesh, sm_layer, theta))
      x = jax.device_put(
          x, jax.sharding.NamedSharding(mesh,
                                        jax.sharding.PartitionSpec("data")))

      def mk_loss(layer):
        return lambda th, x: jnp.mean(jnp.square(layer.FProp(th, x)))

      sm_val, sm_grad = jax.jit(jax.value_and_grad(mk_loss(sm_layer)))(
          theta, x)
      es_val, es_grad = jax.jit(jax.value_and_grad(mk_loss(es_layer)))(
          theta, x)
    np.testing.assert_allclose(float(sm_val), float(es_val), rtol=1e-5)
    for sm_l, es_l in zip(jax.tree_util.tree_leaves(sm_grad),
                          jax.tree_util.tree_leaves(es_grad)):
      np.testing.assert_allclose(np.asarray(sm_l), np.asarray(es_l),
                                 rtol=2e-5, atol=2e-5)

  def test_named_remat_boundaries_present(self):
    # the checkpoint_name tags must survive tracing so the 'dots' remat
    # policy can pin them (transformer.RepeatedTransformerLayer)
    mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                             devices=jax.devices()[:8])
    layer, theta = _MoeLayer()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
    with mesh_lib.MeshContext(mesh):
      jaxpr = jax.make_jaxpr(lambda th, x: layer.FProp(th, x))(theta, x)
    names = re.findall(r"name=(\w+)", str(jaxpr))
    assert "moe_dispatched" in names, names
    assert "moe_combined" in names, names


class TestNumGroupsAutoDerivation:
  """num_groups auto-derivation (0 = derive from the ambient mesh)."""

  def test_no_mesh_defaults_to_batch_capped(self):
    layer, _ = _MoeLayer()
    assert layer._NumGroups(4, 16) == 4   # min(b, 8)
    assert layer._NumGroups(16, 4) == 8   # capped at 8
    assert layer._NumGroups(3, 5) == 3

  def test_mesh_product_data_times_expert(self):
    if len(jax.devices()) < 8:
      pytest.skip("needs the 8-device CPU mesh")
    layer, _ = _MoeLayer()
    with mesh_lib.MeshContext(
        mesh_lib.MakeMesh({"data": 4, "expert": 2},
                          devices=jax.devices()[:8])):
      assert layer._NumGroups(8, 8) == 8
      assert layer._GroupAxes() == ("data", "expert")
    with mesh_lib.MeshContext(
        mesh_lib.MakeMesh({"expert": 8}, devices=jax.devices()[:8])):
      assert layer._NumGroups(4, 16) == 8
      assert layer._GroupAxes() == ("expert",)

  def test_clamps_to_token_divisor(self):
    if len(jax.devices()) < 8:
      pytest.skip("needs the 8-device CPU mesh")
    layer, _ = _MoeLayer()
    with mesh_lib.MeshContext(
        mesh_lib.MakeMesh({"expert": 8}, devices=jax.devices()[:8])):
      # b*t=6 < mesh product 8: largest divisor of 6 not above 8 is 6
      assert layer._NumGroups(3, 2) == 6

  def test_explicit_non_divisor_fails_loudly(self):
    layer, _ = _MoeLayer(num_groups=5)
    with pytest.raises(AssertionError):
      layer._NumGroups(4, 16)


@pytest.mark.slow
class TestMoEDispatchSoak:
  """Multi-device soak: bigger shapes, several steps, both dispatch paths."""

  def test_multi_step_parity_at_scale(self):
    if len(jax.devices()) < 8:
      pytest.skip("needs the 8-device CPU mesh")
    mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                             devices=jax.devices()[:8])
    sm_layer, theta = _MoeLayer(num_experts=8, num_groups=8)
    es_layer, _ = _MoeLayer(num_experts=8, num_groups=8,
                            dispatch_method="einsum")
    with mesh_lib.MeshContext(mesh):
      theta = jax.device_put(theta,
                             mesh_lib.ThetaShardings(mesh, sm_layer, theta))

      def mk_step(layer):
        def loss(th, x):
          return jnp.mean(jnp.square(layer.FProp(th, x)))
        grad_fn = jax.jit(jax.value_and_grad(loss))
        def step(th, x):
          val, g = grad_fn(th, x)
          th = jax.tree_util.tree_map(lambda w, gw: w - 1e-2 * gw, th, g)
          return th, float(val)
        return step

      sm_step, es_step = mk_step(sm_layer), mk_step(es_layer)
      sm_th = es_th = theta
      for i in range(4):
        x = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(10 + i), (16, 32, 16)),
            jax.sharding.NamedSharding(mesh,
                                       jax.sharding.PartitionSpec("data")))
        sm_th, sm_val = sm_step(sm_th, x)
        es_th, es_val = es_step(es_th, x)
        np.testing.assert_allclose(sm_val, es_val, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(sm_th),
                    jax.tree_util.tree_leaves(es_th)):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                 rtol=1e-4, atol=1e-4)


class TestNonDivisibleFallback:

  def test_odd_groups_fall_back_to_einsum(self):
    # groups=3 divides neither data*expert nor expert: the einsum path must
    # still produce correct values (and not assert)
    if len(jax.devices()) < 8:
      pytest.skip("needs the 8-device CPU mesh")
    mesh = mesh_lib.MakeMesh({"expert": 8}, devices=jax.devices()[:8])
    layer, theta = _MoeLayer(num_groups=3, num_experts=8)
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 8, 16))
    ref = layer.FProp(theta, x)  # no mesh: plain indexed path
    with mesh_lib.MeshContext(mesh):
      out = jax.jit(layer.FProp)(theta, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
