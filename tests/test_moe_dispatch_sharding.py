"""Compile-level assertions on the round-5 MoE dispatch program: joint
('data','expert') group sharding, shard_map all-to-all engagement, no
collective-permute resharding storm, named remat boundaries
(docs/moe_collectives.md)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.parallel import gshard, mesh as mesh_lib


def _CollectiveDefs(hlo: str):
  """Defining-instruction opcode counts (the attribution parser's rule)."""
  counts = {}
  inst = re.compile(
      r"[}\])]\s+(all-to-all|all-gather|all-reduce|reduce-scatter|"
      r"collective-permute)(-start|-done)?\(")
  for line in hlo.splitlines():
    if not re.match(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=", line):
      continue
    m = inst.search(line)
    if m and m.group(2) != "-done":
      counts[m.group(1)] = counts.get(m.group(1), 0) + 1
  return counts


def _MoeLayer(num_experts=4, num_groups=0, **kw):
  p = gshard.MoEFeedForwardLayer.Params().Set(
      name="moe", input_dim=16, hidden_dim=32, num_experts=num_experts,
      num_groups=num_groups, **kw)
  layer = p.Instantiate()
  theta = layer.InstantiateVariables(jax.random.PRNGKey(0))
  return layer, theta


class TestJointGroupSharding:

  def setup_method(self, _):
    if len(jax.devices()) < 8:
      pytest.skip("needs the 8-device CPU mesh")

  def _Lower(self, mesh_axes, num_groups=0, batch=8, **kw):
    mesh = mesh_lib.MakeMesh(mesh_axes, devices=jax.devices()[:8])
    layer, theta = _MoeLayer(num_groups=num_groups, **kw)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, 8, 16))
    with mesh_lib.MeshContext(mesh):
      theta = jax.device_put(theta,
                             mesh_lib.ThetaShardings(mesh, layer, theta))
      x = jax.device_put(
          x, jax.sharding.NamedSharding(
              mesh, jax.sharding.PartitionSpec(
                  "data" if "data" in mesh_axes else None)))

      def loss(th, x):
        return jnp.mean(jnp.square(layer.FProp(th, x)))

      fn = jax.jit(jax.value_and_grad(loss))
      hlo = fn.lower(theta, x).compile().as_text()
      val, grad = fn(theta, x)
    return hlo, float(val), grad

  def test_auto_groups_is_data_times_expert(self):
    mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                             devices=jax.devices()[:8])
    layer, _ = _MoeLayer()
    with mesh_lib.MeshContext(mesh):
      assert layer._NumGroups(8, 8) == 4
      assert layer._GroupAxes() == ("data", "expert")

  def test_dispatch_all_to_all_no_permute_storm(self):
    hlo, val, grad = self._Lower({"data": 2, "expert": 2, "model": 2})
    counts = _CollectiveDefs(hlo)
    assert counts.get("all-to-all", 0) >= 2, counts  # dispatch + combine
    # the round-4 einsum fallback produced ~49 collective-permutes; the
    # explicit path needs none (a handful from unrelated CPU lowering
    # details are tolerated)
    assert counts.get("collective-permute", 0) <= 4, counts
    assert np.isfinite(val)
    assert all(np.isfinite(l).all() for l in jax.tree_util.tree_leaves(grad))

  def test_matches_single_device(self):
    # the sharded program computes the same loss as one device
    layer, theta = _MoeLayer(num_groups=4)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
    ref = float(jnp.mean(jnp.square(layer.FProp(theta, x))))
    hlo, val, _ = self._Lower({"data": 2, "expert": 2, "model": 2},
                              num_groups=4)
    np.testing.assert_allclose(val, ref, rtol=1e-5)

  def test_expert_only_mesh_still_works(self):
    hlo, val, _ = self._Lower({"expert": 8})
    assert _CollectiveDefs(hlo).get("all-to-all", 0) >= 2
    assert np.isfinite(val)

  def test_named_remat_boundaries_present(self):
    # the checkpoint_name tags must survive tracing so the 'dots' remat
    # policy can pin them (transformer.RepeatedTransformerLayer)
    mesh = mesh_lib.MakeMesh({"data": 2, "expert": 2, "model": 2},
                             devices=jax.devices()[:8])
    layer, theta = _MoeLayer()
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 8, 16))
    with mesh_lib.MeshContext(mesh):
      jaxpr = jax.make_jaxpr(lambda th, x: layer.FProp(th, x))(theta, x)
    names = re.findall(r"name=(\w+)", str(jaxpr))
    assert "moe_dispatched" in names, names
    assert "moe_combined" in names, names


class TestNonDivisibleFallback:

  def test_odd_groups_fall_back_to_einsum(self):
    # groups=3 divides neither data*expert nor expert: the einsum path must
    # still produce correct values (and not assert)
    if len(jax.devices()) < 8:
      pytest.skip("needs the 8-device CPU mesh")
    mesh = mesh_lib.MakeMesh({"expert": 8}, devices=jax.devices()[:8])
    layer, theta = _MoeLayer(num_groups=3, num_experts=8)
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 8, 16))
    ref = layer.FProp(theta, x)  # no mesh: plain indexed path
    with mesh_lib.MeshContext(mesh):
      out = jax.jit(layer.FProp)(theta, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
