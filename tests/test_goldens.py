"""Golden-value layer tests (VERDICT r3 Missing #5): lock init+FProp
numerics of core layers against silent drift, on the deterministic
name-derived seed system. Ref `lingvo/core/test_utils.py:406-468` and the
reference layer tests' CompareToGoldenSingleFloat usage.

Regenerate intentionally-changed goldens with:
  LINGVO_TPU_UPDATE_GOLDENS=1 python -m pytest tests/test_goldens.py
"""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import attention as attention_lib
from lingvo_tpu.core import conformer_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core import rnn_layers
from lingvo_tpu.core.test_utils import CompareToGoldenSingleFloat

KEY = jax.random.PRNGKey(0)


def _build(p):
  layer = p.Instantiate()
  layer.FinalizePaths()
  return layer, layer.InstantiateVariables(KEY)


def _x(shape, k=1):
  return jax.random.normal(jax.random.PRNGKey(k), shape, jnp.float32)


class TestLayerGoldens:

  def test_layer_norm(self):
    layer, theta = _build(layers_lib.LayerNorm.Params().Set(
        name="ln", input_dim=8))
    out = layer.FProp(theta, _x((2, 5, 8)))
    # LN output sums to ~0 by construction; abs-sum is drift-sensitive
    CompareToGoldenSingleFloat(67.787010, jnp.sum(jnp.abs(out)))

  def test_projection(self):
    layer, theta = _build(layers_lib.ProjectionLayer.Params().Set(
        name="proj", input_dim=8, output_dim=4, activation="TANH"))
    out = layer.FProp(theta, _x((3, 8)))
    CompareToGoldenSingleFloat(-1.537703, jnp.sum(out))

  def test_feedforward_net(self):
    layer, theta = _build(layers_lib.FeedForwardNet.Params().Set(
        name="ffn", input_dim=8, hidden_layer_dims=[16, 4],
        activation=["RELU", "NONE"]))
    out = layer.FProp(theta, _x((3, 8)))
    CompareToGoldenSingleFloat(5.394782, jnp.sum(out))

  def test_batch_norm_eval(self):
    layer, theta = _build(layers_lib.BatchNormLayer.Params().Set(
        name="bn", dim=8))
    with py_utils.EvalContext():
      out = layer.FProp(theta, _x((4, 8)))
    CompareToGoldenSingleFloat(0.653572, jnp.sum(out))

  def test_lstm_cell(self):
    cell, theta = _build(rnn_cell.LSTMCellSimple.Params().Set(
        name="lstm", num_input_nodes=6, num_output_nodes=5))
    state = cell.FProp(theta, cell.InitState(3), _x((3, 6)))
    total = jnp.sum(state.m) + jnp.sum(state.c)
    CompareToGoldenSingleFloat(0.169895, total)

  def test_layer_norm_lstm_cell(self):
    cell, theta = _build(
        rnn_cell.LayerNormalizedLSTMCellSimple.Params().Set(
            name="lnlstm", num_input_nodes=6, num_output_nodes=5))
    state = cell.FProp(theta, cell.InitState(3), _x((3, 6)))
    total = jnp.sum(state.m) + jnp.sum(state.c)
    CompareToGoldenSingleFloat(2.053796, total)

  def test_gru_cell(self):
    cell, theta = _build(rnn_cell.GRUCell.Params().Set(
        name="gru", num_input_nodes=6, num_output_nodes=5))
    state = cell.FProp(theta, cell.InitState(3), _x((3, 6)))
    CompareToGoldenSingleFloat(0.266808, jnp.sum(state.m))

  def test_frnn_over_time(self):
    layer, theta = _build(rnn_layers.FRNN.Params().Set(
        name="frnn",
        cell=rnn_cell.LSTMCellSimple.Params().Set(
            num_input_nodes=6, num_output_nodes=5)))
    out, _ = layer.FProp(theta, _x((2, 7, 6)))
    CompareToGoldenSingleFloat(1.015172, jnp.sum(out))

  def test_multi_headed_attention(self):
    layer, theta = _build(attention_lib.MultiHeadedAttention.Params().Set(
        name="mha", input_dim=8, hidden_dim=8, num_heads=2))
    out, _ = layer.FProp(theta, _x((2, 5, 8)))
    CompareToGoldenSingleFloat(-5.047753, jnp.sum(out))

  def test_conformer_block(self):
    layer, theta = _build(conformer_layer.ConformerLayer.Params().Set(
        name="conf", input_dim=8, atten_num_heads=2, kernel_size=3))
    with py_utils.EvalContext():  # BN in the LConv branch uses moving stats
      out = layer.FProp(theta, _x((2, 6, 8)))
    # block ends in LayerNorm (sum ~ 0): abs-sum catches drift
    CompareToGoldenSingleFloat(80.987740, jnp.sum(jnp.abs(out)))


class TestVariantGoldens:
  """Attention variants + MoE + conv: the rest of the hot layer zoo."""

  def test_transformer_xl_attention(self):
    from lingvo_tpu.core import attention_variants
    layer, theta = _build(attention_variants.TransformerXLAttention.Params(
    ).Set(name="xl", input_dim=8, hidden_dim=8, num_heads=2))
    out, _ = layer.FProp(theta, _x((2, 5, 8)))
    CompareToGoldenSingleFloat(-0.850885, jnp.sum(out))

  def test_performer_attention(self):
    from lingvo_tpu.core import attention_variants
    layer, theta = _build(attention_variants.PerformerAttention.Params(
    ).Set(name="perf", input_dim=8, hidden_dim=8, num_heads=2,
          num_random_features=16))
    out, _ = layer.FProp(theta, _x((2, 5, 8)))
    CompareToGoldenSingleFloat(-0.166709, jnp.sum(out))

  def test_conv2d(self):
    layer, theta = _build(layers_lib.Conv2DLayer.Params().Set(
        name="conv", filter_shape=(3, 3, 2, 4), batch_norm=False,
        has_bias=True, activation="RELU"))
    out = layer.FProp(theta, _x((2, 6, 6, 2)))
    CompareToGoldenSingleFloat(79.663170, jnp.sum(out))

  def test_sru_cell(self):
    cell, theta = _build(rnn_cell.SRUCell.Params().Set(
        name="sru", num_input_nodes=6, num_output_nodes=6))
    x = cell.PreProcessInputs(theta, _x((3, 1, 6)))[:, 0]
    state = cell.FProp(theta, cell.InitState(3), x, preprocessed=True)
    CompareToGoldenSingleFloat(0.658072, jnp.sum(state.m))

  def test_moe_layer(self):
    from lingvo_tpu.parallel import gshard
    layer, theta = _build(gshard.MoEFeedForwardLayer.Params().Set(
        name="moe", input_dim=8, hidden_dim=16, num_experts=4,
        num_groups=2))
    out = layer.FProp(theta, _x((2, 8, 8)))
    CompareToGoldenSingleFloat(0.669588, jnp.sum(out))


class TestGoldenHarness:

  def test_updater_rewrites_call_site(self, tmp_path):
    from lingvo_tpu.core import test_utils
    line = ("    test_utils.CompareToGoldenSingleFloat(1.500000, "
            "jnp.sum(out))\n")
    new = test_utils._ReplaceGoldenSingleFloat(line, 2.25)
    assert new == ("    test_utils.CompareToGoldenSingleFloat(2.250000, "
                   "jnp.sum(out))\n")
    f = tmp_path / "t.py"
    f.write_text("x = 1\n" + line)
    test_utils._ReplaceOneLineInFile(str(f), 1, line, new)
    assert f.read_text().splitlines()[1].strip().startswith(
        "test_utils.CompareToGoldenSingleFloat(2.250000")

  def test_numeric_gradient_matches_jax(self):
    from lingvo_tpu.core import test_utils
    w = np.asarray([[0.3, -0.2], [0.1, 0.4]], np.float64)

    def f(m):
      return float(np.tanh(m).sum() + (m ** 2).sum())

    num = test_utils.ComputeNumericGradient(f, w)
    ana = np.asarray(jax.grad(
        lambda m: jnp.sum(jnp.tanh(m)) + jnp.sum(m ** 2))(
            jnp.asarray(w, jnp.float64) if jax.config.jax_enable_x64
            else jnp.asarray(w, jnp.float32)))
    np.testing.assert_allclose(num, ana, rtol=1e-3, atol=1e-4)
