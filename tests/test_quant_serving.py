"""Quantized serving subsystem (lingvo_tpu/quant/, docs/quantized_serving.md).

Covers the numerics contract end to end:
- `Int8QuantizeWeight`/`Int8Einsum` under both 'dv' and 'vd' layouts (and
  the legacy all-but-last default), `Int8Weight` as a jit-transparent
  pytree leaf,
- `QuantizeKv` per-token-per-head symmetric quantization error bounds and
  the `KvBytesPerToken` accounting (incl. the >= 1.8x bf16 -> int8 ratio
  at serving head dims),
- the int8 block-table decode kernels: the XLA twin is BITWISE equal to
  the Pallas(interpret) twin — including after the allocator frees pages
  and hands them to another sequence — and both are bitwise equal to the
  float kernel run on the dequantized pools (dequantize-on-read is the
  only difference between the paths),
- quantized `BlockPrefill` against the same dequantized-pool float run,
- the dense (non-paged) int8 cache: ExtendStep/Prefill parity with float,
- the serving engine with kv_cache_dtype='int8' (+ serve_int8_weights):
  greedy token parity with the f32 engine, Stats() visibility
  (kv_cache_dtype / kv_bytes_per_token / quantized_steps / pool_bytes),
  dense-fallback visibility for ineligible configs, and default-off
  bit-exactness (no sidecars allocated, legacy path classification),
- the export round trip: Export(quantize_int8=True) ->
  Predictor.Int8ServingTheta('dequant') is bitwise the frozen theta
  (ScoreSequences bitwise equal), mode='int8' has a bounded delta, and the
  manifest records per-leaf layouts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import quant_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.ops import block_decode
from lingvo_tpu.quant import kv as kv_quant
from lingvo_tpu.quant import weights as quant_weights
from lingvo_tpu.serving import engine as engine_lib
from lingvo_tpu.serving import kv_cache


def _TinyLmParams(**overrides):
  from lingvo_tpu.models.lm import layers as lm_layers
  p = lm_layers.TransformerLm.Params().Set(
      name="lm", vocab_size=64, model_dim=32, num_layers=2, num_heads=2,
      hidden_dim=64, use_rotary=True)
  return p.Set(**overrides)


@pytest.fixture(scope="module")
def tiny_lm():
  task = _TinyLmParams().Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  return task, theta


# -- weight quantization -----------------------------------------------------


class TestInt8Weights:

  def test_dv_layout_einsum_close_to_float(self):
    rng = np.random.RandomState(0)
    x = rng.randn(3, 5, 16).astype(np.float32)
    w = rng.randn(16, 2, 8).astype(np.float32)       # [D, N, H], contract D
    w8, scale = quant_utils.Int8QuantizeWeight(
        jnp.asarray(w), layout="dv", contract_ndim=1)
    assert w8.shape == w.shape and scale.shape == (1, 2, 8)
    out = quant_utils.Int8Einsum(jnp.asarray(x), w8, scale,
                                 layout="dv", contract_ndim=1)
    ref = np.einsum("btd,dnh->btnh", x, w)
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), ref,
                               atol=0.05 * np.abs(ref).max())

  def test_vd_layout_einsum_close_to_float(self):
    rng = np.random.RandomState(1)
    x = rng.randn(3, 2, 8).astype(np.float32)        # [B, N, H]
    w = rng.randn(2, 8, 16).astype(np.float32)       # [N, H, D], contract N,H
    # NOTE: 'vd' means the contraction axes TRAIL — transpose to [D, N, H]?
    # No: w_post's einsum "BNH,NHD->BD" contracts the LEADING axes of w
    # when stored [N, H, D]... the serving layout stores w_post [D, N, H]
    # ('vd', 2): output axis leads, the 2 contraction axes trail.
    w_vd = np.transpose(w, (2, 0, 1))                # [D, N, H]
    w8, scale = quant_utils.Int8QuantizeWeight(
        jnp.asarray(w_vd), layout="vd", contract_ndim=2)
    assert w8.shape == w_vd.shape and scale.shape == (16, 1, 1)
    out = quant_utils.Int8Einsum(jnp.asarray(x), w8, scale,
                                 layout="vd", contract_ndim=2)
    ref = np.einsum("bnh,dnh->bd", x, w_vd)
    np.testing.assert_allclose(np.asarray(out), ref,
                               atol=0.05 * np.abs(ref).max())

  def test_legacy_default_matches_explicit_dv(self):
    """The pre-layout 3-arg call (all-but-last reduction) must keep its
    meaning: for a 2-D [in, out] weight it equals ('dv', 1)."""
    rng = np.random.RandomState(2)
    x = rng.randn(4, 6).astype(np.float32)
    w = rng.randn(6, 10).astype(np.float32)
    w8a, sa = quant_utils.Int8QuantizeWeight(jnp.asarray(w))
    w8b, sb = quant_utils.Int8QuantizeWeight(jnp.asarray(w), layout="dv",
                                             contract_ndim=1)
    np.testing.assert_array_equal(np.asarray(w8a), np.asarray(w8b))
    np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))
    out_a = quant_utils.Int8Einsum(jnp.asarray(x), w8a, sa)
    out_b = quant_utils.Int8Einsum(jnp.asarray(x), w8b, sb,
                                   layout="dv", contract_ndim=1)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))

  def test_int8weight_is_jit_transparent_pytree(self):
    rng = np.random.RandomState(3)
    w = rng.randn(8, 12).astype(np.float32)
    x = rng.randn(2, 8).astype(np.float32)
    node = quant_utils.Int8Weight.Quantize(jnp.asarray(w), layout="dv",
                                           contract_ndim=1)
    leaves, treedef = jax.tree_util.tree_flatten(node)
    assert len(leaves) == 2      # (w_int8, scale); layout rides as aux
    rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
    assert rebuilt.layout == "dv" and rebuilt.contract_ndim == 1
    eager = node.Einsum(jnp.asarray(x))
    jitted = jax.jit(lambda n, xx: n.Einsum(xx))(node, jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(jitted))
    np.testing.assert_allclose(np.asarray(node.Dequant()), w,
                               atol=np.abs(w).max() / 127)

  def test_stacked_repeated_leaves_get_per_repeat_scales(self):
    """A Repeated stack's `.body.` weight [reps, ...] must quantize each
    repeat independently — the repeat axis is batch, not contraction."""
    rng = np.random.RandomState(4)
    w = rng.randn(3, 8, 12).astype(np.float32)       # [reps, in, out]
    w[1] *= 100.0                                    # wildly different range
    node = quant_weights.QuantizeLeafInt8(jnp.asarray(w), "dv", 1,
                                          stacked=True)
    assert node.w_int8.shape == (3, 8, 12)
    assert node.scale.shape == (3, 1, 12)
    # per-repeat scales: repeat 1's huge range cannot poison repeat 0
    per_rep = [quant_utils.Int8Weight.Quantize(jnp.asarray(w[i]),
                                               layout="dv", contract_ndim=1)
               for i in range(3)]
    for i in range(3):
      np.testing.assert_array_equal(np.asarray(node.w_int8[i]),
                                    np.asarray(per_rep[i].w_int8))
    np.testing.assert_allclose(np.asarray(node.Dequant()), w,
                               atol=np.abs(w[1]).max() / 127)

  def test_serving_theta_rewrites_only_table_leaves(self, tiny_lm):
    task, theta = tiny_lm
    t8, paths = quant_weights.Int8ServingTheta(theta)
    for path in paths:
      assert path.rsplit(".", 1)[-1] in quant_weights.SERVING_WEIGHT_LAYOUTS
      assert isinstance(t8.Get(path), quant_utils.Int8Weight)
    # non-table leaves (biases, layer norm) are untouched
    for path, leaf in theta.FlattenItems():
      if path not in paths:
        np.testing.assert_array_equal(np.asarray(leaf),
                                      np.asarray(t8.Get(path)))


# -- KV quantization ---------------------------------------------------------


class TestKvQuant:

  def test_roundtrip_error_bounded_by_half_scale(self):
    rng = np.random.RandomState(0)
    x = (rng.randn(5, 7, 4, 16) * rng.lognormal(size=(5, 7, 4, 1))
         ).astype(np.float32)
    q, scale = kv_quant.QuantizeKv(jnp.asarray(x))
    back = kv_quant.DequantKv(q, scale)
    err = np.abs(np.asarray(back) - x)
    bound = np.asarray(scale)[..., None] * 0.5 + 1e-6
    assert (err <= bound).all()

  def test_all_zero_rows_quantize_and_dequantize_to_zero(self):
    q, scale = kv_quant.QuantizeKv(jnp.zeros((2, 3, 8)))
    np.testing.assert_array_equal(np.asarray(q), 0)
    np.testing.assert_array_equal(np.asarray(kv_quant.DequantKv(q, scale)), 0)

  def test_resolve_dtype_defaults_and_validation(self):
    dt, quant = kv_quant.ResolveKvCacheDtype(None, jnp.bfloat16)
    assert dt == jnp.bfloat16 and not quant
    dt, quant = kv_quant.ResolveKvCacheDtype("int8", jnp.float32)
    assert dt == jnp.int8 and quant
    dt, quant = kv_quant.ResolveKvCacheDtype("bfloat16", jnp.float32)
    assert dt == jnp.bfloat16 and not quant
    with pytest.raises(ValueError, match="kv_cache_dtype"):
      kv_quant.ResolveKvCacheDtype("int4", jnp.float32)

  def test_bytes_per_token_and_compression_ratio(self):
    # serving head dim (H=64): f32 2048, bf16 1024, int8 544 per layer
    n, h = 4, 64
    f32 = kv_quant.KvBytesPerToken(n, h, None, jnp.float32)
    bf16 = kv_quant.KvBytesPerToken(n, h, "bfloat16", jnp.float32)
    i8 = kv_quant.KvBytesPerToken(n, h, "int8", jnp.float32)
    assert (f32, bf16, i8) == (2048, 1024, 544)
    # the ISSUE's fixed-HBM admission criterion: int8 must fit >= 1.8x the
    # sequences a bf16 cache fits
    assert bf16 / i8 >= 1.8

  def test_stack_census_counts_repeated_layers(self, tiny_lm):
    task, _ = tiny_lm
    census = kv_quant.StackKvCensus(task)
    # 2 repeated layers x (2 heads * 16 dim * 2(K,V) * 4B) = 512 B/token
    assert census == {"kv_cache_dtype": "float32",
                      "kv_bytes_per_token": 512, "attention_layers": 2}
    census8 = kv_quant.StackKvCensus(task, "int8")
    assert census8["kv_cache_dtype"] == "int8"
    # per layer: 2*2*16*1 + 2*2*4 = 80 -> 160 total
    assert census8["kv_bytes_per_token"] == 160


# -- int8 kernel twins -------------------------------------------------------


def _QuantizePools(k_pool, v_pool):
  """float pools [NP, P, N, H] -> int8 pools + TRANSPOSED [NP, N, P]
  sidecars (the device layout attention.InitPagedStates allocates)."""
  k8, ks = kv_quant.QuantizeKv(jnp.asarray(k_pool))   # scale [NP, P, N]
  v8, vs = kv_quant.QuantizeKv(jnp.asarray(v_pool))
  return (k8, jnp.swapaxes(ks, 1, 2).astype(jnp.float32),
          v8, jnp.swapaxes(vs, 1, 2).astype(jnp.float32))


def _DequantPools(k8, ks, v8, vs):
  """The float pools an int8 run must reproduce bitwise: elementwise
  dequantization in the same [NP, P, N, H] layout."""
  kf = kv_quant.DequantKv(k8.swapaxes(1, 2), ks).swapaxes(1, 2)
  vf = kv_quant.DequantKv(v8.swapaxes(1, 2), vs).swapaxes(1, 2)
  return kf, vf


class TestInt8KernelTwins:

  def _Inputs(self, b=2, t_pages=2, page=8, n=1, h=8, seed=0):
    rng = np.random.RandomState(seed)
    np_total = b * t_pages + 1
    q = rng.randn(b, 1, n, h).astype(np.float32)
    k_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    v_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    tables = rng.permutation(np_total - 1).reshape(b, t_pages).astype(
        np.int32)
    return q, k_pool, v_pool, tables

  def test_int8_twins_bitwise_and_match_float_on_dequant_grid(self):
    """int8 XLA == int8 Pallas(interpret) bitwise, and both == the float
    kernel run on the dequantized pools bitwise: dequantize-on-read is the
    ONLY thing the quantized path adds."""
    q, k_pool, v_pool, tables = self._Inputs()
    k8, ks, v8, vs = _QuantizePools(k_pool, v_pool)
    kf, vf = _DequantPools(k8, ks, v8, vs)
    for lens in ([0, 16], [5, 16], [1, 9], [8, 8]):
      ln = jnp.asarray(lens, jnp.int32)
      out_x = block_decode.BlockDecode(
          jnp.asarray(q), k8, v8, jnp.asarray(tables), ln, page_size=8,
          k_scale=ks, v_scale=vs, lowering="xla")
      out_p = block_decode.BlockDecode(
          jnp.asarray(q), k8, v8, jnp.asarray(tables), ln, page_size=8,
          k_scale=ks, v_scale=vs, lowering="pallas", interpret=True)
      out_f = block_decode.BlockDecode(
          jnp.asarray(q), kf, vf, jnp.asarray(tables), ln, page_size=8,
          lowering="xla")
      np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
      np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_f))

  def test_int8_twins_bitwise_after_page_reuse(self):
    """The eviction scenario: a real allocator frees one sequence's pages,
    hands them to another, and the new tokens overwrite the int8 pages AND
    their scale sidecars in place. Twins must stay bitwise equal."""
    q, k_pool, v_pool, tables = self._Inputs()
    k8, ks, v8, vs = _QuantizePools(k_pool, v_pool)

    def _Both(ln_np, tb):
      ln = jnp.asarray(ln_np, jnp.int32)
      out_x = block_decode.BlockDecode(
          jnp.asarray(q), k8, v8, jnp.asarray(tb), ln, page_size=8,
          k_scale=ks, v_scale=vs, lowering="xla")
      out_p = block_decode.BlockDecode(
          jnp.asarray(q), k8, v8, jnp.asarray(tb), ln, page_size=8,
          k_scale=ks, v_scale=vs, lowering="pallas", interpret=True)
      np.testing.assert_array_equal(np.asarray(out_x), np.asarray(out_p))
      return np.asarray(out_x)

    before = _Both([5, 16], tables)

    alloc = kv_cache.PageAllocator(num_pages=4, page_size=8)
    alloc.Allocate("a", 2)
    alloc.Allocate("b", 2)
    alloc.Free("a")
    reused = alloc.Allocate("c", 2)
    assert reused == [0, 1]
    rng = np.random.RandomState(7)
    for pg in reused:
      # quantize-on-write: fresh tokens land as int8 + new per-slot scales
      fresh_k = rng.randn(8, 1, 8).astype(np.float32) * 3.0
      fresh_v = rng.randn(8, 1, 8).astype(np.float32) * 3.0
      fk8, fks = kv_quant.QuantizeKv(jnp.asarray(fresh_k))
      fv8, fvs = kv_quant.QuantizeKv(jnp.asarray(fresh_v))
      k8 = k8.at[pg].set(fk8)
      ks = ks.at[pg].set(jnp.swapaxes(fks, 0, 1))
      v8 = v8.at[pg].set(fv8)
      vs = vs.at[pg].set(jnp.swapaxes(fvs, 0, 1))
    tables2 = np.array([reused, list(alloc.PagesOf("b"))], np.int32)
    after = _Both([12, 16], tables2)
    # the overwrite actually changed what row 0 attends to
    assert not np.array_equal(before[0], after[0])
    # and the float-on-dequant-grid equality still holds post-reuse
    kf, vf = _DequantPools(k8, ks, v8, vs)
    out_f = block_decode.BlockDecode(
        jnp.asarray(q), kf, vf, jnp.asarray(tables2),
        jnp.asarray([12, 16], jnp.int32), page_size=8, lowering="xla")
    np.testing.assert_array_equal(after, np.asarray(out_f))

  def test_int8_block_prefill_matches_float_on_dequant_grid(self):
    b, c, n, h, page, t_pages = 2, 4, 2, 8, 4, 3
    rng = np.random.RandomState(3)
    np_total = b * t_pages + 1
    q = rng.randn(b, c, n, h).astype(np.float32)
    k_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    v_pool = rng.randn(np_total, page, n, h).astype(np.float32)
    tables = rng.permutation(np_total - 1).reshape(b, t_pages).astype(
        np.int32)
    k8, ks, v8, vs = _QuantizePools(k_pool, v_pool)
    kf, vf = _DequantPools(k8, ks, v8, vs)
    q_pos = jnp.asarray([0, 5], jnp.int32)
    in_len = jnp.asarray([4, 3], jnp.int32)
    out8 = block_decode.BlockPrefill(
        jnp.asarray(q), k8, v8, jnp.asarray(tables), q_pos, in_len,
        page_size=page, k_scale=ks, v_scale=vs)
    outf = block_decode.BlockPrefill(
        jnp.asarray(q), kf, vf, jnp.asarray(tables), q_pos, in_len,
        page_size=page)
    np.testing.assert_array_equal(np.asarray(out8), np.asarray(outf))

  def test_gather_scales_layout(self):
    scales = jnp.arange(2 * 3 * 4, dtype=jnp.float32).reshape(2, 3, 4)
    tables = jnp.asarray([[1, 0]], jnp.int32)
    out = block_decode.GatherScales(scales, tables)     # [1, 8, 3]
    assert out.shape == (1, 8, 3)
    # logical slot 0 = page 1 slot 0; per-head values = scales[1, :, 0]
    np.testing.assert_array_equal(np.asarray(out[0, 0]),
                                  np.asarray(scales[1, :, 0]))
    np.testing.assert_array_equal(np.asarray(out[0, 4]),
                                  np.asarray(scales[0, :, 0]))


# -- dense (non-paged) int8 cache --------------------------------------------


class TestDenseCacheInt8:

  @pytest.fixture(scope="class")
  def int8_lm(self):
    task = _TinyLmParams(kv_cache_dtype="int8").Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    return task, theta

  def test_init_states_carry_scale_sidecars(self, tiny_lm, int8_lm):
    task8, theta8 = int8_lm
    states = task8.InitDecodeState(theta8, 2, 16)
    leaves = {p for p, _ in states.FlattenItems()}
    assert any("key_scale" in p for p in leaves)
    assert any(l.dtype == jnp.int8 for _, l in states.FlattenItems()
               if hasattr(l, "dtype"))
    task, theta = tiny_lm
    legacy = task.InitDecodeState(theta, 2, 16)
    assert not any("key_scale" in p for p, _ in legacy.FlattenItems())

  def test_extend_step_greedy_matches_float(self, tiny_lm, int8_lm):
    """Same theta, int8 vs float dense cache: logits stay close and the
    greedy continuation is identical on a fixed prompt."""
    task, theta = tiny_lm
    task8, _ = int8_lm
    prompt = [5, 9, 2, 33, 17]

    def _Roll(tk):
      states = tk.InitDecodeState(theta, 1, 12)
      ext = jax.jit(lambda th, ids, st: tk.ExtendStep(th, ids, st))
      logits = None
      for t in prompt:
        logits, states = ext(theta, jnp.asarray([[t]], jnp.int32), states)
      toks, lgs = [], []
      for _ in range(5):
        nxt = int(np.argmax(np.asarray(logits[0])))
        toks.append(nxt)
        lgs.append(np.asarray(logits[0]))
        logits, states = ext(theta, jnp.asarray([[nxt]], jnp.int32), states)
      return toks, np.stack(lgs)

    toks_f, lg_f = _Roll(task)
    toks_8, lg_8 = _Roll(task8)
    assert toks_f == toks_8
    np.testing.assert_allclose(lg_8, lg_f, atol=0.05 * np.abs(lg_f).max())

  def test_prefill_matches_float_closely(self, tiny_lm, int8_lm):
    task, theta = tiny_lm
    task8, _ = int8_lm
    ids = jnp.asarray([[5, 9, 2, 33, 17, 4]], jnp.int32)
    states = task.InitDecodeState(theta, 1, 8)
    logits_f, _ = jax.jit(task.Prefill)(theta, ids, states)
    states8 = task8.InitDecodeState(theta, 1, 8)
    logits_8, _ = jax.jit(task8.Prefill)(theta, ids, states8)
    np.testing.assert_allclose(
        np.asarray(logits_8), np.asarray(logits_f),
        atol=0.05 * np.abs(np.asarray(logits_f)).max())


# -- quantized serving engine ------------------------------------------------


class TestQuantizedEngine:

  _PROMPTS = np.array([[5, 9, 2, 33, 17], [7, 7, 7, 0, 0]], np.int32)
  _LENS = np.array([5, 3], np.int32)

  def _Engine(self, task, theta, **kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("num_pages", 16)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 16)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("default_max_new", 4)
    return engine_lib.ServingLoop(task, theta, **kw)

  def test_int8_engine_token_parity_and_stats(self, tiny_lm):
    task, theta = tiny_lm
    eng_f = self._Engine(task, theta)
    eng_8 = self._Engine(task, theta, kv_cache_dtype="int8")
    out_f = eng_f.RunBatch(self._PROMPTS, self._LENS, 4)
    out_8 = eng_8.RunBatch(self._PROMPTS, self._LENS, 4)
    np.testing.assert_array_equal(out_f, out_8)

    sf, s8 = eng_f.Stats(), eng_8.Stats()
    from lingvo_tpu.observe import schema as observe_schema
    observe_schema.ValidateEngineStats(sf)
    observe_schema.ValidateEngineStats(s8)
    base = "pallas" if jax.default_backend() == "tpu" else "xla"
    assert sf["paged_path"] == base
    assert sf["kv_cache_dtype"] == "float32"
    assert sf["quantized_steps"] == 0
    assert s8["paged_path"] == base + "-int8"
    assert s8["kv_cache_dtype"] == "int8"
    assert s8["quantized_steps"] == s8["steps"] > 0
    assert s8["dense_fallback_steps"] == 0
    # honest HBM accounting: per-token bytes shrink ~3.2x, pool bytes match
    assert sf["kv_bytes_per_token"] == 512 and s8["kv_bytes_per_token"] == 160
    assert s8["kv_pages"]["pool_bytes"] == 160 * 4 * 16
    # the quantized pool really is int8 + sidecars on device
    leaves = list(eng_8._states.FlattenItems())
    assert any(hasattr(l, "dtype") and l.dtype == jnp.int8
               for _, l in leaves)
    assert any("key_scale" in p for p, _ in leaves)

  def test_default_off_allocates_no_sidecars(self, tiny_lm):
    """kv_cache_dtype unset = the bit-exact legacy engine: float pool, no
    scale sidecars, legacy path name, zero quantized steps."""
    task, theta = tiny_lm
    eng = self._Engine(task, theta)
    leaves = list(eng._states.FlattenItems())
    assert not any("scale" in p for p, _ in leaves)
    assert not any(hasattr(l, "dtype") and l.dtype == jnp.int8
                   for _, l in leaves)

  def test_int8_weights_engine_token_parity(self, tiny_lm):
    task, theta = tiny_lm
    eng_f = self._Engine(task, theta)
    eng_w = self._Engine(task, theta, kv_cache_dtype="int8",
                         serve_int8_weights=True)
    out_f = eng_f.RunBatch(self._PROMPTS, self._LENS, 4)
    out_w = eng_w.RunBatch(self._PROMPTS, self._LENS, 4)
    np.testing.assert_array_equal(out_f, out_w)
    sw = eng_w.Stats()
    assert sw["serve_int8_weights"] is True
    assert sw["quantized_steps"] == sw["steps"] > 0

  def test_ineligible_int8_config_falls_back_dense_and_visibly(self):
    """atten_logit_cap fails the eligibility gate with a quantized pool
    too: the engine still serves the int8 pages (gather + dequantize +
    dense attention) and reports 'dense', never silently."""
    from lingvo_tpu.core import attention as attention_lib
    p = _TinyLmParams()
    p.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
        atten_logit_cap=50.0)
    task = p.Instantiate()
    task.FinalizePaths()
    theta = task.InstantiateVariables(jax.random.PRNGKey(0))
    eng_d = self._Engine(task, theta)                       # float dense ref
    eng_8 = self._Engine(task, theta, kv_cache_dtype="int8")
    assert eng_8.paged_path == "dense"
    out_d = eng_d.RunBatch(self._PROMPTS, self._LENS, 4)
    out_8 = eng_8.RunBatch(self._PROMPTS, self._LENS, 4)
    np.testing.assert_array_equal(out_d, out_8)
    s8 = eng_8.Stats()
    from lingvo_tpu.observe import schema as observe_schema
    observe_schema.ValidateEngineStats(s8)
    assert s8["paged_path"] == "dense"
    assert s8["kv_cache_dtype"] == "int8"
    assert s8["dense_fallback_steps"] == s8["steps"] > 0
    assert s8["quantized_steps"] == s8["steps"]


# -- export round trip -------------------------------------------------------


class TestInt8ExportRoundTrip:

  def test_export_predict_int8_serving_theta(self, tiny_lm, tmp_path):
    from lingvo_tpu.serving import export as export_lib
    task, theta = tiny_lm
    export_dir = str(tmp_path / "export_int8")
    manifest = export_lib.InferenceGraphExporter.Export(
        task, theta, export_dir, quantize_int8=True)
    # the manifest records how every artifact leaf was laid out
    assert set(manifest["int8_layouts"]) == set(manifest["int8_weights"])
    lay = manifest["int8_layouts"]
    assert lay["emb.emb"] == {"layout": "vd", "contract_ndim": 1,
                              "stacked": False, "serving_eligible": True}
    atten = "stack.body.self_atten.atten."
    assert lay[atten + "w_post"]["layout"] == "vd"
    assert lay[atten + "w_post"]["contract_ndim"] == 2
    assert lay[atten + "w_query"] == {"layout": "dv", "contract_ndim": 1,
                                      "stacked": True,
                                      "serving_eligible": True}

    pred = export_lib.Predictor(export_dir)
    frozen = pred._theta
    ids = np.array([[5, 9, 2, 33, 17, 4, 8, 1]], np.int32)
    batch = NestedMap(ids=jnp.asarray(ids),
                      labels=jnp.asarray(np.roll(ids, -1, axis=1)),
                      paddings=jnp.zeros(ids.shape, jnp.float32))
    score = jax.jit(task.ScoreSequences)

    # freeze contract (export.py Export/QuantizeThetaInt8): the dequant-mode
    # serving theta IS the frozen theta, bit for bit — so scoring through it
    # matches the frozen-float export bitwise
    th_dq = pred.Int8ServingTheta(mode="dequant")
    for path, leaf in frozen.FlattenItems():
      np.testing.assert_array_equal(np.asarray(leaf),
                                    np.asarray(th_dq.Get(path)), err_msg=path)
    s_frozen = score(frozen, batch)
    s_dq = score(th_dq, batch)
    np.testing.assert_array_equal(np.asarray(s_frozen.label_log_probs),
                                  np.asarray(s_dq.label_log_probs))

    # integer-matmul mode: bounded, reported delta vs the frozen export
    th_i8 = pred.Int8ServingTheta(mode="int8")
    s_i8 = score(th_i8, batch)
    delta = np.abs(np.asarray(s_i8.label_log_probs) -
                   np.asarray(s_frozen.label_log_probs))
    assert float(delta.mean()) < 0.1 and float(delta.max()) < 0.5

  def test_gshard_decode_serve_int8_weights(self, tmp_path):
    """The batch-synchronous driver serves int8 weights behind the same
    flag and reports it (plus the KV census) in telemetry."""
    from lingvo_tpu.core import checkpointer as checkpointer_lib
    from lingvo_tpu.runners import gshard_decode
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    train_dir = str(tmp_path / "train")
    ckpt = checkpointer_lib.Checkpointer(train_dir)
    state = task.CreateTrainState(jax.random.PRNGKey(3))
    ckpt.Save(1, state, force=True)
    ckpt.Close()
    prompts = np.array([[5, 6, 7, 8], [9, 10, 0, 0]], np.int32)
    lens = np.array([4, 2], np.int32)

    d_f = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "f.jsonl"), max_decode_steps=4)
    d_8 = gshard_decode.GShardDecode(
        task, train_dir, str(tmp_path / "i8.jsonl"), max_decode_steps=4,
        serve_int8_weights=True)
    recs_f = d_f.DecodeOnce(1, prompts, lens)
    recs_8 = d_8.DecodeOnce(1, prompts, lens)
    for rf, r8 in zip(recs_f, recs_8):
      assert rf["output_ids"] == r8["output_ids"]
    t8 = d_8._last_telemetry
    assert t8["serve_int8_weights"] is True
    assert t8["kv_cache_dtype"] == "float32"
    assert t8["kv_bytes_per_token"] > 0
    # the rewrite is cached per checkpoint: a second call reuses it
    cached = d_8._int8_theta
    d_8.DecodeOnce(1, prompts, lens)
    assert d_8._int8_theta is cached
