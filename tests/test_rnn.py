"""RNN cell/layer tests (ref rnn_cell_test / rnn_layers_test coverage)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import recurrent, rnn_cell, rnn_layers
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(5)
B, T, D, H = 2, 8, 4, 6


def _cell(cls, **kw):
  p = cls.Params().Set(name="cell", num_input_nodes=D, num_output_nodes=H,
                       **kw)
  cell = p.Instantiate()
  return cell, cell.InstantiateVariables(KEY)


class TestCells:

  @pytest.mark.parametrize("cls", [
      rnn_cell.LSTMCellSimple, rnn_cell.LayerNormalizedLSTMCellSimple,
      rnn_cell.GRUCell, rnn_cell.SRUCell
  ])
  def test_step_shapes_and_finite(self, cls):
    cell, theta = _cell(cls)
    state = cell.InitState(B)
    x = jax.random.normal(KEY, (B, D))
    state1 = cell.FProp(theta, state, x)
    assert cell.GetOutput(state1).shape == (B, H)
    assert np.all(np.isfinite(np.asarray(cell.GetOutput(state1))))
    assert not np.allclose(cell.GetOutput(state1), 0.0)

  def test_padding_freezes_state(self):
    cell, theta = _cell(rnn_cell.LSTMCellSimple)
    state = cell.InitState(B)
    x = jax.random.normal(KEY, (B, D))
    s1 = cell.FProp(theta, state, x, padding=jnp.array([0.0, 1.0]))
    # row 1 padded: state unchanged
    np.testing.assert_allclose(s1.m[1], state.m[1])
    assert not np.allclose(s1.m[0], state.m[0])

  def test_lstm_projection(self):
    cell, theta = _cell(rnn_cell.LSTMCellSimple, num_hidden_nodes=12)
    assert theta.w_proj.shape == (12, H)
    state = cell.InitState(B)
    assert state.c.shape == (B, 12) and state.m.shape == (B, H)
    s1 = cell.FProp(theta, state, jnp.ones((B, D)))
    assert s1.m.shape == (B, H)

  def test_forget_gate_bias_effect(self):
    c1, t1 = _cell(rnn_cell.LSTMCellSimple, forget_gate_bias=0.0)
    c2 = rnn_cell.LSTMCellSimple.Params().Set(
        name="cell", num_input_nodes=D, num_output_nodes=H,
        forget_gate_bias=5.0).Instantiate()
    # same weights, different forget bias -> different cell evolution
    state = c1.InitState(B)
    state = NestedMap(m=jnp.ones((B, H)) * 0.3, c=jnp.ones((B, H)) * 0.5)
    x = jnp.ones((B, D))
    s_a = c1.FProp(t1, state, x)
    s_b = c2.FProp(t1, state, x)
    assert float(jnp.abs(s_b.c).mean()) > float(jnp.abs(s_a.c).mean())


class TestRecurrent:

  def test_scan_matches_loop(self):
    cell, theta = _cell(rnn_cell.LSTMCellSimple)
    xs = jax.random.normal(KEY, (T, B, D))
    state = cell.InitState(B)
    inputs = NestedMap(x=xs, padding=jnp.zeros((T, B)))

    def cell_fn(th, s, inp):
      return cell.FProp(th, s, inp.x, inp.padding)

    all_states, final = recurrent.Recurrent(theta, state, inputs, cell_fn)
    # manual loop
    s = cell.InitState(B)
    for t in range(T):
      s = cell.FProp(theta, s, xs[t])
    np.testing.assert_allclose(np.asarray(final.m), np.asarray(s.m),
                               rtol=1e-5)
    assert all_states.m.shape == (T, B, H)

  def test_remat_same_grads(self):
    cell, theta = _cell(rnn_cell.GRUCell)
    xs = jax.random.normal(KEY, (T, B, D))
    inputs = NestedMap(x=xs, padding=jnp.zeros((T, B)))

    def loss(th, remat):
      _, final = recurrent.Recurrent(
          th, cell.InitState(B), inputs,
          lambda t_, s, i: cell.FProp(t_, s, i.x, i.padding), remat=remat)
      return jnp.sum(jnp.square(final.m))

    g1 = jax.grad(lambda th: loss(th, False))(theta)
    g2 = jax.grad(lambda th: loss(th, True))(theta)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
      np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)

  def test_numeric_gradient_check(self):
    """Finite differences vs autodiff through the scan (ref
    recurrent_test.py numeric grad checks)."""
    cell, theta = _cell(rnn_cell.SRUCell)
    xs = jax.random.normal(KEY, (4, 1, D))
    inputs = NestedMap(x=xs, padding=jnp.zeros((4, 1)))

    def loss_w(w):
      th = theta.Copy()
      th.w = w
      _, final = recurrent.Recurrent(
          th, cell.InitState(1), inputs,
          lambda t_, s, i: cell.FProp(t_, s, i.x, i.padding))
      return jnp.sum(final.m)

    g = jax.grad(loss_w)(theta.w)
    eps = 1e-3
    w = np.asarray(theta.w).copy()
    idxs = [(0, 0), (1, 5), (3, 2 * H + 1)]
    for i, j in idxs:
      w_p, w_m = w.copy(), w.copy()
      w_p[i, j] += eps
      w_m[i, j] -= eps
      fd = (float(loss_w(jnp.asarray(w_p))) -
            float(loss_w(jnp.asarray(w_m)))) / (2 * eps)
      np.testing.assert_allclose(float(g[i, j]), fd, rtol=0.05, atol=1e-3)


class TestRnnLayers:

  def test_frnn_shapes_and_padding(self):
    p = rnn_layers.FRNN.Params().Set(
        name="frnn",
        cell=rnn_cell.LSTMCellSimple.Params().Set(
            num_input_nodes=D, num_output_nodes=H))
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    x = jax.random.normal(KEY, (B, T, D))
    paddings = jnp.zeros((B, T)).at[1, 4:].set(1.0)
    out, final = layer.FProp(theta, x, paddings)
    assert out.shape == (B, T, H)
    # padded tail: output equals the frozen state at t=3
    np.testing.assert_allclose(np.asarray(out[1, 4]), np.asarray(out[1, 7]),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(final.m[1]), np.asarray(out[1, 3]),
                               rtol=1e-5)

  def test_frnn_reverse_flips_time(self):
    cellp = rnn_cell.GRUCell.Params().Set(
        num_input_nodes=D, num_output_nodes=H)
    fwd = rnn_layers.FRNN.Params().Set(name="f", cell=cellp).Instantiate()
    theta = fwd.InstantiateVariables(KEY)
    rev = rnn_layers.FRNN.Params().Set(
        name="f", cell=cellp, reverse=True).Instantiate()
    x = jax.random.normal(KEY, (B, T, D))
    out_f, _ = fwd.FProp(theta, x)
    out_r, _ = rev.FProp(theta, jnp.flip(x, axis=1))
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(jnp.flip(out_r, axis=1)), rtol=1e-5)

  def test_bidirectional(self):
    p = rnn_layers.BidirectionalFRNN.Params().Set(
        name="birnn",
        fwd=rnn_cell.LSTMCellSimple.Params().Set(
            num_input_nodes=D, num_output_nodes=H),
        bak=rnn_cell.LSTMCellSimple.Params().Set(
            num_input_nodes=D, num_output_nodes=H))
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    out = layer.FProp(theta, jax.random.normal(KEY, (B, T, D)))
    assert out.shape == (B, T, 2 * H)

  def test_stacked_with_residual(self):
    p = rnn_layers.StackedFRNNLayerByLayer.Params().Set(
        name="stack", num_layers=3, num_input_nodes=D, num_output_nodes=D,
        cell_tpl=rnn_cell.SRUCell.Params())
    layer = p.Instantiate()
    theta = layer.InstantiateVariables(KEY)
    out = layer.FProp(theta, jax.random.normal(KEY, (B, T, D)))
    assert out.shape == (B, T, D)

  def test_frnn_trains(self):
    """FRNN learns a toy cumulative-sum-sign task end to end."""
    from lingvo_tpu.core import learner as learner_lib
    from lingvo_tpu.core import optimizer as opt_lib
    p = rnn_layers.FRNN.Params().Set(
        name="frnn",
        cell=rnn_cell.GRUCell.Params().Set(
            num_input_nodes=1, num_output_nodes=8))
    layer = p.Instantiate()
    theta = NestedMap(
        rnn=layer.InstantiateVariables(KEY),
        readout=jax.random.normal(KEY, (8, 1)) * 0.1)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 10, 1).astype("float32")
    y = (np.cumsum(x[:, :, 0], axis=1) > 0).astype("float32")

    def loss_fn(th):
      out, _ = layer.FProp(th.rnn, jnp.asarray(x))
      logits = (out @ th.readout)[:, :, 0]
      return jnp.mean(
          jnp.maximum(logits, 0) - logits * y +
          jnp.log1p(jnp.exp(-jnp.abs(logits))))

    lrn = learner_lib.Learner.Params().Set(
        name="l", learning_rate=0.05,
        optimizer=opt_lib.Adam.Params()).Instantiate()
    state = lrn.InitState(theta)
    step = jax.jit(lambda th, s: (lambda g: lrn.Apply(th, g, 0, s))(
        jax.grad(loss_fn)(th)))
    first = float(loss_fn(theta))
    for _ in range(60):
      theta, state, _ = step(theta, state)
    assert float(loss_fn(theta)) < 0.6 * first
