"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's multi-virtual-device-in-one-process testing strategy
(SURVEY.md §4) but with real SPMD on fake devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Tests are CPU-only: drop any non-cpu PJRT plugin factories (e.g. a tunneled
# TPU plugin injected via sitecustomize) so backend init can't block on a
# remote handshake.
try:
  import jax  # noqa: E402  (may already be imported by sitecustomize)
  # chex/checkify and pallas register lowering rules for the 'tpu' platform
  # at import; do it BEFORE we strip non-cpu plugin factories or the
  # registration fails.
  try:
    import chex  # noqa: E402,F401
  except ImportError:
    pass
  try:
    import jax.experimental.pallas  # noqa: E402,F401
    import jax.experimental.pallas.tpu  # noqa: E402,F401
  except ImportError:
    pass
  from jax._src import xla_bridge  # noqa: E402

  # sitecustomize may have imported jax with JAX_PLATFORMS=axon already
  # baked into the config: force it back to cpu.
  jax.config.update("jax_platforms", "cpu")
  for _name in list(getattr(xla_bridge, "_backend_factories", {})):
    if _name not in ("cpu", "interpreter"):
      xla_bridge._backend_factories.pop(_name, None)
except Exception:
  pass

# Goldens were recorded under jax<=0.4.36's default of partitionable
# threefry (also the sharding-friendly lowering: no gathers under GSPMD);
# 0.4.37 flipped the default back to False, so pin it explicitly.
try:
  import jax  # noqa: E402

  jax.config.update("jax_threefry_partitionable", True)
except Exception:
  pass

# Persistent compile cache (same dir bench.py uses): a cold tier-1 run sits
# at the edge of the driver's verify budget; warm reruns are much faster.
# Keep the cache primed by running the suite once after growing it. Own try
# block: a failure here (or in the pruning above) must not silently take the
# other down with it.
try:
  import jax  # noqa: E402

  _cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, ".jax_cache")
  os.makedirs(_cache_dir, exist_ok=True)
  jax.config.update("jax_compilation_cache_dir", _cache_dir)
  jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
  pass

# -- shared tiny LMs (session-scoped) -----------------------------------------
# One instantiation of each tiny model serves EVERY serving-stack test
# module (test_serving_engine / test_spec_decode / test_ragged_step /
# test_tree_spec): theta init and jit warm-up are the dominant fixture
# cost, and hoisting them session-wide is what keeps the suite inside the
# verify budget as the serving matrix grows.

import pytest  # noqa: E402


def TinyLmParams(every_n=None, num_layers=2, use_repeat=False, **overrides):
  """The stack-under-test: 2-layer rotary TransformerLm, vocab 64.

  every_n switches attention mixers for GatedSSMLayer every n layers
  (0 = pure O(1)-state stack, the only shape ModelDraft accepts)."""
  from lingvo_tpu.core import ssm
  from lingvo_tpu.models.lm import layers as lm_layers
  p = lm_layers.TransformerLm.Params().Set(
      name="lm", vocab_size=64, model_dim=32, num_layers=num_layers,
      num_heads=2, hidden_dim=64, use_rotary=True)
  if every_n is not None:
    p = p.Set(use_repeat_layer=use_repeat,
              mixer_tpl=ssm.GatedSSMLayer.Params().Set(state_dim=8,
                                                       chunk_size=4),
              mixer_atten_every_n=every_n)
  return p.Set(**overrides)


def InstantiateLm(p, seed=0):
  import jax
  task = p.Instantiate()
  task.FinalizePaths()
  theta = task.InstantiateVariables(jax.random.PRNGKey(seed))
  return task, theta


@pytest.fixture(scope="session")
def tiny_lm():
  return InstantiateLm(TinyLmParams())


@pytest.fixture(scope="session")
def tiny_lm_swapped(tiny_lm):
  # the same task with a different checkpoint — the "new theta" of hot
  # UpdateTheta swap tests. Session-scoped so its id is stable for the
  # _GreedyRef memo key in test_serving_engine.
  import jax
  task, _ = tiny_lm
  return task, task.InstantiateVariables(jax.random.PRNGKey(7))


@pytest.fixture(scope="session")
def hybrid_lm():
  # flat (non-repeat) stack so a 1-layer early-exit prefix is legal; the
  # repeat-stack prefix path gets its own engine tests
  return InstantiateLm(TinyLmParams(every_n=2, use_repeat=False))


@pytest.fixture(scope="session")
def ssm_draft_lm():
  # pure O(1)-state stack: the only shape ModelDraft accepts (pageless)
  return InstantiateLm(TinyLmParams(every_n=0), seed=1)
