"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's multi-virtual-device-in-one-process testing strategy
(SURVEY.md §4) but with real SPMD on fake devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# Tests are CPU-only: drop any non-cpu PJRT plugin factories (e.g. a tunneled
# TPU plugin injected via sitecustomize) so backend init can't block on a
# remote handshake.
try:
  import jax  # noqa: E402  (may already be imported by sitecustomize)
  # chex/checkify and pallas register lowering rules for the 'tpu' platform
  # at import; do it BEFORE we strip non-cpu plugin factories or the
  # registration fails.
  try:
    import chex  # noqa: E402,F401
  except ImportError:
    pass
  try:
    import jax.experimental.pallas  # noqa: E402,F401
    import jax.experimental.pallas.tpu  # noqa: E402,F401
  except ImportError:
    pass
  from jax._src import xla_bridge  # noqa: E402

  # sitecustomize may have imported jax with JAX_PLATFORMS=axon already
  # baked into the config: force it back to cpu.
  jax.config.update("jax_platforms", "cpu")
  for _name in list(getattr(xla_bridge, "_backend_factories", {})):
    if _name not in ("cpu", "interpreter"):
      xla_bridge._backend_factories.pop(_name, None)
except Exception:
  pass

# Goldens were recorded under jax<=0.4.36's default of partitionable
# threefry (also the sharding-friendly lowering: no gathers under GSPMD);
# 0.4.37 flipped the default back to False, so pin it explicitly.
try:
  import jax  # noqa: E402

  jax.config.update("jax_threefry_partitionable", True)
except Exception:
  pass

# Persistent compile cache (same dir bench.py uses): a cold tier-1 run sits
# at the edge of the driver's verify budget; warm reruns are much faster.
# Keep the cache primed by running the suite once after growing it. Own try
# block: a failure here (or in the pruning above) must not silently take the
# other down with it.
try:
  import jax  # noqa: E402

  _cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, ".jax_cache")
  os.makedirs(_cache_dir, exist_ok=True)
  jax.config.update("jax_compilation_cache_dir", _cache_dir)
  jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
  pass
