"""Test config: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's multi-virtual-device-in-one-process testing strategy
(SURVEY.md §4) but with real SPMD on fake devices.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
  os.environ["XLA_FLAGS"] = (
      flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
