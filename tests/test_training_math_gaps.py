"""EGDD, GradDrop, gradient combiners, DevBasedSchedule, scatter_update."""

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import (graddrop, gradient_combiner, optimizer,
                             scatter_update, schedule)
from lingvo_tpu.core.nested_map import NestedMap

KEY = jax.random.PRNGKey(11)


class TestEGDD:

  def _opt(self):
    return optimizer.EGDD.Params().Set(name="egdd").Instantiate()

  def test_reduces_quadratic_loss(self):
    opt = self._opt()
    params = NestedMap(w=jnp.array([2.0, -3.0, 1.0]))
    state = opt.InitState(params)

    def loss(p):
      return jnp.sum(p.w ** 2)

    l0 = float(loss(params))
    for step in range(30):
      grads = jax.grad(loss)(params)
      params, state = opt.Update(state, grads, params, 0.05, step)
    assert float(loss(params)) < 0.2 * l0

  def test_bf16_params_scan_stable_state(self):
    """Optimizer state dtypes must be stable across steps (lax.scan carry)."""
    opt = self._opt()
    params = NestedMap(w=jnp.ones((4,), jnp.bfloat16))
    state0 = opt.InitState(params)

    def body(carry, _):
      params, state = carry
      grads = NestedMap(w=jnp.full((4,), 0.1, jnp.bfloat16))
      params, state = opt.Update(state, grads, params, 0.01, 0)
      return (params, state), ()

    (params, _), _ = jax.lax.scan(body, (params, state0), None, length=3)
    assert params.w.dtype == jnp.bfloat16

  def test_gain_and_scale_clipped(self):
    opt = self._opt()
    params = NestedMap(w=jnp.ones((4,)))
    state = opt.InitState(params)
    for step in range(50):
      grads = NestedMap(w=jnp.full((4,), 100.0))  # consistent huge grads
      params, state = opt.Update(state, grads, params, 0.01, step)
    assert float(jnp.max(state.gain.w)) <= opt.p.max_gain + 1e-6
    assert float(state.lr_scale.w) <= opt.p.max_scale + 1e-6


class TestGradDrop:

  def test_forward_is_identity(self):
    x = jax.random.normal(KEY, (4, 8))
    cfg = graddrop.GradDropConfig()
    xs = graddrop.GradDropSplit(x, KEY, 3, cfg)
    assert len(xs) == 3
    for xi in xs:
      np.testing.assert_allclose(np.asarray(xi), np.asarray(x))

  def test_agreeing_grads_pass_through_norm_preserved(self):
    """Two identical losses: no sign conflicts, combined grad keeps the
    original direction and norm."""
    x = jax.random.normal(KEY, (4, 8))
    cfg = graddrop.GradDropConfig()

    def total(x):
      a, b = graddrop.GradDropSplit(x, KEY, 2, cfg)
      return jnp.sum(a * 2.0) + jnp.sum(b * 2.0)

    g = jax.grad(total)(x)
    g_ref = jax.grad(lambda x: jnp.sum(x * 4.0))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-5)

  def test_conflicting_grads_are_sign_dropped(self):
    """Opposite-sign per-task grads: each element keeps only one task's
    contribution (up to the gradnorm rescale), never the zero sum."""
    x = jnp.ones((2, 4))
    cfg = graddrop.GradDropConfig(keep_gradnorm_constant=False,
                                  marginalize_batch_dim=False,
                                  use_input_sign_only=True)

    def total(x):
      a, b = graddrop.GradDropSplit(x, KEY, 2, cfg)
      return jnp.sum(a) - jnp.sum(b)  # grads +1 and -1 everywhere

    g = np.asarray(jax.grad(total)(x))
    # plain backprop would give exactly 0; GradDrop picks a sign per element
    assert np.all(np.abs(g) == 1.0), g

  def test_leak_passes_original(self):
    x = jnp.ones((2, 4))
    cfg = graddrop.GradDropConfig(leak_ratios=(1.0, 1.0),
                                  keep_gradnorm_constant=False)

    def total(x):
      a, b = graddrop.GradDropSplit(x, KEY, 2, cfg)
      return jnp.sum(a) - jnp.sum(b)

    g = np.asarray(jax.grad(total)(x))
    np.testing.assert_allclose(g, 0.0)  # full leak = plain sum = 0


class TestGradientCombiners:

  def _lg(self, gdicts):
    out = {}
    for name, g in gdicts.items():
      out[name] = NestedMap(loss_metric=(jnp.asarray(1.0), 1.0),
                            grads=NestedMap(w=jnp.asarray(g)))
    return out

  def test_linear(self):
    comb = gradient_combiner.LinearCombiner.Params().Instantiate()
    vmap = NestedMap(w=jnp.zeros(2))
    out = comb.Combine(vmap, self._lg({"a": [1.0, 0.0], "b": [0.0, 2.0]}))
    np.testing.assert_allclose(np.asarray(out.w), [1.0, 2.0])

  def test_pcgrad_projects_conflict(self):
    comb = gradient_combiner.PCGradCombiner.Params().Instantiate()
    vmap = NestedMap(w=jnp.zeros(2))
    # g_a = (1, 0); g_b = (-1, 1): conflicting (<g_a, g_b> = -1)
    out = comb.Combine(vmap, self._lg({"a": [1.0, 0.0], "b": [-1.0, 1.0]}))
    # PCGrad: a' = a - (a.b/|b|^2) b = (0.5, 0.5); b' = b - (b.a/|a|^2) a
    # = (0, 1); sum = (0.5, 1.5)
    np.testing.assert_allclose(np.asarray(out.w), [0.5, 1.5], rtol=1e-5)

  def test_pcgrad_no_conflict_is_sum(self):
    comb = gradient_combiner.PCGradCombiner.Params().Instantiate()
    vmap = NestedMap(w=jnp.zeros(2))
    out = comb.Combine(vmap, self._lg({"a": [1.0, 0.0], "b": [0.0, 1.0]}))
    np.testing.assert_allclose(np.asarray(out.w), [1.0, 1.0], rtol=1e-5)


class TestDevBasedSchedule:

  def test_decays_on_plateau(self, tmp_path):
    from lingvo_tpu.core import early_stop
    mh = early_stop.MetricHistory(str(tmp_path), "eval", "loss")
    sched = schedule.DevBasedSchedule.Params().Set(
        window=100, decay=0.5, min_factor=0.1).Instantiate()
    sched.SetMetricHistory(mh)

    mh.ConditionalAppend(10, 1.0)   # best at step 10
    mh.ConditionalAppend(50, 1.2)
    assert not sched.UpdateFromHistory()      # 50 - 10 < window
    assert float(sched.Value(0)) == 1.0

    mh.ConditionalAppend(200, 1.3)            # 200 - 10 > window -> decay
    assert sched.UpdateFromHistory()
    assert float(sched.Value(0)) == 0.5
    assert sched.HostStateKey() == 0.5

    mh.ConditionalAppend(250, 1.4)            # ref_step moved to 200
    assert not sched.UpdateFromHistory()
    mh.ConditionalAppend(350, 1.5)
    assert sched.UpdateFromHistory()
    assert float(sched.Value(0)) == 0.25

  def test_floor(self, tmp_path):
    from lingvo_tpu.core import early_stop
    mh = early_stop.MetricHistory(str(tmp_path), "eval", "loss")
    sched = schedule.DevBasedSchedule.Params().Set(
        window=10, decay=0.1, min_factor=0.3).Instantiate()
    sched.SetMetricHistory(mh)
    mh.ConditionalAppend(1, 1.0)
    step = 1
    for _ in range(5):
      step += 100
      mh.ConditionalAppend(step, 2.0)
      sched.UpdateFromHistory()
    assert abs(float(sched.Value(0)) - 0.3) < 1e-6

  def test_program_refresh_drops_cached_fn(self, tmp_path):
    """A multiplier change must invalidate TrainProgram's jitted loop."""
    from lingvo_tpu import model_registry
    import lingvo_tpu.models.all_params  # noqa: F401
    from lingvo_tpu.core import early_stop
    from lingvo_tpu.runners import program as program_lib

    mp = model_registry.GetParams("lm.synthetic_packed_input.DenseLmTiny",
                                  "Train")
    mp.task.input = mp.input
    mh = early_stop.MetricHistory(str(tmp_path), "eval", "loss")
    mp.task.train.learner.lr_schedule = (
        schedule.DevBasedSchedule.Params().Set(window=10, decay=0.5,
                                               history_path=mh.path))
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    tp = program_lib.TrainProgram.Params().Set(
        task=mp.task, logdir=str(tmp_path), steps_per_loop=2,
        on_device_loop=False)
    prog = program_lib.TrainProgram(tp, task=task,
                                    input_generator=mp.input.Instantiate())
    state, _ = prog.Run(state)
    fn1 = prog._step_fn
    assert fn1 is not None
    state, _ = prog.Run(state)
    assert prog._step_fn is fn1          # unchanged -> cache kept
    mh.ConditionalAppend(1, 1.0)
    mh.ConditionalAppend(100, 2.0)       # plateau > window -> decay
    state, _ = prog.Run(state)
    assert prog._step_fn is not fn1      # cache dropped and rebuilt


class TestScatterUpdate:

  def test_update_and_add(self):
    x = jnp.zeros((4, 3))
    y = scatter_update.Update(x, 2, jnp.ones((3,)))
    assert float(y[2, 0]) == 1.0 and float(y[0, 0]) == 0.0
    z = scatter_update.Add(y, 2, jnp.ones((3,)))
    assert float(z[2, 1]) == 2.0

  def test_inplace_context_noop(self):
    with scatter_update.SetInplaceUpdate(True):
      x = scatter_update.Update(jnp.zeros((2,)), 0, 5.0)
    assert float(x[0]) == 5.0

  def test_restart_replay_recovers_multiplier(self, tmp_path):
    """A fresh schedule instance recovers the decayed factor from the
    history file alone (restart safety; no checkpointed state)."""
    from lingvo_tpu.core import early_stop
    mh = early_stop.MetricHistory(str(tmp_path), "eval", "loss")
    mh.ConditionalAppend(1, 1.0)
    mh.ConditionalAppend(200, 2.0)   # decay 1
    mh.ConditionalAppend(400, 2.1)   # decay 2
    p = schedule.DevBasedSchedule.Params().Set(window=100, decay=0.5,
                                               min_factor=0.01)
    s1 = p.Instantiate(); s1.SetMetricHistory(mh)
    s1.UpdateFromHistory()
    s2 = p.Instantiate(); s2.SetMetricHistory(mh)  # "restarted" job
    s2.UpdateFromHistory()
    assert float(s1.Value(0)) == float(s2.Value(0)) == 0.25
