"""KITTI-format car pipeline: label/calib parsing, file-based scene input
over the native yielder, and the e2e fixture test (train -> decode with
oriented NMS -> mAP + breakdown metrics). VERDICT r2 Next #4."""

import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu import model_registry
import lingvo_tpu.models.all_params  # noqa: F401
from lingvo_tpu.models.car import breakdown_metric, kitti_input


def _LabelLine(velo_box, cls="Car"):
  """Builds a KITTI label line whose parsed bbox3d == velo_box [7]."""
  x, y, z, l, w, h, phi = [float(v) for v in velo_box]
  rot_y = -(phi + math.pi / 2.0)
  # nominal velo->cam: cam_x = -velo_y, cam_y = -velo_z, cam_z = velo_x
  zb = z - h / 2.0  # KITTI location is at the box bottom
  cam = (-y, -zb, x)
  return (f"{cls} 0.00 0 0.0 0 0 50 50 "
          f"{h:.3f} {w:.3f} {l:.3f} {cam[0]:.3f} {cam[1]:.3f} {cam[2]:.3f} "
          f"{rot_y:.4f}")


class TestLabelParsing:

  def test_parse_valid_line(self):
    obj = kitti_input.ParseKittiLabelLine(
        "Car 0.00 0 -1.58 587.01 173.33 614.12 200.12 "
        "1.65 1.67 3.64 -0.65 1.71 46.70 -1.59")
    assert obj["type"] == "Car"
    assert obj["dimensions"] == [1.65, 1.67, 3.64]
    assert obj["location"] == [-0.65, 1.71, 46.70]
    assert obj["score"] == -1

  def test_invalid_type_and_token_count_raise(self):
    with pytest.raises(ValueError, match="invalid type"):
      kitti_input.ParseKittiLabelLine(
          "Robot 0 0 0 0 0 0 0 1 1 1 0 0 0 0")
    with pytest.raises(ValueError, match="tokens"):
      kitti_input.ParseKittiLabelLine("Car 1 2 3")

  def test_box_conversion_round_trip(self):
    box = np.array([10.0, 3.0, 0.5, 4.0, 1.6, 1.5, 0.4], np.float32)
    obj = kitti_input.ParseKittiLabelLine(_LabelLine(box))
    got = kitti_input.KittiObjectToBBox3D(obj)
    np.testing.assert_allclose(got[:6], box[:6], atol=1e-3)
    assert abs(math.sin(got[6] - box[6])) < 1e-3

  def test_no_3d_info_returns_none(self):
    obj = kitti_input.ParseKittiLabelLine(
        "DontCare -1 -1 -10 0 0 50 50 -1 -1 -1 -1000 -1000 -1000 -10")
    assert kitti_input.KittiObjectToBBox3D(obj) is None

  def test_calib_matrices_invert(self):
    calib = {
        "R0_rect": [0.9999, 0.01, 0, -0.01, 0.9999, 0, 0, 0, 1.0],
        "Tr_velo_to_cam": [0, -1, 0, -0.02, 0, 0, -1, -0.06, 1, 0, 0, -0.4],
    }
    v2c = kitti_input.VeloToCameraTransformation(calib)
    c2v = kitti_input.CameraToVeloTransformation(calib)
    np.testing.assert_allclose(v2c @ c2v, np.eye(4), atol=1e-6)


def _WriteScenes(path, num_scenes=8, seed=7):
  """JSONL fixture: boxes inside the tiny model's [0, 16) grid with
  class-colored points inside each box."""
  rng = np.random.RandomState(seed)
  with open(path, "w") as f:
    for _ in range(num_scenes):
      labels, pts = [], []
      for _ in range(3):
        cx, cy = rng.uniform(2, 14, 2)
        cz = rng.uniform(-0.5, 0.5)
        l, w, h = rng.uniform(0.8, 2.0, 3)
        phi = rng.uniform(-math.pi, math.pi)
        cls = rng.choice(["Car", "Pedestrian"])
        labels.append(_LabelLine([cx, cy, cz, l, w, h, phi], cls))
        cls_id = kitti_input.CLASS_IDS[cls]
        for _ in range(12):
          pts.append([cx + rng.uniform(-l / 2, l / 2),
                      cy + rng.uniform(-w / 2, w / 2),
                      cz + rng.uniform(-h / 2, h / 2), float(cls_id)])
      f.write(json.dumps({"points": pts, "labels": labels}) + "\n")


class TestKittiSceneInput:

  def test_process_record_shapes_and_boxes(self, tmp_path):
    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        name="kitti", batch_size=2, max_points=64, max_objects=4)
    gen = p.Instantiate()
    box = [5.0, 6.0, 0.0, 2.0, 1.0, 1.0, 0.3]
    rec = json.dumps({
        "points": [[5.0, 6.0, 0.0, 1.0]] * 3,
        "labels": [_LabelLine(box), _LabelLine(box, "DontCare")],
    }).encode()
    ex = gen.ProcessRecord(rec)
    assert ex.lasers.shape == (64, 4)
    assert ex.gt_boxes.shape == (4, 7)
    np.testing.assert_allclose(ex.gt_boxes[0][:6], box[:6], atol=1e-3)
    assert ex.gt_classes[0] == 1 and ex.gt_classes[1] == 0  # DontCare drop
    assert (ex.laser_paddings == 0).sum() == 3
    assert ex.reg_weights.sum() == 1.0  # one grid cell carries the target

  def test_batches_from_file(self, tmp_path):
    path = str(tmp_path / "scenes.jsonl")
    _WriteScenes(path, num_scenes=6)
    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        name="kitti", batch_size=2, max_points=64, max_objects=4,
        file_pattern=f"text:{path}")
    gen = p.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.lasers.shape == (2, 64, 4)
    assert batch.gt_boxes.shape == (2, 4, 7)
    assert (np.asarray(batch.gt_classes) > 0).any()


class TestKittiInputHardening:

  def test_batch_size_propagates_to_batcher(self, tmp_path):
    path = str(tmp_path / "scenes.jsonl")
    _WriteScenes(path, num_scenes=8)
    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        name="kitti", batch_size=4, max_points=32, max_objects=4,
        file_pattern=f"text:{path}")
    gen = p.Instantiate()
    batch = gen.GetPreprocessedInputBatch()
    assert batch.lasers.shape[0] == 4  # not the bucket default

  def test_malformed_label_line_drops_record(self):
    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        name="kitti", batch_size=2)
    gen = p.Instantiate()
    bad = json.dumps({"points": [], "labels": ["Car 1 2 3"]}).encode()
    assert gen.ProcessRecord(bad) is None
    assert gen.ProcessRecord(b"not json") is None

  def test_real_kitti_grid_ranges(self):
    # negative-y boxes land in the grid when ranges cover them
    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        name="kitti", batch_size=2, grid_size=8,
        grid_range_x=(0.0, 70.4), grid_range_y=(-40.0, 40.0))
    gen = p.Instantiate()
    box = [35.0, -20.0, 0.0, 4.0, 1.6, 1.5, 0.0]
    rec = json.dumps({"points": [[35.0, -20.0, 0.0, 1.0]],
                      "labels": [_LabelLine(box)]}).encode()
    ex = gen.ProcessRecord(rec)
    assert ex.reg_weights.sum() == 1.0
    cell = int(np.argmax(ex.reg_weights))
    row, col = cell // 8, cell % 8
    assert row == int((-20.0 + 40) / 80 * 8) and col == int(35.0 / 70.4 * 8)

  def test_num_classes_filters_types(self):
    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        name="kitti", batch_size=2, num_classes=1)  # Car only
    gen = p.Instantiate()
    box = [5.0, 6.0, 0.0, 2.0, 1.0, 1.0, 0.3]
    rec = json.dumps({"points": [],
                      "labels": [_LabelLine(box, "Car"),
                                 _LabelLine(box, "Pedestrian")]}).encode()
    ex = gen.ProcessRecord(rec)
    assert (np.asarray(ex.gt_classes) > 0).sum() == 1


class TestKittiE2E:

  def test_train_decode_map_with_nms(self, tmp_path):
    """KITTI fixture end to end: file input -> StarNet train -> oriented-NMS
    decode -> AP + distance-breakdown AP."""
    path = str(tmp_path / "scenes.jsonl")
    _WriteScenes(path, num_scenes=8)

    mp = model_registry.GetParams("car.kitti.StarNetCarTiny", "Train")
    mp.task.num_classes = 3
    mp.task.use_oriented_nms = True
    mp.task.max_detections = 4
    mp.input = kitti_input.KittiSceneInputGenerator.Params().Set(
        name="kitti", batch_size=2, max_points=64, max_objects=4,
        num_classes=3, file_pattern=f"text:{path}")
    mp.task.input = mp.input
    task = mp.task.Instantiate()
    task.FinalizePaths()
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    gen = mp.input.Instantiate()

    step = jax.jit(task.TrainStep, donate_argnums=(0,))
    losses = []
    for _ in range(6):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step(state, batch)
      losses.append(float(out.metrics.loss[0]))
    assert np.isfinite(losses).all()

    batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
    dec = jax.jit(task.Decode)(state.theta, batch)
    assert dec.boxes.shape[-1] == 7
    metrics = task.CreateDecoderMetrics()
    task.PostProcessDecodeOut(dec, metrics)
    res = task.DecodeFinalize(metrics)
    assert 0.0 <= res["ap"] <= 1.0

    # breakdown AP by distance over the same decode output
    bd = breakdown_metric.ByDistance(max_distance=20.0, num_bins=2)
    boxes = np.asarray(dec.boxes)
    scores = np.asarray(dec.scores)
    classes = np.asarray(dec.classes)
    gtb = np.asarray(dec.gt_boxes)
    gtc = np.asarray(dec.gt_classes)
    for i in range(boxes.shape[0]):
      valid = scores[i] > 0
      gt_mask = gtc[i] > 0
      bd.Update(boxes[i][valid], scores[i][valid], gtb[i][gt_mask],
                pred_classes=classes[i][valid], gt_classes=gtc[i][gt_mask])
    vals = bd.value
    assert set(vals) == {"dist_0_10", "dist_10_20"}
    assert all(0.0 <= v <= 1.0 for v in vals.values())


class TestKittiConverter:

  def test_raw_tree_to_jsonl_feeds_input(self, tmp_path):
    """Raw KITTI layout -> JSONL -> KittiSceneInputGenerator batches."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "kitti_to_jsonl",
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "tools", "kitti_to_jsonl.py"))
    conv = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(conv)

    root = tmp_path / "training"
    for sub in ("velodyne", "label_2", "calib"):
      (root / sub).mkdir(parents=True)
    rng = np.random.RandomState(0)
    for i in range(3):
      pts = rng.uniform(0, 15, (50, 4)).astype(np.float32)
      pts.tofile(root / "velodyne" / f"{i:06d}.bin")
      (root / "label_2" / f"{i:06d}.txt").write_text(
          "Car 0.00 0 1.57 0 0 50 50 1.5 1.6 4.0 5.0 1.0 10.0 -1.57\n")
      (root / "calib" / f"{i:06d}.txt").write_text(
          "R0_rect: 1 0 0 0 1 0 0 0 1\n"
          "Tr_velo_to_cam: 0 -1 0 0 0 0 -1 0 1 0 0 0\n")
    out = tmp_path / "scenes.jsonl"
    n = conv.Convert(str(root), str(out))
    assert n == 3

    p = kitti_input.KittiSceneInputGenerator.Params().Set(
        batch_size=2, file_pattern=f"text:{out}", num_classes=3,
        max_points=64, max_objects=4, grid_size=8,
        grid_range_x=(0.0, 16.0), grid_range_y=(-8.0, 8.0))
    gen = p.Instantiate()
    b = gen.GetPreprocessedInputBatch()
    assert b.lasers.shape == (2, 64, 4)
    assert (np.asarray(b.gt_classes) == 1).any()  # the Car survived


class TestCalibration:

  def test_curve_and_ece(self):
    from lingvo_tpu.models.car import calibration
    # perfectly calibrated: score == empirical hit rate
    scores = np.concatenate([np.full(50, 0.25), np.full(50, 0.75)])
    hits = np.concatenate([(np.arange(50) < 13), (np.arange(50) < 37)])
    pred, emp, counts = calibration.CalibrationCurve(scores, hits, 10)
    assert counts.sum() == 100
    ece = calibration.ExpectedCalibrationError(pred, emp, counts)
    assert ece < 0.02, ece
    # badly calibrated: confident but always wrong
    m = calibration.CalibrationMetric()
    m.Update(np.full(100, 0.9), np.zeros(100))
    assert m.value > 0.8

  def test_from_ap_metric(self):
    from lingvo_tpu.models.car import ap_metric, calibration
    m = ap_metric.ApMetric(iou_threshold=0.5)
    gt = np.array([[0, 0, 0, 4, 2, 1.5, 0.0]])
    pred = np.concatenate([gt, [[50, 50, 0, 4, 2, 1.5, 0.0]]])
    m.Update(pred, np.array([0.9, 0.8]), gt)
    cal = calibration.CalibrationMetric().FromApMetric(m)
    assert cal.total_weight == 2  # one hit, one miss accumulated

  def test_kitti_difficulty_protocol(self):
    from lingvo_tpu.models.car import kitti_input
    easy = {"bbox": [0, 0, 10, 50], "occluded": 0, "truncated": 0.1}
    mod = {"bbox": [0, 0, 10, 30], "occluded": 1, "truncated": 0.2}
    hard = {"bbox": [0, 0, 10, 30], "occluded": 2, "truncated": 0.4}
    excl = {"bbox": [0, 0, 10, 10], "occluded": 3, "truncated": 0.9}
    assert kitti_input.KittiDifficulty(easy) == 0
    assert kitti_input.KittiDifficulty(mod) == 1
    assert kitti_input.KittiDifficulty(hard) == 2
    assert kitti_input.KittiDifficulty(excl) == -1

  def test_cumulative_difficulty_ap(self):
    # easy gt counts in every level; hard gt only at 'hard'; a detection
    # matched to a hard gt must not poison the easy slice
    m = breakdown_metric.ByKittiDifficulty()
    gt = np.array([[0, 0, 0, 4, 2, 1.5, 0.0, 0],      # easy
                   [20, 20, 0, 4, 2, 1.5, 0.0, 2]])   # hard
    pred = gt[:, :7].copy()
    m.Update(pred, np.array([0.9, 0.8]), gt,
             pred_classes=np.array([1, 1]), gt_classes=np.array([1, 1]))
    vals = m.value
    assert vals["easy"] == 1.0 and vals["moderate"] == 1.0
    assert vals["hard"] == 1.0
    # a second scene with only the hard gt detected late (missed easy)
    m2 = breakdown_metric.ByKittiDifficulty()
    m2.Update(gt[1:, :7], np.array([0.8]), gt,
              pred_classes=np.array([1]), gt_classes=np.array([1, 1]))
    v2 = m2.value
    assert v2["easy"] == 0.0        # easy gt missed entirely
    assert v2["hard"] < 1.0         # hard slice: 1 of 2 gts found


class TestBreakdownMetrics:

  def test_by_rotation_bins(self):
    m = breakdown_metric.ByRotation(num_bins=2)
    gt = np.array([[0, 0, 0, 2, 2, 2, 0.1],       # bin 0
                   [5, 5, 0, 2, 2, 2, 2.0]])      # bin 1
    pred = gt.copy()
    m.Update(pred, np.array([0.9, 0.8]), gt,
             pred_classes=np.array([1, 1]), gt_classes=np.array([1, 1]))
    vals = m.value
    assert vals["rot_0_of_2"] == 1.0 and vals["rot_1_of_2"] == 1.0

  def test_count_points_in_boxes(self):
    pts = np.array([[0, 0, 0], [0.4, 0.4, 0], [5, 5, 5]])
    boxes = np.array([[0, 0, 0, 1.0, 1.0, 1.0, 0.0]])
    counts = breakdown_metric.CountPointsInBoxes(pts, boxes)
    assert counts[0] == 2

  def test_matched_excluded_gt_not_counted_as_fp(self):
    # A prediction matching a gt that bin_of_gt excludes (-1) must score in
    # no bin — not flood every bin as a false positive.
    m = breakdown_metric.BreakdownApMetric(
        ["b0"], lambda g: -1 if g[0] > 100 else 0,
        bin_preds_by_matched_gt=True)
    gt = np.array([[200.0, 0, 0, 2, 2, 2, 0.0],   # excluded
                   [0.0, 0, 0, 2, 2, 2, 0.0]])    # bin 0
    pred = gt.copy()
    m.Update(pred, np.array([0.9, 0.8]), gt)
    assert m.value["b0"] == 1.0

  def test_by_num_points_bins_preds_by_matched_gt(self):
    # 7-DOF predictions (no count column) must land in the bin of the gt
    # they overlap, so a perfect detector scores 1.0 in every populated bin.
    m = breakdown_metric.ByNumPoints(edges=(10, 100))
    gt = np.array([[0, 0, 0, 2, 2, 2, 0.0, 5.0],     # 5 pts -> bin 0
                   [20, 20, 0, 2, 2, 2, 0.0, 50.0]])  # 50 pts -> bin 1
    pred = gt[:, :7].copy()
    m.Update(pred, np.array([0.9, 0.8]), gt,
             pred_classes=np.array([1, 1]), gt_classes=np.array([1, 1]))
    vals = m.value
    assert vals["pts_lt_10"] == 1.0 and vals["pts_lt_100"] == 1.0
