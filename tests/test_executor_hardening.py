"""Executor failure recovery + multi-task training + checkpoint-polling jobs
(VERDICT r1 item 4; ref base_runner._RunLoop retry taxonomy, executor
GetExecutorParams multi-task expansion, _FindNewCheckpoint polling)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import retry as retry_lib
from lingvo_tpu.core import task_scheduler
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.runners import base_runner
from lingvo_tpu.runners import executor as executor_lib
from lingvo_tpu.runners import program as program_lib


class _RegressionTask(base_model.BaseTask):
  """y = 2x regression on synthetic data (ref trainer_test_utils)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("dim", 4, "")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild(
        "proj",
        layers.ProjectionLayer.Params().Set(
            input_dim=self.p.dim, output_dim=self.p.dim))

  def ComputePredictions(self, theta, input_batch):
    return self.proj.FProp(theta.proj, input_batch.x)

  def ComputeLoss(self, theta, predictions, input_batch):
    err = jnp.mean(jnp.square(predictions - input_batch.y))
    b = input_batch.x.shape[0]
    return NestedMap(loss=(err, float(b))), NestedMap()


class _RegressionInput:
  """Minimal generator protocol for TrainProgram."""

  def __init__(self, dim=4, batch=16, seed=0):
    self._rng = np.random.RandomState(seed)
    self._dim, self._batch = dim, batch

  def GetPreprocessedInputBatch(self):
    x = self._rng.randn(self._batch, self._dim).astype("float32")
    return NestedMap(x=x, y=2.0 * x)

  def GlobalBatchSize(self):
    return self._batch

  def InfeedBatchSize(self):
    return self._batch

  def __iter__(self):
    while True:
      yield self.GetPreprocessedInputBatch()


def _TaskParams(name="reg", lr=0.05, max_steps=30, steps_per_loop=5,
                save_interval=10):
  p = _RegressionTask.Params().Set(name=name, dim=4)
  p.train.learner = learner_lib.Learner.Params().Set(
      learning_rate=lr, optimizer=opt_lib.Adam.Params())
  p.train.max_steps = max_steps
  p.train.tpu_steps_per_loop = steps_per_loop
  p.train.save_interval_steps = save_interval
  return p


def _MakeScheduleAndTask(logdir, **kw):
  task_p = _TaskParams(**kw)
  task = task_p.Instantiate()
  task.FinalizePaths()
  train_p = program_lib.TrainProgram.Params().Set(
      task=task_p, logdir=logdir,
      steps_per_loop=task_p.train.tpu_steps_per_loop)
  sched_p = program_lib.SimpleProgramSchedule.Params().Set(
      train_program=train_p)
  sched = program_lib.SimpleProgramSchedule(
      sched_p, task=task, input_generators={"Train": _RegressionInput()})
  return sched, task, task_p


class TestRetryTaxonomy:

  def test_is_transient(self):
    assert retry_lib.IsTransient(RuntimeError("UNAVAILABLE: socket closed"))
    assert retry_lib.IsTransient(RuntimeError("DEADLINE_EXCEEDED"))
    assert not retry_lib.IsTransient(RuntimeError("Compilation failure: x"))
    assert not retry_lib.IsTransient(ValueError("shapes mismatch"))
    # fatal patterns win even when transient text co-occurs
    assert not retry_lib.IsTransient(
        RuntimeError("UNAVAILABLE while RESOURCE_EXHAUSTED"))

  def test_retry_decorator(self):
    calls = []

    @retry_lib.Retry(initial_delay_sec=0.01, max_retries=3)
    def flaky():
      calls.append(1)
      if len(calls) < 3:
        raise RuntimeError("UNAVAILABLE: try again")
      return "ok"

    assert flaky() == "ok"
    assert len(calls) == 3

    @retry_lib.Retry(initial_delay_sec=0.01, max_retries=3)
    def fatal():
      raise ValueError("nope")

    with pytest.raises(ValueError):
      fatal()


class TestExecutorRecovery:

  def test_transient_failure_restores_and_completes(self, tmp_path):
    """A backend death mid-run must resume from the last checkpoint."""
    logdir = str(tmp_path)
    sched, task, _ = _MakeScheduleAndTask(logdir, max_steps=30)

    real_run = sched.Run
    fail_state = {"armed": True}

    def _FlakyRun(state):
      step = int(jax.device_get(state.step))
      if fail_state["armed"] and step >= 10:
        fail_state["armed"] = False
        raise RuntimeError("UNAVAILABLE: TPU backend connection dropped")
      return real_run(state)

    sched.Run = _FlakyRun
    ex = executor_lib.ExecutorTpu(_TaskParams(), logdir, schedule=sched,
                                  task=task)
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 30
    assert not fail_state["armed"]  # the failure did fire

  def test_fatal_failure_raises(self, tmp_path):
    logdir = str(tmp_path)
    sched, task, _ = _MakeScheduleAndTask(logdir)

    def _CompileError(state):
      raise RuntimeError("Compilation failure: rank mismatch")

    sched.Run = _CompileError
    ex = executor_lib.ExecutorTpu(_TaskParams(), logdir, schedule=sched,
                                  task=task)
    with pytest.raises(RuntimeError, match="Compilation failure"):
      ex.Start()

  def test_retries_exhausted_raises(self, tmp_path):
    logdir = str(tmp_path)
    sched, task, _ = _MakeScheduleAndTask(logdir)

    def _AlwaysDown(state):
      raise RuntimeError("UNAVAILABLE: tunnel down")

    sched.Run = _AlwaysDown
    ex = executor_lib.ExecutorTpu(_TaskParams(), logdir, schedule=sched,
                                  task=task, max_train_retries=2)
    with pytest.raises(RuntimeError, match="UNAVAILABLE"):
      ex.Start()


class TestMultiTaskExecutor:

  def test_two_tasks_train_with_sampled_schedule(self, tmp_path):
    logdir = str(tmp_path)
    import lingvo_tpu.core.hyperparams as hp
    task_ps = {"a": _TaskParams("a"), "b": _TaskParams("b")}
    tasks = {}
    train_programs = hp.Params()
    gens = {}
    for name, tp_ in task_ps.items():
      tasks[name] = tp_.Instantiate()
      tasks[name].FinalizePaths()
      train_programs.Define(
          name,
          program_lib.TrainProgram.Params().Set(
              task=tp_, logdir=logdir, name=f"train_{name}",
              steps_per_loop=5), "")
      gens[(name, "Train")] = _RegressionInput(seed=hash(name) % 100)
    sched_p = program_lib.MultiTaskProgramSchedule.Params().Set(
        task_schedule=task_scheduler.ConstantScheduler.Params().Set(
            task_probs=[("a", 0.5), ("b", 0.5)], seed=3),
        train_programs=train_programs)
    sched = program_lib.MultiTaskProgramSchedule(sched_p, tasks=tasks,
                                                 input_generators=gens)
    ex = executor_lib.ExecutorTpu(None, logdir, schedule=sched)
    state = ex.Start()
    steps = {n: int(jax.device_get(state.tasks.GetItem(n).step))
             for n in ("a", "b")}
    assert sum(steps.values()) >= 30
    assert steps["a"] > 0 and steps["b"] > 0  # both tasks actually sampled
    # checkpoint round-trips the combined state
    template = sched.CreateTrainState(jax.random.PRNGKey(0))
    restored, step = ex.checkpointer.Restore(template)
    assert step == sum(steps.values())


class TestCheckpointPoller:

  def test_poller_sees_new_checkpoints_and_stops(self, tmp_path):
    logdir = str(tmp_path)
    # produce a training run with checkpoints at 10/20/30
    sched, task, task_p = _MakeScheduleAndTask(logdir, max_steps=30,
                                               save_interval=10)
    ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task)
    ex.Start()

    class _EvalProg:
      def __init__(self):
        self.p = NestedMap(name="eval_test")
        self.seen = []

      def Run(self, state):
        self.seen.append(int(jax.device_get(state.step)))
        return state, {"loss": 0.0}

    prog = _EvalProg()
    poller = base_runner.CheckpointPollingRunner(
        task, [prog], os.path.join(logdir, "train"),
        poll_interval_secs=0.1, timeout_secs=5.0)
    poller.Run()
    # the final checkpoint (step 30) must be scored; poller then exits
    assert prog.seen and prog.seen[-1] == 30


class TestTrialWiring:

  def test_trial_reports_and_stops(self, tmp_path):
    """The executor consults the Trial each cycle (ref executor trial hooks
    + base_trial.Trial): eval measures reported, early stop honored."""
    from lingvo_tpu.core import base_trial

    class CountingTrial(base_trial.NoOpTrial):
      def __init__(self):
        self.reports = []
        self.done = None

      def ReportEvalMeasure(self, step, metrics, checkpoint_path=""):
        self.reports.append((step, dict(metrics)))
        return len(self.reports) >= 2

      def ReportDone(self, infeasible=False, reason=""):
        self.done = (infeasible, reason)

    logdir = str(tmp_path)
    task_p = _TaskParams(max_steps=100, steps_per_loop=5)
    task = task_p.Instantiate()
    task.FinalizePaths()
    train_p = program_lib.TrainProgram.Params().Set(
        task=task_p, logdir=logdir, steps_per_loop=5)
    eval_p = program_lib.EvalProgram.Params().Set(
        task=task_p, logdir=logdir, name="eval_test", steps_per_loop=2)
    sched = program_lib.SimpleProgramSchedule(
        program_lib.SimpleProgramSchedule.Params().Set(
            train_program=train_p, eval_programs=[eval_p]),
        task=task,
        input_generators={"Train": _RegressionInput(),
                          "Test": _RegressionInput(seed=9)})
    trial = CountingTrial()
    ex = executor_lib.ExecutorTpu(task_p, logdir, schedule=sched, task=task,
                                  trial=trial)
    state = ex.Start()
    assert int(jax.device_get(state.step)) == 10  # stopped early, not 100
    assert len(trial.reports) == 2
    assert "loss" in trial.reports[0][1]


class TestInputBenchmark:

  def test_reports_throughput(self, tmp_path):
    task_p = _TaskParams()
    task = task_p.Instantiate()
    task.FinalizePaths()
    p = program_lib.InputBenchmarkProgram.Params().Set(
        task=task_p, logdir=str(tmp_path), steps_per_loop=10)
    prog = program_lib.InputBenchmarkProgram(
        p, task=task, input_generator=_RegressionInput())
    state = task.CreateTrainState(jax.random.PRNGKey(0))
    _, result = prog.Run(state)
    assert result["batches_per_second"] > 0
    assert result["examples_per_second"] >= result["batches_per_second"]
