"""Fused blockwise LM-head cross-entropy (ops/fused_xent.py).

Covers docs/fused_xent.md:
- fused forward == dense logits + f32 log-softmax xent (label smoothing
  on/off, tanh logits cap on/off, ragged V % block != 0 tail, both weight
  layouts), including the label log-prob, logsumexp and argmax outputs,
- fused gradients (custom_vjp, block-recompute backward) == dense
  gradients for hidden / weight / bias, under padded-position weighting,
  and through the label_log_prob / lse outputs,
- the xent_block_size eligibility gate on SimpleFullSoftmax /
  SharedEmbeddingSoftmaxLayer (0 = legacy dense path, dense fallback when
  class_probabilities are passed),
- TransformerLm / BertLm end-to-end: loss, fraction_of_correct (per-block
  argmax), theta gradients and ScoreSequences match the dense path; the
  Inference 'score' subgraph still exports full log-probs,
- the Pallas TPU kernel matches the XLA reference lowering (slow).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.ops import fused_xent


def _DenseRef(x, w_vd, b, labels, cap, ls):
  """Dense reference: f32 logits + XentLossFromLogits-style xent."""
  logits = (x @ w_vd.T).astype(jnp.float32)
  if b is not None:
    logits = logits + b
  if cap > 0:
    logits = cap * jnp.tanh(logits / cap)
  log_probs = jax.nn.log_softmax(logits)
  v = w_vd.shape[0]
  q = jax.nn.one_hot(labels, v, dtype=jnp.float32)
  if ls > 0:
    q = (1.0 - ls) * q + ls / v
  xent = -jnp.sum(q * log_probs, axis=-1)
  return xent, log_probs, logits


def _Inputs(m=9, d=16, v=50, seed=0):
  kx, kw, kb, kl = jax.random.split(jax.random.PRNGKey(seed), 4)
  x = jax.random.normal(kx, (m, d), jnp.float32)
  w = jax.random.normal(kw, (v, d), jnp.float32) * 0.3
  b = jax.random.normal(kb, (v,), jnp.float32) * 0.1
  labels = jax.random.randint(kl, (m,), 0, v)
  return x, w, b, labels


class TestFusedXentOp:

  @pytest.mark.parametrize("cap", [0.0, 5.0])
  @pytest.mark.parametrize("ls", [0.0, 0.1])
  @pytest.mark.parametrize("v,block", [(48, 16), (50, 16), (50, 64)])
  def test_forward_matches_dense(self, cap, ls, v, block):
    """Online blockwise stats == dense f32 log-softmax: xent, label
    log-prob, lse and argmax — incl. the ragged V % block tail and a
    block larger than V."""
    x, w, b, labels = _Inputs(v=v)
    out = fused_xent.FusedXent(
        x, w, labels, block_size=block, bias=b, logits_soft_max=cap,
        label_smoothing=ls, lowering="xla")
    xent_d, lp_d, logits_d = _DenseRef(x, w, b, labels, cap, ls)
    np.testing.assert_allclose(out.per_example_xent, xent_d,
                               rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(
        out.label_log_prob,
        jnp.take_along_axis(lp_d, labels[:, None], -1)[:, 0],
        rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(
        out.lse, jax.scipy.special.logsumexp(logits_d, axis=-1),
        rtol=2e-5, atol=2e-6)
    np.testing.assert_array_equal(out.argmax,
                                  jnp.argmax(logits_d, axis=-1))

  @pytest.mark.parametrize("cap,ls", [(0.0, 0.0), (5.0, 0.1)])
  @pytest.mark.parametrize("layout", ["vd", "dv"])
  def test_grads_match_dense(self, cap, ls, layout):
    """custom_vjp block-recompute backward == autodiff through the dense
    path, for d_hidden, d_emb and d_bias, with padded positions carrying
    zero weight (V=50, block=16: ragged tail exercised in bwd too)."""
    x, w, b, labels = _Inputs(v=50)
    wgt = jnp.asarray([1.0] * 6 + [0.0] * 3)  # padded tail positions

    def fused_loss(x, w, b):
      w_arg = w if layout == "vd" else w.T
      out = fused_xent.FusedXent(
          x, w_arg, labels, block_size=16, bias=b, logits_soft_max=cap,
          label_smoothing=ls, weight_layout=layout, lowering="xla")
      return jnp.sum(out.per_example_xent * wgt)

    def dense_loss(x, w, b):
      return jnp.sum(_DenseRef(x, w, b, labels, cap, ls)[0] * wgt)

    gf = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(x, w, b)
    for got, want in zip(gf, gd):
      np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-6)

  def test_grads_through_score_outputs(self):
    """label_log_prob and lse carry exact cotangents too (the scoring
    path is differentiable, not stop-gradiented)."""
    x, w, b, labels = _Inputs(v=50)

    def fused_score(x, w, b):
      out = fused_xent.FusedXent(x, w, labels, block_size=16, bias=b,
                                 lowering="xla")
      return jnp.sum(out.label_log_prob) + 0.5 * jnp.sum(out.lse)

    def dense_score(x, w, b):
      _, lp, logits = _DenseRef(x, w, b, labels, 0.0, 0.0)
      return (jnp.sum(jnp.take_along_axis(lp, labels[:, None], -1))
              + 0.5 * jnp.sum(jax.scipy.special.logsumexp(logits, -1)))

    gf = jax.grad(fused_score, argnums=(0, 1, 2))(x, w, b)
    gd = jax.grad(dense_score, argnums=(0, 1, 2))(x, w, b)
    for got, want in zip(gf, gd):
      np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-6)

  def test_leading_dims_and_jit(self):
    """[B, T, D] inputs keep their leading shape; works under jit."""
    x, w, b, labels = _Inputs(m=12, v=50)
    x3 = x.reshape(3, 4, -1)
    l2 = labels.reshape(3, 4)
    out = jax.jit(lambda x, w, b: fused_xent.FusedXent(
        x, w, l2, block_size=16, bias=b, lowering="xla"))(x3, w, b)
    assert out.per_example_xent.shape == (3, 4)
    flat = fused_xent.FusedXent(x, w, labels, block_size=16, bias=b,
                                lowering="xla")
    np.testing.assert_allclose(out.per_example_xent.reshape(-1),
                               flat.per_example_xent, rtol=1e-6)


class TestLayerGate:

  def _Softmax(self, block, has_bias=True, cap=0.0):
    p = layers_lib.SimpleFullSoftmax.Params().Set(
        name="sm", input_dim=16, num_classes=50, has_bias=has_bias,
        logits_soft_max=cap, xent_block_size=block)
    layer = p.Instantiate()
    layer.FinalizePaths()
    return layer

  def test_simple_full_softmax_gate(self):
    """xent_block_size>0 FProp == dense FProp per_example_xent; logits /
    log_probs are deliberately absent; argmax matches the dense argmax."""
    dense, fused = self._Softmax(0, cap=4.0), self._Softmax(16, cap=4.0)
    theta = dense.InstantiateVariables(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (3, 5, 16))
    ids = jax.random.randint(jax.random.PRNGKey(2), (3, 5), 0, 50)
    out_d = dense.FProp(theta, x, class_ids=ids, label_smoothing=0.1)
    out_f = fused.FProp(theta, x, class_ids=ids, label_smoothing=0.1)
    np.testing.assert_allclose(out_f.per_example_xent,
                               out_d.per_example_xent, rtol=2e-5, atol=2e-6)
    assert out_f.logits is None and out_f.log_probs is None
    np.testing.assert_array_equal(out_f.argmax,
                                  jnp.argmax(out_d.logits, -1))

  def test_gate_falls_back_on_class_probabilities(self):
    """Dense class_probabilities would re-materialize [.., V] anyway: the
    gate takes the exact legacy path (logits present)."""
    fused = self._Softmax(16)
    theta = fused.InstantiateVariables(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 16))
    probs = jax.nn.softmax(jax.random.normal(jax.random.PRNGKey(2), (4, 50)))
    out = fused.FProp(theta, x, class_probabilities=probs)
    assert out.logits is not None

  def test_shared_embedding_gate(self):
    p0 = layers_lib.SharedEmbeddingSoftmaxLayer.Params().Set(
        name="emb", vocab_size=50, embedding_dim=16, logits_soft_max=3.0)
    p1 = p0.Copy().Set(xent_block_size=16)
    dense, fused = p0.Instantiate(), p1.Instantiate()
    dense.FinalizePaths(), fused.FinalizePaths()
    theta = dense.InstantiateVariables(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 16))
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, 50)
    out_d = dense.FProp(theta, x, class_ids=ids)
    out_f = fused.FProp(theta, x, class_ids=ids)
    np.testing.assert_allclose(out_f.per_example_xent,
                               out_d.per_example_xent, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(
        out_f.label_log_probs,
        jnp.take_along_axis(out_d.log_probs, ids[..., None], -1)[..., 0],
        rtol=2e-5, atol=2e-6)


def _Lm(block, cls=None, **kw):
  from lingvo_tpu.models.lm import layers as lm_layers
  cls = cls or lm_layers.TransformerLm
  kw.setdefault("label_smoothing", 0.1)
  p = cls.Params().Set(
      name="lm", vocab_size=50, model_dim=32, num_layers=2, num_heads=2,
      hidden_dim=64, xent_block_size=block, **kw)
  task = p.Instantiate()
  task.FinalizePaths()
  return task


def _LmBatch(b=2, t=8, vocab=50, masked=False):
  batch = NestedMap(
      ids=jax.random.randint(jax.random.PRNGKey(1), (b, t), 1, vocab),
      labels=jax.random.randint(jax.random.PRNGKey(2), (b, t), 1, vocab),
      paddings=jnp.concatenate(
          [jnp.zeros((b, t - 2)), jnp.ones((b, 2))], axis=1))
  if masked:
    batch.masked_weights = (batch.ids % 3 == 0).astype(jnp.float32)
  return batch


class TestTransformerLmFused:

  def test_loss_metrics_and_grads_match_dense(self):
    """Same theta (the gate adds no variables): loss, log_pplx and
    fraction_of_correct_next_step_preds (fused per-block argmax) match
    the dense path, as do gradients wrt every theta leaf."""
    t0, t1 = _Lm(0), _Lm(16)
    theta = t0.InstantiateVariables(jax.random.PRNGKey(0))
    batch = _LmBatch()

    def loss(task, th):
      metrics, _ = task.ComputeLoss(
          th, task.ComputePredictions(th, batch), batch)
      return metrics.loss[0], metrics

    (l0, m0) = loss(t0, theta)
    (l1, m1) = loss(t1, theta)
    np.testing.assert_allclose(l0, l1, rtol=1e-5)
    np.testing.assert_allclose(
        m0.fraction_of_correct_next_step_preds[0],
        m1.fraction_of_correct_next_step_preds[0], rtol=1e-6)
    g0 = jax.grad(lambda th: loss(t0, th)[0])(theta)
    g1 = jax.grad(lambda th: loss(t1, th)[0])(theta)
    for got, want in zip(jax.tree_util.tree_leaves(g1),
                         jax.tree_util.tree_leaves(g0)):
      np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-6)

  def test_predictions_defer_logits(self):
    """The fused gate keeps [B, T, V] out of the predictions map."""
    t1 = _Lm(16)
    theta = t1.InstantiateVariables(jax.random.PRNGKey(0))
    preds = t1.ComputePredictions(theta, _LmBatch())
    assert "logits" not in preds and "hidden" in preds

  def test_score_sequences_fused_vs_dense(self):
    t0, t1 = _Lm(0), _Lm(16)
    theta = t0.InstantiateVariables(jax.random.PRNGKey(0))
    batch = _LmBatch()
    s0 = t0.ScoreSequences(theta, batch)
    s1 = t1.ScoreSequences(theta, batch)
    np.testing.assert_allclose(s1.label_log_probs, s0.label_log_probs,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(s0.weights, s1.weights)

  def test_inference_score_still_dense(self):
    """Serving export needs the full distribution: the 'score' subgraph
    falls back to dense logits from the deferred hidden."""
    t1 = _Lm(16)
    theta = t1.InstantiateVariables(jax.random.PRNGKey(0))
    fn, _ = t1.Inference()["score"]
    batch = _LmBatch()
    out = fn(theta, NestedMap(ids=batch.ids, paddings=batch.paddings))
    assert out.log_probs.shape == (*batch.ids.shape, 50)

  def test_bert_lm_fused(self):
    from lingvo_tpu.models.lm import layers as lm_layers
    t0 = _Lm(0, cls=lm_layers.BertLm)
    t1 = _Lm(16, cls=lm_layers.BertLm)
    theta = t0.InstantiateVariables(jax.random.PRNGKey(0))
    batch = _LmBatch(masked=True)
    m0, _ = t0.ComputeLoss(theta, t0.ComputePredictions(theta, batch), batch)
    m1, _ = t1.ComputeLoss(theta, t1.ComputePredictions(theta, batch), batch)
    np.testing.assert_allclose(m0.loss[0], m1.loss[0], rtol=1e-5)
    np.testing.assert_allclose(m0.mlm_accuracy[0], m1.mlm_accuracy[0],
                               rtol=1e-6)

  def test_sampled_softmax_excludes_fused(self):
    with pytest.raises(AssertionError):
      _Lm(16, softmax_num_sampled=8, label_smoothing=0.0)


@pytest.mark.slow
class TestPallasKernel:
  """Pallas TPU kernel vs the XLA reference lowering (interpret mode —
  same twin-kernel contract as tests/test_decode_fast_path.py)."""

  @pytest.mark.parametrize("cap,ls", [(0.0, 0.0), (5.0, 0.1)])
  @pytest.mark.parametrize("v", [256, 200])  # aligned + ragged tail
  def test_pallas_matches_xla(self, cap, ls, v):
    m, d, bs = 13, 128, 128
    kx, kw, kl = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (m, d), jnp.float32)
    w = jax.random.normal(kw, (v, d), jnp.float32) * 0.3
    labels = jax.random.randint(kl, (m,), 0, v)
    kw_args = dict(block_size=bs, logits_soft_max=cap, label_smoothing=ls)
    o_x = fused_xent.FusedXent(x, w, labels, lowering="xla", **kw_args)
    o_p = fused_xent.FusedXent(x, w, labels, lowering="pallas",
                               interpret=True, **kw_args)
    for name in ("per_example_xent", "label_log_prob", "lse"):
      np.testing.assert_allclose(getattr(o_p, name), getattr(o_x, name),
                                 rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(o_p.argmax, o_x.argmax)

  def test_pallas_dv_layout(self):
    m, d, v, bs = 16, 128, 256, 128
    x = jax.random.normal(jax.random.PRNGKey(0), (m, d))
    w = jax.random.normal(jax.random.PRNGKey(1), (v, d)) * 0.3
    labels = jax.random.randint(jax.random.PRNGKey(2), (m,), 0, v)
    o_x = fused_xent.FusedXent(x, w, labels, block_size=bs, lowering="xla")
    o_p = fused_xent.FusedXent(x, w.T, labels, block_size=bs,
                               weight_layout="dv", lowering="pallas",
                               interpret=True)
    np.testing.assert_allclose(o_p.per_example_xent, o_x.per_example_xent,
                               rtol=1e-6, atol=1e-6)


class TestSupportedOnTpu:

  def test_alignment_gate(self):
    assert fused_xent.SupportedOnTpu(128, 256)
    assert not fused_xent.SupportedOnTpu(100, 256)
    assert not fused_xent.SupportedOnTpu(128, 100)
