"""GShard MoE: top-2 gating + expert-parallel dispatch/combine.

Re-implements the semantics of the reference's MoE stack
(`gshard_layers.py`: `Top2GatingOnLogits:1932` — capacity, aux
load-balancing loss, second-expert probabilistic sampling;
`FeedForwardNetworksApplyGating:2992` — dispatch/combine einsums over the
expert dim). TPU-native: expert weights carry the 'expert' mesh axis on their
leading dim; the dispatch einsum produces an expert-major tensor whose
sharding flips from data-major to expert-major — XLA lowers that resharding
to the all-to-all over ICI, exactly the compiler path the reference relies
on. No hand-written collective needed in the dense-einsum formulation.

Gating math parity notes (vs `Top2GatingOnLogits`):
  * softmax over experts in f32;
  * aux_loss = mean_over_tokens(density_1 * density_1_proxy) * num_experts^2
    (ref `:2064-2073`);
  * second expert sampled with prob proportional to its gate value when
    `second_expert_policy='random'` (ref `:2123-2140`);
  * per-expert capacity = ceil(tokens/experts * capacity_factor), tokens over
    capacity are dropped (ref position-in-expert cumsum logic).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams
from lingvo_tpu.parallel import mesh as mesh_lib


def Top2Gating(logits: jax.Array,
               paddings: jax.Array | None,
               capacity_factor: float = 2.0,
               second_expert_policy: str = "all",
               prng_key: jax.Array | None = None,
               capacity: int | None = None):
  """Top-2 gating over [G, S, E] logits (G=groups, S=tokens/group, E=experts).

  Returns NestedMap(combine_tensor [G,S,E,C], dispatch_tensor bool [G,S,E,C],
  aux_loss scalar).
  """
  g, s, e = logits.shape
  if capacity is None:
    capacity = max(1, int(math.ceil(s / e * capacity_factor)))
  c = capacity
  raw_gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [G,S,E]

  nonpad = (1.0 - paddings) if paddings is not None else jnp.ones(
      (g, s), jnp.float32)

  # --- top-1 ---
  index_1 = jnp.argmax(raw_gates, axis=-1)                       # [G,S]
  mask_1 = jax.nn.one_hot(index_1, e, dtype=jnp.float32)
  mask_1 = mask_1 * nonpad[..., None]
  gate_1 = jnp.sum(raw_gates * mask_1, axis=-1)                  # [G,S]

  # aux load-balancing loss (ref :2064): density_1 = fraction routed to e,
  # density_1_proxy = mean gate prob of e.
  denom = jnp.maximum(jnp.sum(nonpad, axis=1, keepdims=True), 1.0)  # [G,1]
  density_1 = jnp.sum(mask_1, axis=1) / denom                    # [G,E]
  density_1_proxy = jnp.sum(raw_gates * nonpad[..., None],
                            axis=1) / denom                      # [G,E]
  aux_loss = jnp.mean(jnp.sum(density_1 * density_1_proxy, axis=-1)) * (e * e)

  # --- top-2 ---
  gates_wo_1 = raw_gates * (1.0 - mask_1)
  index_2 = jnp.argmax(gates_wo_1, axis=-1)
  mask_2 = jax.nn.one_hot(index_2, e, dtype=jnp.float32) * nonpad[..., None]
  gate_2 = jnp.sum(gates_wo_1 * mask_2, axis=-1)

  if second_expert_policy == "random":
    # keep the 2nd expert with prob 2*gate_2/(gate_1+gate_2) (ref :2123).
    assert prng_key is not None
    sampled = jax.random.uniform(prng_key, gate_2.shape)
    keep_2 = (sampled < 2.0 * gate_2 / jnp.maximum(gate_1 + gate_2, 1e-9))
    mask_2 = mask_2 * keep_2[..., None].astype(mask_2.dtype)
    gate_2 = gate_2 * keep_2.astype(gate_2.dtype)

  # --- capacity assignment via cumsum position-in-expert ---
  pos_1 = jnp.cumsum(mask_1, axis=1) - mask_1                    # [G,S,E]
  mask_1 = mask_1 * (pos_1 < c)
  pos_1_tok = jnp.sum(pos_1 * mask_1, axis=-1)                   # [G,S]
  # expert-1 counts offset expert-2 positions
  count_1 = jnp.sum(mask_1, axis=1, keepdims=True)               # [G,1,E]
  pos_2 = jnp.cumsum(mask_2, axis=1) - mask_2 + count_1
  mask_2 = mask_2 * (pos_2 < c)
  pos_2_tok = jnp.sum(pos_2 * mask_2, axis=-1)

  # renormalize surviving gates
  mask_1_flat = jnp.sum(mask_1, axis=-1)                         # [G,S]
  mask_2_flat = jnp.sum(mask_2, axis=-1)
  gate_1 = gate_1 * mask_1_flat
  gate_2 = gate_2 * mask_2_flat
  total = jnp.maximum(gate_1 + gate_2, 1e-9)
  gate_1, gate_2 = gate_1 / total, gate_2 / total

  def _Combine(gate, mask, pos_tok):
    # [G,S] gate, [G,S,E] mask, [G,S] position -> [G,S,E,C]
    onehot_c = jax.nn.one_hot(pos_tok.astype(jnp.int32), c,
                              dtype=jnp.float32)                 # [G,S,C]
    return gate[..., None, None] * mask[..., None] * onehot_c[:, :, None, :]

  combine = _Combine(gate_1, mask_1, pos_1_tok) + _Combine(
      gate_2, mask_2, pos_2_tok)
  dispatch = combine > 0.0
  return NestedMap(
      combine_tensor=combine, dispatch_tensor=dispatch, aux_loss=aux_loss)


class MoEFeedForwardLayer(base_layer.BaseLayer):
  """Expert-parallel MoE FFN block (pre-LN, residual), GShard-style.

  Weights wi/wo are [E, D, H] / [E, H, D] with 'expert' on dim 0 — under a
  mesh with an expert axis the dispatch einsum reshards tokens
  data-major -> expert-major (compiler all-to-all), experts run as one big
  batched matmul on the MXU, and combine reshards back.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim D.")
    p.Define("hidden_dim", 0, "Expert FFN hidden dim H.")
    p.Define("num_experts", 8, "E.")
    p.Define("num_groups", 1,
             "G: gating groups per batch (ref num_groups; tokens compete for "
             "capacity within a group).")
    p.Define("capacity_factor", 2.0, "Per-expert capacity factor.")
    p.Define("activation", "RELU", "Expert FFN activation.")
    p.Define("second_expert_policy", "all", "'all' or 'random'.")
    p.Define("aux_loss_weight", 0.01, "Aux load-balancing loss weight.")
    p.Define("residual_dropout_prob", 0.0, "Residual dropout.")
    p.Define("norm_tpl", layers_lib.LayerNorm.Params(), "Pre-norm template.")
    p.Define("expert_capacity", 0, "Fixed capacity override (0 = derive).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim and p.hidden_dim and p.num_experts
    self.CreateChild("ln", p.norm_tpl.Copy().Set(input_dim=p.input_dim))
    self.CreateVariable(
        "gating",
        WeightParams((p.input_dim, p.num_experts), p.params_init, p.dtype))
    self.CreateVariable(
        "wi",
        WeightParams((p.num_experts, p.input_dim, p.hidden_dim),
                     p.params_init, p.dtype,
                     tensor_split_dims_mapping=("expert", None, "model")))
    self.CreateVariable(
        "wo",
        WeightParams((p.num_experts, p.hidden_dim, p.input_dim),
                     p.params_init, p.dtype,
                     tensor_split_dims_mapping=("expert", "model", None)))
    self.CreateChild("dropout", layers_lib.DeterministicDropoutLayer.Params())

  def FProp(self, theta, inputs, paddings=None):
    """inputs [B, T, D] -> [B, T, D]; aux loss emitted via AddAuxLoss."""
    p = self.p
    th = self.CastTheta(theta)
    b, t, d = inputs.shape
    x = self.ln.FProp(theta.ln, inputs)
    g = p.num_groups
    assert (b * t) % g == 0, (b, t, g)
    s = b * t // g
    xg = x.reshape(g, s, d)
    pg = (paddings.reshape(g, s) if paddings is not None else None)

    logits = jnp.einsum("GSD,DE->GSE", xg, th.gating.astype(xg.dtype))
    # 'random' second-expert sampling is a TRAIN-time policy; eval/decode
    # (no step seed) falls back to deterministic top-2 (ref: the reference
    # disables sampling at inference).
    policy = p.second_expert_policy
    prng_key = None
    if policy == "random":
      if py_utils.DoEval() or not py_utils.HasStepSeed():
        policy = "all"
      else:
        prng_key = py_utils.StepSeed(f"{self.path}/gating")
    gating = Top2Gating(
        logits, pg, p.capacity_factor, policy, prng_key,
        capacity=p.expert_capacity or None)

    dispatch = gating.dispatch_tensor.astype(xg.dtype)    # [G,S,E,C]
    combine = gating.combine_tensor.astype(xg.dtype)
    # data-major -> expert-major (XLA inserts all-to-all over 'expert')
    expert_in = jnp.einsum("GSEC,GSD->EGCD", dispatch, xg)
    expert_in = mesh_lib.WithShardingConstraint(
        expert_in, ("expert", None, None, None))
    h = jnp.einsum("EGCD,EDH->EGCH", expert_in, th.wi)
    from lingvo_tpu.core import activations
    h = activations.GetFn(p.activation)(h)
    expert_out = jnp.einsum("EGCH,EHD->EGCD", h, th.wo)
    expert_out = mesh_lib.WithShardingConstraint(
        expert_out, ("expert", None, None, None))
    # expert-major -> data-major combine
    out = jnp.einsum("GSEC,EGCD->GSD", combine, expert_out)
    out = out.reshape(b, t, d)
    if p.residual_dropout_prob > 0:
      out = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), out,
          keep_prob=1.0 - p.residual_dropout_prob)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    aux = gating.aux_loss * p.aux_loss_weight
    py_utils.AddAuxLoss(f"{self.path}/aux_loss", aux)
    return inputs + out


class DenseMoEBlock(base_layer.BaseLayer):
  """The GShard interleave unit: one dense transformer layer + one MoE layer.

  Ref: gshard MoE transformers alternate dense and MoE feed-forwards
  (`gshard_builder.py` DenseBuilder.MoE interleave); scanning this block
  N/2 times gives an N-layer half-MoE stack with O(1) compile time.
  """

  @classmethod
  def Params(cls):
    from lingvo_tpu.core import transformer as transformer_lib
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("dense_tpl", transformer_lib.TransformerLayer.Params(),
             "Dense transformer layer template.")
    p.Define("moe_tpl", None, "MoETransformerLayer template.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "dense",
        p.dense_tpl.Copy().Set(input_dim=p.input_dim, num_heads=p.num_heads))
    moe_tpl = p.moe_tpl or MoETransformerLayer.Params()
    self.CreateChild(
        "moe_layer",
        moe_tpl.Copy().Set(input_dim=p.input_dim, num_heads=p.num_heads))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, atten_mask=None, segment_ids=None):
    x = self.dense.FProp(theta.dense, inputs, paddings, aux_vecs,
                         aux_paddings, atten_mask=atten_mask,
                         segment_ids=segment_ids)
    return self.moe_layer.FProp(theta.moe_layer, x, paddings,
                                atten_mask=atten_mask,
                                segment_ids=segment_ids)


class MoETransformerLayer(base_layer.BaseLayer):
  """Transformer layer whose FFN is an MoE block (GShard MoE transformer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    from lingvo_tpu.core import transformer as transformer_lib
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("moe_tpl", MoEFeedForwardLayer.Params(), "MoE FFN template.")
    p.Define("tr_atten_tpl",
             transformer_lib.TransformerAttentionLayer.Params(),
             "Self-attention template.")
    p.Define("mask_self_atten", True, "Causal.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "self_atten",
        p.tr_atten_tpl.Copy().Set(
            input_dim=p.input_dim, num_heads=p.num_heads,
            is_masked=p.mask_self_atten))
    self.CreateChild(
        "moe", p.moe_tpl.Copy().Set(input_dim=p.input_dim))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, atten_mask=None, segment_ids=None):
    assert aux_vecs is None, (
        "MoETransformerLayer has no cross-attention; use a TransformerLayer "
        "with has_aux_atten=True for encoder-decoder stacks")
    x, _ = self.self_atten.FProp(
        theta.self_atten, inputs, paddings=paddings, atten_mask=atten_mask,
        segment_ids=segment_ids)
    return self.moe.FProp(theta.moe, x, paddings)
