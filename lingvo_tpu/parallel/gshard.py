"""GShard MoE: top-2 gating + expert-parallel dispatch/combine.

Re-implements the semantics of the reference's MoE stack
(`gshard_layers.py`: `Top2GatingOnLogits:1932` — capacity, aux
load-balancing loss, second-expert probabilistic sampling;
`FeedForwardNetworksApplyGating:2992` — dispatch/combine einsums over the
expert dim). TPU-native: expert weights carry the 'expert' mesh axis on their
leading dim; the dispatch einsum produces an expert-major tensor whose
sharding flips from data-major to expert-major — XLA lowers that resharding
to the all-to-all over ICI, exactly the compiler path the reference relies
on. No hand-written collective needed in the dense-einsum formulation.

Gating math parity notes (vs `Top2GatingOnLogits`):
  * softmax over experts in f32;
  * aux_loss = mean_over_tokens(density_1 * density_1_proxy) * num_experts^2
    (ref `:2064-2073`);
  * second expert sampled with prob proportional to its gate value when
    `second_expert_policy='random'` (ref `:2123-2140`);
  * per-expert capacity = ceil(tokens/experts * capacity_factor), tokens over
    capacity are dropped (ref position-in-expert cumsum logic).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams
from lingvo_tpu.parallel import mesh as mesh_lib


def _DeriveCapacity(s: int, e: int, capacity_factor: float,
                    capacity: int | None) -> int:
  """Per-expert capacity = ceil(tokens/experts * factor) unless overridden."""
  if capacity is not None:
    return capacity
  return max(1, int(math.ceil(s / e * capacity_factor)))


def _PositionInExpert(mask: jax.Array, c: int, offset=0):
  """Cumsum position-in-expert with capacity truncation.

  mask [G,S,E] one-hot-ish -> (truncated mask, per-token position [G,S]).
  """
  pos = jnp.cumsum(mask, axis=1) - mask + offset
  mask = mask * (pos < c)
  return mask, jnp.sum(pos * mask, axis=-1)


def Top2Gating(logits: jax.Array,
               paddings: jax.Array | None,
               capacity_factor: float = 2.0,
               second_expert_policy: str = "all",
               prng_key: jax.Array | None = None,
               capacity: int | None = None,
               build_tensors: bool = True):
  """Top-2 gating over [G, S, E] logits (G=groups, S=tokens/group, E=experts).

  Returns NestedMap(combine_tensor [G,S,E,C], dispatch_tensor bool [G,S,E,C],
  aux_loss scalar) plus the indexed form consumed by the gather/scatter
  dispatch path: indices/positions [K,G,S] int32 and gates [K,G,S] f32
  (K=2 here; gates are 0 for dropped/over-capacity tokens). With
  `build_tensors=False` the O(G*S*E*C) one-hot tensors are skipped — the
  indexed form carries the same information in O(G*S).
  """
  g, s, e = logits.shape
  c = _DeriveCapacity(s, e, capacity_factor, capacity)
  raw_gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [G,S,E]

  nonpad = (1.0 - paddings) if paddings is not None else jnp.ones(
      (g, s), jnp.float32)

  # --- top-1 ---
  index_1 = jnp.argmax(raw_gates, axis=-1)                       # [G,S]
  mask_1 = jax.nn.one_hot(index_1, e, dtype=jnp.float32)
  mask_1 = mask_1 * nonpad[..., None]
  gate_1 = jnp.sum(raw_gates * mask_1, axis=-1)                  # [G,S]

  # aux load-balancing loss (ref :2064): density_1 = fraction routed to e,
  # density_1_proxy = mean gate prob of e.
  denom = jnp.maximum(jnp.sum(nonpad, axis=1, keepdims=True), 1.0)  # [G,1]
  density_1 = jnp.sum(mask_1, axis=1) / denom                    # [G,E]
  density_1_proxy = jnp.sum(raw_gates * nonpad[..., None],
                            axis=1) / denom                      # [G,E]
  aux_loss = jnp.mean(jnp.sum(density_1 * density_1_proxy, axis=-1)) * (e * e)

  # --- top-2 ---
  gates_wo_1 = raw_gates * (1.0 - mask_1)
  index_2 = jnp.argmax(gates_wo_1, axis=-1)
  mask_2 = jax.nn.one_hot(index_2, e, dtype=jnp.float32) * nonpad[..., None]
  gate_2 = jnp.sum(gates_wo_1 * mask_2, axis=-1)

  if second_expert_policy == "random":
    # keep the 2nd expert with prob 2*gate_2/(gate_1+gate_2) (ref :2123).
    assert prng_key is not None
    sampled = jax.random.uniform(prng_key, gate_2.shape)
    keep_2 = (sampled < 2.0 * gate_2 / jnp.maximum(gate_1 + gate_2, 1e-9))
    mask_2 = mask_2 * keep_2[..., None].astype(mask_2.dtype)
    gate_2 = gate_2 * keep_2.astype(gate_2.dtype)

  # --- capacity assignment via cumsum position-in-expert ---
  mask_1, pos_1_tok = _PositionInExpert(mask_1, c)
  # expert-1 counts offset expert-2 positions
  count_1 = jnp.sum(mask_1, axis=1, keepdims=True)               # [G,1,E]
  mask_2, pos_2_tok = _PositionInExpert(mask_2, c, offset=count_1)

  # renormalize surviving gates
  mask_1_flat = jnp.sum(mask_1, axis=-1)                         # [G,S]
  mask_2_flat = jnp.sum(mask_2, axis=-1)
  gate_1 = gate_1 * mask_1_flat
  gate_2 = gate_2 * mask_2_flat
  total = jnp.maximum(gate_1 + gate_2, 1e-9)
  gate_1, gate_2 = gate_1 / total, gate_2 / total

  out = NestedMap(
      aux_loss=aux_loss,
      capacity=c,
      indices=jnp.stack([index_1, index_2]).astype(jnp.int32),
      positions=jnp.stack([pos_1_tok, pos_2_tok]).astype(jnp.int32),
      gates=jnp.stack([gate_1, gate_2]))
  if build_tensors:
    def _Combine(gate, mask, pos_tok):
      # [G,S] gate, [G,S,E] mask, [G,S] position -> [G,S,E,C]
      onehot_c = jax.nn.one_hot(pos_tok.astype(jnp.int32), c,
                                dtype=jnp.float32)               # [G,S,C]
      return gate[..., None, None] * mask[..., None] * onehot_c[:, :, None, :]

    out.combine_tensor = _Combine(gate_1, mask_1, pos_1_tok) + _Combine(
        gate_2, mask_2, pos_2_tok)
    out.dispatch_tensor = out.combine_tensor > 0.0
  return out


def _MaskedSinkhorn(log_p: jax.Array, nonpad: jax.Array, num_iters: int):
  """Sinkhorn iterations over [G,S,E] with pad ROWS excluded.

  A plain doubly-stochastic normalization lets pad rows keep full mass
  (row normalization cancels any uniform shift), so pad tokens would eat
  most of each expert's column budget in short groups and the balance
  guarantee among real tokens would quietly vanish. Here pad rows are
  forced to ~zero mass after every row step, so column marginals equalize
  over REAL tokens only.
  """
  neg = -1e9
  real = nonpad[..., None] > 0                       # [G,S,1]

  def _Iter(lp, _):
    lp = jnp.where(real, lp - jax.nn.logsumexp(lp, -1, keepdims=True), neg)
    lp = lp - jax.nn.logsumexp(lp, -2, keepdims=True)
    return lp, ()

  lp, _ = jax.lax.scan(_Iter, jnp.where(real, log_p, neg), None,
                       length=num_iters)
  return jnp.exp(lp) * nonpad[..., None]


def SinkhornGating(logits: jax.Array,
                   paddings: jax.Array | None,
                   capacity_factor: float = 2.0,
                   num_iters: int = 10,
                   temperature: float = 1.0,
                   capacity: int | None = None,
                   build_tensors: bool = True):
  """Optimal-transport (Sinkhorn) top-1 gating (ref `gshard_layers.py:2736`
  optimal-transport gating, via `differentiable_assignment.py`).

  A Sinkhorn-balanced transport plan picks each token's expert — the plan's
  column marginals are equalized, so routing is load-balanced *by
  construction* and no aux loss is needed (aux_loss = 0). The combine
  weight is the ordinary softmax gate probability of the chosen expert.

  Gradient contract: the plan is consumed through argmax, so the router
  trains ONLY through the gate values of the selected experts (like top-1
  gating); `num_iters`/`temperature` shape the forward routing decision,
  not the gradient. Balance comes from the forward plan, not from loss
  pressure.
  """
  g, s, e = logits.shape
  c = _DeriveCapacity(s, e, capacity_factor, capacity)
  raw_gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # [G,S,E]
  nonpad = (1.0 - paddings) if paddings is not None else jnp.ones(
      (g, s), jnp.float32)
  plan = _MaskedSinkhorn(logits.astype(jnp.float32) / temperature,
                         nonpad, num_iters)                       # [G,S,E]
  index_1 = jnp.argmax(plan, axis=-1)                             # [G,S]
  mask_1 = jax.nn.one_hot(index_1, e, dtype=jnp.float32) * nonpad[..., None]
  gate_1 = jnp.sum(raw_gates * mask_1, axis=-1)                   # [G,S]
  mask_1, pos_1_tok = _PositionInExpert(mask_1, c)
  gate_1 = gate_1 * jnp.sum(mask_1, axis=-1)
  out = NestedMap(aux_loss=jnp.zeros((), jnp.float32), capacity=c,
                  indices=index_1[None].astype(jnp.int32),
                  positions=pos_1_tok[None].astype(jnp.int32),
                  gates=gate_1[None])
  if build_tensors:
    onehot_c = jax.nn.one_hot(pos_1_tok.astype(jnp.int32), c,
                              dtype=jnp.float32)                  # [G,S,C]
    out.combine_tensor = gate_1[..., None, None] * mask_1[..., None] * \
        onehot_c[:, :, None, :]
    out.dispatch_tensor = out.combine_tensor > 0.0
  return out


def HashGating(token_ids: jax.Array,
               num_experts: int,
               paddings: jax.Array | None,
               capacity_factor: float = 2.0,
               capacity: int | None = None,
               build_tensors: bool = True):
  """Hash-based top-1 routing (ref `gshard_layers.py` HashGatingOnLogits:2367).

  Routes each token to `hash(token_id) % E` with gate weight 1 — no learned
  router, no aux loss. token_ids: [G, S] int32.
  """
  g, s = token_ids.shape
  e = num_experts
  c = _DeriveCapacity(s, e, capacity_factor, capacity)
  # Knuth multiplicative hash, good enough for id-bucket spreading.
  hashed = (token_ids.astype(jnp.uint32) * jnp.uint32(2654435761)) % e
  mask = jax.nn.one_hot(hashed.astype(jnp.int32), e, dtype=jnp.float32)
  if paddings is not None:
    mask = mask * (1.0 - paddings)[..., None]
  mask, pos_tok = _PositionInExpert(mask, c)
  out = NestedMap(aux_loss=jnp.zeros((), jnp.float32), capacity=c,
                  indices=hashed.astype(jnp.int32)[None],
                  positions=pos_tok[None].astype(jnp.int32),
                  gates=jnp.sum(mask, axis=-1)[None])
  if build_tensors:
    onehot_c = jax.nn.one_hot(pos_tok.astype(jnp.int32), c, dtype=jnp.float32)
    out.combine_tensor = mask[..., None] * onehot_c[:, :, None, :]
    out.dispatch_tensor = out.combine_tensor > 0.0
  return out


def ExpertChoiceGating(logits: jax.Array,
                       paddings: jax.Array | None,
                       capacity_factor: float = 2.0,
                       capacity: int | None = None,
                       build_tensors: bool = True):
  """Expert-choice routing (Zhou et al. 2022, arXiv:2202.09368; beyond the
  reference's top2/hash/sinkhorn set): each EXPERT picks its top-C tokens
  instead of tokens picking experts — perfect per-expert load balance by
  construction, no aux loss, no dropped-capacity asymmetry; a token may be
  served by 0..E experts.

  NOT CAUSAL over the token axis: a token's selection depends on the
  whole group's router scores (per-expert top-k over S), so use it for
  encoders / teacher-forced non-AR objectives — autoregressive decode
  routes differently than training (the leak Zhou et al. §4 call out).

  Output matches the other gating fns (indices/positions/gates use K=E
  rows: row k describes the token's slot in expert k, gate 0 when expert
  k did not choose it) and additionally carries the native expert-major
  form (`ec_top_i`/`ec_top_v` [G,E,C]) that `EcIndexedDispatch` consumes
  directly. Everything is O(G*E*C) / O(G*S*E); the quadratic one-hot is
  built only under build_tensors (the einsum dispatch path).
  """
  g, s, e = logits.shape
  c = _DeriveCapacity(s, e, capacity_factor, capacity)
  c = min(c, s)
  scores = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)   # [G,S,E]
  if paddings is not None:
    scores = scores * (1.0 - paddings)[..., None]
  col = scores.transpose(0, 2, 1)                                # [G,E,S]
  top_v, top_i = jax.lax.top_k(col, c)                           # [G,E,C]
  valid = top_v > 0.0  # padded/zero-score picks (short groups) are unreal
  top_v = top_v * valid

  # scatter the chosen (slot, gate) back to token-major [G,E,S]; invalid
  # picks scatter out of bounds -> dropped
  gi = jnp.broadcast_to(jnp.arange(g)[:, None, None], (g, e, c))
  ei = jnp.broadcast_to(jnp.arange(e)[None, :, None], (g, e, c))
  idx = jnp.where(valid, top_i, s)
  selected = jnp.zeros((g, e, s), jnp.float32).at[gi, ei, idx].set(
      1.0, mode="drop")
  slot = jnp.zeros((g, e, s), jnp.float32).at[gi, ei, idx].set(
      jnp.broadcast_to(jnp.arange(c, dtype=jnp.float32), (g, e, c)),
      mode="drop")
  gates_es = col * selected                                      # [G,E,S]
  out = NestedMap(
      aux_loss=jnp.zeros((), jnp.float32),
      capacity=c,
      ec_top_i=top_i.astype(jnp.int32),
      ec_top_v=top_v,
      # K=E: entry k is expert k's view of each token
      indices=jnp.broadcast_to(
          jnp.arange(e, dtype=jnp.int32)[:, None, None], (e, g, s)),
      positions=slot.transpose(1, 0, 2).astype(jnp.int32),       # [E,G,S]
      gates=gates_es.transpose(1, 0, 2))                         # [E,G,S]
  if build_tensors:
    onehot_s = jax.nn.one_hot(top_i, s, dtype=jnp.float32) * valid[
        ..., None]                                               # [G,E,C,S]
    out.combine_tensor = jnp.einsum("GECS,GEC->GSEC", onehot_s, top_v)
    out.dispatch_tensor = out.combine_tensor > 0.0
  return out


def EcIndexedDispatch(xg: jax.Array, gating: NestedMap) -> jax.Array:
  """[G,S,D] tokens -> [E,G,C,D] expert inputs: ONE gather at the
  expert-choice indices (top_i IS the gather index), vs the generic K=E
  indexed path's E passes over [G,S]."""
  top_i = gating.ec_top_i                                        # [G,E,C]
  expert_in = jnp.take_along_axis(
      xg[:, None], top_i[..., None], axis=2)                     # [G,E,C,D]
  return expert_in.transpose(1, 0, 2, 3)


def EcIndexedCombine(expert_out: jax.Array, gating: NestedMap,
                     s: int) -> jax.Array:
  """[E,G,C,D] expert outputs -> [G,S,D]: gate-weighted scatter-add back
  to the chosen token rows."""
  e, g, c, d = expert_out.shape
  weighted = expert_out.transpose(1, 0, 2, 3) * gating.ec_top_v[
      ..., None].astype(expert_out.dtype)                        # [G,E,C,D]
  gi = jnp.broadcast_to(jnp.arange(g)[:, None, None], (g, e, c))
  idx = jnp.where(gating.ec_top_v > 0.0, gating.ec_top_i, s)
  out = jnp.zeros((g, s, d), expert_out.dtype)
  return out.at[gi, idx].add(weighted, mode="drop")


def TokenShufflePerm(shape, prng_key):
  """Random within-group token shuffle (ref `gshard_layers.py:2496`:
  capacity truncation by cumsum position biases early tokens; shuffling
  makes the drops uniform).

  Returns (perm, inv_perm) [G, S]; the caller permutes its gating inputs,
  gates, then inverse-permutes the gating tensors.
  """
  g, s = shape
  perm = jax.vmap(lambda k: jax.random.permutation(k, s))(
      jax.random.split(prng_key, g))                             # [G,S]
  inv = jnp.argsort(perm, axis=-1)
  return perm, inv


def _TakeAlongS(x, perm):
  """Applies a per-group permutation along the S (token) axis of [G,S,...]."""
  idx = perm.reshape(perm.shape + (1,) * (x.ndim - 2))
  return jnp.take_along_axis(x, jnp.broadcast_to(
      idx, perm.shape + x.shape[2:]), axis=1)


def SlotSources(gating: NestedMap, e: int, s: int) -> jax.Array:
  """Token index feeding each expert slot: [G, E*C] int32 in [0, s] (s=empty).

  The one-hot dispatch tensor is a permutation-ish matrix: every (expert,
  capacity) slot receives at most one (token, k) assignment, because
  position-in-expert is a per-expert cumsum (expert-2 positions are offset
  past expert-1 counts). So dispatch reduces to a scatter of token indices
  into slots — O(tokens) instead of the O(tokens*E*C*D) dispatch einsum
  (ref FeedForwardNetworksApplyGating:2992 computes the same routing as a
  dense einsum; this is the TPU-friendly sparse formulation of it).
  """
  c = gating.capacity
  k, g, _ = gating.indices.shape
  src = jnp.full((g, e * c), s, jnp.int32)
  iota_s = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (g, s))
  for i in range(k):
    flat = gating.indices[i] * c + gating.positions[i]
    # dropped tokens (gate 0) scatter out of bounds -> mode="drop"
    flat = jnp.where(gating.gates[i] > 0, flat, e * c)
    src = jax.vmap(lambda sr, fi, io: sr.at[fi].set(io, mode="drop"))(
        src, flat, iota_s)
  return src


def IndexedDispatch(xg: jax.Array, gating: NestedMap, e: int) -> jax.Array:
  """[G,S,D] tokens -> [E,G,C,D] expert inputs via gather (no einsum)."""
  g, s, d = xg.shape
  c = gating.capacity
  src = SlotSources(gating, e, s)                                # [G,E*C]
  xg_pad = jnp.concatenate([xg, jnp.zeros((g, 1, d), xg.dtype)], axis=1)
  expert_in = jnp.take_along_axis(xg_pad, src[..., None], axis=1)
  return expert_in.reshape(g, e, c, d).transpose(1, 0, 2, 3)


def IndexedCombine(expert_out: jax.Array, gating: NestedMap) -> jax.Array:
  """[E,G,C,D] expert outputs -> [G,S,D] tokens: gather + gate-weighted sum."""
  e, g, c, d = expert_out.shape
  k, _, s = gating.indices.shape
  eo = expert_out.transpose(1, 0, 2, 3).reshape(g, e * c, d)
  out = jnp.zeros((g, s, d), expert_out.dtype)
  for i in range(k):
    flat = jnp.clip(gating.indices[i] * c + gating.positions[i], 0, e * c - 1)
    vals = jnp.take_along_axis(eo, flat[..., None], axis=1)      # [G,S,D]
    out = out + gating.gates[i][..., None].astype(eo.dtype) * vals
  return out


class MoEFeedForwardLayer(base_layer.BaseLayer):
  """Expert-parallel MoE FFN block (pre-LN, residual), GShard-style.

  Weights wi/wo are [E, D, H] / [E, H, D] with 'expert' on dim 0 — under a
  mesh with an expert axis the dispatch einsum reshards tokens
  data-major -> expert-major (compiler all-to-all), experts run as one big
  batched matmul on the MXU, and combine reshards back.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Model dim D.")
    p.Define("hidden_dim", 0, "Expert FFN hidden dim H.")
    p.Define("num_experts", 8, "E.")
    p.Define("num_groups", 0,
             "G: gating groups per batch (tokens compete for capacity within "
             "a group). 0 = auto: the 'expert' axis size of the active mesh "
             "(groups shard over that axis), falling back to the 'data' "
             "axis then min(batch, 8) — keeps the dispatch tensor "
             "[G, S/G, E, C] bounded instead of [1, B*T, E, C].")
    p.Define("capacity_factor", 2.0, "Per-expert capacity factor.")
    p.Define("activation", "RELU", "Expert FFN activation.")
    p.Define("gating_policy", "top2",
             "'top2' (learned router), 'hash' (id-hash top-1, ref "
             "HashGatingOnLogits:2367; requires token_ids at FProp), "
             "'sinkhorn' (optimal-transport balanced top-1, ref "
             "gshard_layers.py:2736; no aux loss), or 'expert_choice' "
             "(experts pick their top-C tokens, arXiv:2202.09368; "
             "perfectly balanced, no aux loss — NOT causal over tokens: "
             "selection sees the whole group, so prefer it for encoders/"
             "non-AR objectives).")
    p.Define("sinkhorn_num_iters", 10, "Sinkhorn iterations ('sinkhorn').")
    p.Define("sinkhorn_temperature", 1.0,
             "Sinkhorn temperature ('sinkhorn').")
    p.Define("shuffle_tokens", False,
             "Randomly permute tokens within each group before capacity "
             "truncation (ref gshard_layers.py:2496) so drops are unbiased; "
             "train-time only.")
    p.Define("dispatch_via_shard_map", None,
             "Dispatch/combine through shard_map with an explicit "
             "jax.lax.all_to_all over the 'expert' axis instead of relying "
             "on GSPMD inferring one from the einsum resharding. None = "
             "auto: use shard_map whenever an 'expert' mesh axis exists and "
             "the group/expert counts divide it (the explicit collective "
             "never regresses to all-gather).")
    p.Define("dispatch_method", "auto",
             "'einsum': one-hot dispatch/combine einsums over [G,S,E,C] "
             "(what GSPMD auto-partitioning needs to infer the all-to-all); "
             "'indexed': scatter/gather slot assignment, O(tokens*D) memory "
             "ops instead of O(tokens*E*C*D) matmul flops; 'auto': indexed "
             "except on the GSPMD einsum multi-device path.")
    p.Define("second_expert_policy", "all", "'all' or 'random'.")
    p.Define("aux_loss_weight", 0.01, "Aux load-balancing loss weight.")
    p.Define("residual_dropout_prob", 0.0, "Residual dropout.")
    p.Define("norm_tpl", layers_lib.LayerNorm.Params(), "Pre-norm template.")
    p.Define("expert_capacity", 0, "Fixed capacity override (0 = derive).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.input_dim and p.hidden_dim and p.num_experts
    self.CreateChild("ln", p.norm_tpl.Copy().Set(input_dim=p.input_dim))
    self.CreateVariable(
        "gating",
        WeightParams((p.input_dim, p.num_experts), p.params_init, p.dtype))
    self.CreateVariable(
        "wi",
        WeightParams((p.num_experts, p.input_dim, p.hidden_dim),
                     p.params_init, p.dtype,
                     tensor_split_dims_mapping=("expert", None, "model")))
    self.CreateVariable(
        "wo",
        WeightParams((p.num_experts, p.hidden_dim, p.input_dim),
                     p.params_init, p.dtype,
                     tensor_split_dims_mapping=("expert", "model", None)))
    self.CreateChild("dropout", layers_lib.DeterministicDropoutLayer.Params())

  def _NumGroups(self, b: int, t: int) -> int:
    """p.num_groups, or auto = data_axis * expert_axis (groups shard over
    BOTH: each data slice routes only its own tokens — see _GroupAxes),
    clamped to a divisor of the token count. An explicit num_groups that
    does not divide the tokens fails loudly (silently changing G would
    change per-group capacity semantics)."""
    p = self.p
    g = p.num_groups
    if g > 0:
      assert (b * t) % g == 0, (
          f"num_groups={g} must divide batch*time={b * t}")
      return g
    g = ((mesh_lib.CurrentMeshAxisSize("expert") or 1)
         * (mesh_lib.CurrentMeshAxisSize("data") or 1))
    if g == 1:
      g = min(b, 8)
    g = min(g, b * t)
    while (b * t) % g != 0:  # largest divisor of b*t not above the target
      g -= 1
    return max(g, 1)

  @staticmethod
  def _GroupAxes() -> tuple:
    """Mesh axes the group (G) dim shards over: ('data', 'expert') when both
    exist. Sharding G over 'expert' ALONE (the pre-round-5 layout) replicates
    every group onto each data slice, so the expert FFN — whose weights are
    replicated over 'data' like any weight — computes every token
    data_axis-many times. Jointly sharding G keeps each data slice routing
    only its own tokens; the dispatch all-to-all rides the 'expert' axis
    within the slice."""
    axes = []
    if mesh_lib.CurrentMeshAxisSize("data"):
      axes.append("data")
    if mesh_lib.CurrentMeshAxisSize("expert"):
      axes.append("expert")
    return tuple(axes)

  def FProp(self, theta, inputs, paddings=None, token_ids=None):
    """inputs [B, T, D] -> [B, T, D]; aux loss emitted via AddAuxLoss.

    token_ids [B, T] (int) is required for p.gating_policy='hash'.
    """
    p = self.p
    th = self.CastTheta(theta)
    b, t, d = inputs.shape
    x = self.ln.FProp(theta.ln, inputs)
    g = self._NumGroups(b, t)
    s = b * t // g
    xg = x.reshape(g, s, d)
    pg = (paddings.reshape(g, s) if paddings is not None else None)
    # Localize the gating math: pin the grouped tokens to the joint
    # ('data', 'expert') group sharding up front (when it divides) so the
    # router softmax / top-k / cumsum ops run local per group shard instead
    # of GSPMD picking a layout mid-gating and resharding (the
    # collective-permute storm in the round-5 attribution analysis).
    gaxes = self._GroupAxes()
    n_gs = 1
    for ax in gaxes:
      n_gs *= mesh_lib.CurrentMeshAxisSize(ax) or 1
    if gaxes and g % n_gs == 0:
      xg = mesh_lib.WithShardingConstraint(xg, (gaxes, None, None))
      if pg is not None:
        pg = mesh_lib.WithShardingConstraint(pg, (gaxes, None))

    # Optional within-group token shuffle before capacity truncation so the
    # cumsum-position drops don't bias early positions (train-time only).
    perm = inv_perm = None
    if p.shuffle_tokens and not py_utils.DoEval() and py_utils.HasStepSeed():
      perm, inv_perm = TokenShufflePerm(
          (g, s), py_utils.StepSeed(f"{self.path}/shuffle"))
      xg_gate = _TakeAlongS(xg, perm)
      pg_gate = _TakeAlongS(pg[..., None], perm)[..., 0] if pg is not None \
          else None
    else:
      xg_gate, pg_gate = xg, pg

    # Pick the dispatch formulation. The explicit shard_map all-to-all (with
    # indexed local dispatch) is the default whenever an 'expert' mesh axis
    # exists and the divisibility constraints hold; without an expert axis
    # the indexed (gather/scatter) path avoids the one-hot einsums entirely;
    # 'einsum' remains for the GSPMD-inferred collective path.
    n_exp_axis = mesh_lib.CurrentMeshAxisSize("expert") or 0
    n_data_axis = mesh_lib.CurrentMeshAxisSize("data") or 1
    use_shard_map = p.dispatch_via_shard_map
    if use_shard_map is None:
      # an explicit dispatch_method='einsum' opts into the GSPMD-inferred
      # collective path, so auto must not steer it into shard_map
      use_shard_map = (p.dispatch_method != "einsum" and bool(n_exp_axis)
                       and g % max(n_exp_axis * n_data_axis, 1) == 0
                       and p.num_experts % max(n_exp_axis, 1) == 0)
    else:
      use_shard_map = (bool(use_shard_map) and bool(n_exp_axis)
                       and g % max(n_exp_axis * n_data_axis, 1) == 0)
    method = p.dispatch_method
    if method == "auto":
      method = "einsum" if (n_exp_axis and not use_shard_map) else "indexed"
    # shard_map dispatches via the indexed form; only the plain einsum path
    # consumes the O(G*S*E*C) one-hot tensors
    build_tensors = method == "einsum" and not use_shard_map

    if p.gating_policy == "hash":
      assert token_ids is not None, "hash gating needs token_ids"
      idg = token_ids.reshape(g, s)
      if perm is not None:
        idg = _TakeAlongS(idg[..., None], perm)[..., 0]
      gating = HashGating(idg, p.num_experts, pg_gate, p.capacity_factor,
                          capacity=p.expert_capacity or None,
                          build_tensors=build_tensors)
    elif p.gating_policy == "sinkhorn":
      logits = jnp.einsum("GSD,DE->GSE", xg_gate,
                          th.gating.astype(xg.dtype))
      gating = SinkhornGating(
          logits, pg_gate, p.capacity_factor,
          num_iters=p.sinkhorn_num_iters,
          temperature=p.sinkhorn_temperature,
          capacity=p.expert_capacity or None,
          build_tensors=build_tensors)
    elif p.gating_policy == "expert_choice":
      logits = jnp.einsum("GSD,DE->GSE", xg_gate,
                          th.gating.astype(xg.dtype))
      gating = ExpertChoiceGating(
          logits, pg_gate, p.capacity_factor,
          capacity=p.expert_capacity or None,
          build_tensors=build_tensors)
    else:
      logits = jnp.einsum("GSD,DE->GSE", xg_gate,
                          th.gating.astype(xg.dtype))
      # 'random' second-expert sampling is a TRAIN-time policy; eval/decode
      # (no step seed) falls back to deterministic top-2 (ref: the reference
      # disables sampling at inference).
      policy = p.second_expert_policy
      prng_key = None
      if policy == "random":
        if py_utils.DoEval() or not py_utils.HasStepSeed():
          policy = "all"
        else:
          prng_key = py_utils.StepSeed(f"{self.path}/gating")
      gating = Top2Gating(
          logits, pg_gate, p.capacity_factor, policy, prng_key,
          capacity=p.expert_capacity or None,
          build_tensors=build_tensors)

    if inv_perm is not None:
      # gating ran in shuffled token order: restore data order on S
      for key in ("indices", "positions", "gates"):
        gating[key] = jnp.stack(
            [_TakeAlongS(a, inv_perm) for a in gating[key]])
      # the EC native form indexes shuffled token order; fall back to the
      # generic K-row path rather than remap (shuffle is pointless for EC
      # anyway — top-k has no cumsum truncation bias to debias)
      gating.pop("ec_top_i", None)
      gating.pop("ec_top_v", None)
      if build_tensors:
        gating.dispatch_tensor = _TakeAlongS(gating.dispatch_tensor, inv_perm)
        gating.combine_tensor = _TakeAlongS(gating.combine_tensor, inv_perm)

    if use_shard_map:
      out = self._DispatchShardMap(th, xg, gating)
    elif method == "indexed" and "ec_top_i" in gating:
      # expert-choice native form: one gather in, one scatter-add out
      expert_in = EcIndexedDispatch(xg, gating)                  # [E,G,C,D]
      expert_out = self._ExpertFfn(th, expert_in)
      out = EcIndexedCombine(expert_out, gating, xg.shape[1])
    elif method == "indexed":
      expert_in = IndexedDispatch(xg, gating, p.num_experts)     # [E,G,C,D]
      expert_out = self._ExpertFfn(th, expert_in)
      out = IndexedCombine(expert_out, gating)
    else:
      dispatch = gating.dispatch_tensor.astype(xg.dtype)  # [G,S,E,C]
      combine = gating.combine_tensor.astype(xg.dtype)
      # GShard layout: token GROUPS shard jointly over ('data', 'expert')
      # (each data slice routes its own tokens; see _GroupAxes) while the
      # dispatch einsum output is constrained expert-major-within-slice, so
      # GSPMD must move tokens G-sharded -> E-sharded: that resharding IS
      # the all-to-all over 'expert' (asserted by
      # test_compiled_hlo_contains_all_to_all — without the group-major
      # constraints below GSPMD falls back to all-gathers).
      gspec = self._GroupAxes() or ("expert",)
      data_ax = "data" if "data" in gspec else None
      xg = mesh_lib.WithShardingConstraint(xg, (gspec, None, None))
      dispatch = mesh_lib.WithShardingConstraint(
          dispatch, (gspec, None, None, None))
      combine = mesh_lib.WithShardingConstraint(
          combine, (gspec, None, None, None))
      # group-major -> expert-major within each data slice (XLA inserts the
      # all-to-all over 'expert'; G stays data-sharded)
      expert_in = jnp.einsum("GSEC,GSD->EGCD", dispatch, xg)
      expert_in = mesh_lib.WithShardingConstraint(
          expert_in, ("expert", data_ax, None, None))
      h = self._ExpertFfn(th, expert_in)
      expert_out = mesh_lib.WithShardingConstraint(
          h, ("expert", data_ax, None, None))
      # expert-major -> group-major combine (second all-to-all)
      out = jnp.einsum("GSEC,EGCD->GSD", combine, expert_out)
      out = mesh_lib.WithShardingConstraint(out, (gspec, None, None))
    out = out.reshape(b, t, d)
    if p.residual_dropout_prob > 0:
      out = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), out,
          keep_prob=1.0 - p.residual_dropout_prob)
    if paddings is not None:
      out = py_utils.ApplyPadding(paddings, out)
    aux = gating.aux_loss * p.aux_loss_weight
    py_utils.AddAuxLoss(f"{self.path}/aux_loss", aux)
    return inputs + out

  def _ExpertFfn(self, th, expert_in):
    """[E, G, C, D] -> [E, G, C, D]: the per-expert FFN as one batched matmul."""
    from lingvo_tpu.core import activations
    h = jnp.einsum("EGCD,EDH->EGCH", expert_in, th.wi)
    h = activations.GetFn(self.p.activation)(h)
    return jnp.einsum("EGCH,EHD->EGCD", h, th.wo)

  def _DispatchShardMap(self, th, xg, gating):
    """Explicit all-to-all dispatch via shard_map; groups shard over
    ('data', 'expert') jointly, the all-to-all rides the 'expert' axis.

    The einsum formulation relies on GSPMD noticing that `expert_in` flips
    from group-major to expert-major sharding and inserting an all-to-all;
    when it mis-infers (an all-gather instead), this path states the
    collective outright (ref FeedForwardNetworksApplyGating:2992 — same
    math, the collective made explicit). Groups shard over BOTH the data
    and expert axes (see _GroupAxes: expert-only sharding replicates the
    expert FFN compute onto every data slice); each data slice exchanges
    tokens with its own expert shards only. Local dispatch/combine use the
    indexed (scatter/gather) formulation, not one-hot einsums:

      per device: gather local groups' tokens into slots -> [E, g_loc, C, D]
      all_to_all over 'expert': split E, concat g -> [e_loc, G/data, C, D]
      local expert FFN (each device owns its experts' weights)
      all_to_all back: split g, concat E -> [E, g_loc, C, D]
      local combine (gather + gate-weighted sum)

    The all_to_all inputs/outputs are tagged with jax.ad_checkpoint
    checkpoint_name so remat policies can pin them (saving the dispatched
    activations stops the backward pass replaying the forward all-to-alls).
    """
    from jax.ad_checkpoint import checkpoint_name
    from jax.sharding import PartitionSpec as P
    mesh = mesh_lib.CurrentMesh()
    n_exp = mesh_lib.CurrentMeshAxisSize("expert")
    gspec = self._GroupAxes() or ("expert",)
    n_group_shards = 1
    for ax in gspec:
      n_group_shards *= mesh_lib.CurrentMeshAxisSize(ax) or 1
    g, s, d = xg.shape
    e = self.p.num_experts
    c = gating.capacity
    assert g % n_group_shards == 0, (
        f"shard_map dispatch needs groups ({g}) divisible by the group "
        f"shards ({n_group_shards} = x{gspec})")
    assert e % n_exp == 0, (e, n_exp)

    # Respect the weights' declared tensor-parallel sharding: wi is
    # ('expert', None, 'model'), wo ('expert', 'model', None). Inside the
    # shard_map each device holds an H-shard of its experts; the wo
    # contraction over H is completed with a psum over 'model'.
    has_model_tp = bool(mesh_lib.CurrentMeshAxisSize("model"))

    def _Local(xg_l, idx_l, pos_l, gate_l, wi_l, wo_l):
      # xg_l [g_loc, S, D]; idx/pos/gate_l [K, g_loc, S]; wi_l [e_loc, D, H?]
      gating_l = NestedMap(indices=idx_l, positions=pos_l, gates=gate_l,
                           capacity=c)
      expert_in = IndexedDispatch(xg_l, gating_l, e)   # [E, g_loc, C, D]
      # split E over devices, gather the slice's group shards:
      # [e_loc, G/data, C, D]
      expert_in = jax.lax.all_to_all(
          expert_in, "expert", split_axis=0, concat_axis=1, tiled=True)
      expert_in = checkpoint_name(expert_in, "moe_dispatched")
      h = self._ExpertFfn(NestedMap(wi=wi_l, wo=wo_l), expert_in)
      if has_model_tp:
        h = jax.lax.psum(h, "model")  # complete the H contraction
      # back: split G, concat E -> [E, g_loc, C, D]
      h = jax.lax.all_to_all(
          h, "expert", split_axis=1, concat_axis=0, tiled=True)
      h = checkpoint_name(h, "moe_combined")
      return IndexedCombine(h, gating_l)

    model_ax = "model" if has_model_tp else None
    # check_vma off: 0.4.x's replication checker has no rule for the
    # checkpoint_name remat tags (the out_specs pin correctness instead)
    return mesh_lib.ShardMap(
        _Local, mesh=mesh,
        in_specs=(P(gspec), P(None, gspec), P(None, gspec), P(None, gspec),
                  P("expert", None, model_ax), P("expert", model_ax, None)),
        out_specs=P(gspec), check_vma=False)(
            xg, gating.indices, gating.positions, gating.gates,
            th.wi, th.wo)


class DenseMoEBlock(base_layer.BaseLayer):
  """The GShard interleave unit: one dense transformer layer + one MoE layer.

  Ref: gshard MoE transformers alternate dense and MoE feed-forwards
  (`gshard_builder.py` DenseBuilder.MoE interleave); scanning this block
  N/2 times gives an N-layer half-MoE stack with O(1) compile time.
  """

  @classmethod
  def Params(cls):
    from lingvo_tpu.core import transformer as transformer_lib
    p = super().Params()
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("dense_tpl", transformer_lib.TransformerLayer.Params(),
             "Dense transformer layer template.")
    p.Define("moe_tpl", None, "MoETransformerLayer template.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "dense",
        p.dense_tpl.Copy().Set(input_dim=p.input_dim, num_heads=p.num_heads))
    moe_tpl = p.moe_tpl or MoETransformerLayer.Params()
    self.CreateChild(
        "moe_layer",
        moe_tpl.Copy().Set(input_dim=p.input_dim, num_heads=p.num_heads))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, atten_mask=None, segment_ids=None,
            token_ids=None):
    x = self.dense.FProp(theta.dense, inputs, paddings, aux_vecs,
                         aux_paddings, atten_mask=atten_mask,
                         segment_ids=segment_ids)
    return self.moe_layer.FProp(theta.moe_layer, x, paddings,
                                atten_mask=atten_mask,
                                segment_ids=segment_ids,
                                token_ids=token_ids)


class MoETransformerLayer(base_layer.BaseLayer):
  """Transformer layer whose FFN is an MoE block (GShard MoE transformer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    from lingvo_tpu.core import transformer as transformer_lib
    p.Define("input_dim", 0, "Model dim.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("moe_tpl", MoEFeedForwardLayer.Params(), "MoE FFN template.")
    p.Define("tr_atten_tpl",
             transformer_lib.TransformerAttentionLayer.Params(),
             "Self-attention template.")
    p.Define("mask_self_atten", True, "Causal.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "self_atten",
        p.tr_atten_tpl.Copy().Set(
            input_dim=p.input_dim, num_heads=p.num_heads,
            is_masked=p.mask_self_atten))
    self.CreateChild(
        "moe", p.moe_tpl.Copy().Set(input_dim=p.input_dim))

  def FProp(self, theta, inputs, paddings=None, aux_vecs=None,
            aux_paddings=None, atten_mask=None, segment_ids=None,
            token_ids=None):
    assert aux_vecs is None, (
        "MoETransformerLayer has no cross-attention; use a TransformerLayer "
        "with has_aux_atten=True for encoder-decoder stacks")
    x, _ = self.self_atten.FProp(
        theta.self_atten, inputs, paddings=paddings, atten_mask=atten_mask,
        segment_ids=segment_ids)
    return self.moe.FProp(theta.moe, x, paddings, token_ids=token_ids)
