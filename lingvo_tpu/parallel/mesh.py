"""Device mesh + sharding lowering: the GSPMD backbone.

TPU-native replacement for the reference's sharding machinery
(`gshard_utils.py:39-135` Split/Replicate/MeshSplit, `TensorShardingSpec:237`,
`base_layer.py:262-280` split_dims_mapping params, device-mesh shapes like
`synthetic_packed_input.py:68`). The reference annotates TF tensors with XLA
sharding ops; here the same annotations are mesh-axis NAMES carried on
`WeightParams.tensor_split_dims_mapping`, lowered to
`jax.sharding.NamedSharding` — identical compiler path (GSPMD), zero custom
partitioning code.

Canonical axis names (SURVEY.md §2.9 mapping):
  'data'    — batch/data parallelism (gradient psum rides ICI)
  'model'   — tensor parallelism (Megatron-style, heads/ffn-hidden)
  'expert'  — MoE expert parallelism (all-to-all dispatch)
  'stage'   — pipeline stages
  'seq'     — sequence/context parallelism (ring attention)
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from lingvo_tpu.core.nested_map import NestedMap

DATA_AXIS = "data"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"
STAGE_AXIS = "stage"
SEQ_AXIS = "seq"


def MakeMesh(axis_sizes: dict[str, int] | None = None,
             devices: Sequence[Any] | None = None) -> Mesh:
  """Builds a Mesh from {axis_name: size}; -1 once means 'all remaining'.

  Axis order follows insertion order of axis_sizes; put the fastest-varying
  (ICI-adjacent) axis last — on TPU slices jax orders devices so that
  trailing mesh dims map to nearest neighbors (what 'model'/'seq' want).
  """
  devices = list(devices if devices is not None else jax.devices())
  n = len(devices)
  axis_sizes = dict(axis_sizes or {DATA_AXIS: -1})
  unknown = [k for k, v in axis_sizes.items() if v == -1]
  known = int(np.prod([v for v in axis_sizes.values() if v != -1])) or 1
  if unknown:
    assert len(unknown) == 1, "only one -1 axis allowed"
    assert n % known == 0, (n, axis_sizes)
    axis_sizes[unknown[0]] = n // known
  total = int(np.prod(list(axis_sizes.values())))
  assert total == n, f"mesh {axis_sizes} != {n} devices"
  shape = tuple(axis_sizes.values())
  dev_array = np.asarray(devices).reshape(shape)
  return Mesh(dev_array, tuple(axis_sizes.keys()))


def SpecFromSplitDims(split_dims_mapping: Sequence[Any] | None
                      ) -> PartitionSpec:
  """tensor_split_dims_mapping (axis names / None per dim) -> PartitionSpec."""
  if split_dims_mapping is None:
    return PartitionSpec()
  return PartitionSpec(*[
      tuple(a) if isinstance(a, (list, tuple)) else a
      for a in split_dims_mapping
  ])


def _FilterSpecToMesh(spec: PartitionSpec, mesh: Mesh,
                      shape: Sequence[int] | None = None) -> PartitionSpec:
  """Drops axis names absent from `mesh` and shardings that don't divide the
  dim evenly (GSPMD would pad; we keep weights exact instead)."""
  axes = set(mesh.axis_names)
  out = []
  for i, entry in enumerate(spec):
    names = entry if isinstance(entry, tuple) else (
        (entry,) if entry is not None else ())
    names = tuple(nm for nm in names if nm in axes)
    if shape is not None and names:
      total = int(np.prod([mesh.shape[nm] for nm in names]))
      if shape[i] % total != 0:
        names = ()
    out.append(names if len(names) > 1 else (names[0] if names else None))
  return PartitionSpec(*out)


def ShardingForWeight(mesh: Mesh, wp, path: str = "") -> NamedSharding:
  """WeightParams -> NamedSharding (replicated when unannotated)."""
  spec = SpecFromSplitDims(getattr(wp, "tensor_split_dims_mapping", None))
  spec = _FilterSpecToMesh(spec, mesh, wp.shape)
  return NamedSharding(mesh, spec)


def ThetaShardings(mesh: Mesh, layer, theta: NestedMap | None = None,
                   stack_axis_name: str | None = None) -> NestedMap:
  """Sharding pytree for a layer's theta, from its WeightParams annotations.

  Pass `theta` when the layer stacks weights (RepeatedTransformerLayer /
  PipelinedLayer): a theta leaf with one extra leading dim vs its spec gets
  that dim replicated — or sharded over `stack_axis_name` (e.g. 'stage').
  """
  specs = layer.VariableSpecs()

  def _One(wp, leaf=None):
    sdm = list(wp.tensor_split_dims_mapping or [None] * len(wp.shape))
    shape = list(wp.shape)
    if leaf is not None and np.ndim(leaf) == len(shape) + 1:
      sdm = [stack_axis_name] + sdm
      shape = [np.shape(leaf)[0]] + shape
    spec = _FilterSpecToMesh(SpecFromSplitDims(sdm), mesh, shape)
    return NamedSharding(mesh, spec)

  # WeightParams is an unregistered dataclass => a pytree leaf already.
  if theta is None:
    return jax.tree_util.tree_map(_One, specs)
  return jax.tree_util.tree_map(_One, specs, theta)


def TrainStateShardings(mesh: Mesh, task, state: NestedMap,
                        fsdp_axis: str | None = None) -> NestedMap:
  """Shardings for a full train state (theta + opt slots + step).

  Optimizer slot tensors inherit the sharding of their weight where shapes
  match (Adam m/v), and the reduced-dim sharding for factored Adafactor
  slots (vr/vc drop the last/second-to-last dim respectively) — the
  TPU-native equivalent of the reference's sharded optimizer slots
  (`optimizer.py:905-1275`).

  fsdp_axis: if set (usually 'data'), ZeRO-style-shard every state tensor
  additionally over that axis, on the first dim that divides evenly and is
  not already model-sharded. f32 master weights, momentum, and factored
  slots then live data-sharded; GSPMD all-gathers the bf16 compute copy per
  scan step (FSDP) and reduce-scatters gradients — what lets 175B-scale
  states fit per-device HBM when tensor parallelism alone cannot (the
  reference's XLAShardingAdafactor slot sharding, taken one step further).
  """
  flat_specs = dict(task.VariableSpecs().FlattenItems())
  replicated = NamedSharding(mesh, PartitionSpec())
  fsdp_size = mesh.shape[fsdp_axis] if (
      fsdp_axis and fsdp_axis in mesh.axis_names) else 0

  def _AddFsdp(spec: PartitionSpec, shape) -> PartitionSpec:
    if not fsdp_size or fsdp_size == 1:
      return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))

    def _Names(entry):
      return entry if isinstance(entry, tuple) else (
          (entry,) if entry is not None else ())

    if any(fsdp_axis in _Names(e) for e in entries):
      return spec  # already sharded over it (on any dim)
    for i, (entry, dim) in enumerate(zip(entries, shape)):
      names = _Names(entry)
      taken = int(np.prod([mesh.shape[nm] for nm in names])) if names else 1
      if dim % (taken * fsdp_size) == 0:
        new = tuple(names) + (fsdp_axis,)
        entries[i] = new if len(new) > 1 else new[0]
        return PartitionSpec(*entries)
    return spec

  def _ForPath(path: str, leaf):
    # state paths look like: theta.a.b.w / opt_states[0].slots.a.b.w.vr /
    # ema_theta.a.b.w
    parts = path.split(".")
    if parts[0] == "theta" or parts[0] == "ema_theta":
      var_path = ".".join(parts[1:])
      slot = None
    elif parts[0].startswith("opt_states"):
      # strip leading opt_states[i] (+ optional 'slots'/'m'/'inner' wrappers)
      rest = parts[1:]
      while rest and rest[0] in ("slots", "inner", "accum", "m", "v", "ms",
                                 "mom", "acc"):
        rest = rest[1:]
      if not rest:
        return replicated
      slot = None
      if rest[-1] in ("vr", "vc", "v", "m"):
        slot = rest[-1]
        rest = rest[:-1]
      var_path = ".".join(rest)
    else:
      return replicated
    wp = flat_specs.get(var_path)
    if wp is None:
      return replicated
    if wp.tensor_split_dims_mapping is None and not fsdp_size:
      return replicated
    sdm = list(wp.tensor_split_dims_mapping or [None] * len(wp.shape))
    shape = list(wp.shape)
    if slot == "vr":  # reduced over last dim
      sdm, shape = sdm[:-1], shape[:-1]
    elif slot == "vc":  # reduced over second-to-last dim
      sdm, shape = sdm[:-2] + sdm[-1:], shape[:-2] + shape[-1:]
    if len(shape) != len(np.shape(leaf)) or list(np.shape(leaf)) != shape:
      # stacked (repeat-layer) leaves: leading dim added
      if (len(np.shape(leaf)) == len(shape) + 1 and
          list(np.shape(leaf))[1:] == shape):
        sdm = [None] + sdm
        shape = [np.shape(leaf)[0]] + shape
      else:
        return replicated
    spec = _FilterSpecToMesh(SpecFromSplitDims(sdm), mesh, shape)
    spec = _AddFsdp(spec, shape)
    return NamedSharding(mesh, spec)

  items = state.FlattenItems()
  return state.Pack([_ForPath(k, v) for k, v in items])


def BatchShardings(mesh: Mesh, batch: NestedMap,
                   batch_axes: Sequence[str] = (DATA_AXIS,)) -> NestedMap:
  """Shards every batch leaf's leading dim over the data axes."""
  axes = tuple(a for a in batch_axes if a in mesh.axis_names)
  spec = PartitionSpec(axes if len(axes) > 1 else (axes[0] if axes else None))
  sharding = NamedSharding(mesh, spec)
  return batch.Transform(lambda _: sharding)


def PutBatch(mesh: Mesh, batch: NestedMap,
             batch_axes: Sequence[str] = (DATA_AXIS,)) -> NestedMap:
  """Host batch -> device arrays sharded over the data axes."""
  shardings = BatchShardings(mesh, batch, batch_axes)
  import jax.numpy as jnp
  return jax.tree_util.tree_map(
      lambda x, s: jax.device_put(jnp.asarray(x), s), batch, shardings)


def MeshContext(mesh: Mesh):
  """Enters `mesh` as the ambient mesh so PartitionSpec-based
  with_sharding_constraint hints (MoE dispatch, pipeline buffers) reach
  GSPMD. Use around jit calls: `with mesh_lib.MeshContext(mesh): ...`."""
  set_mesh = getattr(jax, "set_mesh", None)
  if set_mesh is not None:  # jax >= 0.6: ambient abstract mesh
    return set_mesh(mesh)
  # jax 0.4.x: the Mesh object itself is the context manager (physical
  # mesh / pjit resource env), which with_sharding_constraint uses to
  # resolve bare PartitionSpecs
  return mesh


def CurrentMesh():
  """The ambient mesh entered by MeshContext, or None.

  Version-tolerant (the whole point — PR-7's shard_map MoE dispatch silently
  deactivated on jax 0.4.x because only the abstract-mesh API was queried):
  jax >= 0.6 exposes the ambient mesh as `jax.sharding.get_abstract_mesh()`;
  on 0.4.x the Mesh context manager populates the pjit resource env
  (`thread_resources.env.physical_mesh`) instead. Returns whichever is
  active and non-empty.
  """
  try:
    from jax.sharding import get_abstract_mesh
    m = get_abstract_mesh()
    if m is not None and tuple(m.axis_names):
      return m
  except Exception:
    pass
  try:  # jax 0.4.x: the physical mesh entered by MeshContext
    from jax._src import mesh as _mesh_impl
    m = _mesh_impl.thread_resources.env.physical_mesh
    if m is not None and not m.empty:
      return m
  except Exception:
    pass
  return None


def ShardMap(fn, mesh=None, *, in_specs, out_specs, check_vma=None):
  """Version-tolerant `shard_map` (jax >= 0.8 `jax.shard_map` with
  `check_vma`; 0.4.x `jax.experimental.shard_map.shard_map` where the same
  knob is called `check_rep`). mesh=None resolves the ambient mesh — raises
  when there is none, since shard_map without a mesh cannot mean anything.
  """
  if mesh is None:
    mesh = CurrentMesh()
    assert mesh is not None, "ShardMap outside a MeshContext"
  try:
    from jax import shard_map as _shard_map  # jax >= 0.8
    kw = {} if check_vma is None else {"check_vma": check_vma}
  except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map
    kw = {} if check_vma is None else {"check_rep": check_vma}
  return _shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **kw)


def WithShardingConstraint(x, spec_or_names):
  """MeshSplit equivalent (ref gshard_utils.MeshSplit): annotate inside jit.

  No-op when there is no mesh context (explicitly detected — annotations are
  best-effort across mesh configs, like the reference's MeshSplit with
  device_mesh=None). Axis names absent from the current mesh are dropped;
  anything else invalid (e.g. wrong-rank spec) raises loudly.
  """
  if isinstance(spec_or_names, PartitionSpec):
    spec = spec_or_names
  else:
    spec = SpecFromSplitDims(spec_or_names)
  mesh = CurrentMesh()
  if mesh is None:
    return x
  mesh_axes = tuple(mesh.axis_names)
  filtered = []
  for entry in spec:
    names = entry if isinstance(entry, tuple) else (
        (entry,) if entry is not None else ())
    names = tuple(nm for nm in names if nm in mesh_axes)
    filtered.append(names if len(names) > 1 else (
        names[0] if names else None))
  return jax.lax.with_sharding_constraint(x, PartitionSpec(*filtered))


def CurrentMeshAxisSize(name: str):
  """Size of axis `name` in the ambient mesh, or None if no such axis."""
  m = CurrentMesh()
  if m is None or name not in tuple(m.axis_names):
    return None
  return int(m.shape[name])
