"""Ulysses sequence parallelism: head-scatter all-to-all attention.

BEYOND-reference capability (SURVEY.md §5 lists it alongside ring
attention as the SP strategies the reference lacks; DeepSpeed-Ulysses,
arXiv:2309.14509). The sequence axis is a mesh dim: each device holds a
T/P slice of Q/K/V with ALL heads. Around attention, one `all_to_all`
re-shards to the FULL sequence with n/P heads per device, the fused flash
kernel runs unchanged (exact, causal-capable), and a second `all_to_all`
restores sequence sharding.

Trade-off vs ring attention: Ulysses moves activations twice (2 x
all-to-all of q/k/v/out) but runs attention as ONE dense kernel per
device — better when heads are plentiful and ICI all-to-all is cheap
(single slice); ring keeps heads whole and rotates KV P times — better
when n < P or for very long T where the 2x activation traffic dominates.
Both are exact; pick per topology.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from lingvo_tpu.ops import flash_attention
from lingvo_tpu.parallel import mesh as mesh_lib


def UlyssesAttention(q, k, v, *, mesh: Mesh,
                     seq_axis: str = mesh_lib.SEQ_AXIS,
                     causal: bool = True, block_q: int = 1024,
                     block_k: int = 1024):
  """q/k/v: [b, T, n, h] GLOBALLY, sharded [b, T/P, n, h] over seq_axis.

  Returns [b, T, n, h] with the same sharding, exactly equal to full
  (flash) attention, differentiable end to end (the all_to_alls transpose
  in the backward pass; the kernel carries its own custom VJP). Requires
  num_heads % mesh.shape[seq_axis] == 0. Scaling by 1/sqrt(h) happens
  inside the kernel.
  """
  num = mesh.shape[seq_axis]
  n = q.shape[2]
  if n % num != 0:
    raise ValueError(
        f"Ulysses needs num_heads ({n}) divisible by the '{seq_axis}' "
        f"mesh axis ({num}); use RingAttention for head-poor configs.")
  interpret = jax.default_backend() != "tpu"

  def _Local(q, k, v):
    # [b, T/P, n, h] -> [b, T, n/P, h]: scatter heads, gather sequence
    q, k, v = (jax.lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1,
                                  tiled=True) for x in (q, k, v))
    out = flash_attention.FlashAttention(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret)
    # [b, T, n/P, h] -> [b, T/P, n, h]: gather heads, scatter sequence
    return jax.lax.all_to_all(out, seq_axis, split_axis=1, concat_axis=2,
                              tiled=True)

  spec = PartitionSpec(None, seq_axis, None, None)
  # check_vma off: the pallas flash kernel doesn't declare varying-across-
  # mesh axes (same setting as ring_attention's shard_maps)
  return mesh_lib.ShardMap(_Local, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)(q, k, v)
