"""Point-to-point device channels, the SPMD way.

Re-designs `lingvo/core/sendrecv.py` (Channel.Send/Recv wrapping TF _Send/
_Recv between named devices). Under JAX SPMD there are no per-device graphs
to stitch: point-to-point transfer IS `jax.lax.ppermute` over a mesh axis
inside `shard_map` — XLA lowers it to collective-permute on ICI, the same
wire primitive TF's _Send/_Recv pair used. These helpers name the common
patterns; `parallel/stacked_recurrent.py` and `parallel/pipeline.py` are the
in-tree consumers of the idiom.
"""

from __future__ import annotations

import jax


def _AxisSize(axis_name: str) -> int:
  """jax.lax.axis_size where available (>=0.6); psum-of-ones otherwise."""
  fn = getattr(jax.lax, "axis_size", None)
  if fn is not None:
    return fn(axis_name)
  return jax.lax.psum(1, axis_name)


def Shift(x, axis_name: str, offset: int = 1, wrap: bool = False):
  """Sends each shard's `x` to the neighbor `offset` steps up the axis.

  Shard i's value arrives at shard i+offset (mod axis size if `wrap`).
  Without wrap, the lowest shards receive zeros (XLA's collective-permute
  semantics for unmatched targets) — the pipeline-fill behavior.
  """
  n = _AxisSize(axis_name)
  if wrap:
    perm = [(i, (i + offset) % n) for i in range(n)]
  else:
    perm = [(i, i + offset) for i in range(n) if 0 <= i + offset < n]
  return jax.lax.ppermute(x, axis_name, perm)


def SendRecv(x, pairs, axis_name: str):
  """Explicit (src, dst) channel list (ref Channel semantics).

  Shards not named as a dst receive zeros.
  """
  return jax.lax.ppermute(x, axis_name, list(pairs))


def SendPages(blocks, pairs, axis_name: str):
  """KV page handoff between fleet workers (serving/fleet.py).

  `blocks` is a pytree of per-paged-leaf [n, ...] page blocks — the
  gathered output of `ServingLoop.ExportPrefixBlocks` (int8 K/V pools
  and their f32 scale sidecars are separate leaves and ride the same
  pairs). Every leaf is ppermuted along `axis_name` with one explicit
  (src, dst) list, so a prefill worker's finished pages land on its
  decode worker in a single collective-permute; non-dst shards receive
  zeros they never read.
  """
  return jax.tree_util.tree_map(
      lambda x: jax.lax.ppermute(x, axis_name, list(pairs)), blocks)
