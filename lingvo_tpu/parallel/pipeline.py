"""Pipeline parallelism inside one SPMD program.

Re-designs `gshard_layers.LayerwiseShardablePipelinedLayer:180` (and the
graph-mode `gpipe.PipeliningLayer:324`) the TPU way: stages are the leading
dim of stacked weights, sharded over the 'stage' mesh axis; a shifting state
buffer moves activations stage->stage each iteration (XLA lowers the shift of
a stage-sharded buffer to collective-permute over ICI); micro-batches stream
through a lax.scan. One program, no per-device graph surgery.

Schedule: classic GPipe fill/drain — M micro-batches through L stages in
M + L - 1 iterations; bubble fraction (L-1)/(M+L-1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.parallel import mesh as mesh_lib


class PipelinedLayer(base_layer.BaseLayer):
  """Runs `body` as `num_stages` pipeline stages over micro-batches.

  theta.body: every leaf stacked [num_stages, ...], annotated to shard dim 0
  over 'stage'. FProp consumes [B, T, D] (B must divide into
  num_microbatches) and is numerically identical to running the body layers
  sequentially.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_stages", 1, "Pipeline stages L.")
    p.Define("num_microbatches", 1, "Micro-batches M per global batch.")
    p.Define("body", None, "Stage body layer params (one stage's compute).")
    p.Define("stage_axis", mesh_lib.STAGE_AXIS,
             "Mesh axis the stage dim shards over.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.num_stages >= 1 and p.body is not None
    self.CreateChild("body", p.body)

  def InstantiateVariables(self, key):
    if self._path is None:
      self.FinalizePaths()
    return NestedMap(body=base_layer.StackedInstantiateVariables(
        self.body, key, self.p.num_stages))

  def VariableSpecs(self):
    return NestedMap(body=base_layer.StackedVariableSpecs(
        self.body, self.p.num_stages))

  def _StageSpec(self, x):
    """PartitionSpec sharding dim 0 (stages) of a buffer."""
    return (self.p.stage_axis,) + (None,) * (x.ndim - 1)

  def FProp(self, theta, inputs, paddings=None):
    p = self.p
    l, m = p.num_stages, p.num_microbatches
    b = inputs.shape[0]
    assert b % m == 0, (b, m)
    mb = b // m
    # [M, mb, T, D] microbatches
    x_micro = inputs.reshape((m, mb) + inputs.shape[1:])
    pad_micro = (paddings.reshape((m, mb) + paddings.shape[1:])
                 if paddings is not None else
                 jnp.zeros((m, mb) + inputs.shape[1:2], jnp.float32))

    state = jnp.zeros((l,) + x_micro.shape[1:], inputs.dtype)
    pad_state = jnp.zeros((l,) + pad_micro.shape[1:], jnp.float32)
    outputs = jnp.zeros_like(x_micro)
    stage_ids = jnp.arange(l)

    aux_flag = py_utils.NewAuxFlag()

    def _OneStage(theta_i, x_i, pad_i, sid):
      with py_utils.StepSeedSalt(sid):
        out = self.body.FProp(theta_i, x_i, pad_i)
      return out[0] if isinstance(out, tuple) else out

    # aux losses inside vmap/scan are trace-local: carried out via outputs.
    _one_wrapped = py_utils.CollectAuxLosses(_OneStage, aux_flag)

    def _RunStages(theta_body, xs, pads):
      return jax.vmap(_one_wrapped)(theta_body, xs, pads, stage_ids)

    def _Iter(carry, i):
      state, pad_state, outputs, aux_acc = carry
      # shift: stage s input <- stage s-1 output; stage 0 <- microbatch i
      feed_idx = jnp.minimum(i, m - 1)
      x_in = jax.lax.dynamic_index_in_dim(x_micro, feed_idx, 0,
                                          keepdims=False)
      pad_in = jax.lax.dynamic_index_in_dim(pad_micro, feed_idx, 0,
                                            keepdims=False)
      shifted = jnp.roll(state, 1, axis=0).at[0].set(x_in)
      pad_shifted = jnp.roll(pad_state, 1, axis=0).at[0].set(pad_in)
      shifted = mesh_lib.WithShardingConstraint(shifted, self._StageSpec(shifted))
      new_state, aux_per_stage = _RunStages(theta.body, shifted, pad_shifted)
      new_state = mesh_lib.WithShardingConstraint(new_state,
                                                 self._StageSpec(new_state))
      # aux losses only from stages holding a REAL microbatch (stage s at
      # iteration i processes microbatch i-s; bubble stages hold garbage).
      micro_idx = i - stage_ids
      valid = ((micro_idx >= 0) & (micro_idx < m)).astype(jnp.float32)
      aux_acc = aux_acc + jnp.sum(aux_per_stage * valid)
      # collect the last stage's output; warmup garbage lands on slot 0 and
      # is overwritten by the real microbatch-0 result at iteration l-1.
      out_idx = jnp.maximum(i - (l - 1), 0)
      outputs = jax.lax.dynamic_update_index_in_dim(
          outputs, new_state[-1], out_idx, 0)
      return (new_state, pad_shifted, outputs, aux_acc), ()

    aux_acc0 = jnp.zeros((), jnp.float32)
    (state, pad_state, outputs, aux_acc), _ = jax.lax.scan(
        _Iter, (state, pad_state, outputs, aux_acc0), jnp.arange(m + l - 1))
    if aux_flag.emitted:
      py_utils.AddAuxLoss(f"{self.path}/aux_loss", aux_acc)
    return outputs.reshape(inputs.shape)
