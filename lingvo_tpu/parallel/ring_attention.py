"""Ring attention: exact causal attention with the sequence dim sharded.

BEYOND-reference capability (SURVEY.md §5: the reference has no sequence/
context parallelism — only blocked approximations like
`batch_major_attention.py:2656,4008`). Here the sequence axis is a
first-class mesh dim ('seq'): each device holds a T/n slice of Q/K/V; K/V
blocks rotate around the ring with `ppermute` over ICI while each device
accumulates its queries' attention online, overlapping compute with
neighbor transfers.

The per-block compute is the Pallas flash kernel (`ops/flash_attention`),
not a naive einsum: each rotation runs `_FlashForward` on (local Q, visiting
KV block) and the normalized block outputs are merged with their logsumexp
(online softmax across blocks). The backward is a second ring pass built on
`_FlashBackward`: dK/dV accumulators rotate WITH their K/V blocks (arriving
home after a full cycle) while dQ accumulates locally — the whole ring is a
single `jax.custom_vjp`, so remat/grad transforms see one opaque exact-
attention op. Numerics match full attention (f32 accumulators/merges).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from lingvo_tpu.ops import flash_attention
from lingvo_tpu.parallel import mesh as mesh_lib

LANES = flash_attention.LANES


def _FitBlock(requested: int, t: int) -> int:
  c = min(requested, t)
  while c > 1 and t % c != 0:
    c //= 2
  return max(c, 1)


def _BlockFlashFwd(q, k, v, mode, block_q, block_k, interpret):
  """One ring step's attention: q [bn,tq,h] vs one KV block [bn,tk,h].

  mode: 0 = block entirely in the causal future (skip), 1 = diagonal block
  (causal mask), 2 = entirely in the past (full attention).
  Returns (out [bn,tq,h] normalized-within-block, lse [bn,tq] f32;
  lse = -inf where the block contributes nothing).
  """

  def _Skip(q, k, v):
    del k, v
    return (jnp.zeros(q.shape, q.dtype),
            jnp.full(q.shape[:2], -jnp.inf, jnp.float32))

  def _Run(causal):
    def _F(q, k, v):
      out, lse = flash_attention._FlashForward(
          q, k, v, None, block_q, block_k, causal, interpret)
      return out, lse[:, :, 0]
    return _F

  return jax.lax.switch(mode, [_Skip, _Run(True), _Run(False)], q, k, v)


def _MergeLse(o_acc, lse_acc, o_blk, lse_blk):
  """Online-softmax merge of normalized partials via their logsumexps."""
  lse = jnp.logaddexp(lse_acc, lse_blk)                  # [bn, t]
  ninf = jnp.isneginf(lse)
  a_acc = jnp.where(jnp.isneginf(lse_acc) | ninf, 0.0,
                    jnp.exp(lse_acc - lse))
  a_blk = jnp.where(jnp.isneginf(lse_blk) | ninf, 0.0,
                    jnp.exp(lse_blk - lse))
  o = a_acc[..., None] * o_acc + a_blk[..., None] * o_blk.astype(jnp.float32)
  return o, lse


def _BlockFlashBwd(q, k, v, do, out, lse3, mode, block_q, block_k,
                   interpret):
  """Per-(q-shard, kv-block) gradients with GLOBAL lse/out (so p and delta
  are the true global attention quantities). Returns (dq, dk, dv) f32."""

  def _Skip(q, k, v, do, out, lse3):
    del do, out, lse3
    return (jnp.zeros(q.shape, jnp.float32),
            jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32))

  def _Run(causal):
    def _F(q, k, v, do, out, lse3):
      dq, dk, dv = flash_attention._FlashBackward(
          q, k, v, None, out, lse3, do, block_q, block_k, causal, interpret)
      return (dq.astype(jnp.float32), dk.astype(jnp.float32),
              dv.astype(jnp.float32))
    return _F

  return jax.lax.switch(mode, [_Skip, _Run(True), _Run(False)],
                        q, k, v, do, out, lse3)


def _Mode(blk_idx, my_idx, causal: bool):
  if not causal:
    return jnp.int32(2)
  return jnp.where(blk_idx == my_idx, 1,
                   jnp.where(blk_idx < my_idx, 2, 0)).astype(jnp.int32)


def _RingFwdLocal(q, k, v, *, axis, num, causal, block_q, block_k,
                  interpret):
  """Per-device forward: q/k/v [bn, t_loc, h] -> (out, lse [bn, t_loc])."""
  my_idx = jax.lax.axis_index(axis)
  perm = [(i, (i + 1) % num) for i in range(num)]
  bn, t_loc, h = q.shape
  o0 = jnp.zeros((bn, t_loc, h), jnp.float32)
  lse0 = jnp.full((bn, t_loc), -jnp.inf, jnp.float32)

  def _Step(_, carry):
    o, lse, kb, vb, bidx = carry
    bo, blse = _BlockFlashFwd(q, kb, vb, _Mode(bidx, my_idx, causal),
                              block_q, block_k, interpret)
    o, lse = _MergeLse(o, lse, bo, blse)
    kb = jax.lax.ppermute(kb, axis, perm)
    vb = jax.lax.ppermute(vb, axis, perm)
    bidx = jax.lax.ppermute(bidx, axis, perm)
    return o, lse, kb, vb, bidx

  o, lse, _, _, _ = jax.lax.fori_loop(
      0, num, _Step, (o0, lse0, k, v, my_idx))
  return o.astype(q.dtype), lse


def _RingBwdLocal(q, k, v, do, out, lse, *, axis, num, causal, block_q,
                  block_k, interpret):
  """Per-device backward ring: dK/dV accumulators travel with their blocks
  and are home again after `num` rotations; dQ accumulates in place."""
  my_idx = jax.lax.axis_index(axis)
  perm = [(i, (i + 1) % num) for i in range(num)]
  bn, t_loc, h = q.shape
  lse3 = jnp.broadcast_to(lse[..., None], (bn, t_loc, LANES))
  dq0 = jnp.zeros((bn, t_loc, h), jnp.float32)

  def _Step(_, carry):
    dq, kb, vb, dkb, dvb, bidx = carry
    dq_c, dk_c, dv_c = _BlockFlashBwd(
        q, kb, vb, do, out, lse3, _Mode(bidx, my_idx, causal),
        block_q, block_k, interpret)
    dq = dq + dq_c
    dkb = dkb + dk_c
    dvb = dvb + dv_c
    kb = jax.lax.ppermute(kb, axis, perm)
    vb = jax.lax.ppermute(vb, axis, perm)
    dkb = jax.lax.ppermute(dkb, axis, perm)
    dvb = jax.lax.ppermute(dvb, axis, perm)
    bidx = jax.lax.ppermute(bidx, axis, perm)
    return dq, kb, vb, dkb, dvb, bidx

  dq, _, _, dk, dv, _ = jax.lax.fori_loop(
      0, num, _Step,
      (dq0, k, v, jnp.zeros_like(dq0), jnp.zeros_like(dq0), my_idx))
  return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _Flat(x):
  b, t, n, h = x.shape
  return x.transpose(0, 2, 1, 3).reshape(b * n, t, h)


def _Unflat(x, b, n):
  bn, t, h = x.shape
  return x.reshape(b, n, t, h).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _RingCore(q, k, v, mesh, seq_axis, causal, block_q, block_k):
  out, _ = _RingCoreFwd(q, k, v, mesh, seq_axis, causal, block_q, block_k)
  return out


def _RingCoreFwd(q, k, v, mesh, seq_axis, causal, block_q, block_k):
  num = mesh.shape[seq_axis]
  interpret = jax.default_backend() != "tpu"
  b, t, n, h = q.shape

  def _Local(q, k, v):
    out, lse = _RingFwdLocal(
        _Flat(q), _Flat(k), _Flat(v), axis=seq_axis, num=num, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret)
    return _Unflat(out, b, n), lse.reshape(b, n, -1)

  spec = PartitionSpec(None, seq_axis, None, None)
  lse_spec = PartitionSpec(None, None, seq_axis)
  out, lse = mesh_lib.ShardMap(
      _Local, mesh=mesh, in_specs=(spec, spec, spec),
      out_specs=(spec, lse_spec), check_vma=False)(q, k, v)
  return out, (q, k, v, out, lse)


def _RingCoreBwd(mesh, seq_axis, causal, block_q, block_k, res, g):
  q, k, v, out, lse = res
  num = mesh.shape[seq_axis]
  interpret = jax.default_backend() != "tpu"
  b, t, n, h = q.shape

  def _Local(q, k, v, do, out, lse):
    dq, dk, dv = _RingBwdLocal(
        _Flat(q), _Flat(k), _Flat(v), _Flat(do), _Flat(out),
        lse.reshape(b * n, -1), axis=seq_axis, num=num, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret)
    return _Unflat(dq, b, n), _Unflat(dk, b, n), _Unflat(dv, b, n)

  spec = PartitionSpec(None, seq_axis, None, None)
  lse_spec = PartitionSpec(None, None, seq_axis)
  return mesh_lib.ShardMap(
      _Local, mesh=mesh, in_specs=(spec, spec, spec, spec, spec, lse_spec),
      out_specs=(spec, spec, spec), check_vma=False)(q, k, v, g, out, lse)


_RingCore.defvjp(_RingCoreFwd, _RingCoreBwd)


def RingAttention(q, k, v, *, mesh: Mesh, seq_axis: str = mesh_lib.SEQ_AXIS,
                  causal: bool = True, block_q: int = 1024,
                  block_k: int = 1024):
  """q/k/v: [b, T, n, h] GLOBALLY, sharded [b, T/num, n, h] over seq_axis.

  Returns [b, T, n, h] attention output with the same sharding, exactly
  equal to full (flash) attention, differentiable end to end. Scaling by
  1/sqrt(h) happens inside the kernel (don't pre-scale q). Call inside jit
  with q/k/v sharded (or let jit reshard by annotation).
  """
  num = mesh.shape[seq_axis]
  t_loc = q.shape[1] // num
  block_q = _FitBlock(block_q, t_loc)
  block_k = _FitBlock(block_k, t_loc)
  return _RingCore(q, k, v, mesh, seq_axis, causal, block_q, block_k)


def RingAttentionSingleDevice(q, k, v, *, num_shards: int,
                              causal: bool = True, block_q: int = 1024,
                              block_k: int = 1024):
  """The ring decomposition executed serially on ONE device.

  Runs exactly the per-device program each of `num_shards` sp devices would
  run (num_shards q-shards x num_shards KV visits, flash per block, lse
  merges) without the ppermutes. Used (a) as an exactness oracle for tests,
  (b) by bench.py to measure the sp compute path on a single chip: with
  ideal ICI overlap, per-device ring step time ~= this / num_shards.
  """
  b, t, n, h = q.shape
  t_loc = t // num_shards
  block_q = _FitBlock(block_q, t_loc)
  block_k = _FitBlock(block_k, t_loc)
  interpret = jax.default_backend() != "tpu"
  qf, kf, vf = _Flat(q), _Flat(k), _Flat(v)
  outs = []
  for me in range(num_shards):
    q_sh = jax.lax.dynamic_slice_in_dim(qf, me * t_loc, t_loc, axis=1)
    o = jnp.zeros(q_sh.shape, jnp.float32)
    lse = jnp.full(q_sh.shape[:2], -jnp.inf, jnp.float32)
    for blk in range(num_shards):
      mode = (1 if blk == me else (2 if blk < me else 0)) if causal else 2
      if mode == 0:
        continue
      k_blk = jax.lax.dynamic_slice_in_dim(kf, blk * t_loc, t_loc, axis=1)
      v_blk = jax.lax.dynamic_slice_in_dim(vf, blk * t_loc, t_loc, axis=1)
      bo, blse = _BlockFlashFwd(q_sh, k_blk, v_blk, jnp.int32(mode),
                                block_q, block_k, interpret)
      o, lse = _MergeLse(o, lse, bo, blse)
    outs.append(o.astype(q.dtype))
  return _Unflat(jnp.concatenate(outs, axis=1), b, n)
