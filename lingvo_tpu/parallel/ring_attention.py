"""Ring attention: exact causal attention with the sequence dim sharded.

BEYOND-reference capability (SURVEY.md §5: the reference has no sequence/
context parallelism — only blocked approximations). Here the sequence axis is
a first-class mesh dim ('seq'): each device holds a T/n slice of Q/K/V; K/V
blocks rotate around the ring with `ppermute` over ICI while each device
accumulates its queries' attention online (flash-attention style running
max/denominator), overlapping compute with neighbor transfers.

Implemented with shard_map so the collective schedule is explicit; numerics
match full attention exactly (f32 accumulators).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from lingvo_tpu.parallel import mesh as mesh_lib


def _BlockAttend(q, k, v, mask):
  """Block scores: q [b,tq,n,h], k/v [b,tk,n,h] -> (scores, ctx-unnormed).

  Returns (m, l, o): running max [b,n,tq], denom [b,n,tq], out [b,tq,n,h]
  for THIS block only (caller merges online).
  """
  s = jnp.einsum("bqnh,bknh->bnqk", q, k).astype(jnp.float32)
  s = jnp.where(mask, s, -jnp.inf)
  m = jnp.max(s, axis=-1)                               # [b,n,q]
  m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
  p = jnp.exp(s - m_safe[..., None])
  p = jnp.where(mask, p, 0.0)
  l = jnp.sum(p, axis=-1)                               # [b,n,q]
  o = jnp.einsum("bnqk,bknh->bqnh", p.astype(v.dtype), v)
  return m, l, o.astype(jnp.float32)


def _Merge(m1, l1, o1, m2, l2, o2):
  """Online-softmax merge of two partial attention results."""
  m = jnp.maximum(m1, m2)
  m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
  a1 = jnp.where(jnp.isfinite(m1), jnp.exp(m1 - m_safe), 0.0)
  a2 = jnp.where(jnp.isfinite(m2), jnp.exp(m2 - m_safe), 0.0)
  l = a1 * l1 + a2 * l2
  o = (a1.swapaxes(1, 2)[..., None] * o1 +
       a2.swapaxes(1, 2)[..., None] * o2)
  return m, l, o


def RingAttention(q, k, v, *, mesh: Mesh, seq_axis: str = mesh_lib.SEQ_AXIS,
                  causal: bool = True):
  """q/k/v: [b, T, n, h] GLOBALLY, sharded [b, T/num, n, h] over seq_axis.

  Returns [b, T, n, h] attention output with the same sharding. Call inside
  jit with q/k/v sharded (or let jit reshard by annotation).
  """
  num = mesh.shape[seq_axis]
  axis = seq_axis

  def _Shard(q, k, v):
    # per-device shapes
    b, t_local, n, h = q.shape
    my_idx = jax.lax.axis_index(axis)
    scale = 1.0 / math.sqrt(h)
    q = q * scale

    q_pos = my_idx * t_local + jnp.arange(t_local)      # global q positions

    m0 = jnp.full((b, n, t_local), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, n, t_local), jnp.float32)
    o0 = jnp.zeros((b, t_local, n, h), jnp.float32)

    perm = [(i, (i + 1) % num) for i in range(num)]

    def _Step(i, carry):
      m, l, o, k_blk, v_blk, blk_idx = carry
      # mask for the currently-held K/V block (global positions)
      k_pos = blk_idx * t_local + jnp.arange(t_local)
      if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
      else:
        mask = jnp.ones((t_local, t_local), jnp.bool_)
      bm, bl, bo = _BlockAttend(q, k_blk, v_blk, mask[None, None])
      m, l, o = _Merge(m, l, o, bm, bl, bo)
      # rotate K/V to the next device (ring over ICI)
      k_next = jax.lax.ppermute(k_blk, axis, perm)
      v_next = jax.lax.ppermute(v_blk, axis, perm)
      idx_next = jax.lax.ppermute(blk_idx, axis, perm)
      return m, l, o, k_next, v_next, idx_next

    carry = (m0, l0, o0, k, v, my_idx)
    carry = jax.lax.fori_loop(0, num, _Step, carry)
    m, l, o, _, _, _ = carry
    l = jnp.maximum(l, 1e-20)
    out = o / l.swapaxes(1, 2)[..., None]
    return out.astype(q.dtype)

  spec = PartitionSpec(None, axis, None, None)
  return jax.shard_map(
      _Shard, mesh=mesh, in_specs=(spec, spec, spec),
      out_specs=spec, check_vma=False)(q, k, v)
