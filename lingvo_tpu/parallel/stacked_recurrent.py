"""Software-pipelined stacked RNN across a 'stage' mesh axis.

Re-designs `lingvo/core/recurrent.py:1423` (StackedRecurrent: RNN layers
placed on different GPUs, software-pipelined over time with sendrecv
channels). TPU-native version: the layer stack is the leading dim of stacked
cell weights, sharded over the 'stage' mesh axis; each scan tick advances
every stage by one timestep, with stage i consuming stage i-1's previous
output through a shifted (collective-permuted) buffer — the skewed schedule
means stage i runs timestep t while stage i+1 runs t-1, exactly the
reference's pipelining, with T + L - 1 ticks total.

Numerically identical to running the L cells sequentially over the sequence
(tested against stacked FRNNs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.parallel import mesh as mesh_lib


class StackedRecurrent(base_layer.BaseLayer):
  """L identical-shape RNN cells pipelined over a stage axis."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_stages", 1, "Number of stacked RNN layers L.")
    p.Define("cell", rnn_cell.LSTMCellSimple.Params(), "Cell template; "
             "num_input_nodes must equal num_output_nodes for stages>0.")
    p.Define("stage_axis", mesh_lib.STAGE_AXIS,
             "Mesh axis the stage dim shards over.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.num_stages >= 1
    assert p.cell.num_input_nodes == p.cell.num_output_nodes, (
        "pipelined stages chain outputs into inputs; dims must match")
    self.CreateChild("cell", p.cell)

  def InstantiateVariables(self, key):
    if self._path is None:
      self.FinalizePaths()
    return NestedMap(cell=base_layer.StackedInstantiateVariables(
        self.cell, key, self.p.num_stages))

  def VariableSpecs(self):
    return NestedMap(cell=base_layer.StackedVariableSpecs(
        self.cell, self.p.num_stages))

  def _StageSpec(self, x):
    return (self.p.stage_axis,) + (None,) * (x.ndim - 1)

  def FProp(self, theta, inputs, paddings=None):
    """inputs [b, t, d] -> outputs [b, t, d] after L pipelined RNN layers."""
    p = self.p
    l = p.num_stages
    b, t, d = inputs.shape
    if paddings is None:
      paddings = jnp.zeros((b, t), jnp.float32)
    x_tm = jnp.swapaxes(inputs, 0, 1)          # [t, b, d]
    pad_tm = jnp.swapaxes(paddings, 0, 1)      # [t, b]
    stage_ids = jnp.arange(l)

    states0 = jax.vmap(lambda _: self.cell.InitState(b))(stage_ids)
    in_buf0 = jnp.zeros((l, b, d), inputs.dtype)
    out_buf0 = jnp.zeros((t, b, d), inputs.dtype)

    def _Tick(carry, tick):
      states, in_buf, out_buf = carry
      # stage s consumes timestep tick - s; freeze state when out of range
      micro = tick - stage_ids                               # [L]
      valid = (micro >= 0) & (micro < t)
      idx = jnp.clip(micro, 0, t - 1)
      x0 = jax.lax.dynamic_index_in_dim(x_tm, jnp.clip(tick, 0, t - 1), 0,
                                        keepdims=False)      # [b, d]
      # shift stage outputs down one stage (stage s input <- stage s-1 out);
      # XLA lowers the roll of a stage-sharded buffer to collective-permute.
      in_buf = in_buf.at[0].set(x0)
      in_buf = mesh_lib.WithShardingConstraint(in_buf, self._StageSpec(in_buf))
      pad_stage = jnp.where(valid[:, None], pad_tm[idx], 1.0)  # [L, b]

      new_states = jax.vmap(
          lambda th, s, x, pd: self.cell.FProp(th, s, x, pd))(
              theta.cell, states, in_buf, pad_stage)
      new_states = jax.tree_util.tree_map(
          lambda ns: mesh_lib.WithShardingConstraint(ns, self._StageSpec(ns)),
          new_states)
      outs = jax.vmap(self.cell.GetOutput)(new_states)        # [L, b, H]
      # collect final stage's output for its timestep tick - (L-1)
      out_idx = jnp.clip(tick - (l - 1), 0, t - 1)
      out_buf = jax.lax.dynamic_update_index_in_dim(
          out_buf, outs[-1].astype(out_buf.dtype), out_idx, 0)
      next_in = jnp.roll(outs.astype(in_buf.dtype), 1, axis=0)
      return (new_states, next_in, out_buf), ()

    (states, _, out_buf), _ = jax.lax.scan(
        _Tick, (states0, in_buf0, out_buf0), jnp.arange(t + l - 1))
    return jnp.swapaxes(out_buf, 0, 1), states
