"""Fleet aggregation: scrape N status endpoints, merge their snapshots.

The substrate the disaggregated router does least-loaded admission
against (ROADMAP: "the router only has to aggregate across replicas"):
every replica exports /statusz (observe/export.py); this module pulls N
of them and folds the registry snapshots into one fleet view with the
only merge semantics that are honest per metric kind:

- **counters** sum — fleet totals of monotonic work counts;
- **histograms** merge bucket-by-bucket when the bounds match (count,
  sum and per-bucket counts add; mean recomputed) — fleet latency
  distributions stay exact because bucketing is lossless under union;
- **everything else** (gauges, section values, config strings) stays
  per-replica under `<label>/<name>` — a level has no meaningful sum.

`Scrape`/`ScrapeAll` speak stdlib urllib to /statusz; `MergeSnapshots`
is pure and also consumed in-process (bench fleet smoke, tests);
`LeastLoaded` picks the admission target. `tools/fleet_report.py` is the
CLI over all of it.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Optional

from lingvo_tpu.observe import schema


def Scrape(url: str, timeout: float = 5.0) -> dict:
  """GETs a replica's /statusz and returns the (validated) document.

  `url` may be a bare `host:port` or a base `http://host:port` — the
  /statusz path is appended when absent."""
  if "://" not in url:
    url = "http://" + url
  if not url.endswith("/statusz"):
    url = url.rstrip("/") + "/statusz"
  with urllib.request.urlopen(url, timeout=timeout) as resp:
    doc = json.loads(resp.read().decode("utf-8"))
  return schema.ValidateStatusz(doc)


def ScrapeAll(urls, timeout: float = 5.0) -> dict:
  """{label: statusz doc} for every reachable url; unreachable replicas
  land as {"error": str} so one dead replica can't hide the fleet."""
  out = {}
  for url in urls:
    label = url.replace("http://", "").replace("/statusz", "").rstrip("/")
    try:
      out[label] = Scrape(url, timeout=timeout)
    except Exception as e:  # noqa: BLE001 - report, don't die
      out[label] = {"error": f"{type(e).__name__}: {e}"}
  return out


def _IsHistogram(v) -> bool:
  return isinstance(v, dict) and "counts" in v and "bounds" in v


def _KindOf(name: str, describe: dict) -> str:
  kind = describe.get(name)
  if kind is not None:
    return kind
  head = name.split("/", 1)[0]
  if describe.get(head) == "section":
    return "gauge"
  return "gauge"


def _MergeHist(a: dict, b: dict) -> dict:
  if a["bounds"] != b["bounds"]:   # incompatible bucketing: keep the larger
    return a if a["count"] >= b["count"] else b
  count = a["count"] + b["count"]
  total = a["sum"] + b["sum"]
  return {
      "count": count,
      "sum": total,
      "mean": total / count if count else 0.0,
      "bounds": list(a["bounds"]),
      "counts": [x + y for x, y in zip(a["counts"], b["counts"])],
  }


def MergeSnapshots(replicas) -> dict:
  """Folds [(label, snapshot, describe)] into one fleet dict.

  Returns {"replicas": [labels], "fleet": {...}, "per_replica":
  {label: {...}}}: `fleet` holds summed counters and merged histograms
  under their original names; `per_replica` holds everything else
  (gauges, sections, strings) keyed by replica label."""
  labels, fleet, per_replica = [], {}, {}
  for label, snapshot, describe in replicas:
    labels.append(label)
    mine = per_replica.setdefault(label, {})
    for name, v in snapshot.items():
      if _IsHistogram(v):
        fleet[name] = _MergeHist(fleet[name], v) if name in fleet else dict(v)
      elif (_KindOf(name, describe) == "counter"
            and isinstance(v, (int, float)) and not isinstance(v, bool)):
        fleet[name] = fleet.get(name, 0) + v
      else:
        mine[name] = v
  return {"replicas": labels, "fleet": fleet, "per_replica": per_replica}


def MergeStatusz(docs: dict) -> dict:
  """MergeSnapshots over {label: statusz doc} (errors skipped)."""
  return MergeSnapshots([
      (label, doc["snapshot"], doc.get("describe", {}))
      for label, doc in docs.items() if "snapshot" in doc])


def LiveLabels(docs: dict, order=None) -> list:
  """Labels of replicas that answered their scrape (have a `snapshot`),
  in deterministic order — the router's DOWN handling primitive: a dead
  replica (scrape error, missing snapshot) is routed AROUND, never
  raised on. `order` fixes the ordering explicitly (the fleet's replica
  declaration order); default is sorted labels."""
  labels = order if order is not None else sorted(docs)
  return [lb for lb in labels
          if isinstance(docs.get(lb), dict) and "snapshot" in docs[lb]]


def LeastLoaded(docs: dict, load_key: str = "scheduler/queue_depth",
                order=None) -> Optional[str]:
  """The replica label with the smallest numeric `load_key` in its
  snapshot — the router's admission primitive. Replicas missing the key
  (or erroring/DOWN) are never chosen; None when nobody qualifies.

  Ties break DETERMINISTICALLY on replica ordering — `order` when given
  (the fleet's declaration order), else sorted labels — never on dict
  insertion order, so N routers scoring the same scrape pick the same
  replica."""
  best, best_load = None, None
  for label in (order if order is not None else sorted(docs)):
    doc = docs.get(label)
    if not isinstance(doc, dict):
      continue
    v = doc.get("snapshot", {}).get(load_key)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
      continue
    if best_load is None or v < best_load:   # strict <: first-in-order wins
      best, best_load = label, v
  return best
