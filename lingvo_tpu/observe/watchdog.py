"""Stall watchdog: heartbeat-driven liveness with automatic flight capture.

Nobody was watching the watchers: a hung device step, a step-time
regression, or a scheduler that admits but never retires all looked like
"the process is up" from outside. `StallWatchdog` closes that gap:

- **Heartbeats.** The train programs and `ServingLoop` call `Beat()` once
  per COMPLETED loop/step — for pipelined training, from the telemetry
  worker when a dispatched loop's device work + metric fetch lands (the
  executor wires `Beat` via `SetLoopDoneCallback`), never from the
  dispatch side: a pipelined host keeps dispatching against a hung
  device, so dispatch-side beats would hold /healthz green through a
  real stall. The watchdog keeps an EMA of inter-beat time. `Check()`
  — run by the /healthz scrape thread, a periodic checker thread, or a
  test — evaluates the trip conditions. The split matters: a hung step
  loop cannot self-report, so liveness must be evaluated on a thread the
  stall can't take down.

- **Trips** (`schema.WATCHDOG_TRIP_KINDS`):
    no_heartbeat     now − last beat > stall_factor × max(EMA, min_interval)
    step_regression  the latest step took > regression_factor × prior EMA
    queue_stall      serving queue depth grew over the observation window
                     while retirements stayed flat
  On a NEW trip episode: the per-kind and total trip counters increment
  (once per episode, not per scrape), `healthy` flips (so /healthz
  returns 503), and — when a capture logdir is configured — a
  `ProfileWindow` flight recorder is armed over the next `capture_steps`
  beats, so the profile covers exactly the recovery/stall neighborhood.
  A condition that clears (a beat arrives, the queue drains) ends the
  episode and restores health.

All state is lock-guarded and every timestamp comes from an injectable
`clock`, so trip windows are testable without sleeping.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from lingvo_tpu.observe import profile as profile_lib
from lingvo_tpu.observe import schema


class StallWatchdog:
  """Heartbeat liveness + stall classification (module docstring).

  registry: optional MetricsRegistry — publishes `Stats()` as the lazy
  `watchdog/*` section plus monotonic trip counters
  (`watchdog/trips_total`, `watchdog/trips_<kind>`). capture_logdir:
  arming directory for the automatic ProfileWindow (None disables
  auto-capture). clock: injectable monotonic-seconds source.
  """

  def __init__(self, registry=None, *, stall_factor: float = 10.0,
               min_interval_s: float = 1.0, regression_factor: float = 4.0,
               ema_alpha: float = 0.2, queue_window: int = 4,
               capture_logdir: Optional[str] = None, capture_steps: int = 5,
               clock=time.monotonic, namespace: str = "watchdog"):
    self._lock = threading.Lock()
    self._clock = clock
    self.stall_factor = float(stall_factor)
    self.min_interval_s = float(min_interval_s)
    self.regression_factor = float(regression_factor)
    self.ema_alpha = float(ema_alpha)
    self.capture_logdir = capture_logdir
    self.capture_steps = int(capture_steps)
    self._beats = 0
    self._last_beat = clock()
    self._ema: Optional[float] = None
    self._last_step_s: Optional[float] = None
    self._prev_ema: Optional[float] = None
    # (depth, retired) observations; a full window with growing depth and
    # flat retirement is the queue_stall signature
    self._queue = deque(maxlen=max(int(queue_window), 2))
    self._tripped: set = set()       # kinds with an active episode
    self._trips_total = 0
    self.capture: Optional[profile_lib.ProfileWindow] = None
    self._counters = None
    if registry is not None:
      self._counters = {
          "total": registry.Counter(f"{namespace}/trips_total"),
          **{k: registry.Counter(f"{namespace}/trips_{k}")
             for k in schema.WATCHDOG_TRIP_KINDS}}
      registry.SectionFn(namespace, self.Stats)
    self._checker: Optional[threading.Thread] = None
    self._checker_stop = threading.Event()

  # -- signal intake ----------------------------------------------------------

  def Beat(self, step_time_s: Optional[float] = None):
    """One completed step. step_time_s overrides the inter-beat elapsed
    time (callers that know the device wall should pass it)."""
    with self._lock:
      now = self._clock()
      if step_time_s is None and self._beats > 0:
        step_time_s = now - self._last_beat
      self._beats += 1
      self._last_beat = now
      if step_time_s is not None:
        self._prev_ema = self._ema
        self._last_step_s = float(step_time_s)
        self._ema = (self._last_step_s if self._ema is None else
                     self.ema_alpha * self._last_step_s
                     + (1.0 - self.ema_alpha) * self._ema)
      if self.capture is not None and self.capture.StepDone():
        self.capture = None   # flight recorder window closed
      self._Evaluate(now)

  def Idle(self):
    """The monitored loop is alive but has no work: refresh liveness
    without folding the idle wait into the step-time EMA. Without this
    a traffic-less serving replica stops beating and reads as a
    no_heartbeat stall after the trip window."""
    with self._lock:
      self._last_beat = self._clock()

  def ObserveQueue(self, depth: int, retired: int):
    """Serving-side signal: queue depth + cumulative retirements."""
    with self._lock:
      self._queue.append((int(depth), int(retired)))

  # -- evaluation -------------------------------------------------------------

  def Check(self) -> dict:
    """Evaluates all trip conditions NOW; returns Stats(). This is the
    entry point for /healthz scrapes and checker threads — it must be
    called from a thread the monitored loop cannot hang."""
    with self._lock:
      self._Evaluate(self._clock())
      return self._StatsLocked()

  def _Evaluate(self, now: float):
    """Trip/clear pass (caller holds the lock)."""
    # no_heartbeat: only meaningful once the loop has started beating
    if self._beats > 0:
      window = self.stall_factor * max(self._ema or 0.0, self.min_interval_s)
      self._SetCondition("no_heartbeat", now - self._last_beat > window)
    # step_regression: latest step vs the EMA before it was folded in
    if self._prev_ema is not None and self._last_step_s is not None:
      self._SetCondition(
          "step_regression",
          self._last_step_s > self.regression_factor
          * max(self._prev_ema, 1e-9))
    # queue_stall: a full window where depth grew but nothing retired
    if len(self._queue) == self._queue.maxlen:
      (d0, r0), (d1, r1) = self._queue[0], self._queue[-1]
      self._SetCondition("queue_stall", d1 > d0 and d1 > 0 and r1 == r0)

  def _SetCondition(self, kind: str, active: bool):
    if active and kind not in self._tripped:
      self._tripped.add(kind)
      self._trips_total += 1
      if self._counters is not None:
        self._counters["total"].Inc()
        self._counters[kind].Inc()
      if self.capture_logdir and self.capture is None:
        self.capture = profile_lib.ProfileWindow(
            self.capture_logdir, steps=self.capture_steps).Start()
    elif not active and kind in self._tripped:
      self._tripped.discard(kind)

  # -- views ------------------------------------------------------------------

  @property
  def healthy(self) -> bool:
    with self._lock:
      return not self._tripped

  def Stats(self) -> dict:
    with self._lock:
      return self._StatsLocked()

  def _StatsLocked(self) -> dict:
    out = {
        "healthy": not self._tripped,
        "beats": self._beats,
        "trips": self._trips_total,
        "tripped": ",".join(sorted(self._tripped)),
        "last_beat_age_s": round(self._clock() - self._last_beat, 6),
        "step_ema_s": round(self._ema, 6) if self._ema is not None else 0.0,
        "capture_armed": self.capture is not None,
    }
    assert set(out) == set(schema.WATCHDOG_STATS_KEYS)
    return out

  # -- optional periodic checker ---------------------------------------------

  def StartChecker(self, interval_s: float = 1.0) -> "StallWatchdog":
    """Background thread calling Check() every interval (for processes
    without a /healthz scraper); StopChecker() to end it."""
    if self._checker is None:
      self._checker_stop.clear()

      def _Run():
        while not self._checker_stop.wait(interval_s):
          self.Check()

      self._checker = threading.Thread(target=_Run, daemon=True,
                                       name="stall-watchdog")
      self._checker.start()
    return self

  def StopChecker(self):
    if self._checker is not None:
      self._checker_stop.set()
      self._checker.join(timeout=5.0)
      self._checker = None

  def Close(self):
    """Teardown: stops the checker thread and any still-armed flight
    recorder. The jax profiler is a process singleton — an abandoned
    window would block every later capture in the process."""
    self.StopChecker()
    with self._lock:
      cap, self.capture = self.capture, None
    if cap is not None:
      cap.Stop()
